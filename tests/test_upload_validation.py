"""Upload-time leader-share validation: the numpy columnar check
(Prio3Wire.validate_leader_share) must reject exactly what the scalar
decode rejects — out-of-field elements and bad lengths — and the
upload handler must answer reportRejected for them."""

import numpy as np
import pytest

from janus_tpu.messages.codec import DecodeError
from janus_tpu.vdaf.registry import VdafInstance, circuit_for, prio3_host
from janus_tpu.vdaf.wire import Prio3Wire


@pytest.mark.parametrize(
    "inst",
    [VdafInstance.count(), VdafInstance.sum_vec(length=3, bits=4)],
    ids=["count-f64", "sumvec-f128"],
)
def test_validate_matches_scalar_decode(inst):
    host = prio3_host(inst)
    circ = circuit_for(inst)
    wire = Prio3Wire(circ)
    m = 1 if inst.kind == "count" else [1, 2, 3]
    _, (ls, _hs) = host.shard(m, bytes(16))
    good = wire.encode_leader_share(ls.measurement_share, ls.proof_share, ls.joint_rand_blind)
    wire.validate_leader_share(good)  # well-formed passes
    wire.decode_leader_share(good)  # and the scalar oracle agrees

    # element == MODULUS: rejected by both paths
    bad = bytearray(good)
    enc = circ.FIELD.ENCODED_SIZE
    bad[0:enc] = circ.FIELD.MODULUS.to_bytes(enc, "little")
    with pytest.raises(DecodeError):
        wire.validate_leader_share(bytes(bad))

    # truncated share: rejected
    with pytest.raises(DecodeError):
        wire.validate_leader_share(good[:-1])


def test_upload_rejects_out_of_range_share():
    """A client sending an out-of-field leader share gets
    reportRejected at upload, not a silent later failure."""
    from janus_tpu.aggregator import Aggregator, Config
    from janus_tpu.aggregator.errors import ReportRejected
    from janus_tpu.client import Client, ClientParameters
    from janus_tpu.core.auth import AuthenticationToken
    from janus_tpu.core.hpke import generate_hpke_config_and_private_key
    from janus_tpu.core.time_util import MockClock
    from janus_tpu.datastore.store import EphemeralDatastore
    from janus_tpu.messages import Role, Time
    from janus_tpu.task import QueryTypeConfig, TaskBuilder

    inst = VdafInstance.count()
    clock = MockClock(Time(1_600_000_000))
    eph = EphemeralDatastore(clock=clock)
    leader_kp = generate_hpke_config_and_private_key(config_id=0)
    helper_kp = generate_hpke_config_and_private_key(config_id=1)
    task = (
        TaskBuilder(QueryTypeConfig.time_interval(), inst, Role.LEADER)
        .with_(
            collector_hpke_config=generate_hpke_config_and_private_key(config_id=9).config,
            aggregator_auth_token=AuthenticationToken.random_bearer(),
            collector_auth_token=AuthenticationToken.random_bearer(),
            hpke_keys=(leader_kp,),
        )
        .build()
    )
    eph.datastore.run_tx(lambda tx: tx.put_task(task))
    agg = Aggregator(eph.datastore, clock, Config())
    ta = agg.task_aggregator_for(task.task_id)

    class EvilClient(Client):
        """Shards honestly, then corrupts the leader share payload."""

        def prepare_report(self, measurement, when=None):
            # rebuild with an out-of-range element by monkeypatching the
            # wire encoder for this one call
            orig = self.wire.encode_leader_share

            def corrupt(meas, proof, blind):
                enc = bytearray(orig(meas, proof, blind))
                size = self.wire.enc_size
                enc[0:size] = self.prio3.circuit.FIELD.MODULUS.to_bytes(size, "little")
                return bytes(enc)

            self.wire.encode_leader_share = corrupt
            try:
                return super().prepare_report(measurement, when=when)
            finally:
                self.wire.encode_leader_share = orig

    params = ClientParameters(task.task_id, "http://x/", "http://y/", task.time_precision)
    client = EvilClient(params, inst, leader_kp.config, helper_kp.config, clock=clock)
    report = client.prepare_report(1)
    with pytest.raises(ReportRejected):
        ta.handle_upload(agg.ds, clock, report, None)
    eph.cleanup()
