"""Datastore tests against an ephemeral SQLite store.

Mirrors the strategy of reference aggregator_core/src/datastore/tests.rs
(44 tests against ephemeral postgres; SURVEY.md section 4.2): every op
exercised through the transactional facade, including lease semantics,
replay detection, crypter round-trips and GC deletes.
"""

import threading

import pytest

from janus_tpu.core.time_util import MockClock
from janus_tpu.datastore import (
    AggregateShareJob,
    AggregationJobModel,
    AggregationJobState,
    Batch,
    BatchAggregation,
    BatchAggregationState,
    BatchState,
    CollectionJobModel,
    CollectionJobState,
    LeaderStoredReport,
    OutstandingBatch,
    ReportAggregationModel,
    ReportAggregationState,
)
from janus_tpu.datastore.store import Crypter, EphemeralDatastore, TxConflict
from janus_tpu.core.hpke import generate_hpke_config_and_private_key
from janus_tpu.messages import (
    AggregationJobId,
    BatchId,
    CollectionJobId,
    Duration,
    HpkeCiphertext,
    HpkeConfigId,
    Interval,
    PrepareError,
    ReportId,
    ReportIdChecksum,
    Role,
    TaskId,
    Time,
)
from janus_tpu.task import QueryTypeConfig, TaskBuilder
from janus_tpu.vdaf.registry import VdafInstance


from conftest import DATASTORE_ENGINES


@pytest.fixture(params=DATASTORE_ENGINES)
def eph(request):
    e = EphemeralDatastore(engine=request.param)
    yield e
    e.cleanup()


def mktask(role=Role.LEADER):
    return TaskBuilder(QueryTypeConfig.time_interval(), VdafInstance.count(), role).build()


def test_task_round_trip(eph):
    ds = eph.datastore
    task = mktask()
    ds.run_tx(lambda tx: tx.put_task(task))
    got = ds.run_tx(lambda tx: tx.get_task(task.task_id))
    assert got == task
    assert ds.run_tx(lambda tx: tx.get_task_ids()) == [task.task_id]
    ds.run_tx(lambda tx: tx.delete_task(task.task_id))
    assert ds.run_tx(lambda tx: tx.get_task(task.task_id)) is None


def _report(task, i=0, t=1000):
    return LeaderStoredReport(
        task.task_id,
        ReportId(bytes([i] * 16)),
        Time(t),
        b"pub",
        b"leader-share-secret",
        HpkeCiphertext(HpkeConfigId(0), b"ek", b"payload"),
    )


def test_client_reports(eph):
    ds = eph.datastore
    task = mktask()
    ds.run_tx(lambda tx: tx.put_task(task))
    rep = _report(task)
    assert ds.run_tx(lambda tx: tx.put_client_report(rep))
    # replay
    assert not ds.run_tx(lambda tx: tx.put_client_report(rep))
    got = ds.run_tx(lambda tx: tx.get_client_report(task.task_id, rep.report_id))
    assert got == rep  # crypter round-trip
    assert ds.run_tx(lambda tx: tx.check_report_replayed(task.task_id, rep.report_id))

    for i in range(1, 5):
        ds.run_tx(lambda tx, i=i: tx.put_client_report(_report(task, i, 1000 + i)))
    claimed = ds.run_tx(lambda tx: tx.get_unaggregated_client_reports_for_task(task.task_id, 3))
    assert len(claimed) == 3
    # claims are exclusive
    claimed2 = ds.run_tx(lambda tx: tx.get_unaggregated_client_reports_for_task(task.task_id, 10))
    assert len(claimed2) == 2
    assert not set(r for r, _ in claimed) & set(r for r, _ in claimed2)
    # release back
    ds.run_tx(lambda tx: tx.mark_reports_unaggregated(task.task_id, [claimed[0][0]]))
    claimed3 = ds.run_tx(lambda tx: tx.get_unaggregated_client_reports_for_task(task.task_id, 10))
    assert [r for r, _ in claimed3] == [claimed[0][0]]

    n = ds.run_tx(
        lambda tx: tx.count_client_reports_for_interval(
            task.task_id, Interval(Time(1000), Duration(3))
        )
    )
    assert n == 3
    total, started = ds.run_tx(lambda tx: tx.count_client_reports_for_task(task.task_id))
    assert total == 5 and started == 5
    # (never-claimed, claimed) split for ledger expiry attribution; both
    # expired rows here were claimed above, so they count as reclaimed.
    deleted = ds.run_tx(lambda tx: tx.delete_expired_client_reports(task.task_id, Time(1002), 10))
    assert deleted == (0, 2)


def _aggjob(task, jid=1):
    return AggregationJobModel(
        task.task_id,
        AggregationJobId(bytes([jid] * 16)),
        b"",
        b"",
        Interval(Time(1000), Duration(100)),
        AggregationJobState.IN_PROGRESS,
        0,
    )


def test_aggregation_job_lease_cycle(eph):
    ds = eph.datastore
    clock = eph.clock
    task = mktask()
    job = _aggjob(task)
    ds.run_tx(lambda tx: tx.put_task(task))
    ds.run_tx(lambda tx: tx.put_aggregation_job(job))
    got = ds.run_tx(lambda tx: tx.get_aggregation_job(task.task_id, job.job_id))
    assert got == job

    acq = ds.run_tx(lambda tx: tx.acquire_incomplete_aggregation_jobs(Duration(600), 10))
    assert len(acq) == 1 and acq[0].lease.attempts == 1
    # second acquire sees nothing (lease held)
    assert ds.run_tx(lambda tx: tx.acquire_incomplete_aggregation_jobs(Duration(600), 10)) == []
    # lease expiry makes it reacquirable with attempts bumped
    clock.advance(Duration(601))
    acq2 = ds.run_tx(lambda tx: tx.acquire_incomplete_aggregation_jobs(Duration(600), 10))
    assert len(acq2) == 1 and acq2[0].lease.attempts == 2
    # stale lease release must conflict
    with pytest.raises(TxConflict):
        ds.run_tx(lambda tx: tx.release_aggregation_job(acq[0]))
    # good release
    ds.run_tx(lambda tx: tx.release_aggregation_job(acq2[0]))
    acq3 = ds.run_tx(lambda tx: tx.acquire_incomplete_aggregation_jobs(Duration(600), 10))
    assert len(acq3) == 1 and acq3[0].lease.attempts == 1

    # finished jobs aren't acquirable
    ds.run_tx(lambda tx: tx.release_aggregation_job(acq3[0]))
    ds.run_tx(lambda tx: tx.update_aggregation_job(job.with_state(AggregationJobState.FINISHED)))
    assert ds.run_tx(lambda tx: tx.acquire_incomplete_aggregation_jobs(Duration(600), 10)) == []


def test_report_aggregations(eph):
    ds = eph.datastore
    task = mktask()
    job = _aggjob(task)
    ds.run_tx(lambda tx: tx.put_task(task))
    ds.run_tx(lambda tx: tx.put_aggregation_job(job))
    ras = [
        ReportAggregationModel(
            task.task_id,
            job.job_id,
            ReportId(bytes([i] * 16)),
            Time(1000 + i),
            i,
            ReportAggregationState.WAITING_LEADER,
            prep_blob=b"secret-prep-" + bytes([i]),
        )
        for i in range(3)
    ]
    ds.run_tx(lambda tx: [tx.put_report_aggregation(ra) for ra in ras])
    got = ds.run_tx(lambda tx: tx.get_report_aggregations_for_job(task.task_id, job.job_id))
    assert got == ras  # order + crypter round trip
    upd = ras[1].failed(PrepareError.VDAF_PREP_ERROR)
    ds.run_tx(lambda tx: tx.update_report_aggregation(upd))
    got = ds.run_tx(lambda tx: tx.get_report_aggregations_for_job(task.task_id, job.job_id))
    assert got[1] == upd and got[1].prepare_error == PrepareError.VDAF_PREP_ERROR
    # helper replay check: one set query over the whole id list
    from janus_tpu.messages import ReportId as _RID

    unknown = _RID(bytes(16))
    replayed = ds.run_tx(
        lambda tx: tx.get_aggregated_report_ids(
            task.task_id, [ras[0].report_id, unknown]
        )
    )
    assert replayed == {ras[0].report_id.data}


def test_batch_aggregations_and_conflict(eph):
    ds = eph.datastore
    task = mktask()
    ds.run_tx(lambda tx: tx.put_task(task))
    iv = Interval(Time(1000), Duration(100))
    ba = BatchAggregation(
        task.task_id,
        iv.to_bytes(),
        b"",
        0,
        BatchAggregationState.AGGREGATING,
        b"share-bytes",
        5,
        iv,
        ReportIdChecksum(b"\x01" * 32),
    )
    ds.run_tx(lambda tx: tx.put_batch_aggregation(ba))
    # unique violation -> TxConflict -> retried by run_tx; do it raw
    with pytest.raises(Exception):
        ds.run_tx(lambda tx: (_ for _ in ()).throw(TxConflict("x")))
    got = ds.run_tx(lambda tx: tx.get_batch_aggregation(task.task_id, iv.to_bytes(), b"", 0))
    assert got == ba
    ds.run_tx(lambda tx: tx.mark_batch_aggregations_collected(task.task_id, iv.to_bytes(), b""))
    got = ds.run_tx(lambda tx: tx.get_batch_aggregation(task.task_id, iv.to_bytes(), b"", 0))
    assert got.state == BatchAggregationState.COLLECTED

    big = Interval(Time(900), Duration(400))
    found = ds.run_tx(lambda tx: tx.get_batch_aggregations_intersecting_interval(task.task_id, big))
    assert [b.ord for b in found] == [0]
    none = ds.run_tx(
        lambda tx: tx.get_batch_aggregations_intersecting_interval(
            task.task_id, Interval(Time(0), Duration(100))
        )
    )
    assert none == []


def test_collection_jobs(eph):
    ds = eph.datastore
    task = mktask()
    ds.run_tx(lambda tx: tx.put_task(task))
    iv = Interval(Time(1000), Duration(100))
    cj = CollectionJobModel(
        task.task_id,
        CollectionJobId(bytes(16)),
        b"query-bytes",
        b"",
        iv.to_bytes(),
        CollectionJobState.START,
    )
    ds.run_tx(lambda tx: tx.put_collection_job(cj))
    assert ds.run_tx(lambda tx: tx.find_collection_job_by_query(task.task_id, b"query-bytes")) == cj
    assert ds.run_tx(lambda tx: tx.find_collection_job_by_query(task.task_id, b"other")) is None

    # START jobs are acquirable (the driver checks readiness itself)
    acq0 = ds.run_tx(lambda tx: tx.acquire_incomplete_collection_jobs(Duration(600), 10))
    assert len(acq0) == 1
    ds.run_tx(lambda tx: tx.release_collection_job(acq0[0]))
    import dataclasses

    cj2 = dataclasses.replace(
        cj,
        state=CollectionJobState.COLLECTABLE,
        report_count=5,
        client_timestamp_interval=iv,
        leader_aggregate_share=b"leader-share",
        helper_encrypted_aggregate_share=b"enc-helper",
    )
    ds.run_tx(lambda tx: tx.update_collection_job(cj2))
    got = ds.run_tx(lambda tx: tx.get_collection_job(task.task_id, cj.collection_job_id))
    assert got == cj2  # crypter round trip on leader share
    acq = ds.run_tx(lambda tx: tx.acquire_incomplete_collection_jobs(Duration(600), 10))
    assert len(acq) == 1
    ds.run_tx(lambda tx: tx.release_collection_job(acq[0]))


def test_aggregate_share_jobs(eph):
    ds = eph.datastore
    task = mktask(Role.HELPER)
    ds.run_tx(lambda tx: tx.put_task(task))
    iv = Interval(Time(1000), Duration(100))
    job = AggregateShareJob(
        task.task_id, iv.to_bytes(), b"", b"helper-share-secret", 7, ReportIdChecksum(b"\x02" * 32)
    )
    ds.run_tx(lambda tx: tx.put_aggregate_share_job(job))
    got = ds.run_tx(lambda tx: tx.get_aggregate_share_job(task.task_id, iv.to_bytes(), b""))
    assert got == job
    assert (
        ds.run_tx(lambda tx: tx.count_aggregate_share_jobs_for_batch(task.task_id, iv.to_bytes()))
        == 1
    )


def test_batches_and_outstanding(eph):
    ds = eph.datastore
    task = mktask()
    ds.run_tx(lambda tx: tx.put_task(task))
    iv = Interval(Time(1000), Duration(100))
    b = Batch(task.task_id, iv.to_bytes(), b"", BatchState.OPEN, 1, iv)
    ds.run_tx(lambda tx: tx.put_batch(b))
    got = ds.run_tx(lambda tx: tx.get_batch(task.task_id, iv.to_bytes(), b""))
    assert got == b
    import dataclasses

    b2 = dataclasses.replace(b, state=BatchState.CLOSED, outstanding_aggregation_jobs=0)
    ds.run_tx(lambda tx: tx.update_batch(b2))
    assert ds.run_tx(lambda tx: tx.get_batch(task.task_id, iv.to_bytes(), b"")) == b2

    ob = OutstandingBatch(task.task_id, BatchId(b"\x07" * 32), Time(1000))
    ds.run_tx(lambda tx: tx.put_outstanding_batch(ob))
    assert ds.run_tx(lambda tx: tx.get_outstanding_batches(task.task_id)) == [ob]
    assert ds.run_tx(lambda tx: tx.get_outstanding_batches(task.task_id, Time(1000))) == [ob]
    assert ds.run_tx(lambda tx: tx.get_outstanding_batches(task.task_id, Time(2000))) == []
    ds.run_tx(lambda tx: tx.mark_outstanding_batch_filled(task.task_id, ob.batch_id))
    assert ds.run_tx(lambda tx: tx.get_outstanding_batches(task.task_id)) == []


def test_global_hpke_keys(eph):
    ds = eph.datastore
    kp = generate_hpke_config_and_private_key(config_id=42)
    ds.run_tx(lambda tx: tx.put_global_hpke_keypair(kp))
    got = ds.run_tx(lambda tx: tx.get_global_hpke_keypairs())
    assert got == [(kp, "pending")]
    ds.run_tx(lambda tx: tx.set_global_hpke_keypair_state(42, "active"))
    assert ds.run_tx(lambda tx: tx.get_global_hpke_keypairs())[0][1] == "active"
    ds.run_tx(lambda tx: tx.delete_global_hpke_keypair(42))
    assert ds.run_tx(lambda tx: tx.get_global_hpke_keypairs()) == []


def test_gc_deletes(eph):
    ds = eph.datastore
    task = mktask()
    ds.run_tx(lambda tx: tx.put_task(task))
    job = _aggjob(task)
    ds.run_tx(lambda tx: tx.put_aggregation_job(job))
    ds.run_tx(
        lambda tx: tx.put_report_aggregation(
            ReportAggregationModel(
                task.task_id,
                job.job_id,
                ReportId(bytes(16)),
                Time(1000),
                0,
                ReportAggregationState.START,
            )
        )
    )
    # cutoff before end: nothing deleted
    assert ds.run_tx(lambda tx: tx.delete_expired_aggregation_artifacts(task.task_id, Time(1050), 10)) == (0, 0, 0)
    # (jobs deleted, non-terminal canonical rows, non-terminal param
    # rows): the START row dies with its job, so the GC books one
    # in-flight expiry in the canonical lane.
    assert ds.run_tx(lambda tx: tx.delete_expired_aggregation_artifacts(task.task_id, Time(1200), 10)) == (1, 1, 0)
    assert ds.run_tx(lambda tx: tx.get_aggregation_job(task.task_id, job.job_id)) is None
    assert ds.run_tx(lambda tx: tx.get_report_aggregations_for_job(task.task_id, job.job_id)) == []


def test_crypter_key_rotation():
    k1, k2 = b"\x01" * 16, b"\x02" * 16
    old = Crypter([k1])
    ct = old.encrypt("t", b"r", "c", b"secret")
    rotated = Crypter([k2, k1])
    assert rotated.decrypt("t", b"r", "c", ct) == b"secret"
    with pytest.raises(ValueError):
        Crypter([k2]).decrypt("t", b"r", "c", ct)
    with pytest.raises(ValueError):
        rotated.decrypt("t", b"wrong-row", "c", ct)


def test_concurrent_lease_acquire(eph):
    """Two threads racing acquires must never double-claim a job."""
    ds = eph.datastore
    task = mktask()
    ds.run_tx(lambda tx: tx.put_task(task))
    for i in range(8):
        ds.run_tx(lambda tx, i=i: tx.put_aggregation_job(_aggjob(task, i + 1)))
    results = [[], []]

    def worker(slot):
        got = ds.run_tx(lambda tx: tx.acquire_incomplete_aggregation_jobs(Duration(600), 8))
        results[slot] = [a.job_id for a in got]

    ts = [threading.Thread(target=worker, args=(i,)) for i in range(2)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert not set(results[0]) & set(results[1])
    assert len(results[0]) + len(results[1]) == 8


# ---------------------------------------------------------------------------
# failpoint-driven crash recovery (janus_tpu.failpoints; the unit-scale
# companion of scripts/chaos_run.py): run_tx seams at tx begin /
# pre-commit / post-commit, and the invariant that a crash AFTER commit
# but BEFORE ack cannot double anything when the work is retried.
# ---------------------------------------------------------------------------


@pytest.fixture
def _failpoints():
    from janus_tpu import failpoints

    failpoints.clear()
    yield failpoints
    failpoints.clear()


def test_run_tx_pre_commit_fault_is_retried_once_committed(eph, _failpoints):
    """Injected pre-commit conflicts are absorbed by run_tx's own retry
    loop: the closure re-runs, the datastore commits exactly once."""
    ds = eph.datastore
    task = mktask()
    ds.run_tx(lambda tx: tx.put_task(task))
    _failpoints.configure("datastore.commit.flaky_write=error:1,count=2")
    runs = {"n": 0}

    def fn(tx):
        runs["n"] += 1
        return tx.put_client_report(_report(task))

    assert ds.run_tx(fn, "flaky_write") is True  # fresh on the attempt that lands
    assert runs["n"] == 3  # two injected conflicts + the committing run
    assert ds.run_tx(lambda tx: tx.check_report_replayed(task.task_id, _report(task).report_id))


def test_run_tx_post_commit_crash_does_not_double_store(eph, _failpoints):
    """Crash after COMMIT, before the caller saw the result (the
    upload-ack window): the retry replays the closure against committed
    state — put_client_report reports a replay, exactly one row exists,
    and the caller's observed result is the idempotent one."""
    ds = eph.datastore
    task = mktask()
    ds.run_tx(lambda tx: tx.put_task(task))
    _failpoints.configure("datastore.post_commit.upload_batch=error:1,count=1")
    fresh = ds.run_tx(lambda tx: tx.put_client_report(_report(task)), "upload_batch")
    # the first attempt COMMITTED, then 'crashed' pre-ack; the retry's
    # answer (replay) is what the caller observes
    assert fresh is False
    rows, _ = ds.run_tx(lambda tx: tx.count_client_reports_for_task(task.task_id))
    assert rows == 1


def test_run_tx_post_commit_crash_does_not_double_aggregate(eph, _failpoints):
    """The exactly-once core, in the driver's REAL transaction shape:
    the accumulator flush shares its transaction with the token-guarded
    lease release (step_agg_job_write). A flush alone is idempotent
    only under rollback-retry; when the commit LANDED and the worker
    dies pre-ack, it is the lease release that refuses the replay — the
    retry's release sees a cleared token, raises TxConflict, and the
    whole replayed transaction rolls back. The ambiguous commit
    surfaces as a loud failure; the batch aggregation is never silently
    doubled."""
    import secrets as _secrets

    from janus_tpu.aggregator.accumulator import Accumulator

    ds = eph.datastore
    task = TaskBuilder(
        QueryTypeConfig.time_interval(), VdafInstance.count(), Role.LEADER
    ).with_(min_batch_size=1).build()
    ds.run_tx(lambda tx: tx.put_task(task))
    ds.run_tx(lambda tx: tx.put_aggregation_job(_aggjob(task, 1)))
    (acquired,) = ds.run_tx(
        lambda tx: tx.acquire_incomplete_aggregation_jobs(Duration(600), 1)
    )
    acc = Accumulator(task, shard_count=1)
    rid = ReportId(_secrets.token_bytes(16))
    acc.update_single(b"batch-fp", [7], rid, Time(1_600_000_000))

    def write(tx):
        acc.flush_to_datastore(tx)
        tx.release_aggregation_job(acquired)

    _failpoints.configure("datastore.post_commit.step_agg_job_write=error:1,count=1")
    with pytest.raises(TxConflict):
        ds.run_tx(write, "step_agg_job_write")
    rows = ds.run_tx(
        lambda tx: tx.get_batch_aggregations_for_batch(task.task_id, b"batch-fp", b"")
    )
    assert len(rows) == 1 and rows[0].report_count == 1  # committed exactly once
    # and the committed attempt DID release the lease: reacquirable now
    (re,) = ds.run_tx(
        lambda tx: tx.acquire_incomplete_aggregation_jobs(Duration(600), 1)
    )
    assert re.lease.attempts == 1


def test_run_tx_tx_begin_fault_never_half_commits(eph, _failpoints):
    """A fault at BEGIN leaves nothing behind: the retry starts from a
    clean transaction."""
    ds = eph.datastore
    task = mktask()
    ds.run_tx(lambda tx: tx.put_task(task))
    _failpoints.configure("datastore.tx_begin.begin_fault=error:1,count=1")
    assert ds.run_tx(lambda tx: tx.put_client_report(_report(task, 9)), "begin_fault")
    rows, _ = ds.run_tx(lambda tx: tx.count_client_reports_for_task(task.task_id))
    assert rows == 1


def test_step_back_lease_semantics(eph):
    """step_back_aggregation_job: token cleared, reacquire delayed,
    attempts refunded (count_attempt=False) or preserved (True); stale
    tokens conflict."""
    ds = eph.datastore
    clock = eph.clock
    task = mktask()
    ds.run_tx(lambda tx: tx.put_task(task))
    ds.run_tx(lambda tx: tx.put_aggregation_job(_aggjob(task, 1)))
    (a1,) = ds.run_tx(lambda tx: tx.acquire_incomplete_aggregation_jobs(Duration(600), 1))
    assert a1.lease.attempts == 1
    ds.run_tx(lambda tx: tx.step_back_aggregation_job(a1, reacquire_delay_s=30))
    assert (
        ds.run_tx(lambda tx: tx.acquire_incomplete_aggregation_jobs(Duration(600), 1)) == []
    )
    clock.advance(Duration(31))
    (a2,) = ds.run_tx(lambda tx: tx.acquire_incomplete_aggregation_jobs(Duration(600), 1))
    assert a2.lease.attempts == 1  # refunded, then re-incremented
    # count_attempt=True keeps the ledger
    ds.run_tx(
        lambda tx: tx.step_back_aggregation_job(a2, reacquire_delay_s=0, count_attempt=True)
    )
    (a3,) = ds.run_tx(lambda tx: tx.acquire_incomplete_aggregation_jobs(Duration(600), 1))
    assert a3.lease.attempts == 2
    # a stale holder cannot step back the new holder's lease
    with pytest.raises(TxConflict):
        with ds.tx() as tx:
            tx.step_back_aggregation_job(a1)


def test_trace_context_round_trip(eph):
    """ISSUE 6: the persisted causality link — a W3C traceparent stored
    on aggregation and collection job rows survives the round trip (and
    the absence of one reads back as None), on every engine."""
    ds = eph.datastore
    task = mktask()
    ds.run_tx(lambda tx: tx.put_task(task))

    tp = "00-0af7651916cd43dd8448eb211c80319c-b7ad6b7169203331-01"
    import dataclasses

    traced = dataclasses.replace(_aggjob(task, jid=1), trace_context=tp)
    bare = _aggjob(task, jid=2)
    ds.run_tx(lambda tx: tx.put_aggregation_job(traced))
    ds.run_tx(lambda tx: tx.put_aggregation_job(bare))
    got = ds.run_tx(lambda tx: tx.get_aggregation_job(task.task_id, traced.job_id))
    assert got.trace_context == tp
    assert got == traced
    assert ds.run_tx(
        lambda tx: tx.get_aggregation_job(task.task_id, bare.job_id)
    ).trace_context is None
    # state updates do not disturb the persisted context
    ds.run_tx(
        lambda tx: tx.update_aggregation_job(
            got.with_state(AggregationJobState.FINISHED)
        )
    )
    assert (
        ds.run_tx(
            lambda tx: tx.get_aggregation_job(task.task_id, traced.job_id)
        ).trace_context
        == tp
    )

    # the collection-link query finds jobs whose client interval
    # INTERSECTS the collection (same semantics as the batch gather:
    # a job straddling the boundary still contributed) — and only
    # those with a context
    links = ds.run_tx(
        lambda tx: tx.get_aggregation_job_trace_contexts(
            task.task_id, interval=Interval(Time(900), Duration(300))
        )
    )
    assert links == [tp]
    # straddle: job covers [1000, 1100), collection [1050, 1150)
    assert ds.run_tx(
        lambda tx: tx.get_aggregation_job_trace_contexts(
            task.task_id, interval=Interval(Time(1050), Duration(100))
        )
    ) == [tp]
    assert (
        ds.run_tx(
            lambda tx: tx.get_aggregation_job_trace_contexts(
                task.task_id, interval=Interval(Time(0), Duration(10))
            )
        )
        == []
    )

    cj = CollectionJobModel(
        task.task_id,
        CollectionJobId(b"\x07" * 16),
        b"query",
        b"",
        Interval(Time(1000), Duration(100)).to_bytes(),
        CollectionJobState.START,
        trace_context=tp,
    )
    ds.run_tx(lambda tx: tx.put_collection_job(cj))
    got_cj = ds.run_tx(
        lambda tx: tx.get_collection_job(task.task_id, cj.collection_job_id)
    )
    assert got_cj.trace_context == tp


def test_unaggregated_report_time_quantiles(eph):
    """The freshness-distribution query behind the sampler's p50/p95/p99
    gauges: quantile client_times over unaggregated reports only."""
    ds = eph.datastore
    task = mktask()
    ds.run_tx(lambda tx: tx.put_task(task))

    def put(tx):
        for i in range(10):
            tx.put_client_report(_report(task, i=i, t=1000 + i))

    ds.run_tx(put)
    # bucket_s=1: exact rank semantics (each bucket holds one second)
    rows = ds.run_tx(
        lambda tx: tx.unaggregated_report_time_quantiles_by_task(bucket_s=1)
    )
    assert len(rows) == 1
    task_id, n, oldest, vals = rows[0]
    assert bytes(task_id) == task.task_id.data and n == 10
    # the same scan carries the EXACT oldest time (the sampler's
    # oldest-age gauge rides it instead of a second index walk)
    assert oldest == 1000
    # ages ascending == client_time descending: p50 is the median time,
    # p95/p99 the oldest (rank/edge choices bias toward the older report)
    assert vals[0.5] == 1004
    assert vals[0.95] == 1000
    assert vals[0.99] == 1000
    # the default minute-wide buckets floor to the bucket's older edge:
    # one DB-side histogram scan, conservative within bucket_s
    coarse = ds.run_tx(lambda tx: tx.unaggregated_report_time_quantiles_by_task())
    assert coarse[0][1] == 10 and coarse[0][2] == 1000
    assert all(v == (1000 // 60) * 60 for v in coarse[0][3].values())
    # claimed (aggregation_started) reports leave the distribution
    claimed = ds.run_tx(
        lambda tx: tx.get_unaggregated_client_reports_for_task(task.task_id, 9)
    )
    assert len(claimed) == 9
    rows = ds.run_tx(
        lambda tx: tx.unaggregated_report_time_quantiles_by_task(bucket_s=1)
    )
    assert rows[0][1] == 1 and rows[0][2] == 1009 and rows[0][3][0.5] == 1009
