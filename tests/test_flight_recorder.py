"""Flight recorder (janus_tpu/flight_recorder.py): config parsing, the
Theil-Sen trend estimator, the bounded on-disk ring, rollup tiers, leak
and p99 verdicts (including the injected-leak failpoint), and the
process-wide install surface (statusz `flight` section, /debug/flight
document)."""

import json
import os
import types

import pytest

from janus_tpu import failpoints
from janus_tpu import flight_recorder as flight
from janus_tpu import metrics, slo, statusz
from janus_tpu.flight_recorder import (
    BUILTIN_SERIES,
    FlightRecorder,
    FlightRecorderConfig,
    SeriesSpec,
    _p99_from_bucket_delta,
    _Ring,
    _RollupTier,
    theil_sen,
)


class FakeTime:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt
        return self.t


# ---------------------------------------------------------------------------
# config
# ---------------------------------------------------------------------------


def test_config_from_dict_defaults():
    cfg = FlightRecorderConfig.from_dict(None)
    assert cfg.enabled is True
    assert cfg.interval_s == 10.0
    assert cfg.dir is None
    assert cfg.window_s == 3600.0
    assert cfg.rollup_secs == (60.0, 600.0)
    assert cfg.analyze_every == 3
    assert cfg.p99_min_samples == 16
    assert cfg.latency_families == ("janus_http_request_duration_seconds",)


def test_config_from_dict_yaml_keys_and_clamps():
    cfg = FlightRecorderConfig.from_dict(
        {
            "enabled": False,
            "interval_secs": 2,
            "dir": "/tmp/fr",
            "max_total_bytes": 1 << 20,
            "max_segment_bytes": 1 << 16,
            "window_secs": 120,
            "rollup_secs": [5, 30],
            "analyze_every": 0,  # clamped to 1
            "min_points": 4,
            "noise_mult": 2.0,
            "min_growth_ratio": 0.1,
            "p99_max_ratio": 3.0,
            "p99_min_samples": 8,
            "latency_families": ["janus_database_transaction_duration_seconds"],
        }
    )
    assert cfg.enabled is False
    assert cfg.interval_s == 2.0
    assert cfg.dir == "/tmp/fr"
    assert cfg.window_s == 120.0
    assert cfg.rollup_secs == (5.0, 30.0)
    assert cfg.analyze_every == 1
    assert cfg.p99_min_samples == 8
    assert cfg.latency_families == ("janus_database_transaction_duration_seconds",)


def test_series_spec_rejects_unknown_source():
    with pytest.raises(ValueError):
        SeriesSpec.from_dict({"name": "x", "source": "proc"})


def test_build_series_merges_yaml_over_builtins_by_name():
    builtin_names = [s.name for s in BUILTIN_SERIES()]
    assert "rss_bytes" in builtin_names
    assert "datastore_rows" in builtin_names
    # gc counter is recorded but never leak-gated
    gc = {s.name: s for s in BUILTIN_SERIES()}["gc_deleted_rows"]
    assert gc.leak is False
    cfg = FlightRecorderConfig(
        series=(
            # override a builtin by name (turn off its leak gate)
            {"name": "rss_bytes", "source": "rss", "leak": False},
            # add a custom one
            {"name": "queue_depth", "metric": "janus_dispatch_queue_depth"},
        )
    )
    specs = {s.name: s for s in cfg.build_series()}
    assert len(specs) == len(builtin_names) + 1
    assert specs["rss_bytes"].leak is False
    assert specs["queue_depth"].metric == "janus_dispatch_queue_depth"
    assert specs["queue_depth"].leak is True


# ---------------------------------------------------------------------------
# trend estimation
# ---------------------------------------------------------------------------


def test_theil_sen_exact_on_linear_data():
    pts = [(float(t), 3.0 * t + 7.0) for t in range(20)]
    slope, intercept, mad = theil_sen(pts)
    assert slope == pytest.approx(3.0)
    assert intercept == pytest.approx(7.0)
    assert mad == pytest.approx(0.0)


def test_theil_sen_robust_to_outliers():
    # one wild outlier (a GC pause spike) must not move the slope the
    # way least squares would
    pts = [(float(t), 2.0 * t) for t in range(21)]
    pts[10] = (10.0, 1e6)
    slope, _, mad = theil_sen(pts)
    assert slope == pytest.approx(2.0, rel=0.05)
    assert mad < 1.0


def test_theil_sen_degenerate_inputs():
    assert theil_sen([]) == (0.0, 0.0, 0.0)
    assert theil_sen([(1.0, 5.0)]) == (0.0, 5.0, 0.0)
    # >60 points decimates but stays exact on linear data
    pts = [(float(t), 0.5 * t) for t in range(500)]
    slope, _, _ = theil_sen(pts)
    assert slope == pytest.approx(0.5)


def test_p99_from_bucket_delta():
    bounds = (0.01, 0.1, 1.0)
    # cumulative [b<=0.01, b<=0.1, b<=1.0, total]
    early = [0.0, 0.0, 0.0, 0.0]
    late = [100.0, 100.0, 100.0, 100.0]
    assert _p99_from_bucket_delta(bounds, early, late) == 0.01
    # everything past the last bound -> +Inf
    assert _p99_from_bucket_delta(bounds, [0, 0, 0, 0], [0, 0, 0, 50]) == float("inf")
    # no observations in the delta window
    assert _p99_from_bucket_delta(bounds, late, late) is None


# ---------------------------------------------------------------------------
# the on-disk ring
# ---------------------------------------------------------------------------


def test_ring_rotation_budget_and_read(tmp_path):
    ring = _Ring(str(tmp_path / "ring"), max_segment_bytes=1, max_total_bytes=8192)
    assert ring.max_segment_bytes == 4096  # clamped floor
    pad = "x" * 80
    for i in range(300):
        ring.append({"t": float(i), "tier": "raw", "v": {"s": float(i)}, "pad": pad})
    st = ring.state()
    assert set(st) == {"dir", "segments", "bytes", "dropped_segments", "torn_lines_skipped"}
    assert st["dropped_segments"] > 0
    # enforcement runs at rotation; the filled active segment can sit on
    # top of the budget but never a whole extra segment beyond that
    assert st["bytes"] <= 8192 + 4096
    recs = ring.read()
    assert recs, "oldest segments dropped but recent records survive"
    assert recs[-1]["v"]["s"] == 299.0
    # read() filters by time and tier
    assert all(r["t"] >= 290.0 for r in ring.read(since_unix=290.0))
    assert ring.read(tier="60") == []
    ring.close()


def test_ring_torn_tail_tolerated(tmp_path):
    ring = _Ring(str(tmp_path / "ring"), max_segment_bytes=4096, max_total_bytes=65536)
    for i in range(3):
        ring.append({"t": float(i), "tier": "raw", "v": {}})
    # simulate a crash mid-append: garbage tail on the active segment
    ring._fh.write(b'{"t": 99, "tier": "raw", "v"')
    ring._fh.flush()
    recs = ring.read()
    assert [r["t"] for r in recs] == [0.0, 1.0, 2.0]
    assert ring.state()["torn_lines_skipped"] == 1
    ring.close()


def test_rollup_tier_emits_bucket_stats():
    tier = _RollupTier(10.0)
    assert tier.feed(0.0, {"a": 1.0}) is None
    assert tier.feed(4.0, {"a": 3.0}) is None
    assert tier.feed(8.0, {"a": 2.0}) is None
    emitted = tier.feed(12.0, {"a": 9.0})  # bucket 0 -> 1 completes bucket 0
    assert emitted == {
        "t": 0.0,
        "tier": "10",
        "v": {"a": {"mean": 2.0, "min": 1.0, "max": 3.0, "n": 3}},
    }


# ---------------------------------------------------------------------------
# snapshot + verdicts
# ---------------------------------------------------------------------------


def _recorder(fake, gauge_name, **cfg_kw):
    """A recorder tracking exactly one leak-gated test gauge (the
    builtin series read live process state and would be noise here)."""
    cfg_kw.setdefault("window_s", 100.0)
    cfg_kw.setdefault("min_points", 5)
    cfg_kw.setdefault("latency_families", ())
    fr = FlightRecorder(FlightRecorderConfig(**cfg_kw), time_fn=fake)
    fr.series = [SeriesSpec(name="test_series", metric=gauge_name, leak=True)]
    return fr


def test_leak_verdict_on_growing_series():
    fake = FakeTime()
    g = metrics.REGISTRY.gauge("janus_test_flight_growing")
    fr = _recorder(fake, "janus_test_flight_growing")
    for i in range(20):
        g.set(1000.0 + 500.0 * i)
        fr.snapshot_once()
        fake.advance(5.0)
    analysis = fr.analyze()
    doc = analysis["series"]["test_series"]
    assert doc["verdict"] == "leak"
    assert doc["slope_per_s"] == pytest.approx(100.0, rel=0.01)
    assert "test_series" in analysis["leaking"]
    assert metrics.flight_leak_active.get(series="test_series") == 1.0
    assert metrics.flight_slope.get(series="test_series") == pytest.approx(
        100.0, rel=0.01
    )


def test_flat_verdict_on_stable_series():
    fake = FakeTime()
    g = metrics.REGISTRY.gauge("janus_test_flight_flat")
    fr = _recorder(fake, "janus_test_flight_flat")
    for i in range(20):
        g.set(1000.0 + (1.0 if i % 2 else -1.0))  # bounded jitter
        fr.snapshot_once()
        fake.advance(5.0)
    analysis = fr.analyze()
    assert analysis["series"]["test_series"]["verdict"] == "flat"
    assert analysis["leaking"] == []
    assert metrics.flight_leak_active.get(series="test_series") == 0.0


def test_relative_floor_ignores_tiny_drift_on_large_level():
    # 0.1/s drift on a ~1e9 level: projected window growth is far below
    # min_growth_ratio * level, so it's flat even though the slope is
    # cleanly positive
    fake = FakeTime()
    g = metrics.REGISTRY.gauge("janus_test_flight_drift")
    fr = _recorder(fake, "janus_test_flight_drift")
    for i in range(20):
        g.set(1e9 + 0.1 * 5.0 * i)
        fr.snapshot_once()
        fake.advance(5.0)
    doc = fr.analyze()["series"]["test_series"]
    assert doc["slope_per_s"] > 0
    assert doc["verdict"] == "flat"


def test_insufficient_data_below_min_points():
    fake = FakeTime()
    g = metrics.REGISTRY.gauge("janus_test_flight_sparse")
    fr = _recorder(fake, "janus_test_flight_sparse", min_points=8)
    for i in range(3):
        g.set(float(i))
        fr.snapshot_once()
        fake.advance(5.0)
    doc = fr.analyze()["series"]["test_series"]
    assert doc["verdict"] == "insufficient_data"
    assert doc["points"] == 3


def test_synthetic_leak_failpoint_drives_detector():
    """The injected-leak negative test: arming flight.synthetic_leak
    grows a synthetic leak-gated series every snapshot, the analyzer
    calls it a leak, and janus_flight_leak_active flips to 1."""
    fake = FakeTime()
    fr = _recorder(fake, "janus_test_flight_unused")
    failpoints.configure("flight.synthetic_leak=error:1.0")
    try:
        for _ in range(15):
            fr.snapshot_once()
            fake.advance(5.0)
    finally:
        failpoints.clear()
    analysis = fr.analyze()
    assert "synthetic_leak_bytes" in analysis["leaking"]
    assert analysis["series"]["synthetic_leak_bytes"]["verdict"] == "leak"
    assert metrics.flight_leak_active.get(series="synthetic_leak_bytes") == 1.0
    # trend SLO signal sees the live gauge
    sig = slo.TrendSignal()
    engine = types.SimpleNamespace(_condition_state={})
    bad, total, has_data = sig.read(engine)
    assert has_data is True and bad == 1.0 and total == 1.0
    evidence = sig.evidence()
    assert any("synthetic_leak_bytes" in k for k in evidence)
    # disarmed + flat window clears the gauge again
    fr2 = _recorder(fake, "janus_test_flight_unused")
    fr2._synthetic_bytes = fr._synthetic_bytes
    for _ in range(15):
        fr2.snapshot_once()
        fake.advance(5.0)
    assert fr2.analyze()["leaking"] == []
    assert metrics.flight_leak_active.get(series="synthetic_leak_bytes") == 0.0


def test_ring_receives_raw_and_rollup_records(tmp_path):
    fake = FakeTime()
    g = metrics.REGISTRY.gauge("janus_test_flight_ringed")
    fr = _recorder(
        fake,
        "janus_test_flight_ringed",
        dir=str(tmp_path / "ring"),
        rollup_secs=(20.0,),
    )
    g.set(5.0)
    for _ in range(10):
        fr.snapshot_once()
        fake.advance(5.0)
    raw = fr._ring.read(tier="raw")
    rollups = fr._ring.read(tier="20")
    assert len(raw) == 10
    assert rollups, "completed 20s buckets emit rollup records"
    assert rollups[0]["v"]["test_series"] == {
        "mean": 5.0,
        "min": 5.0,
        "max": 5.0,
        "n": 4,
    }
    fr.stop()


# ---------------------------------------------------------------------------
# p99 window-vs-window
# ---------------------------------------------------------------------------


def _latency_recorder(fake, family, **cfg_kw):
    cfg_kw.setdefault("window_s", 100.0)
    fr = FlightRecorder(
        FlightRecorderConfig(latency_families=(family,), **cfg_kw), time_fn=fake
    )
    fr.series = []
    return fr


def test_p99_degraded_when_late_window_slows():
    fake = FakeTime()
    h = metrics.REGISTRY.histogram("janus_test_flight_lat_degraded_seconds")
    fr = _latency_recorder(fake, "janus_test_flight_lat_degraded_seconds")
    fr.snapshot_once()  # baseline
    for _ in range(32):
        h.observe(0.005)  # early window: fast
    fake.advance(10.0)
    fr.snapshot_once()  # mid
    for _ in range(32):
        h.observe(5.0)  # late window: slow
    fake.advance(10.0)
    fr.snapshot_once()
    doc = fr.analyze()["latency"]["janus_test_flight_lat_degraded_seconds"]
    assert doc["verdict"] == "degraded"
    assert doc["p99_ratio"] > 2.0
    assert metrics.flight_p99_ratio.get(family="janus_test_flight_lat_degraded_seconds") > 2.0


def test_p99_stable_when_both_windows_match():
    fake = FakeTime()
    h = metrics.REGISTRY.histogram("janus_test_flight_lat_stable_seconds")
    fr = _latency_recorder(fake, "janus_test_flight_lat_stable_seconds")
    fr.snapshot_once()  # baseline
    for _ in range(32):
        h.observe(0.02)
    fake.advance(10.0)
    fr.snapshot_once()  # mid
    for _ in range(32):
        h.observe(0.02)
    fake.advance(10.0)
    fr.snapshot_once()
    doc = fr.analyze()["latency"]["janus_test_flight_lat_stable_seconds"]
    assert doc["verdict"] == "stable"
    assert doc["p99_ratio"] == pytest.approx(1.0)


def test_p99_insufficient_below_min_samples():
    # a handful of observations per half is pure noise, not a verdict
    fake = FakeTime()
    h = metrics.REGISTRY.histogram("janus_test_flight_lat_sparse_seconds")
    fr = _latency_recorder(fake, "janus_test_flight_lat_sparse_seconds", p99_min_samples=16)
    fr.snapshot_once()  # baseline
    for _ in range(4):
        h.observe(0.005)
    fake.advance(10.0)
    fr.snapshot_once()  # mid
    for _ in range(4):
        h.observe(5.0)
    fake.advance(10.0)
    fr.snapshot_once()
    doc = fr.analyze()["latency"]["janus_test_flight_lat_sparse_seconds"]
    assert doc["verdict"] == "insufficient_data"
    assert doc["early_n"] == 4 and doc["late_n"] == 4


# ---------------------------------------------------------------------------
# serving surface
# ---------------------------------------------------------------------------


def test_document_and_status_shapes(tmp_path):
    fake = FakeTime()
    g = metrics.REGISTRY.gauge("janus_test_flight_doc")
    fr = _recorder(fake, "janus_test_flight_doc", dir=str(tmp_path / "ring"))
    g.set(1.0)
    for _ in range(6):
        fr.snapshot_once()
        fake.advance(5.0)
    doc = fr.document()
    assert doc["enabled"] is True
    assert doc["series_tracked"] == ["test_series"]
    assert doc["snapshots_total"] == 6
    assert len(doc["snapshots"]) == 6
    assert doc["ring"]["segments"] >= 1
    assert doc["analysis"]["series"]["test_series"]["verdict"] in ("flat", "leak")
    # document decimates to max_points evenly
    small = fr.document(max_points=3)
    assert len(small["snapshots"]) == 3
    st = fr.status()
    assert st["running"] is False
    assert st["snapshots"] == 6
    assert st["last_snapshot_age_s"] == pytest.approx(5.0)
    assert st["leaks_active"] == {}
    fr.stop()


def test_install_uninstall_and_statusz_section():
    prev = flight.get_flight_recorder()
    try:
        fr = flight.install_flight_recorder(
            FlightRecorderConfig(interval_s=60.0), start=False
        )
        assert flight.get_flight_recorder() is fr
        fr.snapshot_once()
        snap = statusz.status_snapshot()
        assert "flight" in snap
        assert snap["flight"]["enabled"] is True
        assert snap["flight"]["snapshots"] == 1
        doc = flight.flight_document()
        assert doc["enabled"] is True and doc["snapshots_total"] == 1
        flight.uninstall_flight_recorder()
        assert flight.get_flight_recorder() is None
        assert "flight" not in statusz.status_snapshot()
        assert flight.flight_document() == {
            "enabled": False,
            "series_tracked": [],
            "snapshots": [],
            "analysis": {},
        }
    finally:
        flight.uninstall_flight_recorder()
        if prev is not None:
            flight.install_flight_recorder(prev.cfg, start=False)


def test_disabled_config_still_installs_statusz_section():
    prev = flight.get_flight_recorder()
    try:
        flight.install_flight_recorder(
            FlightRecorderConfig(enabled=False), start=True
        )
        fr = flight.get_flight_recorder()
        assert fr is not None and fr.running is False
        assert statusz.status_snapshot()["flight"]["enabled"] is False
    finally:
        flight.uninstall_flight_recorder()
        if prev is not None:
            flight.install_flight_recorder(prev.cfg, start=False)


def test_recorder_loop_start_stop():
    fr = flight.FlightRecorder(FlightRecorderConfig(interval_s=0.02, min_points=2))
    fr.series = []
    fr.start()
    try:
        deadline = 50
        while fr._snapshots < 2 and deadline:
            import time as _time

            _time.sleep(0.02)
            deadline -= 1
        assert fr._snapshots >= 2
        assert fr.running is True
    finally:
        fr.stop()
    assert fr.running is False
    assert fr.status()["overhead_ratio"] < 0.5  # trivially cheap series set


def test_ring_records_are_valid_jsonl(tmp_path):
    fake = FakeTime()
    fr = _recorder(fake, "janus_test_flight_jsonl", dir=str(tmp_path / "ring"))
    for _ in range(3):
        fr.snapshot_once()
        fake.advance(1.0)
    fr.stop()
    files = sorted(os.listdir(tmp_path / "ring"))
    assert files and all(f.startswith("flight-") and f.endswith(".jsonl") for f in files)
    with open(tmp_path / "ring" / files[0]) as fh:
        for line in fh:
            rec = json.loads(line)
            assert rec["tier"] == "raw" and "t" in rec and "v" in rec
