"""Admission-controlled ingest pipeline (janus_tpu.ingest;
docs/INGEST.md): token buckets + queue watermarks shed with
429 + Retry-After in priority order, admitted uploads commit exactly
once through the staged pipeline, handler threads stay bounded, and
well-behaved clients honor the server's Retry-After in their retry
loop (core/retries.py)."""

import threading
import time
from concurrent.futures import ThreadPoolExecutor

import pytest

from janus_tpu import metrics
from janus_tpu.aggregator import Aggregator, Config
from janus_tpu.aggregator.http_handlers import DapHttpApp, DapServer
from janus_tpu.client import Client, ClientParameters
from janus_tpu.core.hpke import generate_hpke_config_and_private_key
from janus_tpu.core.http_client import HttpClient
from janus_tpu.core.retries import Backoff, DeadlineExceeded, retry_http_request
from janus_tpu.core.time_util import MockClock
from janus_tpu.datastore.store import EphemeralDatastore
from janus_tpu.ingest import (
    AdmissionConfig,
    AdmissionController,
    IngestPipeline,
    ShedError,
    TokenBucket,
)
from janus_tpu.messages import Role, Time
from janus_tpu.task import QueryTypeConfig, TaskBuilder
from janus_tpu.vdaf.registry import VdafInstance


# ---------------------------------------------------------------------------
# admission primitives
# ---------------------------------------------------------------------------


def test_token_bucket_burst_then_refill():
    now = [0.0]
    bucket = TokenBucket(rate=2.0, burst=3, clock=lambda: now[0])
    assert [bucket.try_acquire() for _ in range(3)] == [0.0, 0.0, 0.0]
    wait = bucket.try_acquire()  # empty: refill hint, not a token
    assert wait == pytest.approx(0.5)
    now[0] += 0.5  # one token refilled at 2/s
    assert bucket.try_acquire() == 0.0
    assert bucket.try_acquire() > 0.0


def test_admission_watermarks_shed_uploads_before_aggregates():
    depth = {"v": (0, 100)}
    ctl = AdmissionController(
        AdmissionConfig(queue_high_watermark=0.75), depth_fn=lambda: depth["v"]
    )
    # below the first watermark: everything admitted
    depth["v"] = (74, 100)
    ctl.admit("upload")
    ctl.admit("aggregate")
    # above upload's watermark but below aggregate's (87.5%): client
    # uploads shed, aggregator-to-aggregator steps still run
    depth["v"] = (80, 100)
    with pytest.raises(ShedError) as ei:
        ctl.admit("upload")
    assert ei.value.reason == "queue"
    ctl.admit("aggregate")
    # near-full: both shed
    depth["v"] = (95, 100)
    with pytest.raises(ShedError):
        ctl.admit("aggregate")


def test_admission_rate_shed_advertises_refill_time():
    ctl = AdmissionController(
        AdmissionConfig(upload_bucket_rate=0.5, upload_bucket_burst=1)
    )
    ctl.admit("upload")
    with pytest.raises(ShedError) as ei:
        ctl.admit("upload")
    assert ei.value.reason == "rate"
    # a 0.5/s bucket refills in <=2s; the hint is clamped to >=1s
    assert 1.0 <= ei.value.retry_after_s <= 2.1
    # unconfigured class: no bucket, no queue signal -> admitted
    ctl.admit("aggregate")


def test_pipeline_queue_full_backstop_sheds():
    """With the decode stage wedged, submits beyond queue_depth raise
    ShedError instead of blocking or growing queues without bound."""
    from janus_tpu.messages import (
        HpkeCiphertext,
        HpkeConfigId,
        Report,
        ReportId,
        ReportMetadata,
    )

    raw = Report(
        ReportMetadata(ReportId(bytes(16)), Time(0)),
        b"",
        HpkeCiphertext(HpkeConfigId(0), b"", b""),
        HpkeCiphertext(HpkeConfigId(0), b"", b""),
    ).to_bytes()
    gate = threading.Event()

    class _StuckTa:
        def upload_prepare(self, clock, report):
            gate.wait(10)
            raise RuntimeError("never admitted")

    class _Writer:
        def submit_report(self, report, on_done=None):
            raise AssertionError("unreachable")

    pipe = IngestPipeline(_Writer(), decrypt_workers=1, queue_depth=2)
    try:
        t1 = pipe.submit(_StuckTa(), None, raw)
        t2 = pipe.submit(_StuckTa(), None, raw)
        with pytest.raises(ShedError) as ei:
            pipe.submit(_StuckTa(), None, raw)
        assert ei.value.reason == "queue_full"
        assert pipe.depth() == (2, 2)
        gate.set()
        for t in (t1, t2):
            with pytest.raises(RuntimeError):
                t.result(timeout_s=10)
        assert pipe.depth() == (0, 2)
    finally:
        gate.set()
        pipe.close()


# ---------------------------------------------------------------------------
# served overload behavior (the acceptance scenario)
# ---------------------------------------------------------------------------


def _leader_stack(cfg: Config, max_handler_threads: int = 4):
    clock = MockClock(Time(1_600_000_000))
    eph = EphemeralDatastore(clock=clock)
    agg = Aggregator(eph.datastore, clock, cfg)
    srv = DapServer(DapHttpApp(agg), max_handler_threads=max_handler_threads).start()
    vdaf = VdafInstance.count()
    leader_kp = generate_hpke_config_and_private_key(config_id=0)
    helper_kp = generate_hpke_config_and_private_key(config_id=1)
    task = (
        TaskBuilder(QueryTypeConfig.time_interval(), vdaf, Role.LEADER)
        .with_(
            leader_aggregator_endpoint=srv.url,
            helper_aggregator_endpoint=srv.url,
            hpke_keys=(leader_kp,),
            min_batch_size=1,
        )
        .build()
    )
    eph.datastore.run_tx(lambda tx: tx.put_task(task))
    params = ClientParameters(task.task_id, srv.url, srv.url, task.time_precision)
    client = Client(params, vdaf, leader_kp.config, helper_kp.config, clock=clock)
    return eph, srv, task, params, client


def test_upload_burst_sheds_429_and_admitted_commit_exactly_once():
    """Synthetic burst above configured capacity: every request answers
    201 or 429+Retry-After, exactly `burst` commit (once), the shed
    counter accounts for every 429, and handler threads stay within the
    configured bound."""
    cfg = Config(upload_bucket_rate=0.001, upload_bucket_burst=4, ingest_queue_depth=32)
    eph, srv, task, params, client = _leader_stack(cfg, max_handler_threads=4)
    try:
        reports = [client.prepare_report(1) for _ in range(12)]
        shed0 = metrics.upload_shed_counter.total()

        def put(report):
            http = HttpClient()
            status, body = http.put(
                params.upload_uri(),
                report.to_bytes(),
                {"Content-Type": "application/dap-report"},
            )
            ra = next(
                (
                    v
                    for k, v in http.last_response_headers.items()
                    if k.lower() == "retry-after"
                ),
                None,
            )
            return status, ra, body

        with ThreadPoolExecutor(max_workers=12) as pool:
            results = list(pool.map(put, reports))

        statuses = [s for s, _, _ in results]
        assert sorted(set(statuses)) == [201, 429]
        assert statuses.count(201) == 4  # the bucket's burst, exactly
        for status, ra, body in results:
            if status == 429:
                assert ra is not None and int(ra) >= 1
                assert b"429" in body
        # every rejection is accounted for
        assert metrics.upload_shed_counter.total() - shed0 == statuses.count(429)
        # admitted reports are durably committed exactly once
        total, _ = eph.datastore.run_tx(
            lambda tx: tx.count_client_reports_for_task(task.task_id)
        )
        assert total == 4
        # bounded serving: handler threads never exceed the bound
        handlers = [
            t.name for t in threading.enumerate() if t.name.startswith("dap-handler")
        ]
        assert 0 < len(handlers) <= 4, handlers
    finally:
        srv.stop()
        eph.cleanup()


def test_pipelined_upload_plain_path_and_replay():
    """Default config (no buckets): uploads flow through the staged
    pipeline, commit, and a replayed report is silent success (201)
    without a second row."""
    cfg = Config()
    eph, srv, task, params, client = _leader_stack(cfg)
    try:
        report = client.prepare_report(1)
        http = HttpClient()
        for _ in range(2):  # second PUT is a replay
            status, body = http.put(
                params.upload_uri(),
                report.to_bytes(),
                {"Content-Type": "application/dap-report"},
            )
            assert status == 201, body
        total, _ = eph.datastore.run_tx(
            lambda tx: tx.count_client_reports_for_task(task.task_id)
        )
        assert total == 1
    finally:
        srv.stop()
        eph.cleanup()


def test_pipeline_errors_map_to_problem_documents():
    """Stage failures inside the pipeline surface as the same problem
    documents the inline upload path produced."""
    cfg = Config()
    eph, srv, task, params, client = _leader_stack(cfg)
    try:
        http = HttpClient()
        # undecodable body -> invalidMessage problem doc (DecodeError
        # raised on the decode stage, re-raised on the handler thread)
        status, body = http.put(
            params.upload_uri(), b"garbage", {"Content-Type": "application/dap-report"}
        )
        assert status == 400
        assert b"invalidMessage" in body or b"undecodable" in body
        # report from the future -> reportTooEarly (decode-stage check)
        late = client.prepare_report(1, when=Time(1_600_000_000 + 10 * 24 * 3600))
        status, body = http.put(
            params.upload_uri(), late.to_bytes(), {"Content-Type": "application/dap-report"}
        )
        assert status == 400
        assert b"reportTooEarly" in body
    finally:
        srv.stop()
        eph.cleanup()


# ---------------------------------------------------------------------------
# Retry-After honoring in the client retry loop (core/retries.py)
# ---------------------------------------------------------------------------


def test_retry_honors_retry_after_header():
    sleeps = []
    calls = {"n": 0}

    def do_request():
        calls["n"] += 1
        if calls["n"] < 3:
            return 429, b"", {"Retry-After": "2"}
        return 201, b"ok"

    backoff = Backoff(initial=0.001, max_interval=10.0, max_elapsed=30.0)
    status, body = retry_http_request(do_request, backoff, sleep=sleeps.append)
    assert (status, body) == (201, b"ok")
    # the server's 2s schedule replaces the millisecond exponential
    assert sleeps == [2.0, 2.0]


def test_retry_after_clamped_by_max_interval():
    sleeps = []
    responses = iter([(503, b"", {"Retry-After": "3600"}), (200, b"done")])
    backoff = Backoff(initial=0.001, max_interval=5.0, max_elapsed=100.0)
    status, _ = retry_http_request(
        lambda: next(responses), backoff, sleep=sleeps.append
    )
    assert status == 200
    assert sleeps == [5.0]  # hostile/huge value cannot park the worker


def test_retry_after_bounded_by_deadline():
    def do_request():
        return 429, b"", {"Retry-After": "30"}

    backoff = Backoff(initial=0.001, max_interval=60.0, max_elapsed=120.0)
    with pytest.raises(DeadlineExceeded):
        retry_http_request(
            do_request,
            backoff,
            sleep=lambda s: None,
            deadline=time.monotonic() + 1.0,
        )


def test_retry_after_zero_cannot_spin_forever():
    """'Retry-After: 0' (or a past HTTP-date) is floored at the
    backoff's initial interval so the max_elapsed budget still spends —
    a hostile server must not turn the retry loop into a hot spin."""
    sleeps = []

    def do_request():
        return 503, b"", {"Retry-After": "0"}

    backoff = Backoff(initial=0.01, max_interval=5.0, max_elapsed=0.05)
    status, _ = retry_http_request(do_request, backoff, sleep=sleeps.append)
    assert status == 503  # budget exhausted -> last response returned
    assert sleeps and all(s >= 0.01 for s in sleeps)
    assert len(sleeps) <= 6  # terminated by max_elapsed, not by luck


def test_connection_close_when_handler_pool_saturated():
    """With every pool worker occupied, responses drop keep-alive so
    parked persistent connections cannot starve later ones."""
    import http.client

    cfg = Config()
    eph, srv, task, params, client = _leader_stack(cfg, max_handler_threads=1)
    try:
        host, port = srv.server.server_address[:2]
        conn = http.client.HTTPConnection(host, port, timeout=10)
        try:
            # this connection occupies the ONLY worker -> saturated
            conn.request("GET", "/healthz")
            resp = conn.getresponse()
            assert resp.status == 200
            resp.read()
            assert resp.getheader("Connection") == "close"
        finally:
            conn.close()
    finally:
        srv.stop()
        eph.cleanup()


def test_keepalive_survives_unsaturated_pool():
    import http.client

    cfg = Config()
    eph, srv, task, params, client = _leader_stack(cfg, max_handler_threads=8)
    try:
        host, port = srv.server.server_address[:2]
        conn = http.client.HTTPConnection(host, port, timeout=10)
        try:
            for _ in range(2):  # second request reuses the connection
                conn.request("GET", "/healthz")
                resp = conn.getresponse()
                assert resp.status == 200
                resp.read()
                assert resp.getheader("Connection") != "close"
        finally:
            conn.close()
    finally:
        srv.stop()
        eph.cleanup()


def test_retry_after_http_date_and_garbage():
    from email.utils import formatdate

    from janus_tpu.core.retries import parse_retry_after

    assert parse_retry_after("7") == 7.0
    assert parse_retry_after(None) is None
    assert parse_retry_after("soon") is None
    delta = parse_retry_after(formatdate(time.time() + 30, usegmt=True))
    assert delta is not None and 20 <= delta <= 31
    # dates in the past mean "retry now", never negative sleeps
    assert parse_retry_after(formatdate(time.time() - 30, usegmt=True)) == 0.0


def test_client_upload_retries_through_shed_then_succeeds():
    """A well-behaved Client retries a shed upload after the advertised
    delay and succeeds once the bucket refills."""
    cfg = Config(upload_bucket_rate=5.0, upload_bucket_burst=1, upload_shed_retry_after_s=1.0)
    eph, srv, task, params, client = _leader_stack(cfg)
    try:
        client.http = HttpClient()
        client.upload(1)  # takes the burst token
        # bucket refills at 5/s and retries honor Retry-After (>=1s),
        # so the second upload sheds once then lands
        client.upload(1)
        total, _ = eph.datastore.run_tx(
            lambda tx: tx.count_client_reports_for_task(task.task_id)
        )
        assert total == 2
    finally:
        srv.stop()
        eph.cleanup()
