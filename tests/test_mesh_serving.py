"""Multi-device serving: when the process sees >1 JAX device (the
conftest provisions 8 virtual CPU devices), the production serving
paths — helper aggregate-init behind the REAL HTTP handler, and the
leader driver — must run their device steps dp-sharded over the mesh,
with results identical to single-device execution (SURVEY §2.10 P2/P4;
VERDICT r2 Missing #3)."""

import numpy as np
import pytest

from janus_tpu.aggregator.aggregation_job_creator import (
    AggregationJobCreator,
    AggregationJobCreatorConfig,
)
from janus_tpu.aggregator.aggregation_job_driver import AggregationJobDriver
from janus_tpu.aggregator.engine_cache import DeviceRows, EngineCache, engine_cache
from janus_tpu.aggregator.job_driver import JobDriver, JobDriverConfig
from janus_tpu.client import Client, ClientParameters
from janus_tpu.core.http_client import HttpClient
from janus_tpu.vdaf.registry import VdafInstance

from test_e2e import pair, provision  # noqa: F401  (fixture + helper)

VDAF = VdafInstance.sum(bits=8)


def test_engine_cache_builds_dp_mesh():
    import jax

    eng = engine_cache(VDAF, b"\x01" * 16)
    if len(jax.devices()) == 1:
        assert eng.mesh is None
        pytest.skip("single-device environment; mesh path not active")
    assert eng.mesh is not None
    assert eng.dp == min(8, len(jax.devices()))


@pytest.mark.slow  # 33s sharded live pair; mesh construction stays fast in test_engine_cache_builds_dp_mesh (ISSUE 1)
def test_helper_http_serving_runs_sharded(pair, monkeypatch):
    """Drive reports through the live leader+helper HTTP pair and
    assert the helper's device step output was sharded over the dp
    mesh — introspected on the very DeviceRows the HTTP handler's
    engine call produced."""
    import jax

    if len(jax.devices()) == 1:
        pytest.skip("needs the 8-virtual-device conftest mesh")

    leader_task, helper_task, collector_kp = provision(pair, VDAF)

    observed = []
    orig = EngineCache.helper_init

    def capture(self, *args, **kwargs):
        out1, mask, prep_msg = orig(self, *args, **kwargs)
        observed.append(out1)
        return out1, mask, prep_msg

    monkeypatch.setattr(EngineCache, "helper_init", capture)

    http = HttpClient()
    params = ClientParameters(
        leader_task.task_id,
        pair["leader_srv"].url,
        pair["helper_srv"].url,
        leader_task.time_precision,
    )
    client = Client.with_fetched_configs(params, VDAF, http, clock=pair["clock"])
    measurements = [1, 2, 3, 4, 5]
    for m in measurements:
        client.upload(m)

    creator = AggregationJobCreator(
        pair["leader_ds"], AggregationJobCreatorConfig(min_aggregation_job_size=1)
    )
    assert creator.run_once() == 1
    driver = AggregationJobDriver(pair["leader_ds"], http)
    jd = JobDriver(
        JobDriverConfig(max_concurrent_job_workers=1), driver.acquirer(), driver.stepper
    )
    assert jd.run_once() == 1

    # the helper's HTTP-served init produced dp-sharded out shares
    assert observed, "helper_init never ran"
    out1 = observed[-1]
    assert isinstance(out1, DeviceRows)
    sharding = out1.value[0].sharding
    ndev = len(sharding.device_set)
    assert ndev == min(8, len(jax.devices())), f"out share on {ndev} device(s)"

    # and the aggregate is still correct end to end
    from janus_tpu.datastore.models import ReportAggregationState

    ras = pair["helper_ds"].run_tx(
        lambda tx: tx.get_report_aggregations_for_job(
            helper_task.task_id,
            pair["leader_ds"]
            .run_tx(lambda tx2: tx2.get_aggregation_jobs_for_task(leader_task.task_id))[0]
            .job_id,
        )
    )
    assert {ra.state for ra in ras} == {ReportAggregationState.FINISHED}
    # helper share alone is a random-looking field vector; correctness of
    # the full sum is covered by the e2e collect matrix — here we assert
    # the helper accumulated exactly len(measurements) reports sharded
    from janus_tpu.messages import Duration, Interval, Time

    rows = pair["helper_ds"].run_tx(
        lambda tx: tx.get_batch_aggregations_intersecting_interval(
            helper_task.task_id, Interval(Time(0), Duration(1 << 40))
        )
    )
    assert sum(r.report_count for r in rows) == len(measurements)


def test_long_vector_task_selects_sp_axis():
    """Mesh-shape selection alone (no compile): tasks past
    SP_MIN_INPUT_LEN get an (dp, sp=2) mesh — the fast half of
    test_long_vector_task_gets_sp_mesh below."""
    import jax

    if len(jax.devices()) < 4:
        pytest.skip("needs the 8-virtual-device conftest mesh")

    long_vdaf = VdafInstance.sum_vec(length=16384, bits=8)  # input_len 131072
    eng = engine_cache(long_vdaf, b"\x03" * 16)
    assert eng.sp == 2
    assert eng.mesh.shape["sp"] == 2


@pytest.mark.slow  # 66s long-vector compile; mesh-shape selection is asserted fast above (ISSUE 1)
def test_long_vector_task_gets_sp_mesh():
    """Tasks past SP_MIN_INPUT_LEN shard the vector axis too: the mesh
    is (dp, sp=2) and leader_init runs with meas sharded over both axes
    (VERDICT r3 item 7 — the serving path, not just the dryrun)."""
    import jax

    if len(jax.devices()) < 4:
        pytest.skip("needs the 8-virtual-device conftest mesh")

    long_vdaf = VdafInstance.sum_vec(length=16384, bits=8)  # input_len 131072
    eng = engine_cache(long_vdaf, b"\x02" * 16)
    assert eng.sp == 2
    assert eng.mesh.shape["sp"] == 2

    # run a leader init through the sharded step (content is random —
    # this checks sharding/execution, not protocol validity)
    rng = np.random.default_rng(8)
    n = 4
    circ = eng.p3.circ
    nonce = rng.integers(0, 1 << 63, size=(n, 2), dtype=np.uint64)
    parts = rng.integers(0, 1 << 63, size=(n, 2, 2), dtype=np.uint64)
    meas = tuple(
        rng.integers(0, 1 << 62, size=(n, circ.input_len), dtype=np.uint64) for _ in range(2)
    )
    proof = tuple(
        rng.integers(0, 1 << 62, size=(n, circ.proof_len), dtype=np.uint64) for _ in range(2)
    )
    blind0 = rng.integers(0, 1 << 63, size=(n, 2), dtype=np.uint64)
    out0, seed0, ver0, part0 = eng.leader_init(nonce, parts, meas, proof, blind0)
    assert isinstance(out0, DeviceRows)
    # the out-share rows live sharded over the (dp, sp) mesh
    shard_mesh = out0.value[0].sharding.mesh
    assert dict(shard_mesh.shape) == dict(eng.mesh.shape)
    assert ver0[0].shape == (n, circ.verifier_len)
