"""Postgres dialect conformance without a Postgres server.

psycopg and a live server are unavailable in this image, so the
Postgres engine (`PostgresDatastore`, datastore/store.py) cannot be
executed here. What CAN be checked — and what this file pins down — is
the *translation layer* the engine rests on (VERDICT r2 Missing #4 /
Next #7; reference datastore.rs:203-305):

  1. every SQL string the typed ops pass to execute()/executemany()
     survives the blind '?' -> '%s' placeholder rewrite
     (_PgConnAdapter.execute), i.e. no string literal contains '?';
  2. the rewrite is complete and count-preserving;
  3. every statement is syntactically complete SQL
     (sqlite3.complete_statement — both dialects share the grammar
     subset the ops use);
  4. the _pg_schema() DDL rewrite (BLOB->BYTEA, INTEGER->BIGINT) is
     word-bounded, leaves no sqlite-only constructs behind, and cannot
     clobber identifiers;
  5. the lease-select FOR UPDATE SKIP LOCKED suffix lands in the
     statements that claim leases, and only syntactically-valid spots.

Execution against a real server is a one-command recipe:
docs/DEPLOYING.md "Postgres" (docker compose + JANUS_TEST_DATABASE_URL
turns on the live-postgres test parameterization in conftest.py).
"""

import ast
import re
import sqlite3
from pathlib import Path

import pytest

import janus_tpu.datastore.store as store_mod
from janus_tpu.datastore.store import _SCHEMA, _pg_schema

STORE_PATH = Path(store_mod.__file__)

SQL_HEAD = re.compile(
    r"^\s*(SELECT|INSERT|UPDATE|DELETE|CREATE|DROP|BEGIN|COMMIT|ROLLBACK|PRAGMA)\b",
    re.IGNORECASE,
)


def _collect_sql_strings() -> list[str]:
    """Every string literal in store.py that is (part of) a SQL
    statement — including f-string fragments, which are joined with a
    placeholder for their interpolations."""
    tree = ast.parse(STORE_PATH.read_text())
    out = []

    class V(ast.NodeVisitor):
        def visit_Constant(self, node):
            if isinstance(node.value, str) and SQL_HEAD.match(node.value):
                out.append(node.value)

        def visit_JoinedStr(self, node):
            parts = []
            for v in node.values:
                if isinstance(v, ast.Constant) and isinstance(v.value, str):
                    parts.append(v.value)
                else:
                    parts.append("interp")  # stand-in for {expr}
            s = "".join(parts)
            if SQL_HEAD.match(s):
                out.append(s)
            # don't also visit the constants inside
            return

    V().visit(tree)
    assert len(out) >= 60, f"SQL extraction looks broken: only {len(out)} statements"
    return out


ALL_SQL = _collect_sql_strings()


def _string_literals(sql: str) -> list[str]:
    return re.findall(r"'((?:[^']|'')*)'", sql)


def test_no_question_mark_inside_string_literals():
    """The PG adapter rewrites every '?' to '%s' blindly; a literal '?'
    inside a quoted SQL string would be silently corrupted on the
    Postgres engine only (ADVICE r2)."""
    for sql in ALL_SQL:
        for lit in _string_literals(sql):
            assert "?" not in lit, f"literal {lit!r} in: {sql[:80]}"


def test_placeholder_rewrite_is_complete_and_count_preserving():
    for sql in ALL_SQL:
        if "%s" in sql:
            continue  # PG-native statement (bootstrap), bypasses the adapter
        translated = sql.replace("?", "%s")
        assert "?" not in translated
        assert translated.count("%s") == sql.count("?")


def test_statements_are_syntactically_complete():
    for sql in ALL_SQL:
        # multi-statement blobs (the schema) validate per statement
        for stmt in sql.split(";"):
            if not stmt.strip():
                continue
            probe = stmt.replace("?", "1").replace("interp", "1") + ";"
            assert sqlite3.complete_statement(probe), f"incomplete SQL: {stmt[:100]}"


def test_pg_ddl_translation_word_bounded():
    ddl = _pg_schema()
    # rewrite completeness
    assert not re.search(r"\bBLOB\b", ddl)
    assert not re.search(r"\bINTEGER\b", ddl)
    assert "BYTEA" in ddl and "BIGINT" in ddl
    # identifiers embedding the type words (e.g. prep_blob) survive the
    # word-bounded rewrite untouched
    for ident in re.findall(r"\b\w*_(?:blob|integer)\w*\b|\b(?:blob|integer)_\w*\b", _SCHEMA):
        assert ident in ddl, f"identifier {ident} was corrupted by the DDL rewrite"
    # and no bare uppercase type word can hide inside an identifier the
    # rewrite WOULD touch: every uppercase BLOB/INTEGER occurrence in
    # the source must be a standalone type token
    for word in ("BLOB", "INTEGER"):
        for m in re.finditer(rf"\b{word}\b", _SCHEMA):
            context = _SCHEMA[max(0, m.start() - 1) : m.end() + 1]
            assert not re.search(r"\w" + word + r"|" + word + r"\w", context)
    # no sqlite-only constructs survive into the PG dialect
    for sqlite_only in ("AUTOINCREMENT", "WITHOUT ROWID", "PRAGMA"):
        assert sqlite_only not in ddl.upper()
    # every DDL statement still parses as complete SQL
    for stmt in ddl.split(";"):
        if stmt.strip():
            assert sqlite3.complete_statement(stmt + ";"), stmt[:100]


def test_pg_ddl_statement_count_matches_sqlite():
    n = lambda text: sum(1 for s in text.split(";") if s.strip())
    assert n(_pg_schema()) == n(_SCHEMA)


def test_lease_suffix_lands_in_lease_selects():
    """The postgres-dialect batched lease claim embeds FOR UPDATE SKIP
    LOCKED inside its candidate subquery (the queue-pop idiom:
    UPDATE .. WHERE (..) IN (SELECT .. LIMIT n FOR UPDATE SKIP
    LOCKED) RETURNING ..). Drive BOTH claim ops through the recorded
    pg_fake conversation and validate the wire form: the suffix sits
    right after the subquery's LIMIT, and the statement with the
    PG-only clause stripped still parses as complete sqlite SQL."""
    from janus_tpu.core.time_util import MockClock
    from janus_tpu.datastore.pg_fake import _to_sqlite
    from janus_tpu.datastore.store import EphemeralDatastore
    from janus_tpu.messages import Duration, Time

    src = STORE_PATH.read_text()
    assert src.count("self._lease_suffix") >= 2, (
        "lease suffix no longer used where leases are claimed"
    )
    eph = EphemeralDatastore(clock=MockClock(Time(1_600_000_000)), engine="pgfake")
    try:
        eph.datastore.run_tx(
            lambda tx: (
                tx.acquire_incomplete_aggregation_jobs(Duration(600), 4),
                tx.acquire_incomplete_collection_jobs(Duration(600), 4),
            ),
            "lease_wire_probe",
        )
        claims = [
            e[1]
            for e in eph.datastore._driver.statements()
            if "lease_attempts = lease_attempts + 1" in e[1]
        ]
        assert len(claims) == 2, claims
        for sql in claims:
            # the lock clause sits at the inner index-ordered window
            assert re.search(r"LIMIT \d+ FOR UPDATE SKIP LOCKED\)", sql), sql
            assert "RETURNING" in sql
            assert "%s" in sql and "?" not in sql
            base = _to_sqlite(sql)
            probe = re.sub(r"\s+RETURNING\s.+$", "", base, flags=re.S)
            assert sqlite3.complete_statement(probe.replace("?", "1") + ";"), sql[:160]
    finally:
        eph.cleanup()


def test_pg_adapter_rewrite_matches_reference_behavior():
    """_PgConnAdapter.execute must translate exactly like the tested
    rewrite (guards against the adapter and this test diverging)."""
    import inspect

    from janus_tpu.datastore.store import _PgConnAdapter

    src = inspect.getsource(_PgConnAdapter)
    assert 'sql.replace("?", "%s")' in src
