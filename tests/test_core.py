"""core layer tests: HPKE round-trips + RFC 9180 test vector, clocks,
auth tokens, retries.

Mirrors reference core/src/hpke.rs tests (round-trip vs
test-vectors.json) and core/src/time.rs tests (SURVEY.md section 4.1).
"""

import pytest

from janus_tpu.core import (
    AuthenticationToken,
    HpkeApplicationInfo,
    Label,
    MockClock,
    RealClock,
    generate_hpke_config_and_private_key,
    hpke_open,
    hpke_seal,
)
from janus_tpu.core.hpke import (
    HpkeError,
    HpkeKeypair,
    _extract_and_expand,
    _key_schedule,
)
from janus_tpu.core.retries import Backoff, retry_http_request
from janus_tpu.messages import Duration, HpkeCiphertext, HpkeConfigId, Role, Time


def test_hpke_round_trip():
    kp = generate_hpke_config_and_private_key(config_id=9)
    info = HpkeApplicationInfo(Label.INPUT_SHARE, Role.CLIENT, Role.LEADER)
    ct = hpke_seal(kp.config, info, b"secret measurement", b"the aad")
    assert ct.config_id == HpkeConfigId(9)
    assert hpke_open(kp, info, ct, b"the aad") == b"secret measurement"


def test_hpke_open_failures():
    kp = generate_hpke_config_and_private_key(config_id=1)
    info = HpkeApplicationInfo(Label.INPUT_SHARE, Role.CLIENT, Role.LEADER)
    ct = hpke_seal(kp.config, info, b"pt", b"aad")
    with pytest.raises(HpkeError):
        hpke_open(kp, info, ct, b"wrong aad")
    wrong_info = HpkeApplicationInfo(Label.INPUT_SHARE, Role.CLIENT, Role.HELPER)
    with pytest.raises(HpkeError):
        hpke_open(kp, wrong_info, ct, b"aad")
    other = generate_hpke_config_and_private_key(config_id=1)
    with pytest.raises(HpkeError):
        hpke_open(other, info, ct, b"aad")
    with pytest.raises(HpkeError):
        hpke_open(kp, info, HpkeCiphertext(HpkeConfigId(2), ct.encapsulated_key, ct.payload), b"aad")


def test_hpke_rfc9180_vector_a1():
    """RFC 9180 appendix A.1 (DHKEM X25519, HKDF-SHA256, AES-128-GCM):
    derive the shared secret / key / base_nonce from the published DH
    inputs and check against the published values."""
    enc = bytes.fromhex("37fda3567bdbd628e88668c3c8d7e97d1d1253b6d4ea6d44c150f741f1bf4431")
    pk_r = bytes.fromhex("3948cfe0ad1ddb695d780e59077195da6c56506b027329794ab02bca80815c4d")
    sk_e = bytes.fromhex("52c4a758a802cd8b936eceea314432798d5baf2d7e9235dc084ab1b9cfa2f736")
    from janus_tpu.core.hpke_backend import x25519_exchange

    dh = x25519_exchange(sk_e, pk_r)
    from janus_tpu.core.hpke import _X25519Kem

    shared_secret = _extract_and_expand(_X25519Kem, dh, enc + pk_r)
    assert shared_secret == bytes.fromhex(
        "fe0e18c9f024ce43799ae393c7e8fe8fce9d218875e8227b0187c04e7d2ea1fc"
    )
    from janus_tpu.core.hpke import HpkeKeypair as _KP
    from janus_tpu.messages import HpkeAeadId, HpkeConfig, HpkeKdfId, HpkeKemId

    cfg = HpkeConfig(
        HpkeConfigId(0),
        HpkeKemId.X25519_HKDF_SHA256,
        HpkeKdfId.HKDF_SHA256,
        HpkeAeadId.AES_128_GCM,
        pk_r,
    )
    aead, base_nonce = _key_schedule(
        cfg, shared_secret, bytes.fromhex("4f6465206f6e2061204772656369616e2055726e")
    )
    assert base_nonce == bytes.fromhex("56d890e5accaaf011cff4b7d")
    # RFC 9180 A.1.1.1 first seal: pt/aad/ct from the published vector
    ct = aead.encrypt(
        base_nonce,
        bytes.fromhex("4265617574792069732074727574682c20747275746820626561757479"),
        bytes.fromhex("436f756e742d30"),
    )
    assert ct == bytes.fromhex(
        "f938558b5d72f1a23810b4be2ab4f84331acc02fc97babc53a52ae8218a355a9"
        "6d8770ac83d07bea87e13c512a"
    )


def test_hpke_suite_matrix_round_trips():
    """Every KEM x KDF x AEAD combination the reference supports
    (core/src/hpke.rs:456 round_trip_check) seals and opens."""
    from janus_tpu.messages import HpkeAeadId, HpkeKdfId, HpkeKemId

    info = HpkeApplicationInfo(Label.INPUT_SHARE, Role.CLIENT, Role.LEADER)
    for kem in (HpkeKemId.X25519_HKDF_SHA256, HpkeKemId.P256_HKDF_SHA256):
        for kdf in (HpkeKdfId.HKDF_SHA256, HpkeKdfId.HKDF_SHA384, HpkeKdfId.HKDF_SHA512):
            for aead in (
                HpkeAeadId.AES_128_GCM,
                HpkeAeadId.AES_256_GCM,
                HpkeAeadId.CHACHA20POLY1305,
            ):
                kp = generate_hpke_config_and_private_key(
                    config_id=3, kem_id=kem, kdf_id=kdf, aead_id=aead
                )
                assert kp.config.kem_id == kem
                ct = hpke_seal(kp.config, info, b"measurement", b"aad")
                assert hpke_open(kp, info, ct, b"aad") == b"measurement"
                with pytest.raises(HpkeError):
                    hpke_open(kp, info, ct, b"bad aad")


def test_hpke_p256_cross_suite_failure():
    """A P-256 recipient cannot open an X25519-sealed ciphertext and
    malformed encapsulated points are rejected, not crashed on."""
    from janus_tpu.messages import HpkeKemId

    info = HpkeApplicationInfo(Label.INPUT_SHARE, Role.CLIENT, Role.LEADER)
    p256 = generate_hpke_config_and_private_key(config_id=5, kem_id=HpkeKemId.P256_HKDF_SHA256)
    x = generate_hpke_config_and_private_key(config_id=5)
    ct = hpke_seal(x.config, info, b"pt", b"aad")
    with pytest.raises(HpkeError):
        hpke_open(p256, info, ct, b"aad")  # 32-byte enc is not a P-256 point
    ct2 = hpke_seal(p256.config, info, b"pt", b"aad")
    bad = HpkeCiphertext(ct2.config_id, b"\x04" + b"\x00" * 64, ct2.payload)
    with pytest.raises(HpkeError):
        hpke_open(p256, info, bad, b"aad")


def test_clocks():
    mc = MockClock(Time(1000))
    assert mc.now() == Time(1000)
    mc.advance(Duration(500))
    assert mc.now() == Time(1500)
    mc.set(Time(7))
    assert mc.now() == Time(7)
    assert RealClock().now().seconds > 1_700_000_000


def test_auth_tokens():
    t = AuthenticationToken.bearer("tok123")
    assert t.request_headers() == {"Authorization": "Bearer tok123"}
    assert t.matches_headers({"authorization": "Bearer tok123"})
    assert not t.matches_headers({"Authorization": "Bearer nope"})
    d = AuthenticationToken.dap_auth("abc")
    assert d.request_headers() == {"DAP-Auth-Token": "abc"}
    assert d.matches_headers({"DAP-Auth-Token": "abc"})
    assert not d.matches_headers({})
    rt = AuthenticationToken.from_dict(t.to_dict())
    assert rt == t
    assert len(AuthenticationToken.random_bearer().token) >= 20


def test_retry_http_request():
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            return 503, b"unavailable"
        return 200, b"ok"

    status, body = retry_http_request(flaky, Backoff.test(), sleep=lambda s: None)
    assert (status, body) == (200, b"ok") and len(calls) == 3

    def always_broken():
        raise ConnectionError("nope")

    with pytest.raises(ConnectionError):
        retry_http_request(always_broken, Backoff.test(), sleep=lambda s: None)

    def bad_request():
        return 400, b"client error"

    assert retry_http_request(bad_request, Backoff.test(), sleep=lambda s: None)[0] == 400
