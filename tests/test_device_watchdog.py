"""Deadline-aware device path (ISSUE 8; docs/ROBUSTNESS.md "Device
hangs & deadlines"): the deadline contextvar/header contract, the
dispatch watchdog (abandon + cap + stack dumps), the engine's hang
quarantine with canary restore, deadline-expired admission shedding,
and the job drivers' step-back translation — a wedged device or a dead
lease budget must never burn a lease TTL or amplify dead work."""

import threading
import time

import numpy as np
import pytest

from janus_tpu import failpoints, metrics
from janus_tpu.aggregator import device_watchdog
from janus_tpu.aggregator.device_watchdog import DeviceHangError, DispatchWatchdog
from janus_tpu.core import deadline as dl

VK = bytes(range(16))


@pytest.fixture(autouse=True)
def _clean():
    """Failpoints and the process watchdog are globals: start and end
    each test disarmed / un-tripped so a hang here can't walk the
    SHARED abandoned cap toward host-only mode for unrelated suites."""
    failpoints.clear()
    device_watchdog.WATCHDOG.reset_for_tests()
    yield
    failpoints.clear()
    time.sleep(0.05)  # released hang-parked workers finish retiring
    device_watchdog.WATCHDOG.reset_for_tests()


# ---------------------------------------------------------------------------
# deadline module
# ---------------------------------------------------------------------------


def test_deadline_scope_and_remaining():
    assert dl.current_deadline() is None
    assert dl.remaining_s() is None
    with dl.deadline_scope(time.monotonic() + 5.0) as d:
        assert dl.current_deadline() == d
        assert 4.0 < dl.remaining_s() <= 5.0
        with dl.deadline_scope(None):  # explicit clear nests
            assert dl.current_deadline() is None
        assert dl.current_deadline() == d
    assert dl.current_deadline() is None


def test_deadline_check_raises_past_deadline_and_counts():
    dl.check("idle")  # no scope: no-op
    with dl.deadline_scope(time.monotonic() + 60):
        dl.check("fresh")  # within budget: no-op
    before = metrics.request_deadline_exceeded_total.get(stage="t_stage")
    with dl.deadline_scope(time.monotonic() - 0.01):
        with pytest.raises(dl.DeadlineExceeded):
            dl.check("t_stage")
    assert metrics.request_deadline_exceeded_total.get(stage="t_stage") == before + 1


def test_deadline_header_roundtrip_and_queue_age():
    # encode: remaining seconds; None when unbounded or already dead
    assert dl.header_value(None) is None
    assert dl.header_value(time.monotonic() - 1) is None
    raw = dl.header_value(time.monotonic() + 10)
    assert 9.0 < float(raw) <= 10.0
    # parse anchors to the receiver's monotonic clock
    parsed = dl.parse_header({dl.DEADLINE_HEADER: raw})
    assert 8.5 < parsed - time.monotonic() <= 10.0
    # header names are case-insensitive (urllib normalizes)
    assert dl.parse_header({dl.DEADLINE_HEADER.lower(): "5"}) is not None
    # queue age backdates: a request that waited 8s of its 5s budget
    # parses to a deadline in the past
    stale = dl.parse_header({dl.DEADLINE_HEADER: "5"}, queue_age_s=8.0)
    assert stale < time.monotonic()
    # garbage/negative/absent are ignored, never fatal
    assert dl.parse_header({dl.DEADLINE_HEADER: "bogus"}) is None
    assert dl.parse_header({dl.DEADLINE_HEADER: "-3"}) is None
    assert dl.parse_header({}) is None


def test_deadline_exceeded_importable_from_retries():
    # canonical home moved; the old import path must keep working
    from janus_tpu.core.retries import DeadlineExceeded

    assert DeadlineExceeded is dl.DeadlineExceeded
    assert issubclass(DeadlineExceeded, TimeoutError)


def test_http_client_stamps_deadline_header():
    """Inside a deadline scope every outbound request carries the
    remaining budget; outside, no header is added."""
    from janus_tpu.binary_utils import HealthServer
    from janus_tpu.core.http_client import HttpClient

    seen = {}
    srv = HealthServer("127.0.0.1:0").start()
    try:
        http = HttpClient()

        # observe what urllib would send by spying on urlopen (the
        # request object carries the merged headers at that point)
        import urllib.request as _ur

        orig_urlopen = _ur.urlopen

        def spy(req, timeout=None):
            seen["headers"] = dict(req.headers)
            return orig_urlopen(req, timeout=timeout)

        _ur.urlopen = spy
        try:
            http.get(f"http://127.0.0.1:{srv.port}/healthz")
            assert not any(
                k.lower() == dl.DEADLINE_HEADER.lower() for k in seen["headers"]
            )
            with dl.deadline_scope(time.monotonic() + 30):
                http.get(f"http://127.0.0.1:{srv.port}/healthz")
            hdr = {k.lower(): v for k, v in seen["headers"].items()}
            assert 28.0 < float(hdr[dl.DEADLINE_HEADER.lower()]) <= 30.0
        finally:
            _ur.urlopen = orig_urlopen
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# watchdog mechanics
# ---------------------------------------------------------------------------


def test_watchdog_disarmed_is_direct_call():
    wd = DispatchWatchdog()
    calls = []
    assert wd.run(lambda: calls.append(threading.get_ident()) or 42) == 42
    # no deadline: ran inline on the caller's thread
    assert calls == [threading.get_ident()]


def test_watchdog_supervised_success_propagates_result_and_errors():
    wd = DispatchWatchdog()
    deadline = time.monotonic() + 10
    assert wd.run(lambda: 7, deadline=deadline) == 7
    with pytest.raises(ValueError, match="boom"):
        wd.run(lambda: (_ for _ in ()).throw(ValueError("boom")), deadline=deadline)
    # worker reuse: successive calls don't grow the thread population
    before = threading.active_count()
    for _ in range(20):
        assert wd.run(lambda: 1, deadline=time.monotonic() + 10) == 1
    assert threading.active_count() <= before + 1


def test_watchdog_propagates_context_into_worker():
    """Trace/deadline contextvars must ride into the worker (spans and
    nested checks depend on it)."""
    wd = DispatchWatchdog()
    with dl.deadline_scope(time.monotonic() + 30):
        got = wd.run(dl.current_deadline, deadline=time.monotonic() + 10)
    assert got is not None


def test_watchdog_abandons_hung_dispatch_and_dumps_stack():
    wd = DispatchWatchdog(abandoned_thread_cap=4)
    gate = threading.Event()
    before_hung = metrics.hung_dispatches_total.get(vdaf="t", op="op1")
    t0 = time.monotonic()
    with pytest.raises(DeviceHangError) as ei:
        wd.run(gate.wait, deadline=time.monotonic() + 0.2, label="op1", vdaf="t")
    assert 0.15 < time.monotonic() - t0 < 2.0  # raised AT the deadline
    assert ei.value.label == "op1"
    assert metrics.hung_dispatches_total.get(vdaf="t", op="op1") == before_hung + 1
    st = wd.status()
    assert st["abandoned_threads"] == 1 and st["host_only"] is False
    (stalled,) = st["stalled"]
    assert stalled["label"] == "op1" and stalled["stack"]  # live stack dump
    assert any("wait" in line for line in stalled["stack"])
    # the wedge clears: the worker retires and the accounting drains
    gate.set()
    deadline = time.monotonic() + 5
    while wd.status()["abandoned_threads"] and time.monotonic() < deadline:
        time.sleep(0.01)
    assert wd.status()["abandoned_threads"] == 0


def test_watchdog_on_hang_hook_fires_before_raise():
    wd = DispatchWatchdog()
    gate = threading.Event()
    hooked = []
    with pytest.raises(DeviceHangError):
        wd.run(
            gate.wait,
            deadline=time.monotonic() + 0.1,
            label="op",
            on_hang=hooked.append,
        )
    assert hooked == ["op"]
    gate.set()


def test_watchdog_cap_trips_host_only_mode():
    wd = DispatchWatchdog(abandoned_thread_cap=2)
    gates = [threading.Event() for _ in range(2)]
    for g in gates:
        with pytest.raises(DeviceHangError):
            wd.run(g.wait, deadline=time.monotonic() + 0.05, label="op")
    assert wd.host_only() is True
    # once tripped, further supervised dispatches refuse immediately
    with pytest.raises(DeviceHangError):
        wd.run(lambda: 1, deadline=time.monotonic() + 10)
    for g in gates:
        g.set()


def test_watchdog_expired_deadline_refuses_before_dispatch():
    wd = DispatchWatchdog()
    with pytest.raises(dl.DeadlineExceeded):
        wd.run(lambda: 1, deadline=time.monotonic() - 0.1)


# ---------------------------------------------------------------------------
# engine quarantine + canary (the device circuit)
# ---------------------------------------------------------------------------


def _job(inst, n=4, seed=1):
    from janus_tpu.vdaf.testing import make_report_batch, random_measurements

    rng = np.random.default_rng(seed)
    return make_report_batch(inst, random_measurements(inst, n, rng), seed=seed)


def test_hang_quarantines_engine_then_canary_restores():
    """The full device-circuit cycle on a real engine: a hung dispatch
    raises DeviceHangError to the caller (NOT absorbed by the OOM
    ladder), the engine serves from the host fallback while
    quarantined (interim work lands), and the canary recompile+probe
    restores the device path with the initial caps."""
    from janus_tpu.aggregator.engine_cache import EngineCache, HostEngineCache
    from janus_tpu.vdaf.registry import VdafInstance

    inst = VdafInstance.count()
    eng = EngineCache(inst, VK)
    eng.QUARANTINE_CANARY_DELAY_SECS = 0.1
    args, m = _job(inst)
    nonce, public, meas, proof, blind0, seeds, blind1 = args
    # healthy reference (also pays the compile outside the hang window)
    want = eng.leader_init(nonce, public, meas, proof, blind0)[2]

    failpoints.configure("engine.dispatch=hang,count=1")
    with dl.deadline_scope(time.monotonic() + 0.4):
        with pytest.raises(DeviceHangError):
            eng.leader_init(nonce, public, meas, proof, blind0)
    assert eng._quarantined is True
    assert eng._backend_state() == "quarantined"
    assert metrics.engine_backend_state.get(vdaf="count", state="quarantined") == 1.0
    assert isinstance(eng._host_fallback, HostEngineCache)

    # interim work lands through the host fallback with correct results
    _, _, ver0_host, _ = eng.leader_init(nonce, public, meas, proof, blind0)
    for a, b in zip(want, ver0_host):
        assert (np.asarray(a) == np.asarray(b)).all()

    # the hang budget is spent: the canary probe succeeds and restores
    failpoints.clear()
    deadline = time.monotonic() + 30
    while eng._quarantined and time.monotonic() < deadline:
        time.sleep(0.05)
    assert eng._quarantined is False
    assert eng._backend_state() == "device"
    assert eng.bucket_cap == eng._initial_bucket_cap
    assert metrics.engine_quarantines_total.get(vdaf="count", event="open") >= 1
    assert metrics.engine_quarantines_total.get(vdaf="count", event="restored") >= 1
    # device path actually serves again
    out0, _, _, _ = eng.leader_init(nonce, public, meas, proof, blind0)
    agg = eng.aggregate(out0, np.ones(4, dtype=bool))
    assert len(agg) >= 1


def test_canary_failure_keeps_quarantine_open():
    """While the device is still wedged (engine.canary hangs too) the
    engine stays quarantined and keeps serving from host; the canary
    backs off and succeeds once the wedge clears."""
    from janus_tpu.aggregator.engine_cache import EngineCache
    from janus_tpu.vdaf.registry import VdafInstance

    inst = VdafInstance.count()
    eng = EngineCache(inst, VK)
    eng.QUARANTINE_CANARY_DELAY_SECS = 0.05
    eng.QUARANTINE_CANARY_TIMEOUT_SECS = 0.2
    args, _ = _job(inst, seed=3)
    nonce, public, meas, proof, blind0, seeds, blind1 = args
    eng.leader_init(nonce, public, meas, proof, blind0)  # compile

    # dispatch hang opens the circuit; the canary's probe hangs as well
    failpoints.configure("engine.dispatch=hang,count=1;engine.canary=hang")
    with dl.deadline_scope(time.monotonic() + 0.4):
        with pytest.raises(DeviceHangError):
            eng.leader_init(nonce, public, meas, proof, blind0)
    deadline = time.monotonic() + 10
    while (
        metrics.engine_quarantines_total.get(vdaf="count", event="canary_failed") < 1
        and time.monotonic() < deadline
    ):
        time.sleep(0.02)
    assert eng._quarantined is True  # failed probe: still quarantined
    # the wedge clears; the backed-off canary restores
    failpoints.clear()
    deadline = time.monotonic() + 30
    while eng._quarantined and time.monotonic() < deadline:
        time.sleep(0.05)
    assert eng._quarantined is False


# ---------------------------------------------------------------------------
# admission + helper handler + driver step-back
# ---------------------------------------------------------------------------


def test_admission_sheds_expired_deadline_503():
    from janus_tpu.ingest.admission import AdmissionConfig, AdmissionController, ShedError

    adm = AdmissionController(AdmissionConfig())
    adm.admit("aggregate")  # no deadline: through
    adm.admit("aggregate", deadline=time.monotonic() + 5)  # live budget
    with pytest.raises(ShedError) as ei:
        adm.admit("aggregate", deadline=time.monotonic() - 0.01)
    assert ei.value.status == 503
    assert ei.value.reason == "deadline_expired"


def test_real_server_queue_age_sheds_expired_deadline():
    """The REAL serving path (socket accept stamp → pool queue →
    handler): with a single-worker pool occupied by a slow request, a
    queued aggregate request whose propagated budget dies while
    waiting sheds 503 deadline_expired — the accept-time stamp must
    survive socket.socket's __slots__ (it rides the server's weak
    map, not the socket object)."""
    import threading as _threading

    from janus_tpu.aggregator.http_handlers import DapServer
    from janus_tpu.core.http_client import fetch_any_status
    from janus_tpu.ingest.admission import AdmissionConfig, AdmissionController
    from janus_tpu.messages import AggregationJobInitializeReq

    adm = AdmissionController(AdmissionConfig())
    first_in = _threading.Event()

    class _App:
        """Minimal DapHttpApp-alike: route PUT aggregation_jobs through
        real admission with the real deadline parse, stall the first
        request to force the second into the accept queue."""

        calls = 0

        def handle(self, method, path, query, headers, body):
            import json as _json

            from janus_tpu.ingest.admission import ShedError

            _App.calls += 1
            me = _App.calls
            try:
                deadline = dl.parse_header(headers, queue_age_s=dl.request_queue_age())
                adm.admit("aggregate", deadline=deadline)
            except ShedError as e:
                return (
                    e.status,
                    "application/problem+json",
                    _json.dumps({"detail": str(e)}).encode(),
                    {},
                )
            if me == 1:
                first_in.set()
                time.sleep(0.8)  # pin the single pool worker
            return 200, "text/plain", b"ok", {}

    srv = DapServer(_App(), max_handler_threads=1)
    srv.start()
    try:
        results = {}

        def send(name, deadline_header):
            headers = {"Content-Type": AggregationJobInitializeReq.MEDIA_TYPE}
            if deadline_header is not None:
                headers[dl.DEADLINE_HEADER] = deadline_header
            results[name] = fetch_any_status(
                srv.url + "tasks/x/aggregation_jobs/y",
                method="PUT",
                body=b"",
                headers=headers,
                timeout=10,
            )

        t1 = _threading.Thread(target=send, args=("slow", "30"))
        t1.start()
        assert first_in.wait(5)
        # queued behind the pinned worker with a 0.2s budget: by the
        # time the worker frees (~0.8s) the budget died IN THE QUEUE
        t2 = _threading.Thread(target=send, args=("queued", "0.2"))
        t2.start()
        t1.join(10)
        t2.join(10)
        assert results["slow"][0] == 200
        status, body = results["queued"]
        assert status == 503, (status, body)
        assert b"deadline_expired" in body
    finally:
        srv.stop()


def test_lease_deadline_floor_never_extends_past_lease():
    """A near-expired (but live) lease gets AT MOST its remaining
    seconds — the old 1 s floor let the step overrun lease expiry and
    run concurrently with a re-acquirer."""
    from janus_tpu.aggregator.job_driver import lease_deadline
    from janus_tpu.messages import Time

    class _Clock:
        def now(self):
            return Time(1_600_000_000)

    class _Lease:
        class expiry:
            seconds = 1_600_000_000 + 1  # 1s of lease left

    d = lease_deadline(_Clock(), _Lease(), skew_s=60)
    assert d - time.monotonic() <= 1.0 + 1e-6  # capped at remaining


def test_stop_canary_ends_loop_without_probe():
    """Process-teardown hook: stop_canary() wakes the quarantined
    engine's canary out of its cool-down and the loop exits WITHOUT
    probing (no native device work racing interpreter finalization);
    the engine stays quarantined, serving host."""
    from janus_tpu.aggregator.engine_cache import EngineCache
    from janus_tpu.vdaf.registry import VdafInstance

    eng = EngineCache(VdafInstance.count(), VK)
    eng.QUARANTINE_CANARY_DELAY_SECS = 30.0  # far future: wait is real
    before = metrics.engine_quarantines_total.get(vdaf="count", event="canary_probe")
    eng._quarantine_on_hang("test")
    assert eng._quarantined and eng._canary_thread.is_alive()
    eng.stop_canary(timeout_s=5.0)
    assert not eng._canary_thread.is_alive()
    assert eng._quarantined is True  # no probe ran, no restore
    assert (
        metrics.engine_quarantines_total.get(vdaf="count", event="canary_probe")
        == before
    )


def test_lease_deadline_raises_on_expired_lease():
    from janus_tpu.aggregator.job_driver import lease_deadline

    class _Lease:
        pass

    class _Clock:
        def now(self):
            from janus_tpu.messages import Time

            return Time(1_600_000_000)

    lease = _Lease()

    class _T:
        def __init__(self, s):
            self.seconds = s

    lease.expiry = _T(1_600_000_000 - 5)  # expired 5s ago
    with pytest.raises(dl.DeadlineExceeded):
        lease_deadline(_Clock(), lease, skew_s=60)
    # a live lease still yields a monotonic bound
    lease.expiry = _T(1_600_000_000 + 100)
    assert lease_deadline(_Clock(), lease, skew_s=60) > time.monotonic()


def test_deadline_request_timeout_raises_instead_of_doomed_floor():
    from janus_tpu.aggregator.job_driver import deadline_request_timeout

    assert deadline_request_timeout(None) is None
    t = deadline_request_timeout(time.monotonic() + 2.0)
    assert 1.5 < t <= 2.0
    # the old max(0.1, …) floor fired a doomed 0.1s attempt here
    with pytest.raises(dl.DeadlineExceeded):
        deadline_request_timeout(time.monotonic() - 0.01)


def _acquired_job(ds):
    from janus_tpu.messages import Duration
    from test_lease_invariants import make_task, put_job

    task = make_task(ds)
    put_job(ds, task, bytes(16))
    (acquired,) = ds.run_tx(
        lambda tx: tx.acquire_incomplete_aggregation_jobs(Duration(600), 1)
    )
    return acquired


@pytest.mark.parametrize(
    "exc,reason",
    [
        (dl.DeadlineExceeded("budget dead"), "deadline_expired"),
        (DeviceHangError("leader_init", 4.0), "device_hang"),
    ],
)
def test_stepper_steps_back_on_deadline_and_hang(monkeypatch, exc, reason):
    """DeadlineExceeded / DeviceHangError from a step are STEP-BACKS
    (lease released, attempt refunded, distinct reason label) — never
    failed attempts marching toward abandonment."""
    from janus_tpu.aggregator.aggregation_job_driver import AggregationJobDriver
    from janus_tpu.core.time_util import MockClock
    from janus_tpu.datastore.store import EphemeralDatastore
    from janus_tpu.messages import Duration, Time

    clock = MockClock(Time(1_600_000_000))
    eph = EphemeralDatastore(clock=clock)
    ds = eph.datastore
    try:
        acquired = _acquired_job(ds)
        drv = AggregationJobDriver(ds, None)
        monkeypatch.setattr(
            drv, "step_aggregation_job", lambda a: (_ for _ in ()).throw(exc)
        )
        before = metrics.job_step_back_total.get(reason=reason)
        drv.stepper(acquired)  # must not raise
        assert metrics.job_step_back_total.get(reason=reason) == before + 1
        clock.advance(Duration(5))
        (re,) = ds.run_tx(
            lambda tx: tx.acquire_incomplete_aggregation_jobs(Duration(600), 1)
        )
        assert re.lease.attempts == 1  # attempt refunded
    finally:
        eph.cleanup()


def test_leader_maps_helper_408_to_deadline_exceeded():
    """The helper's conclusive DEADLINE_EXCEEDED_STATUS answer raises
    DeadlineExceeded at the leader (→ step-back), not a generic job
    failure, and is not retried."""
    from janus_tpu.aggregator.aggregation_job_driver import (
        AggregationJobDriver,
        AggregationJobDriverConfig,
    )
    from janus_tpu.core.retries import Backoff
    from janus_tpu.messages import (
        AggregationJobId,
        AggregationJobInitializeReq,
        PartialBatchSelector,
        Role,
    )
    from janus_tpu.task import QueryTypeConfig, TaskBuilder
    from janus_tpu.vdaf.registry import VdafInstance

    class _DeadlineHttp:
        last_response_headers: dict = {}

        def __init__(self):
            self.calls = 0

        def _req(self, *a, **k):
            self.calls += 1
            return dl.DEADLINE_EXCEEDED_STATUS, b'{"status":408}'

        put = post = _req

    task = (
        TaskBuilder(QueryTypeConfig.time_interval(), VdafInstance.count(), Role.LEADER)
        .with_(helper_aggregator_endpoint="http://helper.test/")
        .build()
    )
    http = _DeadlineHttp()
    drv = AggregationJobDriver(
        None, http, AggregationJobDriverConfig(http_backoff=Backoff.test())
    )
    req = AggregationJobInitializeReq(b"", PartialBatchSelector.time_interval(), ())
    with pytest.raises(dl.DeadlineExceeded):
        drv._send_agg_job_request(task, AggregationJobId(bytes(16)), "PUT", req)
    assert http.calls == 1  # conclusive: never retried


def test_handler_maps_deadline_exceeded_to_408(monkeypatch):
    """A DeadlineExceeded escaping an aggregate handler answers the
    conclusive 408 problem document, not a retryable 5xx."""
    from janus_tpu.aggregator.http_handlers import DapHttpApp
    from janus_tpu.messages import AggregationJobInitializeReq

    app = DapHttpApp.__new__(DapHttpApp)

    class _Admission:
        def admit(self, route_class, deadline=None):
            pass

    monkeypatch.setattr(
        DapHttpApp, "_ensure_ingest", lambda self: (None, _Admission())
    )
    monkeypatch.setattr(
        DapHttpApp,
        "h_aggregate_init",
        lambda self, match, query, headers, body: (_ for _ in ()).throw(
            dl.DeadlineExceeded("died in decrypt")
        ),
    )
    tid = "A" * 43
    jid = "B" * 22
    status, ctype, body, *_ = app._handle(
        "PUT",
        f"/tasks/{tid}/aggregation_jobs/{jid}",
        {},
        {"Content-Type": AggregationJobInitializeReq.MEDIA_TYPE},
        b"",
    )
    assert status == dl.DEADLINE_EXCEEDED_STATUS
    assert ctype == "application/problem+json"
    import json

    assert json.loads(body)["status"] == dl.DEADLINE_EXCEEDED_STATUS


def test_helper_sheds_expired_deadline_before_crypto(monkeypatch):
    """End-to-end handler path: an aggregate-init whose propagated
    deadline is already dead (expired while queued) sheds 503 with the
    deadline_expired reason BEFORE reaching the handler body."""
    from janus_tpu.aggregator.http_handlers import DapHttpApp
    from janus_tpu.ingest.admission import AdmissionConfig, AdmissionController
    from janus_tpu.messages import AggregationJobInitializeReq

    app = DapHttpApp.__new__(DapHttpApp)
    adm = AdmissionController(AdmissionConfig())
    monkeypatch.setattr(DapHttpApp, "_ensure_ingest", lambda self: (None, adm))
    reached = []
    monkeypatch.setattr(
        DapHttpApp,
        "h_aggregate_init",
        lambda self, match, query, headers, body: reached.append(1)
        or (200, "text/plain", b""),
    )
    tid = "A" * 43
    jid = "B" * 22
    before = metrics.upload_shed_counter.get(route="aggregate", reason="deadline_expired")
    # remaining 0.05s, but the request sat 10s in the accept queue
    dl.set_request_queue_age(10.0)
    try:
        result = app._handle(
            "PUT",
            f"/tasks/{tid}/aggregation_jobs/{jid}",
            {},
            {
                "Content-Type": AggregationJobInitializeReq.MEDIA_TYPE,
                dl.DEADLINE_HEADER: "0.05",
            },
            b"",
        )
    finally:
        dl.set_request_queue_age(0.0)
    assert result[0] == 503
    assert reached == []  # shed before any handler/crypto work
    assert (
        metrics.upload_shed_counter.get(route="aggregate", reason="deadline_expired")
        == before + 1
    )
    # a live budget goes through (and the scope is set for the handler)
    result = app._handle(
        "PUT",
        f"/tasks/{tid}/aggregation_jobs/{jid}",
        {},
        {
            "Content-Type": AggregationJobInitializeReq.MEDIA_TYPE,
            dl.DEADLINE_HEADER: "30",
        },
        b"",
    )
    assert result[0] == 200 and reached == [1]
