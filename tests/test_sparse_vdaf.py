"""Block-sparse vector aggregation (ISSUE 17).

A PREAMBLE-style sparse VDAF: each measurement is up to `max_blocks`
(block_index, dense block) pairs over a logical length-L vector. The
FLP legs run at the COMPACT length (max_blocks * block_size); the
block indices are PUBLIC (they ride the public share, bound by the
AAD) and aggregation scatters each verified report's compact blocks
into a dense logical accumulator. These tests pin:

  * the host reference: shard -> wire codec round trip -> two-party
    prepare -> aggregate_sparse -> unshard equals the expanded
    plaintext sum;
  * reject-divergence fuzz between the per-report reference index
    decoder (decode_block_indices) and the vectorized batch fast path
    (decode_index_columns) used by the batched upload validation;
  * out-of-range / duplicate / descending / mid-padding index
    rejection lands on exactly the offending lane;
  * rejected-lane equivalence fuzz: the device scatter path over a
    batch with rejected lanes equals the dense expanded oracle over
    the accepted lanes only, with two-party closure;
  * the resident scatter-merge path (aggregate_pending ->
    resident_merge -> resident_take) including multi-job merges and
    LRU eviction flush — nothing lost, sums exact;
  * prewarm/shape-manifest key separation: a sparse config and the
    dense config with the same compact geometry produce distinct
    manifest keys, and the scatter_merge prewarm variant warms only
    sparse engines;
  * the scatter observability surface: janus_engine_scatter_rows_total,
    janus_engine_sparse_block_occupancy, and the `sparse` sections of
    resident_status / resident_accumulators_status.
"""

import numpy as np
import pytest

from janus_tpu import metrics
from janus_tpu.aggregator.engine_cache import (
    EngineCache,
    HostEngineCache,
    resident_accumulators_status,
)
from janus_tpu.messages import Duration, Interval, Time
from janus_tpu.messages.codec import DecodeError
from janus_tpu.vdaf.reference import (
    Prio3Sparse,
    SparsePublicShare,
    SparseSumVec,
    VdafError,
    validate_block_indices,
)
from janus_tpu.vdaf.registry import VdafInstance, circuit_for, prio3_host
from janus_tpu.vdaf.testing import (
    make_report_batch,
    random_measurements,
    sparse_compact_batch,
)
from janus_tpu.vdaf.wire import (
    IDX_ENC_SIZE,
    Prio3Wire,
    decode_block_indices,
    decode_index_columns,
    encode_block_indices,
    flat_scatter_indices,
)

VK = bytes(range(16))
IV = Interval(Time(0), Duration(3600))


def _inst(**kw):
    d = dict(bits=3, length=48, block_size=4, max_blocks=3)
    d.update(kw)
    return VdafInstance.sparse_sumvec(**d)


def _expanded_oracle(circ, meas, lanes):
    """Plaintext logical-length sums (mod p) over the given lanes."""
    p = circ.FIELD.MODULUS
    want = [0] * circ.logical_length
    for i in lanes:
        for bi, block in meas[i]:
            for off, v in enumerate(block):
                k = bi * circ.block_size + off
                want[k] = (want[k] + int(v)) % p
    return want


# ---------------------------------------------------------------------------
# host reference + wire codec
# ---------------------------------------------------------------------------


def test_registry_round_trip_and_circuit():
    inst = _inst()
    assert inst.kind == "sparse_sumvec"
    d = inst.to_dict()
    assert d["block_size"] == 4 and d["max_blocks"] == 3
    assert VdafInstance.from_dict(d) == inst
    circ = circuit_for(inst)
    assert isinstance(circ, SparseSumVec)
    assert circ.logical_length == 48
    assert circ.output_len == 12  # compact: max_blocks * block_size
    assert circ.agg_output_len == 48  # aggregation is logical-length
    assert isinstance(prio3_host(inst), Prio3Sparse)


def test_host_two_party_through_wire_codec():
    """shard -> encode/decode the public share (indices on the wire) ->
    prepare both parties -> aggregate_sparse -> unshard == plaintext."""
    inst = _inst()
    host = prio3_host(inst)
    circ = host.circuit
    wire = Prio3Wire(circ)
    rng = np.random.default_rng(7)
    meas = random_measurements(inst, 5, rng)
    pairs0, pairs1 = [], []
    for i, m in enumerate(meas):
        nonce = bytes([i]) * 16
        public, (ls, hs) = host.shard(m, nonce)
        raw = wire.encode_public_share(public)
        assert len(raw) == wire.public_share_len
        decoded = wire.decode_public_share(raw)
        assert isinstance(decoded, SparsePublicShare)
        assert tuple(decoded.indices) == tuple(public.indices)
        assert list(decoded) == list(public)
        st0, ps0 = host.prepare_init(VK, 0, nonce, decoded, ls)
        st1, ps1 = host.prepare_init(VK, 1, nonce, decoded, hs)
        prep = host.prepare_shares_to_prep([ps0, ps1])
        out0 = host.prepare_next(st0, prep)
        out1 = host.prepare_next(st1, prep)
        pairs0.append((decoded.indices, out0))
        pairs1.append((decoded.indices, out1))
    agg0 = host.aggregate_sparse(pairs0)
    agg1 = host.aggregate_sparse(pairs1)
    got = host.unshard([agg0, agg1], len(meas))
    want = _expanded_oracle(circ, meas, range(len(meas)))
    assert [int(x) for x in got] == want
    # dense aggregate() without indices must refuse, not mis-aggregate
    with pytest.raises(VdafError):
        host.aggregate([out0])


def test_host_prepare_rejects_invalid_indices():
    inst = _inst()
    host = prio3_host(inst)
    m = [(0, [1, 0, 0, 0]), (3, [0, 2, 0, 0])]
    nonce = bytes(16)
    public, (ls, _) = host.shard(m, nonce)
    for bad in ([0, 0, -1], [3, 0, -1], [99, -1, -1], [0, -1, 1]):
        with pytest.raises(VdafError):
            host.prepare_init(VK, 0, nonce, SparsePublicShare(list(public), bad), ls)


def test_index_blob_codec_goldens():
    inst = _inst()
    circ = circuit_for(inst)
    blob = encode_block_indices([2, 7, -1])
    assert blob == (2).to_bytes(4, "big") + (7).to_bytes(4, "big") + b"\xff" * 4
    assert decode_block_indices(blob, circ) == (2, 7, -1)
    with pytest.raises(DecodeError):
        decode_block_indices(blob + b"\x00", circ)  # wrong length
    with pytest.raises(DecodeError):
        decode_block_indices(encode_block_indices([7, 2, -1]), circ)  # descending


def test_wire_reject_divergence_fuzz():
    """Mutational fuzz: the vectorized batch index decoder must agree
    with the per-report reference decoder on accept/reject for every
    mutated row, and on the decoded indices whenever both accept."""
    inst = _inst(length=64, block_size=4, max_blocks=4)
    circ = circuit_for(inst)
    rng = np.random.default_rng(21)
    blob_len = circ.max_blocks * IDX_ENC_SIZE
    rows, want_ok, want_idx = [], [], []
    for trial in range(300):
        nb = int(rng.integers(1, circ.max_blocks + 1))
        idxs = sorted(rng.choice(circ.n_logical_blocks, size=nb, replace=False).tolist())
        blob = bytearray(
            encode_block_indices(idxs + [-1] * (circ.max_blocks - nb))
        )
        # mutate: random byte flips, lane swaps, truncation to padding
        for _ in range(int(rng.integers(0, 3))):
            kind = int(rng.integers(0, 3))
            if kind == 0:
                blob[int(rng.integers(0, blob_len))] = int(rng.integers(0, 256))
            elif kind == 1:
                a, b = rng.integers(0, circ.max_blocks, size=2)
                a, b = int(a) * 4, int(b) * 4
                blob[a : a + 4], blob[b : b + 4] = blob[b : b + 4], blob[a : a + 4]
            else:
                k = int(rng.integers(0, circ.max_blocks)) * 4
                blob[k : k + 4] = b"\xff" * 4
        blob = bytes(blob)
        try:
            ref = decode_block_indices(blob, circ)
            want_ok.append(True)
            want_idx.append(tuple(ref))
        except DecodeError:
            want_ok.append(False)
            want_idx.append(None)
        rows.append(blob)
    got_idx, got_ok = decode_index_columns(rows, circ)
    assert got_ok.tolist() == want_ok
    for i, ok in enumerate(want_ok):
        if ok:
            assert tuple(int(x) for x in got_idx[i]) == want_idx[i]
        else:
            assert (got_idx[i] == -1).all()  # rejected lanes scatter nothing
    # length divergence: short/None rows reject in the fast path exactly
    # like the reference's length check
    _, ok2 = decode_index_columns([rows[0][:-1], None, rows[0]], circ)
    assert ok2.tolist() == [False, False, True]


def test_rejection_lands_on_offending_lane_only():
    inst = _inst()
    circ = circuit_for(inst)
    good = encode_block_indices([1, 5, -1])
    bad_rows = [
        encode_block_indices([2, 2, -1]),  # duplicate
        encode_block_indices([5, 1, -1]),  # descending
        encode_block_indices([0, 12, -1]),  # out of range (12 blocks: 0..11)
        encode_block_indices([0, -1, 3]),  # value after padding
    ]
    rows = [good, *bad_rows, good]
    idx, ok = decode_index_columns(rows, circ)
    assert ok.tolist() == [True, False, False, False, False, True]
    assert (idx[1:5] == -1).all()
    assert [int(x) for x in idx[0]] == [1, 5, -1]


# ---------------------------------------------------------------------------
# device engine: scatter paths
# ---------------------------------------------------------------------------


def test_engine_scatter_matches_oracle_with_rejected_lanes_fuzz():
    """Two-party batched engine with random accept/reject: the classic
    aggregate_sparse per-bucket scatter reduce equals the expanded
    oracle over accepted lanes only (closure mod p), and rejected lanes
    contribute nothing."""
    inst = _inst()
    eng = EngineCache(inst, VK)
    circ = eng.p3.circ
    p = eng.p3.jf.MODULUS
    rng = np.random.default_rng(99)
    for trial in range(3):
        n = int(rng.integers(4, 9))
        meas = random_measurements(inst, n, rng)
        args, m = make_report_batch(inst, meas, seed=50 + trial)
        nonce, public, mv, proof, blind0, seeds, blind1 = args
        _, block_idx = sparse_compact_batch(inst, meas)
        flat_idx = flat_scatter_indices(block_idx, circ)
        out0, _, ver0, part0 = eng.leader_init(nonce, public, mv, proof, blind0)
        out1, ok, _ = eng.helper_init(
            nonce, public, seeds, blind1, ver0, part0, np.ones(n, dtype=bool)
        )
        assert np.asarray(ok).all()
        accept = rng.random(n) > 0.4
        if not accept.any():
            accept[0] = True
        a = eng.aggregate_sparse(out0, accept, flat_idx)
        b = eng.aggregate_sparse(out1, accept, flat_idx)
        assert len(a) == circ.logical_length
        got = [(int(x) + int(y)) % p for x, y in zip(a, b)]
        want = _expanded_oracle(circ, m, [i for i in range(n) if accept[i]])
        assert got == want


def test_engine_matches_host_engine_fallback():
    """The HostEngineCache fallback's aggregate_sparse is bit-identical
    to the device engine's."""
    inst = _inst()
    eng = EngineCache(inst, VK)
    host = HostEngineCache(inst, VK)
    rng = np.random.default_rng(3)
    n = 5
    meas = random_measurements(inst, n, rng)
    args, _ = make_report_batch(inst, meas, seed=9)
    nonce, public, mv, proof, blind0, seeds, blind1 = args
    _, block_idx = sparse_compact_batch(inst, meas)
    flat_idx = flat_scatter_indices(block_idx, circuit_for(inst))
    out0, _, ver0, part0 = eng.leader_init(nonce, public, mv, proof, blind0)
    accept = np.array([True, False, True, True, False])
    dev = eng.aggregate_sparse(out0, accept, flat_idx)
    hst = host.aggregate_sparse(
        tuple(np.asarray(x) for x in out0.to_numpy())
        if hasattr(out0, "to_numpy")
        else out0,
        accept,
        flat_idx,
    )
    assert [int(x) for x in dev] == [int(x) for x in hst]


def test_resident_scatter_merge_multi_job_and_eviction():
    """Pending sparse deltas merge into resident slots across jobs and
    buckets; LRU eviction past the byte cap FLUSHES (never drops) — the
    sum of all flushed + taken shares equals the plaintext total."""
    inst = _inst()
    eng0 = EngineCache(inst, VK)
    circ = eng0.p3.circ
    p = eng0.p3.jf.MODULUS
    rng = np.random.default_rng(17)
    keys = [(b"task", b"", b"bucket-a"), (b"task", b"", b"bucket-b")]
    flushed: dict[tuple, list[int]] = {k: [0] * circ.logical_length for k in keys}
    truth: dict[tuple, list[int]] = {k: [0] * circ.logical_length for k in keys}

    def add_into(acc, share):
        for i, v in enumerate(share):
            acc[i] = (acc[i] + int(v)) % p

    for trial in range(3):
        n = int(rng.integers(3, 7))
        meas = random_measurements(inst, n, rng)
        args, m = make_report_batch(inst, meas, seed=80 + trial)
        nonce, public, mv, proof, blind0, seeds, blind1 = args
        _, block_idx = sparse_compact_batch(inst, meas)
        flat_idx = flat_scatter_indices(block_idx, circ)
        out0, _, ver0, part0 = eng0.leader_init(nonce, public, mv, proof, blind0)
        _, ok, _ = eng0.helper_init(
            nonce, public, seeds, blind1, ver0, part0, np.ones(n, dtype=bool)
        )
        assert np.asarray(ok).all()
        lane_bucket = rng.integers(0, 2, size=n).astype(np.int32)
        pend = eng0.aggregate_pending(out0, lane_bucket, 2, flat_idx=flat_idx)
        entries = [
            (keys[j], j, int((lane_bucket == j).sum()), IV) for j in range(2)
        ]
        for rec in eng0.resident_merge(entries, pend):
            add_into(flushed[rec["key"]], rec["share"])
        for j in range(2):
            lanes = [i for i in range(n) if lane_bucket[i] == j]
            add_into(truth[keys[j]], _expanded_oracle(circ, m, lanes))
    for rec in eng0.resident_take():
        add_into(flushed[rec["key"]], rec["share"])
    # leader-share-only comparison: truth here is the plaintext, and the
    # leader share alone is NOT the plaintext — so instead assert via
    # the helper closure on a fresh single-job run below; for the
    # multi-job path assert slot arithmetic consistency instead
    # (flushed leader state must equal the classic leader aggregate)
    eng1 = EngineCache(inst, VK)
    check: dict[tuple, list[int]] = {k: [0] * circ.logical_length for k in keys}
    rng = np.random.default_rng(17)
    for trial in range(3):
        n = int(rng.integers(3, 7))
        meas = random_measurements(inst, n, rng)
        args, m = make_report_batch(inst, meas, seed=80 + trial)
        nonce, public, mv, proof, blind0, seeds, blind1 = args
        _, block_idx = sparse_compact_batch(inst, meas)
        flat_idx = flat_scatter_indices(block_idx, circ)
        out0, _, ver0, part0 = eng1.leader_init(nonce, public, mv, proof, blind0)
        eng1.helper_init(
            nonce, public, seeds, blind1, ver0, part0, np.ones(n, dtype=bool)
        )
        lane_bucket = rng.integers(0, 2, size=n).astype(np.int32)
        for j in range(2):
            add_into(
                check[keys[j]], eng1.aggregate_sparse(out0, lane_bucket == j, flat_idx)
            )
    assert flushed == check


def test_resident_two_party_closure():
    """Leader and helper engines both run the resident scatter-merge
    path; their taken shares sum (mod p) to the plaintext expansion."""
    inst = _inst()
    eng = EngineCache(inst, VK)
    circ = eng.p3.circ
    p = eng.p3.jf.MODULUS
    rng = np.random.default_rng(23)
    n = 6
    meas = random_measurements(inst, n, rng)
    args, m = make_report_batch(inst, meas, seed=5)
    nonce, public, mv, proof, blind0, seeds, blind1 = args
    _, block_idx = sparse_compact_batch(inst, meas)
    flat_idx = flat_scatter_indices(block_idx, circ)
    out0, _, ver0, part0 = eng.leader_init(nonce, public, mv, proof, blind0)
    out1, ok, _ = eng.helper_init(
        nonce, public, seeds, blind1, ver0, part0, np.ones(n, dtype=bool)
    )
    assert np.asarray(ok).all()
    key = (b"task", b"", b"bid")
    shares = []
    for out in (out0, out1):
        pend = eng.aggregate_pending(out, np.zeros(n, dtype=np.int32), 1, flat_idx=flat_idx)
        assert eng.resident_merge([(key, 0, n, IV)], pend) == []
        recs = eng.resident_take()
        assert len(recs) == 1 and recs[0]["rows"] == n
        shares.append(recs[0]["share"])
    got = [(int(x) + int(y)) % p for x, y in zip(*shares)]
    assert got == _expanded_oracle(circ, m, range(n))
    # the resident slot held ONE dense logical row, not per-report state
    assert eng._scatter_rows >= 2 * n


def test_sparse_engine_forces_single_device_mesh_fallback():
    """Under the 8-virtual-device test topology a sparse engine must
    fall back to single-device dispatch with an explicit reason (the
    scatter kernel is not mesh-sharded yet); dense engines keep their
    mesh."""
    import jax

    eng = EngineCache(_inst(), VK)
    assert eng.sparse
    if len(jax.devices()) > 1:
        assert eng.mesh is None
        assert eng.mesh_fallback_reason == "sparse_scatter_single_device"
    else:
        assert eng.mesh_fallback_reason is None


# ---------------------------------------------------------------------------
# prewarm / shape-manifest key separation (satellite 5)
# ---------------------------------------------------------------------------


def test_manifest_keys_distinguish_sparse_from_dense():
    """A sparse config and the dense SumVec with the SAME compact
    geometry (so the same bucket sizes and jit shapes) must produce
    different shape-manifest/prewarm keys — a prewarm replay must never
    hand a dense program to a sparse engine or vice versa."""
    from janus_tpu.aggregator.prewarm import _vdaf_key

    sparse = _inst()  # compact length 12
    dense = VdafInstance.sum_vec(length=12, bits=3)
    assert sparse.to_dict() != dense.to_dict()
    assert _vdaf_key(sparse.to_dict()) != _vdaf_key(dense.to_dict())
    # and two sparse configs differing only in block geometry at the
    # same compact length are ALSO distinct prewarm keys
    other = _inst(length=96, block_size=2, max_blocks=6)  # compact 12 too
    assert _vdaf_key(sparse.to_dict()) != _vdaf_key(other.to_dict())


def test_prewarm_scatter_variant_gates_on_sparse():
    """The scatter_merge prewarm variant warms sparse engines (tracing
    the same shapes serving uses) and reports unsupported for dense."""
    from janus_tpu.aggregator.prewarm import _Warmer

    warmer = _Warmer()
    sp = EngineCache(_inst(), VK)
    dn = EngineCache(VdafInstance.sum_vec(length=12, bits=3), VK)
    entry = {"op": "aggregate", "bucket": 32, "key": ["scatter_merge", 32]}
    # dense: never warmed by the sparse variant (a meshed dense engine
    # fails the geometry gate first; a single-device one the sparse gate)
    assert warmer.warm(dn, entry) in ("unsupported", "geometry_mismatch")
    before = sp._scatter_rows
    assert warmer.warm(sp, entry) == "warmed"
    assert sp._scatter_rows > before


# ---------------------------------------------------------------------------
# observability (satellite 2)
# ---------------------------------------------------------------------------


def test_scatter_metrics_and_statusz_sections():
    from janus_tpu.aggregator.engine_cache import engine_cache

    inst = _inst(length=80, block_size=4, max_blocks=3)
    base_rows = metrics.engine_scatter_rows_total.get(vdaf=inst.kind)
    # through the REGISTERED cache so the process-wide statusz rollups
    # (resident_accumulators, mesh) see this engine
    eng = engine_cache(inst, VK)
    rng = np.random.default_rng(31)
    n = 4
    meas = random_measurements(inst, n, rng)
    args, _ = make_report_batch(inst, meas, seed=13)
    nonce, public, mv, proof, blind0, seeds, blind1 = args
    _, block_idx = sparse_compact_batch(inst, meas)
    flat_idx = flat_scatter_indices(block_idx, circuit_for(inst))
    out0, _, ver0, part0 = eng.leader_init(nonce, public, mv, proof, blind0)
    _, ok, _ = eng.helper_init(
        nonce, public, seeds, blind1, ver0, part0, np.ones(n, dtype=bool)
    )
    eng.aggregate_sparse(out0, np.asarray(ok), flat_idx)
    assert metrics.engine_scatter_rows_total.get(vdaf=inst.kind) == base_rows + n
    occ = metrics.engine_sparse_block_occupancy.get(vdaf=inst.kind)
    assert 0.0 < occ <= 1.0
    assert eng._sparse_last_occupancy == occ
    st = eng.resident_status()
    assert st["sparse"]["logical_length"] == 80
    assert st["sparse"]["block_size"] == 4
    assert st["sparse"]["max_blocks"] == 3
    assert st["sparse"]["scatter_rows"] == eng._scatter_rows >= n
    assert st["sparse"]["block_occupancy"] == occ
    agg_st = resident_accumulators_status()
    assert agg_st["sparse"]["engines"] >= 1
    assert agg_st["sparse"]["scatter_rows"] >= n
    # mesh statusz carries the sparse fallback reason field
    from janus_tpu.aggregator.engine_cache import mesh_status

    ms = mesh_status()
    ours = [
        e
        for e in ms.get("engines", [])
        if e.get("fallback_reason") == "sparse_scatter_single_device"
    ]
    import jax

    if len(jax.devices()) > 1:
        assert ours, ms
