"""Device Poplar1 prepare vs the host walk — bit-identical values.

The batched IDPF eval + sketch (vdaf.poplar1_jax) must produce exactly
the host `Poplar1.prepare_init` outputs for both parties, inner
(Field64) and leaf (Field128) levels, arbitrary prefix sets, and
reports that are / are not on the queried paths.
"""

import secrets

import numpy as np
import pytest

from janus_tpu.vdaf.poplar1 import Poplar1, Poplar1AggParam
from janus_tpu.vdaf.poplar1_jax import prepare_init_batched

VK = bytes(range(16))


def _shard_batch(poplar, alphas):
    keys0, keys1 = [], []
    for a in alphas:
        _, (k0, k1) = poplar.shard(a)
        keys0.append(k0)
        keys1.append(k1)
    return keys0, keys1


@pytest.mark.parametrize("bits,level,prefixes", [
    (4, 1, (0, 1, 2, 3)),          # inner level, Field64, full fan
    (4, 3, (0b0110, 0b1011, 0b1111)),  # leaf level, Field128
    (8, 4, (0b01101, 0b10000)),    # sparse prefixes mid-tree
    (2, 0, (0, 1)),                # minimal tree
])
@pytest.mark.parametrize("party", [0, 1])
def test_prepare_init_matches_host(bits, level, prefixes, party):
    poplar = Poplar1(bits)
    rng = np.random.default_rng(bits * 131 + level)
    alphas = [int(rng.integers(0, 1 << bits)) for _ in range(5)]
    keys0, keys1 = _shard_batch(poplar, alphas)
    keys = keys0 if party == 0 else keys1
    param = Poplar1AggParam(level, prefixes)
    nonces = [secrets.token_bytes(16) for _ in alphas]

    y, A, B, a_sh, c_sh = prepare_init_batched(bits, party, keys, param, VK, nonces)

    for i, key in enumerate(keys):
        state, msg1 = poplar.prepare_init(party, key, param, VK, nonces[i])
        assert y[i] == [int(v) for v in state.y_shares], i
        assert A[i] == int(msg1[0]), i
        assert B[i] == int(msg1[1]), i
        assert int(a_sh[i]) == int(state.a_share)
        assert int(c_sh[i]) == int(state.c_share)


def test_two_party_shares_verify_and_aggregate():
    """Device shares from both parties combine into a passing sketch and
    the right aggregate (counts per queried prefix)."""
    bits = 6
    poplar = Poplar1(bits)
    alphas = [0b101011, 0b101011, 0b010000, 0b111111]
    keys0, keys1 = _shard_batch(poplar, alphas)
    level = 2
    prefixes = (0b101, 0b010, 0b110)
    param = Poplar1AggParam(level, prefixes)
    nonces = [secrets.token_bytes(16) for _ in alphas]
    F = poplar.idpf.field_at(level)

    y0, A0, B0, a0, c0 = prepare_init_batched(bits, 0, keys0, param, VK, nonces)
    y1, A1, B1, a1, c1 = prepare_init_batched(bits, 1, keys1, param, VK, nonces)

    agg = [0] * len(prefixes)
    for i in range(len(alphas)):
        A = F.add(A0[i], A1[i])
        B = F.add(B0[i], B1[i])
        sigmas = []
        for party, (a_sh, c_sh) in ((0, (a0[i], c0[i])), (1, (a1[i], c1[i]))):
            s = F.neg(F.sub(F.mul(2 % F.MODULUS, F.mul(A, a_sh)), c_sh))
            if party == 0:
                s = F.add(s, F.sub(F.mul(A, A), B))
            sigmas.append(s)
        assert F.add(sigmas[0], sigmas[1]) == 0, f"sketch failed for report {i}"
        agg = [F.add(g, F.add(u, v)) for g, u, v in zip(agg, y0[i], y1[i])]

    want = [sum(1 for a in alphas if (a >> (bits - level - 1)) == p) for p in prefixes]
    assert [int(x) for x in agg] == want
