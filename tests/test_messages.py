"""DAP message round-trips + fixed byte-layout vectors.

Mirrors the reference's message tests (messages/src/lib.rs inline test
modules use hex golden vectors; SURVEY.md section 4.1).
"""

import pytest

from janus_tpu import messages as m


def rt(obj, cls=None, *args):
    raw = obj.to_bytes()
    back = (cls or type(obj)).from_bytes(raw, *args)
    assert back == obj
    return raw


def test_fixed_length_ids():
    for cls in (m.TaskId, m.BatchId, m.ReportId, m.AggregationJobId, m.CollectionJobId):
        v = cls.random()
        assert len(rt(v)) == cls.SIZE
        with pytest.raises(ValueError):
            cls(b"\x00")


def test_time_interval_layout():
    iv = m.Interval(m.Time(0x0102030405060708), m.Duration(0x1122334455667788))
    raw = rt(iv)
    assert raw == bytes.fromhex("0102030405060708" "1122334455667788")
    assert iv.end == m.Time(0x0102030405060708 + 0x1122334455667788)
    assert iv.contains(m.Time(0x0102030405060709))
    assert not iv.contains(iv.end)


def test_time_rounding():
    t = m.Time(12345)
    assert t.to_batch_interval_start(m.Duration(100)) == m.Time(12300)
    assert m.Interval(m.Time(200), m.Duration(400)).aligned_to(m.Duration(100))
    assert not m.Interval(m.Time(250), m.Duration(400)).aligned_to(m.Duration(100))


def test_checksum_xor_combine():
    a, b = m.ReportId(b"a" * 16), m.ReportId(b"b" * 16)
    ca = m.ReportIdChecksum.for_report_id(a)
    cb = m.ReportIdChecksum.for_report_id(b)
    combined = ca.combined_with(cb)
    assert combined == m.ReportIdChecksum().updated_with(a).updated_with(b)
    assert combined.combined_with(cb) == ca  # XOR involution
    rt(combined)


def test_hpke_structs():
    cfg = m.HpkeConfig(
        m.HpkeConfigId(7),
        m.HpkeKemId.X25519_HKDF_SHA256,
        m.HpkeKdfId.HKDF_SHA256,
        m.HpkeAeadId.AES_128_GCM,
        b"\x01" * 32,
    )
    raw = rt(cfg)
    assert raw[:7] == bytes.fromhex("07" "0020" "0001" "0001")
    rt(m.HpkeConfigList((cfg, cfg)))
    ct = m.HpkeCiphertext(m.HpkeConfigId(7), b"enc-key", b"payload")
    rt(ct)


def test_report_roundtrip():
    meta = m.ReportMetadata(m.ReportId.random(), m.Time(1700000000))
    ct = m.HpkeCiphertext(m.HpkeConfigId(1), b"ek", b"pl")
    rep = m.Report(meta, b"public", ct, ct)
    rt(rep)
    pis = m.PlaintextInputShare((m.Extension(m.ExtensionType.TBD, b"x"),), b"payload")
    rt(pis)
    aad = m.InputShareAad(m.TaskId.random(), meta, b"public")
    rt(aad)


def test_queries_and_selectors():
    iv = m.Interval(m.Time(1000), m.Duration(100))
    rt(m.Query.time_interval(iv))
    rt(m.Query.fixed_size(m.FixedSizeQuery(m.FixedSizeQuery.CURRENT_BATCH)))
    bid = m.BatchId.random()
    rt(m.Query.fixed_size(m.FixedSizeQuery(m.FixedSizeQuery.BY_BATCH_ID, bid)))
    rt(m.PartialBatchSelector.time_interval())
    rt(m.PartialBatchSelector.fixed_size(bid))
    rt(m.BatchSelector.time_interval(iv))
    rt(m.BatchSelector.fixed_size(bid))
    rt(m.CollectionReq(m.Query.time_interval(iv), b"param"))


def test_aggregation_job_messages():
    from janus_tpu.vdaf.wire import PP_CONTINUE, PP_INITIALIZE, encode_pingpong

    meta = m.ReportMetadata(m.ReportId.random(), m.Time(1700000000))
    ct = m.HpkeCiphertext(m.HpkeConfigId(1), b"ek", b"pl")
    share = m.ReportShare(meta, b"pub", ct)
    init = m.PrepareInit(share, encode_pingpong(PP_INITIALIZE, None, b"prep-share"))
    req = m.AggregationJobInitializeReq(b"", m.PartialBatchSelector.time_interval(), (init, init))
    rt(req)

    resp = m.AggregationJobResp(
        (
            m.PrepareResp(
                meta.report_id,
                m.PrepareStepResult.cont(encode_pingpong(PP_CONTINUE, b"msg", b"share")),
            ),
            m.PrepareResp(meta.report_id, m.PrepareStepResult.finished()),
            m.PrepareResp(
                meta.report_id,
                m.PrepareStepResult.reject(m.PrepareError.VDAF_PREP_ERROR),
            ),
        )
    )
    rt(resp)

    cont = m.AggregationJobContinueReq(
        m.AggregationJobStep(1),
        (m.PrepareContinue(meta.report_id, encode_pingpong(PP_INITIALIZE, None, b"m")),),
    )
    rt(cont)
    assert m.AggregationJobStep(0).increment() == m.AggregationJobStep(1)


def test_collection_and_share_messages():
    iv = m.Interval(m.Time(1000), m.Duration(100))
    ct = m.HpkeCiphertext(m.HpkeConfigId(1), b"ek", b"pl")
    rt(m.Collection(m.PartialBatchSelector.time_interval(), 5, iv, ct, ct))
    rt(
        m.AggregateShareReq(
            m.BatchSelector.time_interval(iv), b"", 5, m.ReportIdChecksum(b"\x05" * 32)
        )
    )
    rt(m.AggregateShare(ct))
    rt(m.AggregateShareAad(m.TaskId.random(), b"p", m.BatchSelector.time_interval(iv)))


def test_decode_errors():
    with pytest.raises(m.DecodeError):
        m.Interval.from_bytes(b"\x00" * 15)
    with pytest.raises(m.DecodeError):
        m.Interval.from_bytes(b"\x00" * 17)  # trailing byte
    with pytest.raises(m.DecodeError):
        m.Query.from_bytes(b"\x09")  # unknown query type
    with pytest.raises(m.DecodeError):
        m.Role.from_bytes(b"\x0a")


def test_roles():
    assert m.Role.from_bytes(b"\x02") == m.Role.LEADER
    assert m.Role.LEADER.to_bytes() == b"\x02"


def test_problem_types():
    pt = m.DapProblemType.REPORT_REJECTED
    assert pt.type_uri == "urn:ietf:params:ppm:dap:error:reportRejected"
    assert m.DapProblemType.from_uri(pt.type_uri) is pt
    doc = pt.document(task_id="abc", detail="nope")
    assert doc["type"].endswith("reportRejected") and doc["taskid"] == "abc"
