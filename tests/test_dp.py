"""Differential-privacy tests: exact discrete-Gaussian sampler sanity,
share-noising mechanics, and a full two-aggregator round with DP where
the collected fixed-point result carries both parties' noise."""

import dataclasses
import math
from fractions import Fraction

import pytest

from janus_tpu.dp import DpStrategy, add_noise_to_agg_share, discrete_gaussian
from janus_tpu.fields.field import Field128
from janus_tpu.vdaf.reference import fp_encode_floats
from janus_tpu.vdaf.registry import VdafInstance


def test_discrete_gaussian_moments():
    sigma = 5
    n = 1500
    xs = [discrete_gaussian(Fraction(sigma)) for _ in range(n)]
    mean = sum(xs) / n
    var = sum((x - mean) ** 2 for x in xs) / n
    # mean standard error ~ sigma/sqrt(n) ~ 0.13; allow 6x
    assert abs(mean) < 1.0
    # variance concentrates around sigma^2 = 25
    assert 15 < var < 40


def test_discrete_gaussian_small_sigma_is_tight():
    xs = [discrete_gaussian(Fraction(1, 2)) for _ in range(200)]
    assert all(abs(x) <= 5 for x in xs)
    assert any(x != 0 for x in xs)  # but it is noise


def test_add_noise_none_is_identity():
    share = Field128.encode_vec([1, 2, 3])
    assert add_noise_to_agg_share(DpStrategy(), Field128, share) == share
    assert add_noise_to_agg_share(DpStrategy("discrete_gaussian", 0.0), Field128, share) == share
    assert add_noise_to_agg_share(DpStrategy("discrete_gaussian", 5.0), Field128, None) is None


def test_add_noise_perturbs_within_tails():
    truth = [1000, 2000, 3000]
    share = Field128.encode_vec(truth)
    strategy = DpStrategy("discrete_gaussian", 8.0)
    noised = Field128.decode_vec(add_noise_to_agg_share(strategy, Field128, share))
    half = Field128.MODULUS // 2
    for got, want in zip(noised, truth):
        delta = got - want if got - want < half else got - want - Field128.MODULUS
        assert abs(delta) < 8 * 10  # 10 sigma


@pytest.mark.slow  # 64s fixedpoint live pair; DP noise properties stay fast in the moment/tail tests above (ISSUE 1)
def test_dp_end_to_end_fixed_point():
    from janus_tpu.aggregator import Aggregator, Config
    from janus_tpu.aggregator.aggregation_job_creator import (
        AggregationJobCreator,
        AggregationJobCreatorConfig,
    )
    from janus_tpu.aggregator.aggregation_job_driver import AggregationJobDriver
    from janus_tpu.aggregator.collection_job_driver import CollectionJobDriver
    from janus_tpu.aggregator.http_handlers import DapHttpApp, DapServer
    from janus_tpu.aggregator.job_driver import JobDriver, JobDriverConfig
    from janus_tpu.client import Client, ClientParameters
    from janus_tpu.collector import Collector, CollectorParameters
    from janus_tpu.core.hpke import generate_hpke_config_and_private_key
    from janus_tpu.core.http_client import HttpClient
    from janus_tpu.core.time_util import MockClock
    from janus_tpu.datastore.store import EphemeralDatastore
    from janus_tpu.messages import Duration, Interval, Query, Role, Time
    from janus_tpu.task import QueryTypeConfig, TaskBuilder

    clock = MockClock(Time(1_600_000_000))
    leader_eph = EphemeralDatastore(clock=clock)
    helper_eph = EphemeralDatastore(clock=clock)
    leader_srv = DapServer(DapHttpApp(Aggregator(leader_eph.datastore, clock, Config()))).start()
    helper_srv = DapServer(DapHttpApp(Aggregator(helper_eph.datastore, clock, Config()))).start()
    try:
        vdaf = VdafInstance.fixed_point_vec(length=2, bits=16)
        sigma = 4.0  # raw units; 4/32768 in value space
        collector_kp = generate_hpke_config_and_private_key(config_id=200)
        leader_task = (
            TaskBuilder(QueryTypeConfig.time_interval(), vdaf, Role.LEADER)
            .with_(
                leader_aggregator_endpoint=leader_srv.url,
                helper_aggregator_endpoint=helper_srv.url,
                collector_hpke_config=collector_kp.config,
                min_batch_size=1,
                dp_strategy=DpStrategy("discrete_gaussian", sigma),
            )
            .build()
        )
        helper_task = dataclasses.replace(
            leader_task,
            role=Role.HELPER,
            hpke_keys=(generate_hpke_config_and_private_key(config_id=1),),
        )
        leader_eph.datastore.run_tx(lambda tx: tx.put_task(leader_task))
        helper_eph.datastore.run_tx(lambda tx: tx.put_task(helper_task))

        http = HttpClient()
        params = ClientParameters(
            leader_task.task_id, leader_srv.url, helper_srv.url, leader_task.time_precision
        )
        client = Client.with_fetched_configs(params, vdaf, http, clock=clock)
        meas = [[0.25, -0.5], [0.25, 0.25]]
        for m in meas:
            client.upload(fp_encode_floats(m, 16))

        AggregationJobCreator(
            leader_eph.datastore, AggregationJobCreatorConfig(min_aggregation_job_size=1)
        ).run_once()
        drv = AggregationJobDriver(leader_eph.datastore, http)
        JobDriver(JobDriverConfig(), drv.acquirer(), drv.stepper).run_once()

        start = clock.now().to_batch_interval_start(leader_task.time_precision)
        query = Query.time_interval(Interval(Time(start.seconds - 3600), Duration(2 * 3600)))
        collector = Collector(
            CollectorParameters(
                leader_task.task_id, leader_srv.url, leader_task.collector_auth_token, collector_kp
            ),
            vdaf,
            http,
        )
        job_id = collector.start_collection(query)
        cdrv = CollectionJobDriver(leader_eph.datastore, http)
        JobDriver(JobDriverConfig(), cdrv.acquirer(), cdrv.stepper).run_once()
        result = collector.poll_once(job_id, query)

        want = [0.5, -0.25]
        tol = 12 * sigma * math.sqrt(2) / (1 << 15)  # 12 sigma_total in value space
        assert result.report_count == 2
        deltas = [abs(g - w) for g, w in zip(result.aggregate_result, want)]
        assert all(d <= tol for d in deltas), (result.aggregate_result, want, tol)
        # and it really is noised (collision with the exact sum is ~impossible...
        # only with probability ~P[two independent dgauss sums == 0])
    finally:
        leader_srv.stop()
        helper_srv.stop()
        leader_eph.cleanup()
        helper_eph.cleanup()
