"""In-process SLO burn-rate engine (janus_tpu/slo.py; ISSUE 10).

Unit tests drive the engine with a synthetic clock over the real
metrics registry: burn-rate math, multi-window AND semantics, firing/
recovery transitions, latency and condition signals, YAML config
merging over the built-ins, the exported gauges, and the /alertz +
statusz snapshots. The live-HTTP proof (a failpoint 5xx storm flipping
the default alert over a real listener) rides the bench dry-run's
`slo_alert` record, pinned by tests/test_tools.py.
"""

from __future__ import annotations

import pytest

from janus_tpu import metrics as m
from janus_tpu import slo
from janus_tpu.metrics import compile_matchers


class FakeTime:
    def __init__(self, t=1000.0):
        self.t = float(t)

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def _counter(name, **kw):
    return m.REGISTRY.counter(name)


@pytest.fixture()
def clock():
    return FakeTime()


def _ratio_slo(name, good_counter, bad_counter, objective=0.999, windows=None):
    return slo.SloDefinition(
        name=name,
        objective=objective,
        signal=slo.RatioSignal(
            good=(slo.Selector(good_counter, ()),),
            bad=(slo.Selector(bad_counter, ()),),
        ),
        windows=tuple(
            slo.BurnWindow.from_dict(w)
            for w in (
                windows
                or (
                    {"long_secs": 10.0, "short_secs": 2.0, "burn_rate": 14.4, "severity": "page"},
                )
            )
        ),
    )


def test_burn_rate_math_and_firing_transitions(clock):
    good = m.REGISTRY.counter("janus_t_slo_good_a_total")
    bad = m.REGISTRY.counter("janus_t_slo_bad_a_total")
    bad.add(0)  # materialize the series so the window starts sampling
    eng = slo.SloEngine(
        [_ratio_slo("t_ratio_a", good.name, bad.name)],
        interval_s=1.0,
        time_fn=clock,
    )
    # healthy traffic: no burn
    for _ in range(5):
        good.add(10)
        eng.evaluate_once()
        clock.advance(1.0)
    doc = eng.alertz_doc()
    (alert,) = doc["alerts"]
    assert alert["state"] == "ok"
    assert alert["burn_rate_long"] == 0.0
    assert m.alert_active.get(alert="t_ratio_a", severity="page") == 0.0

    # 50% errors in the recent ticks: the SHORT window sees pure 50%
    # (burn 500x the 0.001 budget), the LONG window dilutes it with the
    # healthy phase — both far over the 14.4 threshold
    for _ in range(3):
        good.add(5)
        bad.add(5)
        eng.evaluate_once()
        clock.advance(1.0)
    doc = eng.alertz_doc()
    (alert,) = doc["alerts"]
    assert alert["state"] == "firing"
    assert alert["firing_since_unix"] is not None
    assert 14.4 <= alert["burn_rate_long"] <= 500.0
    assert alert["burn_rate_short"] == pytest.approx(500.0, rel=0.3)
    assert doc["firing"] == ["t_ratio_a/page"]
    assert m.alert_active.get(alert="t_ratio_a", severity="page") == 1.0
    # burn-rate gauge exported per window
    assert m.slo_burn_rate.get(slo="t_ratio_a", window="10s") > 14.4

    # recovery: healthy traffic until the bad burst slides out of the
    # 10s long window
    for _ in range(15):
        good.add(10)
        eng.evaluate_once()
        clock.advance(1.0)
    doc = eng.alertz_doc()
    (alert,) = doc["alerts"]
    assert alert["state"] == "ok"
    assert alert["firing_since_unix"] is None
    assert m.alert_active.get(alert="t_ratio_a", severity="page") == 0.0


def test_multiwindow_and_semantics_short_window_gates(clock):
    """A burst that has already stopped keeps the LONG window hot but
    empties the SHORT window — the alert must NOT fire (the whole point
    of multi-window alerting: no paging on stale burn)."""
    good = m.REGISTRY.counter("janus_t_slo_good_b_total")
    bad = m.REGISTRY.counter("janus_t_slo_bad_b_total")
    good.add(0)
    bad.add(0)
    eng = slo.SloEngine(
        [_ratio_slo("t_ratio_b", good.name, bad.name)],
        interval_s=1.0,
        time_fn=clock,
    )
    eng.evaluate_once()
    clock.advance(1.0)
    bad.add(100)  # one hard burst
    eng.evaluate_once()
    clock.advance(1.0)
    # 3s later: short window (2s) covers only quiet ticks
    for _ in range(3):
        good.add(10)
        eng.evaluate_once()
        clock.advance(1.0)
    doc = eng.alertz_doc()
    (alert,) = doc["alerts"]
    assert alert["burn_rate_long"] > 14.4  # long window still remembers
    assert alert["burn_rate_short"] == 0.0
    assert alert["state"] == "ok"


def test_no_traffic_means_no_burn(clock):
    good = m.REGISTRY.counter("janus_t_slo_good_c_total")
    bad = m.REGISTRY.counter("janus_t_slo_bad_c_total")
    eng = slo.SloEngine(
        [_ratio_slo("t_ratio_c", good.name, bad.name)], interval_s=1.0, time_fn=clock
    )
    # a registered-but-never-incremented counter has no samples: the
    # window freezes as no-data rather than recording fake all-good
    eng.evaluate_once()
    assert eng.alertz_doc()["slos"][0]["no_data"] is True
    good.add(0)  # series born, still zero traffic
    for _ in range(5):
        eng.evaluate_once()
        clock.advance(1.0)
    doc = eng.alertz_doc()
    (alert,) = doc["alerts"]
    assert alert["state"] == "ok"
    assert alert["burn_rate_long"] == 0.0
    assert doc["slos"][0]["no_data"] is False


def test_missing_series_is_no_data_not_all_good(clock):
    eng = slo.SloEngine(
        [_ratio_slo("t_ratio_d", "janus_t_never_registered_a", "janus_t_never_registered_b")],
        interval_s=1.0,
        time_fn=clock,
    )
    eng.evaluate_once()
    doc = eng.alertz_doc()
    assert doc["slos"][0]["no_data"] is True
    assert doc["slos"][0]["evidence"] == {
        "good:janus_t_never_registered_a": None,
        "bad:janus_t_never_registered_b": None,
    }


def test_latency_signal_threshold_rounds_up_to_bucket(clock):
    hist = m.REGISTRY.histogram("janus_t_slo_lat_seconds", buckets=(0.1, 1.0, 10.0))
    definition = slo.SloDefinition(
        name="t_latency",
        objective=0.9,
        signal=slo.LatencySignal(
            metric=hist.name, labels=compile_matchers({"stage": "x"}), threshold_s=0.5
        ),
        windows=(
            slo.BurnWindow(long_s=10.0, short_s=2.0, burn_rate=2.0, severity="page"),
        ),
    )
    assert definition.signal.effective_threshold_s() == 1.0  # 0.5 rounds up
    eng = slo.SloEngine([definition], interval_s=1.0, time_fn=clock)
    # prime the series with fast observations, then a slow burst: the
    # window delta is 4 fast + 4 slow -> err 0.5, budget 0.1 -> burn 5
    for _ in range(4):
        hist.observe(0.2, stage="x")
    eng.evaluate_once()
    clock.advance(1.0)
    for _ in range(4):
        hist.observe(0.2, stage="x")
        hist.observe(5.0, stage="x")
    eng.evaluate_once()
    doc = eng.alertz_doc()
    (alert,) = doc["alerts"]
    assert alert["state"] == "firing"
    assert doc["slos"][0]["effective_threshold_s"] == 1.0
    # other-label observations are invisible to the matcher
    hist.observe(99.0, stage="other")
    good_n, total, n = hist.le_total_matching(1.0, compile_matchers({"stage": "x"}))
    assert total == 12 and good_n == 8 and n == 1


def test_condition_signal_gauge_and_delta(clock):
    gauge = m.REGISTRY.gauge("janus_t_slo_cond_gauge")
    counter = m.REGISTRY.counter("janus_t_slo_cond_delta_total")
    definition = slo.SloDefinition(
        name="t_condition",
        objective=0.5,  # budget 0.5: fires when >50% of ticks are bad
        signal=slo.ConditionSignal(
            conditions=(
                slo.Condition(selector=slo.Selector(gauge.name, ()), op=">", value=0.0),
                slo.Condition(
                    selector=slo.Selector(counter.name, ()),
                    op=">",
                    value=0.0,
                    mode="delta",
                ),
            )
        ),
        windows=(
            slo.BurnWindow(long_s=6.0, short_s=2.0, burn_rate=1.5, severity="page"),
        ),
    )
    gauge.set(0)
    eng = slo.SloEngine([definition], interval_s=1.0, time_fn=clock)
    for _ in range(3):
        eng.evaluate_once()
        clock.advance(1.0)
    assert eng.alertz_doc()["alerts"][0]["state"] == "ok"

    # gauge goes unhealthy: every tick is bad -> burn = 1/0.5 = 2 > 1.5
    gauge.set(3)
    for _ in range(6):
        eng.evaluate_once()
        clock.advance(1.0)
    assert eng.alertz_doc()["alerts"][0]["state"] == "firing"

    # recover the gauge; ticks go good again
    gauge.set(0)
    for _ in range(8):
        eng.evaluate_once()
        clock.advance(1.0)
    assert eng.alertz_doc()["alerts"][0]["state"] == "ok"

    # a counter DELTA (new hung dispatch) makes the tick bad once,
    # without latching forever
    counter.add(2)
    eng.evaluate_once()
    ev = eng.alertz_doc()["slos"][0]["evidence"]
    assert ev[f"increase({counter.name}) > 0"] == 2.0
    st = eng._condition_state[id(definition.signal)]
    assert st["bad"] >= 1


def test_window_scale_shrinks_ladder_uniformly(clock):
    good = m.REGISTRY.counter("janus_t_slo_good_e_total")
    bad = m.REGISTRY.counter("janus_t_slo_bad_e_total")
    definition = _ratio_slo(
        "t_ratio_e",
        good.name,
        bad.name,
        windows=(
            {"long_secs": 3600.0, "short_secs": 300.0, "burn_rate": 14.4, "severity": "page"},
        ),
    )
    # scale 1/900: the 1h window behaves as 4s, but the LABEL keeps the
    # nominal window (dashboards stay stable across test configs)
    eng = slo.SloEngine(
        [definition], interval_s=1.0, window_scale=1.0 / 900, time_fn=clock
    )
    bad.add(10)
    eng.evaluate_once()
    clock.advance(1.0)
    bad.add(10)
    eng.evaluate_once()
    assert eng.alertz_doc()["alerts"][0]["state"] == "firing"
    assert m.slo_burn_rate.get(slo="t_ratio_e", window="1h") > 14.4
    # 6 scaled seconds later the 4s-effective long window is clean
    for _ in range(6):
        clock.advance(1.0)
        good.add(1)
        eng.evaluate_once()
    assert eng.alertz_doc()["alerts"][0]["state"] == "ok"


def test_error_budget_remaining_ratio(clock):
    good = m.REGISTRY.counter("janus_t_slo_good_f_total")
    bad = m.REGISTRY.counter("janus_t_slo_bad_f_total")
    definition = _ratio_slo("t_ratio_f", good.name, bad.name, objective=0.9)
    eng = slo.SloEngine(
        [definition], interval_s=1.0, budget_window_s=100.0, time_fn=clock
    )
    good.add(0)
    bad.add(0)
    eng.evaluate_once()
    clock.advance(1.0)
    good.add(95)
    bad.add(5)  # 5% errors against a 10% budget: half the budget left
    eng.evaluate_once()
    doc = eng.alertz_doc()
    assert doc["slos"][0]["error_budget_remaining_ratio"] == pytest.approx(0.5, abs=0.01)
    assert m.slo_error_budget_remaining.get(slo="t_ratio_f") == pytest.approx(
        0.5, abs=0.01
    )


def test_builtin_definitions_cover_the_paper_surface():
    names = {d.name for d in slo.BUILTIN_SLOS()}
    assert names == {
        "upload_availability",
        "aggregate_step_latency",
        "collect_latency",
        "datastore_up",
        "device_health",
        "peer_reachable",
        "resource_trend",
        "report_conservation",
        "resident_lost",
    }
    for d in slo.BUILTIN_SLOS():
        assert 0 < d.objective < 1
        # every built-in ships the two-rung workbook ladder
        assert {w.severity for w in d.windows} == {"page", "ticket"}


def test_config_merges_over_builtins_by_name():
    cfg = slo.SloEngineConfig.from_dict(
        {
            "evaluation_interval_secs": 2.5,
            "window_scale": 0.5,
            "definitions": [
                # partial override: tighten a built-in without
                # re-stating its signal
                {"name": "upload_availability", "objective": 0.9999},
                # drop one
                {"name": "device_health", "enabled": False},
                # add a custom one
                {
                    "name": "custom_ratio",
                    "objective": 0.99,
                    "signal": {
                        "kind": "counter_ratio",
                        "good": [{"metric": "janus_t_cfg_good_total"}],
                        "bad": [
                            {
                                "metric": "janus_t_cfg_bad_total",
                                "labels": {"reason": "~x.*"},
                            }
                        ],
                    },
                    "windows": [
                        {
                            "long_secs": 60,
                            "short_secs": 5,
                            "burn_rate": 10,
                            "severity": "page",
                        }
                    ],
                },
            ],
        }
    )
    assert cfg.evaluation_interval_s == 2.5
    defs = {d.name: d for d in cfg.build_definitions()}
    assert "device_health" not in defs
    assert defs["upload_availability"].objective == 0.9999
    # the built-in signal survived the partial override
    assert isinstance(defs["upload_availability"].signal, slo.RatioSignal)
    custom = defs["custom_ratio"]
    assert isinstance(custom.signal, slo.RatioSignal)
    assert custom.windows[0].burn_rate == 10.0


def test_config_rejects_unknown_signal_kind_and_missing_name():
    with pytest.raises(ValueError, match="unknown SLO signal kind"):
        slo.signal_from_dict({"kind": "nope"})
    cfg = slo.SloEngineConfig(definitions=({"objective": 0.9},))
    with pytest.raises(ValueError, match="without a name"):
        cfg.build_definitions()


def test_install_uninstall_and_alertz_snapshot():
    assert slo.get_slo_engine() is None or slo.uninstall_slo_engine() is None
    disabled = slo.alertz_snapshot()
    assert disabled == {"enabled": False, "firing": [], "alerts": [], "slos": []}
    engine = slo.install_slo_engine(
        slo.SloEngineConfig(evaluation_interval_s=0.05), start=False
    )
    try:
        engine.evaluate_once()
        doc = slo.alertz_snapshot()
        assert doc["enabled"] is True
        assert len(doc["slos"]) == len(slo.BUILTIN_SLOS())
        assert all("burn_rates" in s for s in doc["slos"])
        # the statusz section is registered and compact
        from janus_tpu.statusz import status_snapshot

        snap = status_snapshot()
        assert "slo" in snap
        assert "budget_remaining" in snap["slo"]
    finally:
        slo.uninstall_slo_engine()
    assert slo.get_slo_engine() is None
    from janus_tpu.statusz import status_snapshot

    assert "slo" not in status_snapshot()


def test_engine_thread_runs_and_stops():
    import time as _time

    engine = slo.SloEngine(
        [  # a tiny definition so the loop does real work
            _ratio_slo(
                "t_thread", "janus_t_slo_good_a_total", "janus_t_slo_bad_a_total"
            )
        ],
        interval_s=0.02,
    )
    engine.start()
    deadline = _time.monotonic() + 5
    while engine.alertz_doc()["evaluations"] < 3 and _time.monotonic() < deadline:
        _time.sleep(0.01)
    assert engine.alertz_doc()["evaluations"] >= 3
    engine.stop()
    n = engine.alertz_doc()["evaluations"]
    _time.sleep(0.1)
    assert engine.alertz_doc()["evaluations"] == n  # loop really stopped


def test_broken_definition_does_not_kill_the_ladder(clock):
    class ExplodingSignal:
        kind = "exploding"

        def read(self, engine):
            raise RuntimeError("boom")

        def evidence(self):
            return {}

    good = m.REGISTRY.counter("janus_t_slo_good_g_total")
    bad = m.REGISTRY.counter("janus_t_slo_bad_g_total")
    eng = slo.SloEngine(
        [
            slo.SloDefinition(
                name="t_exploding", objective=0.99, signal=ExplodingSignal()
            ),
            _ratio_slo("t_ratio_g", good.name, bad.name),
        ],
        interval_s=1.0,
        time_fn=clock,
    )
    good.add(0)
    eng.evaluate_once()  # must not raise
    clock.advance(1.0)
    good.add(5)
    eng.evaluate_once()
    doc = eng.alertz_doc()
    healthy = next(s for s in doc["slos"] if s["name"] == "t_ratio_g")
    assert healthy["budget_window_events"] == 5.0


def test_same_severity_rungs_do_not_clobber_each_other(clock):
    """The Workbook's 3-rung ladder has TWO page rungs; a quiet later
    rung must not resolve an earlier firing one in the same pass
    (alert state is per rung, the gauge ORs rungs per severity)."""
    good = m.REGISTRY.counter("janus_t_slo_good_h_total")
    bad = m.REGISTRY.counter("janus_t_slo_bad_h_total")
    good.add(0)
    bad.add(0)
    definition = _ratio_slo(
        "t_ratio_h",
        good.name,
        bad.name,
        windows=(
            {"long_secs": 4.0, "short_secs": 1.0, "burn_rate": 14.4, "severity": "page"},
            # second page rung with an unreachable threshold: stays ok
            {"long_secs": 8.0, "short_secs": 2.0, "burn_rate": 1e9, "severity": "page"},
        ),
    )
    eng = slo.SloEngine([definition], interval_s=1.0, time_fn=clock)
    eng.evaluate_once()
    clock.advance(1.0)
    bad.add(50)
    eng.evaluate_once()
    doc = eng.alertz_doc()
    states = [a["state"] for a in doc["alerts"]]
    assert states == ["firing", "ok"]
    # the severity gauge ORs the rungs; the firing list dedupes
    assert m.alert_active.get(alert="t_ratio_h", severity="page") == 1.0
    assert doc["firing"] == ["t_ratio_h/page"]
    # stays latched across further passes while the burn persists
    clock.advance(0.2)
    bad.add(50)
    eng.evaluate_once()
    doc = eng.alertz_doc()
    assert [a["state"] for a in doc["alerts"]] == ["firing", "ok"]
    assert m.alert_active.get(alert="t_ratio_h", severity="page") == 1.0


def test_condition_mode_typo_is_rejected():
    with pytest.raises(ValueError, match="unknown condition mode"):
        slo.Condition.from_dict(
            {"metric": "janus_x_total", "op": ">", "value": 0, "mode": "deltas"}
        )
