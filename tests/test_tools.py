"""CLI tools tests (the analog of the reference's trycmd golden tests,
tools/tests/cli.rs): hpke_keygen output is usable key material,
dap_decode round-trips wire messages, and the collect CLI runs a real
collection against an in-process leader+helper pair."""

import base64
import dataclasses
import secrets

import pytest

from janus_tpu.aggregator import Aggregator, Config
from janus_tpu.aggregator.aggregation_job_creator import (
    AggregationJobCreator,
    AggregationJobCreatorConfig,
)
from janus_tpu.aggregator.aggregation_job_driver import AggregationJobDriver
from janus_tpu.aggregator.collection_job_driver import CollectionJobDriver
from janus_tpu.aggregator.http_handlers import DapHttpApp, DapServer
from janus_tpu.aggregator.job_driver import JobDriver, JobDriverConfig
from janus_tpu.client import Client, ClientParameters
from janus_tpu.core.hpke import (
    HpkeApplicationInfo,
    HpkeKeypair,
    Label,
    generate_hpke_config_and_private_key,
    hpke_open,
    hpke_seal,
)
from janus_tpu.core.http_client import HttpClient
from janus_tpu.core.time_util import MockClock
from janus_tpu.datastore.store import EphemeralDatastore
from janus_tpu.messages import (
    Duration,
    HpkeConfig,
    Report,
    Role,
    Time,
)
from janus_tpu.task import QueryTypeConfig, TaskBuilder
from janus_tpu.tools import collect, dap_decode, hpke_keygen
from janus_tpu.vdaf.registry import VdafInstance


def unb64(s: str) -> bytes:
    return base64.urlsafe_b64decode(s + "=" * (-len(s) % 4))


def test_hpke_keygen_produces_working_keypair(capsys):
    assert hpke_keygen.main(["7"]) == 0
    out = dict(
        line.split(": ") for line in capsys.readouterr().out.strip().splitlines()
    )
    config = HpkeConfig.from_bytes(unb64(out["hpke_config"]))
    assert config.id.id == 7
    kp = HpkeKeypair(config, unb64(out["private_key"]))
    info = HpkeApplicationInfo(Label.AGGREGATE_SHARE, Role.HELPER, Role.COLLECTOR)
    ct = hpke_seal(config, info, b"payload", b"aad")
    assert hpke_open(kp, info, ct, b"aad") == b"payload"


def test_dap_decode_report(tmp_path, capsys):
    vdaf = VdafInstance.count()
    task = TaskBuilder(QueryTypeConfig.time_interval(), vdaf, Role.LEADER).build()
    params = ClientParameters(task.task_id, "http://l/", "http://h/", task.time_precision)
    hpke = generate_hpke_config_and_private_key(config_id=3)
    client = Client(params, vdaf, hpke.config, hpke.config, clock=MockClock(Time(1_600_000_000)))
    report = client.prepare_report(1)
    path = tmp_path / "report.bin"
    path.write_bytes(report.to_bytes())

    assert dap_decode.main([str(path), "--media-type", "report"]) == 0
    out = capsys.readouterr().out
    assert "Report" in out and str(report.metadata.report_id) in out


def test_collect_cli_arg_validation():
    base = [
        "--task-id", "x", "--leader", "http://l/",
        "--authorization-bearer-token", "t",
        "--hpke-config", "x", "--hpke-private-key", "x",
        "--current-batch",
    ]
    with pytest.raises(SystemExit):
        collect.main(base + ["--vdaf", "sum"])  # missing --bits
    with pytest.raises(SystemExit):
        collect.main(base + ["--vdaf", "histogram"])  # missing --length
    with pytest.raises(SystemExit):
        collect.main(base + ["--vdaf", "fixedpoint16vec"])  # missing --length


def test_bench_dry_run_smoke():
    """CI smoke of `bench.py --dry-run` (no accelerator): the HBM
    feasibility report must be well-formed, the EngineCache OOM-retry /
    host-fallback machinery must survive an injected
    RESOURCE_EXHAUSTED, and the admission-controlled ingest pipeline
    must shed a real over-capacity upload burst with 429 + Retry-After
    while committing admitted reports exactly once — so both serving
    failure paths are exercised on every CPU test run, not just on
    chip."""
    import json
    import os
    import pathlib
    import subprocess
    import sys

    repo = pathlib.Path(__file__).resolve().parent.parent
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    # don't inherit conftest's 8-virtual-device XLA_FLAGS: the smoke
    # models the single-accelerator serving shape (bucket floor = 1)
    env.pop("XLA_FLAGS", None)
    proc = subprocess.run(
        [sys.executable, "bench.py", "--dry-run", "--config", "count"],
        cwd=repo, env=env, capture_output=True, text=True, timeout=1200,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    line = [l for l in proc.stdout.splitlines() if l.startswith("{")][-1]
    rec = json.loads(line)
    assert rec["metric"] == "dry_run"
    fz = rec["feasibility"]
    assert fz["row_bytes"] > 0 and fz["budget_bytes"] > 0
    smoke = rec["oom_fallback_smoke"]
    assert smoke["halved_retry_ok"] is True
    assert smoke["host_fallback_ok"] is True
    ingest = rec["ingest_smoke"]
    assert ingest["accepted"] == 3  # the configured bucket burst
    assert ingest["shed"] == 5  # everything above it: 429
    assert ingest["shed_counter_delta"] == ingest["shed"]  # all accounted
    assert ingest["retry_after_present"] is True
    assert ingest["committed_exactly_once"] is True
    # batched ingest crypto (ISSUE 11): a real loopback burst through
    # the window-batched path answers the exact 201/4xx split with
    # exactly-once commits, and the direct feed proves the windowing
    # deterministically (8 submits in one linger -> ONE batched open)
    batch = rec["ingest_batch_smoke"]
    assert batch["accepted"] == 12
    assert batch["rejected_4xx"] == 4  # 1 tampered + 3 undecodable
    assert batch["statuses_other"] == []
    assert batch["committed_exactly_once"] is True
    assert batch["replay_still_201"] is True
    assert batch["direct_feed_ok"] is True
    assert batch["direct_batch_calls"] == 1
    assert batch["direct_batch_lanes"] == 8
    assert batch["decrypt_batch_seconds_sampled"] is True
    # server-side decode+decrypt speed: bit-identical stored reports,
    # the measured speedup is the record's tracked number (the >=3x
    # acceptance gate reads the BENCH json; the test bound is loose so
    # a loaded CI host carries the real number instead of flaking)
    speed = rec["upload_batch_speed"]
    assert speed["window"] == 256
    assert speed["stored_reports_identical"] is True
    assert speed["speedup"] > 1.5
    # open-loop (coordinated-omission-free) upload overload: sustained
    # 2x-capacity load sheds ~half 429 with exact accounting, and the
    # p50/p99-from-intended-send numbers are present
    ol = rec["open_loop_upload"]
    assert ol["accepted_201"] > 0 and ol["shed_429"] > 0
    assert ol["errors"] == 0
    assert ol["shed_accounted"] is True
    assert ol["p50_ms_201"] is not None and ol["p99_ms_201"] is not None
    assert ol["p99_ms_201"] >= ol["p50_ms_201"]
    # observability (ISSUE 3): the span hot path is measured, not
    # assumed, and the full metrics/statusz/profile surface works over
    # HTTP against a live health listener
    overhead = rec["tracing_overhead"]
    assert overhead["disabled_rps"] > 0 and overhead["spans_per_iter"] == 4
    # measured, not assumed; a generous bound — on a loaded 2-core
    # host scheduling noise swings the ratio, and the record's job is
    # to carry the real numbers, not to gate on them
    assert 0 < overhead["disabled_vs_baseline"] < 2.0
    assert overhead["chrome_rps"] > 0 and overhead["otlp_rps"] > 0
    # the always-on flight recorder stays the same order as the
    # recorder-off span cost (the bound is loose for scheduler noise;
    # the record carries the real numbers)
    assert overhead["span_ns_recorder_off"] > 0
    assert overhead["span_ns_disabled"] < 20 * overhead["span_ns_recorder_off"]
    # SLO burn-rate engine live proof (ISSUE 10): a failpoint-driven
    # 5xx storm on REAL uploads over loopback HTTP flips the default
    # upload_availability alert on /alertz with burn rates over the
    # 14.4x threshold, janus_alert_active=1 lands in /metrics, an
    # OpenMetrics latency exemplar resolves against a live
    # /debug/traces capture, recovery clears the alert, and the
    # one-command debug bundle inventories every endpoint
    sa = rec["observability_smoke"]["slo_alert"]
    assert sa["baseline_statuses"] == [201, 201, 201]
    assert sa["baseline_firing"] == []
    assert sa["storm_statuses_5xx"] >= 1
    assert sa["alert_fired"] is True, sa
    assert sa["burn_over_threshold"] is True
    assert sa["burn_rate_long"] >= sa["burn_rate_threshold"] == 14.4
    assert sa["firing_since_set"] is True
    assert "upload_availability/page" in sa["alertz_firing_list"]
    assert sa["budget_remaining_while_firing"] < 1.0
    assert sa["evidence_present"] is True
    assert sa["alert_active_in_metrics"] is True
    assert sa["default_scrape_exemplar_free"] is True
    assert sa["default_scrape_valid"] is True
    assert sa["openmetrics_content_type_ok"] is True
    assert sa["openmetrics_scrape_valid"] is True, sa.get("openmetrics_errors")
    assert sa["upload_exemplar_count"] >= 1
    assert sa["exemplar_resolves_in_debug_traces"] is True
    assert sa["alert_cleared_after_recovery"] is True
    assert sa["alert_active_gauge_after_recovery"] == 0.0
    assert sa["bundle_rc"] == 0, sa.get("bundle_err")
    assert sa["bundle_manifest_complete"] is True
    assert set(sa["bundle_endpoints_captured"]) == {
        "healthz",
        "readyz",
        "metrics",
        "metrics_openmetrics",
        "statusz",
        "debug_vars",
        "debug_traces",
        "alertz",
        "debug_profile",
        "debug_profile_json",
        "debug_boot",
        "debug_flight",
        "debug_ledger",
    }
    obs = rec["observability_smoke"]
    assert obs["scrape_valid"] is True, obs.get("scrape_errors")
    assert obs["engine_dispatch_samples"] > 0  # non-zero dispatch histogram
    assert obs["jobs_in_progress"] == 1.0  # non-zero janus_jobs sample
    assert obs["hostile_label_roundtrip"] is True  # '"' and '\n' in a label
    assert obs["statusz_tasks"] == 1
    assert obs["statusz_engine_cache_entries"] >= 1
    assert obs["statusz_job_health_present"] is True
    assert obs["profile_status_codes"] == [200, 409]  # concurrent capture 409s
    assert obs["profile_host_trace_loadable"] is True
    assert obs["debug_traces_ok"] is True  # flight recorder over live HTTP
    assert obs["statusz_flight_recorder_present"] is True
    assert obs["scrape_check_rc"] == 0, obs.get("scrape_check_err")
    # continuous profiler (ISSUE 13): the live listener serves a
    # well-formed collapsed-stack document and the JSON role shares,
    # /debug/boot answers, the statusz profile/device_cost sections are
    # registered, and the sampler saw the device-lane thread family
    assert obs["profile_collapsed_ok"] is True
    assert obs["debug_boot_ok"] is True
    assert obs["statusz_profile_present"] is True
    assert obs["statusz_device_cost_present"] is True
    assert "main" in obs["profile_roles"], obs["profile_roles"]
    assert "device_lane" in obs["profile_roles"], obs["profile_roles"]
    # sampler cost measured, not assumed: on/off A/B at the production
    # 19 Hz (the <= 2% acceptance gate result rides the record;
    # the test bound is loose so a loaded CI host carries the real
    # number instead of flaking) plus the hostile-name fold proof
    po = rec["profiler_overhead"]
    assert po["collapsed_well_formed"] is True, po.get("collapsed_errors")
    assert po["samples"] > 0
    assert 0.0 <= po["self_measured_overhead_ratio"] < 0.05
    assert po["overhead_pct"] < 15.0, po
    assert "gate_ok" in po and "median_pair_ratio" in po
    # report-lifecycle tracing (ISSUE 6): ONE persisted trace id spans
    # creator -> driver round 1 -> helper init -> a FRESH driver
    # instance's round 2 (the restart analog: nothing shared but the
    # datastore row) -> helper continue; the collection job persists
    # its own trace context, the collect-finish span links back to the
    # aggregation trace, and both e2e SLO stages recorded samples
    tl = obs["trace_lifecycle"]
    assert tl["collected"] == 3 and tl["aggregate"] == 2
    assert tl["job_trace_context_persisted"] is True
    assert tl["helper_row_same_trace"] is True
    assert tl["leader_init_span_in_trace"] and tl["leader_continue_span_in_trace"]
    assert tl["helper_init_span_in_trace"] and tl["helper_continue_span_in_trace"]
    assert tl["collection_trace_context_persisted"] is True
    assert tl["collect_finish_span_in_collection_trace"] is True
    assert tl["collect_links_include_job_trace"] is True
    assert tl["e2e_aggregate_delta"] > 0 and tl["e2e_collect_delta"] > 0
    # robustness (ISSUE 4): with JANUS_FAILPOINTS unset the failpoint
    # sites compile to a no-op — sub-microsecond against the ms-scale
    # upload/commit work they sit on (the bound is deliberately loose:
    # it gates "accidentally armed / accidentally slow", not scheduler
    # noise on a loaded 2-core runner)
    fp = rec["failpoint_overhead"]
    assert fp["disabled_ns_per_hit"] < 5_000, fp
    # crash-recovery chaos smoke (scripts/chaos_run.py --smoke): driver
    # killed between helper ack and leader commit, restart into a
    # transport/5xx storm through the circuit breaker, lease reacquired
    # within TTL, collection equals the admitted ground truth exactly
    chaos = rec["chaos_smoke"]
    assert chaos.get("ok") is True, chaos
    assert chaos["crash_exit_code"] == 77  # failpoints.CRASH_EXIT_CODE
    assert chaos["exactly_once_ok"] is True
    assert chaos["lease_reacquired_within_ttl_ok"] is True
    assert chaos["circuit_cycle_ok"] is True, chaos["circuit_transitions"]
    assert chaos["drain_ok"] is True
    # datastore-outage survival (ISSUE 7; chaos_run.py --scenario
    # db_outage): uploads keep acking 201 through a full datastore
    # outage on the strength of the spill journal's fsync, /readyz
    # flips 503 -> 200 across recovery while aggregate routes shed 503,
    # the journal drains to empty on recovery, the final collection
    # equals every 201-acked report exactly once, and the armed-but-
    # idle journal performed ZERO fsyncs while the datastore was
    # healthy (no new hot-path cost)
    dbout = rec["db_outage_smoke"]
    assert dbout.get("ok") is True, dbout
    assert dbout["healthy_fsyncs_ok"] is True  # journal idle = no fsyncs
    assert dbout["readyz_up_ok"] and dbout["readyz_down_ok"]
    assert dbout["readyz_recovered_ok"] is True
    assert dbout["aggregate_shed_status"] == 503
    assert dbout["driver_parked_ok"] is True  # no lease attempts burned
    assert dbout["acked_during_outage"] > 0
    assert dbout["spilled_acked_ok"] is True
    assert dbout["journal_drained_ok"] is True
    assert dbout["uploads_all_acked_ok"] is True, dbout["upload_errors"]
    assert dbout["exactly_once_ok"] is True
    assert dbout["collected_count"] == dbout["admitted"]
    # peer-outage survival (ISSUE 19; chaos_run.py --scenario
    # peer_outage): the helper sits behind a netsim fault proxy; a
    # blackhole past the breaker-open threshold keeps uploads at 201
    # while BOTH real driver binaries park (claim txes frozen,
    # janus_peer_parked=1, zero lease conflicts), the cheap half-open
    # probe resumes them on heal, the slow-drip + truncation lanes
    # recover without wedging a worker, and the two disjoint
    # collections partition the admitted ground truth exactly
    po = rec["peer_outage_smoke"]
    assert po.get("ok") is True, po
    assert po["uploads_during_blackhole_ok"] is True
    assert po["both_parked_ok"] is True
    assert po["claims_frozen_while_parked_ok"] is True
    assert po["step_backs_bounded_ok"] is True
    assert po["outage_seconds_counted_ok"] is True
    assert po["statusz_peer_health_ok"] is True
    assert po["unparked_ok"] and po["recovery_agg_ok"]
    assert po["collect1_exact_ok"] is True, po.get("collect1")
    assert po["slicer_lane_ok"] and po["truncate_lane_ok"]
    assert po["lease_conflicts_ok"] and po["probes_alive_ok"]
    assert po["exactly_once_ok"] is True
    assert po["drain_ok"] is True
    # deadline-aware device path (ISSUE 8): the disarmed dispatch
    # watchdog is one contextvar read — the acceptance bound is
    # ≤ 1 µs/dispatch (the record carries the real numbers)
    wd = rec["watchdog_overhead"]
    assert 0 <= wd["disarmed_overhead_ns"] < 1_000, wd
    assert wd["armed_ns_per_dispatch"] > 0
    # device-hang chaos smoke (chaos_run.py --scenario device_hang):
    # with engine.dispatch=hang armed in the REAL driver binary, the
    # hung step releases its lease BEFORE expiry (watchdog abandon +
    # step-back, never a TTL burn), the engine runs quarantined →
    # canary-probed → restored observed live via /metrics + /statusz
    # (incl. the stalled-thread stack dump), the abandoned-thread count
    # stays under the cap, interim work lands through host fallback,
    # and the final collection equals the admitted ground truth exactly
    dh = rec["device_hang_smoke"]
    assert dh.get("ok") is True, dh
    assert dh["lease_bounded_ok"] is True
    assert dh["hung_dispatch_ok"] and dh["stepped_back_device_hang_ok"]
    assert dh["quarantined_observed_ok"] and dh["quarantine_cycle_ok"]
    assert dh["restored_ok"] is True
    assert dh["abandoned_under_cap_ok"] and dh["stalled_stack_ok"]
    assert dh["drain_ok"] is True
    assert dh["exactly_once_ok"] is True
    assert dh["collected_count"] == dh["admitted"]
    # warm canary restore (ISSUE 14): with the compile + AOT caches on,
    # quarantine-open -> restored is seconds (canary cool-down + a warm
    # rebuild), never a cold multi-minute recompile
    assert dh["restore_warm_ok"] is True, dh.get("restore_elapsed_s")
    # cold-start A/B (ISSUE 14; chaos_run.py --scenario cold_start):
    # interleaved cold-cache vs warm-cache REAL driver boots, both
    # prewarming the same shape manifest before /readyz flips ready.
    # The warm boot must come up under the 10 s ROADMAP target and
    # meaningfully faster than cold (the >= 3x gate rides the full
    # BENCH record; the smoke gates 1.5x so a CPU-starved CI host
    # carries the real number instead of flaking), with AOT executable
    # saves observed cold and loads observed warm.
    cs = rec["cold_start"]
    assert cs.get("ok") is True, cs
    assert cs["boots_ready_ok"] is True
    assert cs["manifest_phase_ok"] is True  # engine_warm_manifest on /debug/boot
    assert cs["prewarm_observed_ok"] is True
    assert cs["warm_under_budget_ok"] is True  # < 10 s warm restart
    assert cs["speedup_ok"] and cs["speedup"] >= 1.5
    assert cs["cold_aot_saves_ok"] and cs["warm_aot_loads_ok"]
    assert cs["warm_cache_hits_ok"] and cs["cold_cache_misses_ok"]
    assert cs["drain_ok"] is True
    # device-resident accumulators (ISSUE 12): the resident vs
    # re-stage A/B on the same dataset must show >= 2x fewer
    # host<->device bytes per report on the accumulate leg with
    # BIT-IDENTICAL aggregate shares (the acceptance gate), and
    # rows/dispatch must go UP (one delta dispatch replaces k
    # per-bucket reduces)
    ra = rec["resident_accumulate"]
    assert ra["aggregates_identical"] is True
    assert ra["hd_bytes_per_report_ratio"] >= 2.0, ra
    assert ra["resident"]["rows_per_dispatch"] > ra["classic"]["rows_per_dispatch"]
    assert ra["resident"]["dispatches"] < ra["classic"]["dispatches"]
    # resident flush-contract live proof (chaos_run.py --scenario
    # resident): LRU eviction, mid-stream quarantine sweep and SIGTERM
    # drain each flush resident state through the write-tx path (no
    # outcome="lost"), and BOTH tasks' collections equal their admitted
    # ground truths exactly
    rs = rec["resident_smoke"]
    assert rs.get("ok") is True, rs
    assert rs["eviction_flush_ok"] is True
    assert rs["quarantined_observed_ok"] and rs["quarantine_flush_ok"]
    assert rs["stepped_back_device_hang_ok"] is True
    assert rs["restored_ok"] and rs["resident_before_drain_ok"]
    assert rs["no_lost_flushes_ok"] is True
    assert rs["drain_ok"] is True
    assert rs["exactly_once_a_ok"] and rs["exactly_once_b_ok"]
    # columnar wire codec (ISSUE 9): one vectorized framing pass must be
    # >= 5x the per-report loop at batch >= 1024 with BIT-IDENTICAL
    # request bytes (the acceptance criterion, measured not assumed)
    codec = rec["step_pipeline"]["codec"]
    assert codec["batch"] >= 1024
    assert codec["wire_bytes_identical"] is True
    assert codec["decode_roundtrip_ok"] is True
    assert codec["encode_speedup"] >= 5.0, codec
    assert codec["decode_speedup"] >= 5.0, codec
    # stage-pipelined stepper (ISSUE 9; chaos_run.py --scenario
    # pipeline): the REAL driver binary with the pipelined stepper
    # proves overlap on loopback — the device lane ran while a
    # (failpoint-stretched) helper RTT was in flight, every stage
    # executed, the drain is clean, and the collection equals the
    # admitted ground truth exactly (never a lost/double-stepped job)
    ps = rec["pipeline_smoke"]
    assert ps.get("ok") is True, ps
    assert ps["overlap_ok"] and ps["overlapped_dispatches"] >= 1
    assert ps["device_lane_busy_ok"] is True
    assert ps["statusz_overlap_events"] > 0  # overlap recorded in statusz
    assert ps["stages_executed_ok"] is True
    assert ps["statusz_pipeline_ok"] is True  # serialized lane, jobs done
    assert ps["drain_ok"] is True
    assert ps["exactly_once_ok"] is True
    assert ps["collected_count"] == ps["admitted"]
    # fleet scale-out (ISSUE 15): two in-process replicas over one
    # store — replica A dies HOLDING its batched claims (the SIGKILL
    # analog), replica B finishes its own shard and steals the dead
    # shard after the delay; nothing is ever double-stepped (conflict
    # counter 0), the claims are batched (jobs per claim tx > 1), and
    # the collection equals the admitted ground truth exactly
    fs = rec["fleet_smoke"]
    assert fs["both_shards_populated"] is True
    assert fs["held_by_dead_replica"] >= 1
    assert fs["survivor_finished_all"] is True, fs
    assert fs["zero_conflicts"] is True
    assert fs["dead_shard_stolen"] is True
    assert fs["batched_claims"] and fs["jobs_per_claim_tx"] > 1.0
    assert fs["exactly_once"] is True
    assert fs["collected_count"] == fs["admitted"]
    # multi-chip serving (ISSUE 16): a subprocess forced to 4 virtual
    # devices drives the serving EngineCache path over a (dp, sp) mesh
    # behind the single-controller dispatch queue; its aggregates and
    # resident shares are bit-identical to the single-device reference
    # computed in THIS process, the old process-global dispatch lock is
    # gone, and the mesh round sustained a measurable rate
    ms = rec["mesh_serving_smoke"]
    assert ms.get("ok") is True, ms
    assert ms["bit_identical"] is True
    assert ms["devices"] == 4 and ms["dp"] * ms["sp"] > 1
    assert ms["queue_submitted"] > 0 and ms["queue_errors"] == 0
    assert ms["lane_alive"] is True
    assert ms["dispatch_lock_removed"] is True
    assert ms["rps"] > 0
    # block-sparse scatter-merge (ISSUE 17): sparse aggregates
    # bit-identical to the dense expanded oracle on BOTH device paths
    # (classic per-bucket reduce and resident pending-delta merge), and
    # the scatter path provably ran (engine counter + cost-ledger rows)
    sp = rec["sparse_scatter"]
    assert sp["classic_identical"] is True, sp
    assert sp["resident_identical"] is True, sp
    assert sp["scatter_path_observed"] is True
    assert sp["scatter_rows"] > 0
    assert 0.0 < sp["block_occupancy"] <= 1.0
    # ISSUE 18: the endurance-soak smoke — churn + GC + exact per-epoch
    # collection, flight-recorder zero-slope verdicts on the clean
    # driver (self-overhead <= 1%), injected leak fires the trend alert
    soak = rec["soak_smoke"]
    assert soak["ok"] is True, {
        k: v for k, v in soak.items() if k.endswith("_ok") and not v
    } or soak
    assert soak["epochs_exact_ok"] is True
    assert soak["gc_deleted_rows"] > 0
    assert soak["zero_slope_ok"] is True
    assert soak["recorder_overhead_ratio"] <= 0.01
    assert soak["leak_detected_ok"] is True
    assert soak["trend_alert_fired_ok"] is True
    # ISSUE 20: report-flow conservation ledger — the real admission
    # path leaves the books balanced; an injected silent loss
    # (ledger.drop_report deletes an admitted report AFTER its tx
    # counted it) is a +1 ingest imbalance on the very next
    # evaluation, breaching immediately (grace 0) and turning the
    # `conservation` SLO signal bad on the same tick
    lg = rec["ledger_smoke"]
    assert lg["balanced_ok"] is True, lg
    assert lg["balanced_breaches"] == []
    assert lg["loss_imbalance_total"] == 1
    assert lg["loss_detected_in_one_evaluation"] is True
    assert lg["breach_fired"] is True
    assert lg["slo_fired"] is True, lg
    # the observability smoke runs the ledger like the real binaries:
    # statusz section present, /debug/ledger well-formed, zero breaches
    obs = rec["observability_smoke"]
    assert obs["statusz_ledger_present"] is True
    assert obs["debug_ledger_ok"] is True, obs
    assert obs["ledger_breaches"] == []


def test_collect_cli_end_to_end(capsys):
    clock = MockClock(Time(1_600_000_000))
    leader_eph = EphemeralDatastore(clock=clock)
    helper_eph = EphemeralDatastore(clock=clock)
    leader_srv = DapServer(DapHttpApp(Aggregator(leader_eph.datastore, clock, Config()))).start()
    helper_srv = DapServer(DapHttpApp(Aggregator(helper_eph.datastore, clock, Config()))).start()
    try:
        vdaf = VdafInstance.count()
        collector_kp = generate_hpke_config_and_private_key(config_id=200)
        leader_task = (
            TaskBuilder(QueryTypeConfig.time_interval(), vdaf, Role.LEADER)
            .with_(
                leader_aggregator_endpoint=leader_srv.url,
                helper_aggregator_endpoint=helper_srv.url,
                collector_hpke_config=collector_kp.config,
                min_batch_size=1,
            )
            .build()
        )
        helper_task = dataclasses.replace(
            leader_task,
            role=Role.HELPER,
            hpke_keys=(generate_hpke_config_and_private_key(config_id=1),),
        )
        leader_eph.datastore.run_tx(lambda tx: tx.put_task(leader_task))
        helper_eph.datastore.run_tx(lambda tx: tx.put_task(helper_task))

        http = HttpClient()
        params = ClientParameters(
            leader_task.task_id, leader_srv.url, helper_srv.url, leader_task.time_precision
        )
        client = Client.with_fetched_configs(params, vdaf, http, clock=clock)
        for m in [1, 1, 0, 1]:
            client.upload(m)

        AggregationJobCreator(
            leader_eph.datastore, AggregationJobCreatorConfig(min_aggregation_job_size=1)
        ).run_once()
        drv = AggregationJobDriver(leader_eph.datastore, http)
        JobDriver(JobDriverConfig(), drv.acquirer(), drv.stepper).run_once()

        start = clock.now().to_batch_interval_start(leader_task.time_precision)

        import threading

        cdrv = CollectionJobDriver(leader_eph.datastore, http)
        cjd = JobDriver(JobDriverConfig(), cdrv.acquirer(), cdrv.stepper)
        # step the collection job shortly after the CLI creates it
        stepper = threading.Timer(1.5, cjd.run_once)
        stepper.start()

        rc = collect.main(
            [
                "--task-id=" + leader_task.to_dict()["task_id"],
                "--leader", leader_srv.url,
                "--authorization-bearer-token="
                + leader_task.collector_auth_token.token,
                # =-form: a random key's base64url may start with '-',
                # which space-form argparse reads as an option (1/64 flake)
                "--hpke-config="
                + base64.urlsafe_b64encode(collector_kp.config.to_bytes()).decode(),
                "--hpke-private-key="
                + base64.urlsafe_b64encode(collector_kp.private_key).decode(),
                "--vdaf", "count",
                "--batch-interval-start", str(start.seconds - 3600),
                "--batch-interval-duration", str(3 * 3600),
            ]
        )
        stepper.join()
        assert rc == 0
        out = capsys.readouterr().out
        assert "Number of reports: 4" in out
        assert "Aggregation result: 3" in out
    finally:
        leader_srv.stop()
        helper_srv.stop()
        leader_eph.cleanup()
        helper_eph.cleanup()


def test_alert_rules_file_in_sync_with_slo_definitions():
    """docs/alerts/janus-alerts.yaml is GENERATED from the in-process
    SLO definitions (python -m janus_tpu.tools.gen_alert_rules); a
    drifted checked-in file is a CI failure, not an operator surprise
    (ISSUE 10 satellite — replaces the prose alert sketches)."""
    import pathlib

    import yaml

    from janus_tpu.slo import BUILTIN_SLOS
    from janus_tpu.tools.gen_alert_rules import generate_rules_text

    path = pathlib.Path(__file__).resolve().parent.parent / "docs" / "alerts" / "janus-alerts.yaml"
    generated = generate_rules_text()
    assert path.read_text() == generated, (
        "docs/alerts/janus-alerts.yaml drifted from janus_tpu/slo.py; "
        "regenerate: python -m janus_tpu.tools.gen_alert_rules > docs/alerts/janus-alerts.yaml"
    )
    # and the file is a structurally valid Prometheus rule file covering
    # every built-in SLO at both severities
    doc = yaml.safe_load(generated)
    rules = doc["groups"][0]["rules"]
    assert len(rules) == 2 * len(BUILTIN_SLOS())
    for rule in rules:
        assert rule["alert"].startswith("Janus")
        assert rule["expr"].strip()
        assert rule["labels"]["severity"] in ("page", "ticket")
        assert rule["labels"]["slo"] in {d.name for d in BUILTIN_SLOS()}
        assert "runbook" in rule["annotations"]


def test_gen_alert_rules_check_mode(tmp_path, capsys):
    from janus_tpu.tools.gen_alert_rules import generate_rules_text, main

    good = tmp_path / "rules.yaml"
    good.write_text(generate_rules_text())
    assert main(["--check", str(good)]) == 0
    stale = tmp_path / "stale.yaml"
    stale.write_text("groups: []\n")
    assert main(["--check", str(stale)]) == 1


def test_debug_bundle_collects_endpoints_config_and_journal(tmp_path):
    """scripts/debug_bundle.py (ISSUE 10): one command against a live
    health listener yields a tar.gz whose MANIFEST inventories every
    endpoint capture, the config rides along with secrets REDACTED,
    and the journal directory state is inventoried without contents."""
    import io
    import json
    import tarfile

    from janus_tpu.binary_utils import HealthServer
    from janus_tpu.tools.debug_bundle import ENDPOINTS, collect_bundle, redact_config

    # redaction unit: secret-smelling keys masked at any depth
    redacted = redact_config(
        {
            "database": {"url": "x.sqlite"},
            "aggregator_api": {"auth_tokens": ["hunter2"], "listen_address": "a:1"},
            "collector_auth_token": "t0",
            "nested": [{"hpke_private_key": "k"}],
        }
    )
    assert redacted["aggregator_api"]["auth_tokens"] == "**REDACTED**"
    assert redacted["collector_auth_token"] == "**REDACTED**"
    assert redacted["nested"][0]["hpke_private_key"] == "**REDACTED**"
    assert redacted["database"]["url"] == "x.sqlite"
    assert redacted["aggregator_api"]["listen_address"] == "a:1"

    cfg = tmp_path / "cfg.yaml"
    cfg.write_text("database:\n  url: x.sqlite\naggregator_api:\n  auth_tokens: [hunter2]\n")
    journal = tmp_path / "journal"
    journal.mkdir()
    (journal / "seg-000001.journal").write_bytes(b"x" * 64)
    (journal / "seg-000002.corrupt").write_bytes(b"y" * 32)
    # shape manifest (ISSUE 14): inventoried beside the journal —
    # entry counts + sibling AOT blob names/sizes, never contents
    from janus_tpu.aggregator.shape_manifest import ShapeManifest

    smpath = tmp_path / "shape_manifest.jsonl"
    sman = ShapeManifest(str(smpath))
    sman.record({"kind": "count"}, "leader_init", 32, ("leader_init", 32), 1.0)
    sman.record({"kind": "count"}, "aggregate", 64, ("aggregate", 64), 2.0)
    aot_dir = tmp_path / "aot"
    aot_dir.mkdir()
    (aot_dir / "deadbeef.jaxexe").write_bytes(b"z" * 128)

    srv = HealthServer("127.0.0.1:0").start()
    try:
        out = tmp_path / "bundle.tar.gz"
        manifest = collect_bundle(
            [f"http://127.0.0.1:{srv.port}"],
            out_path=str(out),
            config_file=str(cfg),
            journal_dir=str(journal),
            shape_manifest=str(smpath),
        )
    finally:
        srv.stop()

    assert out.exists()
    target = next(iter(manifest["targets"].values()))
    assert set(target["endpoints"]) == {name for name, _ in ENDPOINTS}
    assert all("error" not in e for e in target["endpoints"].values())
    # fleet attribution (ISSUE 15): every capture target records WHICH
    # replica it was, read off the /statusz fleet section
    from janus_tpu import metrics as _metrics

    assert target["replica_id"] == _metrics.replica_id()
    with tarfile.open(out) as tar:
        names = tar.getnames()
        top = names[0].split("/")[0]
        members = {n.split("/", 1)[1] if "/" in n else n for n in names}
        # MANIFEST inventories exactly the files in the tar
        mf = json.load(tar.extractfile(f"{top}/MANIFEST.json"))
        assert {f["path"] for f in mf["files"]} == set(names) - {f"{top}/MANIFEST.json"}
        for entry in mf["files"]:
            assert entry["sha256"] and entry["bytes"] >= 0
        cfg_text = tar.extractfile(f"{top}/resolved-config.yaml").read().decode()
        assert "hunter2" not in cfg_text and "**REDACTED**" in cfg_text
        jd = json.load(tar.extractfile(f"{top}/upload-journal.json"))
        assert jd["segment_count"] == 2
        assert jd["total_bytes"] == 96
        assert jd["corrupt_segments"] == ["seg-000002.corrupt"]
        sd = json.load(tar.extractfile(f"{top}/shape-manifest.json"))
        assert sd["entries"] == 2 and sd["bytes"] > 0
        assert sd["aot"]["blob_count"] == 1
        assert sd["aot"]["blobs"][0]["name"] == "deadbeef.jaxexe"
        assert "contents" not in sd  # inventory only, never payloads
        # alertz capture present for the target
        assert any(n.endswith("/alertz.json") for n in names)
    # an unreachable listener degrades to a manifest error, not a crash
    manifest2 = collect_bundle(
        ["http://127.0.0.1:1"], out_path=str(tmp_path / "b2.tar.gz"), timeout=0.5
    )
    t2 = next(iter(manifest2["targets"].values()))
    assert all("error" in e for e in t2["endpoints"].values())
    assert t2["replica_id"] is None  # unreachable: attribution degrades
