"""Property tests on the lease/accumulate invariants — the project's
answer to the reference's concurrency story (REPEATABLE READ + retry,
documented write-write races; SURVEY.md §5 'race detection')."""

import secrets
import threading

from janus_tpu.aggregator.accumulator import Accumulator
from janus_tpu.core.time_util import MockClock
from janus_tpu.datastore.models import (
    AggregationJobModel,
    AggregationJobState,
    LeaderStoredReport,
)
from janus_tpu.datastore.store import EphemeralDatastore

# Parameterize the invariants over both engines (Postgres skips unless
# a server URL + psycopg are present); engine list shared via conftest.
import pytest
from conftest import DATASTORE_ENGINES


@pytest.fixture(params=DATASTORE_ENGINES)
def engine(request):
    return request.param
from janus_tpu.messages import (
    Duration,
    HpkeCiphertext,
    HpkeConfigId,
    Interval,
    ReportId,
    Role,
    Time,
)
from janus_tpu.task import QueryTypeConfig, TaskBuilder
from janus_tpu.vdaf.registry import VdafInstance


def make_task(ds):
    task = (
        TaskBuilder(QueryTypeConfig.time_interval(), VdafInstance.count(), Role.LEADER)
        .with_(min_batch_size=1)
        .build()
    )
    ds.run_tx(lambda tx: tx.put_task(task))
    return task


def put_job(ds, task, job_id_bytes):
    from janus_tpu.messages import AggregationJobId

    job = AggregationJobModel(
        task.task_id,
        AggregationJobId(job_id_bytes),
        b"",
        b"\x01",  # time-interval PBS body
        Interval(Time(1_600_000_000), Duration(1)),
        AggregationJobState.IN_PROGRESS,
        0,
    )
    ds.run_tx(lambda tx: tx.put_aggregation_job(job))
    return job


def test_concurrent_lease_acquisition_never_double_assigns(engine):
    """N workers racing to acquire M jobs: every job is handed to exactly
    one worker (the FOR UPDATE SKIP LOCKED analog)."""
    eph = EphemeralDatastore(clock=MockClock(Time(1_600_000_000)), engine=engine)
    ds = eph.datastore
    try:
        task = make_task(ds)
        n_jobs = 24
        for i in range(n_jobs):
            put_job(ds, task, i.to_bytes(16, "big"))

        acquired = []
        lock = threading.Lock()

        def worker():
            got = ds.run_tx(
                lambda tx: tx.acquire_incomplete_aggregation_jobs(Duration(600), 8),
                "acq",
            )
            with lock:
                acquired.extend(got)

        threads = [threading.Thread(target=worker) for _ in range(6)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)

        ids = [a.job_id.data for a in acquired]
        assert len(ids) == len(set(ids)), "a job was leased to two workers"
        assert len(ids) == n_jobs  # 6 workers x 8 >= 24: all handed out once
    finally:
        eph.cleanup()


def test_release_requires_matching_lease_token(engine):
    """A stale worker (expired lease re-acquired by another) cannot
    release the new holder's lease."""
    import pytest

    from janus_tpu.datastore.store import TxConflict

    clock = MockClock(Time(1_600_000_000))
    eph = EphemeralDatastore(clock=clock, engine=engine)
    ds = eph.datastore
    try:
        task = make_task(ds)
        put_job(ds, task, bytes(16))
        (first,) = ds.run_tx(
            lambda tx: tx.acquire_incomplete_aggregation_jobs(Duration(10), 1)
        )
        clock.advance(Duration(60))  # first lease expires
        (second,) = ds.run_tx(
            lambda tx: tx.acquire_incomplete_aggregation_jobs(Duration(600), 1)
        )
        assert second.lease.token != first.lease.token
        # a single transaction suffices: the mismatch is deterministic and
        # run_tx would otherwise burn its full retry budget on it
        with pytest.raises(TxConflict):
            with ds.tx() as tx:
                tx.release_aggregation_job(first)
        ds.run_tx(lambda tx: tx.release_aggregation_job(second))  # holder can
    finally:
        eph.cleanup()


def test_concurrent_report_claims_are_disjoint(engine):
    """Racing creators claim disjoint report sets (aggregation_started
    flip is atomic per report)."""
    eph = EphemeralDatastore(clock=MockClock(Time(1_600_000_000)), engine=engine)
    ds = eph.datastore
    try:
        task = make_task(ds)

        def put_reports(tx):
            for _ in range(40):
                tx.put_client_report(
                    LeaderStoredReport(
                        task.task_id,
                        ReportId(secrets.token_bytes(16)),
                        Time(1_600_000_000),
                        b"",
                        b"x",
                        HpkeCiphertext(HpkeConfigId(0), b"", b""),
                    )
                )

        ds.run_tx(put_reports)
        claims = []
        lock = threading.Lock()

        def claim():
            got = ds.run_tx(
                lambda tx: tx.get_unaggregated_client_reports_for_task(task.task_id, 15)
            )
            with lock:
                claims.append(got)

        threads = [threading.Thread(target=claim) for _ in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        all_ids = [r[0].data for c in claims for r in c]
        assert len(all_ids) == len(set(all_ids)), "a report was claimed twice"
        assert len(all_ids) == 40
    finally:
        eph.cleanup()


def test_accumulator_flush_is_idempotent_under_tx_retry(engine):
    """Re-flushing the same accumulator state (a retried transaction)
    yields the same batch rows, not doubled counts."""
    eph = EphemeralDatastore(clock=MockClock(Time(1_600_000_000)), engine=engine)
    ds = eph.datastore
    try:
        task = make_task(ds)
        acc = Accumulator(task, shard_count=1)
        rid = ReportId(secrets.token_bytes(16))
        acc.update_single(b"batch-1", [5], rid, Time(1_600_000_000))

        # first attempt rolls back mid-tx, second commits
        attempts = {"n": 0}

        def flaky(tx):
            unmerged = acc.flush_to_datastore(tx)
            attempts["n"] += 1
            if attempts["n"] == 1:
                from janus_tpu.datastore.store import TxConflict

                raise TxConflict("injected rollback")
            return unmerged

        ds.run_tx(flaky)
        rows = ds.run_tx(
            lambda tx: tx.get_batch_aggregations_for_batch(task.task_id, b"batch-1", b"")
        )
        assert len(rows) == 1 and rows[0].report_count == 1
    finally:
        eph.cleanup()
