"""Differential tests: streamed FLP query == whole-share query.

flp_query_streamed (engine.py) must be field-element identical to
flp_query_batched + truncate for both the helper (expanded-by-group)
and leader (sliced) measurement sources, at sizes small enough for CPU.
The production threshold (STREAM_MIN_INPUT_LEN) is monkeypatched down
so the streamed path activates on toy circuits.
"""

import numpy as np
import pytest

from janus_tpu.vdaf import engine
from janus_tpu.vdaf.prio3_jax import Prio3Batched, bytes_to_lane_batch
from janus_tpu.vdaf.reference import Histogram, SumVec
from janus_tpu.vdaf.registry import VdafInstance, prio3_batched


def _mk(circ):
    return Prio3Batched(circ)


def _rand_lanes(rng, batch, n):
    return rng.integers(0, 1 << 63, size=(batch, n), dtype=np.uint64)


CIRCUITS = [
    # the sumvec variants compile 23-42s apiece on CPU; the tiled-prepare
    # suite keeps a fast streamed-sumvec equivalence check in tier-1, so
    # these run nightly/on-chip (ISSUE 1 CI triage)
    pytest.param(
        SumVec(40, 16, chunk_length=5),  # input_len 640; align lcm(7,16)/gcd(.,5)=112 calls... exercises call padding
        marks=pytest.mark.slow,
    ),
    pytest.param(
        SumVec(56, 8, chunk_length=7),  # chunk divisible by 7
        marks=pytest.mark.slow,
    ),
    Histogram(200, chunk_length=9),
]


@pytest.mark.parametrize("circ", CIRCUITS, ids=["sumvec-ch5", "sumvec-ch7", "histogram"])
def test_streamed_equals_batched(circ, monkeypatch):
    monkeypatch.setattr(engine, "STREAM_MIN_INPUT_LEN", 1)
    p3 = _mk(circ)
    bc = p3.bc
    plan = engine.stream_plan(bc)
    assert plan is not None
    assert plan.group % 7 == 0  # XOF block alignment
    rng = np.random.default_rng(42)
    batch = 3
    verify_key = bytes(range(16))
    nonce = _rand_lanes(rng, batch, 2)
    helper_seed = _rand_lanes(rng, batch, 2)
    blind = _rand_lanes(rng, batch, 2) if p3.uses_joint_rand else None
    public_parts = (
        np.stack([_rand_lanes(rng, batch, 2), _rand_lanes(rng, batch, 2)], axis=1)
        if p3.uses_joint_rand
        else None
    )

    # helper: streamed (threshold=1) vs whole-share (threshold huge)
    out_s, seed_s, ver_s, part_s = p3.prepare_init_helper(
        verify_key, nonce, public_parts, helper_seed, blind
    )
    monkeypatch.setattr(engine, "STREAM_MIN_INPUT_LEN", 1 << 60)
    out_u, seed_u, ver_u, part_u = p3.prepare_init_helper(
        verify_key, nonce, public_parts, helper_seed, blind
    )
    for a, b in zip(out_s, out_u):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    for a, b in zip(ver_s, ver_u):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    if p3.uses_joint_rand:
        np.testing.assert_array_equal(np.asarray(seed_s), np.asarray(seed_u))
        np.testing.assert_array_equal(np.asarray(part_s), np.asarray(part_u))

    # leader: meas/proof staged as device arrays
    jf = p3.jf
    meas = tuple(
        rng.integers(0, 1 << 62, size=(batch, circ.input_len), dtype=np.uint64)
        for _ in range(jf.LIMBS)
    )
    proof = tuple(
        rng.integers(0, 1 << 62, size=(batch, circ.proof_len), dtype=np.uint64)
        for _ in range(jf.LIMBS)
    )
    blind0 = _rand_lanes(rng, batch, 2) if p3.uses_joint_rand else None
    monkeypatch.setattr(engine, "STREAM_MIN_INPUT_LEN", 1)
    lo_s = p3.prepare_init_leader(verify_key, nonce, public_parts, meas, proof, blind0)
    monkeypatch.setattr(engine, "STREAM_MIN_INPUT_LEN", 1 << 60)
    lo_u = p3.prepare_init_leader(verify_key, nonce, public_parts, meas, proof, blind0)
    for s, u in zip(lo_s, lo_u):
        if s is None:
            assert u is None
            continue
        if isinstance(s, tuple):
            for a, b in zip(s, u):
                np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        else:
            np.testing.assert_array_equal(np.asarray(s), np.asarray(u))


@pytest.mark.slow  # 27s; test_tiled_prepare keeps a two-party streamed step in tier-1 (ISSUE 1 CI triage)
def test_full_two_party_step_streamed(monkeypatch):
    """End-to-end: shard on the unstreamed path, prepare on the streamed
    path, decide + aggregate — all reports accepted, sum correct."""
    import jax

    monkeypatch.setattr(engine, "STREAM_MIN_INPUT_LEN", 1)
    inst = VdafInstance.sum_vec(length=21, bits=4)
    from janus_tpu.vdaf.testing import make_report_batch, random_measurements
    from janus_tpu.parallel.api import two_party_step

    rng = np.random.default_rng(7)
    meas = random_measurements(inst, 4, rng)
    step_args, _ = make_report_batch(inst, meas, seed=3)
    step = jax.jit(two_party_step(inst, bytes(range(16))))
    agg0, agg1, count = step(*step_args)
    assert int(count) == 4
    p3 = prio3_batched(inst)
    total = p3.merge_agg_shares(agg0, agg1)
    vals = p3.jf.to_ints(total)
    expected = np.asarray(meas).sum(axis=0)
    np.testing.assert_array_equal(np.asarray([int(v) for v in vals]), expected)


def test_stream_plan_gating():
    """Plan geometry: alignment and activation threshold."""
    bc_small = engine.batched_circuit(SumVec(10, 4))
    assert engine.stream_plan(bc_small) is None  # below threshold
    big = SumVec(100000, 16)
    bc = engine.batched_circuit(big)
    plan = engine.stream_plan(bc)
    assert plan is not None
    assert plan.group % 7 == 0 and plan.group % 16 == 0
    assert plan.n_steps * plan.gcalls >= bc.calls
    assert plan.gcalls * (plan.n_steps - 1) < bc.calls  # no empty tail step
