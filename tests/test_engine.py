"""Differential tests: batched device FLP engine vs the host oracle.

Mirrors the reference's golden-transcript strategy (SURVEY.md section 4:
`run_vdaf` in core/src/test_util/mod.rs) — every batched output is
compared element-wise against the scalar host implementation.
"""

import secrets

import numpy as np
import pytest

from janus_tpu.fields.field import Field64, Field128
from janus_tpu.ops.ntt import intt_batched, ntt_batched, powers, poly_eval_powers
from janus_tpu.vdaf import reference as ref
from janus_tpu.vdaf.engine import (
    batched_circuit,
    flp_decide_batched,
    flp_prove_batched,
    flp_query_batched,
)
from janus_tpu.fields.jfield import JF64, JF128

RNG = np.random.default_rng(0x1A05)


def rand_elems(field, shape):
    flat = [int(RNG.integers(0, field.MODULUS % (1 << 63))) for _ in range(int(np.prod(shape)))]
    # cover high range too
    for i in range(0, len(flat), 3):
        flat[i] = (flat[i] * 3 + field.MODULUS - 7) % field.MODULUS
    return np.array(flat, dtype=object).reshape(shape)


def to_dev(jf, arr):
    return jf.from_ints(arr)


@pytest.mark.parametrize("jf,field", [(JF64, Field64), (JF128, Field128)])
@pytest.mark.parametrize("n", [2, 8, 64, 256])
def test_ntt_matches_host(jf, field, n):
    batch = 3
    coeffs = rand_elems(field, (batch, n))
    got = jf.to_ints(ntt_batched(jf, to_dev(jf, coeffs), n))
    for b in range(batch):
        want = ref.ntt(field, list(coeffs[b]), n)
        assert list(got[b]) == want
    # round trip
    back = jf.to_ints(intt_batched(jf, ntt_batched(jf, to_dev(jf, coeffs), n)))
    assert (back == coeffs).all()


@pytest.mark.parametrize("jf,field", [(JF64, Field64), (JF128, Field128)])
def test_powers_and_eval(jf, field):
    batch, n = 4, 13
    x = rand_elems(field, (batch,))
    pw = jf.to_ints(powers(jf, to_dev(jf, x), n))
    for b in range(batch):
        assert list(pw[b]) == [field.pow(int(x[b]), k) for k in range(n)]
    coeffs = rand_elems(field, (batch, n))
    ev = jf.to_ints(poly_eval_powers(jf, to_dev(jf, coeffs), powers(jf, to_dev(jf, x), n)))
    for b in range(batch):
        assert ev[b] == ref.poly_eval(field, list(coeffs[b]), int(x[b]))


CIRCUITS = [
    ref.Count(),
    ref.Sum(bits=8),
    ref.SumVec(length=5, bits=4),
    ref.Histogram(length=10),
]


@pytest.mark.parametrize(
    "circ",
    CIRCUITS[:3]
    # 26s: the SumVec differential drives the same streamed-query code;
    # histogram tiled-vs-untiled bit-identity runs fast in
    # test_tiled_prepare (ISSUE 1 CI triage)
    + [pytest.param(CIRCUITS[3], marks=pytest.mark.slow)],
    ids=lambda c: type(c).__name__,
)
def test_flp_prove_query_decide_differential(circ):
    batch = 6
    bc = batched_circuit(circ)
    jf = bc.jf
    F = circ.FIELD

    # random valid-ish inputs: mix valid encodings and garbage
    inps, proofs, prove_rands, joint_rands, query_rands = [], [], [], [], []
    for b in range(batch):
        if b % 2 == 0:
            meas = {
                ref.Count: lambda: b % 2,
                ref.Sum: lambda: b * 37 % 256,
                ref.SumVec: lambda: [(b + i) % 16 for i in range(5)],
                ref.Histogram: lambda: b % 10,
            }[type(circ)]()
            inp = circ.encode(meas)
        else:
            inp = [int(x) for x in rand_elems(F, (circ.input_len,))]
        pr = [int(x) for x in rand_elems(F, (circ.prove_rand_len,))]
        jr = [int(x) for x in rand_elems(F, (circ.joint_rand_len,))]
        qr = [int(x) for x in rand_elems(F, (circ.query_rand_len,))]
        inps.append(inp)
        prove_rands.append(pr)
        joint_rands.append(jr)
        query_rands.append(qr)
        proofs.append(ref.flp_prove(circ, inp, pr, jr))

    d_inp = to_dev(jf, np.array(inps, dtype=object))
    d_pr = to_dev(jf, np.array(prove_rands, dtype=object))
    d_jr = to_dev(jf, np.array(joint_rands, dtype=object).reshape(batch, circ.joint_rand_len))
    d_qr = to_dev(jf, np.array(query_rands, dtype=object))

    got_proofs = jf.to_ints(flp_prove_batched(bc, d_inp, d_pr, d_jr))
    for b in range(batch):
        assert list(got_proofs[b]) == proofs[b], f"proof mismatch report {b}"

    # query each share of a 2-party additive split, batched, vs host
    inp_split0 = [[int(x) for x in rand_elems(F, (circ.input_len,))] for _ in range(batch)]
    inp_split1 = [
        [F.sub(x, s) for x, s in zip(inps[b], inp_split0[b])] for b in range(batch)
    ]
    pf_split0 = [[int(x) for x in rand_elems(F, (circ.proof_len,))] for _ in range(batch)]
    pf_split1 = [
        [F.sub(x, s) for x, s in zip(proofs[b], pf_split0[b])] for b in range(batch)
    ]

    ver_shares_host = [[], []]
    for b in range(batch):
        ver_shares_host[0].append(
            ref.flp_query(circ, inp_split0[b], pf_split0[b], query_rands[b], joint_rands[b], 2)
        )
        ver_shares_host[1].append(
            ref.flp_query(circ, inp_split1[b], pf_split1[b], query_rands[b], joint_rands[b], 2)
        )

    for si, (inp_s, pf_s) in enumerate([(inp_split0, pf_split0), (inp_split1, pf_split1)]):
        got = jf.to_ints(
            flp_query_batched(
                bc,
                to_dev(jf, np.array(inp_s, dtype=object)),
                to_dev(jf, np.array(pf_s, dtype=object)),
                d_qr,
                d_jr,
                2,
            )
        )
        for b in range(batch):
            assert list(got[b]) == ver_shares_host[si][b], f"verifier mismatch share {si} report {b}"

    # combine + decide
    combined = [
        [F.add(a, c) for a, c in zip(ver_shares_host[0][b], ver_shares_host[1][b])]
        for b in range(batch)
    ]
    want_valid = [ref.flp_decide(circ, v) for v in combined]
    d_combined = to_dev(jf, np.array(combined, dtype=object))
    got_valid = np.asarray(flp_decide_batched(bc, d_combined))
    assert list(got_valid) == want_valid
    # sanity: the valid encodings accept, garbage rejects (w.h.p.)
    for b in range(batch):
        if b % 2 == 0:
            assert want_valid[b], f"valid report {b} rejected"


@pytest.mark.parametrize("circ", CIRCUITS, ids=lambda c: type(c).__name__)
def test_encode_batch_matches_host(circ):
    bc = batched_circuit(circ)
    meas = {
        ref.Count: [0, 1, 1],
        ref.Sum: [0, 255, 129],
        ref.SumVec: [[0, 1, 2, 3, 4], [15, 0, 15, 0, 15], [7, 7, 7, 7, 7]],
        ref.Histogram: [0, 9, 5],
    }[type(circ)]
    got = bc.encode_batch(meas)
    for i, m in enumerate(meas):
        assert [int(x) for x in got[i]] == circ.encode(m)
