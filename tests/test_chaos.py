"""Chaos harness schedules (scripts/chaos_run.py; docs/ROBUSTNESS.md).

The fast deterministic smoke runs in tier-1 through bench --dry-run
(test_tools.test_bench_dry_run_smoke asserts its record). This file
holds the heavy full schedule — crash between helper ack and leader
commit, restart into a transport/5xx storm through the circuit
breaker, a SECOND crash after commit-before-ack, a clean restart that
finds nothing to redo, and an exact-ground-truth collection — plus
cheap schedule-definition sanity that does run in tier-1.
"""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_chaos_module():
    """Import scripts/chaos_run.py (not a package) without letting its
    env setup leak into the test process."""
    import importlib.util

    saved = {k: os.environ.get(k) for k in ("XLA_FLAGS", "JAX_PLATFORMS")}
    try:
        spec = importlib.util.spec_from_file_location(
            "chaos_run", os.path.join(REPO, "scripts", "chaos_run.py")
        )
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def test_schedules_parse():
    """The harness's fault schedules must stay valid failpoint specs —
    a typo would silently inject nothing and void the chaos proof."""
    from janus_tpu import failpoints

    chaos = _load_chaos_module()
    for spec in (
        chaos.CRASH_SCHEDULE,
        chaos.POST_COMMIT_CRASH_SCHEDULE,
        chaos.STORM_SCHEDULE,
        chaos.HELPER_5XX_SCHEDULE,
        chaos.DB_OUTAGE_SCHEDULE,
        chaos.FLEET_RTT_SCHEDULE,
    ):
        assert failpoints.parse_spec(spec)
    crash = failpoints.parse_spec(chaos.CRASH_SCHEDULE)[
        "datastore.commit.step_agg_job_write"
    ]
    assert crash.action == "crash" and crash.count == 1
    outage = failpoints.parse_spec(chaos.DB_OUTAGE_SCHEDULE)[
        "datastore.connect.leader"
    ]
    assert outage.action == "error" and outage.prob == 1.0


@pytest.mark.slow  # ~60-90s: four driver subprocess boots
@pytest.mark.chaos
def test_chaos_full_schedule(tmp_path):
    """The full schedule end to end, as an operator would run it."""
    import json

    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    env.pop("XLA_FLAGS", None)
    proc = subprocess.run(
        [
            sys.executable,
            os.path.join("scripts", "chaos_run.py"),
            "--json",
            "--workdir",
            str(tmp_path),
        ],
        cwd=REPO,
        env=env,
        capture_output=True,
        text=True,
        timeout=540,
    )
    assert proc.returncode == 0, proc.stdout[-2000:] + proc.stderr[-2000:]
    rec = json.loads([l for l in proc.stdout.splitlines() if l.startswith("{")][-1])
    assert rec["ok"] is True
    assert rec["schedule"] == "full"
    assert rec["post_commit_crash_ok"] is True
    assert rec["clean_restart_ok"] is True
    assert rec["exactly_once_ok"] is True


@pytest.mark.slow  # ~15s: outage window + replay drain + collection
@pytest.mark.chaos
def test_chaos_db_outage_full_schedule(tmp_path):
    """Datastore-outage survival, full schedule: a sustained upload
    load rides through a multi-second datastore outage on the spill
    journal, /readyz cycles, the journal drains, and the collection
    equals every 201-acked report exactly once."""
    import json

    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    env.pop("XLA_FLAGS", None)
    proc = subprocess.run(
        [
            sys.executable,
            os.path.join("scripts", "chaos_run.py"),
            "--scenario",
            "db_outage",
            "--json",
            "--workdir",
            str(tmp_path),
        ],
        cwd=REPO,
        env=env,
        capture_output=True,
        text=True,
        timeout=540,
    )
    assert proc.returncode == 0, proc.stdout[-2000:] + proc.stderr[-2000:]
    rec = json.loads([l for l in proc.stdout.splitlines() if l.startswith("{")][-1])
    assert rec["ok"] is True
    assert rec["schedule"] == "db_outage_full"
    assert rec["acked_during_outage"] > 0
    assert rec["healthy_fsyncs_ok"] is True
    assert rec["journal_drained_ok"] is True
    assert rec["exactly_once_ok"] is True
    assert rec["collected_count"] == rec["admitted"]
