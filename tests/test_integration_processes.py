"""Full deployment integration: the real binaries as real processes.

The reference runs containerized leader+helper pairs over a network
(integration_tests/tests/janus.rs:14-60, interop_binaries/src/
testcontainer.rs). This is that harness at process scope: both DAP
deployments run as actual `python -m janus_tpu.bin.*` processes over
localhost with SQLite —

  leader side: aggregator + aggregation_job_creator +
               aggregation_job_driver + collection_job_driver
  helper side: aggregator

— tasks provisioned through janus_cli, reports uploaded through the
real Client, results collected through the real Collector, and every
process SIGTERM-drained at the end. Unlike tests/test_e2e.py (the
in-process loopback pair), nothing here shares an interpreter: datastore
Crypter keys, YAML configs, compile caches and HTTP all cross real
process boundaries.
"""

import base64
import os
import secrets
import signal
import subprocess
import sys
import time
import urllib.request

import pytest
import yaml

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

LEADER_DAP = 21310
HELPER_DAP = 21311
HEALTH_BASE = 21320


def _wait_healthz(port: int, deadline_s: float = 90.0) -> None:
    deadline = time.monotonic() + deadline_s
    while True:
        try:
            with urllib.request.urlopen(f"http://127.0.0.1:{port}/healthz", timeout=2) as r:
                assert r.status == 200
                return
        except Exception:
            if time.monotonic() > deadline:
                raise
            time.sleep(0.5)


def _spawn(name: str, cfg_path, key: str, log_path):
    env = dict(os.environ, PYTHONPATH=REPO, DATASTORE_KEYS=key, JAX_PLATFORMS="cpu")
    logf = open(log_path, "wb")
    return subprocess.Popen(
        [sys.executable, "-m", f"janus_tpu.bin.{name}", "--config-file", str(cfg_path)],
        env=env,
        stdout=logf,
        stderr=subprocess.STDOUT,
        cwd=REPO,
    )


@pytest.mark.slow  # 25s subprocess pair; the loopback live-pair e2e keeps the protocol path in tier-1 (ISSUE 1)
def test_deployed_process_pair_end_to_end(tmp_path):
    from janus_tpu.bin import janus_cli
    from janus_tpu.client import Client, ClientParameters
    from janus_tpu.collector import Collector, CollectorParameters
    from janus_tpu.core.auth import AuthenticationToken
    from janus_tpu.core.hpke import generate_hpke_config_and_private_key
    from janus_tpu.core.http_client import HttpClient
    from janus_tpu.core.time_util import RealClock
    from janus_tpu.messages import Duration, Interval, Query, Role, Time
    from janus_tpu.task import QueryTypeConfig, TaskBuilder
    from janus_tpu.vdaf.registry import VdafInstance

    key = base64.urlsafe_b64encode(secrets.token_bytes(16)).decode().rstrip("=")
    leader_db = str(tmp_path / "leader.sqlite")
    helper_db = str(tmp_path / "helper.sqlite")
    leader_url = f"http://127.0.0.1:{LEADER_DAP}/"
    helper_url = f"http://127.0.0.1:{HELPER_DAP}/"

    # --- provision tasks via the real CLI, one DB per deployment ---
    import dataclasses

    vdaf = VdafInstance.count()
    collector_kp = generate_hpke_config_and_private_key(config_id=200)
    leader_task = (
        TaskBuilder(QueryTypeConfig.time_interval(), vdaf, Role.LEADER)
        .with_(
            leader_aggregator_endpoint=leader_url,
            helper_aggregator_endpoint=helper_url,
            collector_hpke_config=collector_kp.config,
            aggregator_auth_token=AuthenticationToken.random_bearer(),
            collector_auth_token=AuthenticationToken.random_bearer(),
            min_batch_size=1,
        )
        .build()
    )
    helper_task = dataclasses.replace(
        leader_task,
        role=Role.HELPER,
        hpke_keys=(generate_hpke_config_and_private_key(config_id=1),),
    )
    for db, task in ((leader_db, leader_task), (helper_db, helper_task)):
        tasks_file = tmp_path / f"tasks_{task.role.name.lower()}.yaml"
        tasks_file.write_text(yaml.safe_dump([task.to_dict()]))
        assert (
            janus_cli.main(
                # =-form: a random key may start with "-" (flag-lookalike)
                ["provision-tasks", str(tasks_file), "--database", db, f"--datastore-keys={key}"]
            )
            == 0
        )

    # --- per-binary YAML configs ---
    def cfg(name: str, db: str, idx: int, extra: str = "") -> str:
        path = tmp_path / f"{name}_{idx}.yaml"
        path.write_text(
            f"database: {{url: {db}}}\n"
            f"health_check_listen_address: \"127.0.0.1:{HEALTH_BASE + idx}\"\n"
            "jax_platform: cpu\n"
            f"compilation_cache_dir: {tmp_path}/xla_cache\n" + extra
        )
        return str(path)

    procs: dict[str, subprocess.Popen] = {}
    try:
        procs["helper"] = _spawn(
            "aggregator",
            cfg("aggregator", helper_db, 0, f'listen_address: "127.0.0.1:{HELPER_DAP}"\n'),
            key,
            tmp_path / "helper.log",
        )
        procs["leader"] = _spawn(
            "aggregator",
            cfg("aggregator", leader_db, 1, f'listen_address: "127.0.0.1:{LEADER_DAP}"\n'),
            key,
            tmp_path / "leader.log",
        )
        procs["creator"] = _spawn(
            "aggregation_job_creator",
            cfg(
                "creator",
                leader_db,
                2,
                "aggregation_job_creation_interval_secs: 0.5\nmin_aggregation_job_size: 1\n",
            ),
            key,
            tmp_path / "creator.log",
        )
        procs["agg_driver"] = _spawn(
            "aggregation_job_driver",
            cfg("agg_driver", leader_db, 3, "worker_lease_duration_secs: 60\n"),
            key,
            tmp_path / "agg_driver.log",
        )
        procs["col_driver"] = _spawn(
            "collection_job_driver",
            cfg("col_driver", leader_db, 4, "worker_lease_duration_secs: 60\n"),
            key,
            tmp_path / "col_driver.log",
        )
        for idx in range(5):
            _wait_healthz(HEALTH_BASE + idx)

        # --- drive the protocol through the real client/collector ---
        clock = RealClock()
        http = HttpClient()
        params = ClientParameters(
            leader_task.task_id, leader_url, helper_url, leader_task.time_precision
        )
        client = Client.with_fetched_configs(params, vdaf, http, clock=clock)
        measurements = [1, 0, 1, 1, 1]
        for m in measurements:
            client.upload(m)

        collector = Collector(
            CollectorParameters(
                leader_task.task_id, leader_url, leader_task.collector_auth_token, collector_kp
            ),
            vdaf,
            http,
        )
        tp = leader_task.time_precision
        start = clock.now().to_batch_interval_start(tp)
        query = Query.time_interval(
            Interval(Time(start.seconds - tp.seconds), Duration(3 * tp.seconds))
        )
        # creator + drivers poll on their own cadence; collection becomes
        # ready once the pipeline has run end to end across 5 processes
        result = collector.collect(query, timeout_s=240.0)
        assert result.report_count == len(measurements)
        assert result.aggregate_result == sum(measurements)

        # --- cross-process trace causality (ISSUE 6): each process's
        # always-on flight recorder is reachable at /debug/traces; the
        # persisted trace_context must stitch spans from genuinely
        # separate interpreters into one trace ---
        import json as _json

        def traces(idx):
            with urllib.request.urlopen(
                f"http://127.0.0.1:{HEALTH_BASE + idx}/debug/traces?limit=2000",
                timeout=10,
            ) as r:
                return _json.loads(r.read())["recent"]

        helper_spans = traces(0)  # helper aggregator
        creator_spans = traces(2)  # aggregation job creator
        agg_driver_spans = traces(3)  # aggregation job driver
        col_driver_spans = traces(4)  # collection job driver

        def ids(spans, name):
            return {s["trace_id"] for s in spans if s["name"] == name}

        # the aggregation-job trace: rooted in the creator process,
        # adopted off the datastore row by the driver process, carried
        # over HTTP to the helper process — one trace id in all three
        job_traces = (
            ids(creator_spans, "creator.create_job")
            & ids(agg_driver_spans, "driver.http_init")
            & ids(helper_spans, "dap.aggregate_init")
        )
        assert job_traces, (
            "no shared aggregation trace id across creator/driver/helper"
        )
        # the collect-time trace contains spans from both aggregator
        # sides: the collection driver's finish span and the helper's
        # aggregate_share handler share the persisted collection trace
        collect_traces = ids(col_driver_spans, "driver.collect_finish") & ids(
            helper_spans, "dap.aggregate_share"
        )
        assert collect_traces, (
            "no shared collection trace id across collection driver/helper"
        )
        # and the collect-finish span links back to the aggregation
        # jobs it covered (the persisted job trace ids)
        finish = next(
            s for s in col_driver_spans if s["name"] == "driver.collect_finish"
        )
        linked = finish.get("args", {}).get("linked_traces", "")
        assert job_traces & set(linked.split(",")), (
            f"collect links {linked!r} do not include the job trace"
        )

        # --- SIGTERM-drain everything cleanly ---
        for proc in procs.values():
            proc.send_signal(signal.SIGTERM)
        for name, proc in procs.items():
            rc = proc.wait(timeout=60)
            assert rc == 0, f"{name} exited {rc}; see {tmp_path}/{name}.log"
            log = (tmp_path / f"{name}.log").read_bytes()
            assert b"shut down" in log, f"{name} did not drain: {log[-1500:]}"
    finally:
        for name, proc in procs.items():
            if proc.poll() is None:
                proc.kill()
            try:
                sys.stderr.write(
                    f"--- {name} tail ---\n"
                    + (tmp_path / f"{name}.log").read_text()[-800:]
                    + "\n"
                )
            except OSError:
                pass
