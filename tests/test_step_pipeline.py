"""Failure-semantics suite for the stage-pipelined leader stepper
(janus_tpu/aggregator/step_pipeline.py, ISSUE 9): a stage error maps to
the existing step-back/attempt semantics, a lease budget that dies
between stages steps back, shutdown drain flushes in-flight stages and
releases failing leases, the device lane serializes dispatches under
concurrent jobs (the PR 7 watchdog/quarantine contract rides the same
ambient deadline), and the pipelined end-to-end step — single- AND
multi-round — lands exactly the serial stepper's datastore state."""

import time

import pytest

from janus_tpu import metrics
from janus_tpu.aggregator.aggregation_job_creator import (
    AggregationJobCreator,
    AggregationJobCreatorConfig,
)
from janus_tpu.aggregator.aggregation_job_driver import AggregationJobDriver
from janus_tpu.aggregator.engine_cache import DeviceHangError
from janus_tpu.aggregator.job_driver import JobDriver, JobDriverConfig, Stopper
from janus_tpu.aggregator.step_pipeline import StepPipeline, StepPipelineConfig
from janus_tpu.client import Client, ClientParameters
from janus_tpu.core.circuit_breaker import CircuitOpenError
from janus_tpu.core.deadline import DeadlineExceeded
from janus_tpu.core.http_client import HttpClient
from janus_tpu.datastore.models import AggregationJobState, ReportAggregationState
from janus_tpu.vdaf.registry import VdafInstance

from test_e2e import pair, provision  # noqa: F401  (fixture + helper)


def _upload(pair, leader_task, vdaf, measurements):
    http = HttpClient()
    params = ClientParameters(
        leader_task.task_id,
        pair["leader_srv"].url,
        pair["helper_srv"].url,
        leader_task.time_precision,
    )
    client = Client.with_fetched_configs(params, vdaf, http, clock=pair["clock"])
    for m in measurements:
        client.upload(m)
    return http


def _make_jobs(pair, job_size=100):
    creator = AggregationJobCreator(
        pair["leader_ds"],
        AggregationJobCreatorConfig(
            min_aggregation_job_size=1, max_aggregation_job_size=job_size
        ),
    )
    return creator.run_once()


def _held_agg_leases(ds):
    return [
        e for e in ds.run_tx(lambda tx: tx.get_held_lease_expiries())
        if e[0] == "aggregation"
    ]


def _agg_job_states(ds):
    counts = ds.run_tx(lambda tx: tx.count_jobs_by_state())
    return {state: n for (typ, state), n in counts.items() if typ == "aggregation"}


def _step_back_delta(reason, fn):
    before = metrics.job_step_back_total.get(reason=reason)
    fn()
    return metrics.job_step_back_total.get(reason=reason) - before


def test_pipelined_step_end_to_end(pair):
    """Multiple concurrent jobs through the full stage chain: all
    finish, all report aggregations land FINISHED, the device lane
    stayed serialized, and every stage executed."""
    vdaf = VdafInstance.count()
    leader_task, _, _ = provision(pair, vdaf)
    http = _upload(pair, leader_task, vdaf, [1, 0, 1, 1, 0, 1])
    assert _make_jobs(pair, job_size=2) == 3

    drv = AggregationJobDriver(pair["leader_ds"], http)
    pipe = StepPipeline(drv, StepPipelineConfig())
    try:
        jd = JobDriver(JobDriverConfig(), drv.acquirer(), drv.stepper, pipeline=pipe)
        while jd.run_once():
            pass
        status = pipe.status()
    finally:
        pipe.close()
    assert _agg_job_states(pair["leader_ds"]) == {"finished": 3}
    assert status["jobs_done"] == 3
    assert status["device_lane"]["dispatches"] >= 6  # init + accumulate per job
    assert status["device_lane"]["concurrent_peak"] <= 1  # serialized lane
    assert not _held_agg_leases(pair["leader_ds"])


def test_pipelined_multi_round_parks_and_finishes(pair):
    """The two-round fake VDAF through the pipeline: round 1 parks
    WaitingLeader via commit_park, round 2 runs the classic continue
    stage — identical states to the serial stepper (test_multi_round)."""
    vdaf = VdafInstance.fake_two_round()
    leader_task, _, _ = provision(pair, vdaf)
    http = _upload(pair, leader_task, vdaf, [1, 0, 1])
    assert _make_jobs(pair) == 1

    drv = AggregationJobDriver(pair["leader_ds"], http)
    pipe = StepPipeline(drv, StepPipelineConfig())
    try:
        jd = JobDriver(JobDriverConfig(), drv.acquirer(), drv.stepper, pipeline=pipe)
        assert jd.run_once() == 1  # init round -> WaitingLeader
        job = pair["leader_ds"].run_tx(
            lambda tx: tx.get_aggregation_jobs_for_task(leader_task.task_id)
        )[0]
        ras = pair["leader_ds"].run_tx(
            lambda tx: tx.get_report_aggregations_for_job(
                leader_task.task_id, job.job_id
            )
        )
        assert {ra.state for ra in ras} == {ReportAggregationState.WAITING_LEADER}
        assert jd.run_once() == 1  # continue round (classic stage) -> finished
    finally:
        pipe.close()
    assert _agg_job_states(pair["leader_ds"]) == {"finished": 1}
    ras = pair["leader_ds"].run_tx(
        lambda tx: tx.get_report_aggregations_for_job(leader_task.task_id, job.job_id)
    )
    assert {ra.state for ra in ras} == {ReportAggregationState.FINISHED}


def _one_leased_job(pair, vdaf=None, measurements=(1, 0, 1)):
    vdaf = vdaf or VdafInstance.count()
    leader_task, _, _ = provision(pair, vdaf)
    http = _upload(pair, leader_task, vdaf, list(measurements))
    assert _make_jobs(pair) == 1
    drv = AggregationJobDriver(pair["leader_ds"], http)
    acquired = drv.acquirer()(1)
    assert len(acquired) == 1
    return drv, acquired[0]


def test_stage_error_maps_to_step_back_with_attempt_refunded(pair):
    """A CircuitOpenError out of the HTTP stage steps the job back:
    lease released early, job still IN_PROGRESS (not failed), counted
    under reason=circuit_open — exactly the serial stepper's mapping."""
    drv, acquired = _one_leased_job(pair)
    attempts_at_first_acquire = acquired.lease.attempts

    def open_circuit(st):
        raise CircuitOpenError("helper", 0.0)

    drv.http_init = open_circuit
    pipe = StepPipeline(drv, StepPipelineConfig())
    try:
        delta = _step_back_delta(
            "circuit_open", lambda: pipe.submit(acquired).result(timeout=60)
        )
    finally:
        pipe.close()
    assert delta == 1
    assert _agg_job_states(pair["leader_ds"]) == {"in_progress": 1}
    assert not _held_agg_leases(pair["leader_ds"])  # released, not held to TTL
    # attempt refunded: the step-back released with count_attempt=False,
    # so the next acquire sees the same attempt count (after the 1s
    # reacquire floor delay, advanced on the mock clock)
    from janus_tpu.messages import Duration

    pair["clock"].advance(Duration(2))
    reacquired = drv.acquirer()(1)
    assert len(reacquired) == 1
    assert reacquired[0].lease.attempts == attempts_at_first_acquire


def test_deadline_expiry_between_stages_steps_back(pair):
    """A lease budget that dies AFTER staging but BEFORE the device
    hand-off trips the stage-boundary re-check: step-back with
    reason=deadline_expired, job untouched."""
    drv, acquired = _one_leased_job(pair)
    drv._lease_deadline = lambda a: time.monotonic() + 0.1
    orig_stage = drv.stage_init

    def slow_stage(*a, **kw):
        st = orig_stage(*a, **kw)
        time.sleep(0.3)  # budget dies while the job heads to the lane
        return st

    drv.stage_init = slow_stage
    pipe = StepPipeline(drv, StepPipelineConfig())
    try:
        delta = _step_back_delta(
            "deadline_expired", lambda: pipe.submit(acquired).result(timeout=60)
        )
    finally:
        pipe.close()
    assert delta == 1
    assert _agg_job_states(pair["leader_ds"]) == {"in_progress": 1}
    assert not _held_agg_leases(pair["leader_ds"])


def test_device_hang_in_lane_steps_back(pair):
    """DeviceHangError surfacing on the device lane maps to the PR 7
    contract: step-back reason=device_hang, never a failed attempt."""
    drv, acquired = _one_leased_job(pair)

    def hang(st):
        raise DeviceHangError("leader_init", 0.1)

    drv.device_init = hang
    pipe = StepPipeline(drv, StepPipelineConfig())
    try:
        delta = _step_back_delta(
            "device_hang", lambda: pipe.submit(acquired).result(timeout=60)
        )
    finally:
        pipe.close()
    assert delta == 1
    assert _agg_job_states(pair["leader_ds"]) == {"in_progress": 1}


def test_shutdown_drain_releases_failing_lease(pair):
    """A stage failing while the stopper is set releases the lease via
    the releaser (the serial _step_one contract): the surviving peer
    reacquires immediately instead of waiting out the TTL."""
    drv, acquired = _one_leased_job(pair)

    def boom(st):
        raise RuntimeError("stage exploded mid-drain")

    drv.http_init = boom
    stopper = Stopper()
    stopper.stop()
    released = []
    pipe = StepPipeline(
        drv,
        StepPipelineConfig(),
        stopper=stopper,
        releaser=lambda a: released.append(a) or drv.step_back(a, "shutdown_drain", 0.0),
    )
    try:
        pipe.submit(acquired).result(timeout=60)
    finally:
        pipe.close()
    assert released == [acquired]
    assert not _held_agg_leases(pair["leader_ds"])


def test_unhandled_stage_error_leaves_lease_to_expire(pair):
    """Outside shutdown, an unhandled stage error must NOT release the
    lease (the serial stepper lets it expire and retry) — and the
    outer future still resolves so the driver loop keeps flowing."""
    drv, acquired = _one_leased_job(pair)

    def boom(st):
        raise RuntimeError("unexpected stage failure")

    drv.device_init = boom
    pipe = StepPipeline(drv, StepPipelineConfig())
    try:
        pipe.submit(acquired).result(timeout=60)
    finally:
        pipe.close()
    assert len(_held_agg_leases(pair["leader_ds"])) == 1  # still leased
    assert _agg_job_states(pair["leader_ds"]) == {"in_progress": 1}


def test_device_lane_serializes_under_concurrent_jobs(pair):
    """With many jobs in flight the lane never runs two device stages
    at once (workers=1), while read/HTTP stages of other jobs overlap
    it — the overlap events the metrics record."""
    vdaf = VdafInstance.count()
    leader_task, _, _ = provision(pair, vdaf)
    http = _upload(pair, leader_task, vdaf, [1] * 8)
    assert _make_jobs(pair, job_size=2) == 4

    drv = AggregationJobDriver(pair["leader_ds"], http)
    orig_device_init = drv.device_init

    def slow_device_init(st):
        time.sleep(0.05)  # widen the window a concurrent dispatch would need
        return orig_device_init(st)

    drv.device_init = slow_device_init
    pipe = StepPipeline(drv, StepPipelineConfig(device_lane_workers=1))
    try:
        jd = JobDriver(
            JobDriverConfig(max_concurrent_job_workers=4),
            drv.acquirer(),
            drv.stepper,
            pipeline=pipe,
        )
        while jd.run_once():
            pass
        status = pipe.status()
    finally:
        pipe.close()
    assert _agg_job_states(pair["leader_ds"]) == {"finished": 4}
    assert status["device_lane"]["concurrent_peak"] == 1
    assert status["device_lane"]["dispatches"] == 8


def test_expired_lease_at_read_steps_back(pair):
    """_lease_deadline raising (already-expired lease) inside the read
    stage maps to reason=deadline_expired — same as the serial path."""
    drv, acquired = _one_leased_job(pair)

    def expired(a):
        raise DeadlineExceeded("lease already expired (test)")

    drv._lease_deadline = expired
    pipe = StepPipeline(drv, StepPipelineConfig())
    try:
        delta = _step_back_delta(
            "deadline_expired", lambda: pipe.submit(acquired).result(timeout=60)
        )
    finally:
        pipe.close()
    assert delta == 1


def test_abandon_after_max_attempts_still_applies(pair):
    """The attempts ceiling is enforced in the pipeline's read stage,
    like the serial stepper's entry check."""
    import dataclasses

    drv, acquired = _one_leased_job(pair)
    lease = dataclasses.replace(
        acquired.lease, attempts=drv.cfg.maximum_attempts_before_failure + 1
    )
    over = dataclasses.replace(acquired, lease=lease)
    before = metrics.job_cancel_counter.get(kind="aggregation")
    pipe = StepPipeline(drv, StepPipelineConfig())
    try:
        pipe.submit(over).result(timeout=60)
    finally:
        pipe.close()
    assert metrics.job_cancel_counter.get(kind="aggregation") == before + 1
    assert _agg_job_states(pair["leader_ds"]) == {"abandoned": 1}
