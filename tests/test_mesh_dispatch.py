"""Multi-chip serving (ISSUE 16): the single-controller mesh dispatch
queue (FIFO fairness, exception propagation, lane accounting — and the
process-global _MESH_DISPATCH_LOCK it replaced being GONE), mesh-vs-
single-device bit-identity through the SERVING EngineCache path (count
+ sumvec, rejected lanes, sharded resident accumulate) both in-process
and in a subprocess forced to a different device topology, geometry
selection, and the prewarm geometry-mismatch skip."""

import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from janus_tpu.aggregator import engine_cache as ec
from janus_tpu.aggregator.engine_cache import (
    EngineCache,
    MeshDispatchQueue,
    mesh_status,
)
from janus_tpu.messages import Duration, Interval, Time
from janus_tpu.vdaf.registry import VdafInstance
from janus_tpu.vdaf.testing import make_report_batch, random_measurements

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

COUNT = VdafInstance.count()
SUMVEC = VdafInstance.sum_vec(length=4, bits=2)


# ---------------------------------------------------------------------------
# the dispatch queue itself (no device work)
# ---------------------------------------------------------------------------


def test_mesh_dispatch_lock_is_gone():
    # the PR 14 process-global lock is replaced by the queue; anything
    # still importing it should fail loudly, not silently double-lock
    assert not hasattr(ec, "_MESH_DISPATCH_LOCK")
    assert isinstance(ec._MESH_QUEUE, MeshDispatchQueue)


def test_mesh_dispatch_queue_single_lane_no_overlap_no_starvation():
    q = MeshDispatchQueue()
    lanes = set()
    executed = []
    busy = threading.Event()
    overlaps = []

    def work(tag):
        if busy.is_set():
            overlaps.append(tag)
        busy.set()
        try:
            lanes.add(threading.current_thread().name)
            executed.append(tag)
            time.sleep(0.001)
        finally:
            busy.clear()
        return tag * 2

    results = {}
    errors = []

    def submitter(base):
        # several sequential submits per thread: a starved submitter
        # would wedge here and trip the join timeout below
        try:
            for j in range(5):
                tag = base * 100 + j
                results[tag] = q.submit(work, (tag,), {}, program="t")
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    threads = [threading.Thread(target=submitter, args=(i,)) for i in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    assert not any(t.is_alive() for t in threads), "submitter starved"
    assert not errors
    assert not overlaps, f"dispatch lane overlapped: {overlaps}"
    assert lanes == {"mesh-dispatch"}
    assert len(executed) == 20
    assert results == {t: t * 2 for t in executed}
    st = q.status()
    assert st["submitted"] == 20
    assert st["completed"] == 20
    assert st["errors"] == 0
    assert st["depth"] == 0
    assert st["lane_alive"] is True
    assert st["busy_s"] > 0


def test_mesh_dispatch_queue_exception_propagates_and_lane_survives():
    q = MeshDispatchQueue()

    class Boom(RuntimeError):
        pass

    boom = Boom("injected")

    def bad():
        raise boom

    with pytest.raises(Boom) as ei:
        q.submit(bad, (), {}, vdaf="count", program="bad")
    # the ORIGINAL exception object: OOM recovery tags the instance
    # (_janus_oom_handled) and type-checks it, so a re-wrap would break
    # the engine's error handling
    assert ei.value is boom
    st = q.status()
    assert st["errors"] == 1
    # the lane survives a failed enqueue and keeps serving
    assert q.submit(lambda: 7, (), {}, program="ok") == 7
    assert q.status()["completed"] == 2


def test_mesh_dispatch_queue_fifo_order_when_backlogged():
    q = MeshDispatchQueue()
    order = []
    gate = threading.Event()

    def blocker():
        gate.wait(30)
        order.append("blocker")

    def tagged(i):
        order.append(i)

    # park the lane on the blocker, then pile up a backlog in a known
    # submit order; the single lane must drain it FIFO
    t0 = threading.Thread(target=q.submit, args=(blocker, (), {}))
    t0.start()
    for _ in range(200):
        if q.status()["depth"] == 0 and q.status()["submitted"] == 1:
            break
        time.sleep(0.005)
    backlog = []
    started = threading.Event()

    def enqueue(i):
        # stagger the racers: each waits for the previous one to be
        # COUNTED as submitted before enqueuing, making submit order
        # deterministic while the lane stays parked
        q.submit(tagged, (i,), {})

    for i in range(6):
        want = 2 + i  # blocker + i prior + this one
        th = threading.Thread(target=enqueue, args=(i,))
        th.start()
        backlog.append(th)
        for _ in range(400):
            if q.status()["submitted"] >= want:
                break
            time.sleep(0.005)
    gate.set()
    t0.join(timeout=30)
    for th in backlog:
        th.join(timeout=30)
    assert order == ["blocker", 0, 1, 2, 3, 4, 5]
    st = q.status()
    assert st["max_depth"] >= 6  # the backlog was really queued


# ---------------------------------------------------------------------------
# geometry selection + mesh status
# ---------------------------------------------------------------------------


def test_choose_mesh_geometry_contract():
    from janus_tpu.parallel.api import choose_mesh_geometry

    # single device: always (1, 1)
    assert choose_mesh_geometry(1, 2, 1, 4096, 32) == (1, 1)
    # auto: largest power of two <= ndev
    assert choose_mesh_geometry(4, 2, 1, 4096, 32) == (4, 1)
    assert choose_mesh_geometry(6, 2, 1, 4096, 32) == (4, 1)
    # long vectors carve an sp=2 axis (input and output divisible)
    dp, sp = choose_mesh_geometry(8, 8192, 8192, 4096, 32)
    assert sp == 2 and dp * sp <= 8
    # explicit overrides validated: non-pow2 dp rounds down, dp*sp
    # clamped to the device count
    assert choose_mesh_geometry(8, 2, 1, 4096, 32, dp=3) == (2, 1)
    assert choose_mesh_geometry(4, 8, 8, 4096, 32, dp=4, sp=2) == (2, 2)
    # sp that doesn't divide the vector falls back to 1
    assert choose_mesh_geometry(8, 7, 7, 0, 32, sp=2)[1] == 1


def test_mesh_statusz_section_shape():
    import jax

    # the statusz section lists engines registered in the process-wide
    # factory cache (direct EngineCache constructions are invisible)
    ec.engine_cache(COUNT, b"\x21" * 16)
    snap = mesh_status()
    assert snap["devices"] == len(jax.devices())
    for key in ("depth", "lane_alive", "submitted", "completed", "errors"):
        assert key in snap["queue"]
    assert any(
        e["vdaf"] == "count" and e["dp"] * e["sp"] >= 1 and "mesh" in e
        for e in snap["engines"]
    )


# ---------------------------------------------------------------------------
# serving-path bit-identity: mesh vs forced-single geometry
# ---------------------------------------------------------------------------


def _serve(eng, inst, n=32, seed=0x51, k=2):
    """One serving round through the REAL EngineCache entry points:
    leader + helper init, masked aggregate with rejected lanes, then
    the sharded resident accumulate + flush. Returns stringified field
    elements so results compare across processes via JSON."""
    rng = np.random.default_rng(seed)
    args, _ = make_report_batch(inst, random_measurements(inst, n, rng), seed=seed)
    nonce, parts, meas, proof, blind0, hseed, blind1 = args
    ok = np.ones(n, dtype=bool)
    ok[::5] = False  # rejected lanes stay in the batch
    out0, _s, ver0, part0 = eng.leader_init(nonce, parts, meas, proof, blind0)
    p0 = part0 if part0 is not None else np.zeros((n, 2), dtype=np.uint64)
    out1, _mask, _pm = eng.helper_init(nonce, parts, hseed, blind1, ver0, p0, ok)
    agg0 = [str(x) for x in eng.aggregate(out0, ok)]
    agg1 = [str(x) for x in eng.aggregate(out1, ok)]
    deltas = eng.aggregate_pending(out0, (np.arange(n) % k).astype(np.int32), k)
    iv = Interval(Time(0), Duration(3600))
    eng.resident_merge([(("g", j), j, n // k, iv) for j in range(k)], deltas)
    res = sorted(
        [str(r["key"]), [str(x) for x in r["share"]]] for r in eng.resident_take()
    )
    return {"agg0": agg0, "agg1": agg1, "resident": res}


@pytest.mark.slow  # the tier-1 bit-identity proof is the subprocess smoke below; this in-process variant adds the 8-device geometry + live queue-counter assertions
def test_mesh_vs_single_device_bit_identical_in_process(monkeypatch):
    import jax

    if len(jax.devices()) < 2:
        pytest.skip("needs the multi-device conftest mesh")
    mesh_eng = EngineCache(SUMVEC, b"\x11" * 16)
    assert mesh_eng.mesh is not None
    monkeypatch.setenv("JANUS_MESH_DP", "1")
    monkeypatch.setenv("JANUS_MESH_SP", "1")
    single_eng = EngineCache(SUMVEC, b"\x11" * 16)
    assert single_eng.mesh is None
    assert _serve(mesh_eng, SUMVEC) == _serve(single_eng, SUMVEC)
    # the mesh engine's work went through the single-controller lane
    st = mesh_status()["queue"]
    assert st["submitted"] > 0 and st["errors"] == 0 and st["lane_alive"]


_SUBPROC_CHILD = """
import json
import numpy as np
import jax; jax.config.update('jax_platforms', 'cpu')
import test_mesh_dispatch as t

out = {"devices": len(jax.devices())}
for name, inst in (("count", t.COUNT), ("sumvec", t.SUMVEC)):
    eng = t.EngineCache(inst, b"\\x11" * 16)
    rec = t._serve(eng, inst)
    rec["dp"], rec["sp"] = eng.dp, eng.sp
    out[name] = rec
print("MESH_BITID:" + json.dumps(out), flush=True)
"""


def test_mesh_subprocess_bit_identity_forced_4dev(monkeypatch):
    """The ISSUE 16 tier-1 smoke: a subprocess forced to a 4-device
    topology (XLA_FLAGS=--xla_force_host_platform_device_count=4)
    serves count + sumvec through the mesh EngineCache; this process
    serves the SAME batches with geometry forced to single-device.
    Every aggregate and resident share must be bit-identical."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    flags = " ".join(
        f
        for f in env.get("XLA_FLAGS", "").split()
        if "xla_force_host_platform_device_count" not in f
    )
    env["XLA_FLAGS"] = f"{flags} --xla_force_host_platform_device_count=4".strip()
    env.pop("JANUS_MESH_DP", None)
    env.pop("JANUS_MESH_SP", None)
    env.setdefault(
        "JAX_COMPILATION_CACHE_DIR", os.path.expanduser("~/.cache/jax_comp_cache")
    )
    script = (
        "import sys; sys.path.insert(0, %r); sys.path.insert(0, %r)\n"
        % (REPO, os.path.join(REPO, "tests"))
    ) + _SUBPROC_CHILD
    proc = subprocess.run(
        [sys.executable, "-c", script],
        env=env,
        cwd=REPO,
        capture_output=True,
        text=True,
        timeout=420,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    rec = None
    for line in reversed(proc.stdout.splitlines()):
        if line.startswith("MESH_BITID:"):
            rec = json.loads(line[len("MESH_BITID:"):])
            break
    assert rec is not None, proc.stdout[-2000:]
    assert rec["devices"] == 4
    assert rec["count"]["dp"] * rec["count"]["sp"] > 1
    monkeypatch.setenv("JANUS_MESH_DP", "1")
    monkeypatch.setenv("JANUS_MESH_SP", "1")
    for name, inst in (("count", COUNT), ("sumvec", SUMVEC)):
        eng = EngineCache(inst, b"\x11" * 16)
        assert eng.mesh is None
        ref = _serve(eng, inst)
        assert rec[name]["agg0"] == ref["agg0"], name
        assert rec[name]["agg1"] == ref["agg1"], name
        assert rec[name]["resident"] == ref["resident"], name


# ---------------------------------------------------------------------------
# prewarm skips manifest entries recorded under a different topology
# ---------------------------------------------------------------------------


def test_prewarm_skips_geometry_mismatch(tmp_path, monkeypatch):
    import jax

    from janus_tpu.aggregator import prewarm, shape_manifest

    if len(jax.devices()) < 2:
        pytest.skip("needs the multi-device conftest mesh")
    prewarm.reset_for_tests()
    man = shape_manifest.install_manifest(str(tmp_path / "m.jsonl"))
    try:
        eng = EngineCache(COUNT, bytes(range(16)))
        assert eng.mesh is not None
        # ONE dispatch records mesh-geometry-keyed manifest entries
        # (leader_init only: the skip logic is per-entry, one suffices)
        rng = np.random.default_rng(1)
        args, _ = make_report_batch(
            COUNT, random_measurements(COUNT, 8, rng), seed=1
        )
        nonce, parts, meas, proof, blind0, _h, _b1 = args
        eng.leader_init(nonce, parts, meas, proof, blind0)
        geoms = {shape_manifest.entry_geometry(e["key"]) for e in man.entries()}
        assert geoms == {(eng.dp, eng.sp, eng._ndev)}
        # a single-device boot replaying this manifest must skip every
        # entry, distinctly counted — not trace programs it never runs
        monkeypatch.setenv("JANUS_MESH_DP", "1")
        monkeypatch.setenv("JANUS_MESH_SP", "1")
        eng2 = EngineCache(COUNT, bytes(range(16)))
        assert eng2.mesh is None
        w = prewarm._Warmer()
        outcomes = [w.warm(eng2, e) for e in man.entries()]
        assert outcomes and all(o == "geometry_mismatch" for o in outcomes)
        # covers() is geometry-aware the same way: the warmup would
        # still owe these compiles on the new topology
        assert not man.covers({"kind": "count"}, "leader_init", 32, geometry=None)
        assert man.covers(
            {"kind": "count"},
            "leader_init",
            32,
            geometry=(eng.dp, eng.sp, eng._ndev),
        )
    finally:
        shape_manifest.uninstall_manifest()
        prewarm.reset_for_tests()
