"""End-to-end test through the interop test API only
(draft-dcook-ppm-dap-interop-test-design), mirroring the reference's
interop_binaries/tests/end_to_end.rs:570-905: everything — task setup,
uploads, collection — goes through the three JSON servers exactly as a
foreign test harness would drive them."""

import base64
import json
import secrets
import time
import urllib.request

import pytest

from janus_tpu.core.time_util import MockClock
from janus_tpu.datastore.store import EphemeralDatastore
from janus_tpu.interop import InteropAggregator, InteropClient, InteropCollector
from janus_tpu.messages import Time


def post(url, doc):
    req = urllib.request.Request(
        url, data=json.dumps(doc).encode(), headers={"Content-Type": "application/json"}
    )
    with urllib.request.urlopen(req) as resp:
        return json.loads(resp.read())


def b64(b):
    return base64.urlsafe_b64encode(b).decode().rstrip("=")


@pytest.fixture()
def stack():
    """Leader + helper interop aggregators, interop client + collector."""
    clock = MockClock(Time(1_600_000_000))
    leader_eph = EphemeralDatastore(clock=clock)
    helper_eph = EphemeralDatastore(clock=clock)
    leader = InteropAggregator(leader_eph.datastore, clock=clock)
    helper = InteropAggregator(helper_eph.datastore, clock=clock)
    leader_srv = leader.server().start()
    helper_srv = helper.server().start()
    leader.start_job_runners()
    client_srv = InteropClient(clock=clock).server().start()
    collector_srv = InteropCollector().server().start()
    yield {
        "clock": clock,
        "leader": leader_srv,
        "helper": helper_srv,
        "client": client_srv,
        "collector": collector_srv,
    }
    leader.stop()
    helper.stop()
    for s in (leader_srv, helper_srv, client_srv, collector_srv):
        s.stop()
    leader_eph.cleanup()
    helper_eph.cleanup()


VDAF_CASES = [
    ({"type": "Prio3Count"}, ["1", "0", "1", "1"], "3"),
    # sumvec compiles ~95s on CPU — nightly/on-chip (ISSUE 1 CI triage);
    # count keeps the interop-API wire path in the fast suite
    pytest.param(
        {"type": "Prio3SumVec", "bits": "8", "length": "3"},
        [["1", "2", "3"], ["10", "20", "30"]],
        ["11", "22", "33"],
        marks=pytest.mark.slow,
    ),
]


@pytest.mark.parametrize("vdaf_obj,measurements,expected", VDAF_CASES, ids=["count", "sumvec"])
def test_interop_end_to_end(stack, vdaf_obj, measurements, expected):
    task_id = b64(secrets.token_bytes(32))
    verify_key = b64(secrets.token_bytes(16))
    leader_token = "leader-" + b64(secrets.token_bytes(8))
    collector_token = "collector-" + b64(secrets.token_bytes(8))
    leader_url = stack["leader"].url
    helper_url = stack["helper"].url
    time_precision = 3600

    # readiness probes
    for srv in ("leader", "helper", "client", "collector"):
        post(stack[srv].url + "internal/test/ready", {})

    # endpoint discovery
    resp = post(
        leader_url + "internal/test/endpoint_for_task",
        {"task_id": task_id, "role": "leader"},
    )
    assert resp["endpoint"] == "/"

    # collector first: it generates the collector HPKE config
    resp = post(
        stack["collector"].url + "internal/test/add_task",
        {
            "task_id": task_id,
            "leader": leader_url,
            "vdaf": vdaf_obj,
            "collector_authentication_token": collector_token,
            "query_type": 1,
        },
    )
    assert resp["status"] == "success", resp
    collector_hpke_config = resp["collector_hpke_config"]

    common = {
        "task_id": task_id,
        "leader": leader_url,
        "helper": helper_url,
        "vdaf": vdaf_obj,
        "leader_authentication_token": leader_token,
        "vdaf_verify_key": verify_key,
        "max_batch_query_count": 1,
        "query_type": 1,
        "min_batch_size": 1,
        "time_precision": time_precision,
        "collector_hpke_config": collector_hpke_config,
        "task_expiration": None,
    }
    resp = post(
        leader_url + "internal/test/add_task",
        {**common, "role": "leader", "collector_authentication_token": collector_token},
    )
    assert resp["status"] == "success", resp
    resp = post(helper_url + "internal/test/add_task", {**common, "role": "helper"})
    assert resp["status"] == "success", resp

    # uploads through the interop client
    for m in measurements:
        resp = post(
            stack["client"].url + "internal/test/upload",
            {
                "task_id": task_id,
                "leader": leader_url,
                "helper": helper_url,
                "vdaf": vdaf_obj,
                "measurement": m,
                "time_precision": time_precision,
            },
        )
        assert resp["status"] == "success", resp

    # collection through the interop collector
    now = stack["clock"].now().seconds
    resp = post(
        stack["collector"].url + "internal/test/collection_start",
        {
            "task_id": task_id,
            "agg_param": "",
            "query": {
                "type": 1,
                "batch_interval_start": (now // time_precision - 1) * time_precision,
                "batch_interval_duration": time_precision * 3,
            },
        },
    )
    assert resp["status"] == "success", resp
    handle = resp["handle"]

    deadline = time.monotonic() + 300
    while True:
        resp = post(
            stack["collector"].url + "internal/test/collection_poll", {"handle": handle}
        )
        if resp["status"] == "complete":
            break
        assert time.monotonic() < deadline, "collection did not complete"
        time.sleep(1)
    assert resp["report_count"] == str(len(measurements))
    assert resp["result"] == expected
