"""Outbound circuit breaker (core/circuit_breaker.py): the state
machine, fail-fast behavior inside the driver's retry loop, the
step-back lease semantics, and the /statusz + metrics surface."""

import threading
import time

import pytest

from janus_tpu import metrics
from janus_tpu.core.circuit_breaker import (
    CircuitBreakerConfig,
    CircuitOpenError,
    OutboundCircuitBreakers,
    default_breakers,
    peer_label,
    reset_default_breakers,
)


def test_peer_label():
    assert peer_label("http://helper.example:8080/dap/") == "helper.example:8080"
    assert peer_label("https://helper.example/") == "helper.example"


def test_closed_until_consecutive_threshold():
    br = OutboundCircuitBreakers(CircuitBreakerConfig(failure_threshold=3))
    for _ in range(2):
        br.record_failure("p")
    br.record_success("p")  # success resets the consecutive counter
    for _ in range(2):
        br.record_failure("p")
    assert br.state("p") == "closed"
    br.record_failure("p")  # third consecutive
    assert br.state("p") == "open"
    assert br.retry_in_s("p") > 0


def test_open_rejects_then_half_open_probe_closes():
    br = OutboundCircuitBreakers(
        CircuitBreakerConfig(failure_threshold=1, open_cooldown_s=0.05)
    )
    br.record_failure("p")
    with pytest.raises(CircuitOpenError) as ei:
        br.check("p")
    assert ei.value.retry_in_s <= 0.05
    time.sleep(0.06)
    br.check("p")  # admitted as the half-open probe
    assert br.state("p") == "half_open"
    br.record_success("p")
    assert br.state("p") == "closed"
    br.check("p")  # closed: free flow


def test_half_open_admits_single_probe_and_reopens_on_failure():
    br = OutboundCircuitBreakers(
        CircuitBreakerConfig(failure_threshold=1, open_cooldown_s=0.01)
    )
    br.record_failure("p")
    time.sleep(0.02)
    br.check("p")  # probe slot taken
    with pytest.raises(CircuitOpenError):
        br.check("p")  # concurrent caller: rejected while probing
    br.record_failure("p")  # probe failed
    assert br.state("p") == "open"
    assert br.retry_in_s("p") > 0  # cooldown restarted


def test_metrics_and_status_surface():
    br = OutboundCircuitBreakers(
        CircuitBreakerConfig(failure_threshold=1, open_cooldown_s=60.0)
    )
    br.record_failure("helper.example:443")
    assert metrics.outbound_circuit_state.get(peer="helper.example:443") == 1.0
    assert (
        metrics.outbound_circuit_transitions.get(peer="helper.example:443", to="open")
        >= 1.0
    )
    st = br.status()
    peer = st["peers"]["helper.example:443"]
    assert peer["state"] == "open" and peer["retry_in_s"] > 0
    assert st["config"]["failure_threshold"] == 1


def test_default_registry_registers_statusz_provider():
    from janus_tpu.statusz import status_snapshot

    reset_default_breakers()
    br = default_breakers(CircuitBreakerConfig(failure_threshold=9))
    assert default_breakers() is br  # shared process-wide
    snap = status_snapshot()
    assert snap["outbound_circuit"]["config"]["failure_threshold"] == 9


def test_disabled_breaker_is_inert():
    br = OutboundCircuitBreakers(CircuitBreakerConfig(enabled=False, failure_threshold=1))
    for _ in range(10):
        br.record_failure("p")
    br.check("p")  # never raises


class _FailingHttp:
    last_response_headers: dict = {}

    def __init__(self, status=None):
        self.calls = 0
        self.status = status  # None = transport error, int = HTTP status

    def _req(self, *a, **k):
        self.calls += 1
        if self.status is None:
            raise ConnectionError("connection refused (test double)")
        return self.status, b"boom"

    put = post = _req


def test_driver_request_opens_circuit_and_fails_fast():
    """Transport failures inside _send_agg_job_request trip the breaker
    at the configured threshold; the NEXT attempt is gated without
    touching the network (fail fast, lease time preserved)."""
    from janus_tpu.aggregator.aggregation_job_driver import (
        AggregationJobDriver,
        AggregationJobDriverConfig,
    )
    from janus_tpu.core.retries import Backoff
    from janus_tpu.messages import AggregationJobId, AggregationJobInitializeReq, PartialBatchSelector, Role
    from janus_tpu.task import QueryTypeConfig, TaskBuilder
    from janus_tpu.vdaf.registry import VdafInstance

    task = (
        TaskBuilder(QueryTypeConfig.time_interval(), VdafInstance.count(), Role.LEADER)
        .with_(helper_aggregator_endpoint="http://helper.test:9999/")
        .build()
    )
    http = _FailingHttp()
    drv = AggregationJobDriver(
        None,
        http,
        AggregationJobDriverConfig(http_backoff=Backoff.test()),
        breakers=OutboundCircuitBreakers(
            CircuitBreakerConfig(failure_threshold=2, open_cooldown_s=60.0)
        ),
    )
    req = AggregationJobInitializeReq(b"", PartialBatchSelector.time_interval(), ())
    with pytest.raises(CircuitOpenError):
        drv._send_agg_job_request(task, AggregationJobId(bytes(16)), "PUT", req)
    assert http.calls == 2  # exactly threshold attempts hit the wire
    assert drv.breakers.state("helper.test:9999") == "open"


def test_driver_5xx_storm_counts_as_failure():
    """Real HTTP 500s (a melting helper, not a dead socket) trip the
    breaker the same way."""
    from janus_tpu.aggregator.aggregation_job_driver import (
        AggregationJobDriver,
        AggregationJobDriverConfig,
    )
    from janus_tpu.core.retries import Backoff
    from janus_tpu.messages import AggregationJobId, AggregationJobInitializeReq, PartialBatchSelector, Role
    from janus_tpu.task import QueryTypeConfig, TaskBuilder
    from janus_tpu.vdaf.registry import VdafInstance

    task = (
        TaskBuilder(QueryTypeConfig.time_interval(), VdafInstance.count(), Role.LEADER)
        .with_(helper_aggregator_endpoint="http://helper5xx.test/")
        .build()
    )
    http = _FailingHttp(status=503)
    drv = AggregationJobDriver(
        None,
        http,
        AggregationJobDriverConfig(http_backoff=Backoff.test()),
        breakers=OutboundCircuitBreakers(
            CircuitBreakerConfig(failure_threshold=3, open_cooldown_s=60.0)
        ),
    )
    req = AggregationJobInitializeReq(b"", PartialBatchSelector.time_interval(), ())
    with pytest.raises(CircuitOpenError):
        drv._send_agg_job_request(task, AggregationJobId(bytes(16)), "PUT", req)
    assert http.calls == 3


def test_stepper_treats_circuit_open_as_step_back(monkeypatch):
    """A breaker-open step releases the lease with the cooldown as the
    reacquire delay and refunds the attempt — the job neither burns a
    lease TTL nor marches toward abandonment."""
    from janus_tpu.aggregator.aggregation_job_driver import (
        AggregationJobDriver,
        AggregationJobDriverConfig,
    )
    from janus_tpu.core.time_util import MockClock
    from janus_tpu.datastore.store import EphemeralDatastore
    from janus_tpu.messages import Duration, Time
    from test_lease_invariants import make_task, put_job

    clock = MockClock(Time(1_600_000_000))
    eph = EphemeralDatastore(clock=clock)
    ds = eph.datastore
    try:
        task = make_task(ds)
        put_job(ds, task, bytes(16))
        (acquired,) = ds.run_tx(
            lambda tx: tx.acquire_incomplete_aggregation_jobs(Duration(600), 1)
        )
        assert acquired.lease.attempts == 1
        drv = AggregationJobDriver(
            ds, None, breakers=OutboundCircuitBreakers(CircuitBreakerConfig())
        )
        monkeypatch.setattr(
            drv,
            "step_aggregation_job",
            lambda a: (_ for _ in ()).throw(CircuitOpenError("helper.test", 4.0)),
        )
        before = metrics.job_step_back_total.get(reason="circuit_open")
        drv.stepper(acquired)  # must not raise
        assert metrics.job_step_back_total.get(reason="circuit_open") == before + 1
        # not reacquirable during the breaker cooldown...
        assert (
            ds.run_tx(lambda tx: tx.acquire_incomplete_aggregation_jobs(Duration(600), 1))
            == []
        )
        clock.advance(Duration(5))
        # ...but afterwards it is, and the attempt was refunded: this
        # acquire's increment lands back on 1, not 2
        (re,) = ds.run_tx(
            lambda tx: tx.acquire_incomplete_aggregation_jobs(Duration(600), 1)
        )
        assert re.lease.attempts == 1
    finally:
        eph.cleanup()


def test_concurrent_checks_race_safely():
    """Many threads hammering check/record around a transition never
    deadlock or corrupt state (the transition lock is the only guard)."""
    br = OutboundCircuitBreakers(
        CircuitBreakerConfig(failure_threshold=2, open_cooldown_s=0.005)
    )
    stop = threading.Event()
    errors: list = []

    def worker(i):
        try:
            while not stop.is_set():
                try:
                    br.check("p")
                except CircuitOpenError:
                    continue
                if i % 2:
                    br.record_failure("p")
                else:
                    br.record_success("p")
        except Exception as e:  # pragma: no cover
            errors.append(e)

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(6)]
    for t in threads:
        t.start()
    time.sleep(0.3)
    stop.set()
    for t in threads:
        t.join(timeout=5)
    assert not errors
    assert br.state("p") in ("closed", "open", "half_open")


def test_half_open_admits_exactly_one_probe_across_threads():
    """Probe-slot stampede: after the cooldown, N workers (driver steps
    and the peer-health prober alike) race check() — exactly ONE gets
    the half-open slot, everyone else fails fast. One success then
    closes the breaker for all of them."""
    br = OutboundCircuitBreakers(
        CircuitBreakerConfig(failure_threshold=1, open_cooldown_s=0.02)
    )
    br.record_failure("p")
    time.sleep(0.03)

    n = 8
    barrier = threading.Barrier(n)
    admitted: list = []
    rejected: list = []
    lock = threading.Lock()

    def worker():
        barrier.wait()
        try:
            br.check("p")
        except CircuitOpenError:
            with lock:
                rejected.append(1)
        else:
            with lock:
                admitted.append(1)

    threads = [threading.Thread(target=worker) for _ in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=10)
    assert len(admitted) == 1 and len(rejected) == n - 1
    br.record_success("p")
    assert br.state("p") == "closed"


def test_retry_after_paces_attempts_under_the_deadline_split():
    """Retry-After steers the inter-attempt sleep (no exponential
    growth, no jitter) while the overall deadline still owns the loop —
    the server paces us, the lease bounds us."""
    from janus_tpu.core.retries import Backoff, retry_http_request

    sleeps: list = []
    calls = {"n": 0}

    def do_request():
        calls["n"] += 1
        if calls["n"] < 3:
            return 429, b"", {"Retry-After": "0.8"}
        return 201, b"ok"

    status, body = retry_http_request(
        do_request,
        backoff=Backoff(initial=0.01, max_interval=2.0, max_elapsed=30.0),
        sleep=sleeps.append,
        deadline=time.monotonic() + 60.0,
    )
    assert (status, body) == (201, b"ok") and calls["n"] == 3
    assert sleeps == [0.8, 0.8]  # server-paced, not 0.01 then 0.02


def test_retry_after_never_outlives_the_lease_deadline():
    """A huge Retry-After is clamped to max_interval, and a sleep that
    would cross the lease deadline is never started — the loop raises
    DeadlineExceeded instead of parking the worker past its lease."""
    from janus_tpu.core.deadline import DeadlineExceeded
    from janus_tpu.core.retries import Backoff, retry_http_request

    def do_request():
        return 429, b"", {"Retry-After": "9999"}

    slept: list = []
    with pytest.raises(DeadlineExceeded):
        retry_http_request(
            do_request,
            backoff=Backoff(initial=0.01, max_interval=5.0, max_elapsed=600.0),
            sleep=slept.append,
            deadline=time.monotonic() + 0.05,
        )
    assert slept == []  # the doomed sleep was never taken


def test_deadline_request_timeout_attempt_cap():
    """The overall-deadline/per-attempt split: each attempt's socket
    timeout is min(remaining lease, attempt cap), so a blackholed peer
    burns attempt_cap seconds per swing, never the whole lease."""
    from janus_tpu.aggregator.job_driver import deadline_request_timeout

    dl = time.monotonic() + 100.0
    assert deadline_request_timeout(dl) == pytest.approx(100.0, abs=1.0)
    assert deadline_request_timeout(dl, attempt_cap_s=2.0) == pytest.approx(
        2.0, abs=0.01
    )
    assert deadline_request_timeout(None, attempt_cap_s=7.0) == 7.0
    assert deadline_request_timeout(None) is None
