"""Codec-equivalence fuzz for the columnar leader<->helper wire path
(ISSUE 9): the vectorized framing/parsing must be BIT-IDENTICAL to the
per-report dataclass codec for every registered VDAF — same bytes out,
same accepts/rejects in — and the order-aligned prepare-resp fast path
must fall back (and count) on a helper that violates the DAP ordering
contract."""

import secrets
import struct

import numpy as np
import pytest

from janus_tpu import metrics
from janus_tpu.messages import (
    AggregationJobContinueReq,
    AggregationJobInitializeReq,
    AggregationJobResp,
    AggregationJobStep,
    DecodeError,
    HpkeCiphertext,
    HpkeConfigId,
    PartialBatchSelector,
    PreEncoded,
    PrepareContinue,
    PrepareError,
    PrepareInit,
    PrepareResp,
    PrepareStepResult,
    ReportId,
    ReportMetadata,
    ReportShare,
    Time,
    decode_prepare_resps_fast,
    encode_report_share_raw,
)
from janus_tpu.vdaf.registry import VdafInstance, circuit_for
from janus_tpu.vdaf.wire import (
    PP_CONTINUE,
    PP_FINISH,
    PP_INITIALIZE,
    Prio3Wire,
    encode_field_rows,
    encode_pingpong,
    encode_pingpong_share_column,
    pingpong_finish_frame_matches,
)

# every registered Prio3 VDAF kind (poplar1 has no FLP circuit and its
# leader path is not columnar), incl. the multi-round fake
ALL_INSTANCES = [
    VdafInstance.count(),
    VdafInstance.sum(8),
    VdafInstance.sum_vec(16, 4),
    VdafInstance.count_vec(6),
    VdafInstance.histogram(10),
    VdafInstance.fixed_point_vec(4),
    VdafInstance.fake(),
    VdafInstance.fake_two_round(),
]


class _JF:
    def __init__(self, circ):
        self.LIMBS = circ.FIELD.ENCODED_SIZE // 8
        self.MODULUS = circ.FIELD.MODULUS


def _random_device_outputs(circ, wire, n, rng):
    v = circ.verifier_len
    jf = _JF(circ)
    ver0 = tuple(
        rng.integers(0, 1 << 31, size=(n, v), dtype=np.uint64)
        for _ in range(jf.LIMBS)
    )
    part0 = (
        rng.integers(0, 1 << 63, size=(n, 2), dtype=np.uint64)
        if wire.uses_jr
        else None
    )
    return jf, ver0, part0


def _random_report_columns(wire, n, rng):
    rids = [secrets.token_bytes(16) for _ in range(n)]
    times = [Time(1_600_000_000 + int(rng.integers(0, 10_000))) for _ in range(n)]
    pubs = [secrets.token_bytes(wire.public_share_len) for _ in range(n)]
    cts = [
        HpkeCiphertext(
            HpkeConfigId(int(rng.integers(0, 256))),
            secrets.token_bytes(int(rng.integers(16, 64))),
            secrets.token_bytes(wire.helper_share_len + 44),
        )
        for _ in range(n)
    ]
    return rids, times, pubs, cts


@pytest.mark.parametrize("inst", ALL_INSTANCES, ids=lambda i: i.kind + str(i.rounds))
def test_init_request_columnar_bytes_identical(inst):
    """The columnar init-request build (framing column + PreEncoded
    splices) produces byte-for-byte the per-report loop's request, for
    every registered VDAF (incl. the multi-round fake)."""
    circ = circuit_for(inst)
    wire = Prio3Wire(circ)
    rng = np.random.default_rng(hash(inst.kind) & 0xFFFF)
    n = 33
    jf, ver0, part0 = _random_device_outputs(circ, wire, n, rng)
    rids, times, pubs, cts = _random_report_columns(wire, n, rng)
    pbs = PartialBatchSelector.time_interval()

    # pre-ISSUE-9 per-report loop
    ver_rows = encode_field_rows(jf, ver0)
    part_rows = (
        [row.tobytes() for row in np.asarray(part0, dtype="<u8")]
        if wire.uses_jr
        else [None] * n
    )
    loop_items = tuple(
        PrepareInit(
            ReportShare(ReportMetadata(ReportId(rids[i]), times[i]), pubs[i], cts[i]),
            encode_pingpong(
                PP_INITIALIZE, None, wire.encode_prep_share_raw(ver_rows[i], part_rows[i])
            ),
        )
        for i in range(n)
    )
    loop_bytes = AggregationJobInitializeReq(b"", pbs, loop_items).to_bytes()

    # columnar path (what AggregationJobDriver.http_init does)
    frames = encode_pingpong_share_column(jf, ver0, part0)
    col_items = tuple(
        PreEncoded(
            encode_report_share_raw(rids[i], times[i].seconds, pubs[i], cts[i])
            + frames.row(i)
        )
        for i in range(n)
    )
    col_bytes = AggregationJobInitializeReq(b"", pbs, col_items).to_bytes()
    assert col_bytes == loop_bytes
    # and the helper-side decoder accepts them identically
    decoded = AggregationJobInitializeReq.from_bytes(col_bytes)
    assert len(decoded.prepare_inits) == n


def test_continue_request_preencoded_bytes_identical():
    """The continue request's PreEncoded splices (report_id || framed
    ping-pong message, incl. multi-round PP_CONTINUE/PP_FINISH frames)
    equal the PrepareContinue dataclass encoding."""
    rng = np.random.default_rng(7)
    n = 17
    rids = [secrets.token_bytes(16) for _ in range(n)]
    msgs = []
    for i in range(n):
        body = secrets.token_bytes(int(rng.integers(0, 40)))
        if i % 3 == 0:
            msgs.append(encode_pingpong(PP_FINISH, body, None))
        elif i % 3 == 1:
            msgs.append(encode_pingpong(PP_CONTINUE, body, secrets.token_bytes(8)))
        else:
            msgs.append(encode_pingpong(PP_INITIALIZE, None, body))
    loop = AggregationJobContinueReq(
        AggregationJobStep(2),
        tuple(PrepareContinue(ReportId(r), m) for r, m in zip(rids, msgs)),
    ).to_bytes()
    col = AggregationJobContinueReq(
        AggregationJobStep(2),
        tuple(PreEncoded(r + m) for r, m in zip(rids, msgs)),
    ).to_bytes()
    assert col == loop


def test_report_share_raw_fuzz():
    rng = np.random.default_rng(11)
    for _ in range(50):
        rid = secrets.token_bytes(16)
        t = int(rng.integers(0, 1 << 40))
        pub = secrets.token_bytes(int(rng.integers(0, 64)))
        ct = HpkeCiphertext(
            HpkeConfigId(int(rng.integers(0, 256))),
            secrets.token_bytes(int(rng.integers(0, 96))),
            secrets.token_bytes(int(rng.integers(0, 200))),
        )
        assert encode_report_share_raw(rid, t, pub, ct) == ReportShare(
            ReportMetadata(ReportId(rid), Time(t)), pub, ct
        ).to_bytes()


def _random_resp(rng, n):
    resps = []
    for _ in range(n):
        kind = int(rng.integers(0, 3))
        rid = ReportId(secrets.token_bytes(16))
        if kind == PrepareStepResult.CONTINUE:
            tag = int(rng.integers(0, 3))
            body = secrets.token_bytes(int(rng.integers(0, 30)))
            if tag == PP_CONTINUE:
                msg = encode_pingpong(tag, body, secrets.token_bytes(4))
            elif tag == PP_FINISH:
                msg = encode_pingpong(tag, body, None)
            else:
                msg = encode_pingpong(tag, None, body)
            resps.append(PrepareResp(rid, PrepareStepResult.cont(msg)))
        elif kind == PrepareStepResult.FINISHED:
            resps.append(PrepareResp(rid, PrepareStepResult.finished()))
        else:
            err = PrepareError(int(rng.integers(0, 10)))
            resps.append(PrepareResp(rid, PrepareStepResult.reject(err)))
    return AggregationJobResp(tuple(resps))


def test_response_fast_parse_equivalent_on_valid_bodies():
    rng = np.random.default_rng(13)
    for trial in range(30):
        resp = _random_resp(rng, int(rng.integers(0, 20)))
        body = resp.to_bytes()
        col = decode_prepare_resps_fast(body)
        ref = AggregationJobResp.from_bytes(body)
        assert col.report_ids == [r.report_id.data for r in ref.prepare_resps]
        assert list(col.kinds) == [r.result.kind for r in ref.prepare_resps]
        assert col.messages == [r.result.message for r in ref.prepare_resps]
        assert col.errors == [r.result.prepare_error for r in ref.prepare_resps]


def test_response_fast_parse_rejects_what_the_codec_rejects():
    """Mutational fuzz: truncations, trailing bytes and corrupted
    tag/kind/error bytes must raise DecodeError from BOTH parsers, or
    parse successfully in both — never diverge."""
    rng = np.random.default_rng(17)
    base = _random_resp(rng, 8).to_bytes()
    mutants = [base[:k] for k in range(0, len(base), 3)]
    mutants += [base + b"\x00", base + secrets.token_bytes(3)]
    for _ in range(200):
        m = bytearray(base)
        pos = int(rng.integers(0, len(m)))
        m[pos] = int(rng.integers(0, 256))
        mutants.append(bytes(m))
    for m in mutants:
        try:
            ref = AggregationJobResp.from_bytes(m)
            ref_outcome = [
                (r.report_id.data, r.result.kind, r.result.message, r.result.prepare_error)
                for r in ref.prepare_resps
            ]
        except DecodeError:
            ref_outcome = "DecodeError"
        try:
            col = decode_prepare_resps_fast(m)
            col_outcome = list(
                zip(col.report_ids, (int(k) for k in col.kinds), col.messages, col.errors)
            )
        except DecodeError:
            col_outcome = "DecodeError"
        if ref_outcome == "DecodeError" or col_outcome == "DecodeError":
            assert ref_outcome == col_outcome == "DecodeError", m.hex()
        else:
            assert [tuple(t) for t in col_outcome] == ref_outcome, m.hex()


def test_order_aligned_fast_path_and_fallback():
    from janus_tpu.aggregator.aggregation_job_driver import AggregationJobDriver

    drv = AggregationJobDriver.__new__(AggregationJobDriver)  # matching is stateless
    rng = np.random.default_rng(19)
    n = 12
    rids = [secrets.token_bytes(16) for _ in range(n)]
    body = AggregationJobResp(
        tuple(PrepareResp(ReportId(r), PrepareStepResult.finished()) for r in rids)
    ).to_bytes()
    col = decode_prepare_resps_fast(body)

    before = metrics.prep_resp_order_mismatch_total.total()
    # aligned: identity mapping, no counter, no dict
    assert drv._match_resps(rids, col) is None
    assert metrics.prep_resp_order_mismatch_total.total() == before

    # shuffled: fallback mapping resolves every id, counter ticks
    perm = list(rng.permutation(n))
    shuffled_body = AggregationJobResp(
        tuple(
            PrepareResp(ReportId(rids[j]), PrepareStepResult.finished()) for j in perm
        )
    ).to_bytes()
    shuffled = decode_prepare_resps_fast(shuffled_body)
    mapping = drv._match_resps(rids, shuffled)
    assert mapping is not None
    assert metrics.prep_resp_order_mismatch_total.total() == before + 1
    for k, j in enumerate(mapping):
        assert shuffled.report_ids[j] == rids[k]

    # missing id: None lane (the driver marks it INVALID_MESSAGE)
    short = decode_prepare_resps_fast(
        AggregationJobResp(
            tuple(
                PrepareResp(ReportId(r), PrepareStepResult.finished())
                for r in rids[1:]
            )
        ).to_bytes()
    )
    mapping = drv._match_resps(rids, short)
    assert mapping[0] is None and all(m is not None for m in mapping[1:])


def test_pingpong_finish_fast_verify_matches_decode_semantics():
    """pingpong_finish_frame_matches must agree with the old
    decode_pingpong-based check on every well-formed frame."""
    from janus_tpu.vdaf.wire import decode_pingpong

    want = secrets.token_bytes(16)
    frames = [
        encode_pingpong(PP_FINISH, want, None),
        encode_pingpong(PP_FINISH, secrets.token_bytes(16), None),
        encode_pingpong(PP_FINISH, secrets.token_bytes(8), None),
        encode_pingpong(PP_FINISH, b"", None),
        encode_pingpong(PP_CONTINUE, want, b"share"),
        encode_pingpong(PP_INITIALIZE, None, want),
    ]
    for frame in frames:
        tag, prep_msg, _ = decode_pingpong(frame)
        if tag != PP_FINISH or prep_msg is None or len(prep_msg) != len(want):
            expected = None  # invalid for this verify
        elif prep_msg == want:
            expected = True
        else:
            expected = False
        assert pingpong_finish_frame_matches(frame, want) is expected, frame.hex()


def test_frame_column_matches_scalar_encoder_for_all_instances():
    for inst in ALL_INSTANCES:
        circ = circuit_for(inst)
        wire = Prio3Wire(circ)
        rng = np.random.default_rng(23)
        n = 9
        jf, ver0, part0 = _random_device_outputs(circ, wire, n, rng)
        frames = encode_pingpong_share_column(jf, ver0, part0)
        ver_rows = encode_field_rows(jf, ver0)
        part_rows = (
            [row.tobytes() for row in np.asarray(part0, dtype="<u8")]
            if wire.uses_jr
            else [None] * n
        )
        for i in range(n):
            assert frames.row(i) == encode_pingpong(
                PP_INITIALIZE, None, wire.encode_prep_share_raw(ver_rows[i], part_rows[i])
            ), inst.kind


def test_length_prefix_layout_pinned():
    """The framing layout (u8 tag || u32 BE length || share) is pinned
    against the codec module's own constants — a drive-by change to
    either side must fail here, not in an interop lab."""
    share = b"\xaa" * 7
    assert encode_pingpong(PP_INITIALIZE, None, share) == b"\x00" + struct.pack(
        ">I", 7
    ) + share
