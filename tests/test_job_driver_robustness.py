"""Job-driver robustness: lease-bounded step deadlines and the
streaming (non-barrier) worker pool (reference
aggregator/src/binary_utils/job_driver.rs:119-196) — one hung helper
must neither outlive its lease nor block other jobs."""

import threading
import time

import pytest

from janus_tpu.aggregator.job_driver import JobDriver, JobDriverConfig, Stopper
from janus_tpu.core.retries import Backoff, retry_http_request


def test_retry_deadline_stops_retrying():
    from janus_tpu.core.retries import DeadlineExceeded

    calls = []

    def do_request():
        calls.append(time.monotonic())
        return 503, b"unavailable"  # retryable forever

    deadline = time.monotonic() + 0.15
    # the deadline (not the backoff budget) ends the retries: that is
    # never a conclusive response — DeadlineExceeded carries the stale
    # status for logging only
    with pytest.raises(DeadlineExceeded) as ei:
        retry_http_request(
            do_request, Backoff(initial=0.01, max_elapsed=60.0), deadline=deadline
        )
    assert ei.value.last_status == 503
    assert time.monotonic() <= deadline + 0.2


def test_retry_deadline_raises_without_any_response():
    def do_request():
        raise OSError("connect refused")

    with pytest.raises(OSError):
        retry_http_request(
            do_request,
            Backoff(initial=0.01, max_elapsed=60.0),
            deadline=time.monotonic() + 0.1,
        )


def test_retry_deadline_already_passed_raises_timeout():
    def do_request():  # pragma: no cover - must not be called
        raise AssertionError("request attempted past deadline")

    with pytest.raises(TimeoutError):
        retry_http_request(do_request, deadline=time.monotonic() - 1)


def test_retry_deadline_during_sleep_raises_not_stale_response():
    """A retryable response followed by a sleep that crosses the
    deadline must surface as DeadlineExceeded (carrying the stale
    status for logging), never as a conclusive (status, body)."""
    from janus_tpu.core.retries import DeadlineExceeded

    deadline = time.monotonic() + 0.05

    def do_request():
        return 503, b"unavailable"

    def sleep(_):  # a sleep that overshoots the deadline
        time.sleep(0.2)

    with pytest.raises(DeadlineExceeded) as ei:
        retry_http_request(
            do_request,
            # huge interval so out_of_budget's now+interval pre-check
            # cannot return early; the top-of-loop deadline check after
            # the overshooting sleep must decide
            Backoff(initial=0.0001, multiplier=1.0, max_elapsed=60.0, jitter=0.0),
            sleep=sleep,
            deadline=deadline,
        )
    assert ei.value.last_status == 503


def test_streaming_pool_hung_job_does_not_block_others():
    """One job hangs; later-discovered jobs still run while it hangs
    (the old run_once barrier would wait for the whole batch)."""
    hang = threading.Event()
    done: dict[str, float] = {}
    lock = threading.Lock()

    jobs = [["hung"], ["a"], ["b"], []]
    calls = {"n": 0}

    def acquirer(limit):
        i = min(calls["n"], len(jobs) - 1)
        calls["n"] += 1
        batch = jobs[i][:limit]
        jobs[i] = jobs[i][len(batch):]
        return batch

    def stepper(job):
        if job == "hung":
            hang.wait(timeout=10)
        with lock:
            done[job] = time.monotonic()

    stopper = Stopper()
    jd = JobDriver(
        JobDriverConfig(
            max_concurrent_job_workers=2,
            job_discovery_interval_s=0.01,
            max_job_discovery_interval_s=0.05,
        ),
        acquirer,
        stepper,
        stopper,
    )
    t = threading.Thread(target=jd.run, daemon=True)
    t.start()
    try:
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            with lock:
                if "a" in done and "b" in done:
                    break
            time.sleep(0.01)
        with lock:
            assert "a" in done and "b" in done, done
            assert "hung" not in done  # still hanging while others ran
    finally:
        hang.set()
        stopper.stop()
        t.join(timeout=5)
    assert "hung" in done  # shutdown drained the in-flight step


def test_retry_aborts_on_should_abort_mid_loop():
    """SIGTERM mid-retry: should_abort() flips after the first attempt
    and the loop raises RequestAborted promptly instead of retrying the
    dead helper through the remaining backoff/lease budget."""
    from janus_tpu.core.retries import RequestAborted

    aborted = threading.Event()
    calls = {"n": 0}

    def do_request():
        calls["n"] += 1
        aborted.set()  # the 'signal' arrives while this attempt runs
        return 503, b"unavailable"

    with pytest.raises(RequestAborted):
        retry_http_request(
            do_request,
            Backoff(initial=0.001, max_elapsed=60.0),
            should_abort=aborted.is_set,
        )
    assert calls["n"] == 1


def test_sigterm_drain_releases_lease_immediately():
    """A step failing during shutdown hands its lease back through the
    releaser (driver step_back) so the surviving peer reacquires NOW,
    not after a full lease TTL; the attempt ledger survives."""
    from janus_tpu.aggregator.aggregation_job_driver import AggregationJobDriver
    from janus_tpu.core.time_util import MockClock
    from janus_tpu.datastore.store import EphemeralDatastore
    from janus_tpu.messages import Duration, Time
    from test_lease_invariants import make_task, put_job

    clock = MockClock(Time(1_600_000_000))
    eph = EphemeralDatastore(clock=clock)
    ds = eph.datastore
    try:
        task = make_task(ds)
        put_job(ds, task, bytes(16))
        drv = AggregationJobDriver(ds, None)
        acquired_box: list = []
        in_step = threading.Event()
        release_step = threading.Event()

        def acquirer(limit):
            if acquired_box:
                return []
            got = ds.run_tx(
                lambda tx: tx.acquire_incomplete_aggregation_jobs(
                    Duration(600), limit
                )
            )
            acquired_box.extend(got)
            return got

        def stepper(acquired):
            in_step.set()
            release_step.wait(timeout=10)
            raise RuntimeError("helper vanished mid-step (test)")

        stopper = Stopper()
        jd = JobDriver(
            JobDriverConfig(job_discovery_interval_s=0.01),
            acquirer,
            stepper,
            stopper,
            releaser=lambda acq: drv.step_back(acq, "shutdown_drain", 0.0),
        )
        t = threading.Thread(target=jd.run, daemon=True)
        t.start()
        assert in_step.wait(timeout=10)
        stopper.stop()  # SIGTERM: stop acquiring, drain in flight
        release_step.set()  # the in-flight step now fails
        t.join(timeout=10)
        assert not t.is_alive()
        # the 600s lease was released immediately: reacquirable without
        # advancing the clock, and the refunded attempt lands back on 1
        (re,) = ds.run_tx(
            lambda tx: tx.acquire_incomplete_aggregation_jobs(Duration(600), 1)
        )
        assert re.lease.attempts == 1
    finally:
        eph.cleanup()


def test_step_failure_without_shutdown_keeps_lease():
    """Outside shutdown the age-out semantics are unchanged: a failed
    step leaves the lease to expire (the retry pacing mechanism)."""
    from janus_tpu.core.time_util import MockClock
    from janus_tpu.datastore.store import EphemeralDatastore
    from janus_tpu.messages import Duration, Time
    from test_lease_invariants import make_task, put_job

    clock = MockClock(Time(1_600_000_000))
    eph = EphemeralDatastore(clock=clock)
    ds = eph.datastore
    try:
        task = make_task(ds)
        put_job(ds, task, bytes(16))
        (acquired,) = ds.run_tx(
            lambda tx: tx.acquire_incomplete_aggregation_jobs(Duration(600), 1)
        )
        released: list = []
        stopper = Stopper()  # NOT stopped
        jd = JobDriver(
            JobDriverConfig(),
            lambda limit: [],
            lambda a: (_ for _ in ()).throw(RuntimeError("boom")),
            stopper,
            releaser=released.append,
        )
        jd._step_one(acquired)
        assert released == []  # no shutdown: lease ages out as before
        assert (
            ds.run_tx(lambda tx: tx.acquire_incomplete_aggregation_jobs(Duration(600), 1))
            == []
        )
    finally:
        eph.cleanup()
