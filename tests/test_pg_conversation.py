"""Wire-conversation assertions for the PostgresDatastore adapter.

Drives `PostgresDatastore` through the recorded-conversation fake
driver (janus_tpu.datastore.pg_fake) and asserts the exact SQL +
parameter streams for the paths whose semantics live in PG-specific
SQL and retry logic: advisory-lock bootstrap, FOR UPDATE SKIP LOCKED
lease acquire, guarded lease release, serialization-failure retry, and
broken-connection discard. In-image executable coverage of the PG
engine (VERDICT r4 item 7); the same flows run against a real server
via docker-compose.pg.yaml + JANUS_TEST_DATABASE_URL.

Reference anchors: datastore.rs:203-305 (run_tx + retry),
datastore.rs:1836-1905 (lease claims).
"""

import pytest

from janus_tpu.core.time_util import MockClock
from janus_tpu.datastore.pg_fake import (
    FakePostgresDriver,
    OperationalError,
    SerializationFailure,
)
from janus_tpu.datastore.store import (
    Crypter,
    PostgresDatastore,
    TxConflict,
)
from janus_tpu.messages import Duration, Time


@pytest.fixture
def pg():
    driver = FakePostgresDriver()
    ds = PostgresDatastore(
        "postgresql://fake-host:5432/janus",
        Crypter(),
        MockClock(Time(1_600_000_000)),
        schema="janus_pgtest",
        driver=driver,
    )
    yield ds, driver
    ds.close()
    driver.cleanup()


def _sqls(driver, kind="execute"):
    return [e[1] for e in driver.statements(kind)]


def test_bootstrap_conversation(pg):
    """Boot: advisory lock serializes schema creation; DDL is the PG
    dialect (BYTEA/BIGINT, never sqlite's BLOB/INTEGER); version row
    checked then inserted; one commit."""
    _, driver = pg
    sqls = _sqls(driver)
    assert sqls[0].startswith("SELECT pg_advisory_xact_lock")
    assert 'CREATE SCHEMA IF NOT EXISTS "janus_pgtest"' in sqls[1]
    ddl = [s for s in sqls if "CREATE TABLE" in s]
    assert ddl, "bootstrap must create tables"
    joined = "\n".join(ddl)
    assert "BYTEA" in joined and "BIGINT" in joined
    assert "BLOB" not in joined
    # sqlite INTEGER must be fully translated (PG INTEGER is 32-bit)
    import re

    assert not re.search(r"\bINTEGER\b", joined)
    assert any("INSERT INTO schema_version" in s for s in sqls)
    assert ("commit",) in driver.log


def test_connection_setup(pg):
    """psycopg connect: transactional (autocommit=False is asserted in
    the fake), REPEATABLE READ isolation, schema search_path option."""
    ds, driver = pg
    conn = ds._connect()
    assert conn.isolation_level == FakePostgresDriver.IsolationLevel.REPEATABLE_READ
    connects = driver.statements("connect")
    assert connects and connects[0][1] == "postgresql://fake-host:5432/janus"
    assert "options" in connects[0][2]  # -c search_path=...


def test_lease_acquire_wire_form(pg):
    """The batched lease claim is ONE statement: UPDATE .. WHERE
    (task_id, job_id) IN (<randomized pick from an index-ordered
    oldest-first window locked FOR UPDATE SKIP LOCKED>) RETURNING ..,
    with a fresh 16-byte token and %s placeholders (never sqlite's
    qmark) — the queue-pop idiom, claiming K jobs per claim
    round-trip instead of per row."""
    ds, driver = pg
    from tests.test_datastore import _aggjob, mktask

    task = mktask()
    ds.run_tx(lambda tx: tx.put_task(task))
    job = _aggjob(task)
    ds.run_tx(lambda tx: tx.put_aggregation_job(job))
    driver.clear_log()

    acquired = ds.run_tx(
        lambda tx: tx.acquire_incomplete_aggregation_jobs(Duration(600), 10)
    )
    assert len(acquired) == 1
    upd = [
        e
        for e in driver.statements()
        if e[1].lstrip().startswith("UPDATE aggregation_jobs SET lease_expiry")
    ]
    assert len(upd) == 1, "the batched claim must be ONE statement"
    sql = upd[0][1]
    # inner: bounded oldest-first window over the lease index, locked
    # FOR UPDATE SKIP LOCKED; outer: RANDOMIZED claim order within it
    # (never the whole-backlog collision-maximizing deterministic scan)
    assert "IN (SELECT task_id, job_id FROM (SELECT task_id, job_id," in sql
    import re as _re

    assert _re.search(
        r"ORDER BY lease_expiry LIMIT \d+ FOR UPDATE SKIP LOCKED\)", sql
    ), sql
    assert _re.search(r"\) AS cand ORDER BY random\(\) LIMIT %s\)", sql), sql
    assert "RETURNING task_id, job_id, lease_attempts, shard_key" in sql
    assert "?" not in sql and "%s" in sql
    expiry, token, now, limit = upd[0][2]
    assert expiry == now + 600
    assert limit == 10
    assert isinstance(token, bytes) and len(token) == 16
    assert acquired[0].lease.token == token


def test_lease_release_guarded_and_conflict(pg):
    """Release is token-guarded; a lost lease raises TxConflict (which
    run_tx treats as retryable, so use the single-attempt tx())."""
    ds, driver = pg
    from tests.test_datastore import _aggjob, mktask

    task = mktask()
    ds.run_tx(lambda tx: tx.put_task(task))
    job = _aggjob(task)
    ds.run_tx(lambda tx: tx.put_aggregation_job(job))
    acq = ds.run_tx(lambda tx: tx.acquire_incomplete_aggregation_jobs(Duration(600), 1))[0]

    driver.clear_log()
    ds.run_tx(lambda tx: tx.release_aggregation_job(acq))
    rel = [e for e in driver.statements() if "lease_token = NULL" in e[1]]
    assert len(rel) == 1
    assert rel[0][1].rstrip().endswith("lease_token = %s")
    # params: (eligible-since stamp, re-stamped shard affinity,
    # task_id, job_id, guarding token)
    assert rel[0][2][4] == acq.lease.token

    # releasing again: token no longer matches -> TxConflict
    with pytest.raises(TxConflict):
        with ds.tx() as tx:
            tx.release_aggregation_job(acq)


def test_serialization_failure_retries(pg):
    """REPEATABLE READ: a SerializationFailure mid-transaction rolls
    back and re-runs the closure (reference run_tx, datastore.rs:216)."""
    ds, driver = pg
    from tests.test_datastore import mktask

    task = mktask()
    driver.inject_once(
        lambda sql, p: sql.startswith("INSERT INTO tasks"),
        SerializationFailure("could not serialize access due to concurrent update"),
    )
    calls = {"n": 0}

    def fn(tx):
        calls["n"] += 1
        tx.put_task(task)

    ds.run_tx(fn)
    assert calls["n"] == 2, "closure must re-run after serialization failure"
    # conversation: INSERT attempt, rollback, INSERT again, commit
    kinds = [e[0] for e in driver.log]
    assert "rollback" in kinds
    inserts = [e for e in driver.statements() if e[1].startswith("INSERT INTO tasks")]
    assert len(inserts) == 2
    assert ds.run_tx(lambda tx: tx.get_task(task.task_id)) is not None


def test_broken_connection_discarded_and_reconnected(pg):
    """An OperationalError on a broken connection must not poison the
    thread-local cache: the adapter discards it and the retry opens a
    fresh connection (reference: deadpool re-checkout)."""
    ds, driver = pg
    from tests.test_datastore import mktask

    task = mktask()
    conn0 = ds._connect()

    def break_conn(sql, p):
        conn0.broken = True
        return sql.startswith("INSERT INTO tasks")

    driver.inject_once(break_conn, OperationalError("server closed the connection unexpectedly"))
    n_before = len(driver.statements("connect"))
    ds.run_tx(lambda tx: tx.put_task(task))
    n_after = len(driver.statements("connect"))
    assert n_after == n_before + 1, "a fresh connection must be opened"
    assert ds._connect() is not conn0
    assert ds.run_tx(lambda tx: tx.get_task(task.task_id)) is not None


def test_no_qmark_reaches_the_wire(pg):
    """Every statement the adapter emits uses %s binding: drive a
    representative op mix and grep the conversation."""
    ds, driver = pg
    from tests.test_datastore import mktask

    task = mktask()
    ds.run_tx(lambda tx: tx.put_task(task))
    ds.run_tx(lambda tx: tx.get_task_ids())
    ds.run_tx(lambda tx: tx.delete_task(task.task_id))
    for e in driver.statements():
        assert "?" not in e[1], e[1]
