"""taskprov tests: wire round-trips, verify-key derivation, datastore
peers, and the full helper-side in-band provisioning flow over HTTP
(reference taskprov_tests.rs / aggregator.rs:639-776)."""

import base64
import dataclasses

import pytest

from janus_tpu.aggregator import Aggregator, Config
from janus_tpu.aggregator.aggregation_job_creator import (
    AggregationJobCreator,
    AggregationJobCreatorConfig,
)
from janus_tpu.aggregator.aggregation_job_driver import AggregationJobDriver
from janus_tpu.aggregator.collection_job_driver import CollectionJobDriver
from janus_tpu.aggregator.http_handlers import DapHttpApp, DapServer
from janus_tpu.aggregator.job_driver import JobDriver, JobDriverConfig
from janus_tpu.client import Client, ClientParameters
from janus_tpu.collector import Collector, CollectorParameters
from janus_tpu.core.auth import AuthenticationToken
from janus_tpu.core.hpke import generate_hpke_config_and_private_key
from janus_tpu.core.http_client import HttpClient
from janus_tpu.core.time_util import MockClock
from janus_tpu.datastore.store import EphemeralDatastore
from janus_tpu.messages import (
    AggregationJobInitializeReq,
    Duration,
    Interval,
    Query,
    Role,
    Time,
)
from janus_tpu.messages.taskprov import (
    TASKPROV_HEADER,
    DpConfig,
    QueryConfig,
    TaskConfig,
    TaskprovQueryType,
    VdafConfig,
    VdafType,
)
from janus_tpu.task import QueryTypeConfig, Task, TaskBuilder
from janus_tpu.taskprov import PeerAggregatorBuilder, hkdf_sha256
from janus_tpu.vdaf.registry import VdafInstance


def sample_task_config(leader_url, helper_url, query_type=TaskprovQueryType.TIME_INTERVAL):
    qc = QueryConfig(
        time_precision=Duration(3600),
        max_batch_query_count=1,
        min_batch_size=1,
        query_type=query_type,
        max_batch_size=100 if query_type == TaskprovQueryType.FIXED_SIZE else None,
    )
    return TaskConfig(
        task_info=b"taskprov e2e test",
        aggregator_endpoints=(leader_url, helper_url),
        query_config=qc,
        task_expiration=Time(2_000_000_000),
        vdaf_config=VdafConfig(DpConfig(), VdafType.prio3_count()),
    )


class TestWire:
    @pytest.mark.parametrize(
        "vt",
        [
            VdafType.prio3_count(),
            VdafType.prio3_sum(32),
            VdafType.prio3_histogram([10, 20, 30]),
            VdafType.poplar1(16),
        ],
        ids=["count", "sum", "histogram", "poplar1"],
    )
    def test_round_trip(self, vt):
        cfg = sample_task_config("https://l.example/", "https://h.example/")
        cfg = dataclasses.replace(cfg, vdaf_config=VdafConfig(DpConfig(), vt))
        assert TaskConfig.from_bytes(cfg.to_bytes()) == cfg

    def test_fixed_size_round_trip(self):
        cfg = sample_task_config(
            "https://l.example/", "https://h.example/", TaskprovQueryType.FIXED_SIZE
        )
        got = TaskConfig.from_bytes(cfg.to_bytes())
        assert got.query_config.max_batch_size == 100

    def test_task_id_is_sha256_of_config(self):
        import hashlib

        cfg = sample_task_config("https://l.example/", "https://h.example/")
        assert cfg.computed_task_id().data == hashlib.sha256(cfg.to_bytes()).digest()

    def test_vdaf_instance_mapping(self):
        assert VdafType.prio3_count().to_vdaf_instance() == VdafInstance.count()
        assert VdafType.prio3_sum(8).to_vdaf_instance() == VdafInstance.sum(8)
        # bucket boundaries -> +1 buckets
        assert VdafType.prio3_histogram([1, 2, 3]).to_vdaf_instance() == VdafInstance.histogram(4)
        # poplar1 maps to a declared instance; using it in the DAP flow
        # raises at circuit dispatch (the reference's practical gate)
        assert VdafType.poplar1(8).to_vdaf_instance() == VdafInstance.poplar1(8)
        from janus_tpu.vdaf.registry import circuit_for

        with pytest.raises(ValueError):
            circuit_for(VdafInstance.poplar1(8))


def test_hkdf_rfc5869_vector1():
    ikm = bytes.fromhex("0b" * 22)
    salt = bytes.fromhex("000102030405060708090a0b0c")
    info = bytes.fromhex("f0f1f2f3f4f5f6f7f8f9")
    okm = hkdf_sha256(salt, ikm, info, 42)
    assert okm == bytes.fromhex(
        "3cb25f25faacd57a90434f64d0362f2a"
        "2d2d0a90cf1a5a4c5db02d56ecc4c5bf"
        "34007208d5b887185865"
    )


def test_peer_aggregator_datastore_round_trip():
    eph = EphemeralDatastore()
    try:
        peer = PeerAggregatorBuilder().with_(endpoint="https://peer.example/").build()
        eph.datastore.run_tx(lambda tx: tx.put_taskprov_peer_aggregator(peer))
        got = eph.datastore.run_tx(
            lambda tx: tx.get_taskprov_peer_aggregator("https://peer.example/", Role.LEADER)
        )
        assert got == peer
        all_ = eph.datastore.run_tx(lambda tx: tx.get_taskprov_peer_aggregators())
        assert all_ == [peer]
        eph.datastore.run_tx(
            lambda tx: tx.delete_taskprov_peer_aggregator("https://peer.example/", Role.LEADER)
        )
        assert eph.datastore.run_tx(lambda tx: tx.get_taskprov_peer_aggregators()) == []
    finally:
        eph.cleanup()


def test_derived_verify_key_is_deterministic_and_task_bound():
    peer = PeerAggregatorBuilder().build()
    from janus_tpu.messages import TaskId

    t1, t2 = TaskId(b"\x01" * 32), TaskId(b"\x02" * 32)
    assert peer.derive_vdaf_verify_key(t1) == peer.derive_vdaf_verify_key(t1)
    assert peer.derive_vdaf_verify_key(t1) != peer.derive_vdaf_verify_key(t2)
    assert len(peer.derive_vdaf_verify_key(t1)) == 16


class TaskprovHeaderHttp(HttpClient):
    """Leader-side HTTP client that attaches the dap-taskprov header on
    helper-bound aggregation requests (what a taskprov-aware leader
    driver sends)."""

    def __init__(self, task_config: TaskConfig):
        super().__init__()
        self.header = base64.urlsafe_b64encode(task_config.to_bytes()).decode().rstrip("=")

    def _with_header(self, url, headers):
        if "aggregation_jobs" in url or "aggregate_shares" in url:
            headers = dict(headers or {})
            headers[TASKPROV_HEADER] = self.header
        return headers

    def put(self, url, body, headers=None, timeout=None):
        return super().put(url, body, self._with_header(url, headers), timeout=timeout)

    def post(self, url, body, headers=None, timeout=None):
        return super().post(url, body, self._with_header(url, headers), timeout=timeout)


def test_helper_side_taskprov_end_to_end():
    """Helper starts with no task; the first aggregate-init carrying the
    dap-taskprov header provisions it (global HPKE keys, derived verify
    key, peer auth), and a full upload->aggregate->collect round trip
    completes."""
    clock = MockClock(Time(1_600_000_000))
    leader_eph = EphemeralDatastore(clock=clock)
    helper_eph = EphemeralDatastore(clock=clock)
    try:
        collector_kp = generate_hpke_config_and_private_key(config_id=200)
        agg_token = AuthenticationToken.random_bearer()
        col_token = AuthenticationToken.random_bearer()
        helper_global_kp = generate_hpke_config_and_private_key(config_id=7)
        helper_eph.datastore.run_tx(
            lambda tx: tx.put_global_hpke_keypair(helper_global_kp, state="active")
        )

        leader_srv = DapServer(DapHttpApp(Aggregator(leader_eph.datastore, clock, Config()))).start()

        # register the leader as a taskprov peer BEFORE the helper starts
        peer = (
            PeerAggregatorBuilder()
            .with_(
                endpoint=leader_srv.url,
                role=Role.LEADER,
                collector_hpke_config=collector_kp.config,
                aggregator_auth_tokens=(agg_token,),
                collector_auth_tokens=(col_token,),
            )
            .build()
        )
        helper_eph.datastore.run_tx(lambda tx: tx.put_taskprov_peer_aggregator(peer))
        helper_agg = Aggregator(helper_eph.datastore, clock, Config(taskprov_enabled=True))
        helper_srv = DapServer(DapHttpApp(helper_agg)).start()

        task_config = sample_task_config(leader_srv.url, helper_srv.url)
        task_id = task_config.computed_task_id()
        vdaf = VdafInstance.count()

        # leader provisions its side out-of-band with the derived key
        leader_task = (
            TaskBuilder(QueryTypeConfig.time_interval(), vdaf, Role.LEADER)
            .with_(
                task_id=task_id,
                leader_aggregator_endpoint=leader_srv.url,
                helper_aggregator_endpoint=helper_srv.url,
                vdaf_verify_key=peer.derive_vdaf_verify_key(task_id),
                collector_hpke_config=collector_kp.config,
                aggregator_auth_token=agg_token,
                collector_auth_token=col_token,
                task_expiration=task_config.task_expiration,
                min_batch_size=1,
            )
            .build()
        )
        leader_eph.datastore.run_tx(lambda tx: tx.put_task(leader_task))

        http = HttpClient()
        params = ClientParameters(task_id, leader_srv.url, helper_srv.url, Duration(3600))
        # client fetches the helper's GLOBAL config (no task provisioned there)
        client = Client.with_fetched_configs(params, vdaf, http, clock=clock)
        measurements = [1, 0, 1, 1]
        for m in measurements:
            client.upload(m)

        AggregationJobCreator(
            leader_eph.datastore, AggregationJobCreatorConfig(min_aggregation_job_size=1)
        ).run_once()

        taskprov_http = TaskprovHeaderHttp(task_config)
        driver = AggregationJobDriver(leader_eph.datastore, taskprov_http)
        assert JobDriver(JobDriverConfig(), driver.acquirer(), driver.stepper).run_once() == 1

        # helper opted in: task exists now with the derived verify key
        helper_task = helper_eph.datastore.run_tx(lambda tx: tx.get_task(task_id))
        assert helper_task is not None
        assert helper_task.role == Role.HELPER
        assert helper_task.vdaf_verify_key == leader_task.vdaf_verify_key
        assert helper_task.vdaf == vdaf
        assert helper_task.hpke_keys == ()

        rows = helper_eph.datastore.run_tx(
            lambda tx: tx.get_batch_aggregations_intersecting_interval(
                task_id, Interval(Time(0), Duration(1 << 40))
            )
        )
        assert sum(r.report_count for r in rows) == len(measurements)

        # collect through both aggregators
        start = clock.now().to_batch_interval_start(Duration(3600))
        query = Query.time_interval(Interval(Time(start.seconds - 3600), Duration(2 * 3600)))
        collector = Collector(
            CollectorParameters(task_id, leader_srv.url, col_token, collector_kp), vdaf, http
        )
        job_id = collector.start_collection(query)
        cdriver = CollectionJobDriver(leader_eph.datastore, taskprov_http)
        assert JobDriver(JobDriverConfig(), cdriver.acquirer(), cdriver.stepper).run_once() == 1
        result = collector.poll_once(job_id, query)
        assert result.report_count == len(measurements)
        assert result.aggregate_result == sum(measurements)

        leader_srv.stop()
        helper_srv.stop()
    finally:
        leader_eph.cleanup()
        helper_eph.cleanup()


def test_taskprov_rejections():
    """Unknown peer -> invalidTask; bad auth -> unauthorizedRequest;
    mismatched task id -> invalidMessage."""
    clock = MockClock(Time(1_600_000_000))
    helper_eph = EphemeralDatastore(clock=clock)
    try:
        peer = PeerAggregatorBuilder().with_(endpoint="https://leader.example/", role=Role.LEADER).build()
        helper_eph.datastore.run_tx(lambda tx: tx.put_taskprov_peer_aggregator(peer))
        helper_agg = Aggregator(helper_eph.datastore, clock, Config(taskprov_enabled=True))
        app = DapHttpApp(helper_agg)

        def init_req(task_config, headers):
            tid = task_config.computed_task_id()
            b64 = base64.urlsafe_b64encode
            url_tid = b64(tid.data).decode().rstrip("=")
            hdrs = {
                "Content-Type": AggregationJobInitializeReq.MEDIA_TYPE,
                TASKPROV_HEADER: b64(task_config.to_bytes()).decode().rstrip("="),
                **headers,
            }
            return app.handle(
                "PUT",
                f"/tasks/{url_tid}/aggregation_jobs/{b64(bytes(16)).decode().rstrip('=')}",
                {},
                hdrs,
                b"",
            )

        good_auth = peer.primary_aggregator_auth_token().request_headers()

        # unknown peer endpoint -> invalidTask (opt-out)
        cfg_bad_peer = sample_task_config("https://other.example/", "https://helper.example/")
        status, _, body, _h = init_req(cfg_bad_peer, good_auth)
        assert status == 400 and b"invalidTask" in body

        # bad auth -> unauthorizedRequest
        cfg = sample_task_config("https://leader.example/", "https://helper.example/")
        status, _, body, _h = init_req(cfg, {"Authorization": "Bearer nope"})
        assert status == 400 and b"unauthorizedRequest" in body

        # expired task -> invalidTask
        cfg_expired = dataclasses.replace(cfg, task_expiration=Time(1))
        status, _, body, _h = init_req(cfg_expired, good_auth)
        assert status == 400 and b"invalidTask" in body

        # task id not matching the config digest -> invalidMessage
        b64 = base64.urlsafe_b64encode
        hdrs = {
            "Content-Type": AggregationJobInitializeReq.MEDIA_TYPE,
            TASKPROV_HEADER: b64(cfg.to_bytes()).decode().rstrip("="),
            **good_auth,
        }
        status, _, body, _h = app.handle(
            "PUT",
            f"/tasks/{b64(bytes(32)).decode().rstrip('=')}/aggregation_jobs/{b64(bytes(16)).decode().rstrip('=')}",
            {},
            hdrs,
            b"",
        )
        assert status == 400 and b"invalidMessage" in body
    finally:
        helper_eph.cleanup()
