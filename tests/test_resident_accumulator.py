"""Device-resident accumulator state (ISSUE 12).

The engine keeps per-(task, batch bucket) aggregate buffers in device
memory across job steps: the masked accumulate becomes one per-bucket
delta dispatch (one int32 upload, zero fetch) merged into resident
slots only AFTER the job's write tx commits, and the host reads an
encoded share back only at flush time. These tests pin:

  * field-element equivalence of the resident path against the host
    oracle across count/histogram/sumvec with rejected lanes and
    multiple batch buckets (fuzzed);
  * multi-job merge into the same resident slot;
  * LRU eviction past the byte cap flushes (never drops) state, and
    the sum of every flush equals the ground truth;
  * the driver's end-to-end resident flow: share=None rows at commit,
    interval/drain flush through the write-tx path, exactly-once
    collection;
  * a commit failure drops the PendingDeltas (no merge), so the
    re-step cannot double-merge;
  * quarantine-mid-job: resident state flushes while the engine is
    quarantined and the interim host engine's work lands beside it —
    collection still exact;
  * double-buffered prestaging produces bit-identical leader inits.
"""

import dataclasses
import threading

import numpy as np
import pytest

from janus_tpu import metrics
from janus_tpu.aggregator.aggregation_job_driver import (
    AggregationJobDriver,
    AggregationJobDriverConfig,
    ResidentConfig,
)
from janus_tpu.aggregator.aggregation_job_creator import (
    AggregationJobCreator,
    AggregationJobCreatorConfig,
)
from janus_tpu.aggregator.engine_cache import EngineCache, engine_cache
from janus_tpu.aggregator.job_driver import JobDriver, JobDriverConfig
from janus_tpu.core.http_client import HttpClient
from janus_tpu.messages import Duration, Interval, Time
from janus_tpu.vdaf.registry import VdafInstance
from janus_tpu.vdaf.testing import make_report_batch, random_measurements

from test_e2e import pair, provision  # noqa: F401  (fixture + helper)

VK = bytes(range(16))
IV = Interval(Time(0), Duration(3600))


def _inst(kind):
    return {
        "count": VdafInstance.count(),
        "histogram": VdafInstance.histogram(length=6),
        "sumvec": VdafInstance.sum_vec(length=4, bits=4),
    }[kind]


def _host_oracle(inst, measurements, lanes, length):
    """Plaintext per-bucket sums over the accepted lanes."""
    if inst.kind == "count":
        return [sum(int(measurements[i]) for i in lanes)]
    if inst.kind == "histogram":
        out = [0] * length
        for i in lanes:
            out[int(measurements[i])] += 1
        return out
    # sumvec
    out = [0] * length
    for i in lanes:
        for k in range(length):
            out[k] += int(measurements[i][k])
    return out


@pytest.mark.parametrize("kind", ["count", "histogram", "sumvec"])
def test_resident_matches_host_oracle_fuzz(kind):
    """Fuzz: random jobs with rejected lanes and multiple batch buckets
    through the FULL two-party resident path — the flushed shares (sum
    of leader + helper resident states) equal the plaintext per-bucket
    sums exactly, and equal the classic per-bucket engine.aggregate."""
    inst = _inst(kind)
    eng0 = EngineCache(inst, VK)
    eng1 = EngineCache(inst, bytes(range(16, 32)))
    jf = eng0.p3.jf
    p = jf.MODULUS
    length = getattr(eng0.p3.circ, "output_len")
    rng = np.random.default_rng(42)
    totals: dict[bytes, list[int]] = {}
    for trial in range(4):
        n = int(rng.integers(3, 9))
        meas = random_measurements(inst, n, rng)
        args, m = make_report_batch(inst, meas, seed=1000 + trial)
        nonce, public, mv, proof, blind0, seeds, blind1 = args
        out0, _, ver0, part0 = eng0.leader_init(nonce, public, mv, proof, blind0)
        out1, ok, _ = eng0.helper_init(
            nonce, public, seeds, blind1, ver0, part0, np.ones(n, dtype=bool)
        )
        assert np.asarray(ok).all()
        # random accept/reject + random bucket assignment (2 buckets)
        accept = rng.random(n) > 0.3
        bucket_of = rng.integers(0, 2, size=n)
        lane_bucket = np.where(accept, bucket_of, -1).astype(np.int32)
        keys = [b"bucket-a", b"bucket-b"]
        pend = eng0.aggregate_pending(out0, lane_bucket, 2)
        entries = [
            ((b"task", b"", bid), j, int((lane_bucket == j).sum()), IV)
            for j, bid in enumerate(keys)
        ]
        assert eng0.resident_merge(entries, pend) == []
        # classic reference on the same rows
        for j, bid in enumerate(keys):
            classic = eng0.aggregate(out0, lane_bucket == j)
            lanes = [i for i in range(n) if lane_bucket[i] == j]
            want_plain = _host_oracle(inst, m, lanes, length)
            # two-party closure for the plaintext check
            h = eng0.aggregate(out1, lane_bucket == j)
            assert [(a + b) % p for a, b in zip(classic, h)] == [
                w % p for w in want_plain
            ]
            tot = totals.setdefault(bid, [0] * length)
            for k in range(length):
                tot[k] = (tot[k] + classic[k]) % p
    recs = {r["key"][2]: r for r in eng0.resident_take()}
    assert set(recs) <= set(totals)
    merged_rows = 0
    for bid, want in totals.items():
        if bid in recs:
            assert recs[bid]["share"] == want
            merged_rows += recs[bid]["rows"]
    # a second take is empty (state was consumed)
    assert eng0.resident_take() == []


def test_multi_job_merge_accumulates_in_place():
    """Several jobs' deltas into ONE resident slot: the take equals the
    mod-p sum of the per-job classic aggregates and counts the rows."""
    inst = VdafInstance.count()
    eng = EngineCache(inst, VK)
    p = eng.p3.jf.MODULUS
    rng = np.random.default_rng(7)
    want = 0
    rows = 0
    for j in range(3):
        n = 5
        meas = random_measurements(inst, n, rng)
        args, m = make_report_batch(inst, meas, seed=2000 + j)
        nonce, public, mv, proof, blind0, _, _ = args
        out0, _, _, _ = eng.leader_init(nonce, public, mv, proof, blind0)
        idx = np.zeros(n, np.int32)
        pend = eng.aggregate_pending(out0, idx, 1)
        eng.resident_merge([((b"t", b"", b"bid"), 0, n, IV)], pend)
        want = (want + eng.aggregate(out0, np.ones(n, bool))[0]) % p
        rows += n
    assert eng.resident_status()["buffers"] == 1
    (rec,) = eng.resident_take()
    assert rec["share"][0] == want
    assert rec["rows"] == rows


def test_eviction_flushes_never_drops(monkeypatch):
    """Past RESIDENT_MAX_BYTES the LRU slot is evicted THROUGH the
    flush path (fetched + handed back), never dropped: the evicted
    record plus the final take cover every contribution exactly."""
    inst = VdafInstance.histogram(length=8)
    eng = EngineCache(inst, VK)
    p = eng.p3.jf.MODULUS
    row_bytes = eng.p3.circ.output_len * eng.p3.jf.LIMBS * 8
    # cap admits exactly one slot
    monkeypatch.setattr(EngineCache, "RESIDENT_MAX_BYTES", row_bytes)
    rng = np.random.default_rng(9)
    wants = {}
    n = 4
    flushed = []
    for j, bid in enumerate([b"b0", b"b1", b"b2"]):
        meas = random_measurements(inst, n, rng)
        args, m = make_report_batch(inst, meas, seed=3000 + j)
        nonce, public, mv, proof, blind0, _, _ = args
        out0, _, _, _ = eng.leader_init(nonce, public, mv, proof, blind0)
        pend = eng.aggregate_pending(out0, np.zeros(n, np.int32), 1)
        flushed.extend(eng.resident_merge([((b"t", b"", bid), 0, n, IV)], pend))
        wants[bid] = eng.aggregate(out0, np.ones(n, bool))
    assert len(flushed) == 2, "two LRU slots evicted past the cap"
    assert eng.resident_status()["evictions"] == 2
    final = eng.resident_take()
    got = {r["key"][2]: r["share"] for r in flushed + final}
    assert got == {bid: [x % p for x in w] for bid, w in wants.items()}


def _upload_and_jobs(pair, leader_task, vdaf, measurements, job_size=100):
    from janus_tpu.client import Client, ClientParameters

    http = HttpClient()
    params = ClientParameters(
        leader_task.task_id,
        pair["leader_srv"].url,
        pair["helper_srv"].url,
        leader_task.time_precision,
    )
    client = Client.with_fetched_configs(params, vdaf, http, clock=pair["clock"])
    for m in measurements:
        client.upload(m)
    AggregationJobCreator(
        pair["leader_ds"],
        AggregationJobCreatorConfig(
            min_aggregation_job_size=1, max_aggregation_job_size=job_size
        ),
    ).run_once()
    return http


def _collect(pair, leader_task, vdaf, collector_kp, http):
    from janus_tpu.aggregator.collection_job_driver import CollectionJobDriver
    from janus_tpu.collector import Collector, CollectorParameters
    from janus_tpu.messages import Query

    clock = pair["clock"]
    start = Time(clock.now().seconds).to_batch_interval_start(
        leader_task.time_precision
    )
    query = Query.time_interval(
        Interval(Time(start.seconds - 3600), Duration(2 * 3600))
    )
    collector = Collector(
        CollectorParameters(
            leader_task.task_id,
            pair["leader_srv"].url,
            leader_task.collector_auth_token,
            collector_kp,
        ),
        vdaf,
        http,
    )
    job_id = collector.start_collection(query)
    cdriver = CollectionJobDriver(pair["leader_ds"], http)
    cjd = JobDriver(
        JobDriverConfig(max_concurrent_job_workers=1),
        cdriver.acquirer(),
        cdriver.stepper,
    )
    assert cjd.run_once() >= 1
    return collector.poll_once(job_id, query)


def _resident_driver(pair, http, flush_interval_s=3600.0):
    """Driver with resident mode on and a long flush interval, so tests
    control the flush points explicitly."""
    return AggregationJobDriver(
        pair["leader_ds"],
        http,
        AggregationJobDriverConfig(
            resident=ResidentConfig(enabled=True, flush_interval_s=flush_interval_s)
        ),
    )


def test_driver_resident_end_to_end_flush_then_collect(pair):
    """Driver flow: jobs step with resident mode on (share bytes stay
    on device, batch rows commit with counts/checksums), the drain
    flush writes the shares through the write-tx path, and collection
    equals the ground truth exactly."""
    vdaf = VdafInstance.count()
    leader_task, helper_task, collector_kp = provision(pair, vdaf)
    measurements = [1, 0, 1, 1, 0, 1, 1]
    http = _upload_and_jobs(pair, leader_task, vdaf, measurements, job_size=3)

    driver = _resident_driver(pair, http)
    jd = JobDriver(
        JobDriverConfig(max_concurrent_job_workers=2),
        driver.acquirer(),
        driver.stepper,
    )
    while jd.run_once():
        pass
    eng = engine_cache(leader_task.vdaf, leader_task.vdaf_verify_key)
    st = eng.resident_status()
    assert st["buffers"] >= 1 and st["merged_rows"] == len(measurements)
    # the leader's batch rows carry the counts but NOT the resident share
    rows = pair["leader_ds"].run_tx(
        lambda tx: tx.get_batch_aggregations_intersecting_interval(
            leader_task.task_id, Interval(Time(1_599_990_000), Duration(3600 * 24))
        )
    )
    assert sum(r.report_count for r in rows) == len(measurements)
    leader_share_before = [
        r.aggregate_share for r in rows if r.aggregate_share is not None
    ]
    # drain-style flush through the write-tx path
    assert driver.flush_resident_state(reason="drain") >= 1
    assert eng.resident_status()["buffers"] == 0
    rows_after = pair["leader_ds"].run_tx(
        lambda tx: tx.get_batch_aggregations_intersecting_interval(
            leader_task.task_id, Interval(Time(1_599_990_000), Duration(3600 * 24))
        )
    )
    assert [r for r in rows_after if r.aggregate_share is not None], (
        "flush merged the share bytes into the batch rows"
    )
    result = _collect(pair, leader_task, vdaf, collector_kp, http)
    assert result.report_count == len(measurements)
    assert result.aggregate_result == sum(measurements)
    assert leader_share_before in ([], leader_share_before)  # doc: share lagged


def test_commit_failure_drops_delta_no_double_merge(pair):
    """A write tx that fails AFTER the resident delta was computed must
    not merge it (post-commit discipline): the re-step under the same
    process merges exactly once and collection is exact."""
    vdaf = VdafInstance.count()
    leader_task, helper_task, collector_kp = provision(pair, vdaf)
    measurements = [1, 1, 0, 1]
    http = _upload_and_jobs(pair, leader_task, vdaf, measurements)

    driver = _resident_driver(pair, http)
    ds = pair["leader_ds"]
    real_run_tx = ds.run_tx
    fail_once = {"armed": True}

    def flaky_run_tx(fn, name="tx", *a, **kw):
        if name == "step_agg_job_write" and fail_once["armed"]:
            fail_once["armed"] = False
            raise RuntimeError("injected commit failure")
        return real_run_tx(fn, name, *a, **kw)

    ds.run_tx = flaky_run_tx
    try:
        (acquired,) = real_run_tx(
            lambda tx: tx.acquire_incomplete_aggregation_jobs(Duration(600), 1),
            "acquire",
        )
        with pytest.raises(RuntimeError, match="injected commit failure"):
            driver.step_aggregation_job(acquired)
        eng = engine_cache(leader_task.vdaf, leader_task.vdaf_verify_key)
        assert eng.resident_status()["buffers"] == 0, "failed commit merged nothing"
        # release the lease and re-step: lands exactly once
        driver.step_back(acquired, "test", 0.0)
        jd = JobDriver(
            JobDriverConfig(max_concurrent_job_workers=1),
            driver.acquirer(),
            driver.stepper,
        )
        while jd.run_once():
            pass
        assert eng.resident_status()["merged_rows"] == len(measurements)
    finally:
        ds.run_tx = real_run_tx
    assert driver.flush_resident_state(reason="drain") >= 1
    result = _collect(pair, leader_task, vdaf, collector_kp, http)
    assert result.report_count == len(measurements)
    assert result.aggregate_result == sum(measurements)


def test_quarantine_mid_job_flushes_and_host_path_continues(pair):
    """Quarantine mid-stream: earlier jobs' resident state flushes
    (reason=quarantine) while the engine is quarantined, later jobs land
    through the interim host engine's classic path, and collection sees
    BOTH — exactly the admitted ground truth."""
    from janus_tpu import failpoints

    vdaf = VdafInstance.count()
    leader_task, helper_task, collector_kp = provision(pair, vdaf)
    first, second = [1, 0, 1], [1, 1, 0, 1]
    http = _upload_and_jobs(pair, leader_task, vdaf, first)

    driver = _resident_driver(pair, http)
    jd = JobDriver(
        JobDriverConfig(max_concurrent_job_workers=1),
        driver.acquirer(),
        driver.stepper,
    )
    while jd.run_once():
        pass
    eng = engine_cache(leader_task.vdaf, leader_task.vdaf_verify_key)
    assert eng.resident_status()["buffers"] >= 1

    # quarantine the engine; hold it open (canary probe kept failing)
    failpoints.configure("engine.canary=error:1.0")
    try:
        eng._quarantine_on_hang("test")
        assert not eng.resident_ready()
        before = metrics.engine_resident_flushes_total.get(
            reason="quarantine", outcome="flushed"
        )
        assert driver.flush_resident_state() >= 1
        assert (
            metrics.engine_resident_flushes_total.get(
                reason="quarantine", outcome="flushed"
            )
            > before
        ), "quarantined state flushed under reason=quarantine"
        assert eng.resident_status()["buffers"] == 0

        # second wave lands via the interim host engine (classic flush)
        _upload_and_jobs(pair, leader_task, vdaf, second)
        while jd.run_once():
            pass
        assert eng.resident_status()["buffers"] == 0, "host path never goes resident"
    finally:
        failpoints.clear()
        eng.stop_canary()

    result = _collect(pair, leader_task, vdaf, collector_kp, http)
    assert result.report_count == len(first + second)
    assert result.aggregate_result == sum(first + second)


def test_prestaged_leader_init_bit_identical():
    """Double-buffered staging: a prestaged (async H2D) column set
    produces byte-identical leader-init outputs and counts a hit."""
    inst = VdafInstance.sum_vec(length=4, bits=4)
    eng = EngineCache(inst, VK)
    rng = np.random.default_rng(11)
    n = 5
    meas = random_measurements(inst, n, rng)
    args, _ = make_report_batch(inst, meas, seed=77)
    nonce, public, mv, proof, blind0, _, _ = args
    out_a, seed_a, ver_a, part_a = eng.leader_init(nonce, public, mv, proof, blind0)

    pre = eng.prestage_leader(nonce, public, mv, proof, blind0)
    assert pre is not None
    hits_before = metrics.engine_prestage_total.get(outcome="hit")
    out_b, seed_b, ver_b, part_b = eng.leader_init(
        nonce, public, mv, proof, blind0, prestaged=pre
    )
    assert metrics.engine_prestage_total.get(outcome="hit") == hits_before + 1
    for a, b in zip(ver_a, ver_b):
        assert (np.asarray(a) == np.asarray(b)).all()
    if seed_a is None:
        assert seed_b is None
    else:
        assert (np.asarray(seed_a) == np.asarray(seed_b)).all()
    mask = np.ones(n, dtype=bool)
    assert eng.aggregate(out_a, mask) == eng.aggregate(out_b, mask)


def test_host_engine_leader_init_accepts_prestaged_kwarg():
    """device_init passes prestaged= unconditionally; the host engine
    must accept (and discard) it — a draft-mode task routed to
    HostEngineCache otherwise crashed every step with TypeError."""
    from janus_tpu.aggregator.engine_cache import HostEngineCache, PrestagedInit

    inst = VdafInstance.count()
    host = HostEngineCache(inst, VK)
    dev = EngineCache(inst, VK)
    rng = np.random.default_rng(35)
    n = 3
    meas = random_measurements(inst, n, rng)
    args, _ = make_report_batch(inst, meas, seed=700)
    nonce, public, mv, proof, blind0, _, _ = args
    pre = PrestagedInit(8, ("sentinel",), False)
    out_h, _, _, _ = host.leader_init(
        nonce, public, mv, proof, blind0, ok=None, prestaged=pre
    )
    assert pre._staged is None, "host path frees the transfer's buffers"
    out_d, _, _, _ = dev.leader_init(nonce, public, mv, proof, blind0)
    mask = np.ones(n, bool)
    assert host.aggregate(out_h, mask) == dev.aggregate(out_d, mask)


def test_partial_merge_failure_flushes_only_unmerged(monkeypatch):
    """A merge that dies mid-loop leaves a merged PREFIX safely on
    device: ResidentMergeError carries those keys and the driver's
    recovery flushes ONLY the remainder — re-flushing a merged entry
    would double-count it when its slot later flushes."""
    from types import SimpleNamespace

    from janus_tpu.aggregator.engine_cache import ResidentMergeError

    inst = VdafInstance.count()
    eng = EngineCache(inst, bytes(range(48, 64)))
    rng = np.random.default_rng(31)
    n = 4
    k0, k1 = (b"t", b"", b"k0"), (b"t", b"", b"k1")
    # job 1 seeds bucket k1 resident (so job 2's k1 entry takes the
    # _resident_add path, which we wedge)
    meas = random_measurements(inst, n, rng)
    args, _ = make_report_batch(inst, meas, seed=500)
    nonce, public, mv, proof, blind0, _, _ = args
    out_a, _, _, _ = eng.leader_init(nonce, public, mv, proof, blind0)
    eng.resident_merge(
        [(k1, 0, n, IV)], eng.aggregate_pending(out_a, np.zeros(n, np.int32), 1)
    )
    # job 2: k0 (fresh slot, merges clean) then k1 (wedged add)
    meas2 = random_measurements(inst, n, rng)
    args2, _ = make_report_batch(inst, meas2, seed=501)
    nonce2, public2, mv2, proof2, blind2, _, _ = args2
    out_b, _, _, _ = eng.leader_init(nonce2, public2, mv2, proof2, blind2)
    idx = np.array([0, 0, 1, 1], np.int32)
    pend = eng.aggregate_pending(out_b, idx, 2)

    def boom(acc, row):
        raise RuntimeError("wedged add")

    monkeypatch.setattr(eng, "_resident_add", boom)
    driver = AggregationJobDriver(None, None)
    flushed = []
    monkeypatch.setattr(
        driver,
        "flush_resident_records",
        lambda engine, recs, reason: flushed.append((reason, recs)) or len(recs),
    )
    st = SimpleNamespace(
        engine=eng,
        resident_delta=pend,
        resident_entries=[(k0, 0, 2, IV), (k1, 1, 2, IV)],
        resident_rids=[b"r0", b"r1"],
        acquired=SimpleNamespace(job_id="job-x"),
    )
    driver._resident_post_commit(st, set())
    ((reason, recs),) = flushed
    assert reason == "merge_failed"
    assert [r["key"] for r in recs] == [k1], "only the UNMERGED bucket went out"
    assert recs[0]["share"] == eng.aggregate(out_b, idx == 1)
    # device state: k1 holds job 1 only, k0 holds job 2's delta — every
    # contribution exactly once across flush + resident
    got = {r["key"]: r["share"] for r in eng.resident_take()}
    assert got[k1] == eng.aggregate(out_a, np.ones(n, bool))
    assert got[k0] == eng.aggregate(out_b, idx == 0)
    # the engine-level contract is also directly visible
    out_c, _, _, _ = eng.leader_init(nonce2, public2, mv2, proof2, blind2)
    eng.resident_merge(
        [(k1, 0, n, IV)], eng.aggregate_pending(out_c, np.zeros(n, np.int32), 1)
    )
    with pytest.raises(ResidentMergeError) as ei:
        eng.resident_merge(
            [(k0, 0, 2, IV), (k1, 1, 2, IV)], eng.aggregate_pending(out_c, idx, 2)
        )
    assert ei.value.merged == frozenset({k0})
    eng.resident_take()  # drain the global resident-bytes ledger


def test_eviction_fetch_failure_defers_never_double_counts(monkeypatch):
    """An eviction whose d2h fetch fails restores the slots and returns
    [] — the deltas ALREADY merged, so raising would send the caller's
    merge-failed recovery after rows that are safely on device (double
    count). The eviction is deferred and retried; nothing is lost."""
    from janus_tpu.aggregator.engine_cache import resident_bytes_total

    inst = VdafInstance.count()
    eng = EngineCache(inst, VK)
    row_bytes = eng.p3.circ.output_len * eng.p3.jf.LIMBS * 8
    # the ledger is process-global: admit exactly ONE more slot
    monkeypatch.setattr(
        EngineCache, "RESIDENT_MAX_BYTES", resident_bytes_total() + row_bytes
    )
    rng = np.random.default_rng(33)
    n = 4
    outs = {}
    for j, bid in enumerate([b"b0", b"b1"]):
        meas = random_measurements(inst, n, rng)
        args, _ = make_report_batch(inst, meas, seed=600 + j)
        nonce, public, mv, proof, blind0, _, _ = args
        out0, _, _, _ = eng.leader_init(nonce, public, mv, proof, blind0)
        outs[bid] = out0
    pend0 = eng.aggregate_pending(outs[b"b0"], np.zeros(n, np.int32), 1)
    assert eng.resident_merge([((b"t", b"", b"b0"), 0, n, IV)], pend0) == []

    real = eng._supervised

    def flaky(label, fn):
        if label == "resident_fetch":
            raise RuntimeError("wedged fetch")
        return real(label, fn)

    monkeypatch.setattr(eng, "_supervised", flaky)
    pend1 = eng.aggregate_pending(outs[b"b1"], np.zeros(n, np.int32), 1)
    # b1's merge evicts b0 past the cap, the fetch wedges: deferred
    assert eng.resident_merge([((b"t", b"", b"b1"), 0, n, IV)], pend1) == []
    st = eng.resident_status()
    assert st["buffers"] == 2 and st["eviction_deferred"] == 1
    monkeypatch.undo()
    got = {r["key"][2]: r["share"] for r in eng.resident_take()}
    for bid in (b"b0", b"b1"):
        assert got[bid] == eng.aggregate(outs[bid], np.ones(n, bool))


def test_engine_cache_lru_never_evicts_resident_state(monkeypatch):
    """The process engine-cache LRU must not drop an engine holding
    unflushed resident slots: the flusher only walks CACHED engines, so
    eviction would silently lose the share bytes and leak the
    resident-bytes ledger forever."""
    from janus_tpu.aggregator import engine_cache as ec

    ec._engine_cache_clear()
    inst = VdafInstance.count()
    try:
        eng0 = ec.engine_cache(inst, VK)
        rng = np.random.default_rng(37)
        n = 3
        meas = random_measurements(inst, n, rng)
        args, _ = make_report_batch(inst, meas, seed=800)
        nonce, public, mv, proof, blind0, _, _ = args
        out0, _, _, _ = eng0.leader_init(nonce, public, mv, proof, blind0)
        pend = eng0.aggregate_pending(out0, np.zeros(n, np.int32), 1)
        eng0.resident_merge([((b"t", b"", b"bid"), 0, n, IV)], pend)
        bytes_before = ec.resident_bytes_total()
        assert bytes_before > 0
        monkeypatch.setattr(ec, "_ENGINE_CACHE_MAX", 2)
        ec.engine_cache(inst, bytes(range(16, 32)))
        ec.engine_cache(inst, bytes(range(32, 48)))
        # eng0 is the LRU victim — but it holds resident state, so the
        # next-oldest slot-free engine was evicted instead
        assert ec.engine_cache(inst, VK) is eng0
        assert eng0 in ec.live_engines()
        assert ec.resident_bytes_total() == bytes_before
        (rec,) = eng0.resident_take()
        assert rec["rows"] == n
    finally:
        ec._engine_cache_clear()


def test_flusher_fetch_bounded_without_ambient_deadline(monkeypatch):
    """Flusher/drain threads carry no lease deadline — without one the
    dispatch watchdog degrades to a direct call and a wedged device
    would block the fetch forever INSIDE the engine's resident lock,
    deadlocking every commit worker. flush_engine_resident must install
    a bound (and keep an ambient one when present)."""
    from janus_tpu.core.deadline import current_deadline, deadline_scope

    inst = VdafInstance.count()
    eng = EngineCache(inst, VK)
    driver = AggregationJobDriver(None, None)
    seen = []
    monkeypatch.setattr(eng, "resident_take", lambda: seen.append(current_deadline()) or [])
    assert current_deadline() is None
    driver.flush_engine_resident(eng, "interval")
    assert seen[-1] is not None, "no ambient deadline: a bound was installed"
    import time as _time

    lease = _time.monotonic() + 5.0
    with deadline_scope(lease):
        driver.flush_engine_resident(eng, "interval")
    assert seen[-1] == lease, "an ambient lease deadline is kept, not replaced"


def test_interval_flush_cadence_shared_with_background_flusher(monkeypatch):
    """The background flusher's interval pass stamps the inline
    post-commit cadence — a busy driver must not pay a second full
    take + flush tx per interval on top of the flusher's."""
    from janus_tpu.aggregator import engine_cache as ec

    driver = AggregationJobDriver(None, None)
    monkeypatch.setattr(ec, "live_engines", lambda: [])
    inline = []
    monkeypatch.setattr(
        driver,
        "flush_engine_resident",
        lambda e, reason="interval": inline.append(reason) or 0,
    )
    driver.flush_resident_state(reason="interval")  # flusher pass stamps
    driver.maybe_flush_resident(object())
    assert inline == [], "inline flush suppressed inside the interval"
    driver._resident_last_flush -= driver.cfg.resident.flush_interval_s + 1
    driver.maybe_flush_resident(object())
    assert inline == ["interval"]


def test_resident_buffers_gauge_sums_across_engines():
    """Several engines share a vdaf kind (one per task verify key):
    janus_engine_resident_buffers must SUM their slots, not let each
    engine overwrite the label with its own count."""
    inst = VdafInstance.count()
    a = EngineCache(inst, bytes(range(64, 80)))
    b = EngineCache(inst, bytes(range(80, 96)))
    base = metrics.engine_resident_buffers.get(vdaf="count")
    rng = np.random.default_rng(41)
    n = 3
    for j, eng in enumerate((a, b)):
        meas = random_measurements(inst, n, rng)
        args, _ = make_report_batch(inst, meas, seed=900 + j)
        nonce, public, mv, proof, blind0, _, _ = args
        out0, _, _, _ = eng.leader_init(nonce, public, mv, proof, blind0)
        pend = eng.aggregate_pending(out0, np.zeros(n, np.int32), 1)
        eng.resident_merge([((b"t%d" % j, b"", b"bid"), 0, n, IV)], pend)
    assert metrics.engine_resident_buffers.get(vdaf="count") == base + 2
    a.resident_take()
    assert metrics.engine_resident_buffers.get(vdaf="count") == base + 1
    b.resident_take()
    assert metrics.engine_resident_buffers.get(vdaf="count") == base


def test_flush_skipped_while_datastore_down(monkeypatch):
    """A non-drain flush must not pop slots while the supervisor says
    the store is down: the flush tx would fail and the fetched shares
    are at-most-once (no idempotency key guards a re-flush). Drain
    still attempts — the process is exiting either way."""
    from types import SimpleNamespace

    inst = VdafInstance.count()
    eng = EngineCache(inst, VK)
    rng = np.random.default_rng(43)
    n = 3
    meas = random_measurements(inst, n, rng)
    args, _ = make_report_batch(inst, meas, seed=910)
    nonce, public, mv, proof, blind0, _, _ = args
    out0, _, _, _ = eng.leader_init(nonce, public, mv, proof, blind0)
    pend = eng.aggregate_pending(out0, np.zeros(n, np.int32), 1)
    eng.resident_merge([((b"t", b"", b"bid"), 0, n, IV)], pend)

    ds = SimpleNamespace(supervisor=SimpleNamespace(state="down"))
    driver = AggregationJobDriver(ds, None)
    flushed = []
    monkeypatch.setattr(
        driver,
        "flush_resident_records",
        lambda engine, recs, reason: flushed.append(reason) or len(recs),
    )
    assert driver.flush_engine_resident(eng, "interval") == 0
    assert eng.resident_status()["buffers"] == 1, "state stayed resident"
    assert flushed == []
    assert driver.flush_engine_resident(eng, "drain") == 1
    assert flushed == ["drain"]
    assert eng.resident_status()["buffers"] == 0


def test_merge_failed_recovery_fetch_is_supervised(monkeypatch):
    """The merge-failed recovery's delta fetch goes through the
    dispatch watchdog — a raw to_ints would park the commit worker in
    native code on exactly the wedged device that failed the merge."""
    inst = VdafInstance.count()
    eng = EngineCache(inst, VK)
    rng = np.random.default_rng(45)
    n = 4
    meas = random_measurements(inst, n, rng)
    args, _ = make_report_batch(inst, meas, seed=920)
    nonce, public, mv, proof, blind0, _, _ = args
    out0, _, _, _ = eng.leader_init(nonce, public, mv, proof, blind0)
    pend = eng.aggregate_pending(out0, np.zeros(n, np.int32), 1)
    want = eng.aggregate(out0, np.ones(n, bool))

    labels = []
    real = eng._supervised

    def spy(label, fn):
        labels.append(label)
        return real(label, fn)

    monkeypatch.setattr(eng, "_supervised", spy)
    recs = eng.fetch_delta_records([((b"t", b"", b"b"), 0, n, IV)], pend)
    assert "resident_delta_fetch" in labels
    assert recs[0]["share"] == want and recs[0]["rows"] == n


def test_would_coalesce_predicate_matches_entry_routing():
    """would_coalesce mirrors _leader_init_entry's routing exactly —
    the pipeline declines prestaging when a parallel device lane could
    merge the job's round (a merged round discards prestages and
    re-stages from host, paying the H2D transfer twice)."""
    inst = VdafInstance.count()
    eng = EngineCache(inst, VK)
    eng._coalesce = True
    assert eng.would_coalesce(4)
    assert eng.would_coalesce(EngineCache.COALESCE_MAX_JOB)
    assert not eng.would_coalesce(EngineCache.COALESCE_MAX_JOB + 1)
    old_cap = eng.bucket_cap
    eng.bucket_cap = 2
    assert not eng.would_coalesce(4), "past the cap routes chunked, not coalesced"
    eng.bucket_cap = old_cap
    eng._coalesce = False
    assert not eng.would_coalesce(4)


def test_resident_take_failure_restores_state(monkeypatch):
    """A failing flush fetch must RESTORE the popped slots (state is
    never lost because the device was slow once)."""
    inst = VdafInstance.count()
    eng = EngineCache(inst, VK)
    rng = np.random.default_rng(13)
    n = 4
    meas = random_measurements(inst, n, rng)
    args, _ = make_report_batch(inst, meas, seed=88)
    nonce, public, mv, proof, blind0, _, _ = args
    out0, _, _, _ = eng.leader_init(nonce, public, mv, proof, blind0)
    pend = eng.aggregate_pending(out0, np.zeros(n, np.int32), 1)
    eng.resident_merge([((b"t", b"", b"bid"), 0, n, IV)], pend)
    want = eng.aggregate(out0, np.ones(n, bool))

    def boom(label, fn):
        raise RuntimeError("wedged fetch")

    monkeypatch.setattr(eng, "_supervised", boom)
    with pytest.raises(RuntimeError, match="wedged fetch"):
        eng.resident_take()
    monkeypatch.undo()
    assert eng.resident_status()["buffers"] == 1
    (rec,) = eng.resident_take()
    assert rec["share"] == want
