"""End-to-end tests of the host Prio3 reference implementation.

Mirrors the reference's transcript-style testing (golden transcripts via
run_vdaf, reference core/src/test_util/mod.rs:50-235): both parties'
states/messages are computed locally, so multi-party protocol logic is
tested without a cluster.
"""

import secrets

import pytest

from janus_tpu.vdaf.reference import (
    Count,
    FixedPointVec,
    Histogram,
    Prio3,
    Sum,
    SumVec,
    VdafError,
    fp_encode_floats,
)

NONCE = bytes(range(16))
VK = b"\x07" * 16


def run_prio3(vdaf: Prio3, measurements, tamper=None):
    """Full shard->prepare->aggregate->unshard transcript for a list of
    measurements; returns the unsharded aggregate result."""
    out_shares = [[], []]
    for m in measurements:
        nonce = secrets.token_bytes(16)
        public_share, shares = vdaf.shard(m, nonce)
        if tamper:
            tamper(public_share, shares)
        states, prep_shares = [], []
        for agg_id in (0, 1):
            st, ps = vdaf.prepare_init(VK, agg_id, nonce, public_share, shares[agg_id])
            states.append(st)
            prep_shares.append(ps)
        prep_msg = vdaf.prepare_shares_to_prep(prep_shares)
        for agg_id in (0, 1):
            out_shares[agg_id].append(vdaf.prepare_next(states[agg_id], prep_msg))
    agg_shares = [vdaf.aggregate(out_shares[0]), vdaf.aggregate(out_shares[1])]
    return vdaf.unshard(agg_shares, len(measurements))


def test_count_roundtrip():
    vdaf = Prio3(Count())
    assert run_prio3(vdaf, [1, 0, 1, 1, 0, 1]) == 4


def test_sum_roundtrip():
    vdaf = Prio3(Sum(bits=16))
    assert run_prio3(vdaf, [100, 200, 65535, 0]) == 65835


def test_sumvec_roundtrip():
    vdaf = Prio3(SumVec(length=5, bits=4))
    got = run_prio3(vdaf, [[1, 2, 3, 4, 5], [15, 0, 1, 0, 2]])
    assert got == [16, 2, 4, 4, 7]


def test_histogram_roundtrip():
    vdaf = Prio3(Histogram(length=10))
    got = run_prio3(vdaf, [3, 3, 7, 0, 9, 3])
    assert got == [1, 0, 0, 3, 0, 0, 0, 1, 0, 1]


def test_fixedpoint_roundtrip():
    vdaf = Prio3(FixedPointVec(length=3, bits=16))
    m1 = fp_encode_floats([0.25, -0.5, 0.125], 16)
    m2 = fp_encode_floats([-0.25, 0.25, 0.5], 16)
    got = run_prio3(vdaf, [m1, m2])
    assert got == pytest.approx([0.0, -0.25, 0.625], abs=1e-3)


def test_fixedpoint_negative_sum_and_64bit():
    # 64-bit entries: length capped at 3 by the Field128 overflow bound.
    vdaf = Prio3(FixedPointVec(length=2, bits=64))
    off = 1 << 63
    m1 = [-(off // 2), off // 4]  # [-0.5, 0.25]
    m2 = [-(off // 4), -(off // 2)]  # [-0.25, -0.5]
    got = run_prio3(vdaf, [m1, m2])
    assert got == pytest.approx([-0.75, -0.25], abs=1e-9)


def test_fixedpoint_norm_overflow_length_rejected():
    with pytest.raises(ValueError):
        FixedPointVec(length=4, bits=64)


def test_fixedpoint_norm_too_large_rejected():
    # A vector with L2 norm >= 1 cannot be encoded honestly...
    circ = FixedPointVec(length=2, bits=16)
    with pytest.raises(AssertionError):
        circ.encode([1 << 14, (1 << 15) - 1])
    circ.encode([1 << 14, 1 << 14])  # norm = 2*2^28 = 2^29 < 2^30: ok


def test_fixedpoint_false_norm_claim_rejected():
    # ...and a dishonest encoding that under-claims the norm must fail
    # the FLP's recomputed-norm equality check.
    circ = FixedPointVec(length=2, bits=16)
    vdaf = Prio3(circ)
    honest = circ.encode([1 << 14, 1 << 14])
    forged = honest[: circ.length * circ.bits] + [0] * circ.norm_bits
    orig_encode = circ.encode
    circ.encode = lambda m: forged
    try:
        with pytest.raises(VdafError):
            run_prio3(vdaf, [None])
    finally:
        circ.encode = orig_encode


def test_fixedpoint_entry_bit_forgery_rejected():
    circ = FixedPointVec(length=2, bits=16)
    vdaf = Prio3(circ)
    honest = circ.encode([100, -100])
    forged = list(honest)
    forged[0] = 2  # not a bit
    orig_encode = circ.encode
    circ.encode = lambda m: forged
    try:
        with pytest.raises(VdafError):
            run_prio3(vdaf, [None])
    finally:
        circ.encode = orig_encode


def test_invalid_count_rejected():
    # A count measurement that is neither 0 nor 1 must fail the FLP.
    vdaf = Prio3(Count())
    circ = vdaf.circuit

    orig_encode = circ.encode
    circ.encode = lambda m: [7]  # invalid: 7^2 - 7 != 0
    try:
        with pytest.raises(VdafError):
            run_prio3(vdaf, [1])
    finally:
        circ.encode = orig_encode


def test_invalid_sum_bit_rejected():
    vdaf = Prio3(Sum(bits=8))
    circ = vdaf.circuit
    orig_encode = circ.encode
    circ.encode = lambda m: [2] + [0] * 7  # entry not a bit
    try:
        with pytest.raises(VdafError):
            run_prio3(vdaf, [1])
    finally:
        circ.encode = orig_encode


def test_invalid_histogram_two_hot_rejected():
    vdaf = Prio3(Histogram(length=4))
    circ = vdaf.circuit
    orig_encode = circ.encode
    circ.encode = lambda m: [1, 1, 0, 0]  # two-hot: sum check must fail
    try:
        with pytest.raises(VdafError):
            run_prio3(vdaf, [0])
    finally:
        circ.encode = orig_encode


def test_tampered_share_rejected():
    vdaf = Prio3(Sum(bits=8))

    def tamper(public_share, shares):
        shares[0].measurement_share[0] = (shares[0].measurement_share[0] + 1) % vdaf.circuit.FIELD.MODULUS

    with pytest.raises(VdafError):
        run_prio3(vdaf, [5], tamper=tamper)


def test_tampered_joint_rand_hint_rejected():
    vdaf = Prio3(Sum(bits=8))

    # Corrupting a joint-rand hint must be caught by the seed check in
    # prepare_next (the hint path), even though the FLP itself may pass.
    nonce = secrets.token_bytes(16)
    public_share, shares = vdaf.shard(5, nonce)
    public_share[0] = bytes(16)
    states, prep_shares = [], []
    for agg_id in (0, 1):
        st, ps = vdaf.prepare_init(VK, agg_id, nonce, public_share, shares[agg_id])
        states.append(st)
        prep_shares.append(ps)
    try:
        prep_msg = vdaf.prepare_shares_to_prep(prep_shares)
    except VdafError:
        return  # acceptable: FLP fails because parties used different jr
    with pytest.raises(VdafError):
        # agg 1 used the corrupted hint for the leader part; its corrected
        # seed cannot match the true prep message.
        vdaf.prepare_next(states[1], prep_msg)


def test_sumvec_chunking_nondivisible():
    # length*bits = 21, chunk default sqrt(21)=4 -> padded final call
    vdaf = Prio3(SumVec(length=7, bits=3, chunk_length=4))
    got = run_prio3(vdaf, [[1, 2, 3, 4, 5, 6, 7]])
    assert got == [1, 2, 3, 4, 5, 6, 7]
