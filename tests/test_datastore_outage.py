"""Datastore connection supervision (datastore/store.py): the typed
error classifier, run_tx's jittered/capped/metered retry, the per-
thread connection registry behind close(), the connection-lost discard
path over pg_fake, the up/degraded/down/recovering supervisor, the
/healthz-vs-/readyz split, and degraded-mode admission shedding
(docs/ROBUSTNESS.md "Datastore outages").
"""

import json
import sqlite3
import threading
import time
import urllib.error
import urllib.request

import pytest

from janus_tpu import failpoints, metrics
from janus_tpu.datastore.pg_fake import (
    OperationalError as PgOperationalError,
    SerializationFailure,
)
from janus_tpu.datastore.store import (
    DatastoreSupervisor,
    EphemeralDatastore,
    TxConflict,
)


@pytest.fixture(autouse=True)
def _clean_failpoints():
    failpoints.clear()
    yield
    failpoints.clear()


@pytest.fixture
def eph():
    e = EphemeralDatastore()
    yield e
    e.cleanup()


@pytest.fixture
def pgfake():
    e = EphemeralDatastore(engine="pgfake")
    yield e
    e.cleanup()


# ---------------------------------------------------------------------------
# error classifier
# ---------------------------------------------------------------------------


def test_classify_error_sqlite(eph):
    ds = eph.datastore
    assert ds.classify_error(TxConflict("x")) == "serialization"
    assert ds.classify_error(sqlite3.OperationalError("database is locked")) == (
        "serialization"
    )
    assert ds.classify_error(
        sqlite3.OperationalError("unable to open database file")
    ) == "connection"
    assert ds.classify_error(sqlite3.OperationalError("disk I/O error")) == (
        "connection"
    )
    assert ds.classify_error(sqlite3.OperationalError("no such table: nope")) == (
        "fatal"
    )
    assert ds.classify_error(ValueError("x")) == "other"


def test_classify_error_pgfake(pgfake):
    ds = pgfake.datastore
    assert ds.classify_error(SerializationFailure("concurrent update")) == (
        "serialization"
    )
    assert ds.classify_error(TxConflict("x")) == "serialization"
    assert ds.classify_error(
        PgOperationalError("server closed the connection unexpectedly")
    ) == "connection"
    assert ds.classify_error(ValueError("x")) == "other"


# ---------------------------------------------------------------------------
# run_tx retry behavior
# ---------------------------------------------------------------------------


def test_tx_retries_metric_by_kind(eph):
    ds = eph.datastore
    ds.failpoint_scope = "retrymetric"
    ser0 = metrics.tx_retries_total.get(tx="kindtest", kind="serialization")
    conn0 = metrics.tx_retries_total.get(tx="kindtest", kind="connection")
    failpoints.configure("datastore.commit.kindtest=error:1.0,count=2")
    assert ds.run_tx(lambda tx: tx.get_task_ids(), "kindtest") == []
    assert metrics.tx_retries_total.get(tx="kindtest", kind="serialization") == ser0 + 2
    failpoints.configure("datastore.connect.retrymetric=error:1.0,count=3")
    assert ds.run_tx(lambda tx: tx.get_task_ids(), "kindtest") == []
    assert metrics.tx_retries_total.get(tx="kindtest", kind="connection") == conn0 + 3


def test_retry_backoff_full_jitter_and_cap(eph):
    ds = eph.datastore
    # jitter: uniform in [0, min(cap, base * 2^n)], never above the cap
    ds.retry_max_interval_s = 0.01
    samples = [ds._retry_sleep_s(a) for a in range(20) for _ in range(5)]
    assert all(0.0 <= s <= 0.01 for s in samples)
    assert len(set(samples)) > 10  # actually jittered, not a fixed ladder
    # early attempts stay under the exponential envelope
    assert all(ds._retry_sleep_s(0) <= 0.002 for _ in range(20))
    # a 16-attempt connection-failure walk under a tight cap stays fast
    ds.failpoint_scope = "captest"
    failpoints.configure("datastore.connect.captest=error:1.0")
    t0 = time.monotonic()
    with pytest.raises(sqlite3.OperationalError):
        ds.run_tx(lambda tx: tx.get_task_ids(), "captest")
    assert time.monotonic() - t0 < 2.0


def test_fatal_errors_do_not_retry(eph):
    ds = eph.datastore
    calls = {"n": 0}

    def fn(tx):
        calls["n"] += 1
        tx._c.execute("SELECT * FROM definitely_not_a_table")

    with pytest.raises(sqlite3.OperationalError):
        ds.run_tx(fn, "fataltest")
    assert calls["n"] == 1  # retrying a schema error cannot help


# ---------------------------------------------------------------------------
# connection registry / close()
# ---------------------------------------------------------------------------


def test_close_closes_every_threads_connection(eph):
    ds = eph.datastore
    conns = {}

    def worker(name):
        ds.run_tx(lambda tx: tx.get_task_ids(), "reg")
        conns[name] = ds._connect()

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(3)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    conns["main"] = ds._connect()
    assert len(set(map(id, conns.values()))) == 4  # one per thread
    ds.close()
    for conn in conns.values():
        with pytest.raises(sqlite3.ProgrammingError):
            conn.execute("SELECT 1")


def test_discard_unregisters(eph):
    ds = eph.datastore
    conn = ds._connect()
    assert conn in ds._conn_registry
    ds._discard(conn)
    assert conn not in ds._conn_registry
    assert ds._connect() is not conn  # fresh dial


# ---------------------------------------------------------------------------
# connection-lost over pg_fake (the Postgres engine's discard path)
# ---------------------------------------------------------------------------


def test_pg_connection_dropped_mid_tx_discarded_and_reconnected(pgfake):
    """A connection dropped mid-transaction (broken flag set, every
    later call on it fails — the psycopg shape) must be DISCARDED
    (closed, unregistered) and the next run_tx attempt must reconnect
    and succeed. Pins the engine behavior the no-op _discard hook used
    to leave untested."""
    ds = pgfake.datastore
    driver = pgfake._pg_driver
    from tests.test_datastore import mktask

    task = mktask()
    conn0 = ds._connect()
    driver.inject_once(
        lambda sql, p: sql.startswith("INSERT INTO tasks"),
        PgOperationalError("server closed the connection unexpectedly"),
        break_connection=True,
    )
    n_before = len(driver.statements("connect"))
    ds.run_tx(lambda tx: tx.put_task(task), "conn_lost")
    # reconnected (fresh dial) and the dead connection was CLOSED, not
    # leaked to the server
    assert len(driver.statements("connect")) == n_before + 1
    assert conn0.closed
    assert conn0 not in ds._conn_registry
    assert ds._connect() is not conn0
    assert ds.run_tx(lambda tx: tx.get_task(task.task_id), "readback") is not None


def test_pg_connection_lost_feeds_supervisor(pgfake):
    """run_tx reports connection-class failures to the attached
    supervisor — at most ONE per run_tx call (a single doomed
    transaction retrying N times is one outage observation, not N),
    and a success afterward starts recovery. No probe thread here: the
    transitions under test are driven purely by real traffic."""
    ds = pgfake.datastore
    ds.supervisor = DatastoreSupervisor(ds, probe_interval_s=3600, down_threshold=2)
    ds.failpoint_scope = "supfeed"
    ds.retry_max_interval_s = 0.001
    failpoints.configure("datastore.connect.supfeed=error:1.0")
    for _ in range(2):
        with pytest.raises(PgOperationalError):
            ds.run_tx(lambda tx: tx.get_task_ids(), "sup_feed")
    # two failed CALLS (not two failed attempts of one call) -> down
    assert ds.supervisor.state == "down"
    assert metrics.datastore_consecutive_failures.get() == 2.0
    failpoints.clear()
    assert ds.run_tx(lambda tx: tx.get_task_ids(), "sup_feed") == []
    assert ds.supervisor.state == "recovering"
    assert metrics.datastore_consecutive_failures.get() == 0.0


def test_one_run_tx_reports_at_most_one_supervisor_failure(eph):
    """A transient blip absorbed by run_tx's own retry must not march
    the supervisor toward down: 2 failed attempts inside one call are
    one observation, and the call's success resets it."""
    ds = eph.datastore
    ds.supervisor = DatastoreSupervisor(ds, probe_interval_s=3600, down_threshold=2)
    ds.failpoint_scope = "blip"
    failpoints.configure("datastore.connect.blip=error:1.0,count=2")
    assert ds.run_tx(lambda tx: tx.get_task_ids(), "blip") == []
    assert ds.supervisor.state == "up"  # never reached down_threshold
    assert ds.supervisor.status()["transitions"].get("down") is None


# ---------------------------------------------------------------------------
# supervisor state machine
# ---------------------------------------------------------------------------


def test_supervisor_state_machine_transitions(eph):
    sup = DatastoreSupervisor(eph.datastore, probe_interval_s=3600, down_threshold=3)
    assert sup.state == "up" and sup.readiness() is None
    sup.record_failure(RuntimeError("x"))
    assert sup.state == "degraded"
    assert sup.readiness() is None  # degraded still serves
    sup.record_failure()
    sup.record_failure()
    assert sup.state == "down"
    assert "datastore down" in sup.readiness()
    assert metrics.datastore_up.get() == 0.0
    sup.record_success()
    assert sup.state == "recovering"
    sup.record_failure()  # relapse during recovery
    assert sup.state == "down"
    sup.record_success()
    sup.record_success()
    assert sup.state == "up"
    assert metrics.datastore_up.get() == 1.0
    assert sup.status()["transitions"]["down"] == 2


def test_supervisor_slow_commit_degrades_with_hold(eph):
    sup = DatastoreSupervisor(
        eph.datastore, probe_interval_s=3600, degraded_hold_s=0.2
    )
    sup.record_slow_commit(3.0)
    assert sup.state == "degraded"
    sup.record_success()
    assert sup.state == "degraded"  # hold window still open
    time.sleep(0.25)
    sup.record_success()
    assert sup.state == "up"


def test_supervisor_probe_cycle_end_to_end(eph):
    ds = eph.datastore
    ds.failpoint_scope = "probecycle"
    sup = ds.start_supervision(
        probe_interval_s=0.05, down_threshold=2, recover_threshold=2
    )
    deadline = time.monotonic() + 5
    while sup.state != "up" and time.monotonic() < deadline:
        time.sleep(0.01)
    failpoints.configure("datastore.connect.probecycle=error:1.0")
    deadline = time.monotonic() + 10
    while sup.state != "down" and time.monotonic() < deadline:
        time.sleep(0.01)
    assert sup.state == "down"
    assert sup.reconnect_delay_s() >= sup.probe_interval_s
    failpoints.clear()
    deadline = time.monotonic() + 10
    while sup.state != "up" and time.monotonic() < deadline:
        time.sleep(0.01)
    assert sup.state == "up"


# ---------------------------------------------------------------------------
# /healthz vs /readyz
# ---------------------------------------------------------------------------


def _get_status(url):
    try:
        with urllib.request.urlopen(url, timeout=5) as r:
            return r.status, r.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()


def test_readyz_splits_from_healthz():
    from janus_tpu.binary_utils import (
        HealthServer,
        register_readiness_check,
        unregister_readiness_check,
    )

    reason = [None]
    register_readiness_check("t_ds", lambda: reason[0])
    srv = HealthServer("127.0.0.1:0").start()
    try:
        base = f"http://127.0.0.1:{srv.port}"
        status, body = _get_status(base + "/readyz")
        assert status == 200 and json.loads(body)["ready"] is True
        reason[0] = "datastore down (3 consecutive failures)"
        status, body = _get_status(base + "/readyz")
        doc = json.loads(body)
        assert status == 503 and doc["ready"] is False
        assert doc["reasons"]["t_ds"].startswith("datastore down")
        # liveness is NOT readiness: /healthz stays 200 (restarting the
        # process would not bring the database back)
        status, _ = _get_status(base + "/healthz")
        assert status == 200
    finally:
        unregister_readiness_check("t_ds")
        srv.stop()


def test_readiness_check_exception_counts_as_not_ready():
    from janus_tpu.binary_utils import (
        readiness_snapshot,
        register_readiness_check,
        unregister_readiness_check,
    )

    def boom():
        raise RuntimeError("kaput")

    register_readiness_check("t_boom", boom)
    try:
        ready, reasons = readiness_snapshot()
        assert not ready and "kaput" in reasons["t_boom"]
    finally:
        unregister_readiness_check("t_boom")


# ---------------------------------------------------------------------------
# degraded-mode admission
# ---------------------------------------------------------------------------


def test_admission_sheds_aggregate_routes_while_datastore_not_up():
    from janus_tpu.ingest.admission import (
        AdmissionConfig,
        AdmissionController,
        ShedError,
    )

    class FakeSup:
        state = "down"

        def reconnect_delay_s(self):
            return 7.0

    sup = FakeSup()
    ctl = AdmissionController(AdmissionConfig(), supervisor_fn=lambda: sup)
    with pytest.raises(ShedError) as ei:
        ctl.admit("aggregate")
    assert ei.value.status == 503
    assert ei.value.reason == "datastore_down"
    assert ei.value.retry_after_s == 7.0
    # uploads are NOT shed: they flow into the spill journal
    ctl.admit("upload")
    sup.state = "up"
    ctl.admit("aggregate")  # healthy again


def test_drivers_park_acquire_while_down(eph):
    from janus_tpu.aggregator.aggregation_job_driver import AggregationJobDriver
    from janus_tpu.aggregator.collection_job_driver import CollectionJobDriver

    ds = eph.datastore
    sup = ds.start_supervision(probe_interval_s=3600, down_threshold=1)
    sup.record_failure()
    assert sup.state == "down"
    assert AggregationJobDriver(ds, None).acquirer(60)(4) == []
    assert CollectionJobDriver(ds, None).acquirer(60)(4) == []


def test_driver_acquirer_absorbs_connection_errors_raises_fatal(eph):
    """The drivers' acquirers absorb CONNECTION-class failures as 'no
    jobs this pass' (a datastore outage must not kill the driver
    process) but re-raise fatal errors — a broken schema retried
    forever behind a healthy /readyz would be a silent stall."""
    from janus_tpu.aggregator.aggregation_job_driver import AggregationJobDriver

    ds = eph.datastore
    ds.failpoint_scope = "acqtol"
    ds.retry_max_interval_s = 0.001
    acquire = AggregationJobDriver(ds, None).acquirer(60)
    failpoints.configure("datastore.connect.acqtol=error:1.0")
    assert acquire(4) == []  # outage absorbed: park, don't crash
    failpoints.clear()
    assert acquire(4) == []  # recovered: acquires normally (no jobs)

    class FatalDs:
        supervisor = None

        def classify_error(self, e):
            return "fatal"

        def run_tx(self, fn, name):
            raise sqlite3.OperationalError("no such table: aggregation_jobs")

    with pytest.raises(sqlite3.OperationalError):
        AggregationJobDriver(FatalDs(), None).acquirer(60)(4)


def test_job_driver_loop_parks_through_outage(eph):
    """End to end through the generic loop: an outage makes the
    acquirer return [] (connection errors absorbed in the driver's
    acquirer), the loop keeps running on its backoff, and recovery
    resumes acquiring — the process never dies."""
    from janus_tpu.aggregator.aggregation_job_driver import AggregationJobDriver
    from janus_tpu.aggregator.job_driver import JobDriver, JobDriverConfig, Stopper

    ds = eph.datastore
    ds.failpoint_scope = "looppark"
    ds.retry_max_interval_s = 0.001
    calls = {"n": 0}
    stopper = Stopper()
    inner = AggregationJobDriver(ds, None).acquirer(60)

    def acquirer(limit):
        calls["n"] += 1
        if calls["n"] >= 3:
            stopper.stop()
        return inner(limit)

    failpoints.configure("datastore.connect.looppark=error:1.0")
    jd = JobDriver(
        JobDriverConfig(
            job_discovery_interval_s=0.01, max_job_discovery_interval_s=0.02
        ),
        acquirer,
        lambda acquired: None,
        stopper,
    )
    jd.run()  # must exit via the stopper, not via the outage
    assert calls["n"] >= 3
