"""Device XOF (batched Keccak) vs host hashlib: byte-identical streams."""

import hashlib

import numpy as np

from janus_tpu.fields import Field64, Field128, JF64, JF128
from janus_tpu.vdaf import keccak_jax as kj
from janus_tpu.vdaf.xof import XofShake128, dst, USAGE_MEASUREMENT_SHARE


def test_shake128_matches_hashlib():
    batch = 3
    msgs = [bytes([i]) * 48 for i in range(batch)]  # 48 bytes = 6 lanes
    lanes = np.stack([kj.bytes_to_lanes(m) for m in msgs])
    import jax.numpy as jnp

    padded = kj.pad_message_lanes([(0, jnp.asarray(lanes))], 48, batch)
    out = kj.shake128_squeeze_lanes(padded, 3)  # 3 blocks = 504 bytes
    out = np.asarray(out)
    for i, m in enumerate(msgs):
        want = hashlib.shake_128(m).digest(3 * 168)
        got = out[i].reshape(-1).astype("<u8").tobytes()
        assert got == want, f"stream mismatch for message {i}"


def test_multiblock_absorb_matches_hashlib():
    # message longer than one rate block (2 blocks = 336 bytes)
    batch = 2
    msgs = [bytes(range(200)) + bytes([i]) * 136 for i in range(batch)]
    lanes = np.stack([kj.bytes_to_lanes(m) for m in msgs])
    import jax.numpy as jnp

    padded = kj.pad_message_lanes([(0, jnp.asarray(lanes))], len(msgs[0]), batch)
    out = np.asarray(kj.shake128_squeeze_lanes(padded, 2))
    for i, m in enumerate(msgs):
        want = hashlib.shake_128(m).digest(2 * 168)
        got = out[i].reshape(-1).astype("<u8").tobytes()
        assert got == want


def test_exact_block_boundary_padding():
    # message exactly one rate block long: padding must go to block 2
    batch = 1
    msg = bytes(range(168))
    import jax.numpy as jnp

    lanes = kj.bytes_to_lanes(msg)[None, :]
    padded = kj.pad_message_lanes([(0, jnp.asarray(lanes))], 168, batch)
    assert padded.shape[1] == 2
    out = np.asarray(kj.shake128_squeeze_lanes(padded, 1))
    want = hashlib.shake_128(msg).digest(168)
    assert out[0].reshape(-1).astype("<u8").tobytes() == want


def test_field_sampling_matches_host():
    d = dst(0x42, USAGE_MEASUREMENT_SHARE)
    for field, jf in [(Field64, JF64), (Field128, JF128)]:
        batch = 4
        length = 33
        seeds = [bytes([i]) * 16 for i in range(batch)]
        binder = (1).to_bytes(8, "little") + bytes(range(16))
        # host (counter-mode stream)
        want = [
            XofShake128(s, d, binder).next_vec(field, length) for s in seeds
        ]
        # device: prefix = dst16 || seed || binder
        import jax.numpy as jnp

        seed_lanes = jnp.asarray(
            np.stack([kj.bytes_to_lanes(s) for s in seeds])
        )
        msg_len = 16 + 16 + len(binder)
        parts = [(0, d), (2, seed_lanes), (4, binder)]
        got = kj.expand_field_vec(jf, parts, msg_len, batch, length)
        got_ints = jf.to_ints(got)
        for b in range(batch):
            assert [int(x) for x in got_ints[b]] == want[b], (field, b)


def test_ctr_stream_matches_host():
    # multi-block counter-mode stream, device vs host XofCtr128
    import jax.numpy as jnp

    from janus_tpu.vdaf.xof import XofCtr128

    d = dst(0x42, USAGE_MEASUREMENT_SHARE)
    batch = 3
    seeds = [bytes([7 * i + 1]) * 16 for i in range(batch)]
    binder = bytes(range(24))
    seed_lanes = jnp.asarray(np.stack([kj.bytes_to_lanes(s) for s in seeds]))
    parts = [(0, d), (2, seed_lanes), (4, binder)]
    out_blocks = 5
    got = np.asarray(
        kj.ctr_stream_lanes(parts, 16 + 16 + len(binder), batch, out_blocks)
    )
    for i, s in enumerate(seeds):
        want = XofCtr128(s, d, binder).next(out_blocks * 168)
        assert got[i].reshape(-1).astype("<u8").tobytes() == want, i


def test_tree_digest_matches_host():
    import jax.numpy as jnp

    from janus_tpu.vdaf.xof import tree_digest

    # sizes spanning: 1 leaf+1, several leaves, multiple tree levels
    for n_bytes in (120, 1000, 9000, 113 * 112):
        rng = np.random.default_rng(n_bytes)
        data = rng.integers(0, 256, size=n_bytes - n_bytes % 8, dtype=np.uint8).tobytes()
        want = tree_digest(data)
        lanes = jnp.asarray(kj.bytes_to_lanes(data)[None, :])
        got = np.asarray(kj.tree_digest_lanes([(0, lanes)], len(data), 1))
        assert got[0].astype("<u8").tobytes() == want, n_bytes


def test_long_binder_derive_matches_host():
    # derive_seed with binder > INLINE_BINDER_MAX goes through the tree.
    # Only the joint-rand-part usage may take the digest substitution
    # (SECURITY-NOTES.md #2); any other usage asserts.
    import pytest

    from janus_tpu.vdaf.xof import INLINE_BINDER_MAX, USAGE_JOINT_RAND_PART, XofCtr128

    d = dst(0x42, USAGE_JOINT_RAND_PART)
    seed = bytes(range(16))
    binder = bytes(range(256))  # > 112, lane-aligned
    assert len(binder) > INLINE_BINDER_MAX
    out = XofCtr128.derive_seed(seed, d, binder)
    # equal to deriving with the digest inline
    from janus_tpu.vdaf.xof import tree_digest

    assert out == XofCtr128.derive_seed(seed, d, tree_digest(binder))

    with pytest.raises(ValueError, match="joint-rand-part"):
        XofCtr128.derive_seed(seed, dst(0x42, USAGE_MEASUREMENT_SHARE), binder)


def test_reduction_sampling_semantics():
    # oversample-and-reduce: element i = (LIMBS+1) lanes little-endian
    # mod p — including values at/above p, which rejection would skip
    import jax.numpy as jnp

    p64 = Field64.MODULUS
    lanes = np.zeros((1, 1, 21), dtype=np.uint64)
    lanes[0, 0, 0] = np.uint64(p64)     # elem 0 = p + 2^64*5 -> 5*2^64 mod p... computed below
    lanes[0, 0, 1] = np.uint64(5)
    lanes[0, 0, 2] = np.uint64(123)     # elem 1 = 123
    lanes[0, 0, 3] = np.uint64(0)
    got = kj.sample_field_vec(JF64, jnp.asarray(lanes), 2)
    vals = [int(x) for x in JF64.to_ints(got)[0]]
    want0 = (p64 + 5 * (1 << 64)) % p64
    assert vals == [want0, 123]

    p128 = Field128.MODULUS
    lanes = np.zeros((1, 1, 21), dtype=np.uint64)
    # elem 0 = l0 + l1*2^64 + l2*2^128
    lanes[0, 0, 0] = np.uint64(7)
    lanes[0, 0, 1] = np.uint64(11)
    lanes[0, 0, 2] = np.uint64(0xDEADBEEF)
    got = kj.sample_field_vec(JF128, jnp.asarray(lanes), 1)
    vals = [int(x) for x in JF128.to_ints(got)[0]]
    want = (7 + 11 * (1 << 64) + 0xDEADBEEF * (1 << 128)) % p128
    assert vals == [want]
