"""Device XOF (batched Keccak) vs host hashlib: byte-identical streams."""

import hashlib

import numpy as np

from janus_tpu.fields import Field64, Field128, JF64, JF128
from janus_tpu.vdaf import keccak_jax as kj
from janus_tpu.vdaf.xof import XofShake128, dst, USAGE_MEASUREMENT_SHARE


def test_shake128_matches_hashlib():
    batch = 3
    msgs = [bytes([i]) * 48 for i in range(batch)]  # 48 bytes = 6 lanes
    lanes = np.stack([kj.bytes_to_lanes(m) for m in msgs])
    import jax.numpy as jnp

    padded = kj.pad_message_lanes([(0, jnp.asarray(lanes))], 48, batch)
    out = kj.shake128_squeeze_lanes(padded, 3)  # 3 blocks = 504 bytes
    out = np.asarray(out)
    for i, m in enumerate(msgs):
        want = hashlib.shake_128(m).digest(3 * 168)
        got = out[i].reshape(-1).astype("<u8").tobytes()
        assert got == want, f"stream mismatch for message {i}"


def test_multiblock_absorb_matches_hashlib():
    # message longer than one rate block (2 blocks = 336 bytes)
    batch = 2
    msgs = [bytes(range(200)) + bytes([i]) * 136 for i in range(batch)]
    lanes = np.stack([kj.bytes_to_lanes(m) for m in msgs])
    import jax.numpy as jnp

    padded = kj.pad_message_lanes([(0, jnp.asarray(lanes))], len(msgs[0]), batch)
    out = np.asarray(kj.shake128_squeeze_lanes(padded, 2))
    for i, m in enumerate(msgs):
        want = hashlib.shake_128(m).digest(2 * 168)
        got = out[i].reshape(-1).astype("<u8").tobytes()
        assert got == want


def test_exact_block_boundary_padding():
    # message exactly one rate block long: padding must go to block 2
    batch = 1
    msg = bytes(range(168))
    import jax.numpy as jnp

    lanes = kj.bytes_to_lanes(msg)[None, :]
    padded = kj.pad_message_lanes([(0, jnp.asarray(lanes))], 168, batch)
    assert padded.shape[1] == 2
    out = np.asarray(kj.shake128_squeeze_lanes(padded, 1))
    want = hashlib.shake_128(msg).digest(168)
    assert out[0].reshape(-1).astype("<u8").tobytes() == want


def test_field_sampling_matches_host():
    d = dst(0x42, USAGE_MEASUREMENT_SHARE)
    for field, jf in [(Field64, JF64), (Field128, JF128)]:
        batch = 4
        length = 33
        seeds = [bytes([i]) * 16 for i in range(batch)]
        binder = (1).to_bytes(8, "little") + bytes(range(16))
        # host
        want = [
            XofShake128(s, d, binder).next_vec(field, length) for s in seeds
        ]
        # device: message = dst16 || seed || binder
        import jax.numpy as jnp

        seed_lanes = jnp.asarray(
            np.stack([kj.bytes_to_lanes(s) for s in seeds])
        )
        msg_len = 16 + 16 + len(binder)
        parts = [(0, d), (2, seed_lanes), (4, binder)]
        got = kj.expand_field_vec(jf, parts, msg_len, batch, length)
        got_ints = jf.to_ints(got)
        for b in range(batch):
            assert [int(x) for x in got_ints[b]] == want[b], (field, b)


def test_rejection_path_exercised():
    # Craft a stream position where a candidate is rejected: brute-force a
    # seed whose early chunk for Field64 is >= p (prob ~2^-32 per chunk is
    # too rare; instead verify the compaction logic on synthetic lanes).
    import jax.numpy as jnp

    # synthetic stream: candidate 0 invalid (>= p), candidates 1.. valid
    p = Field64.MODULUS
    lanes = np.zeros((1, 2, 21), dtype=np.uint64)
    lanes[0, 0, 0] = np.uint64(p)  # rejected
    for i in range(1, 21):
        lanes[0, 0, i] = np.uint64(i)
    for i in range(21):
        lanes[0, 1, i] = np.uint64(100 + i)
    got = kj.sample_field_vec(JF64, jnp.asarray(lanes), 25)
    vals = [int(x) for x in JF64.to_ints(got)[0]]
    assert vals == [*range(1, 21), 100, 101, 102, 103, 104]
