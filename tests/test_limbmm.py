"""Exactness fuzzing for the MXU limb-contraction and pow2 const-mul.

fold_contract must be bit-identical to the sequential field math for
arbitrary reduced inputs — it replaces the FLP query's hot loop, where
any deviation flips verifier equality and rejects honest reports.
"""

import os

import numpy as np
import pytest

from janus_tpu.fields.jfield import JF64, JF128, fmul_pow2, fsum
from janus_tpu.ops.limbmm import fold_contract


def _rand_field(jf, rng, shape):
    ints = rng.integers(0, np.iinfo(np.uint64).max, size=shape, dtype=np.uint64)
    vals = ints.astype(object)
    if jf.LIMBS == 2:
        hi = rng.integers(0, np.uint64(1) << np.uint64(63), size=shape, dtype=np.uint64)
        vals = vals + (hi.astype(object) << 64)
    vals = vals % jf.MODULUS
    return jf.from_ints(vals), vals


@pytest.mark.parametrize("jf", [JF64, JF128], ids=["f64", "f128"])
@pytest.mark.parametrize("dtype", ["int8", "f32"])
def test_fold_contract_exact(jf, dtype, monkeypatch):
    monkeypatch.setenv("JANUS_LIMBMM_DTYPE", dtype)
    rng = np.random.default_rng(42 + jf.LIMBS)
    b, W, calls, C = 3, 2, 37, 11
    w, w_ints = _rand_field(jf, rng, (b, W, calls))
    X, x_ints = _rand_field(jf, rng, (b, calls, C))
    got = jf.to_ints(fold_contract(jf, w, X))
    p = jf.MODULUS
    for bi in range(b):
        for wi in range(W):
            for c in range(C):
                expect = (
                    sum(int(w_ints[bi, wi, k]) * int(x_ints[bi, k, c]) for k in range(calls))
                    % p
                )
                assert int(got[bi, wi, c]) == expect, (bi, wi, c)


@pytest.mark.parametrize("jf", [JF64, JF128], ids=["f64", "f128"])
def test_fold_contract_matches_sequential_field_ops(jf):
    """Same value as mul+fsum on device (the path it replaces)."""
    rng = np.random.default_rng(7)
    b, W, calls, C = 2, 3, 50, 8
    w, _ = _rand_field(jf, rng, (b, W, calls))
    X, _ = _rand_field(jf, rng, (b, calls, C))
    got = fold_contract(jf, w, X)
    import jax.numpy as jnp

    from janus_tpu.fields.jfield import fmap

    prod = jf.mul(
        fmap(lambda a: a[:, :, :, None], w), fmap(lambda a: a[:, None, :, :], X)
    )
    want = fsum(jf, prod, axis=2)
    for g, e in zip(got, want):
        assert (np.asarray(g) == np.asarray(e)).all()


@pytest.mark.parametrize("jf", [JF64, JF128], ids=["f64", "f128"])
def test_fold_contract_segmented(jf, monkeypatch):
    """f32 path segments the contraction at 1024 calls; force a tiny
    segment to exercise multi-segment accumulation."""
    import janus_tpu.ops.limbmm as mm

    monkeypatch.setitem(mm._SEG, "int8", 16)
    rng = np.random.default_rng(11)
    b, W, calls, C = 2, 1, 45, 5
    w, w_ints = _rand_field(jf, rng, (b, W, calls))
    X, x_ints = _rand_field(jf, rng, (b, calls, C))
    got = jf.to_ints(fold_contract(jf, w, X))
    p = jf.MODULUS
    expect = (
        sum(int(w_ints[0, 0, k]) * int(x_ints[0, k, 2]) for k in range(calls)) % p
    )
    assert int(got[0, 0, 2]) == expect


@pytest.mark.parametrize("jf", [JF64, JF128], ids=["f64", "f128"])
@pytest.mark.parametrize("k", [0, 1, 7, 15, 16, 31, 32, 33, 47, 63])
def test_fmul_pow2(jf, k):
    rng = np.random.default_rng(100 + k)
    v, ints = _rand_field(jf, rng, (64,))
    got = jf.to_ints(fmul_pow2(jf, v, k))
    for i in range(64):
        assert int(got[i]) == (int(ints[i]) << k) % jf.MODULUS


@pytest.mark.parametrize("kind", ["sumvec", "histogram"])
def test_query_mm_matches_fold_path(kind, monkeypatch):
    """The MXU query and the VPU fold query are the same field elements
    (both batched and streamed): flip engine._QUERY_MM at call time."""
    import jax.numpy as jnp

    import janus_tpu.vdaf.engine as eng
    from janus_tpu.vdaf.engine import batched_circuit, flp_query_batched
    from janus_tpu.vdaf.reference import Histogram, SumVec

    circ = SumVec(length=9, bits=4) if kind == "sumvec" else Histogram(24)
    bc = batched_circuit(circ)
    jf = bc.jf
    rng = np.random.default_rng(5)
    b = 4
    inp, _ = _rand_field(jf, rng, (b, circ.input_len))
    proof, _ = _rand_field(jf, rng, (b, circ.proof_len))
    qr, _ = _rand_field(jf, rng, (b, circ.query_rand_len))
    jr, _ = _rand_field(jf, rng, (b, circ.joint_rand_len))

    monkeypatch.setattr(eng, "_QUERY_MM", True)
    got = flp_query_batched(bc, inp, proof, qr, jr, 2)
    monkeypatch.setattr(eng, "_QUERY_MM", False)
    want = flp_query_batched(bc, inp, proof, qr, jr, 2)
    for g, e in zip(got, want):
        assert (np.asarray(g) == np.asarray(e)).all()


def test_streamed_query_mm_matches_fold_path(monkeypatch):
    import janus_tpu.vdaf.engine as eng
    from janus_tpu.vdaf.engine import (
        batched_circuit,
        flp_query_streamed,
        sliced_meas_source,
        stream_plan,
    )
    from janus_tpu.vdaf.reference import SumVec

    circ = SumVec(length=64, bits=4)  # small but multi-step under a low cap
    bc = batched_circuit(circ)
    jf = bc.jf
    plan = stream_plan(bc, min_input_len=1)
    assert plan is not None and plan.n_steps > 1
    rng = np.random.default_rng(17)
    b = 3
    meas, _ = _rand_field(jf, rng, (b, circ.input_len))
    proof, _ = _rand_field(jf, rng, (b, circ.proof_len))
    qr, _ = _rand_field(jf, rng, (b, circ.query_rand_len))
    jr, _ = _rand_field(jf, rng, (b, circ.joint_rand_len))

    out = {}
    for flag in (True, False):
        monkeypatch.setattr(eng, "_QUERY_MM", flag)
        src = sliced_meas_source(bc, plan, meas)
        out[flag] = flp_query_streamed(bc, plan, src, proof, qr, jr, 2)
    for g, e in zip(out[True][0] + out[True][1], out[False][0] + out[False][1]):
        assert (np.asarray(g) == np.asarray(e)).all()
