"""Official VDAF test-vector harness (draft-irtf-cfrg-vdaf Prio3).

Drop the official JSON vectors (the draft reference implementation's
``Prio3*.json`` format) into ``tests/vectors/`` and this module checks
the draft-mode implementation byte-for-byte: sharding under the given
(measurement, nonce, rand), wire encodings of public/input shares,
prepare shares/messages, output shares, aggregate shares, and the
aggregate result.

This build environment has no network access, so no vectors ship with
the repo and the module skips. The harness exists so conformance is a
drop-in *verification*, not a code change: any byte mismatch between
XofSponge128/draft-mode Prio3 and the published vectors fails here
first. Reference anchor: the reference's prio 0.15 dependency
implements VDAF-07 (Cargo.lock:2939); its own conformance suite lives
upstream in that crate.
"""

import glob
import json
import os

import pytest

from janus_tpu.vdaf.registry import VdafInstance, prio3_host
from janus_tpu.vdaf.wire import Prio3Wire

VECTOR_DIR = os.path.join(os.path.dirname(__file__), "vectors")
VECTOR_FILES = sorted(glob.glob(os.path.join(VECTOR_DIR, "Prio3*.json")))

_KIND_BY_PREFIX = {
    "Prio3Count": lambda d: VdafInstance("count", xof_mode="draft"),
    "Prio3Sum": lambda d: VdafInstance("sum", bits=int(d["bits"]), xof_mode="draft"),
    "Prio3SumVec": lambda d: VdafInstance(
        "sumvec",
        bits=int(d["bits"]),
        length=int(d["length"]),
        chunk_length=int(d.get("chunk_length", 0)),
        xof_mode="draft",
    ),
    "Prio3Histogram": lambda d: VdafInstance(
        "histogram",
        length=int(d["length"]),
        chunk_length=int(d.get("chunk_length", 0)),
        xof_mode="draft",
    ),
}


def _instance_for(path: str, data: dict) -> VdafInstance:
    name = os.path.basename(path)
    # longest prefix wins (Prio3Sum vs Prio3SumVec)
    best = None
    for prefix, mk in _KIND_BY_PREFIX.items():
        if name.startswith(prefix) and (best is None or len(prefix) > len(best[0])):
            best = (prefix, mk)
    if best is None:
        pytest.skip(f"unrecognized vector file {name}")
    return best[1](data)


@pytest.mark.skipif(
    not VECTOR_FILES, reason="no official vectors in tests/vectors/ (no network)"
)
@pytest.mark.parametrize("path", VECTOR_FILES, ids=os.path.basename)
def test_official_vector(path):
    with open(path) as f:
        data = json.load(f)
    assert int(data.get("shares", 2)) == 2, "DAP uses exactly 2 shares"
    inst = _instance_for(path, data)
    host = prio3_host(inst)
    wire = Prio3Wire(host.circuit)
    verify_key = bytes.fromhex(data["verify_key"])

    out_shares_all = [[], []]
    for prep in data["prep"]:
        nonce = bytes.fromhex(prep["nonce"])
        rand = bytes.fromhex(prep["rand"])
        m = prep["measurement"]
        public, (ls, hs) = host.shard(m, nonce, rand)

        assert wire.encode_public_share(public).hex() == prep["public_share"]
        enc_shares = [
            wire.encode_leader_share(
                ls.measurement_share, ls.proof_share, ls.joint_rand_blind
            ),
            wire.encode_helper_share(hs.seed, hs.joint_rand_blind),
        ]
        for got, want in zip(enc_shares, prep["input_shares"]):
            assert got.hex() == want

        st0, ps0 = host.prepare_init(verify_key, 0, nonce, public, ls)
        st1, ps1 = host.prepare_init(verify_key, 1, nonce, public, hs)
        got_prep_shares = [
            wire.encode_prep_share(ps.verifier_share, ps.joint_rand_part)
            for ps in (ps0, ps1)
        ]
        for got, want in zip(got_prep_shares, prep["prep_shares"][0]):
            assert got.hex() == want

        msg = host.prepare_shares_to_prep([ps0, ps1])
        assert (msg or b"").hex() == prep["prep_messages"][0]

        for k, st in enumerate((st0, st1)):
            out = host.prepare_next(st, msg)
            out_shares_all[k].append(out)
            want_out = prep["out_shares"][k]
            got_out = [int(x) for x in out]
            want_ints = [
                int(w, 16) if isinstance(w, str) else int(w) for w in want_out
            ]
            assert got_out == want_ints

    F = host.circuit.FIELD
    aggs = [host.aggregate(s) for s in out_shares_all]
    for got, want in zip(aggs, data["agg_shares"]):
        assert F.encode_vec(got).hex() == want
    got_result = host.unshard(aggs, len(data["prep"]))
    assert got_result == data["agg_result"]
