"""Ops-shell tests: config parsing, metrics, health listener, CLI,
aggregator API, garbage collector.

Mirrors the reference's config round-trip tests (config.rs:213), CLI
arg tests (janus_cli.rs verify_clap_app), aggregator_api handler tests
and garbage_collector.rs tests, at the same altitude (no containers).
"""

import base64
import json
import secrets
import urllib.request

import pytest
import yaml

from janus_tpu.aggregator.garbage_collector import GarbageCollector
from janus_tpu.aggregator_api import AggregatorApi, AggregatorApiServer
from janus_tpu.bin import janus_cli
from janus_tpu.binary_utils import HealthServer, parse_datastore_keys
from janus_tpu.config import (
    AggregatorConfig,
    JobCreatorConfig,
    JobDriverBinaryConfig,
    load_config,
)
from janus_tpu.core.time_util import MockClock
from janus_tpu.datastore.store import EphemeralDatastore
from janus_tpu.messages import Duration, Role, Time
from janus_tpu.metrics import REGISTRY, MetricsRegistry
from janus_tpu.task import QueryTypeConfig, TaskBuilder
from janus_tpu.vdaf.registry import VdafInstance


# --- config ---


def test_aggregator_config_from_sample():
    cfg = load_config("docs/samples/aggregator.yaml", AggregatorConfig)
    assert cfg.listen_address == "0.0.0.0:8080"
    assert cfg.batch_aggregation_shard_count == 32
    assert cfg.common.database.url == "/var/lib/janus/janus.sqlite"
    assert cfg.common.health_check_listen_address == "0.0.0.0:9001"
    assert not cfg.taskprov.enabled
    pc = cfg.protocol_config()
    assert pc.max_upload_batch_size == 100


def test_job_driver_config_from_sample():
    cfg = load_config("docs/samples/aggregation_job_driver.yaml", JobDriverBinaryConfig)
    assert cfg.job_driver.max_concurrent_job_workers == 4
    assert cfg.job_driver.worker_lease_duration_s == 600
    assert cfg.job_driver.maximum_attempts_before_failure == 10


def test_job_creator_config_from_sample():
    cfg = load_config("docs/samples/aggregation_job_creator.yaml", JobCreatorConfig)
    assert cfg.creator_config().min_aggregation_job_size == 10
    assert cfg.creator_config().max_aggregation_job_size == 500


def test_parse_datastore_keys():
    k = base64.urlsafe_b64encode(b"0123456789abcdef").decode().rstrip("=")
    assert parse_datastore_keys(f"{k},{k}") == [b"0123456789abcdef"] * 2
    with pytest.raises(ValueError):
        parse_datastore_keys("")
    with pytest.raises(ValueError):
        parse_datastore_keys(base64.urlsafe_b64encode(b"short").decode())


# --- metrics ---


def test_metrics_counter_and_histogram_render():
    reg = MetricsRegistry()
    c = reg.counter("test_requests", "requests")
    c.add(status="200")
    c.add(status="200")
    c.add(status="400")
    h = reg.histogram("test_latency", "latency", buckets=(0.1, 1.0))
    h.observe(0.05)
    h.observe(0.5)
    h.observe(5.0)
    text = reg.render()
    assert 'test_requests{status="200"} 2.0' in text
    assert 'test_requests{status="400"} 1.0' in text
    assert 'test_latency_bucket{le="0.1"} 1' in text
    assert 'test_latency_bucket{le="1"} 2' in text
    assert 'test_latency_bucket{le="+Inf"} 3' in text
    assert "test_latency_count 3" in text


def test_health_server_serves_healthz_and_metrics():
    REGISTRY.counter("janus_http_requests").add(route="test")
    srv = HealthServer("127.0.0.1:0").start()
    try:
        with urllib.request.urlopen(f"http://127.0.0.1:{srv.port}/healthz") as resp:
            assert resp.status == 200
        with urllib.request.urlopen(f"http://127.0.0.1:{srv.port}/metrics") as resp:
            body = resp.read().decode()
        assert "janus_http_requests" in body
    finally:
        srv.stop()


# --- janus_cli ---


def test_cli_create_datastore_key(capsys):
    assert janus_cli.main(["create-datastore-key"]) == 0
    key = capsys.readouterr().out.strip()
    assert len(base64.urlsafe_b64decode(key + "=" * (-len(key) % 4))) == 16


def test_cli_provision_and_list_tasks(tmp_path, capsys):
    task = TaskBuilder(
        QueryTypeConfig.time_interval(), VdafInstance.count(), Role.LEADER
    ).build()
    tasks_file = tmp_path / "tasks.yaml"
    tasks_file.write_text(yaml.safe_dump([task.to_dict()]))
    db = str(tmp_path / "ds.sqlite")
    key = base64.urlsafe_b64encode(secrets.token_bytes(16)).decode().rstrip("=")

    # --opt=value form: a random base64url key starts with "-" ~1/64 of
    # the time and the separate-arg form then parses it as a flag
    rc = janus_cli.main(
        ["provision-tasks", str(tasks_file), "--database", db, f"--datastore-keys={key}"]
    )
    assert rc == 0
    out = json.loads(capsys.readouterr().out)
    assert out[0]["task_id"] == task.to_dict()["task_id"]

    rc = janus_cli.main(["list-tasks", "--database", db, f"--datastore-keys={key}"])
    assert rc == 0
    listing = capsys.readouterr().out
    assert task.to_dict()["task_id"] in listing
    assert "role=leader" in listing and "vdaf=count" in listing


# --- aggregator API ---


@pytest.fixture()
def api_ds():
    eph = EphemeralDatastore()
    yield eph.datastore
    eph.cleanup()


TOKEN = "testtoken"
AUTH = {"Authorization": f"Bearer {TOKEN}"}


def api_call(api, method, path, doc=None, headers=AUTH, query=None):
    body = json.dumps(doc).encode() if doc is not None else b""
    return api.handle(method, path, query or {}, headers, body)


def test_api_auth_required(api_ds):
    api = AggregatorApi(api_ds, auth_tokens=(TOKEN,))
    status, doc = api_call(api, "GET", "/task_ids", headers={})
    assert status == 401
    status, doc = api_call(api, "GET", "/task_ids", headers={"Authorization": "Bearer nope"})
    assert status == 401


def test_api_task_crud_and_metrics(api_ds):
    api = AggregatorApi(api_ds, auth_tokens=(TOKEN,))
    task_doc = TaskBuilder(
        QueryTypeConfig.time_interval(), VdafInstance.sum(bits=8), Role.LEADER
    ).build().to_dict()
    status, created = api_call(api, "POST", "/tasks", task_doc)
    assert status == 201
    tid = created["task_id"]
    # private keys never come back
    assert all(isinstance(k, str) for k in created["hpke_keys"])

    status, got = api_call(api, "GET", f"/tasks/{tid}")
    assert status == 200 and got["task_id"] == tid

    status, ids = api_call(api, "GET", "/task_ids")
    assert status == 200 and tid in ids["task_ids"]

    status, m = api_call(api, "GET", f"/tasks/{tid}/metrics")
    assert status == 200 and m == {"reports": 0, "report_aggregations": 0}

    status, _ = api_call(api, "DELETE", f"/tasks/{tid}")
    assert status == 204
    status, _ = api_call(api, "GET", f"/tasks/{tid}")
    assert status == 404


def test_api_post_task_fills_defaults(api_ds):
    api = AggregatorApi(api_ds, auth_tokens=(TOKEN,))
    minimal = {
        "leader_aggregator_endpoint": "https://leader.example.com/",
        "helper_aggregator_endpoint": "https://helper.example.com/",
        "query_type": {"code": 1},
        "vdaf": {"kind": "count"},
        "role": int(Role.HELPER),
        "time_precision": 3600,
    }
    status, created = api_call(api, "POST", "/tasks", minimal)
    assert status == 201
    assert created["vdaf_verify_key"]
    assert created["hpke_keys"], "helper gets a generated HPKE keypair"


def test_api_hpke_config_lifecycle(api_ds):
    api = AggregatorApi(api_ds, auth_tokens=(TOKEN,))
    status, kp = api_call(api, "PUT", "/hpke_configs", {})
    assert status == 201 and kp["state"] == "pending"
    status, listing = api_call(api, "GET", "/hpke_configs")
    assert status == 200 and len(listing) == 1
    cfg_bytes = base64.urlsafe_b64decode(listing[0]["config"])
    config_id = cfg_bytes[0]
    status, _ = api_call(api, "PATCH", f"/hpke_configs/{config_id}", {"state": "active"})
    assert status == 200
    status, listing = api_call(api, "GET", "/hpke_configs")
    assert listing[0]["state"] == "active"
    status, _ = api_call(api, "DELETE", f"/hpke_configs/{config_id}")
    assert status == 204
    status, listing = api_call(api, "GET", "/hpke_configs")
    assert listing == []


def test_api_over_http(api_ds):
    api = AggregatorApi(api_ds, auth_tokens=(TOKEN,))
    srv = AggregatorApiServer(api).start()
    try:
        req = urllib.request.Request(srv.url + "/", headers=AUTH)
        with urllib.request.urlopen(req) as resp:
            doc = json.loads(resp.read())
        assert doc["protocol"] == "DAP-07"
    finally:
        srv.stop()


# --- garbage collector ---


def test_garbage_collector_deletes_expired():
    clock = MockClock(Time(1_600_000_000))
    eph = EphemeralDatastore(clock=clock)
    ds = eph.datastore
    try:
        task = (
            TaskBuilder(QueryTypeConfig.time_interval(), VdafInstance.count(), Role.LEADER)
            .with_(report_expiry_age=Duration(100))
            .build()
        )
        ds.run_tx(lambda tx: tx.put_task(task))

        from janus_tpu.datastore.models import LeaderStoredReport
        from janus_tpu.messages import HpkeCiphertext, HpkeConfigId, ReportId

        def put_report(tx, when):
            rid = ReportId(secrets.token_bytes(16))
            tx.put_client_report(
                LeaderStoredReport(
                    task_id=task.task_id,
                    report_id=rid,
                    client_time=Time(when),
                    public_share=b"",
                    leader_input_share=b"x",
                    helper_encrypted_input_share=HpkeCiphertext(HpkeConfigId(0), b"", b""),
                )
            )

        ds.run_tx(lambda tx: put_report(tx, 1_600_000_000 - 1000))  # expired
        ds.run_tx(lambda tx: put_report(tx, 1_600_000_000 - 10))  # fresh

        gc = GarbageCollector(ds, clock)
        deleted = gc.run_once()
        assert deleted["reports"] == 1
        total, _ = ds.run_tx(lambda tx: tx.count_client_reports_for_task(task.task_id))
        assert total == 1
    finally:
        eph.cleanup()
