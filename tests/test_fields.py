"""Differential tests: JAX field ops vs Python-int oracle."""

import random

import numpy as np
import pytest

from janus_tpu.fields import Field64, Field128, JF64, JF128
from janus_tpu.fields import jfield as jf


CASES = [(Field64, JF64), (Field128, JF128)]


def _rand_elems(field, n, rng):
    # bias toward edge cases
    p = field.MODULUS
    edge = [0, 1, 2, p - 1, p - 2, (p - 1) // 2, 2**32, 2**32 - 1, 2**64 - 1 if p > 2**64 else 0, p >> 1]
    vals = [e % p for e in edge]
    vals += [rng.randrange(p) for _ in range(n - len(vals))]
    return vals[:n]


@pytest.mark.parametrize("field,jfield", CASES)
def test_add_sub_mul_neg(field, jfield):
    rng = random.Random(1234)
    n = 256
    a = _rand_elems(field, n, rng)
    b = _rand_elems(field, n, rng)
    rng.shuffle(b)
    ja = jfield.from_ints(a)
    jb = jfield.from_ints(b)

    got = jfield.to_ints(jfield.add(ja, jb))
    want = [field.add(x, y) for x, y in zip(a, b)]
    assert list(got) == want

    got = jfield.to_ints(jfield.sub(ja, jb))
    want = [field.sub(x, y) for x, y in zip(a, b)]
    assert list(got) == want

    got = jfield.to_ints(jfield.mul(ja, jb))
    want = [field.mul(x, y) for x, y in zip(a, b)]
    assert list(got) == want

    got = jfield.to_ints(jfield.neg(ja))
    want = [field.neg(x) for x in a]
    assert list(got) == want


@pytest.mark.parametrize("field,jfield", CASES)
def test_pow_inv(field, jfield):
    rng = random.Random(99)
    a = [rng.randrange(1, field.MODULUS) for _ in range(32)]
    ja = jfield.from_ints(a)
    got = jfield.to_ints(jf.finv(jfield, ja))
    want = [field.inv(x) for x in a]
    assert list(got) == want

    e = rng.randrange(field.MODULUS)
    got = jfield.to_ints(jf.fpow_const(jfield, ja, e))
    want = [field.pow(x, e) for x in a]
    assert list(got) == want


@pytest.mark.parametrize("field,jfield", CASES)
def test_fsum_fdot(field, jfield):
    rng = random.Random(7)
    n = 77  # non-power-of-two
    a = [rng.randrange(field.MODULUS) for _ in range(n)]
    b = [rng.randrange(field.MODULUS) for _ in range(n)]
    ja = jfield.from_ints(a)
    jb = jfield.from_ints(b)
    got = jfield.to_ints(jf.fsum(jfield, ja, axis=0))
    assert int(got) == sum(a) % field.MODULUS
    got = jfield.to_ints(jf.fdot(jfield, ja, jb, axis=0))
    assert int(got) == sum(x * y for x, y in zip(a, b)) % field.MODULUS


@pytest.mark.parametrize("field,jfield", CASES)
def test_root_of_unity_on_device(field, jfield):
    # w^order == 1 and w^(order/2) == p-1 computed on device
    order = 1 << 16
    w = field.root_of_unity(order)
    jw = jfield.from_ints([w])
    got = jfield.to_ints(jf.fpow_const(jfield, jw, order))
    assert int(got[0]) == 1
    got = jfield.to_ints(jf.fpow_const(jfield, jw, order // 2))
    assert int(got[0]) == field.MODULUS - 1


@pytest.mark.parametrize("field,jfield", CASES)
def test_mul_fuzz_wide(field, jfield):
    rng = random.Random(4321)
    n = 2048
    a = [rng.randrange(field.MODULUS) for _ in range(n)]
    b = [rng.randrange(field.MODULUS) for _ in range(n)]
    got = jfield.to_ints(jfield.mul(jfield.from_ints(a), jfield.from_ints(b)))
    want = [(x * y) % field.MODULUS for x, y in zip(a, b)]
    assert list(got) == want


def test_encode_decode_roundtrip():
    rng = random.Random(5)
    for field in (Field64, Field128):
        for _ in range(20):
            v = rng.randrange(field.MODULUS)
            assert field.decode(field.encode(v)) == v
        with pytest.raises(ValueError):
            field.decode(b"\xff" * field.ENCODED_SIZE)


def test_shapes_and_where():
    a = JF128.from_ints(np.arange(12).reshape(3, 4))
    b = JF128.from_ints(np.zeros((3, 4), dtype=int))
    s = JF128.add(a, b)
    assert jf.fshape(s) == (3, 4)
    m = np.array([True, False, True, False])
    w = jf.fwhere(m, a, b)
    got = JF128.to_ints(w)
    assert got[0, 0] == 0 and got[0, 1] == 0 and got[1, 2] == 6
