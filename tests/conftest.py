"""Test configuration: force the CPU backend with 8 virtual devices.

Multi-chip sharding is validated on a virtual device mesh
(xla_force_host_platform_device_count), mirroring how the driver
dry-runs the multichip path; real-TPU runs happen via bench.py.

Note: the environment preimports jax in every process (sitecustomize)
with JAX_PLATFORMS=axon, so we must override via jax.config, not just
env vars, and before any backend is initialized.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
# Persistent compilation cache: the suite jit-compiles many small
# programs; caching them across runs keeps `pytest tests/` fast.
os.environ.setdefault(
    "JAX_COMPILATION_CACHE_DIR", os.path.expanduser("~/.cache/jax_comp_cache")
)
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES", "0")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0")
# Shape-manifest hermeticity: binaries booted by tests (in-process or
# as subprocesses inheriting this env) must not read/append the
# developer's real manifest next to the compile cache — a stale
# populated manifest would make every test boot pay a prewarm pass.
import tempfile as _tempfile

os.environ.setdefault(
    "JANUS_SHAPE_MANIFEST",
    os.path.join(_tempfile.mkdtemp(prefix="janus-shapes-"), "shape_manifest.jsonl"),
)
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax

jax.config.update("jax_platforms", "cpu")
# env vars above are no-ops when sitecustomize preimported jax; the
# config route always works
jax.config.update(
    "jax_compilation_cache_dir", os.environ["JAX_COMPILATION_CACHE_DIR"]
)
jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)

# Datastore engines under test: SQLite always; Postgres when a server
# URL and psycopg are both available (the reference's datastore tests
# run against a real postgres testcontainer,
# datastore/test_util.rs:26-120). Shared by every engine-parameterized
# suite so coverage can't silently diverge between files.
import importlib.util

DATASTORE_ENGINES = ["sqlite", "pgfake"]
if os.environ.get("JANUS_TEST_DATABASE_URL") and importlib.util.find_spec("psycopg"):
    DATASTORE_ENGINES.append("postgres")

# XLA:CPU's in-process compiler state degrades after many hundreds of
# compilations in one interpreter (observed: deterministic segfault in
# backend_compile_and_load roughly two-thirds into `pytest tests/`,
# independent of which test runs there; every file passes in
# isolation). Clearing jax's tracing/executable caches between test
# modules bounds that growth — subsequent modules retrace, which the
# persistent on-disk cache keeps cheap.
import pytest


@pytest.fixture(autouse=True, scope="module")
def _clear_jax_caches_between_modules():
    yield
    jax.clear_caches()
    # lru-cached engine wrappers hold compiled callables; drop them with
    # the caches they reference
    try:
        from janus_tpu.aggregator.engine_cache import engine_cache

        engine_cache.cache_clear()
    except Exception:
        pass
