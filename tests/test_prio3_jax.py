"""End-to-end differential test: batched device Prio3 vs host oracle.

Runs the full two-party protocol (shard -> prepare_init on both sides
-> combine/decide -> aggregate -> unshard) for every circuit, with the
same seeds on host and device, and checks every intermediate value.
This is the golden-transcript strategy of the reference
(core/src/test_util/mod.rs run_vdaf; SURVEY.md section 4.3) applied
cross-implementation.
"""

import secrets

import numpy as np
import pytest

from janus_tpu.vdaf import reference as ref
from janus_tpu.vdaf.prio3_jax import (
    Prio3Batched,
    bytes_to_lane_batch,
    lanes_to_bytes,
)

CASES = [
    (ref.Count(), [0, 1, 1, 0, 1]),
    (ref.Sum(bits=8), [0, 255, 7, 200, 33]),
    (ref.SumVec(length=4, bits=4), [[0, 1, 2, 3], [15, 15, 15, 15], [5, 0, 9, 2], [1, 1, 1, 1], [0, 0, 0, 0]]),
    (ref.Histogram(length=7), [0, 6, 3, 3, 1]),
    # 29s compile on CPU; fixedpoint device/host parity runs nightly —
    # the four core families keep the differential in tier-1 (ISSUE 1)
    pytest.param(
        ref.FixedPointVec(length=3, bits=16),
        [[8192, -8192, 0], [100, -100, 12000], [0, 0, 0], [-16384, 1, 1], [4096, 4096, 4096]],
        marks=pytest.mark.slow,
    ),
]

RNG = np.random.default_rng(0xD1FF)


def det_bytes(n):
    return RNG.integers(0, 256, size=n, dtype=np.uint8).tobytes()


@pytest.mark.parametrize("circ,meas", CASES, ids=lambda c: type(c).__name__ if isinstance(c, ref.Circuit) else "")
def test_device_vs_host_full_protocol(circ, meas):
    batch = len(meas)
    host = ref.Prio3(circ)
    dev = Prio3Batched(circ)
    jf = dev.jf
    F = circ.FIELD

    verify_key = det_bytes(16)
    nonces = [det_bytes(16) for _ in range(batch)]
    rands = [det_bytes(host.rand_size) for _ in range(batch)]

    # --- host protocol run ---
    host_out = []
    for b in range(batch):
        public, (ls, hs) = host.shard(meas[b], nonces[b], rands[b])
        st0, ps0 = host.prepare_init(verify_key, 0, nonces[b], public, ls)
        st1, ps1 = host.prepare_init(verify_key, 1, nonces[b], public, hs)
        prep_msg = host.prepare_shares_to_prep([ps0, ps1])
        o0 = host.prepare_next(st0, prep_msg)
        o1 = host.prepare_next(st1, prep_msg)
        host_out.append((public, ls, hs, ps0, ps1, o0, o1))

    # --- device protocol run, same seeds ---
    inp = jf.from_ints(
        np.array([circ.encode(m) for m in meas], dtype=object)
    )
    nonce_lanes = bytes_to_lane_batch(nonces)
    n_seeds = host.rand_size // 16
    rand_lanes = np.stack(
        [bytes_to_lane_batch([r[i * 16 : (i + 1) * 16] for r in rands]) for i in range(n_seeds)],
        axis=1,
    )
    sh = dev.shard(inp, nonce_lanes, rand_lanes)

    # sharded values must match host exactly
    lm = jf.to_ints(sh["leader_meas"])
    lp = jf.to_ints(sh["leader_proof"])
    for b in range(batch):
        ls = host_out[b][1]
        assert list(lm[b]) == ls.measurement_share, f"meas share mismatch {b}"
        assert list(lp[b]) == ls.proof_share, f"proof share mismatch {b}"
        if dev.uses_joint_rand:
            got_parts = lanes_to_bytes(np.asarray(sh["public_parts"])[:, 0])[b], lanes_to_bytes(np.asarray(sh["public_parts"])[:, 1])[b]
            assert list(got_parts) == host_out[b][0], f"public share mismatch {b}"

    # leader prepare
    out0, seed0, ver0, part0 = dev.prepare_init_leader(
        verify_key, nonce_lanes, sh["public_parts"], sh["leader_meas"], sh["leader_proof"], sh["blind0"]
    )
    # helper prepare
    out1, seed1, ver1, part1 = dev.prepare_init_helper(
        verify_key, nonce_lanes, sh["public_parts"], sh["helper_seed"], sh["blind1"]
    )

    v0 = jf.to_ints(ver0)
    v1 = jf.to_ints(ver1)
    for b in range(batch):
        assert list(v0[b]) == host_out[b][3].verifier_share, f"leader verifier mismatch {b}"
        assert list(v1[b]) == host_out[b][4].verifier_share, f"helper verifier mismatch {b}"

    mask, prep_msg = dev.prep_shares_to_prep(ver0, ver1, part0, part1)
    mask0 = dev.prepare_finish(seed0, prep_msg, mask)
    mask1 = dev.prepare_finish(seed1, prep_msg, mask)
    assert np.asarray(mask0).all(), "valid reports rejected on device"
    assert np.asarray(mask1).all()

    o0 = jf.to_ints(out0)
    o1 = jf.to_ints(out1)
    for b in range(batch):
        assert list(o0[b]) == host_out[b][5], f"leader out share mismatch {b}"
        assert list(o1[b]) == host_out[b][6], f"helper out share mismatch {b}"

    # aggregate + unshard matches direct sum of measurements
    agg0 = dev.aggregate(out0, mask0)
    agg1 = dev.aggregate(out1, mask1)
    total = jf.to_ints(dev.merge_agg_shares(agg0, agg1))
    want = host.unshard(
        [[int(x) for x in jf.to_ints(agg0)], [int(x) for x in jf.to_ints(agg1)]], batch
    )
    got = circ.decode([int(x) % F.MODULUS for x in total], batch)
    assert got == want
    # semantic check against raw measurements
    if isinstance(circ, ref.Count):
        assert got == sum(meas)
    elif isinstance(circ, ref.Sum):
        assert got == sum(meas)
    elif isinstance(circ, ref.SumVec):
        assert got == [sum(col) for col in zip(*meas)]
    elif isinstance(circ, ref.Histogram):
        want_hist = [0] * circ.length
        for m in meas:
            want_hist[m] += 1
        assert got == want_hist


@pytest.mark.slow  # 38s incl teardown; reject masking is covered fast by test_failures + the coalesce window tests (ISSUE 1)
def test_invalid_reports_masked_not_fatal():
    """Tampered shares must yield False lanes, valid lanes unaffected."""
    circ = ref.Sum(bits=4)
    host = ref.Prio3(circ)
    dev = Prio3Batched(circ)
    jf = dev.jf
    batch = 4
    meas = [3, 9, 15, 0]
    verify_key = det_bytes(16)
    nonces = [det_bytes(16) for _ in range(batch)]
    rands = [det_bytes(host.rand_size) for _ in range(batch)]

    inp_rows = [circ.encode(m) for m in meas]
    # tamper report 1: break the bit encoding (2 is not a bit)
    inp_rows[1] = [2] + inp_rows[1][1:]
    inp = jf.from_ints(np.array(inp_rows, dtype=object))
    nonce_lanes = bytes_to_lane_batch(nonces)
    n_seeds = host.rand_size // 16
    rand_lanes = np.stack(
        [bytes_to_lane_batch([r[i * 16 : (i + 1) * 16] for r in rands]) for i in range(n_seeds)],
        axis=1,
    )
    sh = dev.shard(inp, nonce_lanes, rand_lanes)
    out0, seed0, ver0, part0 = dev.prepare_init_leader(
        verify_key, nonce_lanes, sh["public_parts"], sh["leader_meas"], sh["leader_proof"], sh["blind0"]
    )
    out1, seed1, ver1, part1 = dev.prepare_init_helper(
        verify_key, nonce_lanes, sh["public_parts"], sh["helper_seed"], sh["blind1"]
    )
    mask, prep_msg = dev.prep_shares_to_prep(ver0, ver1, part0, part1)
    mask = dev.prepare_finish(seed0, prep_msg, mask)
    got = list(np.asarray(mask))
    assert got == [True, False, True, True]

    # aggregate skips the masked lane
    agg = dev.merge_agg_shares(dev.aggregate(out0, mask), dev.aggregate(out1, mask))
    total = [int(x) for x in jf.to_ints(agg)]
    assert circ.decode(total, 3) == 3 + 15 + 0
