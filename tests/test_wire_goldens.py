"""Byte-exact DAP wire-conformance goldens.

Every hex string below is transcribed from the reference's own test
vectors (reference messages/src/lib.rs:2905-5019 `roundtrip_encoding`
corpus, and messages/src/taskprov.rs:470-833). These are protocol
test vectors, not code: they pin our encodings byte-equal to what the
reference (and hence any interoperating DAP-07 implementation) puts on
the wire. VERDICT r3 item #3.

Each case asserts encode(value) == bytes.fromhex(golden) AND
decode(golden) == value (full roundtrip, like the reference's
`roundtrip_encoding` helper).
"""

import pytest

from janus_tpu import messages as m
from janus_tpu.messages import taskprov as tp
from janus_tpu.messages.codec import DecodeError
from janus_tpu.vdaf.wire import PP_CONTINUE, PP_FINISH, PP_INITIALIZE, encode_pingpong


def golden(value, hex_encoding, cls=None):
    raw = value.to_bytes()
    assert raw == bytes.fromhex(hex_encoding), (
        f"encoding differs for {value!r}:\n got {raw.hex()}\nwant {hex_encoding.lower()}"
    )
    back = (cls or type(value)).from_bytes(raw)
    assert back == value, f"decode roundtrip differs for {value!r}"


# --- primitives (lib.rs roundtrip_duration/_time/_interval) ---------------


def test_duration():
    golden(m.Duration(0), "0000000000000000")
    golden(m.Duration(12345), "0000000000003039")
    golden(m.Duration(2**64 - 1), "FFFFFFFFFFFFFFFF")


def test_time():
    golden(m.Time(0), "0000000000000000")
    golden(m.Time(12345), "0000000000003039")
    golden(m.Time(2**64 - 1), "FFFFFFFFFFFFFFFF")


def test_interval():
    golden(m.Interval(m.Time(0), m.Duration(2**64 - 1)), "0000000000000000" "FFFFFFFFFFFFFFFF")
    golden(m.Interval(m.Time(54321), m.Duration(12345)), "000000000000D431" "0000000000003039")
    golden(m.Interval(m.Time(2**64 - 1), m.Duration(0)), "FFFFFFFFFFFFFFFF" "0000000000000000")
    # end overflowing u64 must be rejected on decode (lib.rs Interval::new)
    with pytest.raises(DecodeError):
        m.Interval.from_bytes(bytes.fromhex("0000000000000001" "FFFFFFFFFFFFFFFF"))


def test_batch_id():
    golden(m.BatchId(bytes(32)), "00" * 32)
    golden(
        m.BatchId(bytes(range(32))),
        "000102030405060708090A0B0C0D0E0F101112131415161718191A1B1C1D1E1F",
    )
    golden(m.BatchId(b"\xff" * 32), "FF" * 32)


def test_task_id():
    golden(m.TaskId(bytes(32)), "00" * 32)
    golden(
        m.TaskId(bytes(range(32))),
        "000102030405060708090A0B0C0D0E0F101112131415161718191A1B1C1D1E1F",
    )
    golden(m.TaskId(b"\xff" * 32), "FF" * 32)


def test_report_id():
    golden(m.ReportId(bytes(range(1, 17))), "0102030405060708090a0b0c0d0e0f10")
    golden(m.ReportId(bytes(range(16, 0, -1))), "100f0e0d0c0b0a090807060504030201")


def test_role():
    golden(m.Role.COLLECTOR, "00")
    golden(m.Role.CLIENT, "01")
    golden(m.Role.LEADER, "02")
    golden(m.Role.HELPER, "03")


def test_hpke_config_id():
    golden(m.HpkeConfigId(0), "00")
    golden(m.HpkeConfigId(10), "0A")
    golden(m.HpkeConfigId(255), "FF")


def test_hpke_algorithm_ids():
    assert m.HpkeKemId.P256_HKDF_SHA256.to_bytes(2, "big") == bytes.fromhex("0010")
    assert m.HpkeKemId.X25519_HKDF_SHA256.to_bytes(2, "big") == bytes.fromhex("0020")
    assert m.HpkeKdfId.HKDF_SHA256.to_bytes(2, "big") == bytes.fromhex("0001")
    assert m.HpkeKdfId.HKDF_SHA384.to_bytes(2, "big") == bytes.fromhex("0002")
    assert m.HpkeKdfId.HKDF_SHA512.to_bytes(2, "big") == bytes.fromhex("0003")
    assert m.HpkeAeadId.AES_128_GCM.to_bytes(2, "big") == bytes.fromhex("0001")
    assert m.HpkeAeadId.AES_256_GCM.to_bytes(2, "big") == bytes.fromhex("0002")
    assert m.HpkeAeadId.CHACHA20POLY1305.to_bytes(2, "big") == bytes.fromhex("0003")


def test_extension():
    golden(m.Extension(m.ExtensionType.TBD, b""), "0000" "0000")
    golden(m.Extension(m.ExtensionType.TBD, b"0123"), "0000" "0004" "30313233")


def test_hpke_ciphertext():
    golden(
        m.HpkeCiphertext(m.HpkeConfigId(10), b"0123", b"4567"),
        "0A" "0004" "30313233" "00000004" "34353637",
    )
    golden(
        m.HpkeCiphertext(m.HpkeConfigId(12), b"01234", b"567"),
        "0C" "0005" "3031323334" "00000003" "353637",
    )


def test_hpke_config():
    golden(
        m.HpkeConfig(
            m.HpkeConfigId(12),
            m.HpkeKemId.P256_HKDF_SHA256,
            m.HpkeKdfId.HKDF_SHA512,
            m.HpkeAeadId.AES_256_GCM,
            b"",
        ),
        "0C" "0010" "0003" "0002" "0000",
    )
    golden(
        m.HpkeConfig(
            m.HpkeConfigId(23),
            m.HpkeKemId.X25519_HKDF_SHA256,
            m.HpkeKdfId.HKDF_SHA256,
            m.HpkeAeadId.CHACHA20POLY1305,
            b"0123456789abcdef",
        ),
        "17" "0020" "0001" "0003" "0010" "30313233343536373839616263646566",
    )


def test_decode_unknown_hpke_algorithms():
    # lib.rs decode_unknown_hpke_algorithms: unknown kem/kdf/aead ids reject
    for hexstr in (
        "0C" "9999" "0003" "0002" "0000",
        "0C" "0010" "9999" "0002" "0000",
        "0C" "0010" "0003" "9999" "0000",
    ):
        with pytest.raises(DecodeError):
            m.HpkeConfig.from_bytes(bytes.fromhex(hexstr))


# --- report structs -------------------------------------------------------


def test_report_metadata():
    golden(
        m.ReportMetadata(m.ReportId(bytes(range(1, 17))), m.Time(12345)),
        "0102030405060708090A0B0C0D0E0F10" "0000000000003039",
    )
    golden(
        m.ReportMetadata(m.ReportId(bytes(range(16, 0, -1))), m.Time(54321)),
        "100F0E0D0C0B0A090807060504030201" "000000000000D431",
    )


def test_plaintext_input_share():
    golden(
        m.PlaintextInputShare((), b"0123"),
        "0000" "00000004" "30313233",
    )
    golden(
        m.PlaintextInputShare((m.Extension(m.ExtensionType.TBD, b"0123"),), b"4567"),
        "0008" "0000" "0004" "30313233" "00000004" "34353637",
    )


LEADER_CT = m.HpkeCiphertext(m.HpkeConfigId(42), b"012345", b"543210")
HELPER_CT = m.HpkeCiphertext(m.HpkeConfigId(13), b"abce", b"abfd")
LEADER_CT_HEX = "2A" "0006" "303132333435" "00000006" "353433323130"
HELPER_CT_HEX = "0D" "0004" "61626365" "00000004" "61626664"


def test_report():
    golden(
        m.Report(
            m.ReportMetadata(m.ReportId(bytes(range(1, 17))), m.Time(12345)),
            b"",
            LEADER_CT,
            HELPER_CT,
        ),
        "0102030405060708090A0B0C0D0E0F10" "0000000000003039"
        "00000000" + LEADER_CT_HEX + HELPER_CT_HEX,
    )
    golden(
        m.Report(
            m.ReportMetadata(m.ReportId(bytes(range(16, 0, -1))), m.Time(54321)),
            b"3210",
            LEADER_CT,
            HELPER_CT,
        ),
        "100F0E0D0C0B0A090807060504030201" "000000000000D431"
        "00000004" "33323130" + LEADER_CT_HEX + HELPER_CT_HEX,
    )


# --- queries and selectors ------------------------------------------------


def test_fixed_size_query():
    golden(
        m.FixedSizeQuery(m.FixedSizeQuery.BY_BATCH_ID, m.BatchId(b"\x0a" * 32)),
        "00" + "0A" * 32,
    )
    golden(m.FixedSizeQuery(m.FixedSizeQuery.CURRENT_BATCH), "01")


def test_query():
    golden(
        m.Query.time_interval(m.Interval(m.Time(54321), m.Duration(12345))),
        "01" "000000000000D431" "0000000000003039",
    )
    golden(
        m.Query.time_interval(m.Interval(m.Time(48913), m.Duration(44721))),
        "01" "000000000000BF11" "000000000000AEB1",
    )
    golden(
        m.Query.fixed_size(m.FixedSizeQuery(m.FixedSizeQuery.BY_BATCH_ID, m.BatchId(b"\x0a" * 32))),
        "02" "00" + "0A" * 32,
    )
    golden(m.Query.fixed_size(m.FixedSizeQuery(m.FixedSizeQuery.CURRENT_BATCH)), "02" "01")


def test_collection_req():
    golden(
        m.CollectionReq(m.Query.time_interval(m.Interval(m.Time(54321), m.Duration(12345))), b""),
        "01" "000000000000D431" "0000000000003039" "00000000",
    )
    golden(
        m.CollectionReq(
            m.Query.time_interval(m.Interval(m.Time(48913), m.Duration(44721))), b"012345"
        ),
        "01" "000000000000BF11" "000000000000AEB1" "00000006" "303132333435",
    )
    golden(
        m.CollectionReq(
            m.Query.fixed_size(
                m.FixedSizeQuery(m.FixedSizeQuery.BY_BATCH_ID, m.BatchId(b"\x0a" * 32))
            ),
            b"",
        ),
        "02" "00" + "0A" * 32 + "00000000",
    )
    golden(
        m.CollectionReq(m.Query.fixed_size(m.FixedSizeQuery(m.FixedSizeQuery.CURRENT_BATCH)), b"012345"),
        "02" "01" "00000006" "303132333435",
    )


def test_partial_batch_selector():
    golden(m.PartialBatchSelector.time_interval(), "01")
    golden(m.PartialBatchSelector.fixed_size(m.BatchId(b"\x03" * 32)), "02" + "03" * 32)
    golden(m.PartialBatchSelector.fixed_size(m.BatchId(b"\x04" * 32)), "02" + "04" * 32)


def test_batch_selector():
    golden(
        m.BatchSelector.time_interval(m.Interval(m.Time(54321), m.Duration(12345))),
        "01" "000000000000D431" "0000000000003039",
    )
    golden(
        m.BatchSelector.time_interval(m.Interval(m.Time(50821), m.Duration(84354))),
        "01" "000000000000C685" "0000000000014982",
    )
    golden(m.BatchSelector.fixed_size(m.BatchId(b"\x0c" * 32)), "02" + "0C" * 32)
    golden(m.BatchSelector.fixed_size(m.BatchId(b"\x07" * 32)), "02" + "07" * 32)


SMALL_LEADER_CT = m.HpkeCiphertext(m.HpkeConfigId(10), b"0123", b"4567")
SMALL_HELPER_CT = m.HpkeCiphertext(m.HpkeConfigId(12), b"01234", b"567")
SMALL_LEADER_CT_HEX = "0A" "0004" "30313233" "00000004" "34353637"
SMALL_HELPER_CT_HEX = "0C" "0005" "3031323334" "00000003" "353637"


def test_collection():
    interval = m.Interval(m.Time(54321), m.Duration(12345))
    interval_hex = "000000000000D431" "0000000000003039"
    golden(
        m.Collection(m.PartialBatchSelector.time_interval(), 0, interval, SMALL_LEADER_CT, SMALL_HELPER_CT),
        "01" "0000000000000000" + interval_hex + SMALL_LEADER_CT_HEX + SMALL_HELPER_CT_HEX,
    )
    golden(
        m.Collection(m.PartialBatchSelector.time_interval(), 23, interval, SMALL_LEADER_CT, SMALL_HELPER_CT),
        "01" "0000000000000017" + interval_hex + SMALL_LEADER_CT_HEX + SMALL_HELPER_CT_HEX,
    )
    golden(
        m.Collection(
            m.PartialBatchSelector.fixed_size(m.BatchId(b"\x03" * 32)),
            0,
            interval,
            SMALL_LEADER_CT,
            SMALL_HELPER_CT,
        ),
        "02" + "03" * 32 + "0000000000000000" + interval_hex + SMALL_LEADER_CT_HEX + SMALL_HELPER_CT_HEX,
    )
    golden(
        m.Collection(
            m.PartialBatchSelector.fixed_size(m.BatchId(b"\x04" * 32)),
            23,
            interval,
            SMALL_LEADER_CT,
            SMALL_HELPER_CT,
        ),
        "02" + "04" * 32 + "0000000000000017" + interval_hex + SMALL_LEADER_CT_HEX + SMALL_HELPER_CT_HEX,
    )


# --- aggregation sub-protocol ---------------------------------------------

RS1 = m.ReportShare(
    m.ReportMetadata(m.ReportId(bytes(range(1, 17))), m.Time(54321)), b"", LEADER_CT
)
RS1_HEX = (
    "0102030405060708090A0B0C0D0E0F10" "000000000000D431" "00000000" + LEADER_CT_HEX
)
RS2 = m.ReportShare(
    m.ReportMetadata(m.ReportId(bytes(range(16, 0, -1))), m.Time(73542)), b"0123", HELPER_CT
)
RS2_HEX = (
    "100F0E0D0C0B0A090807060504030201" "0000000000011F46" "00000004" "30313233" + HELPER_CT_HEX
)

PP_INIT_MSG = encode_pingpong(PP_INITIALIZE, None, b"012345")
PP_INIT_MSG_HEX = "00" "00000006" "303132333435"
PP_FINISH_MSG = encode_pingpong(PP_FINISH, b"", None)
PP_FINISH_MSG_HEX = "02" "00000000"


def test_report_share():
    golden(RS1, RS1_HEX)
    golden(RS2, RS2_HEX)


def test_prepare_init():
    golden(m.PrepareInit(RS1, PP_INIT_MSG), RS1_HEX + PP_INIT_MSG_HEX)
    golden(m.PrepareInit(RS2, PP_FINISH_MSG), RS2_HEX + PP_FINISH_MSG_HEX)


def test_prepare_resp():
    golden(
        m.PrepareResp(
            m.ReportId(bytes(range(1, 17))),
            m.PrepareStepResult.cont(encode_pingpong(PP_CONTINUE, b"012345", b"6789")),
        ),
        "0102030405060708090A0B0C0D0E0F10" "00"
        "01" "00000006" "303132333435" "00000004" "36373839",
    )
    golden(
        m.PrepareResp(m.ReportId(bytes(range(16, 0, -1))), m.PrepareStepResult.finished()),
        "100F0E0D0C0B0A090807060504030201" "01",
    )
    golden(
        m.PrepareResp(
            m.ReportId(b"\xff" * 16), m.PrepareStepResult.reject(m.PrepareError.VDAF_PREP_ERROR)
        ),
        "FFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFF" "02" "05",
    )


def test_prepare_error():
    for err, hexstr in [
        (m.PrepareError.BATCH_COLLECTED, "00"),
        (m.PrepareError.REPORT_REPLAYED, "01"),
        (m.PrepareError.REPORT_DROPPED, "02"),
        (m.PrepareError.HPKE_UNKNOWN_CONFIG_ID, "03"),
        (m.PrepareError.HPKE_DECRYPT_ERROR, "04"),
        (m.PrepareError.VDAF_PREP_ERROR, "05"),
    ]:
        assert err.to_bytes() == bytes.fromhex(hexstr)


def test_aggregation_job_initialize_req():
    prep_inits = (m.PrepareInit(RS1, PP_INIT_MSG), m.PrepareInit(RS2, PP_FINISH_MSG))
    body = "0000006E" + RS1_HEX + PP_INIT_MSG_HEX + RS2_HEX + PP_FINISH_MSG_HEX
    golden(
        m.AggregationJobInitializeReq(b"012345", m.PartialBatchSelector.time_interval(), prep_inits),
        "00000006" "303132333435" "01" + body,
    )
    golden(
        m.AggregationJobInitializeReq(
            b"012345", m.PartialBatchSelector.fixed_size(m.BatchId(b"\x02" * 32)), prep_inits
        ),
        "00000006" "303132333435" "02" + "02" * 32 + body,
    )


def test_aggregation_job_continue_req():
    golden(
        m.AggregationJobContinueReq(
            m.AggregationJobStep(42405),
            (
                m.PrepareContinue(m.ReportId(bytes(range(1, 17))), PP_INIT_MSG),
                m.PrepareContinue(m.ReportId(bytes(range(16, 0, -1))), PP_INIT_MSG),
            ),
        ),
        "A5A5" "00000036"
        "0102030405060708090A0B0C0D0E0F10" + PP_INIT_MSG_HEX
        + "100F0E0D0C0B0A090807060504030201" + PP_INIT_MSG_HEX,
    )


def test_aggregation_job_resp():
    golden(
        m.AggregationJobResp(
            (
                m.PrepareResp(
                    m.ReportId(bytes(range(1, 17))),
                    m.PrepareStepResult.cont(encode_pingpong(PP_CONTINUE, b"01234", b"56789")),
                ),
                m.PrepareResp(m.ReportId(bytes(range(16, 0, -1))), m.PrepareStepResult.finished()),
            )
        ),
        "00000035"
        "0102030405060708090A0B0C0D0E0F10" "00"
        "01" "00000005" "3031323334" "00000005" "3536373839"
        "100F0E0D0C0B0A090807060504030201" "01",
    )


def test_aggregate_share_req():
    golden(
        m.AggregateShareReq(
            m.BatchSelector.time_interval(m.Interval(m.Time(54321), m.Duration(12345))),
            b"",
            439,
            m.ReportIdChecksum(bytes(32)),
        ),
        "01" "000000000000D431" "0000000000003039" "00000000" "00000000000001B7" + "00" * 32,
    )
    golden(
        m.AggregateShareReq(
            m.BatchSelector.time_interval(m.Interval(m.Time(50821), m.Duration(84354))),
            b"012345",
            8725,
            m.ReportIdChecksum(b"\xff" * 32),
        ),
        "01" "000000000000C685" "0000000000014982" "00000006" "303132333435"
        "0000000000002215" + "FF" * 32,
    )
    golden(
        m.AggregateShareReq(
            m.BatchSelector.fixed_size(m.BatchId(b"\x0c" * 32)),
            b"",
            439,
            m.ReportIdChecksum(bytes(32)),
        ),
        "02" + "0C" * 32 + "00000000" "00000000000001B7" + "00" * 32,
    )
    golden(
        m.AggregateShareReq(
            m.BatchSelector.fixed_size(m.BatchId(b"\x07" * 32)),
            b"012345",
            8725,
            m.ReportIdChecksum(b"\xff" * 32),
        ),
        "02" + "07" * 32 + "00000006" "303132333435" "0000000000002215" + "FF" * 32,
    )


def test_aggregate_share():
    golden(m.AggregateShare(SMALL_LEADER_CT), SMALL_LEADER_CT_HEX)
    golden(m.AggregateShare(SMALL_HELPER_CT), SMALL_HELPER_CT_HEX)


def test_input_share_aad():
    golden(
        m.InputShareAad(
            m.TaskId(b"\x0c" * 32),
            m.ReportMetadata(m.ReportId(bytes(range(1, 17))), m.Time(54321)),
            b"0123",
        ),
        "0C" * 32 + "0102030405060708090A0B0C0D0E0F10" "000000000000D431" "00000004" "30313233",
    )


def test_aggregate_share_aad():
    golden(
        m.AggregateShareAad(
            m.TaskId(b"\x0c" * 32),
            bytes([0, 1, 2, 3]),
            m.BatchSelector.time_interval(m.Interval(m.Time(54321), m.Duration(12345))),
        ),
        "0C" * 32 + "00000004" "00010203" "01" "000000000000D431" "0000000000003039",
    )
    golden(
        m.AggregateShareAad(
            m.TaskId(bytes(32)),
            bytes([3, 2, 1, 0]),
            m.BatchSelector.fixed_size(m.BatchId(b"\x07" * 32)),
        ),
        "00" * 32 + "00000004" "03020100" "02" + "07" * 32,
    )


# --- taskprov (messages/src/taskprov.rs vectors) --------------------------


def test_dp_config():
    golden(tp.DpConfig(tp.DpMechanism.RESERVED), "00")
    golden(tp.DpConfig(tp.DpMechanism.NONE), "01")


def test_vdaf_type():
    golden(tp.VdafType.prio3_count(), "00000000")
    golden(tp.VdafType.prio3_sum(0), "00000001" "00")
    golden(tp.VdafType.prio3_sum(0x80), "00000001" "80")
    golden(tp.VdafType.prio3_sum(0xFF), "00000001" "FF")
    golden(
        tp.VdafType.prio3_histogram([0x00ABCDEF, 0x40404040, 0xDEADBEEF]),
        "00000002" "000018" "0000000000ABCDEF" "0000000040404040" "00000000DEADBEEF",
    )
    golden(
        tp.VdafType.prio3_histogram([0, 2**64 - 1]),
        "00000002" "000010" "0000000000000000" "FFFFFFFFFFFFFFFF",
    )
    golden(tp.VdafType.poplar1(0), "00001000" "0000")
    golden(tp.VdafType.poplar1(0xABAB), "00001000" "ABAB")
    golden(tp.VdafType.poplar1(0xFFFF), "00001000" "FFFF")


def test_vdaf_config():
    golden(
        tp.VdafConfig(tp.DpConfig(tp.DpMechanism.NONE), tp.VdafType.prio3_count()),
        "01" "00000000",
    )
    golden(
        tp.VdafConfig(tp.DpConfig(tp.DpMechanism.NONE), tp.VdafType.prio3_sum(0x42)),
        "01" "00000001" "42",
    )
    golden(
        tp.VdafConfig(
            tp.DpConfig(tp.DpMechanism.NONE), tp.VdafType.prio3_histogram([0xAAAAAAAA])
        ),
        "01" "00000002" "000008" "00000000AAAAAAAA",
    )
    # empty histogram buckets reject on decode
    with pytest.raises((DecodeError, ValueError)):
        tp.VdafConfig.from_bytes(bytes.fromhex("01" "00000002" "000000"))


def test_query_config():
    golden(
        tp.QueryConfig(m.Duration(0x3C), 0x40, 0x24, tp.TaskprovQueryType.TIME_INTERVAL),
        "01" "000000000000003C" "0040" "00000024",
    )
    golden(
        tp.QueryConfig(m.Duration(0), 0, 0, tp.TaskprovQueryType.FIXED_SIZE, 0),
        "02" "0000000000000000" "0000" "00000000" "00000000",
    )
    golden(
        tp.QueryConfig(m.Duration(0x3C), 0x40, 0x24, tp.TaskprovQueryType.FIXED_SIZE, 0xFAFA),
        "02" "000000000000003C" "0040" "00000024" "0000FAFA",
    )
    golden(
        tp.QueryConfig(
            m.Duration(2**64 - 1), 0xFFFF, 0xFFFFFFFF, tp.TaskprovQueryType.FIXED_SIZE, 0xFFFFFFFF
        ),
        "02" "FFFFFFFFFFFFFFFF" "FFFF" "FFFFFFFF" "FFFFFFFF",
    )


def test_task_config():
    golden(
        tp.TaskConfig(
            b"foobar",
            ("https://example.com/", "https://another.example.com/"),
            tp.QueryConfig(m.Duration(0xAAAA), 0xBBBB, 0xCCCC, tp.TaskprovQueryType.FIXED_SIZE, 0xDDDD),
            m.Time(0xEEEE),
            tp.VdafConfig(tp.DpConfig(tp.DpMechanism.NONE), tp.VdafType.prio3_count()),
        ),
        "06" "666F6F626172"
        "0034"
        "0014" "68747470733A2F2F6578616D706C652E636F6D2F"
        "001C" "68747470733A2F2F616E6F746865722E6578616D706C652E636F6D2F"
        "02" "000000000000AAAA" "BBBB" "0000CCCC" "0000DDDD"
        "000000000000EEEE"
        "01" "00000000",
    )
    golden(
        tp.TaskConfig(
            b"f",
            ("https://example.com",),
            tp.QueryConfig(m.Duration(0xAAAA), 0xBBBB, 0xCCCC, tp.TaskprovQueryType.TIME_INTERVAL),
            m.Time(0xEEEE),
            tp.VdafConfig(
                tp.DpConfig(tp.DpMechanism.NONE), tp.VdafType.prio3_histogram([0xFFFF])
            ),
        ),
        "01" "66"
        "0015"
        "0013" "68747470733A2F2F6578616D706C652E636F6D"
        "01" "000000000000AAAA" "BBBB" "0000CCCC"
        "000000000000EEEE"
        "01" "00000002" "000008" "000000000000FFFF",
    )
    # empty task_info / empty aggregator_endpoints reject on decode
    tail = (
        "01" "000000000000AAAA" "BBBB" "0000CCCC"
        "000000000000EEEE"
        "01" "00000002" "000008" "000000000000FFFF"
    )
    with pytest.raises((DecodeError, ValueError)):
        tp.TaskConfig.from_bytes(bytes.fromhex("00" + "0003" "0001" "68" + tail))
    with pytest.raises((DecodeError, ValueError)):
        tp.TaskConfig.from_bytes(bytes.fromhex("01" "66" + "0000" + tail))


# --- ping-pong framing itself (prio topology::ping_pong) ------------------


def test_pingpong_framing():
    assert encode_pingpong(PP_INITIALIZE, None, b"012345") == bytes.fromhex(
        "00" "00000006" "303132333435"
    )
    assert encode_pingpong(PP_CONTINUE, b"012345", b"6789") == bytes.fromhex(
        "01" "00000006" "303132333435" "00000004" "36373839"
    )
    assert encode_pingpong(PP_FINISH, b"", None) == bytes.fromhex("02" "00000000")
