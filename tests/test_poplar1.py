"""Poplar1 / IDPF tests: point-function correctness at every level,
sketch rejection of malformed keys, and the end-to-end heavy-hitters
loop (the capability the reference declares via its Poplar1 variant,
core/src/task.rs, but never exercises end-to-end)."""

import pytest

from janus_tpu.vdaf.poplar1 import (
    Idpf,
    Poplar1,
    Poplar1AggParam,
    heavy_hitters,
)
from janus_tpu.vdaf.reference import VdafError


def reconstruct(idpf, k0, k1, level, prefixes):
    F = idpf.field_at(level)
    v0 = idpf.eval_prefixes(0, k0, level, prefixes)
    v1 = idpf.eval_prefixes(1, k1, level, prefixes)
    return [[F.add(a, b) for a, b in zip(x, y)] for x, y in zip(v0, v1)]


def test_idpf_point_function_every_level():
    bits = 6
    alpha = 0b101101
    idpf = Idpf(bits)
    _, k0, k1 = idpf.gen(alpha)
    for level in range(bits):
        prefixes = list(range(1 << (level + 1)))
        vals = reconstruct(idpf, k0, k1, level, prefixes)
        on_path = alpha >> (bits - 1 - level)
        for p, v in zip(prefixes, vals):
            if p == on_path:
                assert v[0] == 1, (level, p, v)
            else:
                assert v[0] == 0, (level, p, v)


def test_idpf_shares_are_pseudorandom():
    idpf = Idpf(4)
    _, k0, k1 = idpf.gen(0b1010)
    # a single party's shares should not be trivially zero
    v0 = idpf.eval_prefixes(0, k0, 3, list(range(16)))
    assert any(x[0] != 0 for x in v0)


def test_poplar1_prefix_counts():
    bits = 4
    poplar = Poplar1(bits)
    measurements = [0b1010, 0b1010, 0b1100, 0b0001]
    keys = [poplar.shard(m)[1] for m in measurements]

    agg_param = Poplar1AggParam(1, (0b10, 0b11, 0b00))
    out = {0: [], 1: []}
    for k0, k1 in keys:
        st0, m0 = poplar.prepare_init(0, k0, agg_param)
        st1, m1 = poplar.prepare_init(1, k1, agg_param)
        out[0].append(poplar.prepare_finish(st0, [m0, m1]))
        out[1].append(poplar.prepare_finish(st1, [m0, m1]))
    counts = poplar.unshard(
        agg_param,
        [poplar.aggregate(agg_param, out[0]), poplar.aggregate(agg_param, out[1])],
    )
    # prefixes of length 2: 10 matches 1010,1010; 11 matches 1100; 00 matches 0001
    assert counts == [2, 1, 1]


def test_poplar1_sketch_rejects_tampered_key():
    poplar = Poplar1(3)
    _, (k0, k1) = poplar.shard(0b101)
    agg_param = Poplar1AggParam(2, tuple(range(8)))
    st0, m0 = poplar.prepare_init(0, k0, agg_param)
    st1, m1 = poplar.prepare_init(1, k1, agg_param)
    # tamper with one party's sketch share
    m1 = [st1.field.add(m1[0], 1)]
    with pytest.raises(VdafError):
        poplar.prepare_finish(st0, [m0, m1])


def test_poplar1_agg_param_round_trip():
    ap = Poplar1AggParam(7, (1, 5, 255, 2**100))
    assert Poplar1AggParam.decode(ap.encode()) == ap


def test_heavy_hitters_loop():
    bits = 5
    poplar = Poplar1(bits)
    population = [0b10110] * 5 + [0b00111] * 4 + [0b10000] * 1 + [0b11111] * 2
    keys = [poplar.shard(m)[1] for m in population]
    k0s = [k[0] for k in keys]
    k1s = [k[1] for k in keys]
    heavy = heavy_hitters(poplar, k0s, k1s, threshold=3)
    assert sorted(heavy) == sorted([0b10110, 0b00111])
