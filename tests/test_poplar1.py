"""Poplar1 / IDPF tests: point-function correctness at every level,
sketch rejection of malformed keys, and the end-to-end heavy-hitters
loop (the capability the reference declares via its Poplar1 variant,
core/src/task.rs, but never exercises end-to-end)."""

import pytest

from janus_tpu.vdaf.poplar1 import (
    Idpf,
    Poplar1,
    Poplar1AggParam,
    heavy_hitters,
)
from janus_tpu.vdaf.reference import VdafError


def reconstruct(idpf, k0, k1, level, prefixes):
    F = idpf.field_at(level)
    v0 = idpf.eval_prefixes(0, k0, level, prefixes)
    v1 = idpf.eval_prefixes(1, k1, level, prefixes)
    return [[F.add(a, b) for a, b in zip(x, y)] for x, y in zip(v0, v1)]


def test_idpf_point_function_every_level():
    bits = 6
    alpha = 0b101101
    idpf = Idpf(bits)
    _, k0, k1 = idpf.gen(alpha)
    for level in range(bits):
        prefixes = list(range(1 << (level + 1)))
        vals = reconstruct(idpf, k0, k1, level, prefixes)
        on_path = alpha >> (bits - 1 - level)
        for p, v in zip(prefixes, vals):
            if p == on_path:
                assert v[0] == 1, (level, p, v)
            else:
                assert v[0] == 0, (level, p, v)


def test_idpf_shares_are_pseudorandom():
    idpf = Idpf(4)
    _, k0, k1 = idpf.gen(0b1010)
    # a single party's shares should not be trivially zero
    v0 = idpf.eval_prefixes(0, k0, 3, list(range(16)))
    assert any(x[0] != 0 for x in v0)


def run_prepare(poplar, k0, k1, agg_param, nonce=b"\x07" * 16, vk=b"\x01" * 16):
    """Both aggregators through the full 2-round sketch."""
    st0, m0 = poplar.prepare_init(0, k0, agg_param, vk, nonce)
    st1, m1 = poplar.prepare_init(1, k1, agg_param, vk, nonce)
    st0, s0 = poplar.prepare_next(st0, [m0, m1])
    st1, s1 = poplar.prepare_next(st1, [m0, m1])
    return poplar.prepare_finish(st0, [s0, s1]), poplar.prepare_finish(st1, [s0, s1])


def test_poplar1_prefix_counts():
    bits = 4
    poplar = Poplar1(bits)
    measurements = [0b1010, 0b1010, 0b1100, 0b0001]
    keys = [poplar.shard(m)[1] for m in measurements]

    agg_param = Poplar1AggParam(1, (0b00, 0b10, 0b11))
    out = {0: [], 1: []}
    for k0, k1 in keys:
        o0, o1 = run_prepare(poplar, k0, k1, agg_param)
        out[0].append(o0)
        out[1].append(o1)
    counts = poplar.unshard(
        agg_param,
        [poplar.aggregate(agg_param, out[0]), poplar.aggregate(agg_param, out[1])],
    )
    # prefixes of length 2: 00 matches 0001; 10 matches 1010,1010; 11 matches 1100
    assert counts == [1, 2, 1]


def test_poplar1_sketch_rejects_tampered_key():
    poplar = Poplar1(3)
    _, (k0, k1) = poplar.shard(0b101)
    agg_param = Poplar1AggParam(2, tuple(range(8)))
    vk, nonce = b"\x01" * 16, b"\x07" * 16
    st0, m0 = poplar.prepare_init(0, k0, agg_param, vk, nonce)
    st1, m1 = poplar.prepare_init(1, k1, agg_param, vk, nonce)
    # tamper with one party's round-1 sketch share
    m1 = [st1.field.add(m1[0], 1), m1[1]]
    st0, s0 = poplar.prepare_next(st0, [m0, m1])
    st1, s1 = poplar.prepare_next(st1, [m0, m1])
    with pytest.raises(VdafError):
        poplar.prepare_finish(st0, [s0, s1])


def test_quadratic_sketch_rejects_forged_sum_preserving_vector():
    """The VERDICT r3 attack: a y vector like (2, -1, 0, ...) passes a
    bare sum(y)==1 check but is NOT one-hot; the quadratic sketch must
    reject it (sigma = 2(r_0 - r_1)^2 != 0 w.h.p.)."""
    import secrets as _secrets

    from janus_tpu.vdaf.poplar1 import IdpfKey, corr_from_seed, verify_rand

    bits = 3
    poplar = Poplar1(bits)
    agg_param = Poplar1AggParam(1, (0, 1, 2, 3))
    F = poplar.idpf.field_at(agg_param.level)
    vk, nonce = b"\x05" * 16, b"\x09" * 16

    # adversarial client: skip the IDPF and directly fabricate shares of
    # y = (2, p-1, 0, 0) — sum(y) == 1 mod p — with honest correlated
    # randomness (the client controls that too, but honest corr shows the
    # sketch itself does the rejecting)
    y = [2, F.MODULUS - 1, 0, 0]
    y0 = [int.from_bytes(_secrets.token_bytes(8), "big") % F.MODULUS for _ in y]
    y1 = [F.sub(v, s) for v, s in zip(y, y0)]

    corr_seed = _secrets.token_bytes(16)
    a = 12345
    b = 98765
    c = F.add(F.mul(a, a), b)
    a1, b1, c1 = corr_from_seed(bits, corr_seed, agg_param.level)
    corr0 = [(0, 0, 0)] * bits
    corr0[agg_param.level] = (F.sub(a, a1), F.sub(b, b1), F.sub(c, c1))

    r = verify_rand(bits, vk, nonce, agg_param)

    def round1(party, y_sh, a_sh, b_sh):
        z = w = 0
        for rp, yp in zip(r, y_sh):
            z = F.add(z, F.mul(rp, yp))
            w = F.add(w, F.mul(F.mul(rp, rp), yp))
        return [F.add(z, a_sh), F.add(w, b_sh)]

    from janus_tpu.vdaf.poplar1 import _PrepState

    st0 = _PrepState(F, y0, 0, corr0[agg_param.level][0], corr0[agg_param.level][2])
    st1 = _PrepState(F, y1, 1, a1, c1)
    m0 = round1(0, y0, corr0[agg_param.level][0], corr0[agg_param.level][1])
    m1 = round1(1, y1, a1, b1)
    st0, s0 = poplar.prepare_next(st0, [m0, m1])
    st1, s1 = poplar.prepare_next(st1, [m0, m1])
    with pytest.raises(VdafError):
        poplar.prepare_finish(st0, [s0, s1])
    with pytest.raises(VdafError):
        poplar.prepare_finish(st1, [s0, s1])

    # sanity: an honest one-hot vector with the same harness passes
    y = [0, 1, 0, 0]
    y0 = [int.from_bytes(_secrets.token_bytes(8), "big") % F.MODULUS for _ in y]
    y1 = [F.sub(v, s) for v, s in zip(y, y0)]
    st0 = _PrepState(F, y0, 0, corr0[agg_param.level][0], corr0[agg_param.level][2])
    st1 = _PrepState(F, y1, 1, a1, c1)
    m0 = round1(0, y0, corr0[agg_param.level][0], corr0[agg_param.level][1])
    m1 = round1(1, y1, a1, b1)
    st0, s0 = poplar.prepare_next(st0, [m0, m1])
    st1, s1 = poplar.prepare_next(st1, [m0, m1])
    assert poplar.prepare_finish(st0, [s0, s1]) == y0
    assert poplar.prepare_finish(st1, [s0, s1]) == y1


def test_poplar1_agg_param_round_trip():
    ap = Poplar1AggParam(7, (1, 5, 255, 2**100))
    assert Poplar1AggParam.decode(ap.encode()) == ap


def test_heavy_hitters_loop():
    bits = 5
    poplar = Poplar1(bits)
    population = [0b10110] * 5 + [0b00111] * 4 + [0b10000] * 1 + [0b11111] * 2
    keys = [poplar.shard(m)[1] for m in population]
    k0s = [k[0] for k in keys]
    k1s = [k[1] for k in keys]
    heavy = heavy_hitters(poplar, k0s, k1s, threshold=3)
    assert sorted(heavy) == sorted([0b10110, 0b00111])
