"""Failure-path tests: report write batching, fake-VDAF failure
injection, and job abandonment — the reference's dummy_vdaf +
TestRuntimeManager strategy (core/src/test_util/dummy_vdaf.rs,
aggregation_job_driver.rs abandon_failing_aggregation_job:3353)."""

import dataclasses
import secrets
import threading

import pytest

from janus_tpu.aggregator import Aggregator, Config
from janus_tpu.aggregator.aggregation_job_creator import (
    AggregationJobCreator,
    AggregationJobCreatorConfig,
)
from janus_tpu.aggregator.aggregation_job_driver import (
    AggregationJobDriver,
    AggregationJobDriverConfig,
)
from janus_tpu.aggregator.http_handlers import DapHttpApp, DapServer
from janus_tpu.aggregator.job_driver import JobDriver, JobDriverConfig
from janus_tpu.aggregator.report_writer import ReportWriteBatcher
from janus_tpu.client import Client, ClientParameters
from janus_tpu.core.hpke import generate_hpke_config_and_private_key
from janus_tpu.core.http_client import HttpClient
from janus_tpu.core.time_util import MockClock
from janus_tpu.datastore.models import (
    AggregationJobState,
    LeaderStoredReport,
    ReportAggregationState,
)
from janus_tpu.datastore.store import EphemeralDatastore
from janus_tpu.messages import (
    HpkeCiphertext,
    HpkeConfigId,
    ReportId,
    Role,
    Time,
)
from janus_tpu.task import QueryTypeConfig, TaskBuilder
from janus_tpu.vdaf.registry import VdafInstance


def make_report(task, when=1_600_000_000):
    return LeaderStoredReport(
        task.task_id,
        ReportId(secrets.token_bytes(16)),
        Time(when),
        b"",
        b"x",
        HpkeCiphertext(HpkeConfigId(0), b"", b""),
    )


@pytest.fixture()
def ds():
    eph = EphemeralDatastore(clock=MockClock(Time(1_600_000_000)))
    yield eph.datastore
    eph.cleanup()


def put_task(ds, vdaf, **kw):
    task = (
        TaskBuilder(QueryTypeConfig.time_interval(), vdaf, Role.LEADER)
        .with_(min_batch_size=1, **kw)
        .build()
    )
    ds.run_tx(lambda tx: tx.put_task(task))
    return task


# --- ReportWriteBatcher ---


def test_batcher_flushes_at_max_batch_size(ds):
    task = put_task(ds, VdafInstance.count())
    batcher = ReportWriteBatcher(ds, max_batch_size=3, max_write_delay_ms=60_000)
    results = []

    def write():
        results.append(batcher.write_report(make_report(task)))

    threads = [threading.Thread(target=write) for _ in range(3)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=10)
    assert results == [True, True, True]
    total, _ = ds.run_tx(lambda tx: tx.count_client_reports_for_task(task.task_id))
    assert total == 3


def test_batcher_flushes_on_delay_and_reports_replays(ds):
    task = put_task(ds, VdafInstance.count())
    batcher = ReportWriteBatcher(ds, max_batch_size=100, max_write_delay_ms=50)
    report = make_report(task)
    assert batcher.write_report(report) is True  # flushed by the timer
    assert batcher.write_report(report) is False  # same id -> replay


def test_batcher_serial_latency_group_commit(ds):
    """A lone serial client must see ~transaction latency, not a fixed
    flush-timer delay (reference default max_upload_batch_write_delay=0,
    aggregator.rs:186-218). p50 < 20ms on this box."""
    import time as _time

    task = put_task(ds, VdafInstance.count())
    batcher = ReportWriteBatcher(ds, max_batch_size=100, max_write_delay_ms=0)
    lat = []
    for _ in range(15):
        r = make_report(task)
        t0 = _time.monotonic()
        assert batcher.write_report(r) is True
        lat.append(_time.monotonic() - t0)
    lat.sort()
    assert lat[len(lat) // 2] < 0.020, f"serial upload p50 {lat[len(lat)//2]*1e3:.1f}ms"


class _BrokenDs:
    def run_tx(self, fn, name="tx"):
        raise RuntimeError("datastore down")


def test_batcher_fans_out_errors(ds):
    task = put_task(ds, VdafInstance.count())
    batcher = ReportWriteBatcher(_BrokenDs(), max_batch_size=1, max_write_delay_ms=50)
    with pytest.raises(RuntimeError, match="datastore down"):
        batcher.write_report(make_report(task))


class _FlakyDs:
    """Fails the first `fail_n` transactions, then delegates."""

    def __init__(self, ds, fail_n=1):
        self._ds = ds
        self._fail_n = fail_n
        self._lock = threading.Lock()

    def run_tx(self, fn, name="tx"):
        with self._lock:
            if self._fail_n > 0:
                self._fail_n -= 1
                raise RuntimeError("datastore down")
        return self._ds.run_tx(fn, name)


def test_batcher_flush_error_reaches_every_waiter_then_recovers(ds):
    """One flusher-transaction failure must fan out to EVERY _Pending
    in the batch — an error, not a hang and not a false "fresh" — and
    the next flush (healthy datastore again) must commit normally."""
    task = put_task(ds, VdafInstance.count())
    flaky = _FlakyDs(ds, fail_n=1)
    batcher = ReportWriteBatcher(flaky, max_batch_size=3, max_write_delay_ms=60_000)
    outcomes = [None, None, None]

    def write(i):
        try:
            outcomes[i] = batcher.write_report(make_report(task), timeout_s=10)
        except BaseException as e:
            outcomes[i] = e

    threads = [threading.Thread(target=write, args=(i,)) for i in range(3)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=15)
    assert all(
        isinstance(o, RuntimeError) and "datastore down" in str(o) for o in outcomes
    ), outcomes
    # nothing landed from the failed transaction
    total, _ = ds.run_tx(lambda tx: tx.count_client_reports_for_task(task.task_id))
    assert total == 0
    # and the batcher recovers: the next full batch commits (3 writers
    # again so the 60s coalescing window is not what we're timing)
    outcomes[:] = [None, None, None]
    threads = [threading.Thread(target=write, args=(i,)) for i in range(3)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=15)
    assert outcomes == [True, True, True], outcomes
    total, _ = ds.run_tx(lambda tx: tx.count_client_reports_for_task(task.task_id))
    assert total == 3


def test_batcher_submit_report_callback_resolution(ds):
    """The non-blocking submit path (the ingest pipeline's commit
    stage): on_done runs after the group commit with the outcome."""
    task = put_task(ds, VdafInstance.count())
    batcher = ReportWriteBatcher(ds, max_batch_size=100, max_write_delay_ms=60_000)
    done = []
    report = make_report(task)
    p1 = batcher.submit_report(report, on_done=lambda p: done.append(("a", p.fresh, p.error)))
    p2 = batcher.submit_report(make_report(task), on_done=lambda p: done.append(("b", p.fresh, p.error)))
    batcher.flush_now()
    assert p1.event.is_set() and p2.event.is_set()
    assert done == [("a", True, None), ("b", True, None)]
    # a replayed id resolves through the callback as fresh=False
    p3 = batcher.submit_report(report, on_done=lambda p: done.append(("c", p.fresh, p.error)))
    batcher.flush_now()
    assert p3.fresh is False and done[-1] == ("c", False, None)


# --- fake VDAF failure injection, end to end ---


@pytest.mark.parametrize("kind", ["fake_fails_prep_init", "fake_fails_prep_step"])
def test_fake_vdaf_failures_fail_all_reports(kind):
    clock = MockClock(Time(1_600_000_000))
    leader_eph = EphemeralDatastore(clock=clock)
    helper_eph = EphemeralDatastore(clock=clock)
    leader_agg = Aggregator(leader_eph.datastore, clock, Config())
    helper_agg = Aggregator(helper_eph.datastore, clock, Config())
    leader_srv = DapServer(DapHttpApp(leader_agg)).start()
    helper_srv = DapServer(DapHttpApp(helper_agg)).start()
    try:
        vdaf = VdafInstance(kind)
        collector_kp = generate_hpke_config_and_private_key(config_id=200)
        leader_task = (
            TaskBuilder(QueryTypeConfig.time_interval(), vdaf, Role.LEADER)
            .with_(
                leader_aggregator_endpoint=leader_srv.url,
                helper_aggregator_endpoint=helper_srv.url,
                collector_hpke_config=collector_kp.config,
                min_batch_size=1,
            )
            .build()
        )
        helper_task = dataclasses.replace(
            leader_task,
            role=Role.HELPER,
            hpke_keys=(generate_hpke_config_and_private_key(config_id=1),),
        )
        leader_eph.datastore.run_tx(lambda tx: tx.put_task(leader_task))
        helper_eph.datastore.run_tx(lambda tx: tx.put_task(helper_task))

        http = HttpClient()
        params = ClientParameters(
            leader_task.task_id, leader_srv.url, helper_srv.url, leader_task.time_precision
        )
        client = Client.with_fetched_configs(params, vdaf, http, clock=clock)
        for m in [1, 0, 1]:
            client.upload(m)

        AggregationJobCreator(
            leader_eph.datastore, AggregationJobCreatorConfig(min_aggregation_job_size=1)
        ).run_once()
        drv = AggregationJobDriver(leader_eph.datastore, http)
        assert JobDriver(JobDriverConfig(), drv.acquirer(), drv.stepper).run_once() == 1

        jobs = leader_eph.datastore.run_tx(
            lambda tx: tx.get_aggregation_jobs_for_task(leader_task.task_id)
        )
        assert len(jobs) == 1 and jobs[0].state == AggregationJobState.FINISHED
        ras = leader_eph.datastore.run_tx(
            lambda tx: tx.get_report_aggregations_for_job(
                leader_task.task_id, jobs[0].job_id
            )
        )
        assert len(ras) == 3
        assert all(ra.state == ReportAggregationState.FAILED for ra in ras)
    finally:
        leader_srv.stop()
        helper_srv.stop()
        leader_eph.cleanup()
        helper_eph.cleanup()


# --- abandonment after repeated failures ---


def test_aggregation_job_abandoned_after_max_attempts(ds):
    task = put_task(ds, VdafInstance.count())
    report = make_report(task, 1_599_998_400)
    ds.run_tx(lambda tx: tx.put_client_report(report))
    AggregationJobCreator(ds, AggregationJobCreatorConfig(min_aggregation_job_size=1)).run_once()

    drv = AggregationJobDriver(
        ds,
        HttpClient(timeout=0.2),
        AggregationJobDriverConfig(maximum_attempts_before_failure=2),
    )

    # every step blows up mid-flight (the reference injects this with a
    # mockito 500 helper; here the read-phase stand-in is simplest)
    def boom(acquired):
        raise RuntimeError("helper unreachable")

    drv.step_aggregation_job = boom
    jd = JobDriver(JobDriverConfig(), drv.acquirer(0), drv.stepper)
    for _ in range(4):  # attempts 1,2 fail; attempt 3 crosses the limit
        jd.run_once()

    jobs = ds.run_tx(lambda tx: tx.get_aggregation_jobs_for_task(task.task_id))
    assert len(jobs) == 1 and jobs[0].state == AggregationJobState.ABANDONED
    # reports released back for a future job
    unagg = ds.run_tx(
        lambda tx: tx.get_unaggregated_client_reports_for_task(task.task_id, 10)
    )
    assert len(unagg) == 1
