"""Failpoint fault injection (janus_tpu.failpoints): spec parsing,
probability/count budgets, the disabled no-op guarantee, and the wiring
at every layer seam (HTTP client, retries, report writer, ingest
pipeline, engine dispatch). docs/ROBUSTNESS.md is the operator view."""

import time
import urllib.error

import pytest

from janus_tpu import failpoints
from janus_tpu.failpoints import FailpointError, FailpointSpecError


@pytest.fixture(autouse=True)
def _disarm():
    """Every test starts and ends disarmed — failpoints are process
    globals and a leak would fail unrelated suites."""
    failpoints.clear()
    yield
    failpoints.clear()


# ---------------------------------------------------------------------------
# spec parsing
# ---------------------------------------------------------------------------


def test_parse_issue_example_spec():
    fps = failpoints.parse_spec(
        "datastore.commit=error:0.3;helper.request=delay:2.0,count=5;engine.dispatch=oom:1"
    )
    assert fps["datastore.commit"].action == "error"
    assert fps["datastore.commit"].prob == pytest.approx(0.3)
    assert fps["helper.request"].action == "delay"
    assert fps["helper.request"].arg == pytest.approx(2.0)
    assert fps["helper.request"].prob == 1.0  # delay arg is seconds, not prob
    assert fps["helper.request"].count == 5
    assert fps["engine.dispatch"].action == "oom"
    assert fps["engine.dispatch"].prob == 1.0


def test_parse_mapping_form_and_modifiers():
    fps = failpoints.parse_spec({"a.b": "error:1.0,prob=0.5,count=2", "c.d": "crash"})
    assert fps["a.b"].prob == 0.5 and fps["a.b"].count == 2
    assert fps["c.d"].action == "crash" and fps["c.d"].prob == 1.0


@pytest.mark.parametrize(
    "bad",
    [
        "nameonly",  # no '='
        "x=explode:1",  # unknown action
        "x=error:notanumber",
        "x=error:1,frequency=2",  # unknown modifier
        "x=error:2.0",  # prob outside [0,1]
        "x=delay:1,count=-1",
    ],
)
def test_malformed_specs_fail_loudly(bad):
    with pytest.raises(FailpointSpecError):
        failpoints.parse_spec(bad)


def test_configure_from_env_precedence():
    failpoints.configure_from_env(
        default="a.a=error:1", environ={"JANUS_FAILPOINTS": "b.b=error:1"}
    )
    assert "b.b" in failpoints.status()["failpoints"]
    # empty env var explicitly disarms, overriding the YAML default
    failpoints.configure_from_env(default="a.a=error:1", environ={"JANUS_FAILPOINTS": ""})
    assert failpoints.status() == {"enabled": False}
    # absent env var falls back to the YAML value
    failpoints.configure_from_env(default="a.a=error:1", environ={})
    assert "a.a" in failpoints.status()["failpoints"]


# ---------------------------------------------------------------------------
# firing semantics
# ---------------------------------------------------------------------------


def test_disabled_is_noop_and_flag_off():
    assert failpoints.ENABLED is False
    failpoints.hit("anything.at.all")  # no raise, no sleep


def test_error_action_default_and_custom_type():
    failpoints.configure("x.y=error:1")
    with pytest.raises(FailpointError):
        failpoints.hit("x.y")
    with pytest.raises(ValueError, match="custom"):
        failpoints.hit("x.y", error_factory=lambda: ValueError("custom"))


def test_count_budget_exhausts():
    failpoints.configure("x.y=error:1,count=2")
    for _ in range(2):
        with pytest.raises(FailpointError):
            failpoints.hit("x.y")
    failpoints.hit("x.y")  # budget spent: inert
    assert failpoints.status()["failpoints"]["x.y"]["fired"] == 2


def test_after_skips_then_arms_and_composes_with_count():
    """`after=K` lets the first K hits pass, then arms; count= budgets
    the firings that follow (let two jobs land, wedge the third)."""
    failpoints.configure("x.y=error:1,after=2,count=1")
    failpoints.hit("x.y")
    failpoints.hit("x.y")  # first two hits pass clean
    with pytest.raises(FailpointError):
        failpoints.hit("x.y")
    failpoints.hit("x.y")  # count budget spent: inert again
    snap = failpoints.status()["failpoints"]["x.y"]
    assert snap["after"] == 2 and snap["hits"] == 4 and snap["fired"] == 1


def test_after_negative_rejected():
    with pytest.raises(FailpointSpecError):
        failpoints.parse_spec("x.y=error:1,after=-1")


def test_prob_zero_never_fires():
    failpoints.configure("x.y=error:0.0")
    for _ in range(50):
        failpoints.hit("x.y")


def test_delay_action_sleeps_then_continues():
    failpoints.configure("x.y=delay:0.05")
    t0 = time.monotonic()
    failpoints.hit("x.y")
    assert time.monotonic() - t0 >= 0.05


def test_timeout_action_raises_site_timeout():
    failpoints.configure("x.y=timeout:0.01")
    with pytest.raises(TimeoutError):
        failpoints.hit("x.y")


def test_hang_spec_grammar_and_budget():
    fps = failpoints.parse_spec("a.b=hang;c.d=hang:2.5,count=1")
    assert fps["a.b"].action == "hang"
    assert fps["a.b"].arg == 0.0  # default: forever (stopper-released)
    assert fps["a.b"].prob == 1.0  # arg is seconds, not probability
    assert fps["c.d"].arg == pytest.approx(2.5)
    assert fps["c.d"].count == 1
    # budgets apply like any other action: one firing, then inert
    failpoints.configure("x.y=hang:0.01,count=1")
    t0 = time.monotonic()
    failpoints.hit("x.y")
    assert time.monotonic() - t0 >= 0.01
    t0 = time.monotonic()
    failpoints.hit("x.y")  # budget spent: no park
    assert time.monotonic() - t0 < 0.01


def test_hang_bounded_parks_then_continues():
    """hang:S is a delay that models a device answering late: nothing
    is raised when the park ends."""
    failpoints.configure("x.y=hang:0.05")
    t0 = time.monotonic()
    failpoints.hit("x.y")  # no exception
    assert time.monotonic() - t0 >= 0.05


def test_hang_forever_released_by_disarm_resumes():
    """A registry reconfigure/disarm releases a forever-hang and the
    site RESUMES — the modeled device finally answered."""
    import threading

    failpoints.configure("x.y=hang")
    done = threading.Event()

    def park():
        failpoints.hit("x.y")
        done.set()

    t = threading.Thread(target=park, daemon=True)
    t.start()
    time.sleep(0.05)
    assert not done.is_set()  # genuinely parked
    failpoints.clear()
    assert done.wait(5)
    t.join(5)


def test_hang_released_by_stopper_raises():
    """The process-stopper release (release_hangs, wired to SIGTERM and
    janus_main teardown) RAISES at the site: a thread woken during
    teardown must not resume real device work while the interpreter
    finalizes underneath it (that segfaulted inside native XLA)."""
    import threading

    failpoints.configure("x.y=hang")
    outcome: dict = {}

    def park():
        try:
            failpoints.hit("x.y")
            outcome["r"] = "resumed"
        except FailpointError:
            outcome["r"] = "raised"

    t = threading.Thread(target=park, daemon=True)
    t.start()
    time.sleep(0.05)
    assert not outcome  # genuinely parked
    failpoints.release_hangs()
    t.join(5)
    assert outcome.get("r") == "raised"


def test_scoped_hit_targets_one_transaction():
    failpoints.configure("datastore.commit.step_agg_job_write=error:1")
    failpoints.hit_scoped("datastore.commit", "upload_batch")  # different scope
    with pytest.raises(FailpointError):
        failpoints.hit_scoped("datastore.commit", "step_agg_job_write")


def test_fired_counter_metric():
    from janus_tpu import metrics

    failpoints.configure("x.y=error:1")
    before = metrics.failpoints_fired_total.get(name="x.y", action="error")
    with pytest.raises(FailpointError):
        failpoints.hit("x.y")
    assert metrics.failpoints_fired_total.get(name="x.y", action="error") == before + 1


# ---------------------------------------------------------------------------
# layer wiring
# ---------------------------------------------------------------------------


def test_datastore_connect_failpoint_wiring():
    """`datastore.connect` (error/delay/timeout) fires at the _connect
    seam on EVERY checkout — cached connections included — raising the
    engine's connection-lost error type, so an outage schedule can take
    a datastore down without killing a real server. Scoped per store
    via failpoint_scope (default: the db file's basename), so one store
    of a multi-store process can go dark alone."""
    import sqlite3

    from janus_tpu.datastore.store import EphemeralDatastore

    e = EphemeralDatastore()
    other = EphemeralDatastore()
    try:
        ds = e.datastore
        ds.failpoint_scope = "connwire"
        # a count-budgeted connect storm is absorbed by run_tx's retry
        failpoints.configure("datastore.connect.connwire=error:1.0,count=2")
        assert ds.run_tx(lambda tx: tx.get_task_ids(), "t") == []
        # a full outage surfaces as the engine's connection error class
        failpoints.configure("datastore.connect.connwire=error:1.0")
        with pytest.raises(sqlite3.OperationalError) as ei:
            ds.run_tx(lambda tx: tx.get_task_ids(), "t")
        assert ds.classify_error(ei.value) == "connection"
        # the scope is honored: an unrelated store keeps working
        assert other.datastore.run_tx(lambda tx: tx.get_task_ids(), "t") == []
        # disarm = instant recovery (no dead cached connection retried into)
        failpoints.clear()
        assert ds.run_tx(lambda tx: tx.get_task_ids(), "t") == []
        # delay action: connection checkout stalls but succeeds (a slow
        # dial / saturated pooler), covered by the same seam
        failpoints.configure("datastore.connect.connwire=delay:0.05")
        t0 = time.monotonic()
        assert ds.run_tx(lambda tx: tx.get_task_ids(), "t") == []
        assert time.monotonic() - t0 >= 0.05
    finally:
        e.cleanup()
        other.cleanup()


def test_http_client_transport_error_and_stale_header_clear():
    """helper.request error raises a retryable URLError AND the
    thread-local response headers are cleared at request start, so a
    transport failure can never expose a previous response's
    Retry-After to the retry loop."""
    from janus_tpu.binary_utils import HealthServer
    from janus_tpu.core.http_client import HttpClient

    srv = HealthServer("127.0.0.1:0").start()
    try:
        http = HttpClient()
        status, _ = http.get(f"http://127.0.0.1:{srv.port}/healthz")
        assert status == 200
        assert http.last_response_headers  # populated by the real response
        failpoints.configure("helper.request=error:1,count=1")
        with pytest.raises(urllib.error.URLError):
            http.get(f"http://127.0.0.1:{srv.port}/healthz")
        assert http.last_response_headers == {}  # stale headers cleared
        status, _ = http.get(f"http://127.0.0.1:{srv.port}/healthz")
        assert status == 200  # budget spent: traffic flows again
    finally:
        srv.stop()


def test_http_error_body_read_reset_is_retryable(monkeypatch):
    """A connection reset while draining an HTTPError body surfaces as
    a retryable URLError, not a raw ConnectionResetError."""
    import email

    from janus_tpu.core.http_client import HttpClient

    class _ResettingBody:
        def read(self, amt=None):
            raise ConnectionResetError(104, "Connection reset by peer")

        def close(self):
            pass

    err = urllib.error.HTTPError(
        "http://x/", 503, "busy", email.message_from_string("Retry-After: 1\n"),
        _ResettingBody(),
    )
    # HTTPError.read delegates to the fp it was constructed with only
    # when it has one; force the delegation explicitly for this double
    monkeypatch.setattr(err, "read", _ResettingBody().read, raising=False)

    def boom(*a, **k):
        raise err

    monkeypatch.setattr(urllib.request, "urlopen", boom)
    http = HttpClient()
    with pytest.raises(urllib.error.URLError) as ei:
        http.request("GET", "http://x/")
    assert not isinstance(ei.value, urllib.error.HTTPError)
    # the retry loop treats URLError as any transport failure
    from janus_tpu.core.retries import Backoff, retry_http_request

    with pytest.raises(urllib.error.URLError):
        retry_http_request(lambda: http.request("GET", "http://x/"), Backoff.test())


def test_retry_attempt_failpoint_is_retried_and_bounded():
    """retry.attempt injects transport errors INSIDE the retry loop; a
    count budget below the backoff budget means the request still
    succeeds after the storm passes."""
    from janus_tpu.core.retries import Backoff, retry_http_request

    failpoints.configure("retry.attempt=error:1,count=2")
    calls = {"n": 0}

    def do_request():
        calls["n"] += 1
        return 200, b"ok"

    status, body = retry_http_request(do_request, Backoff.test())
    assert (status, body) == (200, b"ok")
    assert calls["n"] == 1  # two injected failures never reached do_request


def test_report_writer_flush_failure_fans_out_and_recovers():
    from janus_tpu.aggregator.report_writer import ReportWriteBatcher
    from janus_tpu.core.time_util import MockClock
    from janus_tpu.datastore.store import EphemeralDatastore
    from janus_tpu.messages import (
        HpkeCiphertext,
        HpkeConfigId,
        ReportId,
        Role,
        Time,
    )
    from janus_tpu.datastore.models import LeaderStoredReport
    from janus_tpu.task import QueryTypeConfig, TaskBuilder
    from janus_tpu.vdaf.registry import VdafInstance

    import secrets

    eph = EphemeralDatastore(clock=MockClock(Time(1_600_000_000)))
    try:
        task = (
            TaskBuilder(QueryTypeConfig.time_interval(), VdafInstance.count(), Role.LEADER)
            .with_(min_batch_size=1)
            .build()
        )
        eph.datastore.run_tx(lambda tx: tx.put_task(task))
        writer = ReportWriteBatcher(eph.datastore)

        def report():
            return LeaderStoredReport(
                task.task_id,
                ReportId(secrets.token_bytes(16)),
                Time(1_600_000_000),
                b"",
                b"x",
                HpkeCiphertext(HpkeConfigId(0), b"", b""),
            )

        failpoints.configure("report_writer.flush=error:1,count=1")
        with pytest.raises(RuntimeError, match="injected flush failure"):
            writer.write_report(report())
        # the storm passed: the writer thread survived and commits again
        assert writer.write_report(report()) is True
    finally:
        eph.cleanup()


def test_ingest_decode_stage_failure_resolves_ticket():
    from janus_tpu.ingest.pipeline import IngestPipeline

    failpoints.configure("ingest.decode=error:1,count=1")
    pipe = IngestPipeline(writer=None, decrypt_workers=1, queue_depth=4)
    try:
        ticket = pipe.submit(ta=None, clock=None, body=b"irrelevant")
        with pytest.raises(FailpointError):
            ticket.result(timeout_s=10)
        assert pipe.depth()[0] == 0  # in-flight slot released
    finally:
        pipe.close()


def test_engine_dispatch_oom_rides_recovery_path():
    """engine.dispatch=oom:1,count=1 injects a RESOURCE_EXHAUSTED that
    the EngineCache absorbs via the halved-bucket retry — the serving
    path sees a slow success, never the injected exception."""
    import numpy as np

    from janus_tpu.aggregator.engine_cache import EngineCache
    from janus_tpu.vdaf.registry import VdafInstance
    from janus_tpu.vdaf.testing import make_report_batch, random_measurements

    inst = VdafInstance.count()
    rng = np.random.default_rng(3)
    (nonce, public, meas, proof, blind0, seeds, blind1), _ = make_report_batch(
        inst, random_measurements(inst, 4, rng), seed=2
    )
    ok = np.ones(4, dtype=bool)
    eng = EngineCache(inst, bytes(range(16)))
    eng.bucket_cap = 32
    out0, seed0, ver0, part0 = eng.leader_init(nonce, public, meas, proof, blind0)
    failpoints.configure("engine.dispatch=oom:1,count=1")
    _, mask, _ = eng.helper_init(nonce, public, seeds, blind1, ver0, part0, ok)
    assert bool(mask.all())
    assert eng._host_fallback is None  # recovered by retry, not fallback
    assert failpoints.status()["failpoints"]["engine.dispatch"]["fired"] == 1


def test_engine_dispatch_hang_rides_watchdog_quarantine_path():
    """engine.dispatch=hang under an ambient deadline models the wedged
    XLA dispatch: the watchdog abandons it at the deadline, the engine
    quarantines, and DeviceHangError reaches the caller (the job
    drivers' step-back signal) instead of an unbounded park."""
    import numpy as np

    from janus_tpu.aggregator import device_watchdog
    from janus_tpu.aggregator.engine_cache import DeviceHangError, EngineCache
    from janus_tpu.core.deadline import deadline_scope
    from janus_tpu.vdaf.registry import VdafInstance
    from janus_tpu.vdaf.testing import make_report_batch, random_measurements

    inst = VdafInstance.count()
    rng = np.random.default_rng(3)
    (nonce, public, meas, proof, blind0, *_), _ = make_report_batch(
        inst, random_measurements(inst, 4, rng), seed=4
    )
    eng = EngineCache(inst, bytes(range(16)))
    eng.QUARANTINE_CANARY_DELAY_SECS = 30.0  # keep the canary out of this test
    eng.leader_init(nonce, public, meas, proof, blind0)  # compile first
    failpoints.configure("engine.dispatch=hang,count=1")
    try:
        t0 = time.monotonic()
        with deadline_scope(time.monotonic() + 0.3):
            with pytest.raises(DeviceHangError):
                eng.leader_init(nonce, public, meas, proof, blind0)
        assert time.monotonic() - t0 < 5.0  # bounded by the deadline
        assert eng._quarantined is True
    finally:
        failpoints.clear()  # unparks the abandoned worker
        time.sleep(0.05)
        device_watchdog.WATCHDOG.reset_for_tests()
