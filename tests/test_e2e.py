"""End-to-end two-aggregator protocol test, in-process over loopback HTTP.

The minimum end-to-end slice of SURVEY.md section 7: real client
sharding + HPKE, leader upload handler, aggregation job creator, the
batched leader driver stepping against a real helper HTTP handler,
collection via the collection job driver, collector decrypt + unshard.
Mirrors the reference's containerized pair test
(integration_tests/tests/janus.rs:14) at process scope.
"""

import dataclasses

import pytest

from janus_tpu.aggregator import Aggregator, Config
from janus_tpu.aggregator.aggregation_job_creator import (
    AggregationJobCreator,
    AggregationJobCreatorConfig,
)
from janus_tpu.aggregator.aggregation_job_driver import AggregationJobDriver
from janus_tpu.aggregator.collection_job_driver import CollectionJobDriver
from janus_tpu.aggregator.http_handlers import DapHttpApp, DapServer
from janus_tpu.aggregator.job_driver import JobDriver, JobDriverConfig
from janus_tpu.client import Client, ClientParameters
from janus_tpu.collector import Collector, CollectorParameters
from janus_tpu.core.auth import AuthenticationToken
from janus_tpu.core.hpke import generate_hpke_config_and_private_key
from janus_tpu.core.http_client import HttpClient
from janus_tpu.core.time_util import MockClock
from janus_tpu.datastore.store import EphemeralDatastore
from janus_tpu.messages import Duration, Interval, Query, Role, Time
from janus_tpu.task import QueryTypeConfig, TaskBuilder
from janus_tpu.vdaf.registry import VdafInstance


@pytest.fixture()
def pair():
    """A leader+helper pair on loopback HTTP with shared task config."""
    clock = MockClock(Time(1_600_000_000))
    leader_eph = EphemeralDatastore(clock=clock)
    helper_eph = EphemeralDatastore(clock=clock)
    leader_agg = Aggregator(leader_eph.datastore, clock, Config())
    helper_agg = Aggregator(helper_eph.datastore, clock, Config())
    leader_srv = DapServer(DapHttpApp(leader_agg)).start()
    helper_srv = DapServer(DapHttpApp(helper_agg)).start()
    yield {
        "clock": clock,
        "leader": leader_agg,
        "helper": helper_agg,
        "leader_srv": leader_srv,
        "helper_srv": helper_srv,
        "leader_ds": leader_eph.datastore,
        "helper_ds": helper_eph.datastore,
    }
    leader_srv.stop()
    helper_srv.stop()
    leader_eph.cleanup()
    helper_eph.cleanup()


def provision(pair, vdaf, max_batch_query_count: int = 1):
    collector_kp = generate_hpke_config_and_private_key(config_id=200)
    agg_token = AuthenticationToken.random_bearer()
    col_token = AuthenticationToken.random_bearer()
    leader_task = (
        TaskBuilder(QueryTypeConfig.time_interval(), vdaf, Role.LEADER)
        .with_(
            leader_aggregator_endpoint=pair["leader_srv"].url,
            helper_aggregator_endpoint=pair["helper_srv"].url,
            collector_hpke_config=collector_kp.config,
            aggregator_auth_token=agg_token,
            collector_auth_token=col_token,
            min_batch_size=1,
            max_batch_query_count=max_batch_query_count,
        )
        .build()
    )
    helper_task = dataclasses.replace(
        leader_task,
        role=Role.HELPER,
        hpke_keys=(generate_hpke_config_and_private_key(config_id=1),),
    )
    pair["leader_ds"].run_tx(lambda tx: tx.put_task(leader_task))
    pair["helper_ds"].run_tx(lambda tx: tx.put_task(helper_task))
    return leader_task, helper_task, collector_kp


# Every VDAF family through the full live-pair protocol (the
# reference's per-VDAF matrix, integration_tests/tests/janus.rs:14-60),
# plus a draft (VDAF-07) XOF framing column for every family: count and
# sum run the DEVICE draft engine (vdaf.draft_jax), the vector cases at
# these small sizes too — a long-stream draft task would fall back to
# the host engine (engine_cache dispatch, tested in test_xof_modes).
CASES = [
    (VdafInstance.count(), [0, 1, 1, 0, 1, 1, 1], 5),
    (VdafInstance.sum(bits=8), [3, 200, 17], 220),
    (
        VdafInstance.sum_vec(length=4, bits=4),
        [[1, 2, 3, 4], [5, 4, 3, 2], [0, 1, 0, 1]],
        [6, 7, 6, 7],
    ),
    (VdafInstance.count_vec(length=3), [[1, 0, 1], [0, 1, 1]], [1, 1, 2]),
    (VdafInstance.histogram(length=4), [0, 1, 1, 3, 2, 1, 0], [2, 3, 1, 1]),
    (
        VdafInstance.fixed_point_vec(length=2, bits=16),
        [[100, -50], [25, 75]],
        [125 / 32768, 25 / 32768],
    ),
    (VdafInstance("count", xof_mode="draft"), [1, 0, 1, 1], 3),
    (VdafInstance("sum", bits=8, xof_mode="draft"), [9, 30], 39),
    (
        VdafInstance("sumvec", bits=4, length=4, xof_mode="draft"),
        [[1, 2, 3, 4], [5, 4, 3, 2]],
        [6, 6, 6, 6],
    ),
    (VdafInstance("countvec", bits=1, length=3, xof_mode="draft"), [[1, 0, 1]], [1, 0, 1]),
    (VdafInstance("histogram", length=4, xof_mode="draft"), [0, 3, 3], [1, 0, 0, 2]),
    (
        VdafInstance("fixedpoint", bits=16, length=2, xof_mode="draft"),
        [[100, -50]],
        [100 / 32768, -50 / 32768],
    ),
]
CASE_IDS = [
    "count",
    "sum",
    "sumvec",
    "countvec",
    "histogram",
    "fixedpoint",
    "count-draft-xof",
    "sum-draft-xof",
    "sumvec-draft-xof",
    "countvec-draft-xof",
    "histogram-draft-xof",
    "fixedpoint-draft-xof",
]


# tier-1 CPU budget (ROADMAP): one live-pair round trip per XOF mode
# stays in the fast suite; the rest of the per-VDAF matrix compiles
# 40-80s apiece on CPU and runs nightly/on-chip (ISSUE 1 CI triage).
_FAST_E2E = {"count", "count-draft-xof"}
CASES = [
    pytest.param(*case, marks=() if cid in _FAST_E2E else pytest.mark.slow, id=cid)
    for case, cid in zip(CASES, CASE_IDS)
]


@pytest.mark.parametrize("vdaf,measurements,expected", CASES)
def test_full_protocol_round_trip(pair, vdaf, measurements, expected):
    leader_task, helper_task, collector_kp = provision(pair, vdaf)
    http = HttpClient()
    clock = pair["clock"]

    # --- upload over HTTP (client fetches HPKE configs from both) ---
    params = ClientParameters(
        leader_task.task_id,
        pair["leader_srv"].url,
        pair["helper_srv"].url,
        leader_task.time_precision,
    )
    client = Client.with_fetched_configs(params, vdaf, http, clock=clock)
    for m in measurements:
        client.upload(m)

    total, started = pair["leader_ds"].run_tx(
        lambda tx: tx.count_client_reports_for_task(leader_task.task_id)
    )
    assert total == len(measurements) and started == 0

    # --- create + drive aggregation jobs ---
    creator = AggregationJobCreator(
        pair["leader_ds"], AggregationJobCreatorConfig(min_aggregation_job_size=1)
    )
    assert creator.run_once() == 1

    driver = AggregationJobDriver(pair["leader_ds"], http)
    jd = JobDriver(JobDriverConfig(max_concurrent_job_workers=2), driver.acquirer(), driver.stepper)
    assert jd.run_once() == 1

    # both sides accumulated
    from janus_tpu.messages import TimeInterval as TI

    for ds, task in ((pair["leader_ds"], leader_task), (pair["helper_ds"], helper_task)):
        rows = ds.run_tx(
            lambda tx, task=task: tx.get_batch_aggregations_intersecting_interval(
                task.task_id, Interval(Time(1_599_998_400 - 3600 * 24), Duration(3600 * 100))
            )
        )
        assert sum(r.report_count for r in rows) == len(measurements)

    # --- collect ---
    start = Time(clock.now().seconds).to_batch_interval_start(leader_task.time_precision)
    query = Query.time_interval(
        Interval(Time(start.seconds - 3600), Duration(2 * 3600))
    )
    collector = Collector(
        CollectorParameters(
            leader_task.task_id,
            pair["leader_srv"].url,
            leader_task.collector_auth_token,
            collector_kp,
        ),
        vdaf,
        http,
    )
    job_id = collector.start_collection(query)

    cdriver = CollectionJobDriver(pair["leader_ds"], http)
    cjd = JobDriver(JobDriverConfig(max_concurrent_job_workers=1), cdriver.acquirer(), cdriver.stepper)
    assert cjd.run_once() == 1

    result = collector.poll_once(job_id, query)
    assert result.report_count == len(measurements)
    if vdaf.kind == "fixedpoint":
        assert result.aggregate_result == pytest.approx(expected)
    else:
        assert result.aggregate_result == expected


def test_upload_rejections(pair):
    vdaf = VdafInstance.count()
    leader_task, _, _ = provision(pair, vdaf)
    http = HttpClient()
    clock = pair["clock"]
    params = ClientParameters(
        leader_task.task_id,
        pair["leader_srv"].url,
        pair["helper_srv"].url,
        leader_task.time_precision,
    )
    client = Client.with_fetched_configs(params, vdaf, http, clock=clock)

    # replayed report id -> silent success (client retries are normal;
    # reference upload dedup answers 201 on the duplicate)
    report = client.prepare_report(1)
    for expected_status in (201, 201):
        status, body = http.put(
            params.upload_uri(),
            report.to_bytes(),
            {"Content-Type": "application/dap-report"},
        )
        assert status == expected_status, body

    # report from the future -> reportTooEarly problem
    future = client.prepare_report(1, when=clock.now().add(Duration(7200)))
    status, body = http.put(
        params.upload_uri(), future.to_bytes(), {"Content-Type": "application/dap-report"}
    )
    assert status == 400 and b"reportTooEarly" in body

    # unknown task -> unrecognizedTask
    import base64

    bogus = base64.urlsafe_b64encode(b"\x99" * 32).decode().rstrip("=")
    status, body = http.put(
        pair["leader_srv"].url.rstrip("/") + f"/tasks/{bogus}/reports",
        report.to_bytes(),
        {"Content-Type": "application/dap-report"},
    )
    assert status == 400 and b"unrecognizedTask" in body


def test_helper_auth_and_idempotency(pair):
    """Bad auth rejected; duplicate init with same body returns same resp."""
    vdaf = VdafInstance.count()
    leader_task, helper_task, _ = provision(pair, vdaf)
    http = HttpClient()
    clock = pair["clock"]
    params = ClientParameters(
        leader_task.task_id,
        pair["leader_srv"].url,
        pair["helper_srv"].url,
        leader_task.time_precision,
    )
    client = Client.with_fetched_configs(params, vdaf, http, clock=clock)
    for m in (1, 0, 1):
        client.upload(m)
    AggregationJobCreator(
        pair["leader_ds"], AggregationJobCreatorConfig(min_aggregation_job_size=1)
    ).run_once()

    # drive once to produce a real init request via a capturing client
    captured = {}

    class CapturingHttp(HttpClient):
        def put(self, url, body, headers=None, timeout=None):
            if "aggregation_jobs" in url:
                captured["url"] = url
                captured["body"] = body
                captured["headers"] = headers
            return super().put(url, body, headers, timeout=timeout)

    driver = AggregationJobDriver(pair["leader_ds"], CapturingHttp())
    jd = JobDriver(JobDriverConfig(), driver.acquirer(), driver.stepper)
    assert jd.run_once() == 1
    assert "body" in captured

    # replay the identical init request: identical response, no double count
    s1, b1 = http.put(captured["url"], captured["body"], captured["headers"])
    assert s1 == 200
    rows = pair["helper_ds"].run_tx(
        lambda tx: tx.get_batch_aggregations_intersecting_interval(
            helper_task.task_id, Interval(Time(0), Duration(1 << 40))
        )
    )
    assert sum(r.report_count for r in rows) == 3  # not 6

    # same job id, different body -> invalidMessage
    s2, b2 = http.put(captured["url"], captured["body"][:-1] + b"\x00", captured["headers"])
    assert s2 == 400 and b"invalidMessage" in b2

    # bad auth -> unauthorizedRequest
    bad_headers = dict(captured["headers"])
    bad_headers["Authorization"] = "Bearer wrong"
    s3, b3 = http.put(captured["url"], captured["body"], bad_headers)
    assert s3 == 400 and b"unauthorizedRequest" in b3


@pytest.mark.slow  # 36s live-pair round trip; fixed-size packing is covered fast in test_batch_creator (ISSUE 1 CI triage)
def test_fixed_size_current_batch_round_trip(pair):
    """Fixed-size task: packing to max_batch_size, current-batch
    collection consuming batches fullest-first (reference
    batch_creator.rs + fixed-size CollectableQueryType)."""
    import janus_tpu.messages as m

    vdaf = VdafInstance.histogram(length=3)
    collector_kp = generate_hpke_config_and_private_key(config_id=200)
    leader_task = (
        TaskBuilder(QueryTypeConfig.fixed_size(max_batch_size=4), vdaf, Role.LEADER)
        .with_(
            leader_aggregator_endpoint=pair["leader_srv"].url,
            helper_aggregator_endpoint=pair["helper_srv"].url,
            collector_hpke_config=collector_kp.config,
            min_batch_size=1,
        )
        .build()
    )
    helper_task = dataclasses.replace(
        leader_task,
        role=Role.HELPER,
        hpke_keys=(generate_hpke_config_and_private_key(config_id=1),),
    )
    pair["leader_ds"].run_tx(lambda tx: tx.put_task(leader_task))
    pair["helper_ds"].run_tx(lambda tx: tx.put_task(helper_task))

    http = HttpClient()
    params = ClientParameters(
        leader_task.task_id,
        pair["leader_srv"].url,
        pair["helper_srv"].url,
        leader_task.time_precision,
    )
    client = Client.with_fetched_configs(params, vdaf, http, clock=pair["clock"])
    for meas in [0, 1, 1, 2, 2, 2]:
        client.upload(meas)

    AggregationJobCreator(
        pair["leader_ds"], AggregationJobCreatorConfig(min_aggregation_job_size=1)
    ).run_once()
    drv = AggregationJobDriver(pair["leader_ds"], http)
    JobDriver(JobDriverConfig(), drv.acquirer(), drv.stepper).run_once()

    collector = Collector(
        CollectorParameters(
            leader_task.task_id,
            pair["leader_srv"].url,
            leader_task.collector_auth_token,
            collector_kp,
        ),
        vdaf,
        http,
    )
    cdrv = CollectionJobDriver(pair["leader_ds"], http)
    query = Query.fixed_size(m.FixedSizeQuery(m.FixedSizeQuery.CURRENT_BATCH))

    job1 = collector.start_collection(query)
    JobDriver(JobDriverConfig(), cdrv.acquirer(), cdrv.stepper).run_once()
    res1 = collector.poll_once(job1, query)
    assert res1.report_count == 4
    assert res1.partial_batch_selector is not None

    job2 = collector.start_collection(query)
    JobDriver(JobDriverConfig(), cdrv.acquirer(), cdrv.stepper).run_once()
    res2 = collector.poll_once(job2, query)
    assert res2.report_count == 2
    assert res2.partial_batch_selector.batch_id != res1.partial_batch_selector.batch_id

    combined = [a + b for a, b in zip(res1.aggregate_result, res2.aggregate_result)]
    assert combined == [1, 2, 3]
