"""Durable upload spill journal (janus_tpu/ingest/journal.py) and the
ReportWriteBatcher spill path (docs/ROBUSTNESS.md "Datastore outages").

The contract under test: 201 ⇒ durably written — when the datastore is
unreachable the ack may rest on the journal's fsync, and replay after
recovery lands every journaled report exactly once (report-id dedup
makes duplicates replayed-ok). The journal is bounded (full ⇒ 503
shed), torn tails from a crash mid-append are tolerated, sealed-segment
corruption is loud, and while the datastore is healthy the armed
journal performs ZERO fsyncs (the hot path is unchanged).
"""

import os
import time

import pytest

from janus_tpu import failpoints, metrics
from janus_tpu.aggregator.report_writer import ReportWriteBatcher
from janus_tpu.datastore.models import LeaderStoredReport
from janus_tpu.datastore.store import EphemeralDatastore
from janus_tpu.ingest.admission import ShedError
from janus_tpu.ingest.journal import JournalFull, JournalReplayer, UploadJournal
from janus_tpu.messages import HpkeCiphertext, HpkeConfigId, ReportId, TaskId, Time


@pytest.fixture(autouse=True)
def _clean_failpoints():
    failpoints.clear()
    yield
    failpoints.clear()


@pytest.fixture
def eph():
    e = EphemeralDatastore()
    yield e
    e.cleanup()


def mkreport(i: int, share: bytes = b"secret-share") -> LeaderStoredReport:
    return LeaderStoredReport(
        TaskId(bytes([i % 256]) * 32),
        ReportId(i.to_bytes(16, "big")),
        Time(1_600_000_000 + i),
        b"public" + bytes([i % 256]),
        share,
        HpkeCiphertext(HpkeConfigId(7), b"ek", b"ct" * 4),
    )


def db_report_count(ds) -> int:
    return ds.run_tx(
        lambda tx: tx._c.execute("SELECT COUNT(*) FROM client_reports").fetchone()[0],
        "count",
    )


# ---------------------------------------------------------------------------
# journal core
# ---------------------------------------------------------------------------


def test_append_read_roundtrip_encrypted_at_rest(tmp_path, eph):
    j = UploadJournal(str(tmp_path / "j"), eph.datastore.crypter)
    reports = [mkreport(i, share=b"PLAINTEXT-SHARE-%d" % i) for i in range(5)]
    j.append_batch(reports)
    assert j.fsyncs == 1  # one fsync per batch, not per report
    assert j.depth()[0] == 5
    j.seal_active()
    (seq,) = j.sealed_segments()
    rows, reason = j.read_segment(seq)
    assert reason == "clean"
    assert [r.report_id.data for r in rows] == [r.report_id.data for r in reports]
    assert rows[0].leader_input_share == b"PLAINTEXT-SHARE-0"
    assert rows[0].public_share == reports[0].public_share
    assert rows[0].helper_encrypted_input_share.to_bytes() == reports[
        0
    ].helper_encrypted_input_share.to_bytes()
    # encrypted at rest: the plaintext share never touches disk
    raw = open(j._seg_path(seq), "rb").read()
    assert b"PLAINTEXT-SHARE" not in raw


def test_torn_tail_tolerated_on_crash_recovery(tmp_path, eph):
    """A crash mid-append leaves a truncated tail frame; those rows
    were never acked (the fsync hadn't returned), so boot recovery
    keeps the valid prefix and replay may truncate the segment."""
    d = str(tmp_path / "j")
    j = UploadJournal(d, eph.datastore.crypter)
    j.append_batch([mkreport(i) for i in range(3)])
    j.close()  # crash: segment left unsealed on disk
    path = j._seg_path(1)
    with open(path, "ab") as f:
        f.write(b"\x40\x00\x00\x00\xde\xad")  # header claims 64B, file ends
    j2 = UploadJournal(d, eph.datastore.crypter)
    (seq,) = j2.sealed_segments()
    rows, reason = j2.read_segment(seq)
    assert reason == "truncated"
    assert len(rows) == 3  # the acked frames survive, the torn tail is dropped
    # a torn-crash segment is NOT corruption: it drains + truncates
    w = ReportWriteBatcher(eph.datastore, journal=j2)
    r = JournalReplayer(j2, w, interval_s=60)
    assert r.drain_once() == 3
    assert j2.quarantined == 0
    assert j2.depth()[0] == 0
    w.close()


def test_double_crash_torn_segments_both_replayed(tmp_path, eph):
    """Two crashes in a row (outage outlives a process twice) leave TWO
    torn segments; both valid prefixes must replay — neither may be
    mistaken for corruption and quarantined away from auto-replay."""
    d = str(tmp_path / "j")
    j = UploadJournal(d, eph.datastore.crypter)
    j.append_batch([mkreport(i) for i in range(2)])
    j.close()
    with open(j._seg_path(1), "ab") as f:
        f.write(b"\x10\x00\x00\x00")  # crash 1: torn tail
    j2 = UploadJournal(d, eph.datastore.crypter)
    j2.append_batch([mkreport(10 + i) for i in range(2)])
    j2.close()
    with open(j2._seg_path(2), "ab") as f:
        f.write(b"\x10\x00\x00\x00")  # crash 2: torn tail again
    j3 = UploadJournal(d, eph.datastore.crypter)
    assert j3.depth()[0] == 4 and j3.quarantined == 0
    w = ReportWriteBatcher(eph.datastore, journal=j3)
    r = JournalReplayer(j3, w, interval_s=60)
    assert r.drain_once() == 4
    assert j3.depth()[0] == 0 and j3.quarantined == 0
    assert db_report_count(eph.datastore) == 4
    w.close()


def test_mid_segment_crc_damage_prefix_replayed_then_quarantined(tmp_path, eph):
    """CRC damage inside a sealed segment: the valid prefix still
    replays, but the file is quarantined (bytes preserved as .corrupt)
    instead of truncated — frames past the damage may be acked data —
    and later segments still drain."""
    ds = eph.datastore
    j = UploadJournal(str(tmp_path / "j"), ds.crypter)
    j.append_batch([mkreport(i) for i in range(3)])
    j.seal_active()
    j.append_batch([mkreport(10 + i) for i in range(2)])
    j.seal_active()
    first, second = j.sealed_segments()
    path = j._seg_path(first)
    data = bytearray(open(path, "rb").read())
    # flip a byte inside the SECOND frame's payload: frame 1 is the
    # replayable prefix, frames 2-3 are behind the damage
    frame1_len = 8 + (len(data) // 3 - 8)
    data[frame1_len + 12] ^= 0xFF
    open(path, "wb").write(bytes(data))
    rows, reason = j.read_segment(first)
    assert reason == "crc" and len(rows) == 1
    w = ReportWriteBatcher(ds, journal=j)
    r = JournalReplayer(j, w, interval_s=60)
    assert r.drain_once() == 3  # prefix of the damaged + all of the healthy
    assert j.sealed_segments() == []
    assert j.quarantined == 1
    assert os.path.exists(path + ".corrupt")  # preserved for manual recovery
    assert db_report_count(ds) == 3
    w.close()


def test_corrupt_length_field_quarantines_not_truncates(tmp_path, eph):
    """A bit-flipped LENGTH field makes the frame overshoot EOF — which
    looks like a torn tail, except acked frames follow it. The reader
    must spot the later frame magic and classify damage (quarantine),
    or truncate_segment would silently destroy the acked tail."""
    ds = eph.datastore
    j = UploadJournal(str(tmp_path / "j"), ds.crypter)
    j.append_batch([mkreport(i) for i in range(3)])
    j.seal_active()
    (seq,) = j.sealed_segments()
    path = j._seg_path(seq)
    data = bytearray(open(path, "rb").read())
    data[6] |= 0x80  # blow up frame 1's u32 length field (offset 4..8)
    open(path, "wb").write(bytes(data))
    rows, reason = j.read_segment(seq)
    assert reason == "crc" and rows == []  # NOT "truncated"
    w = ReportWriteBatcher(ds, journal=j)
    r = JournalReplayer(j, w, interval_s=60)
    r.drain_once()
    assert j.quarantined == 1
    assert os.path.exists(path + ".corrupt")  # acked frames preserved
    w.close()


def test_undecodable_row_quarantines_instead_of_wedging(tmp_path, eph):
    """A CRC-valid row the crypter can no longer decrypt (rotated-out
    key) must not wedge the replayer forever: the decodable prefix
    replays and the segment is quarantined."""
    from janus_tpu.datastore.store import Crypter

    ds = eph.datastore
    other = Crypter()  # a different key: decrypt will fail
    j = UploadJournal(str(tmp_path / "j"), other)
    j.append_batch([mkreport(1)])
    j.seal_active()
    j2 = UploadJournal(str(tmp_path / "j2"), ds.crypter)
    j2.append_batch([mkreport(2)])
    j2.seal_active()
    # read the wrong-key journal through the datastore's crypter
    j.crypter = ds.crypter
    rows, reason = j.read_segment(j.sealed_segments()[0])
    assert reason == "crc" and rows == []
    w = ReportWriteBatcher(ds, journal=j)
    r = JournalReplayer(j, w, interval_s=60)
    r.drain_once()
    assert j.quarantined == 1 and j.depth()[0] == 0  # not wedged
    w.close()


def test_quarantined_seq_never_reused_across_restart(tmp_path, eph):
    """After a restart, a fresh segment must never take a quarantined
    file's sequence number — a later quarantine's rename would
    overwrite the preserved .corrupt bytes."""
    d = str(tmp_path / "j")
    j = UploadJournal(d, eph.datastore.crypter)
    j.append_batch([mkreport(1)])
    j.seal_active()
    (seq,) = j.sealed_segments()
    j.quarantine_segment(seq)
    j.close()
    j2 = UploadJournal(d, eph.datastore.crypter)
    assert j2._active_seq > seq
    # and an in-process name collision appends .corrupt.N, never clobbers
    j2.append_batch([mkreport(2)])
    j2.seal_active()
    (seq2,) = j2.sealed_segments()
    open(j2._seg_path(seq2) + ".corrupt", "wb").write(b"preserved")
    j2.quarantine_segment(seq2)
    assert open(j2._seg_path(seq2) + ".corrupt", "rb").read() == b"preserved"
    assert os.path.exists(j2._seg_path(seq2) + ".corrupt.1")


def test_zero_record_torn_segment_is_cleaned_up(tmp_path, eph):
    """A crash during the very FIRST append of an outage leaves a
    segment holding only a torn partial frame (0 valid records): the
    drain must still truncate it, or its bytes pin journal capacity
    forever."""
    d = str(tmp_path / "j")
    os.makedirs(d, exist_ok=True)
    open(os.path.join(d, "upload-journal-0000000000000001.wal"), "wb").write(
        b"JUJ1\x40\x00\x00\x00"  # torn first frame, nothing valid
    )
    j = UploadJournal(d, eph.datastore.crypter)
    assert j.depth() == (0, 8, 1)
    w = ReportWriteBatcher(eph.datastore, journal=j)
    r = JournalReplayer(j, w, interval_s=60)
    r.drain_once()
    assert j.depth() == (0, 0, 0)  # dead segment truncated, capacity freed
    assert j.quarantined == 0
    w.close()


def test_quarantined_bytes_count_toward_the_bound(tmp_path, eph):
    """Quarantine preserves bytes, and preserved bytes still occupy the
    bounded disk: .corrupt files are charged against max_total_bytes
    (including across restarts) until an operator removes them."""
    d = str(tmp_path / "j")
    j = UploadJournal(d, eph.datastore.crypter, max_total_bytes=1 << 20)
    j.append_batch([mkreport(i) for i in range(4)])
    j.seal_active()
    (seq,) = j.sealed_segments()
    size = os.path.getsize(j._seg_path(seq))
    j.quarantine_segment(seq)
    assert j.quarantined_bytes == size
    # a fresh process still accounts for the quarantined file
    j2 = UploadJournal(d, eph.datastore.crypter, max_total_bytes=1 << 20)
    assert j2.quarantined == 1 and j2.quarantined_bytes == size


def test_boot_survives_corrupt_segment(tmp_path, eph):
    """CRC damage in any segment at boot must not crash-loop the
    aggregator: recovery keeps the valid prefix in the queue (ERROR
    logged) and the drain quarantines the file after landing it."""
    d = str(tmp_path / "j")
    j = UploadJournal(d, eph.datastore.crypter)
    j.append_batch([mkreport(i) for i in range(3)])
    j.seal_active()
    j.append_batch([mkreport(10 + i) for i in range(2)])
    j.close()
    first = j.sealed_segments()[0]
    path = j._seg_path(first)
    data = bytearray(open(path, "rb").read())
    data[12] ^= 0xFF  # first frame damaged: prefix is empty
    open(path, "wb").write(bytes(data))
    j2 = UploadJournal(d, eph.datastore.crypter)  # must not raise
    w = ReportWriteBatcher(eph.datastore, journal=j2)
    r = JournalReplayer(j2, w, interval_s=60)
    assert r.drain_once() == 2  # the healthy rows land
    assert j2.quarantined == 1
    assert os.path.exists(path + ".corrupt")
    assert db_report_count(eph.datastore) == 2
    w.close()


def test_segment_rotation_and_bound(tmp_path, eph):
    j = UploadJournal(
        str(tmp_path / "j"),
        eph.datastore.crypter,
        max_segment_bytes=4096,
        max_total_bytes=8192,
    )
    with pytest.raises(JournalFull) as ei:
        for i in range(200):
            j.append_batch([mkreport(i)])
    # JournalFull is a ShedError answering 503 (availability, not rate)
    assert isinstance(ei.value, ShedError)
    assert ei.value.status == 503
    assert ei.value.reason == "journal_full"
    assert len(j.sealed_segments()) >= 1  # rotation happened on the way
    assert j.is_full()
    assert j.readiness() is not None  # /readyz fails while full


def test_boot_recovery_scan(tmp_path, eph):
    d = str(tmp_path / "j")
    j1 = UploadJournal(d, eph.datastore.crypter)
    j1.append_batch([mkreport(i) for i in range(4)])
    j1.close()  # process death with a non-empty journal
    j2 = UploadJournal(d, eph.datastore.crypter)
    records, _, segments = j2.depth()
    assert records == 4 and segments == 1
    # the recovered segment is already sealed and replayable
    assert len(j2.sealed_segments()) == 1


# ---------------------------------------------------------------------------
# replayer
# ---------------------------------------------------------------------------


def test_replay_drains_and_truncates_after_commit(tmp_path, eph):
    ds = eph.datastore
    j = UploadJournal(str(tmp_path / "j"), ds.crypter)
    w = ReportWriteBatcher(ds, journal=j)
    j.append_batch([mkreport(i) for i in range(6)])
    r = JournalReplayer(j, w, interval_s=60)  # no thread: drive by hand
    assert r.drain_once() == 6
    assert j.depth() == (0, 0, 0)
    assert db_report_count(ds) == 6
    assert r.replayed_fresh == 6 and r.replayed_dupes == 0
    # segment files are gone
    assert not [f for f in os.listdir(j.dir) if f.endswith(".wal")]
    w.close()


def test_replay_failure_keeps_segment_for_retry(tmp_path, eph):
    """Truncate only after the covering commit: a failed replay pass
    must leave the segment on disk, and the next pass (datastore back)
    must drain it."""
    ds = eph.datastore
    ds.failpoint_scope = "jtest"
    j = UploadJournal(str(tmp_path / "j"), ds.crypter)
    w = ReportWriteBatcher(ds, journal=j)
    j.append_batch([mkreport(i) for i in range(3)])
    failpoints.configure("datastore.connect.jtest=error:1.0")
    r = JournalReplayer(j, w, interval_s=60)
    assert r.drain_once() == 0
    assert j.depth()[0] == 3  # nothing lost, nothing truncated
    failpoints.clear()
    assert r.drain_once() == 3
    assert j.depth()[0] == 0
    assert db_report_count(ds) == 3
    w.close()


def test_replay_duplicate_is_replayed_ok(tmp_path, eph):
    """A journaled report that already landed in the datastore (e.g. a
    retry that was acked twice, once from each path) dedups on replay —
    exactly-once, counted as outcome=replayed."""
    ds = eph.datastore
    j = UploadJournal(str(tmp_path / "j"), ds.crypter)
    w = ReportWriteBatcher(ds, journal=j)
    dup = mkreport(1)
    assert w.write_report(dup) is True  # already durable in the DB
    j.append_batch([dup, mkreport(2)])
    before = metrics.upload_journal_replayed_total.get(outcome="replayed")
    r = JournalReplayer(j, w, interval_s=60)
    assert r.drain_once() == 2
    assert db_report_count(ds) == 2  # no double row
    assert r.replayed_dupes == 1 and r.replayed_fresh == 1
    assert metrics.upload_journal_replayed_total.get(outcome="replayed") == before + 1
    w.close()


def test_replayer_waits_out_datastore_down(tmp_path, eph):
    class FakeSup:
        state = "down"

    ds = eph.datastore
    j = UploadJournal(str(tmp_path / "j"), ds.crypter)
    w = ReportWriteBatcher(ds, journal=j)
    j.append_batch([mkreport(1)])
    r = JournalReplayer(j, w, supervisor_fn=lambda: FakeSup(), interval_s=60)
    assert r.drain_once() == 0  # replaying into a dead DB is pointless
    assert j.depth()[0] == 1
    w.close()


# ---------------------------------------------------------------------------
# writer spill integration
# ---------------------------------------------------------------------------


def test_healthy_path_has_no_fsyncs_and_no_spill(tmp_path, eph):
    ds = eph.datastore
    j = UploadJournal(str(tmp_path / "j"), ds.crypter)
    w = ReportWriteBatcher(ds, journal=j)
    for i in range(5):
        assert w.write_report(mkreport(i)) is True
    assert j.fsyncs == 0  # armed but idle: the hot path is unchanged
    assert j.depth()[0] == 0
    assert db_report_count(ds) == 5
    w.close()


def test_spill_on_connection_error_resolves_201(tmp_path, eph):
    ds = eph.datastore
    ds.failpoint_scope = "spill"
    j = UploadJournal(str(tmp_path / "j"), ds.crypter)
    w = ReportWriteBatcher(ds, journal=j)
    failpoints.configure("datastore.connect.spill=error:1.0")
    assert w.write_report(mkreport(1)) is True  # ack rests on the journal
    assert j.depth()[0] == 1 and j.fsyncs == 1
    assert db_report_count.__name__  # (db unreachable: no count here)
    failpoints.clear()
    # recovery drains it into the DB exactly once
    r = JournalReplayer(j, w, interval_s=60)
    assert r.drain_once() == 1
    assert db_report_count(ds) == 1
    w.close()


def test_no_journal_connection_error_still_fails_loudly(eph):
    """Without a journal the old contract holds: a datastore outage is
    a loud 500, never a silent 201."""
    import sqlite3

    ds = eph.datastore
    ds.failpoint_scope = "nojournal"
    w = ReportWriteBatcher(ds)
    failpoints.configure("datastore.connect.nojournal=error:1.0")
    with pytest.raises(sqlite3.OperationalError):
        w.write_report(mkreport(1))
    failpoints.clear()
    w.close()


def test_non_connection_errors_never_spill(tmp_path, eph):
    """Only connection-class failures spill: the injected flush fault
    (a RuntimeError) must keep failing loudly even with a journal."""
    ds = eph.datastore
    j = UploadJournal(str(tmp_path / "j"), ds.crypter)
    w = ReportWriteBatcher(ds, journal=j)
    failpoints.configure("report_writer.flush=error:1,count=1")
    with pytest.raises(RuntimeError):
        w.write_report(mkreport(1))
    assert j.depth()[0] == 0
    assert w.write_report(mkreport(2)) is True  # writer recovered
    w.close()


def test_supervisor_down_bypasses_doomed_tx(tmp_path, eph):
    """While the supervisor says not-up, flushes go straight to the
    journal without burning run_tx's retry budget: ack latency through
    an outage stays ~fsync, not ~seconds."""
    ds = eph.datastore
    ds.failpoint_scope = "bypass"
    sup = ds.start_supervision(probe_interval_s=0.05, down_threshold=2)
    j = UploadJournal(str(tmp_path / "j"), ds.crypter)
    w = ReportWriteBatcher(ds, journal=j)
    failpoints.configure("datastore.connect.bypass=error:1.0")
    deadline = time.monotonic() + 10
    while sup.state != "down" and time.monotonic() < deadline:
        time.sleep(0.01)
    assert sup.state == "down"
    t0 = time.monotonic()
    assert w.write_report(mkreport(1)) is True
    assert time.monotonic() - t0 < 0.5  # no 16-attempt retry walk
    assert j.depth()[0] == 1
    failpoints.clear()
    w.close()


def test_journal_full_resolves_shed_error(tmp_path, eph):
    from janus_tpu.datastore.store import DatastoreSupervisor

    ds = eph.datastore
    # attach WITHOUT starting the probe thread: its immediate first
    # probe would race the manual failures below
    sup = ds.supervisor = DatastoreSupervisor(ds, probe_interval_s=3600)
    # force not-up so the writer takes the spill path
    sup.record_failure()
    sup.record_failure()
    sup.record_failure()
    assert sup.state == "down"
    j = UploadJournal(
        str(tmp_path / "j"), ds.crypter, max_segment_bytes=4096, max_total_bytes=4096
    )
    w = ReportWriteBatcher(ds, journal=j)
    with pytest.raises(JournalFull) as ei:
        for i in range(200):
            w.write_report(mkreport(i))
    assert ei.value.status == 503 and ei.value.retry_after_s > 0
    w.close()


def test_slow_commit_degrades_and_spills_next_flush(tmp_path, eph):
    """A commit past spill_latency_s marks the supervisor degraded, so
    the NEXT flush spills — bounded ack latency through a brownout."""
    ds = eph.datastore
    ds.start_supervision(probe_interval_s=3600)
    j = UploadJournal(str(tmp_path / "j"), ds.crypter)
    # every commit "exceeds" a microscopic threshold
    w = ReportWriteBatcher(ds, journal=j, spill_latency_s=1e-9)
    assert w.write_report(mkreport(1)) is True  # lands in DB, trips the threshold
    assert db_report_count(ds) == 1
    assert ds.supervisor.state == "degraded"
    assert w.write_report(mkreport(2)) is True  # spilled
    assert j.depth()[0] == 1
    w.close()
