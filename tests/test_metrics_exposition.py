"""Prometheus exposition correctness + the observability endpoints.

The scrape is an interface: a single malformed label value or a
non-monotone bucket silently corrupts every downstream dashboard, so
every registered metric must render output the shared parser
(janus_tpu.exposition — also used by scripts/scrape_check.py and the
bench dry-run smoke) accepts, and the naming conventions are linted so
new metrics can't drift. Plus: /statusz, /debug/vars, the
/debug/profile concurrency guard, the span->metric bridge, and the
job-health sampler.
"""

import json
import threading
import time
import urllib.error
import urllib.request

import pytest

from janus_tpu import metrics as m
from janus_tpu.exposition import (
    lint_metric_names,
    parse_exposition,
    registry_names_by_type,
    validate_exposition,
)


# ---------------------------------------------------------------------------
# exposition format
# ---------------------------------------------------------------------------


def test_label_escaping_roundtrip():
    """A label value carrying backslash, double quote, and newline must
    render escaped and parse back to the original value."""
    hostile = 'task"id\nwith\\everything'
    c = m.Counter("janus_escape_test_total", "escaping probe")
    c.add(3, task=hostile)
    text = c.render()
    # the raw text must not contain an unescaped newline inside a label
    sample_lines = [l for l in text.splitlines() if not l.startswith("#")]
    assert len(sample_lines) == 1, sample_lines
    families, errors = parse_exposition(
        f"# HELP {c.name} x\n# TYPE {c.name} counter\n" + sample_lines[0]
    )
    assert not errors, errors
    ((name, labels, value),) = families[c.name].samples
    assert labels["task"] == hostile
    assert value == 3.0


def test_unescaped_scrape_would_be_rejected():
    """The parser the deploy check uses actually catches the corruption
    escaping prevents (guards against a silently lax parser)."""
    bad = '# TYPE janus_x_total counter\njanus_x_total{a="broken\nvalue"} 1\n'
    families, errors = parse_exposition(bad)
    assert errors  # unescaped newline splits the sample line


def test_full_registry_scrape_valid_and_linted():
    """Every registered metric — after populating representative
    samples including a hostile label — renders a scrape the shared
    parser validates, and every name passes the convention lint."""
    m.aggregate_step_failure_counter.add(type='weird"type\nname\\x')
    m.http_request_duration.observe(0.012, route="upload")
    m.http_request_duration.observe(31.0, route="upload")  # +Inf overflow
    m.engine_dispatch_seconds.observe(0.004, op="helper_init", phase="put", vdaf="count")
    m.engine_compile_seconds.observe(42.0, op="helper_init", bucket="32")
    m.jobs_gauge.set(2, type="aggregation", state="in_progress")
    m.engine_backend_state.set(1.0, vdaf="count", state="device")
    text = m.REGISTRY.render()
    assert validate_exposition(text) == []
    assert lint_metric_names(registry_names_by_type(m.REGISTRY)) == []


def test_histogram_bucket_monotonicity_and_sums():
    h = m.Histogram("janus_mono_test_seconds", "probe", buckets=(0.1, 1.0, 10.0))
    for v in (0.05, 0.5, 0.5, 5.0, 50.0):
        h.observe(v, op="x")
    families, errors = parse_exposition(
        "# HELP janus_mono_test_seconds p\n# TYPE janus_mono_test_seconds histogram\n"
        + "\n".join(l for l in h.render().splitlines() if not l.startswith("#"))
    )
    assert not errors
    samples = families["janus_mono_test_seconds"].samples
    buckets = [
        (labels["le"], v) for name, labels, v in samples if name.endswith("_bucket")
    ]
    counts = [v for _, v in buckets]
    assert counts == sorted(counts)  # cumulative
    count = next(v for name, _, v in samples if name.endswith("_count"))
    assert count == 5
    inf_bucket = next(v for le, v in buckets if le == "+Inf")
    assert inf_bucket == count
    total = next(v for name, _, v in samples if name.endswith("_sum"))
    assert total == pytest.approx(56.05)


def test_naming_lint_flags_violations():
    errs = lint_metric_names(
        {
            "not_janus_thing": "gauge",
            "janus_new_counter": "counter",  # missing _total, not grandfathered
            "janus_upload_decrypt_failures": "counter",  # grandfathered
            "janus_some_duration": "histogram",  # missing _seconds
        }
    )
    assert any("not_janus_thing" in e for e in errs)
    assert any("janus_new_counter" in e for e in errs)
    assert any("janus_some_duration" in e for e in errs)
    assert not any("janus_upload_decrypt_failures" in e for e in errs)


def test_counter_gauge_locked_reads_and_totals():
    g = m.Gauge("janus_gauge_probe", "probe")
    g.set(2.0, k="a")
    g.add(3.0, k="b")
    assert g.get(k="a") == 2.0
    assert g.total() == 5.0
    c = m.Counter("janus_counter_probe_total", "probe")
    c.add(4, k="a")
    assert c.get(k="a") == 4.0
    assert c.total() == 4.0


# ---------------------------------------------------------------------------
# span -> metric bridge
# ---------------------------------------------------------------------------


def test_span_metric_bridge_records_duration_with_labels():
    from janus_tpu.trace import register_span_metric, span

    h = m.Histogram("janus_bridge_probe_seconds", "probe")
    register_span_metric(
        "bridge.probe", h, labels={"op": "x", "phase": "put"}, arg_labels=("vdaf",)
    )
    with span("bridge.probe", vdaf="count"):
        time.sleep(0.01)
    key = (("op", "x"), ("phase", "put"), ("vdaf", "count"))
    assert h._totals[key] == 1
    assert h._sums[key] >= 0.01
    # a span without the optional arg label still records
    with span("bridge.probe"):
        pass
    key2 = (("op", "x"), ("phase", "put"))
    assert h._totals[key2] == 1


def test_engine_spans_are_registered_with_dispatch_histogram():
    """The bridge registrations in metrics.py cover the engine span
    names engine_cache.py emits — drift here silently zeroes the
    dispatch histogram."""
    from janus_tpu.trace import _span_metrics

    for op in ("helper_init", "leader_init"):
        for phase in ("put", "dispatch", "fetch"):
            assert f"engine.{op}.{phase}" in _span_metrics
    assert "engine.aggregate.dispatch" in _span_metrics
    for name in (
        "engine.leader_init.fetch_seed",
        "engine.leader_init.fetch_ver",
        "engine.leader_init.fetch_part",
        "engine.leader_init.put_all_async",
        "engine.leader_init.chunk",
    ):
        assert name in _span_metrics
        assert _span_metrics[name][0] is m.engine_dispatch_seconds


# ---------------------------------------------------------------------------
# health listener endpoints
# ---------------------------------------------------------------------------


@pytest.fixture()
def health_server():
    from janus_tpu import profiler as prof
    from janus_tpu.binary_utils import HealthServer

    from janus_tpu import flight_recorder as flight

    # the real binaries run the continuous profiler and the flight
    # recorder (janus_main installs both by default) and scrape_check
    # enforces that — the fixture matches the deploy shape
    prof.install_profiler(prof.ProfilerConfig(hz=100.0, window_secs=10.0))
    fr = flight.install_flight_recorder(flight.FlightRecorderConfig(interval_s=0.2))
    fr.snapshot_once()
    srv = HealthServer("127.0.0.1:0").start()
    try:
        yield f"http://127.0.0.1:{srv.port}"
    finally:
        srv.stop()
        flight.uninstall_flight_recorder()
        prof.uninstall_profiler()


def _get(url):
    with urllib.request.urlopen(url, timeout=10) as resp:
        return resp.status, resp.headers.get("Content-Type", ""), resp.read()


def test_metrics_endpoint_content_type_and_validity(health_server):
    status, ctype, body = _get(health_server + "/metrics")
    assert status == 200
    assert ctype == "text/plain; version=0.0.4; charset=utf-8"
    assert validate_exposition(body.decode()) == []


def test_statusz_json_and_html(health_server):
    from janus_tpu.statusz import register_status_provider, unregister_status_provider

    register_status_provider("probe_section", lambda: {"answer": 42})
    try:
        status, ctype, body = _get(health_server + "/statusz")
        assert status == 200 and ctype.startswith("application/json")
        snap = json.loads(body)
        assert snap["probe_section"] == {"answer": 42}
        status, ctype, body = _get(health_server + "/statusz?format=html")
        assert status == 200 and ctype.startswith("text/html")
        assert b"probe_section" in body
    finally:
        unregister_status_provider("probe_section")


def test_statusz_survives_broken_provider(health_server):
    from janus_tpu.statusz import register_status_provider, unregister_status_provider

    register_status_provider("broken", lambda: 1 / 0)
    try:
        status, _, body = _get(health_server + "/statusz")
        assert status == 200
        snap = json.loads(body)
        assert "error" in snap["broken"]
    finally:
        unregister_status_provider("broken")


def test_debug_vars_dumps_registry(health_server):
    m.upload_shed_counter.add(route="upload", reason="probe")
    status, ctype, body = _get(health_server + "/debug/vars")
    assert status == 200 and ctype.startswith("application/json")
    vars_ = json.loads(body)
    fam = vars_["janus_upload_shed_total"]
    assert fam["type"] == "counter"
    assert any(
        s["labels"] == {"route": "upload", "reason": "probe"} for s in fam["samples"]
    )


def test_profile_capture_concurrent_second_409s(health_server):
    """POST /debug/profile: a capture while the guard is held answers
    409; with the guard free it answers 200 with a loadable host
    Chrome trace. Deterministic — the guard lock is held directly
    instead of racing two HTTP requests on a loaded host (the bench
    dry-run smoke exercises the truly concurrent pair)."""
    import janus_tpu.binary_utils as _bu

    def post(seconds):
        req = urllib.request.Request(
            health_server + f"/debug/profile?seconds={seconds}", method="POST"
        )
        try:
            with urllib.request.urlopen(req, timeout=120) as resp:
                return resp.status, resp.read()
        except urllib.error.HTTPError as e:
            return e.code, e.read()

    assert _bu._profile_lock.acquire(blocking=False)
    try:
        status, body = post(1)
        assert status == 409, (status, body)
    finally:
        _bu._profile_lock.release()

    status, body = post(1)
    assert status == 200, (status, body)
    artifacts = json.loads(body)
    raw = open(artifacts["host_chrome_trace"]).read().rstrip()
    events = json.loads(raw if raw.endswith("]") else raw + "{}]")
    assert isinstance(events, list)


def test_profile_window_clamped():
    from janus_tpu.binary_utils import PROFILE_MAX_SECONDS, capture_profile

    out = capture_profile(0.0)  # below the floor
    assert out["seconds"] >= 0.1
    assert PROFILE_MAX_SECONDS <= 60.0


def test_scrape_check_tool_against_live_listener(health_server, tmp_path):
    """scripts/scrape_check.py (the deploy smoke) passes against a live
    listener and fails against garbage."""
    import pathlib
    import runpy
    import sys

    script = pathlib.Path(__file__).resolve().parent.parent / "scripts" / "scrape_check.py"
    sys.path.insert(0, str(script.parent.parent))
    try:
        mod = runpy.run_path(str(script), run_name="scrape_check")
        assert mod["main"](["--url", health_server, "--statusz"]) == 0
        assert mod["main"](["--url", health_server + "/nope"]) != 0
    finally:
        sys.path.pop(0)


# ---------------------------------------------------------------------------
# job/task health sampler
# ---------------------------------------------------------------------------


def _provision_backlog(ds, clock):
    from janus_tpu.datastore.models import (
        AggregationJobModel,
        AggregationJobState,
        LeaderStoredReport,
    )
    from janus_tpu.messages import (
        AggregationJobId,
        Duration,
        HpkeCiphertext,
        HpkeConfigId,
        Interval,
        ReportId,
        Role,
        Time,
    )
    from janus_tpu.task import QueryTypeConfig, TaskBuilder
    from janus_tpu.vdaf.registry import VdafInstance

    task = (
        TaskBuilder(QueryTypeConfig.time_interval(), VdafInstance.count(), Role.LEADER)
        .with_(min_batch_size=1)
        .build()
    )
    now = clock.now().seconds

    def provision(tx):
        tx.put_task(task)
        tx.put_aggregation_job(
            AggregationJobModel(
                task.task_id,
                AggregationJobId(b"\x07" * 16),
                b"",
                b"",
                Interval(Time(now - 60), Duration(60)),
                AggregationJobState.IN_PROGRESS,
                0,
                None,
            )
        )
        tx.put_client_report(
            LeaderStoredReport(
                task.task_id,
                ReportId(b"\x08" * 16),
                Time(now - 500),
                b"",
                b"share",
                HpkeCiphertext(HpkeConfigId(0), b"enc", b"payload"),
            )
        )

    ds.run_tx(provision)
    return task


def test_health_sampler_exports_backlog_lag_and_lease_age():
    from janus_tpu.aggregator.health_sampler import HealthSampler, _b64_task_id
    from janus_tpu.datastore.store import EphemeralDatastore
    from janus_tpu.messages import Duration

    eph = EphemeralDatastore()
    try:
        ds = eph.datastore
        task = _provision_backlog(ds, eph.clock)
        sampler = HealthSampler(ds, interval_s=0.1)
        snap = sampler.run_once()
        assert snap["jobs"]["aggregation/in_progress"] == 1
        assert snap["jobs"]["collection/start"] == 0  # zero-filled
        assert m.jobs_gauge.get(type="aggregation", state="in_progress") == 1.0
        label = _b64_task_id(task.task_id.data)
        assert snap["oldest_unaggregated_report_age_seconds"][label] == 500.0
        assert (
            m.oldest_unaggregated_report_age_seconds.get(task_id=label) == 500.0
        )

        # lease age: acquire a lease, then advance the clock — age is
        # measured from first observation
        acquired = ds.run_tx(
            lambda tx: tx.acquire_incomplete_aggregation_jobs(Duration(600), 1)
        )
        assert len(acquired) == 1
        sampler.run_once()
        assert m.job_lease_age_seconds.get() == 0.0
        eph.clock.advance(Duration(30))
        snap = sampler.run_once()
        assert snap["max_lease_age_seconds"] == 30
        assert m.job_lease_age_seconds.get() == 30.0

        # releasing the lease drops the age back to zero
        ds.run_tx(lambda tx: tx.release_aggregation_job(acquired[0]))
        snap = sampler.run_once()
        assert snap["max_lease_age_seconds"] == 0

        # the report getting claimed clears the per-task lag gauge
        ds.run_tx(
            lambda tx: tx.get_unaggregated_client_reports_for_task(task.task_id, 10)
        )
        snap = sampler.run_once()
        assert label not in snap["oldest_unaggregated_report_age_seconds"]
        assert m.oldest_unaggregated_report_age_seconds.get(task_id=label) == 0.0
    finally:
        eph.cleanup()


def test_accumulator_counts_reports_at_accumulate_time():
    from janus_tpu.aggregator.accumulator import Accumulator
    from janus_tpu.datastore.store import EphemeralDatastore
    from janus_tpu.messages import ReportId, Time

    eph = EphemeralDatastore()
    try:
        task = _provision_backlog(eph.datastore, eph.clock)
        label = m.task_id_label(task.task_id.data)
        before = m.task_reports_aggregated_total.get(task_id=label)
        acc = Accumulator(task)
        acc.update_single(b"batch", [1], ReportId(b"\x09" * 16), Time(0))
        acc.update_single(b"batch", [1], ReportId(b"\x0a" * 16), Time(0))
        assert m.task_reports_aggregated_total.get(task_id=label) - before == 2
    finally:
        eph.cleanup()


def test_debug_traces_endpoint_serves_flight_recorder(health_server):
    """GET /debug/traces: the always-on flight recorder as JSON —
    recent spans, slow captures, per-name digests; ?limit bounds the
    recent list (ISSUE 6)."""
    from janus_tpu.trace import span

    with span("debug.traces.test", probe=1):
        pass
    status, ctype, body = _get(health_server + "/debug/traces")
    assert status == 200 and ctype.startswith("application/json")
    doc = json.loads(body)
    assert {"recorded_total", "capacity", "recent", "slow_traces", "digests"} <= set(doc)
    assert doc["recorded_total"] > 0
    ours = [e for e in doc["recent"] if e["name"] == "debug.traces.test"]
    assert ours and ours[-1]["args"]["probe"] == 1
    assert "debug.traces.test" in doc["digests"]
    assert doc["digests"]["debug.traces.test"]["count"] >= 1
    # limit respected (and bad limits don't 500)
    _, _, body = _get(health_server + "/debug/traces?limit=2")
    assert len(json.loads(body)["recent"]) == 2
    status, _, _ = _get(health_server + "/debug/traces?limit=bogus")
    assert status == 200


def test_statusz_carries_flight_recorder_section(health_server):
    from janus_tpu.trace import span

    with span("statusz.recorder.test"):
        pass
    _, _, body = _get(health_server + "/statusz")
    snap = json.loads(body)
    fr = snap["flight_recorder"]
    assert fr["recorded_total"] > 0 and fr["capacity"] >= 16
    assert "statusz.recorder.test" in fr["names"]


def test_health_sampler_exports_freshness_quantiles():
    """The sampler exports per-task unaggregated-report age QUANTILES
    (p50/p95/p99), not only the oldest report (ISSUE 6 satellite)."""
    from janus_tpu.aggregator.health_sampler import HealthSampler, _b64_task_id
    from janus_tpu.datastore.models import LeaderStoredReport
    from janus_tpu.datastore.store import EphemeralDatastore
    from janus_tpu.messages import HpkeCiphertext, HpkeConfigId, ReportId, Time

    eph = EphemeralDatastore()
    try:
        ds = eph.datastore
        task = _provision_backlog(ds, eph.clock)
        now = eph.clock.now().seconds

        def more(tx):
            # ages 0..90 in 10s steps (plus the backlog's 500s report)
            for i in range(10):
                tx.put_client_report(
                    LeaderStoredReport(
                        task.task_id,
                        ReportId(bytes([0x40 + i] * 16)),
                        Time(now - 10 * i),
                        b"",
                        b"s",
                        HpkeCiphertext(HpkeConfigId(0), b"e", b"p"),
                    )
                )

        ds.run_tx(more)
        sampler = HealthSampler(ds, interval_s=0.1)
        snap = sampler.run_once()
        label = _b64_task_id(task.task_id.data)
        fresh = snap["unaggregated_report_age_quantiles"][label]
        assert fresh["count"] == 11
        # minute-bucketed, older-edge-biased: p99 covers the 500s-old
        # report conservatively (>= true age, within one bucket)
        assert fresh["p50"] <= fresh["p95"] <= fresh["p99"]
        assert 500.0 <= fresh["p99"] < 560.0
        assert (
            m.unaggregated_report_age_quantiles.get(task_id=label, quantile="p99")
            == fresh["p99"]
        )
        assert (
            m.unaggregated_report_age_quantiles.get(task_id=label, quantile="p50")
            == fresh["p50"]
        )

        # a drained task resets its quantile series to 0
        ds.run_tx(
            lambda tx: tx.get_unaggregated_client_reports_for_task(task.task_id, 20)
        )
        snap = sampler.run_once()
        assert label not in snap["unaggregated_report_age_quantiles"]
        for q in ("p50", "p95", "p99"):
            assert m.unaggregated_report_age_quantiles.get(task_id=label, quantile=q) == 0.0
    finally:
        eph.cleanup()


def test_report_e2e_histogram_observed_at_accumulate_time():
    """janus_report_e2e_seconds{stage="aggregate"}: observed from the
    client report timestamp at accumulate time, outside the write tx."""
    from janus_tpu.aggregator.accumulator import observe_report_e2e
    from janus_tpu.core.time_util import MockClock
    from janus_tpu.messages import Time

    clock = MockClock(Time(10_000))

    def count(stage):
        fam = m.REGISTRY.snapshot().get("janus_report_e2e_seconds", {})
        return next(
            (
                s["count"]
                for s in fam.get("samples", ())
                if s["labels"].get("stage") == stage
            ),
            0,
        )

    before = count("aggregate")
    observe_report_e2e(clock, [Time(9_400), Time(10_000), Time(11_000)])
    assert count("aggregate") - before == 3
    # a clockless call (host paths without one) is a no-op, not a crash
    observe_report_e2e(None, [Time(0)])
    assert count("aggregate") - before == 3


# ---------------------------------------------------------------------------
# OpenMetrics exemplars (ISSUE 10): Histogram.observe samples the
# ambient trace context (or the bridge's explicit trace id) per bucket,
# rendered only in the openmetrics exposition mode; the parser accepts
# well-formed exemplars and rejects malformed ones.
# ---------------------------------------------------------------------------


def test_histogram_exemplar_storage_and_openmetrics_render():
    h = m.Histogram("janus_t_ex_seconds", "t", buckets=(0.1, 1.0))
    h.observe(0.05, exemplar_trace_id="ab" * 16, route="u")
    h.observe(0.5, exemplar_trace_id=0x1234, route="u")
    h.observe(7.0, exemplar_trace_id="cd" * 16, route="u")  # +Inf bucket
    default = h.render()
    assert " # {" not in default  # default mode is bit-compatible
    om = h.render(openmetrics=True)
    assert '# {trace_id="' + "ab" * 16 + '"} 0.05' in om
    assert '# {trace_id="cd' in om  # +Inf bucket carries one too
    # last-write wins within a bucket
    h.observe(0.06, exemplar_trace_id="ef" * 16, route="u")
    om = h.render(openmetrics=True)
    assert "ab" * 16 not in om
    assert "ef" * 16 in om
    exemplars = h.exemplars()
    assert {e["le"] for e in exemplars} == {"0.1", "1", "+Inf"}
    assert all(e["trace_id"] for e in exemplars)


def test_histogram_exemplar_from_ambient_trace_context():
    from janus_tpu.trace import span, trace_id_of, current_traceparent

    h = m.Histogram("janus_t_ex2_seconds", "t")
    captured = {}
    with span("t.exemplar_ambient"):
        captured["trace_id"] = trace_id_of(current_traceparent())
        h.observe(0.2)
    (ex,) = h.exemplars()
    assert ex["trace_id"] == captured["trace_id"]
    # without a context: no exemplar
    h2 = m.Histogram("janus_t_ex3_seconds", "t")
    h2.observe(0.2)
    assert h2.exemplars() == []


def test_histogram_exemplar_label_set_bound():
    h = m.Histogram("janus_t_ex4_seconds", "t")
    for i in range(m.Histogram.MAX_EXEMPLAR_LABEL_SETS + 10):
        h.observe(0.2, exemplar_trace_id="aa" * 16, series=str(i))
    assert len(h._exemplars) == m.Histogram.MAX_EXEMPLAR_LABEL_SETS
    # counts are unaffected by the exemplar cap
    assert sum(h._totals.values()) == m.Histogram.MAX_EXEMPLAR_LABEL_SETS + 10


def test_span_metric_bridge_attaches_exemplar_trace_id():
    from janus_tpu.trace import (
        _span_metrics,
        register_span_metric,
        span,
        trace_id_of,
        current_traceparent,
    )

    h = m.Histogram("janus_t_ex5_seconds", "t")
    register_span_metric("t.bridge_exemplar", h, labels={"op": "x"})
    try:
        seen = {}
        with span("t.bridge_exemplar"):
            seen["trace_id"] = trace_id_of(current_traceparent())
        (ex,) = h.exemplars()
        assert ex["trace_id"] == seen["trace_id"]
        assert ex["labels"] == {"op": "x"}
    finally:
        _span_metrics.pop("t.bridge_exemplar", None)


def test_openmetrics_parser_accepts_and_rejects_exemplars():
    header = "# HELP x_seconds t\n# TYPE x_seconds histogram\n"
    tail = 'x_seconds_bucket{le="+Inf"} 1\nx_seconds_sum 0.05\nx_seconds_count 1\n# EOF\n'
    good = (
        header
        + 'x_seconds_bucket{le="0.1"} 1 # {trace_id="abc"} 0.05 1700000000.0\n'
        + tail
    )
    assert validate_exposition(good, openmetrics=True) == []
    fams, _ = parse_exposition(good, openmetrics=True)
    (name, labels, ex) = fams["x_seconds"].exemplars[0]
    assert ex == {"labels": {"trace_id": "abc"}, "value": 0.05, "ts": 1700000000.0}

    # default mode rejects exemplar syntax outright
    assert validate_exposition(good) != []

    # exemplar above its bucket bound
    bad = (
        header
        + 'x_seconds_bucket{le="0.1"} 1 # {trace_id="abc"} 5.0\n'
        + tail
    )
    assert any("above bucket bound" in e for e in validate_exposition(bad, openmetrics=True))

    # exemplar on a gauge
    bad = '# TYPE g gauge\ng 1 # {trace_id="a"} 0.5\n# EOF\n'
    assert any("only histogram buckets" in e for e in validate_exposition(bad, openmetrics=True))

    # unterminated label set / junk value / oversized label set
    bad = header + 'x_seconds_bucket{le="0.1"} 1 # {trace_id="a 0.05\n' + tail
    assert any("unterminated" in e for e in validate_exposition(bad, openmetrics=True))
    bad = header + 'x_seconds_bucket{le="0.1"} 1 # {trace_id="a"} zap\n' + tail
    assert any("unparseable exemplar value" in e for e in validate_exposition(bad, openmetrics=True))
    bad = header + 'x_seconds_bucket{le="0.1"} 1 # {trace_id="' + "x" * 200 + '"} 0.05\n' + tail
    assert any("128 runes" in e for e in validate_exposition(bad, openmetrics=True))

    # missing # EOF
    assert any(
        "missing # EOF" in e
        for e in validate_exposition(header + tail.replace("# EOF\n", ""), openmetrics=True)
    )
    # content after # EOF
    assert any(
        "content after # EOF" in e
        for e in validate_exposition(good + "x_seconds_count 2\n", openmetrics=True)
    )


def test_hash_inside_label_value_is_not_an_exemplar():
    c = m.Counter("janus_t_hash_total", "t")
    c.add(reason='before # {fake="exemplar"} 1 after')
    text = "# TYPE janus_t_hash_total counter\n" + c.render().splitlines()[-1] + "\n# EOF\n"
    fams, errors = parse_exposition(text, openmetrics=True)
    assert errors == []
    assert fams["janus_t_hash_total"].exemplars == []
    (_, labels, _) = fams["janus_t_hash_total"].samples[0]
    assert labels["reason"] == 'before # {fake="exemplar"} 1 after'


def test_registry_openmetrics_mode_is_superset_and_default_unchanged():
    h = m.REGISTRY.histogram("janus_t_ex6_seconds", "t")
    default_before = m.REGISTRY.render()
    h.observe(0.2, exemplar_trace_id="ab" * 16)
    default_after = m.REGISTRY.render()
    # storing an exemplar changes the default scrape only by the new
    # histogram SAMPLE, never by exemplar clauses
    assert " # {" not in default_after
    om = m.REGISTRY.render(openmetrics=True)
    assert om.rstrip().endswith("# EOF")
    assert validate_exposition(om, openmetrics=True) == []
    fams_om, _ = parse_exposition(om, openmetrics=True)
    fams_def, _ = parse_exposition(default_after)
    assert set(fams_om) == set(fams_def)


# ---------------------------------------------------------------------------
# build info / process start time (ISSUE 10 satellite)
# ---------------------------------------------------------------------------


def test_build_info_and_process_start_time_registered():
    import sys

    m.register_build_info(backend="cpu")
    snap = m.REGISTRY.snapshot()
    info = snap["janus_build_info"]
    live = [s for s in info["samples"] if s["value"] == 1]
    assert len(live) == 1
    labels = live[0]["labels"]
    assert labels["backend"] == "cpu"
    assert labels["python"] == "%d.%d.%d" % sys.version_info[:3]
    assert set(labels) == {"version", "python", "jax", "backend"}
    start = m.process_start_time_seconds.get()
    assert 0 < start <= time.time()
    # re-registration with a different backend zeroes the old series
    m.register_build_info(backend="tpu")
    info = m.REGISTRY.snapshot()["janus_build_info"]
    live = [s for s in info["samples"] if s["value"] == 1]
    assert len(live) == 1 and live[0]["labels"]["backend"] == "tpu"
    m.register_build_info()  # restore the environment default


# ---------------------------------------------------------------------------
# /alertz + index page on the health listener (ISSUE 10)
# ---------------------------------------------------------------------------


def test_alertz_endpoint_disabled_and_enabled(health_server):
    from janus_tpu import slo

    slo.uninstall_slo_engine()
    status, ctype, body = _get(health_server + "/alertz")
    assert status == 200 and ctype.startswith("application/json")
    doc = json.loads(body)
    assert doc == {"enabled": False, "firing": [], "alerts": [], "slos": []}

    slo.install_slo_engine(slo.SloEngineConfig(evaluation_interval_s=0.02))
    try:
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            doc = json.loads(_get(health_server + "/alertz")[2])
            if doc.get("evaluations", 0) >= 1:
                break
            time.sleep(0.01)
        assert doc["enabled"] is True
        assert {s["name"] for s in doc["slos"]} >= {"upload_availability"}
        for a in doc["alerts"]:
            assert {"alert", "severity", "state", "burn_rate_threshold"} <= set(a)
    finally:
        slo.uninstall_slo_engine()


def test_index_page_links_endpoints(health_server):
    status, ctype, body = _get(health_server + "/")
    assert status == 200 and ctype.startswith("text/html")
    text = body.decode()
    for link in (
        "/healthz",
        "/readyz",
        "/metrics",
        "/statusz",
        "/alertz",
        "/debug/vars",
        "/debug/traces",
    ):
        assert f'href="{link}"' in text
    # still 404 on unknown paths
    with pytest.raises(urllib.error.HTTPError) as exc_info:
        _get(health_server + "/nope")
    assert exc_info.value.code == 404


def test_metrics_endpoint_openmetrics_negotiation(health_server):
    h = m.REGISTRY.histogram("janus_t_ex7_seconds", "t")
    h.observe(0.2, exemplar_trace_id="ab" * 16)
    status, ctype, body = _get(health_server + "/metrics?openmetrics=1")
    assert status == 200
    assert ctype == "application/openmetrics-text; version=1.0.0; charset=utf-8"
    text = body.decode()
    assert validate_exposition(text, openmetrics=True) == []
    assert 'janus_t_ex7_seconds_bucket' in text and "ab" * 16 in text
    # Accept negotiation
    req = urllib.request.Request(
        health_server + "/metrics",
        headers={"Accept": "application/openmetrics-text"},
    )
    with urllib.request.urlopen(req, timeout=10) as resp:
        assert resp.headers["Content-Type"].startswith("application/openmetrics-text")
    # the default mode stays exemplar-free and 0.0.4-typed
    status, ctype, body = _get(health_server + "/metrics")
    assert ctype == "text/plain; version=0.0.4; charset=utf-8"
    assert " # {" not in body.decode()


# ---------------------------------------------------------------------------
# /statusz HTML escaping (ISSUE 10 satellite: hostile label values must
# render inert — the text exposition has escaped them since PR 3, the
# HTML path now has the same pin)
# ---------------------------------------------------------------------------


def test_statusz_html_escapes_hostile_values(health_server):
    from janus_tpu.statusz import register_status_provider, unregister_status_provider

    hostile = {
        "task_id": '<script>alert(1)</script>"quoted"\nnewline\\end',
        "<img src=x onerror=alert(2)>": "key is hostile too",
    }
    register_status_provider("hostile_section<script>", lambda: hostile)
    try:
        status, ctype, body = _get(health_server + "/statusz?format=html")
        assert status == 200 and ctype.startswith("text/html")
        text = body.decode()
        assert "<script>alert(1)</script>" not in text
        assert "<img src=x" not in text
        assert "hostile_section<script>" not in text
        # escaped forms present: the data survives, inert
        assert "&lt;script&gt;alert(1)&lt;/script&gt;" in text
        assert "hostile_section&lt;script&gt;" in text
        # the JSON view carries the raw values (escaping is the HTML
        # renderer's job, not the provider's)
        snap = json.loads(_get(health_server + "/statusz")[2])
        assert snap["hostile_section<script>"]["task_id"].startswith("<script>")
    finally:
        unregister_status_provider("hostile_section<script>")
