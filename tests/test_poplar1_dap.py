"""Poplar1 end-to-end through the live DAP pair — heavy hitters with
NONTRIVIAL aggregation parameters, the piece the reference declares
but punts on (README.md:9-11; VERDICT r2 Next #6).

Flow per level: the collector starts a collection with
agg_param=(level, prefixes); the collection driver creates
param-scoped aggregation jobs; the aggregation driver runs the
two-round sketch exchange (init -> WaitingHelper/WaitingLeader ->
continue) over live HTTP; the collection driver then computes the
aggregate share for that parameter and the collector unshards
per-prefix counts."""

import pytest

from janus_tpu.aggregator.aggregation_job_driver import AggregationJobDriver
from janus_tpu.aggregator.collection_job_driver import CollectionJobDriver
from janus_tpu.aggregator.job_driver import JobDriver, JobDriverConfig
from janus_tpu.client import Client, ClientParameters
from janus_tpu.collector import Collector, CollectorParameters
from janus_tpu.core.http_client import HttpClient
from janus_tpu.datastore.models import ReportAggregationState
from janus_tpu.messages import Duration, Interval, Query, Time
from janus_tpu.vdaf.poplar1 import Poplar1AggParam
from janus_tpu.vdaf.registry import VdafInstance

from test_e2e import pair, provision  # noqa: F401  (fixture + helper)

BITS = 4
VDAF = VdafInstance.poplar1(bits=BITS)


def _drive(pair, http, rounds=8):
    """Run collection + aggregation drivers until quiescent."""
    adriver = AggregationJobDriver(pair["leader_ds"], http)
    ajd = JobDriver(
        JobDriverConfig(max_concurrent_job_workers=1), adriver.acquirer(), adriver.stepper
    )
    cdriver = CollectionJobDriver(pair["leader_ds"], http)
    cjd = JobDriver(
        JobDriverConfig(max_concurrent_job_workers=1), cdriver.acquirer(), cdriver.stepper
    )
    for _ in range(rounds):
        worked = cjd.run_once() + ajd.run_once()
        if not worked:
            break


@pytest.mark.slow  # 29s live-pair heavy-hitters e2e; DAP wiring stays in tier-1 via test_poplar1_invalid_report_rejected, the Poplar1 math via test_poplar1_jax (ISSUE 1 CI triage)
def test_poplar1_heavy_hitters_via_dap(pair):
    leader_task, helper_task, collector_kp = provision(
        pair, VDAF, max_batch_query_count=BITS + 1
    )
    http = HttpClient()
    clock = pair["clock"]

    # measurements: 0b1010 is heavy (3 uploads), 0b0110 appears twice,
    # 0b0001 once
    measurements = [0b1010, 0b1010, 0b1010, 0b0110, 0b0110, 0b0001]
    params = ClientParameters(
        leader_task.task_id,
        pair["leader_srv"].url,
        pair["helper_srv"].url,
        leader_task.time_precision,
    )
    client = Client.with_fetched_configs(params, VDAF, http, clock=clock)
    for m in measurements:
        client.upload(m)

    start = clock.now().to_batch_interval_start(leader_task.time_precision)
    query = Query.time_interval(Interval(Time(start.seconds - 3600), Duration(2 * 3600)))
    collector = Collector(
        CollectorParameters(
            leader_task.task_id,
            pair["leader_srv"].url,
            leader_task.collector_auth_token,
            collector_kp,
        ),
        VDAF,
        http,
    )

    threshold = 2
    prefixes = [0, 1]
    heavy = None
    for level in range(BITS):
        agg_param = Poplar1AggParam(level, tuple(sorted(prefixes))).encode()
        job_id = collector.start_collection(query, agg_param=agg_param)
        _drive(pair, http)
        result = collector.poll_once(job_id, query, agg_param=agg_param)
        assert result.report_count == len(measurements)
        counts = result.aggregate_result
        # exact per-prefix expectation (reports whose path left the
        # queried set — pruned at an earlier level — count nowhere)
        expected = [
            sum(1 for m in measurements if (m >> (BITS - 1 - level)) == p)
            for p in sorted(prefixes)
        ]
        assert counts == expected, (level, sorted(prefixes), counts, expected)
        survivors = [
            p for p, c in zip(sorted(prefixes), counts) if c >= threshold
        ]
        if level == BITS - 1:
            heavy = survivors
            break
        prefixes = [p << 1 for p in survivors] + [(p << 1) | 1 for p in survivors]

    assert heavy == [0b0110, 0b1010], heavy

    # both sides drove the real two-round machinery: every report
    # aggregation row under every parameter is FINISHED on the helper
    jobs = pair["helper_ds"].run_tx(
        lambda tx: tx.get_aggregation_jobs_for_task(helper_task.task_id)
    )
    assert len(jobs) == BITS  # one per level
    for job in jobs:
        states = {
            ra.state
            for ra in pair["helper_ds"].run_tx(
                lambda tx: tx.get_report_aggregations_for_job(
                    helper_task.task_id, job.job_id
                )
            )
        }
        assert states == {ReportAggregationState.FINISHED}, (job.job_id, states)


def test_poplar1_invalid_report_rejected(pair):
    """A malformed (multi-path) IDPF key must fail the sketch and be
    rejected by both aggregators, not silently counted."""
    import dataclasses

    from janus_tpu.vdaf.poplar1 import (
        Poplar1,
        encode_input_share,
        encode_public_share,
    )

    leader_task, helper_task, collector_kp = provision(
        pair, VDAF, max_batch_query_count=BITS + 1
    )
    http = HttpClient()
    clock = pair["clock"]
    params = ClientParameters(
        leader_task.task_id,
        pair["leader_srv"].url,
        pair["helper_srv"].url,
        leader_task.time_precision,
    )
    client = Client.with_fetched_configs(params, VDAF, http, clock=clock)

    # one honest report
    client.upload(0b1100)

    # one corrupt report: swap in a mismatched helper key share (from a
    # DIFFERENT sharding), so the two parties' evaluations do not form
    # a one-hot path and the sketch cannot verify
    poplar = Poplar1(BITS)
    cws_a, (k0_a, _) = poplar.shard(0b1100)
    _, (_, k1_b) = poplar.shard(0b0011)
    orig = Client.prepare_report

    def corrupt(self, measurement, when=None):
        report = orig(self, measurement, when=when)
        from janus_tpu.core.hpke import HpkeApplicationInfo, Label, hpke_seal
        from janus_tpu.messages import InputShareAad, PlaintextInputShare, Role

        public = encode_public_share(BITS, cws_a)
        aad = InputShareAad(self.params.task_id, report.metadata, public).to_bytes()
        leader_ct = hpke_seal(
            self.leader_hpke_config,
            HpkeApplicationInfo(Label.INPUT_SHARE, Role.CLIENT, Role.LEADER),
            PlaintextInputShare((), encode_input_share(k0_a, 0, BITS)).to_bytes(),
            aad,
        )
        helper_ct = hpke_seal(
            self.helper_hpke_config,
            HpkeApplicationInfo(Label.INPUT_SHARE, Role.CLIENT, Role.HELPER),
            PlaintextInputShare((), encode_input_share(k1_b, 1, BITS)).to_bytes(),
            aad,
        )
        return dataclasses.replace(
            report,
            public_share=public,
            leader_encrypted_input_share=leader_ct,
            helper_encrypted_input_share=helper_ct,
        )

    client.prepare_report = corrupt.__get__(client)
    client.upload(0)  # measurement ignored by the corrupt shard

    start = clock.now().to_batch_interval_start(leader_task.time_precision)
    query = Query.time_interval(Interval(Time(start.seconds - 3600), Duration(2 * 3600)))
    collector = Collector(
        CollectorParameters(
            leader_task.task_id,
            pair["leader_srv"].url,
            leader_task.collector_auth_token,
            collector_kp,
        ),
        VDAF,
        http,
    )
    agg_param = Poplar1AggParam(0, (0, 1)).encode()
    job_id = collector.start_collection(query, agg_param=agg_param)
    _drive(pair, http)
    result = collector.poll_once(job_id, query, agg_param=agg_param)
    # only the honest report survives; 0b1100 has prefix 1 at level 0
    assert result.report_count == 1
    assert result.aggregate_result == [0, 1]
