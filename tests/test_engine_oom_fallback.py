"""EngineCache OOM robustness: halve-the-bucket retry + host fallback.

Before r6 a device RESOURCE_EXHAUSTED in a serving round killed the
aggregation job (only bench.py had recovery). Now EngineCache absorbs
it: the bucket cap halves and the round retries in smaller chunks; at
the bucket floor the engine installs a permanent HostEngineCache and
the job completes at host speed. No exception may escape to the job
driver, and recovered results must be identical to a healthy engine's.
"""

import numpy as np
import pytest

from janus_tpu.aggregator import engine_cache as ec
from janus_tpu.aggregator.engine_cache import (
    DeviceRows,
    DeviceRowsChunks,
    EngineCache,
    HostEngineCache,
    bucket_size,
    is_oom_error,
)
from janus_tpu.vdaf.registry import VdafInstance
from janus_tpu.vdaf.testing import make_report_batch, random_measurements

VK = bytes(range(16))

# One instance + one module-scoped healthy reference engine: every test
# that needs an uncapped reference round reuses its compiled functions
# (three per-test EngineCaches used to recompile the identical bucket-32
# program set, ~19s each on the CPU tier-1 runner). Count keeps the
# trace/compile cost minimal — the subject here is the engine's OOM
# handling, which is circuit-independent; multi-element aggregation and
# window masking are covered by test_engine_coalesce.
INST = VdafInstance.count()


@pytest.fixture(scope="module")
def healthy():
    return EngineCache(INST, VK)

try:
    from jaxlib.xla_extension import XlaRuntimeError
except ImportError:  # pragma: no cover
    XlaRuntimeError = RuntimeError


def _oom():
    return XlaRuntimeError("RESOURCE_EXHAUSTED: Out of memory while trying to allocate")


def _job(inst, n=4, seed=1):
    rng = np.random.default_rng(seed)
    meas = random_measurements(inst, n, rng)
    args, m = make_report_batch(inst, meas, seed=seed)
    return args, m


def _full_round(eng, args, n=4):
    """Leader init + helper init + both masked aggregates through the
    public engine surface (what the job drivers call)."""
    nonce, public, meas, proof, blind0, seeds, blind1 = args
    out0, seed0, ver0, part0 = eng.leader_init(nonce, public, meas, proof, blind0)
    out1, mask, _ = eng.helper_init(
        nonce, public, seeds, blind1, ver0, part0, np.ones(n, dtype=bool)
    )
    assert np.asarray(mask).all(), "honest reports must verify"
    agg0 = eng.aggregate(out0, mask)
    agg1 = eng.aggregate(out1, mask)
    p = eng.p3.jf.MODULUS
    return [(a + b) % p for a, b in zip(agg0, agg1)]


def _failing_jit(eng, n_failures: int, exc_factory=_oom):
    """Monkeypatch the engine's jit-call seam: the first n_failures
    compiled-step invocations raise (the acceptance's 'monkeypatched
    jit call'). Thread-safe — concurrent submitters must not over-fire
    the injection budget."""
    import threading

    orig = eng._jit
    lock = threading.Lock()
    state = {"left": n_failures, "raised": 0}

    def patched(name, fn, in_shardings=None):
        real = orig(name, fn, in_shardings=in_shardings)

        def wrapper(*a, **k):
            with lock:
                fire = state["left"] > 0
                if fire:
                    state["left"] -= 1
                    state["raised"] += 1
            if fire:
                raise exc_factory()
            return real(*a, **k)

        return wrapper

    eng._jit = patched
    return state


def test_is_oom_error_classifier():
    assert is_oom_error(_oom())
    assert is_oom_error(RuntimeError("XLA:TPU ran Out of memory"))
    assert not is_oom_error(ValueError("shape mismatch"))
    # the tunnel's opaque compile 500 counts as OOM (it fires on HBM
    # overflow) but not as DEFINITE (it also fires on tunnel outages)
    tunnel = RuntimeError("remote_compile: HTTP 500 from tunnel")
    assert is_oom_error(tunnel)
    assert not ec._is_definite_oom(tunnel)
    assert ec._is_definite_oom(_oom())


def test_bucket_size_cap():
    assert bucket_size(40) == 64
    assert bucket_size(40, cap=16) == 16  # caller chunks to <= 16
    assert bucket_size(10, cap=16) == 16
    assert bucket_size(1, cap=1) == 1
    assert bucket_size(5) == 32  # MIN_BUCKET floor unchanged


def test_injected_oom_halves_bucket_and_succeeds(healthy):
    """One RESOURCE_EXHAUSTED from the jitted step: the round retries
    with a halved cap and completes with correct results."""
    inst = INST
    args, meas = _job(inst)
    want = _full_round(healthy, args)

    eng = EngineCache(inst, VK)
    # observed bucket for n=4 is MIN_BUCKET (32) — above the bucket
    # floor even on the conftest 8-virtual-device mesh (floor = dp)
    eng.bucket_cap = 32
    state = _failing_jit(eng, 1)
    got = _full_round(eng, args)
    assert got == want
    assert state["raised"] == 1
    assert eng.bucket_cap == 16  # halved from the observed bucket 32
    assert eng._host_fallback is None
    want_sum = np.atleast_1d(np.asarray(meas).sum(axis=0))
    assert got[: len(want_sum)] == [int(x) for x in want_sum]


def test_persistent_oom_falls_back_to_host_engine(healthy):
    """Every jit call raising RESOURCE_EXHAUSTED: the cap walks down to
    the bucket floor (1), the engine installs HostEngineCache, and the
    round still completes correctly — nothing escapes to the driver."""
    inst = INST
    args, meas = _job(inst)
    want = _full_round(healthy, args)

    eng = EngineCache(inst, VK)
    _failing_jit(eng, 10**9)
    got = _full_round(eng, args)
    assert got == want
    assert isinstance(eng._host_fallback, HostEngineCache)
    # subsequent rounds go straight to the host engine (no device call)
    got2 = _full_round(eng, _job(inst, seed=2)[0])
    healthy2 = _full_round(healthy, _job(inst, seed=2)[0])
    assert got2 == healthy2


def test_ambiguous_tunnel_500_fallback_reprobes_device(healthy, monkeypatch):
    """A host fallback reached only through the ambiguous tunnel-500
    marker is TIMED, not permanent: inside the cool-down the engine
    serves from the host; past it the device path is re-probed with the
    initial caps restored, so a transient tunnel outage doesn't pin a
    long-lived aggregator to the scalar host loop forever. (A definite
    RESOURCE_EXHAUSTED keeps the permanent fallback —
    test_persistent_oom_falls_back_to_host_engine.)"""
    import time as time_mod

    inst = INST
    args, _ = _job(inst)
    want = _full_round(healthy, args)

    eng = EngineCache(inst, VK)
    eng.bucket_cap = 32
    state = _failing_jit(
        eng, 10**9, exc_factory=lambda: RuntimeError("remote_compile: HTTP 500 from tunnel")
    )
    got = _full_round(eng, args)
    assert got == want
    assert isinstance(eng._host_fallback, HostEngineCache)
    assert eng._host_fallback_until is not None  # timed, not permanent

    # inside the cool-down: still served by the host engine
    state["left"] = 0  # the tunnel "recovers"
    args2, _ = _job(inst, seed=7)
    assert _full_round(eng, args2) == _full_round(healthy, args2)
    assert eng._host_fallback is not None

    # past the cool-down: device path re-probed, initial caps restored
    now = time_mod.monotonic()
    monkeypatch.setattr(
        ec.time, "monotonic", lambda: now + EngineCache.HOST_FALLBACK_RETRY_SECS + 1
    )
    args3, _ = _job(inst, seed=8)
    assert _full_round(eng, args3) == _full_round(healthy, args3)
    assert eng._host_fallback is None
    assert eng.bucket_cap == eng._initial_bucket_cap
    assert eng._co_leader._max_rows == eng._initial_round_rows


def test_non_oom_errors_still_raise():
    inst = VdafInstance.count()
    args, _ = _job(inst)
    eng = EngineCache(inst, VK)
    _failing_jit(eng, 10**9, exc_factory=lambda: ValueError("bad trace"))
    nonce, public, meas, proof, blind0, seeds, blind1 = args
    with pytest.raises(ValueError, match="bad trace"):
        eng.leader_init(nonce, public, meas, proof, blind0)
    assert eng._host_fallback is None


def test_capped_batch_chunks_and_matches_uncapped(healthy):
    """A batch larger than the cap splits into serial cap-sized
    dispatches (DeviceRowsChunks) with results identical to the
    uncapped engine."""
    inst = INST
    ref = healthy
    # cap and batch scale with dp so each chunk stays mesh-dispatchable
    # (the conftest runs an 8-virtual-device mesh; dp divides buckets);
    # n stays inside the shared healthy engine's bucket so the uncapped
    # reference round reuses its compiled functions
    cap = max(8, ref.dp)
    n = 3 * cap
    assert bucket_size(n) == bucket_size(4), "reference must reuse the healthy bucket"
    args, meas = _job(inst, n=n, seed=3)
    want = _full_round(ref, args, n=n)

    eng = EngineCache(inst, VK)
    eng.bucket_cap = cap
    eng._coalesce = False  # force the direct (chunked) path
    nonce, public, meas_v, proof, blind0, seeds, blind1 = args
    out0, seed0, ver0, part0 = eng.leader_init(nonce, public, meas_v, proof, blind0)
    assert isinstance(out0, DeviceRowsChunks)
    assert [c.n for c in out0.chunks] == [cap, cap, cap]
    out1, mask, _ = eng.helper_init(
        nonce, public, seeds, blind1, ver0, part0, np.ones(n, dtype=bool)
    )
    assert isinstance(out1, DeviceRowsChunks)
    assert np.asarray(mask).all()
    agg0 = eng.aggregate(out0, mask)
    agg1 = eng.aggregate(out1, mask)
    p = eng.p3.jf.MODULUS
    got = [(a + b) % p for a, b in zip(agg0, agg1)]
    assert got == want


def test_coalesced_round_oom_halves_cap_once(healthy):
    """One OOM in a COALESCED round must halve the cap exactly once,
    from the dispatched round's bucket — not once per co-batched
    submitter from each submitter's own small n (which walked the cap
    to the floor and permanently installed the host fallback)."""
    from concurrent.futures import ThreadPoolExecutor

    inst = INST
    eng = EngineCache(inst, VK)
    eng.bucket_cap = 32
    state = _failing_jit(eng, 1)
    jobs = [_job(inst, seed=20 + j) for j in range(6)]
    wants = [_full_round(healthy, a) for a, _ in jobs]

    def run(args):
        return _full_round(eng, args)

    with ThreadPoolExecutor(max_workers=6) as pool:
        got = list(pool.map(run, [a for a, _ in jobs]))
    assert got == wants
    assert state["raised"] == 1
    # halved once from the failed dispatch's bucket (<= 32), never to
    # the floor: the device engine must survive one transient OOM
    assert eng.bucket_cap == 16
    assert eng._host_fallback is None


def test_stale_cap_gate_chunks_instead_of_negative_pad(healthy):
    """A call that passed the entry gate before a concurrent OOM halved
    the cap reaches the inner dispatch with n > cap; it must chunk
    (DeviceRowsChunks), not die in np.pad with a negative width."""
    inst = INST
    eng = EngineCache(inst, VK)
    cap = max(1, eng.dp)  # mesh dispatches need dp | bucket
    n = 2 * cap
    args, meas = _job(inst, n=n, seed=5)
    nonce, public, meas_v, proof, blind0, seeds, blind1 = args
    _, _, ver0, part0 = healthy.leader_init(nonce, public, meas_v, proof, blind0)
    eng.bucket_cap = cap  # as if halved after the caller's gate check
    # call the inner dispatch directly — the deterministic equivalent of
    # losing the entry-gate race
    out1, mask, _ = eng._helper_init_inner(
        nonce, public, seeds, blind1, ver0, part0, np.ones(n, dtype=bool)
    )
    assert isinstance(out1, DeviceRowsChunks)
    assert np.asarray(mask).all()


def test_persistent_aggregate_oom_on_resident_rows_terminates(healthy):
    """A DeviceRows aggregate re-dispatches at the BUFFER's fixed bucket
    no matter how far the cap halves, so a persistent OOM there can
    never reach the bucket floor. The engine must fetch and reduce THAT
    buffer on host — not spin forever in aggregate()'s retry loop, and
    not install the engine-wide host fallback for an OOM specific to
    one oversized resident buffer (init dispatches at smaller buckets
    would still work on device)."""
    inst = INST
    n = 4
    args, meas = _job(inst, n=n)
    nonce, public, meas_v, proof, blind0, seeds, blind1 = args
    out0, _, _, _ = healthy.leader_init(nonce, public, meas_v, proof, blind0)
    want = healthy.aggregate(out0, np.ones(n, dtype=bool))

    eng = EngineCache(inst, VK)

    # pre-annotated exceptions model the async case where the OOM
    # surfaces at the fetch and carries the fixed buffer-bucket mark —
    # without the host-side reduce this loops forever (cap pinned at
    # observed//2, floor unreachable) and the test would hang
    def _oom_fixed():
        e = _oom()
        e._janus_dispatch_bucket = out0.value[0].shape[0]
        e._janus_fixed_bucket = True
        return e

    _failing_jit(eng, 10**9, exc_factory=_oom_fixed)
    got = eng.aggregate(out0, np.ones(n, dtype=bool))
    assert got == want
    # the device path survives: no engine-wide fallback installed
    assert eng._host_fallback is None


def test_feasibility_cap_applied_at_construction(monkeypatch):
    """A pinned JANUS_HBM_BUDGET must produce a finite bucket cap from
    the model at construction time."""
    monkeypatch.setenv("JANUS_HBM_BUDGET", str(1 << 30))  # 1 GiB
    inst = VdafInstance.sum_vec(length=1000, bits=16)
    eng = EngineCache(inst, VK)
    assert eng.bucket_cap is not None
    assert eng.bucket_cap & (eng.bucket_cap - 1) == 0
    # coalescer rounds may never exceed the cap
    assert eng._co_leader._max_rows <= eng.bucket_cap
    assert eng._co_helper._max_rows <= eng.bucket_cap


def test_env_bucket_cap_override(monkeypatch):
    monkeypatch.setenv("JANUS_BUCKET_CAP", "16")
    inst = VdafInstance.count()
    eng = EngineCache(inst, VK)
    assert eng.bucket_cap == 16
    monkeypatch.setenv("JANUS_BUCKET_CAP", "0")
    eng2 = EngineCache(inst, VK)
    assert eng2.bucket_cap is None
