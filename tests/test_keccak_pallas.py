"""Pallas Keccak kernel vs the scan-based XLA path.

Always-on in default CI: the kernels are round-parameterized, so on
CPU the differentials run the full kernel plumbing (u32-pair relayout,
tiling, padding, grid, dispatch threshold) at ROUNDS=2 in interpret
mode — the 24-round unrolled body is the only thing too slow for a
single-core interpret compile, and the round function is identical at
any count. On TPU (or with JANUS_PALLAS_TESTS=1 on a many-core host)
the same tests run at the full 24 rounds; the scan path they compare
against is pinned to hashlib at 24 rounds by tests/test_keccak.py,
which always runs.
"""

import os

import numpy as np

import jax
import jax.numpy as jnp
import pytest

from janus_tpu.vdaf import keccak_jax as kj
from janus_tpu.ops import keccak_pallas as kp

FULL = os.environ.get("JANUS_PALLAS_TESTS") == "1" or jax.default_backend() == "tpu"
ROUNDS = 24 if FULL else 2


@pytest.fixture(autouse=True)
def _interpret_mode(monkeypatch):
    if jax.default_backend() != "tpu":
        monkeypatch.setattr(kp, "_mode", lambda: "interpret")
    yield


@pytest.mark.parametrize("shape", [(4, 129)])  # pads 516 -> 1024 columns
def test_pallas_permutation_matches_scan(shape):
    rng = np.random.default_rng(sum(shape))
    state = tuple(
        jnp.asarray(rng.integers(0, 1 << 63, size=shape, dtype=np.uint64))
        for _ in range(25)
    )

    def scan_path(st):
        out, _ = jax.lax.scan(
            lambda a, rc: (kj._keccak_round(a, rc), None),
            st,
            jnp.asarray(kj._RC[:ROUNDS]),
        )
        return out

    want = scan_path(state)
    got = kp.keccak_f1600_pallas(state, rounds=ROUNDS)
    for lane, (w, g) in enumerate(zip(want, got)):
        assert (np.asarray(w) == np.asarray(g)).all(), lane


def test_pallas_stream_matches_oracle(monkeypatch):
    """Full ctr stream through the kernel path. At 24 rounds the oracle
    is hashlib (XofCtr128); at reduced rounds it is the scan path at
    the same count — either way the kernel's relayout, MIN_COLUMNS
    dispatch, and counter framing are exercised end to end."""
    from janus_tpu.vdaf.xof import XofCtr128, dst

    monkeypatch.setattr(kp, "MIN_COLUMNS", 0)
    d = dst(0x42, 2)
    seed = bytes(range(16))
    seed_lanes = jnp.asarray(kj.bytes_to_lanes(seed)[None, :])
    parts = [(0, d), (2, seed_lanes)]

    if FULL:
        got = np.asarray(kj.ctr_stream_lanes(parts, 32, 1, 3))
        want = XofCtr128(seed, d).next(3 * 168)
        assert got[0].reshape(-1).astype("<u8").tobytes() == want
        return

    # reduced rounds through BOTH paths: kernel (interpret) vs scan —
    # KECCAK_ROUNDS governs every dispatch site incl. the single-block
    # kernel the ctr path now uses
    monkeypatch.setattr(kj, "KECCAK_ROUNDS", ROUNDS)
    got = np.asarray(kj.ctr_stream_lanes(parts, 32, 1, 3))
    monkeypatch.setattr(kp, "_mode", lambda: "off")
    want = np.asarray(kj.ctr_stream_lanes(parts, 32, 1, 3))
    assert (got == want).all()


def test_single_block_kernel_matches_general(monkeypatch):
    """The 42-in/2N-out single-block kernel equals the general 50/50
    kernel's first lanes on the same messages (interpret mode, ROUNDS)."""
    rng = np.random.default_rng(9)
    shape = (3, 200)  # pads 600 -> 1024 columns
    rate = tuple(
        jnp.asarray(rng.integers(0, 1 << 63, size=shape, dtype=np.uint64))
        for _ in range(21)
    )
    state = rate + tuple(jnp.zeros(shape, jnp.uint64) for _ in range(4))
    want = kp.keccak_f1600_pallas(state, rounds=ROUNDS)
    for out_lanes in (2, 21):
        got = kp.keccak_single_block_pallas(rate, out_lanes, rounds=ROUNDS)
        assert len(got) == out_lanes
        for i in range(out_lanes):
            assert (np.asarray(got[i]) == np.asarray(want[i])).all(), i
