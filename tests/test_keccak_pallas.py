"""Pallas Keccak kernel vs the scan-based XLA path.

On TPU the kernel runs natively (validated on-chip: bit-exact vs the
scan path, see janus_tpu/ops/keccak_pallas.py). On CPU it runs in
pallas interpret mode, which for this 24-round unrolled body takes
tens of minutes on a single-core host — so these differential tests
are opt-in via JANUS_PALLAS_TESTS=1 (CI boxes with cores should set
it)."""

import os

import numpy as np
import pytest

import jax.numpy as jnp

from janus_tpu.vdaf import keccak_jax as kj
from janus_tpu.ops import keccak_pallas as kp

pytestmark = pytest.mark.skipif(
    os.environ.get("JANUS_PALLAS_TESTS") != "1"
    and __import__("jax").default_backend() != "tpu",
    reason="pallas interpret mode too slow on this host; set JANUS_PALLAS_TESTS=1",
)


@pytest.mark.parametrize("shape", [(4, 129)])  # pads 516 -> 1024 columns
def test_pallas_permutation_matches_scan(shape):
    rng = np.random.default_rng(sum(shape))
    state = tuple(
        jnp.asarray(rng.integers(0, 1 << 63, size=shape, dtype=np.uint64))
        for _ in range(25)
    )

    def scan_path(st):
        out, _ = __import__("jax").lax.scan(
            lambda a, rc: (kj._keccak_round(a, rc), None),
            st,
            jnp.asarray(kj._RC),
        )
        return out

    want = scan_path(state)
    got = kp.keccak_f1600_pallas(state)  # interpret mode off-TPU
    for lane, (w, g) in enumerate(zip(want, got)):
        assert (np.asarray(w) == np.asarray(g)).all(), lane


def test_pallas_stream_matches_hashlib(monkeypatch):
    # force the pallas (interpret) path through the full ctr stream:
    # both the mode AND the size threshold must be overridden, or the
    # tiny test stream silently takes the lax.scan path
    from janus_tpu.vdaf.xof import XofCtr128, dst

    monkeypatch.setattr(kp, "_mode", lambda: "interpret")
    monkeypatch.setattr(kp, "MIN_COLUMNS", 0)
    d = dst(0x42, 2)
    seed = bytes(range(16))
    seed_lanes = jnp.asarray(kj.bytes_to_lanes(seed)[None, :])
    parts = [(0, d), (2, seed_lanes)]
    got = np.asarray(kj.ctr_stream_lanes(parts, 32, 1, 3))
    want = XofCtr128(seed, d).next(3 * 168)
    assert got[0].reshape(-1).astype("<u8").tobytes() == want
