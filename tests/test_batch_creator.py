"""Fixed-size batch packing semantics (reference batch_creator.rs:
greedy fill toward max_batch_size, fullest-batch-first, filled batches
retired)."""

import secrets

from janus_tpu.aggregator.aggregation_job_creator import (
    AggregationJobCreator,
    AggregationJobCreatorConfig,
)
from janus_tpu.core.time_util import MockClock
from janus_tpu.datastore.store import EphemeralDatastore
from janus_tpu.messages import HpkeCiphertext, HpkeConfigId, ReportId, Role, Time
from janus_tpu.task import QueryTypeConfig, TaskBuilder
from janus_tpu.vdaf.registry import VdafInstance


def put_reports(ds, task, n, when=1_600_000_000):
    from janus_tpu.datastore.models import LeaderStoredReport

    def tx_fn(tx):
        for _ in range(n):
            tx.put_client_report(
                LeaderStoredReport(
                    task.task_id,
                    ReportId(secrets.token_bytes(16)),
                    Time(when),
                    b"",
                    b"x",
                    HpkeCiphertext(HpkeConfigId(0), b"", b""),
                )
            )

    ds.run_tx(tx_fn)


def test_fixed_size_packing_fills_and_spills():
    eph = EphemeralDatastore(clock=MockClock(Time(1_600_000_000)))
    ds = eph.datastore
    try:
        task = (
            TaskBuilder(
                QueryTypeConfig.fixed_size(max_batch_size=5),
                VdafInstance.count(),
                Role.LEADER,
            )
            .with_(min_batch_size=1)
            .build()
        )
        ds.run_tx(lambda tx: tx.put_task(task))
        put_reports(ds, task, 12)

        creator = AggregationJobCreator(
            ds,
            AggregationJobCreatorConfig(
                min_aggregation_job_size=1, max_aggregation_job_size=100
            ),
        )
        creator.run_once()

        # outstanding batches: two filled (5+5), one open with 2
        rows = ds.run_tx(
            lambda tx: tx._c.execute(
                "SELECT size, filled FROM outstanding_batches WHERE task_id = ?"
                " ORDER BY size DESC",
                (task.task_id.data,),
            ).fetchall()
        )
        assert [tuple(r) for r in rows] == [(5, 1), (5, 1), (2, 0)]

        # a later pass tops up the open batch first
        put_reports(ds, task, 4)
        creator.run_once()
        rows = ds.run_tx(
            lambda tx: tx._c.execute(
                "SELECT size, filled FROM outstanding_batches WHERE task_id = ?"
                " ORDER BY size DESC",
                (task.task_id.data,),
            ).fetchall()
        )
        assert [tuple(r) for r in rows] == [(5, 1), (5, 1), (5, 1), (1, 0)]

        # every report aggregation's job points at a batch with size <= 5
        jobs = ds.run_tx(lambda tx: tx.get_aggregation_jobs_for_task(task.task_id))
        per_batch = {}
        for job in jobs:
            ras = ds.run_tx(
                lambda tx, j=job: tx.get_report_aggregations_for_job(task.task_id, j.job_id)
            )
            per_batch[job.partial_batch_identifier] = per_batch.get(
                job.partial_batch_identifier, 0
            ) + len(ras)
        assert sum(per_batch.values()) == 16
        assert all(v <= 5 for v in per_batch.values())
    finally:
        eph.cleanup()
