"""Fixed-size batch packing semantics (reference batch_creator.rs:
greedy fill toward max_batch_size, fullest-batch-first, filled batches
retired)."""

import secrets

from janus_tpu.aggregator.aggregation_job_creator import (
    AggregationJobCreator,
    AggregationJobCreatorConfig,
)
from janus_tpu.core.time_util import MockClock
from janus_tpu.datastore.store import EphemeralDatastore
from janus_tpu.messages import HpkeCiphertext, HpkeConfigId, ReportId, Role, Time
from janus_tpu.task import QueryTypeConfig, TaskBuilder
from janus_tpu.vdaf.registry import VdafInstance


def put_reports(ds, task, n, when=1_600_000_000):
    from janus_tpu.datastore.models import LeaderStoredReport

    def tx_fn(tx):
        for _ in range(n):
            tx.put_client_report(
                LeaderStoredReport(
                    task.task_id,
                    ReportId(secrets.token_bytes(16)),
                    Time(when),
                    b"",
                    b"x",
                    HpkeCiphertext(HpkeConfigId(0), b"", b""),
                )
            )

    ds.run_tx(tx_fn)


def test_fixed_size_packing_fills_and_spills():
    eph = EphemeralDatastore(clock=MockClock(Time(1_600_000_000)))
    ds = eph.datastore
    try:
        task = (
            TaskBuilder(
                QueryTypeConfig.fixed_size(max_batch_size=5),
                VdafInstance.count(),
                Role.LEADER,
            )
            .with_(min_batch_size=1)
            .build()
        )
        ds.run_tx(lambda tx: tx.put_task(task))
        put_reports(ds, task, 12)

        creator = AggregationJobCreator(
            ds,
            AggregationJobCreatorConfig(
                min_aggregation_job_size=1, max_aggregation_job_size=100
            ),
        )
        creator.run_once()

        # outstanding batches: two filled (5+5), one open with 2
        rows = ds.run_tx(
            lambda tx: tx._c.execute(
                "SELECT size, filled FROM outstanding_batches WHERE task_id = ?"
                " ORDER BY size DESC",
                (task.task_id.data,),
            ).fetchall()
        )
        assert [tuple(r) for r in rows] == [(5, 1), (5, 1), (2, 0)]

        # a later pass tops up the open batch first
        put_reports(ds, task, 4)
        creator.run_once()
        rows = ds.run_tx(
            lambda tx: tx._c.execute(
                "SELECT size, filled FROM outstanding_batches WHERE task_id = ?"
                " ORDER BY size DESC",
                (task.task_id.data,),
            ).fetchall()
        )
        assert [tuple(r) for r in rows] == [(5, 1), (5, 1), (5, 1), (1, 0)]

        # every report aggregation's job points at a batch with size <= 5
        jobs = ds.run_tx(lambda tx: tx.get_aggregation_jobs_for_task(task.task_id))
        per_batch = {}
        for job in jobs:
            ras = ds.run_tx(
                lambda tx, j=job: tx.get_report_aggregations_for_job(task.task_id, j.job_id)
            )
            per_batch[job.partial_batch_identifier] = per_batch.get(
                job.partial_batch_identifier, 0
            ) + len(ras)
        assert sum(per_batch.values()) == 16
        assert all(v <= 5 for v in per_batch.values())
    finally:
        eph.cleanup()


def test_creator_sweeps_tasks_concurrently():
    """N tasks sweep in parallel workers with no cross-task
    serialization (reference runs a worker per task,
    aggregation_job_creator.rs:210): every task gets its job, and at
    least two sweeps are observed in flight simultaneously."""
    import threading

    from janus_tpu.core.auth import AuthenticationToken
    from janus_tpu.core.hpke import generate_hpke_config_and_private_key

    clock = MockClock(Time(1_600_000_000))
    eph = EphemeralDatastore(clock=clock)
    ds = eph.datastore
    tasks = []
    for i in range(4):
        task = (
            TaskBuilder(QueryTypeConfig.time_interval(), VdafInstance.count(), Role.LEADER)
            .with_(
                collector_hpke_config=generate_hpke_config_and_private_key(config_id=i).config,
                aggregator_auth_token=AuthenticationToken.random_bearer(),
                collector_auth_token=AuthenticationToken.random_bearer(),
                min_batch_size=1,
            )
            .build()
        )
        ds.run_tx(lambda tx, t=task: tx.put_task(t))
        put_reports(ds, task, 3)
        tasks.append(task)

    creator = AggregationJobCreator(
        ds, AggregationJobCreatorConfig(min_aggregation_job_size=1, max_concurrent_tasks=4)
    )
    in_flight = {"now": 0, "peak": 0}
    lock = threading.Lock()
    gate = threading.Barrier(2, timeout=10.0)
    orig = creator.create_jobs_for_task

    def instrumented(task):
        with lock:
            in_flight["now"] += 1
            in_flight["peak"] = max(in_flight["peak"], in_flight["now"])
        try:
            gate.wait()  # blocks until a second sweep is concurrent
        except threading.BrokenBarrierError:
            pass  # >2 workers racing past an already-broken barrier is fine
        try:
            return orig(task)
        finally:
            with lock:
                in_flight["now"] -= 1
        
    creator.create_jobs_for_task = instrumented
    created = creator.run_once()
    assert created == 4
    assert in_flight["peak"] >= 2
    for task in tasks:
        jobs = ds.run_tx(lambda tx, t=task: tx.get_aggregation_jobs_for_task(t.task_id))
        assert len(jobs) == 1
    eph.cleanup()
