"""Fleet scale-out invariants (ISSUE 15; docs/ARCHITECTURE.md "Running
a fleet"): batched sharded lease claims, steal-after-delay drain of a
dead replica's shard, lease-token provenance, conflict counting, and
the creator's task-shard preference."""

import os
import secrets
import threading

import pytest
from conftest import DATASTORE_ENGINES

from janus_tpu.core.time_util import MockClock
from janus_tpu.datastore.models import AggregationJobModel, AggregationJobState, ShardSpec
from janus_tpu.datastore.store import (
    SHARD_KEY_SPACE,
    EphemeralDatastore,
    LeaseConflict,
    job_shard_key,
    lease_holder_hex,
    replica_holder_tag,
)
from janus_tpu.messages import AggregationJobId, Duration, Interval, Role, Time
from janus_tpu.task import QueryTypeConfig, TaskBuilder
from janus_tpu.vdaf.registry import VdafInstance


@pytest.fixture(params=DATASTORE_ENGINES)
def engine(request):
    return request.param


def make_task(ds):
    task = (
        TaskBuilder(QueryTypeConfig.time_interval(), VdafInstance.count(), Role.LEADER)
        .with_(min_batch_size=1)
        .build()
    )
    ds.run_tx(lambda tx: tx.put_task(task))
    return task


def put_job(ds, task, job_id_bytes):
    job = AggregationJobModel(
        task.task_id,
        AggregationJobId(job_id_bytes),
        b"",
        b"\x01",
        Interval(Time(1_600_000_000), Duration(1)),
        AggregationJobState.IN_PROGRESS,
        0,
    )
    ds.run_tx(lambda tx: tx.put_aggregation_job(job))
    return job


def test_shard_key_is_stable_and_bounded():
    """Same (task, job) identity -> same key, every process, any
    PYTHONHASHSEED; keys stay inside the declared modulo space."""
    t, j = secrets.token_bytes(32), secrets.token_bytes(16)
    k = job_shard_key(t, j)
    assert k == job_shard_key(t, j)
    assert 0 <= k < SHARD_KEY_SPACE
    # distinct jobs spread (not a collision proof, a sanity bound)
    keys = {job_shard_key(t, i.to_bytes(16, "big")) for i in range(256)}
    assert len(keys) > 200


def test_batched_claim_partitions_exactly_across_racing_handles(engine):
    """Two datastore handles racing batched claims over the same rows
    must partition them exactly: no row claimed twice, no eligible row
    missed — the FOR UPDATE SKIP LOCKED contract, batched."""
    eph = EphemeralDatastore(clock=MockClock(Time(1_600_000_000)), engine=engine)
    ds = eph.datastore
    try:
        task = make_task(ds)
        n_jobs = 24
        for i in range(n_jobs):
            put_job(ds, task, i.to_bytes(16, "big"))
        acquired = []
        lock = threading.Lock()

        def worker():
            got = ds.run_tx(
                lambda tx: tx.acquire_incomplete_aggregation_jobs(Duration(600), 12),
                "acq",
            )
            with lock:
                acquired.extend(got)

        threads = [threading.Thread(target=worker) for _ in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        ids = [a.job_id.data for a in acquired]
        assert len(ids) == len(set(ids)), "a job was leased to two claimers"
        assert len(ids) == n_jobs
        # every batch shares ONE token (identity pins the row); tokens
        # differ BETWEEN claim transactions
        by_token = {}
        for a in acquired:
            by_token.setdefault(a.lease.token, []).append(a)
        assert len(by_token) >= 2
    finally:
        eph.cleanup()


def test_expired_lease_reacquired_with_monotone_attempts(engine):
    """The expired-lease re-acquire path through the batched claim:
    attempts increment monotonically across generations, and the stale
    holder's guarded writes raise LeaseConflict."""
    clock = MockClock(Time(1_600_000_000))
    eph = EphemeralDatastore(clock=clock, engine=engine)
    ds = eph.datastore
    try:
        task = make_task(ds)
        put_job(ds, task, bytes(16))
        (a1,) = ds.run_tx(
            lambda tx: tx.acquire_incomplete_aggregation_jobs(Duration(10), 4)
        )
        assert a1.lease.attempts == 1
        # not yet expired: nothing eligible
        assert (
            ds.run_tx(lambda tx: tx.acquire_incomplete_aggregation_jobs(Duration(10), 4))
            == []
        )
        clock.advance(Duration(60))
        (a2,) = ds.run_tx(
            lambda tx: tx.acquire_incomplete_aggregation_jobs(Duration(600), 4)
        )
        assert a2.lease.attempts == 2
        assert a2.lease.token != a1.lease.token
        with pytest.raises(LeaseConflict):
            with ds.tx() as tx:
                tx.release_aggregation_job(a1)
        ds.run_tx(lambda tx: tx.release_aggregation_job(a2))
    finally:
        eph.cleanup()


def test_shard_predicate_and_steal_after_delay(engine):
    """Replica 0 of 2 claims only its own shard immediately; the other
    shard's rows become claimable to it only after steal_after_s of
    eligibility — a dead replica's shard drains, late."""
    clock = MockClock(Time(1_600_000_000))
    eph = EphemeralDatastore(clock=clock, engine=engine)
    ds = eph.datastore
    try:
        task = make_task(ds)
        jobs = [put_job(ds, task, i.to_bytes(16, "big")) for i in range(32)]
        count = 2
        own = {
            j.job_id.data
            for j in jobs
            if job_shard_key(task.task_id.data, j.job_id.data) % count == 0
        }
        assert 0 < len(own) < len(jobs)  # both shards populated
        shard0 = ShardSpec(shard_count=2, shard_index=0, steal_after_s=30)
        got = ds.run_tx(
            lambda tx: tx.acquire_incomplete_aggregation_jobs(
                Duration(600), 64, shard=shard0
            )
        )
        assert {a.job_id.data for a in got} == own, "claimed outside the shard"
        # before the steal delay: the foreign shard stays foreign
        clock.advance(Duration(10))
        assert (
            ds.run_tx(
                lambda tx: tx.acquire_incomplete_aggregation_jobs(
                    Duration(600), 64, shard=shard0
                )
            )
            == []
        )
        # past the steal delay: the dead replica's shard drains
        clock.advance(Duration(31))
        stolen = ds.run_tx(
            lambda tx: tx.acquire_incomplete_aggregation_jobs(
                Duration(600), 64, shard=shard0
            )
        )
        assert {a.job_id.data for a in stolen} == {
            j.job_id.data for j in jobs
        } - own
    finally:
        eph.cleanup()


def test_own_shard_claims_before_stolen_rows(engine):
    """With both own and stealable rows eligible, the claim order
    prefers the replica's own shard (the CASE priority ahead of the
    random() shuffle)."""
    clock = MockClock(Time(1_600_000_000))
    eph = EphemeralDatastore(clock=clock, engine=engine)
    ds = eph.datastore
    try:
        task = make_task(ds)
        jobs = [put_job(ds, task, i.to_bytes(16, "big")) for i in range(32)]
        count = 2
        own = {
            j.job_id.data
            for j in jobs
            if job_shard_key(task.task_id.data, j.job_id.data) % count == 0
        }
        clock.advance(Duration(60))  # everything past any steal delay
        shard0 = ShardSpec(shard_count=2, shard_index=0, steal_after_s=1)
        got = ds.run_tx(
            lambda tx: tx.acquire_incomplete_aggregation_jobs(
                Duration(600), len(own), shard=shard0
            )
        )
        assert {a.job_id.data for a in got} == own
    finally:
        eph.cleanup()


def test_shutdown_handback_is_instantly_stealable(engine):
    """A clean shutdown hand-back (step_back handback=True) RELEASES
    the row's shard affinity: a FOREIGN-shard survivor claims the job
    immediately — and the claim classifies as a hand-back (never a
    steal: rolling restarts must not fire the starving-shard alert) —
    while a plain step-back stays fenced for steal_after_s."""
    clock = MockClock(Time(1_600_000_000))
    eph = EphemeralDatastore(clock=clock, engine=engine)
    ds = eph.datastore
    try:
        task = make_task(ds)
        jobs = [put_job(ds, task, i.to_bytes(16, "big")) for i in range(16)]
        shard0 = ShardSpec(shard_count=2, shard_index=0, steal_after_s=30)
        shard1 = ShardSpec(shard_count=2, shard_index=1, steal_after_s=30)
        own1 = {
            j.job_id.data
            for j in jobs
            if job_shard_key(task.task_id.data, j.job_id.data) % 2 == 1
        }
        assert own1  # P(empty) = 2^-16 over the random task id
        got = ds.run_tx(
            lambda tx: tx.acquire_incomplete_collection_jobs(Duration(600), 1)
        )  # no collection jobs; keep the claim paths exercised symmetrically
        assert got == []
        held = ds.run_tx(
            lambda tx: tx.acquire_incomplete_aggregation_jobs(
                Duration(600), 64, shard=shard1
            )
        )
        assert {a.job_id.data for a in held} == own1
        # some hand back cleanly (shutdown drain), the rest plain
        # step-back — disjoint slices, at least one handed back
        half = max(1, len(held) // 2)
        handed, fenced = held[:half], held[half:]

        def give_back(tx):
            for a in handed:
                tx.step_back_aggregation_job(a, 0, handback=True)
            for a in fenced:
                tx.step_back_aggregation_job(a, 0)

        ds.run_tx(give_back)
        crossed = ds.run_tx(
            lambda tx: tx.acquire_incomplete_aggregation_jobs(
                Duration(600), 64, shard=shard0
            )
        )
        crossed_foreign = {
            a.job_id.data
            for a in crossed
            if job_shard_key(task.task_id.data, a.job_id.data) % 2 == 1
        }
        # the handed-back jobs crossed the shard fence IMMEDIATELY; the
        # plain step-backs stayed fenced
        assert crossed_foreign == {a.job_id.data for a in handed}
        # ...and they carry the released-affinity sentinel, so the
        # steal classifier never counts a hand-back as a steal
        from janus_tpu import metrics
        from janus_tpu.aggregator.job_driver import record_acquire

        handed_claims = [a for a in crossed if a.job_id.data in crossed_foreign]
        assert all(a.shard_key is not None and a.shard_key < 0 for a in handed_claims)
        steals0 = metrics.lease_steals_total.get(kind="aggregation")
        record_acquire("aggregation", crossed, shard0)
        assert metrics.lease_steals_total.get(kind="aggregation") == steals0
    finally:
        eph.cleanup()


def test_parked_acquirer_records_no_claim_tx(engine):
    """An acquirer parked on a datastore outage ran NO claim
    transaction and must not count one (the fleet claim counters stay
    honest through exactly the outages they should measure)."""
    import types

    from janus_tpu import metrics
    from janus_tpu.aggregator.aggregation_job_driver import AggregationJobDriver

    eph = EphemeralDatastore(clock=MockClock(Time(1_600_000_000)), engine=engine)
    ds = eph.datastore
    try:
        task = make_task(ds)
        put_job(ds, task, bytes(16))
        acquire = AggregationJobDriver(ds, http=None).acquirer(600)
        before = (
            metrics.lease_acquire_tx_total.get(kind="aggregation", outcome="empty"),
            metrics.lease_acquire_tx_total.get(kind="aggregation", outcome="claimed"),
        )
        ds.supervisor = types.SimpleNamespace(state="down", stop=lambda: None)
        assert acquire(4) == []  # parked, no tx
        after = (
            metrics.lease_acquire_tx_total.get(kind="aggregation", outcome="empty"),
            metrics.lease_acquire_tx_total.get(kind="aggregation", outcome="claimed"),
        )
        assert after == before
        ds.supervisor = None
        assert len(acquire(4)) == 1  # healthy again: the claim counts
        assert (
            metrics.lease_acquire_tx_total.get(kind="aggregation", outcome="claimed")
            == before[1] + 1
        )
    finally:
        eph.cleanup()


def test_claim_order_is_randomized_within_the_window(engine):
    """Satellite: the deterministic ORDER BY lease_expiry scan is gone
    — single-row claims over a fresh 20-row store must not always hand
    out the same row (P[all equal] = 20^-7 under random order)."""
    seen = set()
    for _ in range(8):
        eph = EphemeralDatastore(clock=MockClock(Time(1_600_000_000)), engine=engine)
        ds = eph.datastore
        try:
            task = make_task(ds)
            for i in range(20):
                put_job(ds, task, i.to_bytes(16, "big"))
            (a,) = ds.run_tx(
                lambda tx: tx.acquire_incomplete_aggregation_jobs(Duration(600), 1)
            )
            seen.add(a.job_id.data)
        finally:
            eph.cleanup()
    assert len(seen) > 1, "claim order is still deterministic"


def test_claim_window_prefers_oldest_under_deep_backlog(engine):
    """The randomization is WINDOWED: with far more eligible rows than
    the candidate window, a claim only ever picks from the oldest
    window — a deep post-outage backlog drains oldest-first at window
    granularity instead of losing all fairness to the shuffle."""
    clock = MockClock(Time(1_600_000_000))
    eph = EphemeralDatastore(clock=clock, engine=engine)
    ds = eph.datastore
    try:
        task = make_task(ds)
        # 96 jobs with staggered eligible-since stamps (creation time)
        by_age = []
        for i in range(96):
            by_age.append(put_job(ds, task, i.to_bytes(16, "big")).job_id.data)
            clock.advance(Duration(1))
        claimed = 0
        for _ in range(6):
            # the window covers the oldest 64 STILL-ELIGIBLE rows, so
            # after `claimed` rows left the pool it can reach at most
            # rank 64 + claimed of the original age order
            allowed = set(by_age[: 64 + claimed])  # window = max(4*limit, 64)
            got = ds.run_tx(
                lambda tx: tx.acquire_incomplete_aggregation_jobs(Duration(600), 4)
            )
            assert got, "eligible rows must keep claiming"
            assert {a.job_id.data for a in got} <= allowed
            claimed += len(got)
    finally:
        eph.cleanup()


def test_lease_conflict_counted_and_fatal(engine):
    """Satellite: a token mismatch on release/step-back counts in
    janus_lease_conflicts_total{kind,op} and classifies fatal — run_tx
    raises immediately instead of burning 16 retries."""
    from janus_tpu import metrics

    clock = MockClock(Time(1_600_000_000))
    eph = EphemeralDatastore(clock=clock, engine=engine)
    ds = eph.datastore
    try:
        task = make_task(ds)
        put_job(ds, task, bytes(16))
        (a1,) = ds.run_tx(
            lambda tx: tx.acquire_incomplete_aggregation_jobs(Duration(10), 1)
        )
        clock.advance(Duration(60))
        (a2,) = ds.run_tx(
            lambda tx: tx.acquire_incomplete_aggregation_jobs(Duration(600), 1)
        )
        before_rel = metrics.lease_conflicts_total.get(
            kind="aggregation", op="release"
        )
        before_sb = metrics.lease_conflicts_total.get(
            kind="aggregation", op="step_back"
        )
        with pytest.raises(LeaseConflict):
            ds.run_tx(lambda tx: tx.release_aggregation_job(a1))
        with pytest.raises(LeaseConflict):
            ds.run_tx(lambda tx: tx.step_back_aggregation_job(a1))
        assert (
            metrics.lease_conflicts_total.get(kind="aggregation", op="release")
            == before_rel + 1
        ), "one conflict event must count exactly once (no retry amplification)"
        assert (
            metrics.lease_conflicts_total.get(kind="aggregation", op="step_back")
            == before_sb + 1
        )
        assert ds.classify_error(LeaseConflict("x")) == "fatal"
        ds.run_tx(lambda tx: tx.release_aggregation_job(a2))
    finally:
        eph.cleanup()


def test_lease_token_carries_replica_provenance(engine):
    """The tokens a fleet-configured acquirer mints carry the replica's
    8-byte provenance tag, readable off the held rows."""
    eph = EphemeralDatastore(clock=MockClock(Time(1_600_000_000)), engine=engine)
    ds = eph.datastore
    try:
        task = make_task(ds)
        put_job(ds, task, bytes(16))
        tag = replica_holder_tag("replica-7")
        (a,) = ds.run_tx(
            lambda tx: tx.acquire_incomplete_aggregation_jobs(
                Duration(600), 1, holder=tag
            )
        )
        assert a.lease.token[:8] == tag
        holders = ds.run_tx(lambda tx: tx.get_lease_holders())
        assert [(h[0], h[3]) for h in holders] == [("aggregation", tag.hex())]
        assert lease_holder_hex(a.lease.token) == tag.hex()
    finally:
        eph.cleanup()


def test_fleet_config_yaml_and_env_overrides(monkeypatch):
    """fleet: stanza parses; env vars (container fleets) win over YAML."""
    from janus_tpu.config import FleetConfig

    cfg = FleetConfig.from_dict(
        {"replica_id": "r-1", "shard_count": 4, "shard_index": 2, "steal_after_secs": 5}
    )
    assert cfg.replica_id == "r-1" and cfg.shard_count == 4 and cfg.shard_index == 2
    spec = cfg.shard_spec()
    assert spec is not None and spec.active and spec.steal_after_s == 5
    assert cfg.holder_tag() == replica_holder_tag("r-1")

    monkeypatch.setenv("JANUS_REPLICA_ID", "env-r")
    monkeypatch.setenv("JANUS_SHARD_COUNT", "8")
    monkeypatch.setenv("JANUS_SHARD_INDEX", "5")
    monkeypatch.setenv("JANUS_STEAL_AFTER_S", "2.5")
    cfg = FleetConfig.from_dict({"replica_id": "yaml-r", "shard_count": 2})
    assert cfg.replica_id == "env-r"
    assert cfg.shard_count == 8 and cfg.shard_index == 5
    assert cfg.steal_after_secs == 2.5
    # unsharded default: the predicate compiles away
    for var in (
        "JANUS_REPLICA_ID",
        "JANUS_SHARD_COUNT",
        "JANUS_SHARD_INDEX",
        "JANUS_STEAL_AFTER_S",
    ):
        monkeypatch.delenv(var)
    assert FleetConfig.from_dict(None).shard_spec() is None


def test_replica_labels_off_by_default_on_when_configured():
    """metrics.replica_labels() stays {} until an explicit identity is
    installed (single-process label sets unchanged), then carries the
    replica id; janus_replica_info re-registration is exclusive."""
    from janus_tpu import metrics

    try:
        metrics.set_replica_identity()  # auto id: UNLABELED
        assert metrics.replica_labels() == {}
        metrics.set_replica_identity("fleet-a", shard_index=1, shard_count=4)
        assert metrics.replica_labels() == {"replica": "fleet-a"}
        live = [
            (k, v)
            for k, v in metrics.replica_info._values.items()
            if v == 1.0
        ]
        assert len(live) == 1
        labels = dict(live[0][0])
        assert labels == {
            "replica_id": "fleet-a",
            "shard_index": "1",
            "shard_count": "4",
        }
    finally:
        metrics.set_replica_identity()  # restore the unlabeled default


def test_acquirer_records_claim_and_steal_metrics(engine):
    """The driver acquirer feeds janus_lease_acquire_tx_total /
    janus_lease_acquired_jobs_total / janus_lease_steals_total —
    including steals through the steal-after fallback."""
    from janus_tpu import metrics
    from janus_tpu.aggregator.aggregation_job_driver import AggregationJobDriver
    from janus_tpu.config import FleetConfig

    clock = MockClock(Time(1_600_000_000))
    eph = EphemeralDatastore(clock=clock, engine=engine)
    ds = eph.datastore
    try:
        task = make_task(ds)
        for i in range(16):
            put_job(ds, task, i.to_bytes(16, "big"))
        clock.advance(Duration(60))  # everything stealable
        fleet = FleetConfig(replica_id="r-0", shard_count=2, shard_index=0,
                            steal_after_secs=1)
        drv = AggregationJobDriver(ds, http=None)
        acquire = drv.acquirer(600, fleet=fleet)
        tx0 = metrics.lease_acquire_tx_total.get(
            kind="aggregation", outcome="claimed", replica="r-0"
        )
        jobs0 = metrics.lease_acquired_jobs_total.get(
            kind="aggregation", replica="r-0"
        )
        steals0 = metrics.lease_steals_total.get(kind="aggregation", replica="r-0")
        # replica labels ride the families only while configured
        metrics.set_replica_identity("r-0", shard_index=0, shard_count=2)
        try:
            got = acquire(16)
        finally:
            metrics.set_replica_identity()
        assert len(got) == 16
        own = sum(
            1
            for a in got
            if job_shard_key(a.task_id.data, a.job_id.data) % 2 == 0
        )
        assert (
            metrics.lease_acquire_tx_total.get(
                kind="aggregation", outcome="claimed", replica="r-0"
            )
            == tx0 + 1
        )
        assert (
            metrics.lease_acquired_jobs_total.get(kind="aggregation", replica="r-0")
            == jobs0 + 16
        )
        assert (
            metrics.lease_steals_total.get(kind="aggregation", replica="r-0")
            == steals0 + (16 - own)
        )
    finally:
        eph.cleanup()


def test_creator_shard_preference_with_steal(engine):
    """A creator replica sweeps only its own shard's tasks until a
    foreign task's unaggregated backlog ages past the steal delay."""
    from janus_tpu.aggregator.aggregation_job_creator import (
        AggregationJobCreator,
        AggregationJobCreatorConfig,
    )
    from janus_tpu.config import FleetConfig
    from janus_tpu.datastore.models import LeaderStoredReport
    from janus_tpu.messages import HpkeCiphertext, HpkeConfigId, ReportId

    clock = MockClock(Time(1_600_000_000))
    eph = EphemeralDatastore(clock=clock, engine=engine)
    ds = eph.datastore
    try:
        # find two tasks landing on opposite creator shards
        tasks = []
        while len(tasks) < 2:
            t = make_task(ds)
            shard = job_shard_key(t.task_id.data, b"") % 2
            if not any(
                job_shard_key(x.task_id.data, b"") % 2 == shard for x in tasks
            ):
                tasks.append(t)
        tasks.sort(key=lambda t: job_shard_key(t.task_id.data, b"") % 2)
        now = clock.now().seconds

        def put_reports(tx):
            for t in tasks:
                for _ in range(3):
                    tx.put_client_report(
                        LeaderStoredReport(
                            t.task_id,
                            ReportId(secrets.token_bytes(16)),
                            Time(now),
                            b"",
                            b"x",
                            HpkeCiphertext(HpkeConfigId(0), b"", b""),
                        )
                    )

        ds.run_tx(put_reports)
        creator = AggregationJobCreator(
            ds,
            AggregationJobCreatorConfig(min_aggregation_job_size=1),
            fleet=FleetConfig(
                replica_id="c-0", shard_count=2, shard_index=0, steal_after_secs=30
            ),
        )
        assert creator.run_once() == 1  # own-shard task only
        jobs_own = ds.run_tx(
            lambda tx: tx.get_aggregation_jobs_for_task(tasks[0].task_id)
        )
        jobs_foreign = ds.run_tx(
            lambda tx: tx.get_aggregation_jobs_for_task(tasks[1].task_id)
        )
        assert len(jobs_own) == 1 and len(jobs_foreign) == 0
        # owner progress resets the window: the "owner" claims a report
        # (aggregated count moves), so even past the steal delay the
        # foreign replica must NOT steal yet
        ds.run_tx(
            lambda tx: tx.get_unaggregated_client_reports_for_task(
                tasks[1].task_id, 1
            ),
            "owner_progress",
        )
        clock.advance(Duration(60))
        assert creator.run_once() == 0
        # no further progress across the whole window: stolen
        clock.advance(Duration(60))
        assert creator.run_once() == 1
        jobs_foreign = ds.run_tx(
            lambda tx: tx.get_aggregation_jobs_for_task(tasks[1].task_id)
        )
        assert len(jobs_foreign) == 1
        # backlog drained -> the steal timer AND the sticky-steal set
        # reset: the next sweep neither steals nor keeps stale state
        clock.advance(Duration(60))
        assert creator.run_once() == 0
        assert creator._foreign_backlog_first_seen == {}
        assert creator._stealing == set()
    finally:
        eph.cleanup()


@pytest.mark.skipif(os.name != "posix", reason="posix-only")
def test_collection_job_claims_shard_and_partition(engine):
    """The collection-job claim shares the batched/sharded contract."""
    from janus_tpu.datastore.models import CollectionJobModel, CollectionJobState
    from janus_tpu.messages import CollectionJobId

    clock = MockClock(Time(1_600_000_000))
    eph = EphemeralDatastore(clock=clock, engine=engine)
    ds = eph.datastore
    try:
        task = make_task(ds)

        def put_cj(tx, i):
            tx.put_collection_job(
                CollectionJobModel(
                    task.task_id,
                    CollectionJobId(i.to_bytes(16, "big")),
                    b"q%d" % i,
                    b"",
                    b"b",
                    CollectionJobState.START,
                )
            )

        for i in range(16):
            ds.run_tx(lambda tx, i=i: put_cj(tx, i))
        shard0 = ShardSpec(shard_count=2, shard_index=0, steal_after_s=30)
        own = {
            i.to_bytes(16, "big")
            for i in range(16)
            if job_shard_key(task.task_id.data, i.to_bytes(16, "big")) % 2 == 0
        }
        got = ds.run_tx(
            lambda tx: tx.acquire_incomplete_collection_jobs(
                Duration(600), 32, shard=shard0
            )
        )
        assert {a.collection_job_id.data for a in got} == own
    finally:
        eph.cleanup()
