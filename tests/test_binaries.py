"""Process-level binary tests: each binary boots from a YAML config,
serves /healthz, and drains cleanly on SIGTERM — the analog of the
reference's graceful-shutdown suite (aggregator/tests/graceful_shutdown.rs)
and trycmd CLI goldens (aggregator/tests/cli.rs)."""

import base64
import os
import secrets
import signal
import subprocess
import sys
import time
import urllib.request

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

BINARIES = [
    ("aggregator", "listen_address: \"127.0.0.1:{dap_port}\"\n"),
    ("aggregation_job_creator", "aggregation_job_creation_interval_secs: 0.5\n"),
    ("aggregation_job_driver", ""),
    ("collection_job_driver", ""),
]


def wait_healthz(port: int, deadline_s: float = 60.0) -> None:
    deadline = time.monotonic() + deadline_s
    while True:
        try:
            with urllib.request.urlopen(f"http://127.0.0.1:{port}/healthz", timeout=2) as r:
                assert r.status == 200
                return
        except Exception:
            if time.monotonic() > deadline:
                raise
            time.sleep(0.5)


@pytest.mark.parametrize(
    "idx,name,extra",
    [(i, n, e) for i, (n, e) in enumerate(BINARIES)],
    ids=[b[0] for b in BINARIES],
)
def test_binary_boots_and_drains_on_sigterm(tmp_path, idx, name, extra):
    health_port = 20200 + idx
    dap_port = health_port + 1000
    cfg = tmp_path / "cfg.yaml"
    cfg.write_text(
        f"database: {{url: {tmp_path}/ds.sqlite}}\n"
        f"health_check_listen_address: \"127.0.0.1:{health_port}\"\n"
        "jax_platform: cpu\n" + extra.format(dap_port=dap_port)
    )
    key = base64.urlsafe_b64encode(secrets.token_bytes(16)).decode().rstrip("=")
    env = dict(os.environ, PYTHONPATH=REPO, DATASTORE_KEYS=key, JAX_PLATFORMS="cpu")
    proc = subprocess.Popen(
        [sys.executable, "-m", f"janus_tpu.bin.{name}", "--config-file", str(cfg)],
        env=env,
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        cwd=REPO,
    )
    try:
        wait_healthz(health_port)
        proc.send_signal(signal.SIGTERM)
        out, _ = proc.communicate(timeout=30)
        assert proc.returncode == 0, out.decode()[-2000:]
        assert b"shut down" in out
    finally:
        if proc.poll() is None:
            proc.kill()


def test_janus_cli_help_and_bad_args():
    env = dict(os.environ, PYTHONPATH=REPO)
    out = subprocess.run(
        [sys.executable, "-m", "janus_tpu.bin.janus_cli", "--help"],
        env=env, capture_output=True, cwd=REPO,
    )
    assert out.returncode == 0
    for cmd in ("provision-tasks", "create-datastore-key", "list-tasks"):
        assert cmd.encode() in out.stdout

    out = subprocess.run(
        [sys.executable, "-m", "janus_tpu.bin.janus_cli", "no-such-command"],
        env=env, capture_output=True, cwd=REPO,
    )
    assert out.returncode != 0


def test_warmup_engines_compiles_provisioned_tasks(caplog):
    """Boot-time engine warmup (CommonConfig.warmup_engines_at_boot)
    traces + compiles the hot steps for each provisioned task."""
    from janus_tpu.binary_utils import warmup_engines
    from janus_tpu.core.hpke import generate_hpke_config_and_private_key
    from janus_tpu.datastore.store import EphemeralDatastore
    from janus_tpu.messages import Role
    from janus_tpu.task import QueryTypeConfig, TaskBuilder
    from janus_tpu.vdaf.registry import VdafInstance

    eph = EphemeralDatastore()
    task = (
        TaskBuilder(QueryTypeConfig.time_interval(), VdafInstance.count(), Role.HELPER)
        .with_(
            collector_hpke_config=generate_hpke_config_and_private_key(config_id=3).config,
        )
        .build()
    )
    eph.datastore.run_tx(lambda tx: tx.put_task(task))
    warmup_engines(eph.datastore)  # must not raise; compiles count engine
    assert "warmup failed" not in caplog.text
    eph.cleanup()


def test_warmup_background_buckets(caplog):
    """warmup_buckets runs ahead-of-time bucket compilation in a daemon
    thread (serving is not blocked) and warms every configured bucket."""
    from janus_tpu.binary_utils import warmup_engines_background
    from janus_tpu.config import CommonConfig
    from janus_tpu.core.hpke import generate_hpke_config_and_private_key
    from janus_tpu.datastore.store import EphemeralDatastore
    from janus_tpu.messages import Role
    from janus_tpu.task import QueryTypeConfig, TaskBuilder
    from janus_tpu.vdaf.registry import VdafInstance

    cfg = CommonConfig.from_dict({"warmup_buckets": [32, 64]})
    assert cfg.warmup_buckets == (32, 64)

    eph = EphemeralDatastore()
    task = (
        TaskBuilder(QueryTypeConfig.time_interval(), VdafInstance.count(), Role.HELPER)
        .with_(
            collector_hpke_config=generate_hpke_config_and_private_key(config_id=4).config,
        )
        .build()
    )
    eph.datastore.run_tx(lambda tx: tx.put_task(task))
    t = warmup_engines_background(eph.datastore, cfg.warmup_buckets)
    assert t.daemon
    t.join(timeout=300)
    assert not t.is_alive()
    assert "warmup failed" not in caplog.text
    eph.cleanup()


@pytest.mark.slow  # 93s; warmup coverage stays fast via test_warmup_engines/test_warmup_background_buckets (ISSUE 1)
def test_provision_precompile_then_warm_first_job(tmp_path):
    """janus_cli provision-tasks --precompile AOT-compiles the task's
    engine steps into the persistent compilation cache; a FRESH process
    sharing that cache dir then runs its first job without paying the
    cold jit (VERDICT r4 item 10: first-job latency < 30 s)."""
    import base64
    import json as _json
    import time

    import yaml as _yaml

    from janus_tpu.core.hpke import generate_hpke_config_and_private_key
    from janus_tpu.messages import Role
    from janus_tpu.task import QueryTypeConfig, TaskBuilder
    from janus_tpu.vdaf.registry import VdafInstance

    task = (
        TaskBuilder(
            QueryTypeConfig.time_interval(),
            VdafInstance.sum_vec(length=16, bits=4),
            Role.HELPER,
        )
        .with_(
            collector_hpke_config=generate_hpke_config_and_private_key(config_id=3).config,
        )
        .build()
    )
    tasks_file = tmp_path / "tasks.yaml"
    tasks_file.write_text(_yaml.safe_dump([task.to_dict()]))
    db = str(tmp_path / "ds.sqlite")
    cache = str(tmp_path / "xla-cache")
    key = base64.urlsafe_b64encode(b"k" * 16).decode().rstrip("=")
    env = dict(
        os.environ,
        PYTHONPATH=REPO,
        JAX_PLATFORMS="cpu",
        JANUS_FORCE_CPU="1",
    )
    # production-faithful: binaries run single-device; the suite's
    # 8-virtual-device XLA_FLAGS would add mesh lowering to both sides
    env["XLA_FLAGS"] = " ".join(
        f
        for f in env.get("XLA_FLAGS", "").split()
        if "xla_force_host_platform_device_count" not in f
    )

    out = subprocess.run(
        [
            sys.executable,
            "-c",
            "import jax; jax.config.update('jax_platforms', 'cpu');"
            "from janus_tpu.bin.janus_cli import main; import sys;"
            f"sys.exit(main(['provision-tasks', {str(tasks_file)!r},"
            f" '--database', {db!r}, '--datastore-keys', {key!r},"
            f" '--precompile', '32', '--compilation-cache-dir', {cache!r}]))",
        ],
        env=env,
        capture_output=True,
        cwd=REPO,
        timeout=600,
    )
    assert out.returncode == 0, out.stderr.decode()[-2000:]
    assert b"precompiled bucket 32" in out.stderr
    assert os.path.isdir(cache) and os.listdir(cache), "cache must be populated"

    # fresh process, same cache dir: first job must start warm
    probe = subprocess.run(
        [
            sys.executable,
            "-c",
            f"""
import time, json, sys
import jax
jax.config.update('jax_platforms', 'cpu')
jax.config.update('jax_compilation_cache_dir', {cache!r})
jax.config.update('jax_persistent_cache_min_entry_size_bytes', 0)
jax.config.update('jax_persistent_cache_min_compile_time_secs', 0)
import numpy as np
from janus_tpu.binary_utils import parse_datastore_keys
from janus_tpu.core.time_util import RealClock
from janus_tpu.datastore.store import Crypter, open_datastore
from janus_tpu.aggregator.engine_cache import engine_cache
from janus_tpu.vdaf.testing import make_report_batch, random_measurements
ds = open_datastore({db!r}, Crypter(parse_datastore_keys({key!r})), RealClock())
task = ds.run_tx(lambda tx: tx.get_tasks())[0]
# reports exist before the job: make_report_batch is CLIENT-side wire
# staging, not aggregator first-job latency
rng = np.random.default_rng(0)
args, _ = make_report_batch(task.vdaf, random_measurements(task.vdaf, 32, rng), seed=0)
nonce, parts, meas, proof, blind0, hseed, blind1 = args
t0 = time.time()
eng = engine_cache(task.vdaf, task.vdaf_verify_key)
out0, seed0, ver0, part0 = eng.leader_init(nonce, parts, meas, proof, blind0)
out1, mask, _ = eng.helper_init(nonce, parts, hseed, blind1, ver0, part0, np.ones(32, bool))
agg = eng.aggregate(out1, mask)
print(json.dumps({{'first_job_s': time.time() - t0}}))
""",
        ],
        env=env,
        capture_output=True,
        cwd=REPO,
        timeout=600,
    )
    assert probe.returncode == 0, probe.stderr.decode()[-2000:]
    stat = _json.loads(probe.stdout.decode().strip().splitlines()[-1])
    assert stat["first_job_s"] < 30, stat
