"""Process-level binary tests: each binary boots from a YAML config,
serves /healthz, and drains cleanly on SIGTERM — the analog of the
reference's graceful-shutdown suite (aggregator/tests/graceful_shutdown.rs)
and trycmd CLI goldens (aggregator/tests/cli.rs)."""

import base64
import os
import secrets
import signal
import subprocess
import sys
import time
import urllib.request

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

BINARIES = [
    ("aggregator", "listen_address: \"127.0.0.1:{dap_port}\"\n"),
    ("aggregation_job_creator", "aggregation_job_creation_interval_secs: 0.5\n"),
    ("aggregation_job_driver", ""),
    ("collection_job_driver", ""),
]


def wait_healthz(port: int, deadline_s: float = 60.0) -> None:
    deadline = time.monotonic() + deadline_s
    while True:
        try:
            with urllib.request.urlopen(f"http://127.0.0.1:{port}/healthz", timeout=2) as r:
                assert r.status == 200
                return
        except Exception:
            if time.monotonic() > deadline:
                raise
            time.sleep(0.5)


@pytest.mark.parametrize(
    "idx,name,extra",
    [(i, n, e) for i, (n, e) in enumerate(BINARIES)],
    ids=[b[0] for b in BINARIES],
)
def test_binary_boots_and_drains_on_sigterm(tmp_path, idx, name, extra):
    health_port = 20200 + idx
    dap_port = health_port + 1000
    cfg = tmp_path / "cfg.yaml"
    cfg.write_text(
        f"database: {{url: {tmp_path}/ds.sqlite}}\n"
        f"health_check_listen_address: \"127.0.0.1:{health_port}\"\n"
        "jax_platform: cpu\n" + extra.format(dap_port=dap_port)
    )
    key = base64.urlsafe_b64encode(secrets.token_bytes(16)).decode().rstrip("=")
    env = dict(os.environ, PYTHONPATH=REPO, DATASTORE_KEYS=key, JAX_PLATFORMS="cpu")
    proc = subprocess.Popen(
        [sys.executable, "-m", f"janus_tpu.bin.{name}", "--config-file", str(cfg)],
        env=env,
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        cwd=REPO,
    )
    try:
        wait_healthz(health_port)
        proc.send_signal(signal.SIGTERM)
        out, _ = proc.communicate(timeout=30)
        assert proc.returncode == 0, out.decode()[-2000:]
        assert b"shut down" in out
    finally:
        if proc.poll() is None:
            proc.kill()


def test_janus_cli_help_and_bad_args():
    env = dict(os.environ, PYTHONPATH=REPO)
    out = subprocess.run(
        [sys.executable, "-m", "janus_tpu.bin.janus_cli", "--help"],
        env=env, capture_output=True, cwd=REPO,
    )
    assert out.returncode == 0
    for cmd in ("provision-tasks", "create-datastore-key", "list-tasks"):
        assert cmd.encode() in out.stdout

    out = subprocess.run(
        [sys.executable, "-m", "janus_tpu.bin.janus_cli", "no-such-command"],
        env=env, capture_output=True, cwd=REPO,
    )
    assert out.returncode != 0


def test_warmup_engines_compiles_provisioned_tasks(caplog):
    """Boot-time engine warmup (CommonConfig.warmup_engines_at_boot)
    traces + compiles the hot steps for each provisioned task."""
    from janus_tpu.binary_utils import warmup_engines
    from janus_tpu.core.hpke import generate_hpke_config_and_private_key
    from janus_tpu.datastore.store import EphemeralDatastore
    from janus_tpu.messages import Role
    from janus_tpu.task import QueryTypeConfig, TaskBuilder
    from janus_tpu.vdaf.registry import VdafInstance

    eph = EphemeralDatastore()
    task = (
        TaskBuilder(QueryTypeConfig.time_interval(), VdafInstance.count(), Role.HELPER)
        .with_(
            collector_hpke_config=generate_hpke_config_and_private_key(config_id=3).config,
        )
        .build()
    )
    eph.datastore.run_tx(lambda tx: tx.put_task(task))
    warmup_engines(eph.datastore)  # must not raise; compiles count engine
    assert "warmup failed" not in caplog.text
    eph.cleanup()


def test_warmup_background_buckets(caplog):
    """warmup_buckets runs ahead-of-time bucket compilation in a daemon
    thread (serving is not blocked) and warms every configured bucket."""
    from janus_tpu.binary_utils import warmup_engines_background
    from janus_tpu.config import CommonConfig
    from janus_tpu.core.hpke import generate_hpke_config_and_private_key
    from janus_tpu.datastore.store import EphemeralDatastore
    from janus_tpu.messages import Role
    from janus_tpu.task import QueryTypeConfig, TaskBuilder
    from janus_tpu.vdaf.registry import VdafInstance

    cfg = CommonConfig.from_dict({"warmup_buckets": [32, 64]})
    assert cfg.warmup_buckets == (32, 64)

    eph = EphemeralDatastore()
    task = (
        TaskBuilder(QueryTypeConfig.time_interval(), VdafInstance.count(), Role.HELPER)
        .with_(
            collector_hpke_config=generate_hpke_config_and_private_key(config_id=4).config,
        )
        .build()
    )
    eph.datastore.run_tx(lambda tx: tx.put_task(task))
    t = warmup_engines_background(eph.datastore, cfg.warmup_buckets)
    assert t.daemon
    t.join(timeout=300)
    assert not t.is_alive()
    assert "warmup failed" not in caplog.text
    eph.cleanup()
