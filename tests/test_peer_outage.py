"""Peer-outage parking (aggregator/peer_health.py): while EVERY known
helper's breaker is open both drivers' claim acquirers park — no claim
transaction, no lease churn — and the background half-open probe
resumes them; state exports as the janus_peer_* metric families and
the /statusz `peer_health` section (docs/ARCHITECTURE.md "Surviving
the other aggregator")."""

import time

import pytest

from conftest import DATASTORE_ENGINES
from janus_tpu import metrics
from janus_tpu.aggregator.job_driver import make_claim_acquirer
from janus_tpu.aggregator.peer_health import (
    PROBE_ALIVE,
    PROBE_DEAD,
    PROBE_REJECTED,
    PeerHealthConfig,
    PeerHealthTracker,
    default_tracker,
    reset_default_tracker,
)
from janus_tpu.core.circuit_breaker import (
    CircuitBreakerConfig,
    OutboundCircuitBreakers,
)

PEER_URL = "http://helper.test:9999/dap/"
PEER = "helper.test:9999"


def _breakers(threshold=1, cooldown=0.01):
    return OutboundCircuitBreakers(
        CircuitBreakerConfig(failure_threshold=threshold, open_cooldown_s=cooldown)
    )


class _FakeFetch:
    """fetch_any_status stand-in: records calls, answers a status or
    raises."""

    def __init__(self, status=404, error=None):
        self.status = status
        self.error = error
        self.calls = 0

    def __call__(self, url, timeout=None, **kw):
        self.calls += 1
        if self.error is not None:
            raise self.error
        return self.status, b""


# ----------------------------------------------------------------------
# the parking predicate
# ----------------------------------------------------------------------
def test_parks_only_when_every_known_peer_is_down():
    br = _breakers()
    tr = PeerHealthTracker(br)
    assert not tr.should_park()  # no peers known yet: never park
    br.record_success("helper-b:80")  # b known and healthy
    br.record_failure("helper-a:80")
    assert not tr.should_park()  # partial outage: per-step step-backs
    assert tr.parked_peers() == ["helper-a:80"]
    br.record_failure("helper-b:80")
    assert tr.should_park()  # EVERY known peer down: park outright


def test_park_knob_and_enabled_knob_disable_parking():
    br = _breakers()
    br.record_failure(PEER)
    assert not PeerHealthTracker(
        br, PeerHealthConfig(park=False)
    ).should_park()
    assert not PeerHealthTracker(
        br, PeerHealthConfig(enabled=False)
    ).should_park()


def test_observe_endpoint_returns_label_and_dedups():
    tr = PeerHealthTracker(_breakers())
    assert tr.observe_endpoint(PEER_URL) == PEER
    assert tr.observe_endpoint(PEER_URL + "tasks/x") == PEER
    assert tr.status()["peers"][PEER]["endpoint"] == PEER_URL


# ----------------------------------------------------------------------
# the acquirer gate: parked = NO claim transaction
# ----------------------------------------------------------------------
@pytest.fixture(params=DATASTORE_ENGINES)
def engine(request):
    return request.param


def test_park_gate_skips_claim_transactions(engine):
    """A parked pass returns [] without opening a claim tx or feeding
    the claim metrics; recovery resumes real claims — the lease metrics
    stay honest through the outage (janus_lease_acquire_tx_total is
    exactly how the chaos gate asserts the freeze)."""
    from janus_tpu.core.time_util import MockClock
    from janus_tpu.datastore.store import EphemeralDatastore
    from janus_tpu.messages import Duration, Time
    from test_lease_invariants import make_task, put_job

    eph = EphemeralDatastore(clock=MockClock(Time(1_600_000_000)), engine=engine)
    ds = eph.datastore
    try:
        task = make_task(ds)
        put_job(ds, task, bytes(16))
        br = _breakers(cooldown=0.01)
        tr = PeerHealthTracker(br)
        tr.observe_endpoint(PEER_URL)

        acquire = make_claim_acquirer(
            ds,
            "aggregation",
            lambda limit: ds.run_tx(
                lambda tx: tx.acquire_incomplete_aggregation_jobs(
                    Duration(600), limit
                ),
                "acq",
            ),
            peer_gate=tr.park_gate(),
        )

        br.record_failure(PEER)  # helper down: breaker open
        assert tr.should_park()
        tx_before = metrics.lease_acquire_tx_total.total()
        assert acquire(8) == []
        assert metrics.lease_acquire_tx_total.total() == tx_before

        # recovery: half-open probe slot + success closes the breaker
        time.sleep(0.02)
        br.check(PEER)
        br.record_success(PEER)
        got = acquire(8)
        assert len(got) == 1
        assert metrics.lease_acquire_tx_total.total() == tx_before + 1
    finally:
        eph.cleanup()


# ----------------------------------------------------------------------
# the probe
# ----------------------------------------------------------------------
def test_probe_any_http_status_resumes():
    """404 on the task endpoint is a LIVE peer: it routed, accepted the
    connection and spoke HTTP — the probe closes the breaker."""
    br = _breakers(cooldown=0.01)
    fetch = _FakeFetch(status=404)
    tr = PeerHealthTracker(br, http=fetch)
    tr.observe_endpoint(PEER_URL)
    br.record_failure(PEER)
    probes_before = metrics.peer_probes_total.get(peer=PEER, outcome=PROBE_ALIVE)
    time.sleep(0.02)
    assert tr.probe(PEER) == PROBE_ALIVE
    assert fetch.calls == 1
    assert br.state(PEER) == "closed"
    assert not tr.should_park()
    assert (
        metrics.peer_probes_total.get(peer=PEER, outcome=PROBE_ALIVE)
        == probes_before + 1
    )
    assert tr.status()["peers"][PEER]["probes"][PROBE_ALIVE] >= 1


def test_probe_transport_failure_restarts_cooldown():
    br = _breakers(cooldown=0.01)
    tr = PeerHealthTracker(
        br, http=_FakeFetch(error=ConnectionError("still dead"))
    )
    tr.observe_endpoint(PEER_URL)
    br.record_failure(PEER)
    time.sleep(0.02)
    assert tr.probe(PEER) == PROBE_DEAD
    assert br.state(PEER) == "open"
    assert br.retry_in_s(PEER) > 0  # full cooldown restarted


def test_probe_does_not_stampede_the_half_open_slot():
    """If a real driver step already holds the single half-open probe
    slot, the tracker's probe is rejected WITHOUT touching the wire."""
    br = _breakers(cooldown=0.01)
    fetch = _FakeFetch()
    tr = PeerHealthTracker(br, http=fetch)
    tr.observe_endpoint(PEER_URL)
    br.record_failure(PEER)
    time.sleep(0.02)
    br.check(PEER)  # the driver's own attempt takes the slot
    assert tr.probe(PEER) == PROBE_REJECTED
    assert fetch.calls == 0


def test_probe_before_cooldown_is_rejected():
    br = _breakers(cooldown=60.0)
    fetch = _FakeFetch()
    tr = PeerHealthTracker(br, http=fetch)
    tr.observe_endpoint(PEER_URL)
    br.record_failure(PEER)
    assert tr.probe(PEER) == PROBE_REJECTED
    assert fetch.calls == 0


def test_probe_without_endpoint_is_rejected():
    br = _breakers(cooldown=0.01)
    br.record_failure(PEER)
    time.sleep(0.02)
    tr = PeerHealthTracker(br, http=_FakeFetch())
    assert tr.probe(PEER) == PROBE_REJECTED  # nowhere to aim


# ----------------------------------------------------------------------
# the tick: gauge + outage-seconds accrual
# ----------------------------------------------------------------------
def test_tick_accrues_outage_seconds_and_parked_gauge():
    br = _breakers(cooldown=3600.0)  # cooldown never elapses: no probes
    tr = PeerHealthTracker(br, http=_FakeFetch())
    tr.observe_endpoint(PEER_URL)
    br.record_failure(PEER)
    outage_before = metrics.peer_outage_seconds_total.get(peer=PEER)

    t0 = 1000.0
    tr.tick(now=t0)  # first beat: establishes the accrual anchor
    tr.tick(now=t0 + 5.0)
    tr.tick(now=t0 + 7.5)
    assert metrics.peer_parked.get(peer=PEER) == 1.0
    accrued = metrics.peer_outage_seconds_total.get(peer=PEER) - outage_before
    assert accrued == pytest.approx(7.5)
    st = tr.status()
    assert st["parked"] is True
    assert st["peers"][PEER]["outage_for_s"] >= 0.0

    # recovery: half-open probe succeeds, next tick clears the gauge
    # and stops the accrual
    br._peers[PEER].opened_at -= 7200.0  # test hook: fast-forward
    br.check(PEER)
    br.record_success(PEER)
    tr.tick(now=t0 + 9.0)
    assert metrics.peer_parked.get(peer=PEER) == 0.0
    assert (
        metrics.peer_outage_seconds_total.get(peer=PEER) - outage_before
        == pytest.approx(7.5)
    )
    assert tr.status()["parked"] is False


def test_tick_probes_after_cooldown():
    br = _breakers(cooldown=0.01)
    fetch = _FakeFetch(status=405)
    tr = PeerHealthTracker(br, http=fetch)
    tr.observe_endpoint(PEER_URL)
    br.record_failure(PEER)
    time.sleep(0.02)
    tr.tick()
    assert fetch.calls == 1
    assert br.state(PEER) == "closed"


# ----------------------------------------------------------------------
# the process-wide default + /statusz
# ----------------------------------------------------------------------
def test_default_tracker_shared_and_on_statusz():
    from janus_tpu.statusz import status_snapshot

    reset_default_tracker()
    try:
        br = _breakers()
        tr = default_tracker(br, PeerHealthConfig(probe_interval_s=123.0))
        assert default_tracker(br) is tr  # both drivers share one
        tr.observe_endpoint(PEER_URL)
        section = status_snapshot()["peer_health"]
        assert section["config"]["probe_interval_s"] == 123.0
        assert section["parked"] is False
        assert PEER in section["peers"]
    finally:
        reset_default_tracker()


def test_background_prober_start_stop():
    br = _breakers(cooldown=0.01)
    fetch = _FakeFetch(status=404)
    tr = PeerHealthTracker(
        br, PeerHealthConfig(probe_interval_s=0.05, probe_timeout_s=0.5), http=fetch
    )
    tr.observe_endpoint(PEER_URL)
    br.record_failure(PEER)
    tr.start()
    try:
        deadline = time.monotonic() + 5.0
        while br.state(PEER) != "closed" and time.monotonic() < deadline:
            time.sleep(0.02)
        assert br.state(PEER) == "closed"  # prober resumed it on its own
        assert fetch.calls >= 1
    finally:
        tr.stop()
    assert tr._thread is None
