"""Native C XOF (janus_tpu/native/xof.c) differential tests vs the
pure-Python SHAKE128 host oracle — every byte of the stream framing and
the oversample-and-reduce field sampling must agree, since host- and
device-side parties exchange shares produced by either path."""

import hashlib

import numpy as np
import pytest

from janus_tpu import native
from janus_tpu.fields.field import Field64, Field128
from janus_tpu.vdaf.xof import XofShake128, dst, prng_expand, prng_expand_batch

pytestmark = pytest.mark.skipif(
    not native.available(), reason="no C compiler for the native library"
)


def test_shake128_matches_hashlib():
    for size in (0, 1, 167, 168, 169, 1000):
        data = bytes(range(256)) * 4
        data = data[:size]
        assert native.shake128(data, 64) == hashlib.shake_128(data).digest(64)


@pytest.mark.parametrize("field", [Field64, Field128], ids=["f64", "f128"])
def test_expand_matches_python_oracle(field):
    d = dst(3, 6)
    seeds = [bytes([i]) * 16 for i in range(4)]
    binders = [bytes([9, i]) * 4 for i in range(4)]
    limbs = field.ENCODED_SIZE // 8
    out = native.expand_field_batch(d, seeds, binders, 19, limbs, field.MODULUS)
    for i, (s, b) in enumerate(zip(seeds, binders)):
        want = XofShake128(s, d, b).next_vec(field, 19)
        got = [
            int(out[i, j, 0]) | (int(out[i, j, 1]) << 64 if limbs == 2 else 0)
            for j in range(19)
        ]
        assert got == want


@pytest.mark.parametrize("field", [Field64, Field128], ids=["f64", "f128"])
def test_prng_expand_routes_through_native(field):
    """prng_expand (used by the host Prio3 via prng_next_vec) must be
    byte-identical to the pure-Python stream, empty and nonempty binder."""
    d = dst(1, 2)
    seed = b"\x07" * 16
    for binder in (b"", b"binder08"):
        assert prng_expand(field, seed, d, binder, 40) == XofShake128(
            seed, d, binder
        ).next_vec(field, 40)


def test_prng_expand_batch_shapes():
    d = dst(1, 6)
    seeds = [bytes([i]) * 16 for i in range(3)]
    out = prng_expand_batch(Field64, d, seeds, None, 5)
    assert out is not None and len(out) == 3 and len(out[0]) == 5
    # unsupported encoded size -> graceful None (fallback path)
    class Odd:
        ENCODED_SIZE = 12
        MODULUS = (1 << 89) - 1

    assert prng_expand_batch(Odd, d, seeds, None, 5) is None


def test_derive_seed_batch_matches_oracle():
    d = dst(2, 8)
    seeds = [bytes([i]) * 16 for i in range(3)]
    binders = [b"\x01" * 40, b"\x02" * 40, b"\x03" * 40]
    out = native.derive_seed_batch(d, seeds, binders)
    for i in range(3):
        assert out[i].tobytes() == XofShake128.derive_seed(seeds[i], d, binders[i])
