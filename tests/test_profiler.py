"""Continuous profiling subsystem (ISSUE 13; janus_tpu/profiler.py):
the sampling wall-clock profiler (role tagging, window ring, collapsed
format under hostile names, measured overhead), the per-dispatch
device cost ledger arithmetic, the boot-phase timeline, the health
listener endpoints, and the shared stack formatter the device
watchdog's stalled dumps reuse.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.request

import pytest

from janus_tpu import profiler as prof
from janus_tpu.profiler import (
    BootTimeline,
    DeviceCostLedger,
    ProfilerConfig,
    SamplingProfiler,
    fold_component,
    format_stack,
    frame_label,
    thread_role,
    validate_collapsed,
)


# ---------------------------------------------------------------------------
# role tagging
# ---------------------------------------------------------------------------


def test_thread_role_covers_every_named_thread_family():
    """Every thread family the codebase creates maps to its documented
    role — a rename at a creation site without a taxonomy update is a
    test failure, not a silent 'other'."""
    expected = {
        # step pipeline (ThreadPoolExecutor appends -0, -1, ...)
        "device-lane-0": "device_lane",
        "device-watchdog-3": "device_lane",  # supervised dispatches run here
        "step-read-1": "prefetch",
        "step-commit-0": "commit",
        "step-http-2": "http_client",
        "dap-handler-5": "http_handler",
        # ingest
        "ingest-decrypt-0": "decrypt_pool",
        "ingest-decode-1": "decode_pool",
        # flushers
        "report-writer": "flusher",
        "resident-flusher": "flusher",
        "upload-journal-replay": "flusher",
        "chrome-trace-flush": "flusher",
        "device-lane-gauge": "flusher",
        # background engines/samplers
        "slo-engine": "slo_engine",
        "health-sampler": "sampler",
        "datastore-supervisor": "supervisor",
        "engine-canary-count": "engine_warm",
        "engine-warmup": "engine_warm",
        # listeners (normalized in this PR — they were unnamed)
        "dap-listener": "listener",
        "health-listener": "listener",
        "api-listener": "listener",
        "interop-listener": "listener",
        # steps real jobs — must NOT fold into the accept-loop role
        "interop-runner": "other",
        "gc-loop": "gc",
        "janus-profiler": "profiler",
        "MainThread": "main",
        # unknown names degrade to 'other', never crash
        "Thread-17 (run)": "other",
        'evil;name\n"x"': "other",
    }
    for name, role in expected.items():
        assert thread_role(name) == role, (name, thread_role(name), role)


# ---------------------------------------------------------------------------
# sampling, folding, hostile names
# ---------------------------------------------------------------------------


def _spin_marker_loop(stop: threading.Event):
    # distinctive frame the sampler must catch (busy, not a wait leaf)
    while not stop.is_set():
        sum(range(256))


def test_sampler_catches_live_thread_with_role_and_frames():
    stop = threading.Event()
    t = threading.Thread(target=_spin_marker_loop, args=(stop,), name="device-lane-9")
    t.start()
    p = SamplingProfiler(ProfilerConfig(hz=200.0, window_secs=60.0))
    p.start()
    try:
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            doc = p.profile_json()
            if doc["roles"].get("device_lane", {}).get("self_samples", 0) > 0:
                break
            time.sleep(0.02)
    finally:
        p.stop()
        stop.set()
        t.join()
    doc = p.profile_json()
    lane = doc["roles"]["device_lane"]
    assert lane["samples"] > 0 and lane["self_samples"] > 0
    assert 0 < lane["self_pct"] <= lane["total_pct"] <= 100.0
    collapsed = p.collapsed()
    assert "_spin_marker_loop" in collapsed
    # the role tags the folded stack's root
    assert any(
        line.startswith("device_lane;") and "_spin_marker_loop" in line
        for line in collapsed.splitlines()
    )
    # the sampler excludes its own thread
    assert "profiler;" not in collapsed
    assert validate_collapsed(collapsed) == []


def test_collapsed_roundtrip_with_hostile_thread_name():
    """A thread named with semicolons/newlines/quotes/spaces — the
    folded-format separators — must not corrupt the document: every
    line still splits into a stack and an integer count."""
    stop = threading.Event()
    t = threading.Thread(
        target=_spin_marker_loop,
        args=(stop,),
        name='evil;stack\ncorruptor "x" 42 ',
    )
    t.start()
    p = SamplingProfiler(ProfilerConfig(hz=500.0, window_secs=60.0))
    p.start()
    try:
        for _ in range(200):
            if p.profile_json()["samples"] > 10:
                break
            time.sleep(0.01)
    finally:
        p.stop()
        stop.set()
        t.join()
    collapsed = p.collapsed()
    assert collapsed
    assert validate_collapsed(collapsed) == []
    for line in collapsed.splitlines():
        stack, _, count = line.rpartition(" ")
        assert count.isdigit()
        assert all(comp and ";" not in comp for comp in stack.split(";"))


def test_fold_component_sanitizes_separators():
    assert fold_component("a;b c\nd\te") == "a_b_c_d_e"
    assert fold_component("") == "_"
    assert fold_component("clean.frame") == "clean.frame"


def test_window_rotation_and_ring_bounds():
    p = SamplingProfiler(ProfilerConfig(hz=50.0, window_secs=0.0, windows=3))
    # drive sampling synchronously (no thread): window_secs=0 rotates
    # on every pass, so the ring must hold at most `windows` windows
    # and aggregation must still sum samples across ring + current
    p._current = prof._Window(time.time())
    for _ in range(10):
        p.sample_once()
    assert len(p._ring) == 3
    stacks, samples, passes = p._aggregate_locked()
    # only ring + current survive: 3 retained + the fresh current
    assert passes <= 4
    assert samples >= 0 and isinstance(stacks, dict)


def test_sampler_overhead_zero_off_and_sane_on():
    from janus_tpu import metrics as m

    p = SamplingProfiler(ProfilerConfig(hz=100.0, window_secs=30.0))
    # off: never started -> ratio 0 via the gauge default and the doc
    assert p.profile_json()["overhead_ratio"] == 0.0
    before = m.profiler_samples_total.get()
    p.start()
    try:
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and p.profile_json()["passes"] < 5:
            time.sleep(0.01)
    finally:
        p.stop()
    doc = p.profile_json()
    assert doc["passes"] >= 5
    # measured: strictly positive, far under the 2% budget even at
    # 100 Hz (the bound is loose for loaded CI hosts)
    assert 0.0 < doc["overhead_ratio"] < 0.2
    assert m.profiler_samples_total.get() > before
    assert m.profiler_overhead_ratio.get() >= 0.0


def test_start_stop_idempotent_and_install_uninstall():
    p = SamplingProfiler(ProfilerConfig(hz=100.0))
    p.start()
    p.start()  # second start is a no-op, not a second thread
    assert sum(1 for t in threading.enumerate() if t.name == "janus-profiler") == 1
    p.stop()
    assert not p.running
    p.stop()  # idempotent

    old = prof.PROFILER
    try:
        inst = prof.install_profiler(ProfilerConfig(hz=100.0, enabled=True))
        assert inst.running and prof.PROFILER is inst
        # the module-level statusz provider follows the installed
        # instance (it reads the module global at call time)
        from janus_tpu.statusz import status_snapshot

        snap = status_snapshot()
        assert snap["profile"]["enabled"] is True
        prof.uninstall_profiler()
        assert not inst.running
        assert status_snapshot()["profile"]["enabled"] is False
        # enabled: false installs but never starts
        inst2 = prof.install_profiler(ProfilerConfig(enabled=False))
        assert not inst2.running
    finally:
        prof.uninstall_profiler()
        prof.PROFILER = old


# ---------------------------------------------------------------------------
# device cost ledger
# ---------------------------------------------------------------------------


def test_cost_ledger_arithmetic_and_gauges():
    from janus_tpu import metrics as m

    ledger = DeviceCostLedger()
    # 2 dispatches, 1000 rows, 0.1 s execute -> 100 µs/report
    ledger.record("count", "aggregate", 32, "execute", 0.1, rows=1000, dispatches=2)
    # transfers attribute to the same op's rows
    ledger.record("count", "aggregate", 32, "h2d", 0.05)
    ledger.record("count", "aggregate", 64, "d2h", 0.02, rows=1000, dispatches=1)
    us = ledger.us_per_report()
    assert us["aggregate"]["execute"] == pytest.approx(50.0)  # 0.1s / 2000 rows
    assert us["aggregate"]["h2d"] == pytest.approx(25.0)
    assert us["aggregate"]["d2h"] == pytest.approx(10.0)
    st = ledger.status()
    by_key = {(e["vdaf"], e["op"], e["bucket"]): e for e in st["entries"]}
    e32 = by_key[("count", "aggregate", 32)]
    assert e32["dispatches"] == 2 and e32["rows"] == 1000
    assert e32["execute_s"] == pytest.approx(0.1)
    assert e32["h2d_s"] == pytest.approx(0.05)
    e64 = by_key[("count", "aggregate", 64)]
    assert e64["d2h_s"] == pytest.approx(0.02)
    # the module-level ledger feeds the gauges/counters
    prof.DEVICE_COST.record("count", "ledger_test_op", 32, "compile", 0.5, rows=500, dispatches=1)
    assert m.device_cost_us_per_report.get(
        op="ledger_test_op", phase="compile"
    ) == pytest.approx(1000.0)
    assert m.device_cost_seconds_total.get(op="ledger_test_op", phase="compile") >= 0.5
    with pytest.raises(ValueError):
        ledger.record("count", "aggregate", 32, "warp", 0.1)


def test_cost_ledger_fed_by_real_engine_dispatches():
    """A real (CPU) engine init + aggregate lands compile/execute rows
    AND the span-hook h2d/d2h attribution in the process ledger."""
    import numpy as np

    from janus_tpu.aggregator.engine_cache import EngineCache
    from janus_tpu.vdaf.registry import VdafInstance
    from janus_tpu.vdaf.testing import make_report_batch, random_measurements

    prof.DEVICE_COST.reset_for_tests()
    inst = VdafInstance.count()
    eng = EngineCache(inst, bytes(range(16)))
    rng = np.random.default_rng(3)
    n = 8
    args, _ = make_report_batch(inst, random_measurements(inst, n, rng), seed=1)
    nonce, public, mv, proof, blind0, _, _ = args
    out0, _, _, _ = eng.leader_init(nonce, public, mv, proof, blind0)
    eng.aggregate(out0, np.ones(n, dtype=bool))
    st = prof.DEVICE_COST.status()
    ops = {e["op"] for e in st["entries"]}
    assert "leader_init" in ops and "aggregate" in ops
    li = [e for e in st["entries"] if e["op"] == "leader_init"]
    # first dispatch of the bucket is the compile; rows counted
    assert sum(e["compile_s"] for e in li) > 0
    assert sum(e["rows"] for e in li) == n
    # the put/fetch span hooks attributed transfer time with the bucket
    assert sum(e["h2d_s"] + e["d2h_s"] for e in li) > 0
    assert all(e["bucket"] > 0 for e in li)
    us = prof.DEVICE_COST.us_per_report()
    assert us["aggregate"].get("execute", 0) > 0 or us["aggregate"].get("compile", 0) > 0


def test_cost_ledger_compile_attribution_tracks_jit_specialization():
    """The resident aggregate_pending path and the classic aggregate
    share op="aggregate" in the engine counters AND the same row
    bucket, but compile different programs — each ledger row must book
    its own first dispatch as phase="compile" (keyed by the jit
    specialization, not the engine-metric (op, bucket))."""
    import numpy as np

    from janus_tpu.aggregator.engine_cache import EngineCache
    from janus_tpu.vdaf.registry import VdafInstance
    from janus_tpu.vdaf.testing import make_report_batch, random_measurements

    prof.DEVICE_COST.reset_for_tests()
    inst = VdafInstance.count()
    eng = EngineCache(inst, bytes(range(16)))
    rng = np.random.default_rng(5)
    n = 8
    args, _ = make_report_batch(inst, random_measurements(inst, n, rng), seed=2)
    nonce, public, mv, proof, blind0, _, _ = args
    out0, _, _, _ = eng.leader_init(nonce, public, mv, proof, blind0)
    # resident path FIRST marks the (op="aggregate", row bucket)
    eng.aggregate_pending(out0, np.zeros(n, dtype=np.int32), 2)
    # ...the classic path's first dispatch still compiles its own
    # program and must NOT book that wall time as execute
    eng.aggregate(out0, np.ones(n, dtype=bool))
    st = prof.DEVICE_COST.status()
    by_op = {}
    for e in st["entries"]:
        agg = by_op.setdefault(e["op"], {"compile_s": 0.0, "execute_s": 0.0})
        agg["compile_s"] += e["compile_s"]
        agg["execute_s"] += e["execute_s"]
    assert by_op["aggregate_pending"]["compile_s"] > 0
    assert by_op["aggregate"]["compile_s"] > 0, by_op


# ---------------------------------------------------------------------------
# boot timeline
# ---------------------------------------------------------------------------


def test_boot_timeline_phases_monotone_and_complete():
    b = BootTimeline(start_unix=time.time() - 0.5)
    b.phase_done("imports")
    time.sleep(0.02)
    b.phase_done("config")
    b.phase_done("backend_init")
    b.mark_ready()
    snap = b.snapshot()
    assert snap["ready"] is True
    names = [p["phase"] for p in snap["phases"]]
    assert names == ["imports", "config", "backend_init"]
    # contiguous + monotone: each phase starts where the previous ended
    last_end = 0.0
    for p in snap["phases"]:
        assert p["start_s"] == pytest.approx(last_end, abs=1e-6)
        assert p["end_s"] >= p["start_s"]
        # seconds and the start/end offsets are rounded independently
        # to 6 decimals, so they can disagree by up to ~2 µs
        assert p["seconds"] == pytest.approx(p["end_s"] - p["start_s"], abs=5e-6)
        last_end = p["end_s"]
    # phases tile process start -> the last mark; ready is moments after
    assert snap["boot_phases_sum_s"] == pytest.approx(snap["total_s"], rel=0.01)
    assert snap["phases"][0]["seconds"] >= 0.5  # the pre-main imports span
    # gauge exported per phase
    from janus_tpu import metrics as m

    assert m.boot_phase_seconds.get(phase="config") > 0

    # a phase reported after ready appends flagged late and does not
    # disturb the sealed sum
    b.phase_done("journal_scan")
    snap2 = b.snapshot()
    assert snap2["phases"][-1]["phase"] == "journal_scan"
    assert snap2["phases"][-1].get("late") is True
    assert snap2["boot_phases_sum_s"] == snap["boot_phases_sum_s"]
    assert snap2["total_s"] == snap["total_s"]
    # mark_ready is idempotent: first call wins
    ready0 = b.ready_unix
    b.mark_ready()
    assert b.ready_unix == ready0


# ---------------------------------------------------------------------------
# endpoints (content types + payload shape over live HTTP)
# ---------------------------------------------------------------------------


def test_health_listener_profile_and_boot_endpoints():
    from janus_tpu.binary_utils import HealthServer

    old = prof.PROFILER
    prof.install_profiler(ProfilerConfig(hz=100.0, window_secs=10.0))
    srv = HealthServer("127.0.0.1:0").start()
    try:
        base = f"http://127.0.0.1:{srv.port}"
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and prof.PROFILER.profile_json()["passes"] < 3:
            time.sleep(0.01)

        with urllib.request.urlopen(base + "/debug/profile", timeout=10) as resp:
            assert resp.headers["Content-Type"].startswith("text/plain")
            collapsed = resp.read().decode()
        assert collapsed and validate_collapsed(collapsed) == []

        with urllib.request.urlopen(
            base + "/debug/profile?format=json", timeout=10
        ) as resp:
            assert resp.headers["Content-Type"].startswith("application/json")
            doc = json.loads(resp.read())
        assert doc["enabled"] is True and doc["samples"] > 0
        assert "roles" in doc and "top_frames" in doc

        # Accept negotiation picks JSON too
        req = urllib.request.Request(
            base + "/debug/profile", headers={"Accept": "application/json"}
        )
        with urllib.request.urlopen(req, timeout=10) as resp:
            assert resp.headers["Content-Type"].startswith("application/json")

        with urllib.request.urlopen(base + "/debug/boot", timeout=10) as resp:
            assert resp.headers["Content-Type"].startswith("application/json")
            boot = json.loads(resp.read())
        assert {"started_unix", "ready", "phases", "boot_phases_sum_s"} <= set(boot)

        # the index page advertises the new endpoints
        with urllib.request.urlopen(base + "/", timeout=10) as resp:
            index = resp.read().decode()
        assert "/debug/profile" in index and "/debug/boot" in index
    finally:
        srv.stop()
        prof.uninstall_profiler()
        prof.PROFILER = old


# ---------------------------------------------------------------------------
# shared stack formatter (watchdog consolidation)
# ---------------------------------------------------------------------------


def test_format_stack_and_frame_label_shared_with_watchdog():
    import sys as _sys

    frame = _sys._getframe()
    label = frame_label(frame)
    assert label.endswith(".test_format_stack_and_frame_label_shared_with_watchdog")
    assert frame_label(frame, lineno=True).rsplit(":", 1)[1].isdigit()
    stack = format_stack(frame, limit=12, lineno=True)
    assert 0 < len(stack) <= 12
    # outermost-first: this test's frame is the LAST entry
    assert "test_format_stack_and_frame_label" in stack[-1]


def test_watchdog_stalled_dump_uses_shared_formatter():
    """A hung supervised dispatch's /statusz stack dump renders through
    profiler.format_stack — the same frame labels as the folded
    profile, so the two renderings cannot diverge."""
    from janus_tpu.aggregator.device_watchdog import DeviceHangError, DispatchWatchdog

    wd = DispatchWatchdog(abandoned_thread_cap=99)
    release = threading.Event()

    def wedge():
        release.wait(20)

    with pytest.raises(DeviceHangError):
        wd.run(wedge, deadline=time.monotonic() + 0.2, label="test_wedge")
    try:
        status = wd.status()
        assert status["abandoned_threads"] == 1
        ent = status["stalled"][0]
        assert ent["label"] == "test_wedge"
        stack = ent.get("stack")
        assert stack, status
        # shared formatter shape: module.func:lineno, innermost last —
        # the parked thread is inside wedge -> Event.wait
        assert all(s.rsplit(":", 1)[1].isdigit() for s in stack)
        assert any("threading" in s and ".wait" in s for s in stack)
    finally:
        release.set()
        wd.drain(2.0)
        wd.reset_for_tests()


def test_validate_collapsed_rejects_malformed_documents():
    assert validate_collapsed("a;b 3\n") == []
    assert validate_collapsed("") == []
    assert validate_collapsed("no_count_here") != []
    assert validate_collapsed("a;b notanint") != []
    assert validate_collapsed("a;;b 3") != []
    assert validate_collapsed("a; b 3") != []
    assert validate_collapsed(" 3") != []
