"""Per-task XOF framing modes: fast (TPU counter-mode) vs draft
(VDAF-07 sequential sponge + rejection sampling).

The draft mode removes every fast-mode deviation (SECURITY-NOTES.md):
sequential squeezing, 8-byte draft DSTs, single-byte aggregator ids,
full-share joint-rand binders, rejection sampling. Host-only; the
aggregator dispatches draft tasks to HostEngineCache.
"""

import hashlib

import numpy as np
import pytest

from janus_tpu.fields.field import Field64, Field128
from janus_tpu.vdaf.registry import VdafInstance, prio3_batched, prio3_host
from janus_tpu.vdaf.xof import XofSponge128, draft_dst

VK = bytes(range(16))


def test_sponge_sequential_squeeze_matches_one_shot():
    x = XofSponge128(b"\x01" * 16, draft_dst(1, 2), b"binder")
    a = x.next(5) + x.next(11) + x.next(170)
    y = XofSponge128(b"\x01" * 16, draft_dst(1, 2), b"binder")
    assert a == y.next(186)
    # and equals the raw SHAKE128 of the absorbed framing
    absorbed = bytes([8]) + draft_dst(1, 2) + b"\x01" * 16 + b"binder"
    assert a == hashlib.shake_128(absorbed).digest(186)


def test_sponge_rejection_sampling_in_range():
    for field in (Field64, Field128):
        v = XofSponge128(b"\x02" * 16, draft_dst(3, 4)).next_vec(field, 300)
        assert len(v) == 300
        assert all(0 <= x < field.MODULUS for x in v)
        # deterministic
        v2 = XofSponge128(b"\x02" * 16, draft_dst(3, 4)).next_vec(field, 300)
        assert v == v2


def test_draft_dst_layout():
    d = draft_dst(0x01020304, 0x0506)
    assert len(d) == 8
    assert d == bytes([7, 0]) + b"\x01\x02\x03\x04" + b"\x05\x06"


def _round_trip(inst: VdafInstance, measurements):
    """Full two-party host transcript; returns the aggregate."""
    host = prio3_host(inst)
    out_shares = [[], []]
    for k, m in enumerate(measurements):
        nonce = bytes([k]) * 16
        public, (ls, hs) = host.shard(m, nonce)
        st0, ps0 = host.prepare_init(VK, 0, nonce, public, ls)
        st1, ps1 = host.prepare_init(VK, 1, nonce, public, hs)
        msg = host.prepare_shares_to_prep([ps0, ps1])
        out_shares[0].append(host.prepare_next(st0, msg))
        out_shares[1].append(host.prepare_next(st1, msg))
    aggs = [host.aggregate(s) for s in out_shares]
    return host.unshard(aggs, len(measurements))


@pytest.mark.parametrize(
    "inst,meas,want",
    [
        (VdafInstance("count", xof_mode="draft"), [1, 0, 1], 2),
        (VdafInstance("sum", bits=8, xof_mode="draft"), [3, 200, 17], 220),
        (
            VdafInstance("sumvec", bits=4, length=5, xof_mode="draft"),
            [[1, 2, 3, 4, 5], [5, 4, 3, 2, 1]],
            [6, 6, 6, 6, 6],
        ),
        (
            VdafInstance("histogram", length=4, xof_mode="draft"),
            [0, 3, 3],
            [1, 0, 0, 2],
        ),
    ],
)
def test_draft_mode_round_trip(inst, meas, want):
    assert _round_trip(inst, meas) == want


def test_modes_produce_disjoint_transcripts():
    """The same (measurement, nonce, rand) shards to different bytes per
    mode, and a cross-mode pair rejects the report."""
    fast = prio3_host(VdafInstance("sum", bits=8))
    draft = prio3_host(VdafInstance("sum", bits=8, xof_mode="draft"))
    rand = bytes(range(fast.rand_size))
    nonce = b"\x07" * 16
    pub_f, (ls_f, hs_f) = fast.shard(9, nonce, rand)
    pub_d, (ls_d, hs_d) = draft.shard(9, nonce, rand)
    assert ls_f.measurement_share != ls_d.measurement_share

    # fast-sharded report, helper running draft framing: FLP rejects
    from janus_tpu.vdaf.reference import VdafError

    st0, ps0 = fast.prepare_init(VK, 0, nonce, pub_f, ls_f)
    st1, ps1 = draft.prepare_init(VK, 1, nonce, pub_f, hs_f)
    with pytest.raises(VdafError):
        draft.prepare_shares_to_prep([ps0, ps1])


def test_batched_engine_draft_dispatch():
    """Draft instances within the sponge-stream cap (raised again in
    r5: nested scans made long chains linear, so the cap now covers
    the north-star len=100k — draft_jax.MAX_STREAM_BLOCKS) get the
    device draft engine; truly huge streams still fall back to the
    scalar host loop."""
    from janus_tpu.vdaf.draft_jax import Prio3BatchedDraft

    p3 = prio3_batched(VdafInstance("count", xof_mode="draft"))
    assert isinstance(p3, Prio3BatchedDraft)
    mid = prio3_batched(VdafInstance("sumvec", bits=16, length=100_000, xof_mode="draft"))
    assert isinstance(mid, Prio3BatchedDraft)
    with pytest.raises(ValueError):
        prio3_batched(VdafInstance("sumvec", bits=16, length=120_000, xof_mode="draft"))


def test_engine_cache_dispatches_by_stream_length():
    from janus_tpu.aggregator.engine_cache import (
        EngineCache,
        HostEngineCache,
        engine_cache,
    )

    fast = engine_cache(VdafInstance("count"), VK)
    draft_short = engine_cache(VdafInstance("count", xof_mode="draft"), VK)
    draft_mid = engine_cache(
        VdafInstance("sumvec", bits=16, length=100_000, xof_mode="draft"), VK
    )
    draft_huge = engine_cache(
        VdafInstance("sumvec", bits=16, length=120_000, xof_mode="draft"), VK
    )
    assert isinstance(fast, EngineCache)
    assert isinstance(draft_short, EngineCache)  # device draft engine
    assert isinstance(draft_mid, EngineCache)  # r5: covers the north star
    assert isinstance(draft_huge, HostEngineCache)  # past the stream cap


def test_host_engine_matches_host_transcript():
    """HostEngineCache's columnar surface reproduces the scalar host
    protocol end to end (leader init -> helper init -> aggregate)."""
    from janus_tpu.aggregator.engine_cache import HostEngineCache
    from janus_tpu.vdaf.wire import (
        decode_field_rows,
        seeds_to_lanes,
    )

    inst = VdafInstance("sumvec", bits=2, length=3, xof_mode="draft")
    host = prio3_host(inst)
    eng = HostEngineCache(inst, VK)
    meas = [[1, 2, 3], [3, 2, 1], [0, 1, 2]]
    n = len(meas)

    nonces, meas_rows, proof_rows, blind_rows, p0_rows, p1_rows = [], [], [], [], [], []
    helper_seed_rows, helper_blind_rows = [], []
    F = host.circuit.FIELD
    for k, m in enumerate(meas):
        nonce = bytes([k + 1]) * 16
        public, (ls, hs) = host.shard(m, nonce)
        nonces.append(nonce)
        meas_rows.append(F.encode_vec(ls.measurement_share))
        proof_rows.append(F.encode_vec(ls.proof_share))
        blind_rows.append(ls.joint_rand_blind)
        p0_rows.append(public[0])
        p1_rows.append(public[1])
        helper_seed_rows.append(hs.seed)
        helper_blind_rows.append(hs.joint_rand_blind)

    nonce_lanes, _ = seeds_to_lanes(nonces)
    meas_l, ok_m = decode_field_rows(eng.jf, meas_rows, host.circuit.input_len)
    proof_l, ok_p = decode_field_rows(eng.jf, proof_rows, host.circuit.proof_len)
    assert ok_m.all() and ok_p.all()
    blind_lanes, _ = seeds_to_lanes(blind_rows)
    p0, _ = seeds_to_lanes(p0_rows)
    p1, _ = seeds_to_lanes(p1_rows)
    public_parts = np.stack([p0, p1], axis=1)

    out0, seed0, ver0, part0 = eng.leader_init(
        nonce_lanes, public_parts, meas_l, proof_l, blind_lanes
    )

    hseed_lanes, _ = seeds_to_lanes(helper_seed_rows)
    hblind_lanes, _ = seeds_to_lanes(helper_blind_rows)
    ok = np.ones(n, dtype=bool)
    out1, accept, prep_msg = eng.helper_init(
        nonce_lanes, public_parts, hseed_lanes, hblind_lanes, ver0, part0, ok
    )
    assert accept.all()
    # leader's corrected seed equals the helper-computed prep message
    assert np.array_equal(seed0, prep_msg)

    agg0 = eng.aggregate(out0, accept)
    agg1 = eng.aggregate(out1, accept)
    total = [(a + b) % F.MODULUS for a, b in zip(agg0, agg1)]
    want = [sum(col) for col in zip(*meas)]
    assert total == want
