"""Cross-job dispatch coalescing (engine_cache._Coalescer).

Concurrent leader/helper init calls on one engine must merge into
shared device dispatches (VERDICT r4 item 3) with results identical to
serial calls — including the masked aggregate over each job's
offset-view of the shared out-share buffer.
"""

import threading
from concurrent.futures import ThreadPoolExecutor

import numpy as np
import pytest

from janus_tpu.aggregator.engine_cache import EngineCache, _Coalescer
from janus_tpu.vdaf.registry import VdafInstance
from janus_tpu.vdaf.testing import make_report_batch, random_measurements

VK = bytes(range(16))


def test_coalescer_merges_concurrent_rounds():
    """Mechanics: with the run fn gated, concurrent submits ride one
    round; results map back per caller; errors propagate."""
    gate = threading.Event()
    seen = []

    def run(args_list, ns):
        gate.wait(5)
        seen.append(list(ns))
        return [sum(a) * n for a, n in zip(args_list, ns)]

    co = _Coalescer(run, max_rows=1000)

    with ThreadPoolExecutor(max_workers=8) as pool:
        futs = [pool.submit(co.submit, (i, i), 2) for i in range(8)]
        import time

        time.sleep(0.2)  # let all 8 enqueue; first is dispatcher
        gate.set()
        results = [f.result(timeout=10) for f in futs]
    assert results == [2 * i * 2 for i in range(8)]
    assert sum(co.rounds) == 8  # every call served exactly once
    # at least one round carried >1 call (7 queued behind the first)
    assert max(co.rounds) > 1, co.rounds


def test_coalescer_round_row_cap():
    gate = threading.Event()

    def run_gated(args_list, ns):
        gate.wait(5)
        assert sum(ns) <= 5
        return [n for n in ns]

    co = _Coalescer(run_gated, max_rows=5)
    with ThreadPoolExecutor(max_workers=6) as pool:
        futs = [pool.submit(co.submit, (), 3) for _ in range(6)]
        import time

        time.sleep(0.2)
        gate.set()
        assert [f.result(timeout=10) for f in futs] == [3] * 6


def test_coalescer_error_propagates_per_round():
    calls = {"n": 0}

    def run(args_list, ns):
        calls["n"] += 1
        raise RuntimeError("boom")

    co = _Coalescer(run, max_rows=100)
    with pytest.raises(RuntimeError):
        co.submit((), 1)
    assert calls["n"] == 1


@pytest.mark.parametrize(
    "kind",
    [
        "count",
        # 39s: the count variant keeps the concurrency invariant in
        # tier-1; sumvec window masking is covered fast by
        # test_coalesced_view_never_leaks_neighbor_rows (ISSUE 1 CI triage)
        pytest.param("sumvec", marks=pytest.mark.slow),
    ],
)
def test_concurrent_jobs_match_serial(kind):
    """8 small 'jobs' through one engine concurrently == serial, and at
    least one dispatch was shared."""
    inst = (
        VdafInstance.count() if kind == "count" else VdafInstance.sum_vec(length=8, bits=4)
    )
    engine = EngineCache(inst, VK)
    rng = np.random.default_rng(3)
    jobs = []
    for j in range(8):
        meas = random_measurements(inst, 4, rng)
        args, m = make_report_batch(inst, meas, seed=100 + j)
        jobs.append((args, m))

    p = engine.p3.jf.MODULUS

    def leader(args):
        """Full two-party job through the engine surface: leader init,
        helper init+decide, masked aggregates of both shares."""
        nonce, public, meas, proof, blind0, seeds, blind1 = args
        out0, seed0, ver0, part0 = engine.leader_init(nonce, public, meas, proof, blind0)
        out1, mask, _ = engine.helper_init(
            nonce, public, seeds, blind1, ver0, part0, np.ones(4, dtype=bool)
        )
        assert mask.all(), "honest reports must verify"
        agg0 = engine.aggregate(out0, mask)
        agg1 = engine.aggregate(out1, mask)
        agg = [(a + b) % p for a, b in zip(agg0, agg1)]
        return agg, seed0, ver0

    # serial reference (coalescer trivially rounds of 1)
    serial = [leader(a) for a, _ in jobs]

    gate = threading.Event()
    co = engine._co_leader
    orig = co._run

    def gated(args_list, ns):
        gate.wait(5)
        return orig(args_list, ns)

    co._run = gated
    co.rounds.clear()
    try:
        with ThreadPoolExecutor(max_workers=8) as pool:
            futs = [pool.submit(leader, a) for a, _ in jobs]
            import time

            time.sleep(0.3)
            gate.set()
            concurrent = [f.result(timeout=120) for f in futs]
    finally:
        co._run = orig

    for (agg_s, seed_s, ver_s), (agg_c, seed_c, ver_c) in zip(serial, concurrent):
        assert agg_s == agg_c
        if seed_s is None:
            assert seed_c is None
        else:
            assert (np.asarray(seed_s) == np.asarray(seed_c)).all()
        for a, b in zip(ver_s, ver_c):
            assert (np.asarray(a) == np.asarray(b)).all()
    assert max(engine._co_leader.rounds) > 1, engine._co_leader.rounds

    # aggregates also match the true sums (count: sum of measurements)
    for (agg, _, _), (_, m) in zip(concurrent, jobs):
        want = np.asarray(m).sum(axis=0)
        want = np.atleast_1d(want)
        assert agg[: len(want)] == [int(x) for x in want]


def test_coalesced_cross_job_masked_aggregate_excludes_neighbors():
    """The cross-JOB form of the window invariant (ISSUE 8 satellite,
    round-5 advisory): force a REAL coalesced round — several jobs'
    rows landing in ONE shared device buffer — where every neighbor row
    carries a nonzero out-share and each job additionally REJECTS one
    of its own lanes. Each job's masked aggregate over its
    [offset, offset+n) view must equal exactly its own accepted rows:
    neighbor rows inside the dynamic-slice window (offset+bucket often
    covers several neighbors at these sizes) must never leak in, and a
    job's own rejected lane must stay out."""
    inst = VdafInstance.sum_vec(length=3, bits=2)
    engine = EngineCache(inst, VK)
    jf = engine.p3.jf
    p = jf.MODULUS
    n_jobs, n = 5, 4
    rng = np.random.default_rng(7)
    jobs = []
    for j in range(n_jobs):
        meas = [[int(x) for x in rng.integers(1, 4, size=3)] for _ in range(n)]
        args, m = make_report_batch(inst, meas, seed=300 + j)
        jobs.append((args, m))
    # per-job masks with one rejected lane each (different positions)
    masks = [np.array([i != (j % n) for i in range(n)]) for j in range(n_jobs)]

    serial = []
    for (args, m), mask in zip(jobs, masks):
        nonce, public, meas_v, proof, blind0, seeds, blind1 = args
        out0, _, _, _ = engine.leader_init(nonce, public, meas_v, proof, blind0)
        serial.append(engine.aggregate(out0, mask))

    # force one coalesced round: gate the leader round until all submit
    gate = threading.Event()
    co = engine._co_leader
    orig = co._run

    def gated(args_list, ns):
        gate.wait(5)
        return orig(args_list, ns)

    co._run = gated
    co.rounds.clear()
    try:
        with ThreadPoolExecutor(max_workers=n_jobs) as pool:
            futs = [
                pool.submit(
                    lambda a: engine.leader_init(a[0], a[1], a[2], a[3], a[4]),
                    args,
                )
                for args, _ in jobs
            ]
            import time

            time.sleep(0.3)
            gate.set()
            outs = [f.result(timeout=120) for f in futs]
    finally:
        co._run = orig
    assert max(co.rounds) > 1, co.rounds
    # the coalesced out-shares genuinely share one buffer (offset views)
    from janus_tpu.aggregator.engine_cache import DeviceRows

    device_rows = [o[0] for o in outs if isinstance(o[0], DeviceRows)]
    assert len({id(d.value[0]) for d in device_rows}) < len(device_rows) or any(
        d.offset for d in device_rows
    )

    # each job's masked aggregate over its view of the SHARED buffer
    # equals its solo-dispatch reference: no neighbor leak, no own
    # rejected lane (the leader aggregate is one additive share, so the
    # plaintext check rides the two-party closure below)
    for (out0, *_), mask, want in zip(outs, masks, serial):
        got = engine.aggregate(out0, mask)
        assert got == want

    # full two-party closure for one job: masked sum over accepted rows
    args, m = jobs[0]
    nonce, public, meas_v, proof, blind0, seeds, blind1 = args
    out0, _, ver0, part0 = engine.leader_init(nonce, public, meas_v, proof, blind0)
    out1, ok_mask, _ = engine.helper_init(
        nonce, public, seeds, blind1, ver0, part0, np.ones(n, dtype=bool)
    )
    assert np.asarray(ok_mask).all()
    mask = masks[0]
    agg0 = engine.aggregate(out0, mask)
    agg1 = engine.aggregate(out1, mask)
    total = [(a + b) % p for a, b in zip(agg0, agg1)]
    cols = np.asarray(m, dtype=object)
    assert total == [
        int(sum(int(cols[i][k]) for i in range(n) if mask[i]) % p) for k in range(3)
    ]


def test_cross_task_coalesced_round_matches_solo_and_excludes_neighbors():
    """Cross-TASK coalescing (ISSUE 12): small jobs of TWO tasks — same
    VdafInstance, different verify keys — merged into ONE device round
    with per-lane verify keys. Re-pins the PR 7 mask-leak invariant
    across the task boundary: each job's masked aggregate over its view
    of the SHARED buffer equals its solo reference (a leaked neighbor
    row would now leak a DIFFERENT TASK's data), honest reports verify
    under their own task's key through the two-party closure, and the
    plaintext sums land exactly."""
    import time

    from janus_tpu.aggregator.engine_cache import EngineCache

    inst = VdafInstance.sum_vec(length=3, bits=2)
    eng_a = EngineCache(inst, VK)
    eng_b = EngineCache(inst, bytes(range(16, 32)))
    assert eng_a._co_leader is eng_b._co_leader, "same-inst engines share the coalescer"
    p = eng_a.p3.jf.MODULUS
    n = 4
    rng = np.random.default_rng(23)
    jobs = []
    for j in range(4):
        eng = (eng_a, eng_b)[j % 2]
        meas = [[int(x) for x in rng.integers(1, 4, size=3)] for _ in range(n)]
        args, m = make_report_batch(inst, meas, seed=700 + j)
        jobs.append((eng, args, m))
    masks = [np.array([i != (j % n) for i in range(n)]) for j in range(4)]

    def full(eng, args, mask):
        nonce, public, mv, proof, blind0, seeds, blind1 = args
        out0, _, ver0, part0 = eng.leader_init(nonce, public, mv, proof, blind0)
        out1, ok, _ = eng.helper_init(
            nonce, public, seeds, blind1, ver0, part0, np.ones(n, dtype=bool)
        )
        assert np.asarray(ok).all(), "honest reports must verify under their own key"
        agg0 = eng.aggregate(out0, mask)
        agg1 = eng.aggregate(out1, mask)
        return [(a + b) % p for a, b in zip(agg0, agg1)]

    serial = [full(e, a, mk) for (e, a, _), mk in zip(jobs, masks)]

    co = eng_a._co_leader
    gate = threading.Event()
    orig = co._run
    round_engines: list[int] = []

    def gated(args_list, ns):
        gate.wait(5)
        round_engines.append(len({id(a[0]) for a in args_list}))
        return orig(args_list, ns)

    co._run = gated
    co.rounds.clear()
    try:
        with ThreadPoolExecutor(max_workers=4) as pool:
            futs = [
                pool.submit(lambda jm: full(jm[0][0], jm[0][1], jm[1]), (j, mk))
                for j, mk in zip(jobs, masks)
            ]
            time.sleep(0.4)
            gate.set()
            concurrent = [f.result(timeout=120) for f in futs]
    finally:
        co._run = orig
    # a genuinely CROSS-task round happened (two engines in one round)
    assert max(co.rounds) > 1, co.rounds
    assert max(round_engines) > 1, round_engines
    assert concurrent == serial
    # plaintext closure: each job's sum over its own accepted rows only
    for (eng, args, m), mk, got in zip(jobs, masks, concurrent):
        want = [
            int(sum(int(m[i][k]) for i in range(n) if mk[i]) % p) for k in range(3)
        ]
        assert got == want, (got, want)


@pytest.mark.parametrize("offset", [0, 8, 40])
def test_coalesced_view_never_leaks_neighbor_rows(offset):
    """Window invariant (round-5 advisory): a job's masked aggregate
    over its [offset, offset+n) view of a shared round buffer must
    exclude the NEIGHBOR jobs' rows even though those rows sit inside
    the [offset, offset+bucket_size(n)) dynamic-slice window and carry
    nonzero out-shares. Covers both the jitted view path
    (offset+bucket <= buffer) and the full-width-mask path (view would
    run past the buffer)."""
    from janus_tpu.aggregator.engine_cache import DeviceRows, bucket_size

    inst = VdafInstance.sum_vec(length=3, bits=2)
    engine = EngineCache(inst, VK)
    jf = engine.p3.jf
    b, n, out_len = 64, 4, 3
    rng = np.random.default_rng(11)
    # every row of the shared buffer nonzero — neighbor rows included
    rows = rng.integers(1, 1000, size=(b, out_len)).astype(object)
    value = jf.from_ints(rows)
    dr = DeviceRows(value, n, offset=offset)
    vb = bucket_size(n)
    in_view_path = (offset or vb < b) and offset + vb <= b
    if offset == 40:
        assert not in_view_path  # 40 + 32 > 64: full-width mask path
    # partial mask inside the job too: row offset+1 rejected
    mask = np.array([True, False, True, True])
    agg = engine.aggregate(dr, mask)
    want = [
        int(sum(int(rows[offset + i][j]) for i in range(n) if mask[i]) % jf.MODULUS)
        for j in range(out_len)
    ]
    assert agg == want
