"""Shape manifest + AOT engine prewarm (ISSUE 14; docs/ARCHITECTURE.md
"Cold-start and prewarm"): the crash-tolerance/bounding contract of the
persisted manifest, the EngineCache first-dispatch feed, the
manifest-driven prewarm (bit-identical to cold compiles, boot-budget
deferral, readiness gating), the fixed warmup (pending-job buckets +
manifest dedup) and the serialized-executable AOT cache."""

import json
import os
import threading
import time
import zlib

import numpy as np
import pytest

from janus_tpu.aggregator import aot_cache, prewarm, shape_manifest
from janus_tpu.aggregator.shape_manifest import MANIFEST_VERSION, ShapeManifest
from janus_tpu.vdaf.registry import VdafInstance


@pytest.fixture(autouse=True)
def _isolated_state():
    prewarm.reset_for_tests()
    aot_cache.reset_for_tests()
    shape_manifest.uninstall_manifest()
    yield
    prewarm.reset_for_tests()
    aot_cache.reset_for_tests()
    shape_manifest.uninstall_manifest()


def _count_entry(man, op="leader_init", bucket=32, cost=1.0, key=None):
    man.record(
        {"kind": "count"}, op, bucket, key or (op, bucket), cost, rows=bucket
    )


# ---------------------------------------------------------------------------
# manifest file contract
# ---------------------------------------------------------------------------


def test_manifest_roundtrip_last_line_wins(tmp_path):
    p = str(tmp_path / "m.jsonl")
    m = ShapeManifest(p)
    _count_entry(m, "leader_init", 32, cost=1.5)
    _count_entry(m, "aggregate", 32, cost=0.5)
    _count_entry(m, "leader_init", 32, cost=0.9)  # re-observation
    m2 = ShapeManifest(p)
    m2.load()
    es = m2.entries()
    assert len(es) == 2
    # priority order is cost-descending; cost keeps the MAX (a cheap
    # cache-hit re-record must not demote a real compile), seen sums
    assert es[0]["op"] == "leader_init"
    assert es[0]["cost_s"] == 1.5 and es[0]["seen"] == 2
    assert m2.covers({"kind": "count"}, "aggregate", 32)
    assert not m2.covers({"kind": "count"}, "helper_init", 32)
    assert not m2.covers({"kind": "sum", "bits": 8}, "aggregate", 32)


def test_manifest_truncated_tail_loads_valid_prefix(tmp_path):
    p = str(tmp_path / "m.jsonl")
    m = ShapeManifest(p)
    _count_entry(m, "leader_init", 32)
    _count_entry(m, "helper_init", 32)
    with open(p, "ab") as f:
        f.write(b'{"v":1,"crc":12,"e"')  # torn mid-append
    m2 = ShapeManifest(p)
    stats = m2.load()
    assert stats["skipped_corrupt"] == 1
    assert len(m2.entries()) == 2  # valid prefix fully loaded


def test_manifest_crc_damage_and_junk_skipped(tmp_path):
    p = str(tmp_path / "m.jsonl")
    m = ShapeManifest(p)
    _count_entry(m, "leader_init", 32)
    entry = {"vdaf": {"kind": "count"}, "op": "aggregate", "bucket": 32, "key": ["aggregate", 32]}
    with open(p, "ab") as f:
        # bad CRC on a well-formed line, then outright junk
        f.write(
            json.dumps({"v": MANIFEST_VERSION, "crc": 1, "e": entry}).encode() + b"\n"
        )
        f.write(b"not json at all\n")
        f.write(b'[1,2,3]\n')
    m2 = ShapeManifest(p)
    stats = m2.load()
    assert stats["skipped_corrupt"] == 3
    assert len(m2.entries()) == 1
    assert not m2.covers({"kind": "count"}, "aggregate", 32)


def test_manifest_version_skew_skipped_and_counted(tmp_path):
    p = str(tmp_path / "m.jsonl")
    m = ShapeManifest(p)
    _count_entry(m, "leader_init", 32)
    entry = {"vdaf": {"kind": "count"}, "op": "x", "bucket": 64, "key": ["x", 64]}
    line = {"v": MANIFEST_VERSION + 1, "crc": zlib.crc32(b"x"), "e": entry}
    with open(p, "ab") as f:
        f.write(json.dumps(line).encode() + b"\n")
    m2 = ShapeManifest(p)
    stats = m2.load()
    assert stats["skipped_version"] == 1
    assert len(m2.entries()) == 1


def test_manifest_compaction_bounds_file_and_entries(tmp_path):
    p = str(tmp_path / "m.jsonl")
    m = ShapeManifest(p, max_entries=8)
    for b in (32, 64, 128, 256, 512, 1024):
        for op in ("leader_init", "helper_init", "aggregate"):
            m.record({"kind": "count"}, op, b, (op, b), b / 100.0)
    st = m.status()
    assert st["entries"] <= 8
    assert st["file_lines"] <= max(64, 2 * 8)
    assert st["compactions"] >= 1
    # highest-cost entries survive the bound
    kept = {(e["op"], e["bucket"]) for e in m.entries()}
    assert ("leader_init", 1024) in kept and ("leader_init", 32) not in kept
    # the compacted file reloads clean
    m2 = ShapeManifest(p, max_entries=8)
    stats = m2.load()
    assert stats["skipped_corrupt"] == 0
    assert {(e["op"], e["bucket"]) for e in m2.entries()} == kept


def test_manifest_covers_is_variant_aware(tmp_path):
    """A manifest holding only the cross-task `_vk` variant of an op
    must NOT cover the plain variant: they are distinct compiled
    programs, and the legacy warmup warms the plain one."""
    m = ShapeManifest(str(tmp_path / "m.jsonl"))
    m.record({"kind": "count"}, "leader_init", 32, ("leader_init_vk", 32), 1.0)
    assert not m.covers({"kind": "count"}, "leader_init", 32)
    m.record({"kind": "count"}, "leader_init", 32, ("leader_init", 32), 1.0)
    assert m.covers({"kind": "count"}, "leader_init", 32)


def test_inspect_file_is_read_only(tmp_path):
    """The debug-bundle inventory parse must not compact/rewrite the
    manifest — corrupt lines are the evidence being captured."""
    p = str(tmp_path / "m.jsonl")
    m = ShapeManifest(p, max_entries=2)
    for b in (32, 64, 128, 256):
        _count_entry(m, "leader_init", b, cost=b / 100.0)
    with open(p, "ab") as f:
        f.write(b"torn garbage line\n")
    before = open(p, "rb").read()
    entries, stats = shape_manifest.inspect_file(p)
    assert stats["skipped_corrupt"] == 1
    assert open(p, "rb").read() == before  # byte-identical: no rewrite
    # while a normal (product-path) load with the same bound compacts
    m2 = ShapeManifest(p, max_entries=2)
    m2.load()
    assert open(p, "rb").read() != before


def test_warmup_no_dedupe_sentinel_warms_covered_geometry(tmp_path):
    """janus_main passes _NO_DEDUPE when the manifest prewarm did not
    run (disabled/failed): a covered geometry must then still warm —
    otherwise BOTH paths skip it and the first job compiles cold."""
    from janus_tpu.binary_utils import _NO_DEDUPE, warmup_engines

    eph, task = _provisioned_store()
    try:
        shape_manifest.install_manifest(str(tmp_path / "m.jsonl"))
        man = shape_manifest.installed()
        for op in ("leader_init", "helper_init", "aggregate"):
            man.record(task.vdaf.to_dict(), op, 32, (op, 32), 1.0)
        r = warmup_engines(eph.datastore, manifest=_NO_DEDUPE)
        assert len(r["warmed"]) == 1 and r["skipped_covered"] == 0
    finally:
        eph.cleanup()


def test_manifest_missing_file_is_empty_not_fatal(tmp_path):
    m = ShapeManifest(str(tmp_path / "nope" / "m.jsonl"))
    assert m.load()["loaded"] == 0
    assert m.entries() == []
    assert m.status()["file_bytes"] == 0


def test_manifest_concurrent_record_while_reading_race_free(tmp_path):
    p = str(tmp_path / "m.jsonl")
    m = ShapeManifest(p, max_entries=32)
    errors = []

    def writer(tid):
        try:
            for i in range(50):
                m.record({"kind": "count"}, f"op{tid}", 32 * (1 + i % 4), (f"op{tid}", i), 0.1)
        except Exception as e:  # pragma: no cover - the assertion
            errors.append(e)

    def reader():
        try:
            for _ in range(100):
                m.entries()
                m.covers({"kind": "count"}, "op0", 32)
                m.status()
        except Exception as e:  # pragma: no cover
            errors.append(e)

    threads = [threading.Thread(target=writer, args=(t,)) for t in range(3)]
    threads.append(threading.Thread(target=reader))
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    assert not errors
    # the file reloads clean after the concurrent churn (+compactions)
    m2 = ShapeManifest(p, max_entries=32)
    assert m2.load()["skipped_corrupt"] == 0


# ---------------------------------------------------------------------------
# EngineCache feed + prewarm
# ---------------------------------------------------------------------------


def _dispatch_once(eng, n=20, seed=1):
    from janus_tpu.vdaf.testing import make_report_batch, random_measurements

    rng = np.random.default_rng(seed)
    args, _ = make_report_batch(
        eng.inst, random_measurements(eng.inst, n, rng), seed=seed
    )
    nonce, parts, meas, proof, blind0, hseed, blind1 = args
    out0, seed0, ver0, part0 = eng.leader_init(nonce, parts, meas, proof, blind0)
    ok = np.ones(n, dtype=bool)
    p0 = part0 if part0 is not None else np.zeros((n, 2), dtype=np.uint64)
    out1, mask, pm = eng.helper_init(nonce, parts, hseed, blind1, ver0, p0, ok)
    agg = eng.aggregate(out0, ok)
    pend = eng.aggregate_pending(out0, (np.arange(n) % 4).astype(np.int32), 4)
    return args, (out1, mask, pm, agg, pend)


def test_engine_first_dispatch_feeds_installed_manifest(tmp_path):
    from janus_tpu.aggregator.engine_cache import EngineCache

    man = shape_manifest.install_manifest(str(tmp_path / "m.jsonl"))
    eng = EngineCache(VdafInstance.count(), bytes(range(16)))
    _dispatch_once(eng)
    ops = {(e["op"], e["bucket"]) for e in man.entries()}
    assert {("leader_init", 32), ("helper_init", 32), ("aggregate", 32)} <= ops
    # the resident kk-geometry records under its own compile key; a
    # mesh engine (the conftest provisions 8 virtual devices) suffixes
    # the key with its ("mesh", dp, sp, ndev) topology (ISSUE 16)
    pend = [e for e in man.entries() if e["op"] == "aggregate_pending"]
    assert pend and pend[0]["key"][:3] == ["aggregate_pending", 4, 32]
    geom = (eng.dp, eng.sp, eng._ndev) if eng.mesh is not None else None
    assert shape_manifest.entry_geometry(pend[0]["key"]) == geom
    # re-dispatching the same specializations appends nothing new
    n_entries = len(man.entries())
    _dispatch_once(eng)
    assert len(man.entries()) == n_entries


def test_record_dispatch_skips_fakes_and_uninstalled():
    # no manifest installed: a dispatch record is a silent no-op
    shape_manifest.record_dispatch(
        VdafInstance.count(), "leader_init", 32, ("leader_init", 32), 1.0
    )
    # fakes never earn a prewarm slot even when installed


def test_prewarm_bit_identical_and_outcomes(tmp_path):
    from janus_tpu.aggregator.engine_cache import EngineCache

    man = shape_manifest.install_manifest(str(tmp_path / "m.jsonl"))
    inst = VdafInstance.count()
    key = bytes(range(16))
    eng = EngineCache(inst, key)
    args, cold = _dispatch_once(eng, seed=7)

    # a FRESH engine warmed purely from the manifest...
    eng2 = EngineCache(inst, key)
    w = prewarm._Warmer()
    outcomes = [w.warm(eng2, e) for e in man.entries()]
    assert outcomes and all(o == "warmed" for o in outcomes)
    # ...produces bit-identical results on the same real inputs
    nonce, parts, meas, proof, blind0, hseed, blind1 = args
    n = nonce.shape[0]
    ok = np.ones(n, dtype=bool)
    out0b, _, ver0b, part0b = eng2.leader_init(nonce, parts, meas, proof, blind0)
    p0 = part0b if part0b is not None else np.zeros((n, 2), dtype=np.uint64)
    out1b, maskb, pmb = eng2.helper_init(nonce, parts, hseed, blind1, ver0b, p0, ok)
    aggb = eng2.aggregate(out0b, ok)
    out1, mask, pm, agg, _ = cold
    assert agg == aggb
    assert (mask == maskb).all()
    assert (np.asarray(pm) == np.asarray(pmb)).all()
    assert all(
        (np.asarray(a) == np.asarray(b)).all()
        for a, b in zip(out1.to_numpy(), out1b.to_numpy())
    )


def test_prewarm_engines_ready_event_and_budget_deferral(tmp_path, monkeypatch):
    # plain (op, bucket) manifest keys are single-device entries; pin
    # the engines to 1x1 so the geometry gate matches them (mesh
    # coverage lives in tests/test_mesh_dispatch.py)
    monkeypatch.setenv("JANUS_MESH_DP", "1")
    monkeypatch.setenv("JANUS_MESH_SP", "1")
    from janus_tpu.core.hpke import generate_hpke_config_and_private_key
    from janus_tpu.datastore.store import EphemeralDatastore
    from janus_tpu.messages import Role
    from janus_tpu.task import QueryTypeConfig, TaskBuilder

    man = shape_manifest.install_manifest(str(tmp_path / "m.jsonl"))
    for op in ("leader_init", "helper_init", "aggregate"):
        _count_entry(man, op, 32, cost=1.0)
    eph = EphemeralDatastore()
    task = (
        TaskBuilder(QueryTypeConfig.time_interval(), VdafInstance.count(), Role.HELPER)
        .with_(
            collector_hpke_config=generate_hpke_config_and_private_key(
                config_id=3
            ).config,
        )
        .build()
    )
    eph.datastore.run_tx(lambda tx: tx.put_task(task))
    try:
        ev = threading.Event()
        summary = prewarm.prewarm_engines(
            eph.datastore, man, boot_budget_s=120.0, ready_event=ev
        )
        assert ev.is_set()
        assert summary["warmed"] == 3 and summary["deferred"] == 0
        st = prewarm.engine_prewarm_status()
        assert st["prewarm"]["state"] == "done"
        assert st["prewarm"]["warmed"] == 3
        assert st["manifest"]["installed"] is True

        # budget 0: the priority set is empty, EVERYTHING defers to the
        # background warmer — readiness is still released immediately
        prewarm.reset_for_tests()
        ev2 = threading.Event()
        s2 = prewarm.prewarm_engines(
            eph.datastore, man, boot_budget_s=0.0, ready_event=ev2
        )
        assert ev2.is_set() and s2["deferred"] == 3
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            if prewarm.engine_prewarm_status()["prewarm"]["state"] == "done":
                break
            time.sleep(0.05)
        assert prewarm.engine_prewarm_status()["prewarm"]["state"] == "done"
    finally:
        eph.cleanup()


def test_prewarm_no_matching_task_counts_no_task(tmp_path):
    from janus_tpu.datastore.store import EphemeralDatastore

    man = shape_manifest.install_manifest(str(tmp_path / "m.jsonl"))
    man.record({"kind": "sum", "bits": 8}, "aggregate", 32, ("aggregate", 32), 1.0)
    eph = EphemeralDatastore()
    try:
        ev = threading.Event()
        summary = prewarm.prewarm_engines(
            eph.datastore, man, boot_budget_s=30.0, ready_event=ev
        )
        assert ev.is_set() and summary["warmed"] == 0
        assert prewarm.engine_prewarm_status()["prewarm"]["no_task"] == 1
    finally:
        eph.cleanup()


def test_manifest_less_prewarm_degrades_to_noop(tmp_path):
    """A boot with no manifest (or an empty one) must behave exactly
    like today: prewarm is a no-op that releases readiness at once."""
    from janus_tpu.datastore.store import EphemeralDatastore

    eph = EphemeralDatastore()
    try:
        ev = threading.Event()
        summary = prewarm.prewarm_engines(eph.datastore, None, ready_event=ev)
        assert ev.is_set() and summary == {
            "entries": 0, "warmed": 0, "deferred": 0, "priority_elapsed_s": 0.0,
        }
    finally:
        eph.cleanup()


def test_unsupported_variant_counted_not_fatal(tmp_path, monkeypatch):
    from janus_tpu.aggregator.engine_cache import EngineCache

    # single-device engine: the geometry gate runs before op support,
    # so a mesh engine would report geometry_mismatch for these plain
    # keys instead of exercising the unsupported path
    monkeypatch.setenv("JANUS_MESH_DP", "1")
    monkeypatch.setenv("JANUS_MESH_SP", "1")
    man = ShapeManifest(str(tmp_path / "m.jsonl"))
    man.record({"kind": "count"}, "mystery_op", 32, ("mystery_op_vq", 32), 1.0)
    man.record({"kind": "count"}, "leader_init", 8, ("leader_init", 8), 1.0)
    eng = EngineCache(VdafInstance.count(), bytes(range(16)))
    w = prewarm._Warmer()
    assert [w.warm(eng, e) for e in man.entries()] == ["unsupported", "unsupported"]


# ---------------------------------------------------------------------------
# warmup_engines: real pending-job buckets + manifest dedup
# ---------------------------------------------------------------------------


def _provisioned_store():
    from janus_tpu.core.hpke import generate_hpke_config_and_private_key
    from janus_tpu.datastore.store import EphemeralDatastore
    from janus_tpu.messages import Role
    from janus_tpu.task import QueryTypeConfig, TaskBuilder

    eph = EphemeralDatastore()
    task = (
        TaskBuilder(QueryTypeConfig.time_interval(), VdafInstance.count(), Role.HELPER)
        .with_(
            collector_hpke_config=generate_hpke_config_and_private_key(
                config_id=9
            ).config,
        )
        .build()
    )
    eph.datastore.run_tx(lambda tx: tx.put_task(task))
    return eph, task


def _put_pending_job(ds, task, job_id: bytes, n_reports: int):
    def tx_body(tx):
        tx._c.execute(
            "INSERT INTO aggregation_jobs (task_id, job_id,"
            " aggregation_parameter, partial_batch_identifier,"
            " client_interval_start, client_interval_duration, state)"
            " VALUES (?, ?, ?, ?, 0, 3600, 'in_progress')",
            (task.task_id.data, job_id, b"", b""),
        )
        for i in range(n_reports):
            tx._c.execute(
                "INSERT INTO report_aggregations (task_id, job_id,"
                " report_id, client_time, ord, state)"
                " VALUES (?, ?, ?, 0, ?, 'waiting')",
                (task.task_id.data, job_id, job_id + bytes([i, i]), i),
            )

    ds.run_tx(tx_body)


def test_pending_aggregation_job_sizes_tx():
    eph, task = _provisioned_store()
    try:
        _put_pending_job(eph.datastore, task, b"job-aaaaaaaaaaaa", 5)
        _put_pending_job(eph.datastore, task, b"job-bbbbbbbbbbbb", 40)
        sizes = eph.datastore.run_tx(
            lambda tx: tx.get_pending_aggregation_job_sizes()
        )
        assert sorted(sizes[task.task_id.data]) == [5, 40]
    finally:
        eph.cleanup()


def test_warmup_warms_pending_job_buckets_and_skips_covered(tmp_path, monkeypatch):
    from janus_tpu.binary_utils import warmup_engines

    # pin 1x1 so the hand-recorded plain (op, bucket) keys in m2 cover
    # the warm dispatches (covers() matches per-geometry)
    monkeypatch.setenv("JANUS_MESH_DP", "1")
    monkeypatch.setenv("JANUS_MESH_SP", "1")

    eph, task = _provisioned_store()
    try:
        # 40 pending reports -> the 64 bucket, NOT the blind MIN_BUCKET
        _put_pending_job(eph.datastore, task, b"job-cccccccccccc", 40)
        man = ShapeManifest(str(tmp_path / "m.jsonl"))
        r = warmup_engines(eph.datastore, manifest=man)
        assert [b for _, b in r["warmed"]] == [64]
        assert r["skipped_covered"] == 0
        # installed manifest recorded the warm dispatches; a second
        # warmup skips the whole covered geometry
        shape_manifest.install_manifest(str(tmp_path / "m2.jsonl"))
        man2 = shape_manifest.installed()
        for op in ("leader_init", "helper_init", "aggregate"):
            man2.record(task.vdaf.to_dict(), op, 64, (op, 64), 1.0)
        r2 = warmup_engines(eph.datastore)  # uses the installed manifest
        assert r2["skipped_covered"] == 1 and not r2["warmed"]
    finally:
        eph.cleanup()


def test_warmup_without_pending_jobs_keeps_min_bucket():
    from janus_tpu.aggregator.engine_cache import MIN_BUCKET
    from janus_tpu.binary_utils import warmup_engines

    eph, _ = _provisioned_store()
    try:
        r = warmup_engines(eph.datastore)
        assert [b for _, b in r["warmed"]] == [MIN_BUCKET]
    finally:
        eph.cleanup()


# ---------------------------------------------------------------------------
# serialized-executable AOT cache
# ---------------------------------------------------------------------------


_AOT_CHILD = r"""
import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
import numpy as np
import jax, jax.numpy as jnp
from janus_tpu.aggregator import aot_cache

aot_cache.arm(sys.argv[1])
x = np.arange(64, dtype=np.uint64)
w = aot_cache.wrap(jax.jit(lambda a: a * jnp.uint64(3) + jnp.uint64(1)), "base-1")
y = np.asarray(w(x))
st = aot_cache.status()
print("RESULT", st["loads"], st["saves"], st["errors"], ",".join(map(str, y[:4])))
"""


def test_aot_cache_save_load_bit_identical_across_processes(tmp_path):
    """The production restart semantics: process A compiles + saves the
    serialized executable, a FRESH process B deserializes it (no
    trace) and computes the identical result. Same-process reloads may
    legitimately fall back (XLA:CPU resident-symbol quirk; covered by
    the corrupt-blob test's fallback path), so each half runs in its
    own interpreter — exactly like a restarted driver."""
    import subprocess
    import sys

    d = str(tmp_path / "aot")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=repo)
    env.pop("XLA_FLAGS", None)  # single device, like the real drivers

    def run():
        out = subprocess.run(
            [sys.executable, "-c", _AOT_CHILD, d],
            env=env, capture_output=True, text=True, timeout=240, cwd=repo,
        )
        assert out.returncode == 0, out.stderr[-2000:]
        line = [l for l in out.stdout.splitlines() if l.startswith("RESULT")][-1]
        _, loads, saves, errors, vals = line.split(" ")
        return int(loads), int(saves), int(errors), vals

    loads1, saves1, errors1, vals1 = run()  # cold: compiles + saves
    assert (loads1, saves1, errors1) == (0, 1, 0)
    loads2, saves2, errors2, vals2 = run()  # warm restart: pure load
    assert (loads2, saves2, errors2) == (1, 0, 0)
    assert vals1 == vals2  # bit-identical across the serialize boundary
    blobs = [n for n in os.listdir(d) if n.endswith(aot_cache.BLOB_SUFFIX)]
    assert len(blobs) == 1


def test_aot_cache_corrupt_blob_falls_back_and_heals(tmp_path):
    import jax
    import jax.numpy as jnp

    d = str(tmp_path / "aot")
    aot_cache.arm(d)
    x = np.arange(32, dtype=np.uint64)

    def fn(a):
        return a + jnp.uint64(7)

    w1 = aot_cache.wrap(jax.jit(fn), "base-c")
    ref = np.asarray(w1(x))
    (blob,) = [n for n in os.listdir(d) if n.endswith(aot_cache.BLOB_SUFFIX)]
    with open(os.path.join(d, blob), "wb") as f:
        f.write(b"garbage, not a pickled executable")
    w2 = aot_cache.wrap(jax.jit(fn), "base-c")
    out = np.asarray(w2(x))
    assert (out == ref).all()
    st = aot_cache.status()
    assert st["errors"] >= 1
    # the corrupt blob was deleted and re-saved by the fallback compile
    assert st["blobs"] == 1 and st["saves"] == 2


def test_aot_cache_disarmed_is_passthrough(tmp_path):
    import jax
    import jax.numpy as jnp

    w = aot_cache.wrap(jax.jit(lambda a: a * jnp.uint64(2)), "base-d")
    out = np.asarray(w(np.arange(8, dtype=np.uint64)))
    assert (out == np.arange(8, dtype=np.uint64) * 2).all()
    st = aot_cache.status()
    assert st["enabled"] is False and st["saves"] == 0 and st["loads"] == 0


# ---------------------------------------------------------------------------
# statusz section shape (what scrape_check enforces on live binaries)
# ---------------------------------------------------------------------------


def test_engine_prewarm_statusz_section_shape(tmp_path):
    man = shape_manifest.install_manifest(str(tmp_path / "m.jsonl"))
    _count_entry(man)
    prewarm.note_compile_cache(str(tmp_path / "cache"))
    snap = prewarm.engine_prewarm_status()
    assert {"compile_cache", "aot", "manifest", "prewarm"} <= set(snap)
    assert {"enabled", "dir", "files", "bytes"} <= set(snap["compile_cache"])
    assert {"enabled", "blobs", "loads", "saves"} <= set(snap["aot"])
    assert snap["manifest"]["installed"] is True
    assert snap["manifest"]["entries"] == 1
    assert {"state", "warmed", "cache_hits", "cache_misses"} <= set(snap["prewarm"])
    # registered as a statusz provider in every binary
    from janus_tpu.statusz import status_snapshot

    assert "engine_prewarm" in status_snapshot()
