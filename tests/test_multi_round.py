"""Multi-round prepare (continue) machinery over the live pair, driven
by the two-round fake VDAF — the same way the reference exercises
aggregation_job_continue.rs with dummy_vdaf: WaitingLeader/WaitingHelper
states, ord-matched AggregationJobContinueReq, step validation, replay
idempotency, accumulate-at-finish."""

import pytest

from janus_tpu.aggregator.aggregation_job_creator import (
    AggregationJobCreator,
    AggregationJobCreatorConfig,
)
from janus_tpu.aggregator.aggregation_job_driver import AggregationJobDriver
from janus_tpu.aggregator.collection_job_driver import CollectionJobDriver
from janus_tpu.aggregator.job_driver import JobDriver, JobDriverConfig
from janus_tpu.client import Client, ClientParameters
from janus_tpu.collector import Collector, CollectorParameters
from janus_tpu.core.http_client import HttpClient
from janus_tpu.datastore.models import ReportAggregationState
from janus_tpu.messages import (
    AggregationJobContinueReq,
    AggregationJobStep,
    Duration,
    Interval,
    Query,
    Time,
)
from janus_tpu.vdaf.registry import VdafInstance

from test_e2e import pair, provision  # noqa: F401  (fixture + helper)

VDAF = VdafInstance.fake_two_round()


def _upload(pair, leader_task, measurements):
    http = HttpClient()
    params = ClientParameters(
        leader_task.task_id,
        pair["leader_srv"].url,
        pair["helper_srv"].url,
        leader_task.time_precision,
    )
    client = Client.with_fetched_configs(params, VDAF, http, clock=pair["clock"])
    for m in measurements:
        client.upload(m)
    return http, params


def _continue_url(pair, leader_task, job_id_bytes):
    import base64

    b64 = lambda b: base64.urlsafe_b64encode(b).decode().rstrip("=")
    return (
        pair["helper_srv"].url.rstrip("/")
        + f"/tasks/{b64(leader_task.task_id.data)}/aggregation_jobs/{b64(job_id_bytes)}"
    )


def _states(ds, task_id, job_id):
    ras = ds.run_tx(lambda tx: tx.get_report_aggregations_for_job(task_id, job_id))
    return [ra.state for ra in ras]


def test_two_round_full_protocol(pair):
    leader_task, helper_task, collector_kp = provision(pair, VDAF)
    measurements = [1, 0, 1, 1]
    http, params = _upload(pair, leader_task, measurements)

    creator = AggregationJobCreator(
        pair["leader_ds"], AggregationJobCreatorConfig(min_aggregation_job_size=1)
    )
    assert creator.run_once() == 1
    driver = AggregationJobDriver(pair["leader_ds"], http)
    jd = JobDriver(
        JobDriverConfig(max_concurrent_job_workers=1), driver.acquirer(), driver.stepper
    )

    # step 1: init round — both sides park in Waiting*
    assert jd.run_once() == 1
    job = pair["leader_ds"].run_tx(
        lambda tx: tx.get_aggregation_jobs_for_task(leader_task.task_id)
    )[0]
    assert set(_states(pair["leader_ds"], leader_task.task_id, job.job_id)) == {
        ReportAggregationState.WAITING_LEADER
    }
    assert set(_states(pair["helper_ds"], helper_task.task_id, job.job_id)) == {
        ReportAggregationState.WAITING_HELPER
    }

    # step 2: continue round — both sides finish, shares accumulate
    assert jd.run_once() == 1
    assert set(_states(pair["leader_ds"], leader_task.task_id, job.job_id)) == {
        ReportAggregationState.FINISHED
    }
    assert set(_states(pair["helper_ds"], helper_task.task_id, job.job_id)) == {
        ReportAggregationState.FINISHED
    }

    # collect end-to-end (the fake runs the Count circuit)
    clock = pair["clock"]
    start = Time(clock.now().seconds).to_batch_interval_start(leader_task.time_precision)
    query = Query.time_interval(Interval(Time(start.seconds - 3600), Duration(2 * 3600)))
    collector = Collector(
        CollectorParameters(
            leader_task.task_id,
            pair["leader_srv"].url,
            leader_task.collector_auth_token,
            collector_kp,
        ),
        VDAF,
        http,
    )
    job_id = collector.start_collection(query)
    cdriver = CollectionJobDriver(pair["leader_ds"], http)
    cjd = JobDriver(
        JobDriverConfig(max_concurrent_job_workers=1), cdriver.acquirer(), cdriver.stepper
    )
    assert cjd.run_once() == 1
    result = collector.poll_once(job_id, query)
    assert result.report_count == len(measurements)
    assert result.aggregate_result == sum(measurements)


def test_continue_step_and_order_validation(pair):
    leader_task, helper_task, _ = provision(pair, VDAF)
    http, params = _upload(pair, leader_task, [1, 1])
    creator = AggregationJobCreator(
        pair["leader_ds"], AggregationJobCreatorConfig(min_aggregation_job_size=1)
    )
    assert creator.run_once() == 1

    captured = {}

    class CapturingHttp(HttpClient):
        def post(self, url, body, headers=None, timeout=None):
            if "aggregation_jobs" in url:
                captured["url"] = url
                captured["body"] = body
                captured["headers"] = headers
            return super().post(url, body, headers, timeout=timeout)

    chttp = CapturingHttp()
    driver = AggregationJobDriver(pair["leader_ds"], chttp)
    jd = JobDriver(
        JobDriverConfig(max_concurrent_job_workers=1), driver.acquirer(), driver.stepper
    )
    assert jd.run_once() == 1  # init round; reports parked

    job = pair["leader_ds"].run_tx(
        lambda tx: tx.get_aggregation_jobs_for_task(leader_task.task_id)
    )[0]
    url = _continue_url(pair, leader_task, job.job_id.data)
    headers = {
        "Content-Type": AggregationJobContinueReq.MEDIA_TYPE,
        **leader_task.aggregator_auth_token.request_headers(),
    }

    # step 0 is never a valid continue target
    bad0 = AggregationJobContinueReq(AggregationJobStep(0), ())
    status, body = http.post(url, bad0.to_bytes(), headers)
    assert status == 400 and b"invalidMessage" in body

    # skipping ahead is a step mismatch
    bad2 = AggregationJobContinueReq(AggregationJobStep(2), ())
    status, body = http.post(url, bad2.to_bytes(), headers)
    assert status == 400 and b"stepMismatch" in body

    # right step but an unknown report id: ord-match rejection (the
    # reference accepts leader-OMITTED rows as ReportDropped but rejects
    # steps addressing reports it is not waiting on,
    # aggregation_job_continue.rs:58-84)
    from janus_tpu.messages import PrepareContinue, ReportId
    from janus_tpu.vdaf.wire import PP_FINISH, encode_pingpong

    bad_unknown = AggregationJobContinueReq(
        AggregationJobStep(1),
        (
            PrepareContinue(
                ReportId(b"\xee" * 16), encode_pingpong(PP_FINISH, b"", None)
            ),
        ),
    )
    status, body = http.post(url, bad_unknown.to_bytes(), headers)
    assert status == 400 and b"invalidMessage" in body
    bad_empty = AggregationJobContinueReq(AggregationJobStep(1), ())

    # drive the real continue; capture the leader's request bytes
    assert jd.run_once() == 1
    assert "body" in captured
    status1, body1 = chttp.post(captured["url"], captured["body"], captured["headers"])
    # identical replay of the continue request: idempotent 200, same resp
    assert status1 == 200
    status2, body2 = http.post(captured["url"], captured["body"], captured["headers"])
    assert status2 == 200 and body2 == body1

    # same step, different request: step mismatch (replay guard)
    status, body = http.post(url, bad_empty.to_bytes(), headers)
    assert status == 400 and b"stepMismatch" in body


def test_init_replay_while_waiting_helper(pair):
    """Leader timeout + re-PUT of the identical init request while the
    helper's rows are parked in WAITING_HELPER must replay the original
    ping-pong CONTINUE response — not reject the reports (the
    _replay_aggregate_init_response multi-round gap, ADVICE r2)."""
    leader_task, helper_task, _ = provision(pair, VDAF)
    http, params = _upload(pair, leader_task, [1, 0, 1])
    creator = AggregationJobCreator(
        pair["leader_ds"], AggregationJobCreatorConfig(min_aggregation_job_size=1)
    )
    assert creator.run_once() == 1

    captured = {}

    class CapturingHttp(HttpClient):
        def put(self, url, body, headers=None, timeout=None):
            if "aggregation_jobs" in url:
                captured["url"] = url
                captured["body"] = body
                captured["headers"] = headers
            return super().put(url, body, headers, timeout=timeout)

    chttp = CapturingHttp()
    driver = AggregationJobDriver(pair["leader_ds"], chttp)
    jd = JobDriver(
        JobDriverConfig(max_concurrent_job_workers=1), driver.acquirer(), driver.stepper
    )
    assert jd.run_once() == 1  # init round; helper rows parked WAITING_HELPER
    assert "body" in captured

    job = pair["leader_ds"].run_tx(
        lambda tx: tx.get_aggregation_jobs_for_task(leader_task.task_id)
    )[0]
    assert set(_states(pair["helper_ds"], helper_task.task_id, job.job_id)) == {
        ReportAggregationState.WAITING_HELPER
    }

    # identical re-PUT: replayed response must match the original —
    # every report still a ping-pong CONTINUE, none rejected
    from janus_tpu.messages import AggregationJobResp, PrepareStepResult

    status, body = chttp.put(captured["url"], captured["body"], captured["headers"])
    assert status in (200, 201)
    resp = AggregationJobResp.from_bytes(body)
    assert len(resp.prepare_resps) == 3
    for pr in resp.prepare_resps:
        assert pr.result.kind == PrepareStepResult.CONTINUE, pr.result
    # rows still parked, step unchanged (replay had no side effects)
    assert set(_states(pair["helper_ds"], helper_task.task_id, job.job_id)) == {
        ReportAggregationState.WAITING_HELPER
    }

    # the job still completes normally after the replay
    assert jd.run_once() == 1
    assert set(_states(pair["helper_ds"], helper_task.task_id, job.job_id)) == {
        ReportAggregationState.FINISHED
    }
