"""HPKE conformance against the reference's own RFC 9180 test vectors.

``tests/vectors/hpke-rfc9180.json`` is a verbatim copy of the
reference's ``core/src/test-vectors.json`` (public RFC 9180 Appendix A
data the reference checks its hpke-dispatch backend against,
``core/src/hpke.rs`` mod tests).  This proves our decap → LabeledExtract/
Expand → key-schedule → AEAD pipeline byte-for-byte against published
vectors rather than only round-tripping against itself.

Of the 24 base-mode vectors, 12 fall inside our suite matrix
(X25519/P-256 KEMs × HKDF-SHA256/512 × AES-128/256-GCM,
ChaCha20Poly1305); X448 and P-521 KEMs are outside DAP's registry and
are asserted to be *rejected*, not silently skipped.
"""

import json
import os

import pytest

from janus_tpu.core import hpke as H
from janus_tpu.messages import (
    HpkeAeadId,
    HpkeConfig,
    HpkeConfigId,
    HpkeKdfId,
    HpkeKemId,
)

VECTOR_PATH = os.path.join(os.path.dirname(__file__), "vectors", "hpke-rfc9180.json")

with open(VECTOR_PATH) as f:
    _ALL = json.load(f)

_SUPPORTED_KEMS = {int(k) for k in H._KEMS}
_SUPPORTED_KDFS = {int(k) for k in H._KDF_HASH}
_SUPPORTED_AEADS = {int(k) for k in H._AEAD}


def _supported(v) -> bool:
    return (
        v["mode"] == 0
        and v["kem_id"] in _SUPPORTED_KEMS
        and v["kdf_id"] in _SUPPORTED_KDFS
        and v["aead_id"] in _SUPPORTED_AEADS
    )


SUPPORTED = [v for v in _ALL if _supported(v)]
UNSUPPORTED = [v for v in _ALL if not _supported(v)]


def _ids(vs):
    return [f"kem{v['kem_id']}-kdf{v['kdf_id']}-aead{v['aead_id']}" for v in vs]


def _config_for(v) -> HpkeConfig:
    return HpkeConfig(
        HpkeConfigId(0),
        HpkeKemId(v["kem_id"]),
        HpkeKdfId(v["kdf_id"]),
        HpkeAeadId(v["aead_id"]),
        bytes.fromhex(v["pkRm"]),
    )


def test_coverage_is_what_we_claim():
    # 24 vectors; exactly half are inside DAP's suite registry.
    assert len(_ALL) == 24
    assert len(SUPPORTED) == 12
    kems = {v["kem_id"] for v in SUPPORTED}
    assert kems == {int(HpkeKemId.X25519_HKDF_SHA256), int(HpkeKemId.P256_HKDF_SHA256)}


@pytest.mark.parametrize("v", SUPPORTED, ids=_ids(SUPPORTED))
def test_recipient_pipeline_matches_vector(v):
    """decap(skRm, enc) → shared secret → key schedule → open each ct."""
    config = _config_for(v)
    kem = H._kem_for(config.kem_id)
    enc = bytes.fromhex(v["enc"])
    sk = bytes.fromhex(v["skRm"])

    dh = kem.decap(sk, enc)
    shared_secret = H._extract_and_expand(kem, dh, enc + config.public_key)
    aead, base_nonce = H._key_schedule(config, shared_secret, bytes.fromhex(v["info"]))

    assert base_nonce == bytes.fromhex(v["base_nonce"])

    for seq, e in enumerate(v["encryptions"]):
        nonce = bytes.fromhex(e["nonce"])
        # RFC 9180 ComputeNonce: base_nonce XOR I2OSP(seq, Nn)
        expect_nonce = bytes(
            b ^ s for b, s in zip(base_nonce, seq.to_bytes(H.NN, "big"))
        )
        assert nonce == expect_nonce
        pt = aead.decrypt(nonce, bytes.fromhex(e["ct"]), bytes.fromhex(e["aad"]))
        assert pt == bytes.fromhex(e["pt"])


@pytest.mark.parametrize("v", SUPPORTED, ids=_ids(SUPPORTED))
def test_seal_roundtrips_through_vector_key(v):
    """Our sender path seals to pkRm; the vector's skRm opens it.

    (Seal is randomized — encap draws a fresh ephemeral key — so the
    vector can't pin sender bytes; interoperating with the vector's
    recipient key *is* the sender-side conformance statement.)
    """
    config = _config_for(v)
    # Use the raw-info internals so the vector's info bytes are honored.
    kem = H._kem_for(config.kem_id)
    dh, enc = kem.encap(config.public_key)
    shared_secret = H._extract_and_expand(kem, dh, enc + config.public_key)
    aead, base_nonce = H._key_schedule(config, shared_secret, bytes.fromhex(v["info"]))
    ct = aead.encrypt(base_nonce, b"round trip", b"aad")

    sk = bytes.fromhex(v["skRm"])
    dh_r = kem.decap(sk, enc)
    ss_r = H._extract_and_expand(kem, dh_r, enc + config.public_key)
    aead_r, base_nonce_r = H._key_schedule(config, ss_r, bytes.fromhex(v["info"]))
    assert base_nonce_r == base_nonce
    assert aead_r.decrypt(base_nonce, ct, b"aad") == b"round trip"


@pytest.mark.parametrize(
    "v",
    [v for v in UNSUPPORTED if v["kem_id"] not in _SUPPORTED_KEMS],
    ids=_ids([v for v in UNSUPPORTED if v["kem_id"] not in _SUPPORTED_KEMS]),
)
def test_unsupported_kems_rejected(v):
    """X448 / P-521 are outside DAP's registry: explicit HpkeError."""
    with pytest.raises((H.HpkeError, ValueError)):
        H._kem_for(HpkeKemId(v["kem_id"]))
