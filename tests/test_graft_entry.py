"""Driver entry points must stay green: single-chip jittable forward
step and the multi-chip sharded dry run (the driver executes these
verbatim; a regression here is invisible to the rest of the suite)."""

import importlib.util
import sys
from pathlib import Path

import pytest

_ENTRY_PATH = Path(__file__).resolve().parents[1] / "__graft_entry__.py"


def _load_entry_module():
    spec = importlib.util.spec_from_file_location("__graft_entry__", str(_ENTRY_PATH))
    mod = importlib.util.module_from_spec(spec)
    sys.modules.setdefault("__graft_entry__", mod)
    spec.loader.exec_module(mod)
    return mod


def test_entry_compiles_and_runs():
    import jax

    mod = _load_entry_module()
    fn, args = mod.entry()
    out = jax.block_until_ready(jax.jit(fn)(*args))
    batch = args[0].shape[0]
    assert int(out[2]) == batch  # every well-formed report accepted


@pytest.mark.slow  # 441s: three sharded len<=100k compiles; the multichip witness runs nightly/on-chip (ISSUE 1 CI triage)
def test_dryrun_multichip_8():
    # conftest forces an 8-device virtual CPU topology
    mod = _load_entry_module()
    mod.dryrun_multichip(8)
