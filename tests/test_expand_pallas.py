"""Fused expansion kernel (janus_tpu/ops/expand_pallas.py).

Two layers:
  - the u32-word mod-p reduction is plain jnp math — differential
    against Python big-int reduction, always runs;
  - the full fused kernel (Keccak + sampling in one pallas_call) runs
    natively on TPU; on CPU it needs pallas interpret mode, which for
    the 24-round unrolled body is far too slow for default CI — opt-in
    via JANUS_PALLAS_TESTS=1, same policy as test_keccak_pallas.py.
    (On-chip validation: bit-exact vs XofCtr128.next_vec, run on real
    TPU hardware during round 3.)
"""

import os

import numpy as np
import pytest

import jax.numpy as jnp

from janus_tpu.fields.field import Field128
from janus_tpu.ops import expand_pallas as ep
from janus_tpu.ops import keccak_pallas as kp
from janus_tpu.vdaf import keccak_jax as kj


def test_reduce_words_matches_bigint():
    rng = np.random.default_rng(7)
    shape = (8, 128)
    # stress the fold bounds: uniform values plus all-ones tails
    w = [rng.integers(0, 1 << 32, size=shape, dtype=np.uint32) for _ in range(6)]
    w[4][0, :] = 0xFFFFFFFF
    w[5][0, :] = 0xFFFFFFFF
    w[5][1, :] = 0
    w[4][1, :] = 0
    zero = jnp.zeros(shape, jnp.uint32)
    words = ep._reduce_f128_words(tuple(jnp.asarray(x) for x in w), zero)
    got = sum(
        np.asarray(words[k]).astype(object) << (32 * k) for k in range(4)
    )
    want = sum(x.astype(object) << (32 * k) for k, x in enumerate(w)) % Field128.MODULUS
    assert (got == want).all()


@pytest.mark.skipif(
    os.environ.get("JANUS_PALLAS_TESTS") != "1"
    and __import__("jax").default_backend() != "tpu",
    reason="pallas interpret-mode compile of the 24-round body is far "
    "too slow on this host; set JANUS_PALLAS_TESTS=1 (needs a warm "
    "JAX_COMPILATION_CACHE_DIR or many cores)",
)
def test_fused_expand_matches_host_xof(monkeypatch):
    """Full fused kernel vs the host XOF oracle, in interpret mode.

    Uses an 8-block tile (cache-safe: the tile size is part of _call's
    key) — same kernel body, same framing, multiple grid cells along
    both axes — to keep the interpret-mode graph as small as possible;
    even so, the unrolled 24-round body costs a one-off multi-minute
    XLA CPU compile, hence the opt-in gate (same policy as
    test_keccak_pallas.py). The production 128-block tile was validated
    bit-exact against the host oracle on real TPU hardware (round 3)."""
    from janus_tpu.vdaf.xof import XofCtr128, dst

    monkeypatch.setattr(kp, "_mode", lambda: "interpret")
    monkeypatch.setattr(ep, "_TILE_BLOCKS", 8)
    d = dst(0x42, 3)
    seeds = [bytes([i] * 16) for i in range(3)]
    binder = (1).to_bytes(8, "little")
    length = 70  # blocks = 10 -> nb=2 tiles of 8, incl. a padded tail
    seed_lanes = jnp.asarray(
        np.stack([kj.bytes_to_lanes(s) for s in seeds]).astype(np.uint64)
    )
    parts = [(0, d), (2, seed_lanes), (4, binder)]
    prefix = kj._assemble_segments(parts, 5, 3)
    from janus_tpu.fields.jfield import JF128

    blocks = kj.sample_count_blocks(JF128, length)
    lo, hi = ep.expand_f128(prefix, blocks, length)
    got = np.asarray(lo).astype(object) + (np.asarray(hi).astype(object) << 64)
    for i, s in enumerate(seeds):
        want = XofCtr128(s, d, binder).next_vec(Field128, length)
        assert got[i].tolist() == want


def test_enabled_gating():
    from janus_tpu.fields.jfield import JF64, JF128

    monkey_mode = kp._mode  # not patched here: CPU default is "off"
    if monkey_mode() == "off":
        assert not ep.enabled(JF128, 10_000)
    # Field64 never dispatches (block straddling), regardless of mode
    assert not ep.enabled(JF64, 10_000)
