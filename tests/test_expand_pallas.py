"""Fused expansion kernel (janus_tpu/ops/expand_pallas.py).

Two layers:
  - the u32-word mod-p reduction is plain jnp math — differential
    against Python big-int reduction, always runs;
  - the full fused kernel (Keccak + sampling in one pallas_call) runs
    natively on TPU; on CPU it needs pallas interpret mode, which for
    the 24-round unrolled body is far too slow for default CI — opt-in
    via JANUS_PALLAS_TESTS=1, same policy as test_keccak_pallas.py.
    (On-chip validation: bit-exact vs XofCtr128.next_vec, run on real
    TPU hardware during round 3.)
"""

import os

import numpy as np
import pytest

import jax.numpy as jnp

from janus_tpu.fields.field import Field128
from janus_tpu.ops import expand_pallas as ep
from janus_tpu.ops import keccak_pallas as kp
from janus_tpu.vdaf import keccak_jax as kj


def test_reduce_words_matches_bigint():
    rng = np.random.default_rng(7)
    shape = (8, 128)
    # stress the fold bounds: uniform values plus all-ones tails
    w = [rng.integers(0, 1 << 32, size=shape, dtype=np.uint32) for _ in range(6)]
    w[4][0, :] = 0xFFFFFFFF
    w[5][0, :] = 0xFFFFFFFF
    w[5][1, :] = 0
    w[4][1, :] = 0
    zero = jnp.zeros(shape, jnp.uint32)
    words = ep._reduce_f128_words(tuple(jnp.asarray(x) for x in w), zero)
    got = sum(
        np.asarray(words[k]).astype(object) << (32 * k) for k in range(4)
    )
    want = sum(x.astype(object) << (32 * k) for k, x in enumerate(w)) % Field128.MODULUS
    assert (got == want).all()


_FULL = (
    os.environ.get("JANUS_PALLAS_TESTS") == "1"
    or __import__("jax").default_backend() == "tpu"
)
_ROUNDS = 24 if _FULL else 2


def test_fused_expand_matches_oracle(monkeypatch):
    """Full fused kernel at the PRODUCTION 128-block tile, always on.

    At 24 rounds (TPU, or JANUS_PALLAS_TESTS=1) the oracle is the host
    XofCtr128. At reduced rounds (default CPU CI) the oracle is the
    unfused device path at the same count — the round function is
    shared, so this pins everything else: prefix interleave, counter
    lanes, SHAKE padding, 128-block tiling with a padded tail tile,
    in-kernel mod-p sampling, and the output transpose (the r4 skip
    gap, VERDICT item 6)."""
    from janus_tpu.vdaf.xof import XofCtr128, dst

    monkeypatch.setattr(kp, "_mode", lambda: "interpret")
    d = dst(0x42, 3)
    seeds = [bytes([i] * 16) for i in range(3)]
    binder = (1).to_bytes(8, "little")
    seed_lanes = jnp.asarray(
        np.stack([kj.bytes_to_lanes(s) for s in seeds]).astype(np.uint64)
    )
    parts = [(0, d), (2, seed_lanes), (4, binder)]
    prefix = kj._assemble_segments(parts, 5, 3)
    from janus_tpu.fields.jfield import JF128

    if _FULL:
        length = 70  # small full-round run: interpret mode is minutes/tile
        monkeypatch.setattr(ep, "_TILE_BLOCKS", 8)
        blocks = kj.sample_count_blocks(JF128, length)
        lo, hi = ep.expand_f128(prefix, blocks, length)
        got = np.asarray(lo).astype(object) + (np.asarray(hi).astype(object) << 64)
        for i, s in enumerate(seeds):
            want = XofCtr128(s, d, binder).next_vec(Field128, length)
            assert got[i].tolist() == want
        return

    length = 7 * 130  # 130 blocks -> two 128-block production tiles
    blocks = kj.sample_count_blocks(JF128, length)
    lo, hi = ep.expand_f128(prefix, blocks, length, rounds=_ROUNDS)
    orig = kj.keccak_f1600
    monkeypatch.setattr(kj, "keccak_f1600", lambda s: orig(s, rounds=_ROUNDS))
    stream = kj.ctr_stream_lanes([(0, prefix)], 40, 3, blocks)
    want = kj.sample_field_vec(JF128, stream, length)
    np.testing.assert_array_equal(np.asarray(lo), np.asarray(want[0]))
    np.testing.assert_array_equal(np.asarray(hi), np.asarray(want[1]))


def test_enabled_gating():
    from janus_tpu.fields.jfield import JF64, JF128

    monkey_mode = kp._mode  # not patched here: CPU default is "off"
    if monkey_mode() == "off":
        assert not ep.enabled(JF128, 10_000)
    # Field64 never dispatches (block straddling), regardless of mode
    assert not ep.enabled(JF64, 10_000)


def test_framing_and_offset_with_mock_permutation(monkeypatch):
    """Always-on smoke test of the kernel's framing/reshape/offset logic
    (ADVICE r3): the 24-round permutation is swapped for a cheap
    bijective mock — rot64(lane[(i+3)%25], 32) ^ C — applied identically
    in the u32-pair kernel and the u64 unfused path, so the prefix
    interleave, counter placement (incl. the new block_offset), SHAKE
    padding lanes, in-kernel mod-p reduction, and output transpose are
    all exercised in interpret mode without the 24-round cost."""
    C = 0xA5A5A5A5_5A5A5A5A

    def mock_pairs(a, rounds=24):
        out = []
        for i in range(25):
            lo, hi = a[(i + 3) % 25]
            # rot64 by 32 == swap halves; xor C on the swapped value
            out.append((hi ^ np.uint32(C & 0xFFFFFFFF), lo ^ np.uint32(C >> 32)))
        return out

    def mock_f1600(state):
        return tuple(
            ((state[(i + 3) % 25] << jnp.uint64(32)) | (state[(i + 3) % 25] >> jnp.uint64(32)))
            ^ jnp.uint64(C)
            for i in range(25)
        )

    monkeypatch.setattr(ep, "permute_pairs", mock_pairs)
    monkeypatch.setattr(kj, "keccak_f1600", mock_f1600)
    monkeypatch.setattr(kp, "_mode", lambda: "interpret")
    ep._call.cache_clear()
    try:
        rng = np.random.default_rng(11)
        batch, p = 3, 6
        prefix = rng.integers(0, 1 << 63, size=(batch, p), dtype=np.uint64)
        jf = kj and __import__("janus_tpu.fields.jfield", fromlist=["JF128"]).JF128
        length, blocks = 7 * 130, 130  # >1 tile along the block axis
        fused = ep.expand_f128(prefix, blocks, length)
        unfused_stream = kj.ctr_stream_lanes([(0, jnp.asarray(prefix))], p * 8, batch, blocks)
        unfused = kj.sample_field_vec(jf, unfused_stream, length)
        for a, b in zip(fused, unfused):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

        # block_offset consistency: expanding [off, off+k) blocks equals
        # the same slice of the offset-0 expansion
        off_blocks, k_blocks = 2, 128
        fused_off = ep.expand_f128(prefix, k_blocks, 7 * k_blocks, block_offset=off_blocks)
        for a, b in zip(fused_off, fused):
            np.testing.assert_array_equal(
                np.asarray(a), np.asarray(b)[:, 7 * off_blocks : 7 * (off_blocks + k_blocks)]
            )
    finally:
        ep._call.cache_clear()
