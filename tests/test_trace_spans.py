"""Chrome trace-file span layer (the reference's trace.rs:68-71
ChromeLayer analog): spans stream as Chrome trace events that load in
chrome://tracing / Perfetto next to jax.profiler device traces."""

import json

from janus_tpu import trace as trace_mod
from janus_tpu.trace import TraceConfiguration, install_chrome_trace, span


def _trace_file(base):
    """install_chrome_trace embeds the PID in the filename."""
    import glob
    import os

    root, ext = os.path.splitext(str(base))
    matches = glob.glob(f"{root}.{os.getpid()}{ext or '.json'}")
    assert matches, f"no trace file for {base}"
    return matches[0]


def _read_events(path):
    raw = open(path).read().rstrip()
    if not raw.endswith("]"):
        raw += "{}]"  # crash-tolerant tail
    return [e for e in json.loads(raw) if e]


def test_spans_stream_chrome_events(tmp_path):
    out = tmp_path / "trace.json"
    install_chrome_trace(str(out))
    try:
        with span("outer", kind="test"):
            with span("inner", n=3):
                pass
    finally:
        trace_mod._chrome_writer.close()
        trace_mod._chrome_writer = None

    events = _read_events(_trace_file(out))
    by_name = {e["name"]: e for e in events}
    assert set(by_name) == {"outer", "inner"}
    assert by_name["outer"]["ph"] == "X"
    assert by_name["outer"]["args"] == {"kind": "test"}
    assert by_name["inner"]["args"] == {"n": 3}
    # inner nests inside outer on the timeline
    o, i = by_name["outer"], by_name["inner"]
    assert o["ts"] <= i["ts"] and i["ts"] + i["dur"] <= o["ts"] + o["dur"] + 50


def test_span_is_noop_without_writer():
    assert trace_mod._chrome_writer is None
    with span("ignored"):
        pass  # must not raise or write anywhere


def test_handlers_emit_spans(tmp_path):
    """The DAP router wraps every request in a dap.<route> span."""
    out = tmp_path / "http.json"
    install_chrome_trace(str(out))
    try:
        from janus_tpu.aggregator.http_handlers import DapHttpApp

        class _NoAgg:
            pass

        app = DapHttpApp(_NoAgg())
        status, _, _ = app.handle("OPTIONS", "/hpke_config", {}, {}, b"")
        assert status == 204
    finally:
        trace_mod._chrome_writer.close()
        trace_mod._chrome_writer = None
    events = _read_events(_trace_file(out))
    assert any(e["name"] == "dap.none" or e["name"].startswith("dap.") for e in events)


def test_config_plumbs_chrome_trace_file(tmp_path):
    cfg = TraceConfiguration.from_dict({"chrome_trace_file": str(tmp_path / "t.json")})
    assert cfg.chrome_trace_file == str(tmp_path / "t.json")
