"""Chrome trace-file span layer (the reference's trace.rs:68-71
ChromeLayer analog): spans stream as Chrome trace events that load in
chrome://tracing / Perfetto next to jax.profiler device traces."""

import json

from janus_tpu import trace as trace_mod
from janus_tpu.trace import TraceConfiguration, install_chrome_trace, span


def _trace_file(base):
    """install_chrome_trace embeds the PID in the filename."""
    import glob
    import os

    root, ext = os.path.splitext(str(base))
    matches = glob.glob(f"{root}.{os.getpid()}{ext or '.json'}")
    assert matches, f"no trace file for {base}"
    return matches[0]


def _read_events(path):
    raw = open(path).read().rstrip()
    if not raw.endswith("]"):
        raw += "{}]"  # crash-tolerant tail
    return [e for e in json.loads(raw) if e]


def test_spans_stream_chrome_events(tmp_path):
    out = tmp_path / "trace.json"
    install_chrome_trace(str(out))
    try:
        with span("outer", kind="test"):
            with span("inner", n=3):
                pass
    finally:
        trace_mod._chrome_writer.close()
        trace_mod._chrome_writer = None

    events = _read_events(_trace_file(out))
    by_name = {e["name"]: e for e in events}
    assert set(by_name) == {"outer", "inner"}
    assert by_name["outer"]["ph"] == "X"
    assert by_name["outer"]["args"]["kind"] == "test"
    assert by_name["inner"]["args"]["n"] == 3
    # inner nests inside outer on the timeline AND in the trace tree
    o, i = by_name["outer"], by_name["inner"]
    assert o["ts"] <= i["ts"] and i["ts"] + i["dur"] <= o["ts"] + o["dur"] + 50
    assert i["args"]["trace_id"] == o["args"]["trace_id"]
    assert i["args"]["parent_span_id"] == o["args"]["span_id"]


def test_traceparent_stitches_leader_and_helper(tmp_path):
    """One trace follows a job step across the leader driver and the
    helper's HTTP handler via the traceparent header (reference
    trace.rs:44-90 OTLP propagation analog): the helper's
    dap.aggregate_init span carries the SAME trace id as the leader's
    job.step span, parented under driver.http_init."""
    import dataclasses

    from janus_tpu.aggregator import Aggregator, Config
    from janus_tpu.aggregator.aggregation_job_creator import (
        AggregationJobCreator,
        AggregationJobCreatorConfig,
    )
    from janus_tpu.aggregator.aggregation_job_driver import AggregationJobDriver
    from janus_tpu.aggregator.http_handlers import DapHttpApp, DapServer
    from janus_tpu.aggregator.job_driver import JobDriver, JobDriverConfig
    from janus_tpu.client import Client, ClientParameters
    from janus_tpu.core.auth import AuthenticationToken
    from janus_tpu.core.hpke import generate_hpke_config_and_private_key
    from janus_tpu.core.http_client import HttpClient
    from janus_tpu.core.time_util import MockClock
    from janus_tpu.datastore.store import EphemeralDatastore
    from janus_tpu.messages import Role, Time
    from janus_tpu.task import QueryTypeConfig, TaskBuilder
    from janus_tpu.vdaf.registry import VdafInstance

    out = tmp_path / "stitch.json"
    install_chrome_trace(str(out))
    clock = MockClock(Time(1_600_000_000))
    leader_eph = EphemeralDatastore(clock=clock)
    helper_eph = EphemeralDatastore(clock=clock)
    leader_srv = DapServer(DapHttpApp(Aggregator(leader_eph.datastore, clock, Config()))).start()
    helper_srv = DapServer(DapHttpApp(Aggregator(helper_eph.datastore, clock, Config()))).start()
    try:
        vdaf = VdafInstance.fake()
        collector_kp = generate_hpke_config_and_private_key(config_id=200)
        leader_task = (
            TaskBuilder(QueryTypeConfig.time_interval(), vdaf, Role.LEADER)
            .with_(
                leader_aggregator_endpoint=leader_srv.url,
                helper_aggregator_endpoint=helper_srv.url,
                collector_hpke_config=collector_kp.config,
                aggregator_auth_token=AuthenticationToken.random_bearer(),
                collector_auth_token=AuthenticationToken.random_bearer(),
                min_batch_size=1,
            )
            .build()
        )
        helper_task = dataclasses.replace(
            leader_task,
            role=Role.HELPER,
            hpke_keys=(generate_hpke_config_and_private_key(config_id=1),),
        )
        leader_eph.datastore.run_tx(lambda tx: tx.put_task(leader_task))
        helper_eph.datastore.run_tx(lambda tx: tx.put_task(helper_task))

        http = HttpClient()
        params = ClientParameters(
            leader_task.task_id, leader_srv.url, helper_srv.url, leader_task.time_precision
        )
        client = Client.with_fetched_configs(params, vdaf, http, clock=clock)
        client.upload(1)
        creator = AggregationJobCreator(
            leader_eph.datastore, AggregationJobCreatorConfig(min_aggregation_job_size=1)
        )
        assert creator.run_once() == 1
        driver = AggregationJobDriver(leader_eph.datastore, http)
        jd = JobDriver(
            JobDriverConfig(max_concurrent_job_workers=1),
            driver.acquirer(),
            driver.stepper,
        )
        assert jd.run_once() == 1
    finally:
        leader_srv.stop()
        helper_srv.stop()
        trace_mod._chrome_writer.close()
        trace_mod._chrome_writer = None
        leader_eph.cleanup()
        helper_eph.cleanup()

    events = _read_events(_trace_file(out))
    job_steps = [e for e in events if e["name"] == "job.step"]
    http_inits = [e for e in events if e["name"] == "driver.http_init"]
    helper_inits = [e for e in events if e["name"] == "dap.aggregate_init"]
    assert job_steps and http_inits and helper_inits
    trace_id = job_steps[0]["args"]["trace_id"]
    assert http_inits[0]["args"]["trace_id"] == trace_id
    assert helper_inits[0]["args"]["trace_id"] == trace_id
    # the helper's handler span is parented under the leader's HTTP span
    assert helper_inits[0]["args"]["parent_span_id"] == http_inits[0]["args"]["span_id"]


def test_span_is_noop_without_writer():
    assert trace_mod._chrome_writer is None
    with span("ignored"):
        pass  # must not raise or write anywhere


def test_handlers_emit_spans(tmp_path):
    """The DAP router wraps every request in a dap.<route> span."""
    out = tmp_path / "http.json"
    install_chrome_trace(str(out))
    try:
        from janus_tpu.aggregator.http_handlers import DapHttpApp

        class _NoAgg:
            pass

        app = DapHttpApp(_NoAgg())
        status, _, _ = app.handle("OPTIONS", "/hpke_config", {}, {}, b"")
        assert status == 204
    finally:
        trace_mod._chrome_writer.close()
        trace_mod._chrome_writer = None
    events = _read_events(_trace_file(out))
    assert any(e["name"] == "dap.none" or e["name"].startswith("dap.") for e in events)


def test_config_plumbs_chrome_trace_file(tmp_path):
    cfg = TraceConfiguration.from_dict({"chrome_trace_file": str(tmp_path / "t.json")})
    assert cfg.chrome_trace_file == str(tmp_path / "t.json")
