"""Chrome trace-file span layer (the reference's trace.rs:68-71
ChromeLayer analog): spans stream as Chrome trace events that load in
chrome://tracing / Perfetto next to jax.profiler device traces."""

import json

from janus_tpu import trace as trace_mod
from janus_tpu.trace import TraceConfiguration, install_chrome_trace, span


def _trace_file(base):
    """install_chrome_trace embeds the PID in the filename."""
    import glob
    import os

    root, ext = os.path.splitext(str(base))
    matches = glob.glob(f"{root}.{os.getpid()}{ext or '.json'}")
    assert matches, f"no trace file for {base}"
    return matches[0]


def _read_events(path):
    raw = open(path).read().rstrip()
    if not raw.endswith("]"):
        raw += "{}]"  # crash-tolerant tail
    return [e for e in json.loads(raw) if e]


def test_spans_stream_chrome_events(tmp_path):
    out = tmp_path / "trace.json"
    install_chrome_trace(str(out))
    try:
        with span("outer", kind="test"):
            with span("inner", n=3):
                pass
    finally:
        trace_mod._chrome_writer.close()
        trace_mod._chrome_writer = None

    events = _read_events(_trace_file(out))
    by_name = {e["name"]: e for e in events}
    assert set(by_name) == {"outer", "inner"}
    assert by_name["outer"]["ph"] == "X"
    assert by_name["outer"]["args"]["kind"] == "test"
    assert by_name["inner"]["args"]["n"] == 3
    # inner nests inside outer on the timeline AND in the trace tree
    o, i = by_name["outer"], by_name["inner"]
    assert o["ts"] <= i["ts"] and i["ts"] + i["dur"] <= o["ts"] + o["dur"] + 50
    assert i["args"]["trace_id"] == o["args"]["trace_id"]
    assert i["args"]["parent_span_id"] == o["args"]["span_id"]


def test_traceparent_stitches_leader_and_helper(tmp_path):
    """One trace follows a job step across the leader driver and the
    helper's HTTP handler via the traceparent header (reference
    trace.rs:44-90 OTLP propagation analog): the helper's
    dap.aggregate_init span carries the SAME trace id as the leader's
    job.step span, parented under driver.http_init."""
    import dataclasses

    from janus_tpu.aggregator import Aggregator, Config
    from janus_tpu.aggregator.aggregation_job_creator import (
        AggregationJobCreator,
        AggregationJobCreatorConfig,
    )
    from janus_tpu.aggregator.aggregation_job_driver import AggregationJobDriver
    from janus_tpu.aggregator.http_handlers import DapHttpApp, DapServer
    from janus_tpu.aggregator.job_driver import JobDriver, JobDriverConfig
    from janus_tpu.client import Client, ClientParameters
    from janus_tpu.core.auth import AuthenticationToken
    from janus_tpu.core.hpke import generate_hpke_config_and_private_key
    from janus_tpu.core.http_client import HttpClient
    from janus_tpu.core.time_util import MockClock
    from janus_tpu.datastore.store import EphemeralDatastore
    from janus_tpu.messages import Role, Time
    from janus_tpu.task import QueryTypeConfig, TaskBuilder
    from janus_tpu.vdaf.registry import VdafInstance

    out = tmp_path / "stitch.json"
    install_chrome_trace(str(out))
    clock = MockClock(Time(1_600_000_000))
    leader_eph = EphemeralDatastore(clock=clock)
    helper_eph = EphemeralDatastore(clock=clock)
    leader_srv = DapServer(DapHttpApp(Aggregator(leader_eph.datastore, clock, Config()))).start()
    helper_srv = DapServer(DapHttpApp(Aggregator(helper_eph.datastore, clock, Config()))).start()
    try:
        vdaf = VdafInstance.fake()
        collector_kp = generate_hpke_config_and_private_key(config_id=200)
        leader_task = (
            TaskBuilder(QueryTypeConfig.time_interval(), vdaf, Role.LEADER)
            .with_(
                leader_aggregator_endpoint=leader_srv.url,
                helper_aggregator_endpoint=helper_srv.url,
                collector_hpke_config=collector_kp.config,
                aggregator_auth_token=AuthenticationToken.random_bearer(),
                collector_auth_token=AuthenticationToken.random_bearer(),
                min_batch_size=1,
            )
            .build()
        )
        helper_task = dataclasses.replace(
            leader_task,
            role=Role.HELPER,
            hpke_keys=(generate_hpke_config_and_private_key(config_id=1),),
        )
        leader_eph.datastore.run_tx(lambda tx: tx.put_task(leader_task))
        helper_eph.datastore.run_tx(lambda tx: tx.put_task(helper_task))

        http = HttpClient()
        params = ClientParameters(
            leader_task.task_id, leader_srv.url, helper_srv.url, leader_task.time_precision
        )
        client = Client.with_fetched_configs(params, vdaf, http, clock=clock)
        client.upload(1)
        creator = AggregationJobCreator(
            leader_eph.datastore, AggregationJobCreatorConfig(min_aggregation_job_size=1)
        )
        assert creator.run_once() == 1
        driver = AggregationJobDriver(leader_eph.datastore, http)
        jd = JobDriver(
            JobDriverConfig(max_concurrent_job_workers=1),
            driver.acquirer(),
            driver.stepper,
        )
        assert jd.run_once() == 1
    finally:
        leader_srv.stop()
        helper_srv.stop()
        trace_mod._chrome_writer.close()
        trace_mod._chrome_writer = None
        leader_eph.cleanup()
        helper_eph.cleanup()

    events = _read_events(_trace_file(out))
    job_steps = [e for e in events if e["name"] == "job.step"]
    http_inits = [e for e in events if e["name"] == "driver.http_init"]
    helper_inits = [e for e in events if e["name"] == "dap.aggregate_init"]
    assert job_steps and http_inits and helper_inits
    trace_id = job_steps[0]["args"]["trace_id"]
    assert http_inits[0]["args"]["trace_id"] == trace_id
    assert helper_inits[0]["args"]["trace_id"] == trace_id
    # the helper's handler span is parented under the leader's HTTP span
    assert helper_inits[0]["args"]["parent_span_id"] == http_inits[0]["args"]["span_id"]


def test_span_is_noop_without_writer():
    assert trace_mod._chrome_writer is None
    with span("ignored"):
        pass  # must not raise or write anywhere


def test_handlers_emit_spans(tmp_path):
    """The DAP router wraps every request in a dap.<route> span."""
    out = tmp_path / "http.json"
    install_chrome_trace(str(out))
    try:
        from janus_tpu.aggregator.http_handlers import DapHttpApp

        class _NoAgg:
            pass

        app = DapHttpApp(_NoAgg())
        status, _, _, _ = app.handle("OPTIONS", "/hpke_config", {}, {}, b"")
        assert status == 204
    finally:
        trace_mod._chrome_writer.close()
        trace_mod._chrome_writer = None
    events = _read_events(_trace_file(out))
    assert any(e["name"] == "dap.none" or e["name"].startswith("dap.") for e in events)


def test_config_plumbs_chrome_trace_file(tmp_path):
    cfg = TraceConfiguration.from_dict({"chrome_trace_file": str(tmp_path / "t.json")})
    assert cfg.chrome_trace_file == str(tmp_path / "t.json")


def test_adopt_traceparent_validation():
    """W3C trace-context field validation (ADVICE r3): version must be
    2 hex digits != 'ff', flags 2 hex digits; bad ids/zero ids reject."""
    from janus_tpu.trace import adopt_traceparent, current_traceparent, reset_traceparent

    tid, sid = "0af7651916cd43dd8448eb211c80319c", "b7ad6b7169203331"
    good = f"00-{tid}-{sid}-01"
    bad = [
        f"zz-{tid}-{sid}-01",  # non-hex version
        f"ff-{tid}-{sid}-01",  # version 0xff invalid
        f"0-{tid}-{sid}-01",  # short version
        f"00-{tid}-{sid}-zzzz",  # bad flags
        f"00-{tid}-{sid}-0",  # short flags
        f"00-{'0' * 32}-{sid}-01",  # zero trace id
        f"00-{tid}-{'0' * 16}-01",  # zero span id
        f"00-{tid[:-1]}-{sid}-01",  # short trace id
    ]
    tok = adopt_traceparent(good)
    assert current_traceparent() == good
    reset_traceparent(tok)
    for h in bad:
        tok = adopt_traceparent(h)
        assert current_traceparent() is None, h
        reset_traceparent(tok)


def test_otlp_export_spans_and_metrics():
    """Spans and metrics export as OTLP/HTTP JSON to a collector (the
    reference's opentelemetry-otlp layers, trace.rs:44-90 /
    metrics.rs:53-80): a local sink receives /v1/traces with the span
    tree ids and /v1/metrics with counter + histogram points."""
    import json as _json
    import threading
    from http.server import BaseHTTPRequestHandler, HTTPServer

    from janus_tpu import metrics as m
    from janus_tpu import trace as tr

    received = {}
    done = threading.Event()

    class Sink(BaseHTTPRequestHandler):
        def do_POST(self):
            body = self.rfile.read(int(self.headers.get("Content-Length", 0)))
            received[self.path] = _json.loads(body)
            self.send_response(200)
            self.send_header("Content-Length", "0")
            self.end_headers()
            if "/v1/traces" in received and "/v1/metrics" in received:
                done.set()

        def log_message(self, *a):
            pass

    srv = HTTPServer(("127.0.0.1", 0), Sink)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    try:
        exporter = tr.install_otlp_export(
            f"http://127.0.0.1:{srv.server_port}", flush_interval_s=3600
        )
        with tr.span("otlp.outer", kind="test"):
            with tr.span("otlp.inner", n=3):
                pass
        m.http_request_counter.add(route="otlp_test", status="200")
        m.http_request_duration.observe(0.02, route="otlp_test")
        exporter.flush()
        assert done.wait(5.0)

        spans = received["/v1/traces"]["resourceSpans"][0]["scopeSpans"][0]["spans"]
        by_name = {s["name"]: s for s in spans if s["name"].startswith("otlp.")}
        outer, inner = by_name["otlp.outer"], by_name["otlp.inner"]
        assert inner["traceId"] == outer["traceId"]
        assert inner["parentSpanId"] == outer["spanId"]
        assert int(inner["endTimeUnixNano"]) >= int(inner["startTimeUnixNano"])
        assert {"key": "kind", "value": {"stringValue": "test"}} in outer["attributes"]

        metrics = received["/v1/metrics"]["resourceMetrics"][0]["scopeMetrics"][0]["metrics"]
        by_metric = {mm["name"]: mm for mm in metrics}
        cnt = by_metric["janus_http_requests"]["sum"]
        assert cnt["isMonotonic"] and cnt["aggregationTemporality"] == 2
        assert any(
            {"key": "route", "value": {"stringValue": "otlp_test"}} in p["attributes"]
            for p in cnt["dataPoints"]
        )
        hist = by_metric["janus_http_request_duration_seconds"]["histogram"]
        pt = next(
            p
            for p in hist["dataPoints"]
            if {"key": "route", "value": {"stringValue": "otlp_test"}} in p["attributes"]
        )
        assert len(pt["bucketCounts"]) == len(pt["explicitBounds"]) + 1
        # OTLP buckets are per-bucket (non-cumulative): they sum to count
        assert sum(int(c) for c in pt["bucketCounts"]) == int(pt["count"])
        assert int(pt["count"]) >= 1
    finally:
        tr._otlp_exporter = None
        srv.shutdown()
