"""Chrome trace-file span layer (the reference's trace.rs:68-71
ChromeLayer analog): spans stream as Chrome trace events that load in
chrome://tracing / Perfetto next to jax.profiler device traces."""

import json

from janus_tpu import trace as trace_mod
from janus_tpu.trace import TraceConfiguration, install_chrome_trace, span


def _trace_file(base):
    """install_chrome_trace embeds the PID in the filename."""
    import glob
    import os

    root, ext = os.path.splitext(str(base))
    matches = glob.glob(f"{root}.{os.getpid()}{ext or '.json'}")
    assert matches, f"no trace file for {base}"
    return matches[0]


def _read_events(path):
    raw = open(path).read().rstrip()
    if not raw.endswith("]"):
        raw += "{}]"  # crash-tolerant tail
    return [e for e in json.loads(raw) if e]


def test_spans_stream_chrome_events(tmp_path):
    out = tmp_path / "trace.json"
    install_chrome_trace(str(out))
    try:
        with span("outer", kind="test"):
            with span("inner", n=3):
                pass
    finally:
        trace_mod._chrome_writer.close()
        trace_mod._chrome_writer = None

    events = _read_events(_trace_file(out))
    by_name = {e["name"]: e for e in events}
    assert set(by_name) == {"outer", "inner"}
    assert by_name["outer"]["ph"] == "X"
    assert by_name["outer"]["args"]["kind"] == "test"
    assert by_name["inner"]["args"]["n"] == 3
    # inner nests inside outer on the timeline AND in the trace tree
    o, i = by_name["outer"], by_name["inner"]
    assert o["ts"] <= i["ts"] and i["ts"] + i["dur"] <= o["ts"] + o["dur"] + 50
    assert i["args"]["trace_id"] == o["args"]["trace_id"]
    assert i["args"]["parent_span_id"] == o["args"]["span_id"]


def test_traceparent_stitches_leader_and_helper(tmp_path):
    """One trace follows a job from its creation across the leader
    driver and the helper's HTTP handler: the creator persists its
    span context in the job row (trace_context column), the driver
    adopts it, and the traceparent header carries it to the helper —
    so creator.create_job, driver.http_init and dap.aggregate_init all
    share ONE trace id, with the helper's handler span parented under
    the leader's HTTP span."""
    import dataclasses

    from janus_tpu.aggregator import Aggregator, Config
    from janus_tpu.aggregator.aggregation_job_creator import (
        AggregationJobCreator,
        AggregationJobCreatorConfig,
    )
    from janus_tpu.aggregator.aggregation_job_driver import AggregationJobDriver
    from janus_tpu.aggregator.http_handlers import DapHttpApp, DapServer
    from janus_tpu.aggregator.job_driver import JobDriver, JobDriverConfig
    from janus_tpu.client import Client, ClientParameters
    from janus_tpu.core.auth import AuthenticationToken
    from janus_tpu.core.hpke import generate_hpke_config_and_private_key
    from janus_tpu.core.http_client import HttpClient
    from janus_tpu.core.time_util import MockClock
    from janus_tpu.datastore.store import EphemeralDatastore
    from janus_tpu.messages import Role, Time
    from janus_tpu.task import QueryTypeConfig, TaskBuilder
    from janus_tpu.vdaf.registry import VdafInstance

    out = tmp_path / "stitch.json"
    install_chrome_trace(str(out))
    clock = MockClock(Time(1_600_000_000))
    leader_eph = EphemeralDatastore(clock=clock)
    helper_eph = EphemeralDatastore(clock=clock)
    leader_srv = DapServer(DapHttpApp(Aggregator(leader_eph.datastore, clock, Config()))).start()
    helper_srv = DapServer(DapHttpApp(Aggregator(helper_eph.datastore, clock, Config()))).start()
    try:
        vdaf = VdafInstance.fake()
        collector_kp = generate_hpke_config_and_private_key(config_id=200)
        leader_task = (
            TaskBuilder(QueryTypeConfig.time_interval(), vdaf, Role.LEADER)
            .with_(
                leader_aggregator_endpoint=leader_srv.url,
                helper_aggregator_endpoint=helper_srv.url,
                collector_hpke_config=collector_kp.config,
                aggregator_auth_token=AuthenticationToken.random_bearer(),
                collector_auth_token=AuthenticationToken.random_bearer(),
                min_batch_size=1,
            )
            .build()
        )
        helper_task = dataclasses.replace(
            leader_task,
            role=Role.HELPER,
            hpke_keys=(generate_hpke_config_and_private_key(config_id=1),),
        )
        leader_eph.datastore.run_tx(lambda tx: tx.put_task(leader_task))
        helper_eph.datastore.run_tx(lambda tx: tx.put_task(helper_task))

        http = HttpClient()
        params = ClientParameters(
            leader_task.task_id, leader_srv.url, helper_srv.url, leader_task.time_precision
        )
        client = Client.with_fetched_configs(params, vdaf, http, clock=clock)
        client.upload(1)
        creator = AggregationJobCreator(
            leader_eph.datastore, AggregationJobCreatorConfig(min_aggregation_job_size=1)
        )
        assert creator.run_once() == 1
        job = leader_eph.datastore.run_tx(
            lambda tx: tx.get_aggregation_jobs_for_task(leader_task.task_id)
        )[0]
        # the creator persisted its span context in the job row
        assert job.trace_context is not None
        persisted_trace_id = job.trace_context.split("-")[1]
        driver = AggregationJobDriver(leader_eph.datastore, http)
        jd = JobDriver(
            JobDriverConfig(max_concurrent_job_workers=1),
            driver.acquirer(),
            driver.stepper,
        )
        assert jd.run_once() == 1
    finally:
        leader_srv.stop()
        helper_srv.stop()
        trace_mod._chrome_writer.close()
        trace_mod._chrome_writer = None
        leader_eph.cleanup()
        helper_eph.cleanup()

    events = _read_events(_trace_file(out))
    created = [e for e in events if e["name"] == "creator.create_job"]
    http_inits = [e for e in events if e["name"] == "driver.http_init"]
    helper_inits = [e for e in events if e["name"] == "dap.aggregate_init"]
    assert created and http_inits and helper_inits
    # creator span == the persisted job trace; the driver adopted it
    # from the ROW (not from any in-process state), and the helper got
    # it over the wire — one trace id across three actors
    assert created[0]["args"]["trace_id"] == persisted_trace_id
    assert http_inits[0]["args"]["trace_id"] == persisted_trace_id
    assert helper_inits[0]["args"]["trace_id"] == persisted_trace_id
    # the helper's handler span is parented under the leader's HTTP span
    assert helper_inits[0]["args"]["parent_span_id"] == http_inits[0]["args"]["span_id"]


def test_span_is_noop_without_writer():
    assert trace_mod._chrome_writer is None
    with span("ignored"):
        pass  # must not raise or write anywhere


def test_handlers_emit_spans(tmp_path):
    """The DAP router wraps every request in a dap.<route> span."""
    out = tmp_path / "http.json"
    install_chrome_trace(str(out))
    try:
        from janus_tpu.aggregator.http_handlers import DapHttpApp

        class _NoAgg:
            pass

        app = DapHttpApp(_NoAgg())
        status, _, _, _ = app.handle("OPTIONS", "/hpke_config", {}, {}, b"")
        assert status == 204
    finally:
        trace_mod._chrome_writer.close()
        trace_mod._chrome_writer = None
    events = _read_events(_trace_file(out))
    assert any(e["name"] == "dap.none" or e["name"].startswith("dap.") for e in events)


def test_config_plumbs_chrome_trace_file(tmp_path):
    cfg = TraceConfiguration.from_dict({"chrome_trace_file": str(tmp_path / "t.json")})
    assert cfg.chrome_trace_file == str(tmp_path / "t.json")


def test_adopt_traceparent_validation():
    """W3C trace-context field validation (ADVICE r3): version must be
    2 hex digits != 'ff', flags 2 hex digits; bad ids/zero ids reject."""
    from janus_tpu.trace import adopt_traceparent, current_traceparent, reset_traceparent

    tid, sid = "0af7651916cd43dd8448eb211c80319c", "b7ad6b7169203331"
    good = f"00-{tid}-{sid}-01"
    bad = [
        f"zz-{tid}-{sid}-01",  # non-hex version
        f"ff-{tid}-{sid}-01",  # version 0xff invalid
        f"0-{tid}-{sid}-01",  # short version
        f"00-{tid}-{sid}-zzzz",  # bad flags
        f"00-{tid}-{sid}-0",  # short flags
        f"00-{'0' * 32}-{sid}-01",  # zero trace id
        f"00-{tid}-{'0' * 16}-01",  # zero span id
        f"00-{tid[:-1]}-{sid}-01",  # short trace id
    ]
    tok = adopt_traceparent(good)
    assert current_traceparent() == good
    reset_traceparent(tok)
    for h in bad:
        tok = adopt_traceparent(h)
        assert current_traceparent() is None, h
        reset_traceparent(tok)


def test_otlp_export_spans_and_metrics():
    """Spans and metrics export as OTLP/HTTP JSON to a collector (the
    reference's opentelemetry-otlp layers, trace.rs:44-90 /
    metrics.rs:53-80): a local sink receives /v1/traces with the span
    tree ids and /v1/metrics with counter + histogram points."""
    import json as _json
    import threading
    from http.server import BaseHTTPRequestHandler, HTTPServer

    from janus_tpu import metrics as m
    from janus_tpu import trace as tr

    received = {}
    done = threading.Event()

    class Sink(BaseHTTPRequestHandler):
        def do_POST(self):
            body = self.rfile.read(int(self.headers.get("Content-Length", 0)))
            received[self.path] = _json.loads(body)
            self.send_response(200)
            self.send_header("Content-Length", "0")
            self.end_headers()
            if "/v1/traces" in received and "/v1/metrics" in received:
                done.set()

        def log_message(self, *a):
            pass

    srv = HTTPServer(("127.0.0.1", 0), Sink)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    try:
        exporter = tr.install_otlp_export(
            f"http://127.0.0.1:{srv.server_port}", flush_interval_s=3600
        )
        with tr.span("otlp.outer", kind="test"):
            with tr.span("otlp.inner", n=3):
                pass
        m.http_request_counter.add(route="otlp_test", status="200")
        m.http_request_duration.observe(0.02, route="otlp_test")
        exporter.flush()
        assert done.wait(5.0)

        spans = received["/v1/traces"]["resourceSpans"][0]["scopeSpans"][0]["spans"]
        by_name = {s["name"]: s for s in spans if s["name"].startswith("otlp.")}
        outer, inner = by_name["otlp.outer"], by_name["otlp.inner"]
        assert inner["traceId"] == outer["traceId"]
        assert inner["parentSpanId"] == outer["spanId"]
        assert int(inner["endTimeUnixNano"]) >= int(inner["startTimeUnixNano"])
        assert {"key": "kind", "value": {"stringValue": "test"}} in outer["attributes"]

        metrics = received["/v1/metrics"]["resourceMetrics"][0]["scopeMetrics"][0]["metrics"]
        by_metric = {mm["name"]: mm for mm in metrics}
        cnt = by_metric["janus_http_requests"]["sum"]
        assert cnt["isMonotonic"] and cnt["aggregationTemporality"] == 2
        assert any(
            {"key": "route", "value": {"stringValue": "otlp_test"}} in p["attributes"]
            for p in cnt["dataPoints"]
        )
        hist = by_metric["janus_http_request_duration_seconds"]["histogram"]
        pt = next(
            p
            for p in hist["dataPoints"]
            if {"key": "route", "value": {"stringValue": "otlp_test"}} in p["attributes"]
        )
        assert len(pt["bucketCounts"]) == len(pt["explicitBounds"]) + 1
        # OTLP buckets are per-bucket (non-cumulative): they sum to count
        assert sum(int(c) for c in pt["bucketCounts"]) == int(pt["count"])
        assert int(pt["count"]) >= 1
    finally:
        tr._otlp_exporter = None
        srv.shutdown()


# ---------------------------------------------------------------------------
# Flight recorder (ISSUE 6): always-on ring, digests, slow capture,
# span failure recording, writer buffering, OTLP buffer cap
# ---------------------------------------------------------------------------


def _with_recorder(capacity=16, slow_capacity=4):
    """Swap in a fresh recorder; returns (recorder, restore_fn)."""
    rec = trace_mod.FlightRecorder(capacity=capacity, slow_capacity=slow_capacity)
    saved = trace_mod._flight_recorder
    trace_mod._flight_recorder = rec
    return rec, lambda: setattr(trace_mod, "_flight_recorder", saved)


def test_flight_recorder_ring_and_digests():
    rec, restore = _with_recorder(capacity=16)
    try:
        for i in range(40):
            with span("ring.op", i=i):
                pass
    finally:
        restore()
    snap = rec.snapshot()
    assert snap["recorded_total"] == 40
    # the ring is bounded at capacity (16 is the construction floor)
    assert len(snap["recent"]) == rec.capacity
    # newest last, oldest evicted
    assert snap["recent"][-1]["args"]["i"] == 39
    assert all(e["name"] == "ring.op" for e in snap["recent"])
    assert all("trace_id" in e and "span_id" in e for e in snap["recent"])
    # streaming digest: all 40 observations, sane percentiles
    d = snap["digests"]["ring.op"]
    assert d["count"] == 40 and d["errors"] == 0
    assert 0 < d["p50_s"] <= d["p95_s"] <= d["p99_s"]
    # recent_limit bounds the payload without touching the ring
    assert len(rec.snapshot(recent_limit=3)["recent"]) == 3


def test_flight_recorder_slow_capture_retains_tree():
    rec, restore = _with_recorder(capacity=32)
    rec.set_slow_threshold("slow.root", 0.0)  # capture every root
    try:
        with span("slow.root", kind="t"):
            with span("slow.child"):
                pass
        # a NON-root span never triggers capture, whatever its duration
        with span("outer.holder"):
            with span("slow.root"):
                pass
    finally:
        restore()
    snap = rec.snapshot()
    assert len(snap["slow_traces"]) == 1
    cap = snap["slow_traces"][0]
    assert cap["root"] == "slow.root"
    names = [s["name"] for s in cap["spans"]]
    # the whole tree: child completed first, root last, same trace id
    assert names == ["slow.child", "slow.root"]
    assert {s["trace_id"] for s in cap["spans"]} == {cap["trace_id"]}
    child, root = cap["spans"]
    assert child["parent_span_id"] == root["span_id"]


def test_span_exception_records_error_and_counter():
    from janus_tpu import metrics as m

    rec, restore = _with_recorder()
    before = m.span_errors_total.get(name="err.op")
    try:
        import pytest

        with pytest.raises(ValueError):
            with span("err.op", n=1):
                raise ValueError("boom")
        with span("err.ok"):
            pass
    finally:
        restore()
    snap = rec.snapshot()
    failed = next(e for e in snap["recent"] if e["name"] == "err.op")
    ok = next(e for e in snap["recent"] if e["name"] == "err.ok")
    # the emitted event carries error=<ExcType>; a clean span does not
    assert failed["error"] == "ValueError"
    assert failed["args"]["error"] == "ValueError"
    assert "error" not in ok
    assert m.span_errors_total.get(name="err.op") == before + 1
    assert snap["digests"]["err.op"]["errors"] == 1


def test_span_error_attribute_reaches_chrome_events(tmp_path):
    import pytest

    out = tmp_path / "err.json"
    install_chrome_trace(str(out))
    try:
        with pytest.raises(RuntimeError):
            with span("chrome.err"):
                raise RuntimeError("x")
    finally:
        trace_mod._chrome_writer.close()
        trace_mod._chrome_writer = None
    events = _read_events(_trace_file(out))
    assert any(
        e["name"] == "chrome.err" and e["args"].get("error") == "RuntimeError"
        for e in events
    )


def test_chrome_writer_buffers_until_threshold(tmp_path):
    """The writer no longer write+flushes per event (~45 µs/span in
    PR 3): events buffer until the size/time threshold or close()."""
    from janus_tpu.trace import ChromeTraceWriter

    path = str(tmp_path / "buffered.json")
    w = ChromeTraceWriter(path, flush_interval_s=3600.0)  # size threshold only
    w.event("a", 0.0, 1.0, {})
    w.event("b", 1.0, 1.0, {})
    # nothing flushed yet — no event has reached the disk
    assert '"name"' not in open(path).read()
    # crossing the size threshold flushes the buffer
    w.FLUSH_BYTES = 1
    w.event("c", 2.0, 1.0, {})
    names = [e["name"] for e in _read_events(path)]
    assert names == ["a", "b", "c"]
    # close() flushes the tail and closes the array
    w.FLUSH_BYTES = ChromeTraceWriter.FLUSH_BYTES
    w.event("d", 3.0, 1.0, {})
    w.close()
    raw = open(path).read().rstrip()
    assert raw.endswith("]")
    assert [e["name"] for e in json.loads(raw) if e] == ["a", "b", "c", "d"]


def test_otlp_buffer_caps_drop_oldest():
    from janus_tpu import metrics as m
    from janus_tpu.trace import OtlpExporter

    before = m.otlp_spans_dropped_total.total()
    # unroutable endpoint + huge interval: no flush during the test
    ex = OtlpExporter("http://127.0.0.1:9", flush_interval_s=3600.0)
    try:
        ex.MAX_BUFFERED_SPANS = 5
        for i in range(9):
            ex.record_span(f"s{i}", 0, 1, 1, i + 1, None, {})
        assert len(ex._spans) == 5
        # oldest dropped, newest retained
        assert [d["name"] for d in ex._spans] == ["s4", "s5", "s6", "s7", "s8"]
        assert m.otlp_spans_dropped_total.total() - before == 4
        # a hung collector can't stall the flush loop past its interval
        assert ex._post_timeout <= 5.0
    finally:
        ex._stop.set()
        ex._spans.clear()


def test_json_formatter_carries_trace_ids():
    import logging

    from janus_tpu.trace import JsonFormatter, adopt_traceparent, reset_traceparent

    fmt = JsonFormatter()
    record = logging.LogRecord("t", logging.INFO, __file__, 1, "hello", (), None)
    # no active context: no trace fields
    doc = json.loads(fmt.format(record))
    assert "trace_id" not in doc and "span_id" not in doc
    tid, sid = "0af7651916cd43dd8448eb211c80319c", "b7ad6b7169203331"
    tok = adopt_traceparent(f"00-{tid}-{sid}-01")
    try:
        doc = json.loads(fmt.format(record))
        assert doc["trace_id"] == tid and doc["span_id"] == sid
    finally:
        reset_traceparent(tok)
    # inside a span() the formatter sees that span's ids
    with span("log.ctx"):
        doc = json.loads(fmt.format(record))
        assert len(doc["trace_id"]) == 32 and len(doc["span_id"]) == 16


def test_use_traceparent_adopts_and_restores():
    from janus_tpu.trace import current_traceparent, use_traceparent

    tid = "0af7651916cd43dd8448eb211c80319c"
    header = f"00-{tid}-b7ad6b7169203331-01"
    assert current_traceparent() is None
    with use_traceparent(header):
        assert current_traceparent() == header
        with span("adopted.child"):
            assert tid in current_traceparent()
    assert current_traceparent() is None
    # falsy header: ambient context preserved (no clearing)
    with span("ambient"):
        before = current_traceparent()
        with use_traceparent(None):
            assert current_traceparent() == before


def test_chrome_writer_idle_tail_flushes_without_new_events(tmp_path):
    """A burst below the size threshold followed by silence still
    reaches disk within the flush interval (daemon flusher) — no new
    event required."""
    import time as _time

    from janus_tpu.trace import ChromeTraceWriter

    path = str(tmp_path / "idle.json")
    w = ChromeTraceWriter(path, flush_interval_s=0.05)
    try:
        w.event("lone", 0.0, 1.0, {})
        deadline = _time.monotonic() + 5.0
        seen = False
        while _time.monotonic() < deadline and not seen:
            raw = open(path).read()
            seen = '"lone"' in raw
            if not seen:
                _time.sleep(0.02)
        assert seen, "idle buffer never flushed"
    finally:
        w.close()


def test_slow_capture_fires_for_adopted_context_roots():
    """A span whose parent is REMOTE (adopted from a persisted
    trace_context / traceparent header) is this process's local root:
    slow capture must fire for it — otherwise a driver step's work
    spans (all children of the persisted creator span) could never be
    captured anywhere."""
    from janus_tpu.trace import use_traceparent

    rec, restore = _with_recorder(capacity=32)
    rec.set_slow_threshold("adopted.work", 0.0)
    header = "00-0af7651916cd43dd8448eb211c80319c-b7ad6b7169203331-01"
    try:
        with use_traceparent(header):
            with span("adopted.work"):
                with span("adopted.child"):
                    pass
    finally:
        restore()
    snap = rec.snapshot()
    # only the adopted-parent root fired (its local child did not)
    assert [c["root"] for c in snap["slow_traces"]] == ["adopted.work"]
    cap = snap["slow_traces"][0]
    assert cap["trace_id"] == "0af7651916cd43dd8448eb211c80319c"
    assert [s["name"] for s in cap["spans"]] == ["adopted.child", "adopted.work"]


def test_trace_id_of_validates():
    from janus_tpu.trace import trace_id_of

    tid = "0af7651916cd43dd8448eb211c80319c"
    assert trace_id_of(f"00-{tid}-b7ad6b7169203331-01") == tid
    assert trace_id_of(None) is None
    assert trace_id_of("garbage-with-three-dashes") is None
    assert trace_id_of(f"ff-{tid}-b7ad6b7169203331-01") is None
