"""Differential tests: device draft engine (Prio3BatchedDraft) vs the
host draft oracle (reference.Prio3(mode="draft")) — byte-for-byte on
every XOF-derived quantity and end-to-end on the two-party prepare."""

import hashlib

import numpy as np
import pytest

from janus_tpu.vdaf.draft_jax import (
    _REJECT_WINDOW,
    Prio3BatchedDraft,
    _assemble_bytes,
    _candidate_count,
    _reject_sample,
    _sponge_stream,
    _stream_blocks_for,
)
from janus_tpu.vdaf.registry import VdafInstance, circuit_for, prio3_host
from janus_tpu.vdaf.xof import XofSponge128


def _shake(msg: bytes, n: int) -> bytes:
    return hashlib.shake_128(msg).digest(n)


def lanes_to_bytes_row(lanes, row=0) -> bytes:
    return np.asarray(lanes, dtype="<u8")[row].tobytes()


class TestAssembly:
    def test_static_only_matches_shake(self):
        msg = b"hello world, odd len!"  # 21 bytes, not lane aligned
        out = _sponge_stream([(0, msg)], len(msg), batch=3, out_blocks=2)
        want = _shake(msg, 2 * 168)
        got = lanes_to_bytes_row(out, 1)
        assert got == want

    @pytest.mark.parametrize("offset", [0, 1, 3, 7, 9, 25, 26, 42])
    def test_dynamic_segment_at_any_byte_offset(self, offset):
        rng = np.random.default_rng(offset)
        dyn = rng.integers(0, 2**63, size=(2, 4), dtype=np.uint64)  # 32 bytes
        head = bytes(range(1, offset + 1))
        msg_len = offset + 32
        out = _sponge_stream([(0, head), (offset, dyn)], msg_len, batch=2, out_blocks=1)
        for row in range(2):
            msg = head + dyn[row].astype("<u8").tobytes()
            assert lanes_to_bytes_row(out, row) == _shake(msg, 168)

    def test_multi_block_absorb(self):
        rng = np.random.default_rng(5)
        dyn = rng.integers(0, 2**63, size=(1, 70), dtype=np.uint64)  # 560 bytes
        head = b"\x08" + b"d" * 8 + b"s" * 16  # 25-byte draft-style prefix
        msg_len = 25 + 560
        out = _sponge_stream([(0, head), (25, dyn)], msg_len, batch=1, out_blocks=3)
        msg = head + dyn[0].astype("<u8").tobytes()
        assert lanes_to_bytes_row(out, 0) == _shake(msg, 3 * 168)


class TestRejectionSampling:
    @pytest.mark.parametrize("kind", ["count", "sum"])
    def test_matches_host_next_vec(self, kind):
        inst = {"count": VdafInstance.count(), "sum": VdafInstance.sum(bits=16)}[kind]
        circ = circuit_for(inst)
        from janus_tpu.vdaf.engine import jf_for

        jf = jf_for(circ)
        F = circ.FIELD
        length = max(circ.query_rand_len, 5)
        batch = 4
        rng = np.random.default_rng(kind == "sum")
        seeds = [rng.bytes(16) for _ in range(batch)]
        dst_ = b"\x07\x00testDST"[:8]
        # device: stream + reject-sample
        import jax.numpy as jnp

        blocks = _stream_blocks_for(jf, length)
        seed_lanes = jnp.asarray(
            np.stack([np.frombuffer(s, dtype="<u8") for s in seeds]).astype(np.uint64)
        )
        stream = _sponge_stream(
            [(0, bytes([8]) + dst_), (9, seed_lanes)], 25, batch, blocks
        )
        got = _reject_sample(jf, stream, length)
        # host oracle
        for i, seed in enumerate(seeds):
            want = XofSponge128(seed, dst_, b"").next_vec(F, length)
            if jf.LIMBS == 1:
                have = [int(x) for x in np.asarray(got[0])[i][:length]]
            else:
                lo = np.asarray(got[0])[i][:length]
                hi = np.asarray(got[1])[i][:length]
                have = [int(a) | (int(b) << 64) for a, b in zip(lo, hi)]
            assert have == want

    def test_crafted_rejects_compact_in_order(self):
        """Real rejects are ~2^-32 events, so craft a candidate stream
        with rejects at known positions and check the window select
        reproduces the draft's skip-and-continue semantics exactly."""
        import jax.numpy as jnp

        from janus_tpu.fields.jfield import JF64

        length = 40
        C = _candidate_count(JF64, length)
        p = JF64.MODULUS
        rng = np.random.default_rng(9)
        cand = rng.integers(0, p, size=(3, C), dtype=np.uint64)
        # report 0: no rejects; report 1: scattered rejects (within the
        # window); report 2: window+1 rejects -> zero tail, never
        # garbage
        cand[1, [0, 7, 7 + 1, 25]] = np.uint64(0xFFFFFFFFFFFFFFFF)
        for k in range(_REJECT_WINDOW + 1):
            cand[2, 2 * k] = np.uint64(0xFFFFFFFFFFFFFFFF)
        # lanes layout: candidates are contiguous 8-byte chunks
        pad_lanes = -(-C // 21) * 21
        stream = np.zeros((3, pad_lanes), dtype=np.uint64)
        stream[:, :C] = cand
        got = np.asarray(_reject_sample(JF64, jnp.asarray(stream), length)[0])

        for r in range(3):
            accepted = [int(c) for c in cand[r] if int(c) < p]
            rejects = sum(1 for c in cand[r] if int(c) >= p)
            want = accepted[:length]
            if rejects > _REJECT_WINDOW:
                # elements whose filling candidate sits beyond the
                # window degrade to zero (explicit FLP-reject path)
                have = [int(x) for x in got[r]]
                assert have != want  # tail degraded...
                assert all(
                    h == w or h == 0 for h, w in zip(have, want)
                )  # ...to zero, never to a wrong value
            else:
                assert [int(x) for x in got[r]] == want


def _lane(v):
    import jax.numpy as jnp

    return jnp.asarray(v, dtype=jnp.uint64)


@pytest.mark.parametrize("kind", ["count", "sum"])
def test_two_party_prepare_differential(kind):
    """Device draft engine vs host draft oracle, end to end: shard on
    host, prepare on device, compare every wire quantity + out shares."""
    inst = {
        "count": VdafInstance("count", xof_mode="draft"),
        "sum": VdafInstance("sum", bits=8, xof_mode="draft"),
    }[kind]
    circ = circuit_for(inst)
    host = prio3_host(inst)
    p3 = Prio3BatchedDraft(circ)
    assert Prio3BatchedDraft.supports_circuit(circ)
    F = circ.FIELD
    verify_key = bytes(range(16))
    batch = 3
    rng = np.random.default_rng(42)
    meas = [int(rng.integers(0, 2)) if kind == "count" else int(rng.integers(0, 200)) for _ in range(batch)]

    nonces, pubs, leaders, helpers = [], [], [], []
    for i, m in enumerate(meas):
        nonce = rng.bytes(16)
        public, (ls, hs) = host.shard(m, nonce)
        nonces.append(nonce)
        pubs.append(public)
        leaders.append(ls)
        helpers.append(hs)

    nonce_lanes = _lane(np.stack([np.frombuffer(n, dtype="<u8") for n in nonces]).astype(np.uint64))
    if host.uses_joint_rand:
        public_parts = _lane(
            np.stack(
                [
                    np.stack([np.frombuffer(p, dtype="<u8") for p in pub]).astype(np.uint64)
                    for pub in pubs
                ]
            )
        )
        blind0 = _lane(
            np.stack([np.frombuffer(ls.joint_rand_blind, dtype="<u8") for ls in leaders]).astype(np.uint64)
        )
        blind1 = _lane(
            np.stack([np.frombuffer(hs.joint_rand_blind, dtype="<u8") for hs in helpers]).astype(np.uint64)
        )
    else:
        public_parts = blind0 = blind1 = None
    helper_seed = _lane(
        np.stack([np.frombuffer(hs.seed, dtype="<u8") for hs in helpers]).astype(np.uint64)
    )

    def ints_to_value(rows):
        arrs = tuple(np.zeros((batch, len(rows[0])), dtype=np.uint64) for _ in range(p3.jf.LIMBS))
        for i, r in enumerate(rows):
            for j, v in enumerate(r):
                arrs[0][i, j] = v & 0xFFFFFFFFFFFFFFFF
                if p3.jf.LIMBS == 2:
                    arrs[1][i, j] = v >> 64
        return tuple(_lane(a) for a in arrs)

    def value_to_ints(val, i):
        if p3.jf.LIMBS == 1:
            return [int(x) for x in np.asarray(val[0])[i]]
        lo, hi = np.asarray(val[0])[i], np.asarray(val[1])[i]
        return [int(a) | (int(b) << 64) for a, b in zip(lo, hi)]

    meas_v = ints_to_value([ls.measurement_share for ls in leaders])
    proof_v = ints_to_value([ls.proof_share for ls in leaders])

    out0, seed0, ver0, part0 = p3.prepare_init_leader(
        verify_key, nonce_lanes, public_parts, meas_v, proof_v, blind0
    )
    out1, seed1, ver1, part1 = p3.prepare_init_helper(
        verify_key, nonce_lanes, public_parts, helper_seed, blind1
    )
    mask, prep_msg = p3.prep_shares_to_prep(ver0, ver1, part0, part1)
    mask = p3.prepare_finish(seed0, prep_msg, mask)
    mask = p3.prepare_finish(seed1, prep_msg, mask)
    assert all(np.asarray(mask)), "all honest reports must verify on device"

    for i in range(batch):
        st0, ps0 = host.prepare_init(verify_key, 0, nonces[i], pubs[i], leaders[i])
        st1, ps1 = host.prepare_init(verify_key, 1, nonces[i], pubs[i], helpers[i])
        msg = host.prepare_shares_to_prep([ps0, ps1])
        o0 = host.prepare_next(st0, msg)
        o1 = host.prepare_next(st1, msg)
        assert value_to_ints(ver0, i) == ps0.verifier_share
        assert value_to_ints(ver1, i) == ps1.verifier_share
        if host.uses_joint_rand:
            assert lanes_to_bytes_row(part0, i) == ps0.joint_rand_part
            assert lanes_to_bytes_row(part1, i) == ps1.joint_rand_part
            assert lanes_to_bytes_row(prep_msg, i) == msg
        assert value_to_ints(out0, i) == o0
        assert value_to_ints(out1, i) == o1


def test_draft_streamed_query_matches_unstreamed(monkeypatch):
    """Draft engine at streaming sizes: the sliced-source streamed query
    must be element-identical to the whole-share path (VERDICT r3
    item 4 — spec-conformant tasks at north-star lengths no longer fall
    back to the host loop; the geometry here is small, the activation
    threshold is monkeypatched down)."""
    import numpy as np

    from janus_tpu.vdaf import engine
    from janus_tpu.vdaf.draft_jax import Prio3BatchedDraft
    from janus_tpu.vdaf.reference import SumVec

    circ = SumVec(40, 16, chunk_length=5)
    p3 = Prio3BatchedDraft(circ)
    assert p3._can_stream and not p3._stream_expand_offsets
    rng = np.random.default_rng(77)
    batch = 2
    vk = bytes(range(16))
    nonce = rng.integers(0, 1 << 63, size=(batch, 2), dtype=np.uint64)
    seeds = rng.integers(0, 1 << 63, size=(batch, 2), dtype=np.uint64)
    blind = rng.integers(0, 1 << 63, size=(batch, 2), dtype=np.uint64)
    parts = np.stack(
        [rng.integers(0, 1 << 63, size=(batch, 2), dtype=np.uint64) for _ in range(2)],
        axis=1,
    )

    monkeypatch.setattr(engine, "STREAM_MIN_INPUT_LEN", 1)
    out_s = p3.prepare_init_helper(vk, nonce, parts, seeds, blind)
    monkeypatch.setattr(engine, "STREAM_MIN_INPUT_LEN", 1 << 60)
    out_u = p3.prepare_init_helper(vk, nonce, parts, seeds, blind)
    for s, u in zip(out_s, out_u):
        if s is None:
            assert u is None
            continue
        if isinstance(s, tuple):
            for a, b in zip(s, u):
                np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        else:
            np.testing.assert_array_equal(np.asarray(s), np.asarray(u))


def test_draft_device_range_covers_north_star():
    """The device draft engine covers the north-star length: round 5
    showed the r4 'superlinear knee' was a flat-scan pathology (nested
    scans are linear, 91 us/block at 152k blocks), so the cap now
    admits SumVec len=100k (152,382 blocks) with margin; truly huge
    streams still fall back to the host loop (draft_jax
    MAX_STREAM_BLOCKS docstring, measured 2026-08-01)."""
    from janus_tpu.vdaf.draft_jax import Prio3BatchedDraft
    from janus_tpu.vdaf.reference import SumVec

    assert Prio3BatchedDraft.supports_circuit(SumVec(14_000, 16))
    assert Prio3BatchedDraft.supports_circuit(SumVec(100_000, 16))
    assert not Prio3BatchedDraft.supports_circuit(SumVec(120_000, 16))
