"""Pipelined (chunked) leader init: outputs must be identical to the
single-dispatch path, and the chunked out shares must aggregate
correctly (VERDICT r3 item 8 — overlap staging with device compute)."""

import numpy as np

from janus_tpu.aggregator.engine_cache import (
    DeviceRowsChunks,
    EngineCache,
    bucket_size,
)
from janus_tpu.vdaf.registry import VdafInstance


def test_pipelined_leader_init_matches_single_dispatch(monkeypatch):
    inst = VdafInstance.sum_vec(length=4, bits=4)
    eng = EngineCache(inst, b"\x03" * 16)
    eng.mesh = None  # pipelining is the single-device serving shape
    monkeypatch.setattr(EngineCache, "PIPELINE_CHUNK", 2)

    circ = eng.p3.circ
    rng = np.random.default_rng(21)
    n = 5  # 3 chunks: 2 + 2 + 1, exercising the remainder bucket
    nonce = rng.integers(0, 1 << 63, size=(n, 2), dtype=np.uint64)
    parts = rng.integers(0, 1 << 63, size=(n, 2, 2), dtype=np.uint64)
    meas = tuple(
        rng.integers(0, 1 << 62, size=(n, circ.input_len), dtype=np.uint64) for _ in range(2)
    )
    proof = tuple(
        rng.integers(0, 1 << 62, size=(n, circ.proof_len), dtype=np.uint64) for _ in range(2)
    )
    blind0 = rng.integers(0, 1 << 63, size=(n, 2), dtype=np.uint64)

    out_p, seed_p, ver_p, part_p = eng.leader_init(nonce, parts, meas, proof, blind0)
    assert isinstance(out_p, DeviceRowsChunks)
    assert [c.n for c in out_p.chunks] == [2, 2, 1]
    assert out_p.n == n

    monkeypatch.setattr(EngineCache, "PIPELINE_CHUNK", 1 << 20)  # force single path
    out_s, seed_s, ver_s, part_s = eng.leader_init(nonce, parts, meas, proof, blind0)

    for a, b in zip(out_p.to_numpy(), out_s.to_numpy()):
        np.testing.assert_array_equal(a, b)
    np.testing.assert_array_equal(seed_p, np.asarray(seed_s)[:n])
    for a, b in zip(ver_p, ver_s):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b)[:n])
    np.testing.assert_array_equal(part_p, np.asarray(part_s)[:n])

    # chunked aggregate == single aggregate under the same mask
    mask = np.array([True, False, True, True, False])
    agg_p = eng.aggregate(out_p, mask)
    agg_s = eng.aggregate(out_s, mask)
    assert agg_p == agg_s
