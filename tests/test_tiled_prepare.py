"""Tiled (fixed-size length-tile) FLP prepare: memory bound + identity.

The r6 tentpole makes device prepare memory-BOUNDED instead of
memory-proportional: the streamed query's scan tile is clamped to
STREAM_TILE_ELEMS, so peak live bytes scale with batch x TILE rather
than batch x input_len. These tests prove:

- the tile geometry is length-independent past the clamp (the O(TILE)
  claim, host math only);
- the jit-compiled memory analysis of the helper prepare at the
  north-star config (SumVec len=100k, batch 256) fits the 15.75 GB
  v5e HBM budget — the configuration round 5 measured at 20.68 GB
  with batch 128 under the proportional plan;
- forcing tiny multi-step tiles produces BIT-IDENTICAL prepare outputs
  to the untiled whole-share engine across Count/Sum/SumVec/Histogram
  (Count/Sum take the untiled path by design — the equality asserts
  the dispatch as well as the math).
"""

import numpy as np
import pytest

from janus_tpu.vdaf import engine
from janus_tpu.vdaf.prio3_jax import Prio3Batched
from janus_tpu.vdaf.reference import Count, Histogram, Sum, SumVec
from janus_tpu.vdaf.registry import VdafInstance

VK = bytes(range(16))

V5E_HBM_BYTES = int(15.75 * (1 << 30))


def test_tile_size_length_independent():
    """Past the clamp the tile stops growing with input_len: the scan's
    per-step working set is O(batch x TILE) by construction. Pinned to
    an alignment-friendly chunk (2520 = 56*45) — with the sqrt-default
    chunk the tile floors at the lcm(7,bits)-alignment quantum instead
    (asserted separately below)."""
    plans = {
        n: engine.stream_plan(engine.batched_circuit(SumVec(n, 16, chunk_length=2520)))
        for n in (100_000, 200_000, 400_000)
    }
    groups = {n: p.group for n, p in plans.items()}
    assert all(p is not None for p in plans.values())
    # identical tile at every length: 4x the length = 4x the steps,
    # NOT 4x the per-step working set (the proportional r5 plan)
    assert groups[100_000] == groups[200_000] == groups[400_000], groups
    assert groups[100_000] <= engine.STREAM_TILE_ELEMS
    assert plans[400_000].n_steps > 2 * plans[100_000].n_steps


def test_tile_bounded_for_default_chunks():
    """Default (sqrt-heuristic) chunks may be coprime with the
    lcm(7,bits) alignment, flooring the tile at one alignment quantum
    a*ch — bounded by max(clamp, quantum) + rounding, never
    input_len-proportional."""
    for n in (100_000, 400_000):
        circ = SumVec(n, 16)
        plan = engine.stream_plan(engine.batched_circuit(circ))
        ch = circ.chunk_length
        import math

        align = math.lcm(7, 16)
        a = align // math.gcd(align, ch)
        bound = max(engine.STREAM_TILE_ELEMS + a * ch // 2, a * ch)
        assert plan.group <= bound, (n, plan.group, bound)
        assert plan.group < circ.input_len  # strictly sub-proportional


def test_short_streams_keep_target_step_plan():
    """Below the clamp the r5 8-step optimum is unchanged."""
    bc = engine.batched_circuit(SumVec(10_000, 16))
    plan = engine.stream_plan(bc)
    assert plan is not None
    assert plan.n_steps <= engine._STREAM_TARGET_STEPS + 1


def test_len100k_batch256_fits_v5e_hbm():
    """North-star acceptance: jit-compiled memory analysis of the
    helper prepare (share expansion + tiled query + truncate) at
    SumVec len=100k batch=256 stays under the 15.75 GB v5e budget."""
    import jax
    import jax.numpy as jnp

    from janus_tpu.parallel.api import helper_init_step

    inst = VdafInstance.sum_vec(length=100_000, bits=16)
    step = helper_init_step(inst, VK)
    B = 256
    u64 = jnp.uint64
    args = (
        jax.ShapeDtypeStruct((B, 2), u64),  # nonce lanes
        jax.ShapeDtypeStruct((B, 2, 2), u64),  # public parts
        jax.ShapeDtypeStruct((B, 2), u64),  # helper seed
        jax.ShapeDtypeStruct((B, 2), u64),  # blind
    )
    compiled = jax.jit(step).lower(*args).compile()
    ma = compiled.memory_analysis()
    total = (
        ma.argument_size_in_bytes
        + ma.output_size_in_bytes
        + ma.temp_size_in_bytes
    )
    assert total < V5E_HBM_BYTES, f"{total / 2**30:.2f} GiB exceeds the v5e budget"
    # and the feasibility model agrees this batch is admissible
    from janus_tpu.vdaf.feasibility import feasible_bucket

    plan = engine.stream_plan(engine.batched_circuit(SumVec(100_000, 16)))
    assert feasible_bucket(
        SumVec(100_000, 16), V5E_HBM_BYTES, tile_elems=plan.group
    ) >= 256


def _rand_lanes(rng, batch, n):
    return rng.integers(0, 1 << 63, size=(batch, n), dtype=np.uint64)


TILED_CIRCUITS = [
    Count(),
    Sum(bits=8),
    SumVec(40, 16, chunk_length=5),
    Histogram(200, chunk_length=9),
]


@pytest.mark.parametrize(
    "circ", TILED_CIRCUITS, ids=["count", "sum", "sumvec", "histogram"]
)
def test_tiled_prepare_bit_identical(circ, monkeypatch):
    """Forced tiny tiles (multi-step scan) == untiled whole-share
    prepare, bit for bit, for both aggregators. Count/Sum never tile
    (stream_plan returns None) — the equality also locks that in."""
    p3 = Prio3Batched(circ)
    rng = np.random.default_rng(17)
    batch = 3
    nonce = _rand_lanes(rng, batch, 2)
    helper_seed = _rand_lanes(rng, batch, 2)
    blind = _rand_lanes(rng, batch, 2) if p3.uses_joint_rand else None
    public_parts = (
        np.stack([_rand_lanes(rng, batch, 2), _rand_lanes(rng, batch, 2)], axis=1)
        if p3.uses_joint_rand
        else None
    )
    jf = p3.jf
    meas = tuple(
        rng.integers(0, 1 << 62, size=(batch, circ.input_len), dtype=np.uint64)
        for _ in range(jf.LIMBS)
    )
    proof = tuple(
        rng.integers(0, 1 << 62, size=(batch, circ.proof_len), dtype=np.uint64)
        for _ in range(jf.LIMBS)
    )

    def both():
        h = p3.prepare_init_helper(VK, nonce, public_parts, helper_seed, blind)
        l = p3.prepare_init_leader(VK, nonce, public_parts, meas, proof, blind)
        return h, l

    # tiled: activation threshold 1, tile clamped to a few gadget-call
    # alignment quanta so every circuit that CAN tile takes >1 step
    ch = getattr(circ, "chunk_length", 0)
    monkeypatch.setattr(engine, "STREAM_MIN_INPUT_LEN", 1)
    monkeypatch.setattr(engine, "STREAM_TILE_ELEMS", 8 * ch if ch else 8)
    plan = engine.stream_plan(p3.bc)
    if type(circ) in (SumVec, Histogram):
        assert plan is not None and plan.n_steps > 1, "tiling must engage"
    else:
        assert plan is None
    tiled_h, tiled_l = both()

    # untiled reference engine
    monkeypatch.setattr(engine, "STREAM_MIN_INPUT_LEN", 1 << 60)
    flat_h, flat_l = both()

    for tiled, flat in ((tiled_h, flat_h), (tiled_l, flat_l)):
        for t, f in zip(tiled, flat):
            if t is None:
                assert f is None
                continue
            if isinstance(t, tuple):
                for a, b in zip(t, f):
                    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
            else:
                np.testing.assert_array_equal(np.asarray(t), np.asarray(f))


def test_tiled_two_party_step_end_to_end(monkeypatch):
    """Shard + tiled prepare + decide + aggregate: every report
    accepted, aggregate equals the true sum (SumVec on the multi-step
    tile plan)."""
    import jax

    from janus_tpu.parallel.api import two_party_step
    from janus_tpu.vdaf.registry import prio3_batched
    from janus_tpu.vdaf.testing import make_report_batch, random_measurements

    monkeypatch.setattr(engine, "STREAM_MIN_INPUT_LEN", 1)
    monkeypatch.setattr(engine, "STREAM_TILE_ELEMS", 250)
    inst = VdafInstance.sum_vec(length=21, bits=4)
    rng = np.random.default_rng(7)
    meas = random_measurements(inst, 4, rng)
    step_args, _ = make_report_batch(inst, meas, seed=3)
    agg0, agg1, count = jax.jit(two_party_step(inst, VK))(*step_args)
    assert int(count) == 4
    p3 = prio3_batched(inst)
    vals = p3.jf.to_ints(p3.merge_agg_shares(agg0, agg1))
    np.testing.assert_array_equal(
        np.asarray([int(v) for v in vals]), np.asarray(meas).sum(axis=0)
    )


def test_feasibility_model_basics(monkeypatch):
    from janus_tpu.vdaf import feasibility as fz

    circ = SumVec(100_000, 16)
    plan = engine.stream_plan(engine.batched_circuit(circ))
    # unbounded when the budget is unknown
    assert fz.feasible_bucket(circ, None, tile_elems=plan.group) is None
    # power-of-two, monotone in budget
    b1 = fz.feasible_bucket(circ, V5E_HBM_BYTES, tile_elems=plan.group)
    b2 = fz.feasible_bucket(circ, 2 * V5E_HBM_BYTES, tile_elems=plan.group)
    assert b1 & (b1 - 1) == 0 and b2 >= b1
    # tiled rows dominate untiled rows at long lengths
    assert fz.prepare_row_bytes(circ, tile_elems=plan.group) < fz.prepare_row_bytes(circ)
    # draft pays the materialized share regardless of tiling
    assert fz.prepare_row_bytes(circ, tile_elems=plan.group, draft=True) > fz.prepare_row_bytes(
        circ, tile_elems=plan.group
    )
    # env override wins
    monkeypatch.setenv("JANUS_HBM_BUDGET", "12345")
    assert fz.device_memory_budget() == 12345


def test_draft_device_gate_consults_budget():
    """vdaf.draft_jax device support is gated on the feasibility bound,
    not just MAX_STREAM_BLOCKS (r6 tentpole)."""
    from janus_tpu.vdaf.draft_jax import Prio3BatchedDraft

    circ = Sum(bits=8)
    # stream-length-eligible circuit: budget-unknown keeps legacy yes
    assert Prio3BatchedDraft.supports_circuit(circ, budget_bytes=None)
    # a budget too small for MIN_DEVICE_ROWS materialized shares: no
    assert not Prio3BatchedDraft.supports_circuit(circ, budget_bytes=1024)
    # ample budget: yes
    assert Prio3BatchedDraft.supports_circuit(circ, budget_bytes=1 << 34)
