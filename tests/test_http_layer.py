"""HTTP-layer conformance: CORS preflights + DAP media-type enforcement
(reference aggregator/src/aggregator/http_handlers.rs:236-259 CORS
wrappers, :512-551 media-type extraction)."""

import urllib.error
import urllib.request

from janus_tpu.aggregator.http_handlers import DapHttpApp, DapServer


class _NoAgg:
    """Routes under test never reach the aggregator."""

    def __getattr__(self, name):  # pragma: no cover - fail loudly
        raise AssertionError(f"aggregator reached via {name}")


def test_options_preflight_routes():
    app = DapHttpApp(_NoAgg())
    status, _, _, _ = app.handle("OPTIONS", "/hpke_config", {}, {}, b"")
    assert status == 204
    status, _, _, _ = app.handle("OPTIONS", "/tasks/x/reports", {}, {}, b"")
    assert status == 204
    status, _, _, _ = app.handle("OPTIONS", "/tasks/x/collection_jobs/y", {}, {}, b"")
    assert status == 204
    # non-CORS route: aggregation jobs are aggregator-to-aggregator
    status, _, _, _ = app.handle("OPTIONS", "/tasks/x/aggregation_jobs/y", {}, {}, b"")
    assert status == 404


def test_wrong_media_type_rejected():
    # exact-match media type, 400 problem document (reference
    # http_handlers.rs validate_content_type answers 400 BadRequest)
    app = DapHttpApp(_NoAgg())
    status, ctype, body, _ = app.handle(
        "PUT",
        "/tasks/x/reports",
        {},
        {"Content-Type": "application/json"},
        b"body",
    )
    assert status == 400
    assert ctype == "application/problem+json"
    # media-type parameters are NOT tolerated (exact match)
    status, _, _, _ = app.handle(
        "PUT",
        "/tasks/x/reports",
        {},
        {"Content-Type": "application/dap-report; charset=utf-8"},
        b"body",
    )
    assert status == 400


def test_no_cors_headers_on_aggregator_routes():
    # ACAO must not leak onto aggregator-to-aggregator endpoints
    # (reference scopes CORS to hpke_config/upload/collection_jobs)
    app = DapHttpApp(_NoAgg())
    srv = DapServer(app).start()
    try:
        req = urllib.request.Request(
            srv.url + "tasks/x/aggregation_jobs/y", method="PUT", data=b""
        )
        try:
            resp = urllib.request.urlopen(req)
        except urllib.error.HTTPError as e:
            resp = e
        assert resp.headers.get("Access-Control-Allow-Origin") is None
    finally:
        srv.stop()


def test_cors_headers_on_server():
    app = DapHttpApp(_NoAgg())
    srv = DapServer(app).start()
    try:
        req = urllib.request.Request(
            srv.url + "tasks/x/reports", method="OPTIONS"
        )
        with urllib.request.urlopen(req) as resp:
            assert resp.status == 204
            assert resp.headers["Access-Control-Allow-Origin"] == "*"
            assert "PUT" in resp.headers["Access-Control-Allow-Methods"]
            assert "content-type" in resp.headers["Access-Control-Allow-Headers"]
    finally:
        srv.stop()
