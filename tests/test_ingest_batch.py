"""Batched ingest crypto + columnar upload decode (ISSUE 11;
docs/INGEST.md "Batched decrypt"): decode_reports_fast must be
bit-identical to Report.from_bytes per lane (accept AND reject),
hpke_open_batch must agree with the per-report hpke_open oracle on
every lane — tamper/wrong-key/truncation rejects landing on the right
report index — the reused EVP cipher context must be
correct across interleaved keys/algorithms/threads, and the
window-batched IngestPipeline must preserve per-report ticket
semantics."""

import dataclasses
import secrets
import threading

import numpy as np
import pytest

from janus_tpu import metrics
from janus_tpu.aggregator import Config
from janus_tpu.aggregator.core import TaskAggregator
from janus_tpu.aggregator.report_writer import ReportWriteBatcher
from janus_tpu.client import Client, ClientParameters
from janus_tpu.core import hpke_backend
from janus_tpu.core.hpke import (
    HpkeApplicationInfo,
    HpkeError,
    Label,
    generate_hpke_config_and_private_key,
    hpke_open,
    hpke_open_batch,
    hpke_seal,
)
from janus_tpu.core.time_util import MockClock
from janus_tpu.datastore.store import EphemeralDatastore
from janus_tpu.ingest import IngestPipeline
from janus_tpu.ingest.pipeline import default_decrypt_workers
from janus_tpu.messages import (
    DecodeError,
    HpkeAeadId,
    HpkeCiphertext,
    HpkeConfigId,
    HpkeKdfId,
    HpkeKemId,
    PlaintextInputShare,
    Report,
    ReportId,
    ReportMetadata,
    Role,
    Time,
    decode_reports_fast,
    plaintext_input_share_payload_fast,
)
from janus_tpu.task import QueryTypeConfig, TaskBuilder
from janus_tpu.vdaf.registry import VdafInstance

UPLOAD_INFO = HpkeApplicationInfo(Label.INPUT_SHARE, Role.CLIENT, Role.LEADER)


# ---------------------------------------------------------------------------
# decode_reports_fast vs Report.from_bytes
# ---------------------------------------------------------------------------


def _random_report(rng) -> Report:
    return Report(
        ReportMetadata(
            ReportId(secrets.token_bytes(16)), Time(int(rng.integers(0, 1 << 40)))
        ),
        secrets.token_bytes(int(rng.integers(0, 48))),
        HpkeCiphertext(
            HpkeConfigId(int(rng.integers(0, 256))),
            secrets.token_bytes(int(rng.integers(0, 64))),
            secrets.token_bytes(int(rng.integers(0, 120))),
        ),
        HpkeCiphertext(
            HpkeConfigId(int(rng.integers(0, 256))),
            secrets.token_bytes(int(rng.integers(0, 64))),
            secrets.token_bytes(int(rng.integers(0, 120))),
        ),
    )


def test_decode_reports_fast_equivalent_on_valid_bodies():
    rng = np.random.default_rng(31)
    reports = [_random_report(rng) for _ in range(60)]
    col = decode_reports_fast([r.to_bytes() for r in reports])
    assert len(col) == 60
    for i, r in enumerate(reports):
        assert col.errors[i] is None
        assert col.report_ids[i] == r.metadata.report_id.data
        assert col.times[i] == r.metadata.time.seconds
        assert col.public_shares[i] == r.public_share
        assert col.leader_config_ids[i] == r.leader_encrypted_input_share.config_id.id
        assert col.leader_encs[i] == r.leader_encrypted_input_share.encapsulated_key
        assert col.leader_payloads[i] == r.leader_encrypted_input_share.payload
        assert col.helper_ciphertext(i) == r.helper_encrypted_input_share
        assert col.report(i) == r


def test_decode_reports_fast_reject_divergence_fuzz():
    """Mutational fuzz: truncations, trailing bytes and corrupted bytes
    must produce a DecodeError lane exactly when Report.from_bytes
    raises — and one bad lane never poisons its window."""
    rng = np.random.default_rng(37)
    base = _random_report(rng).to_bytes()
    mutants = [base[:k] for k in range(0, len(base), 2)]
    mutants += [base + b"\x00", base + secrets.token_bytes(5)]
    for _ in range(300):
        m = bytearray(base)
        m[int(rng.integers(0, len(m)))] = int(rng.integers(0, 256))
        mutants.append(bytes(m))
    # decode the WHOLE mutant set as one window: per-lane verdicts
    col = decode_reports_fast(mutants)
    for i, m in enumerate(mutants):
        try:
            ref = Report.from_bytes(m)
        except DecodeError:
            ref = None
        if ref is None:
            assert isinstance(col.errors[i], DecodeError), m.hex()
        else:
            assert col.errors[i] is None, m.hex()
            assert col.report(i) == ref


def test_plaintext_input_share_fast_parse_divergence_fuzz():
    rng = np.random.default_rng(41)
    from janus_tpu.messages import Extension

    base = PlaintextInputShare(
        (Extension(0, b"ab"), Extension(0xFF00, b"")), secrets.token_bytes(33)
    ).to_bytes()
    mutants = [base[:k] for k in range(len(base))] + [base + b"\x00"]
    for _ in range(250):
        m = bytearray(base)
        m[int(rng.integers(0, len(m)))] = int(rng.integers(0, 256))
        mutants.append(bytes(m))
    for m in mutants:
        try:
            want = PlaintextInputShare.from_bytes(m).payload
        except DecodeError:
            want = "ERR"
        try:
            got = plaintext_input_share_payload_fast(m)
        except DecodeError:
            got = "ERR"
        assert got == want, m.hex()


# ---------------------------------------------------------------------------
# hpke_open_batch vs the per-report oracle
# ---------------------------------------------------------------------------

SUITES = [
    (HpkeKemId.X25519_HKDF_SHA256, HpkeKdfId.HKDF_SHA256, HpkeAeadId.AES_128_GCM),
    (HpkeKemId.X25519_HKDF_SHA256, HpkeKdfId.HKDF_SHA512, HpkeAeadId.CHACHA20POLY1305),
    (HpkeKemId.P256_HKDF_SHA256, HpkeKdfId.HKDF_SHA384, HpkeAeadId.AES_256_GCM),
]


@pytest.mark.parametrize("kem,kdf,aead", SUITES, ids=lambda v: getattr(v, "name", v))
def test_hpke_open_batch_equivalence_fuzz(kem, kdf, aead):
    """Every lane of a mixed window (valid, tampered payload, truncated
    payload, wrong/malformed encapsulated key, wrong AAD) must agree
    with the per-report oracle: same plaintext on accepts, an
    HpkeError lane exactly where the oracle raises — on the SAME
    index."""
    kp = generate_hpke_config_and_private_key(0, kem, kdf, aead)
    other = generate_hpke_config_and_private_key(0, kem, kdf, aead)
    rng = np.random.default_rng(43)
    n = 24
    pts = [secrets.token_bytes(int(rng.integers(1, 90))) for _ in range(n)]
    aads = [secrets.token_bytes(int(rng.integers(0, 24))) for _ in range(n)]
    cts = [hpke_seal(kp.config, UPLOAD_INFO, p, a) for p, a in zip(pts, aads)]
    encs = [c.encapsulated_key for c in cts]
    pays = [c.payload for c in cts]
    # sabotage specific lanes
    pays[3] = bytes([pays[3][0] ^ 1]) + pays[3][1:]  # tampered ciphertext
    pays[5] = pays[5][:7]  # shorter than the AEAD tag
    encs[7] = secrets.token_bytes(3)  # malformed encapsulated key
    encs[9] = hpke_seal(other.config, UPLOAD_INFO, b"x", b"").encapsulated_key
    pays[9] = hpke_seal(other.config, UPLOAD_INFO, b"x", b"").payload  # wrong key
    aads[11] = aads[11] + b"!"  # AAD mismatch

    got = hpke_open_batch(kp, UPLOAD_INFO, encs, pays, aads)
    expected_err = {3, 5, 7, 9, 11}
    for i in range(n):
        try:
            want = hpke_open(
                kp, UPLOAD_INFO, HpkeCiphertext(kp.config.id, encs[i], pays[i]), aads[i]
            )
        except HpkeError:
            want = None
        if want is None:
            assert isinstance(got[i], HpkeError), i
            assert i in expected_err or i not in range(n)
        else:
            assert got[i] == want == pts[i], i
    # sanity: the sabotaged lanes really were the reject lanes
    assert {i for i in range(n) if isinstance(got[i], HpkeError)} == expected_err


def test_hpke_open_batch_bad_recipient_key_rejects_per_lane():
    """A corrupt RECIPIENT private key (bad provisioning) must come
    back as per-lane HpkeError values — the oracle rejects each report
    individually, so the batch must never throw a window-wide
    exception (which the pipeline would surface as 500s)."""
    from janus_tpu.core.hpke import HpkeKeypair

    kp = generate_hpke_config_and_private_key(0)
    ct = hpke_seal(kp.config, UPLOAD_INFO, b"x", b"a")
    bad = HpkeKeypair(kp.config, b"not-32-bytes")
    out = hpke_open_batch(
        bad, UPLOAD_INFO, [ct.encapsulated_key] * 3, [ct.payload] * 3, [b"a"] * 3
    )
    assert len(out) == 3 and all(isinstance(o, HpkeError) for o in out)
    with pytest.raises(HpkeError):
        hpke_open(bad, UPLOAD_INFO, ct, b"a")


def test_x25519_exchange_batch_matches_scalar():
    if hpke_backend.BACKEND != "libcrypto":
        pytest.skip("libcrypto-only surface")
    pk_a, sk_a = hpke_backend.x25519_generate()
    peers = [hpke_backend.x25519_generate()[0] for _ in range(8)]
    got = hpke_backend.x25519_exchange_batch(sk_a, peers)
    for pk, dh in zip(peers, got):
        assert dh == hpke_backend.x25519_exchange(sk_a, pk)
    # malformed lanes are None, in place, without failing the window
    mixed = [peers[0], b"short", None, peers[1]]
    got = hpke_backend.x25519_exchange_batch(sk_a, mixed)
    assert got[0] == hpke_backend.x25519_exchange(sk_a, peers[0])
    assert got[1] is None and got[2] is None
    assert got[3] == hpke_backend.x25519_exchange(sk_a, peers[1])


def test_aead_context_reuse_correctness_across_keys_and_threads():
    """The pooled/reused EVP cipher context (the per-call create/free
    fix) must not leak state between ops: interleaved encrypt/decrypt
    across AES-128/AES-256/ChaCha instances, auth failures in the
    middle, and 4 threads hammering concurrently all round-trip."""
    rng = np.random.default_rng(47)
    ciphers = [
        hpke_backend.AESGCM(secrets.token_bytes(16)),
        hpke_backend.AESGCM(secrets.token_bytes(32)),
        hpke_backend.ChaCha20Poly1305(secrets.token_bytes(32)),
    ]
    errors = []

    def hammer(seed: int) -> None:
        local_rng = np.random.default_rng(seed)
        try:
            for k in range(120):
                c = ciphers[k % 3]
                nonce = secrets.token_bytes(12)
                pt = secrets.token_bytes(int(local_rng.integers(0, 64)))
                aad = secrets.token_bytes(int(local_rng.integers(0, 16)))
                blob = c.encrypt(nonce, pt, aad)
                assert c.decrypt(nonce, blob, aad) == pt
                if k % 5 == 0:  # auth failure mid-stream must not poison
                    bad = bytes([blob[0] ^ 1]) + blob[1:]
                    try:
                        c.decrypt(nonce, bad, aad)
                        raise AssertionError("tampered ciphertext accepted")
                    except ValueError:
                        pass
                    assert c.decrypt(nonce, blob, aad) == pt
        except BaseException as e:  # surfaced on the main thread
            errors.append(e)

    threads = [threading.Thread(target=hammer, args=(s,)) for s in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors, errors
    # batch open interleaves keys of both AES sizes through ONE context
    keys = [secrets.token_bytes(16), secrets.token_bytes(32)] * 4
    nonces = [secrets.token_bytes(12) for _ in keys]
    pts = [secrets.token_bytes(20) for _ in keys]
    blobs = [
        hpke_backend.AESGCM(k).encrypt(nn, p, b"a")
        for k, nn, p in zip(keys, nonces, pts)
    ]
    blobs[3] = blobs[3][:-1] + bytes([blobs[3][-1] ^ 1])
    out = hpke_backend.aead_open_batch(
        hpke_backend.AESGCM, keys, nonces, blobs, [b"a"] * len(keys)
    )
    for i, p in enumerate(pts):
        if i == 3:
            assert out[i] is None
        else:
            assert out[i] == p


# ---------------------------------------------------------------------------
# TaskAggregator batch stages vs the per-report oracle
# ---------------------------------------------------------------------------


def _leader_task(inst=None):
    clock = MockClock(Time(1_600_000_000))
    vdaf = inst or VdafInstance.count()
    leader_kp = generate_hpke_config_and_private_key(config_id=0)
    helper_kp = generate_hpke_config_and_private_key(config_id=1)
    task = (
        TaskBuilder(QueryTypeConfig.time_interval(), vdaf, Role.LEADER)
        .with_(
            leader_aggregator_endpoint="http://leader",
            helper_aggregator_endpoint="http://helper",
            hpke_keys=(leader_kp,),
            min_batch_size=1,
        )
        .build()
    )
    params = ClientParameters(
        task.task_id, "http://leader", "http://helper", task.time_precision
    )
    client = Client(params, vdaf, leader_kp.config, helper_kp.config, clock=clock)
    return clock, task, client


@pytest.mark.parametrize(
    "inst",
    [VdafInstance.count(), VdafInstance.histogram(6), VdafInstance.sum_vec(16, 4)],
    ids=lambda i: i.kind,
)
def test_upload_batch_stages_equivalent_to_oracle(inst):
    """A window mixing valid reports with every per-report failure mode
    (future timestamp, unknown config id, tampered ciphertext, bad
    plaintext structure, share out of range) must resolve each lane to
    exactly what the per-report oracle produces: same stored reports,
    same error types, on the same indexes."""
    clock, task, client = _leader_task(inst)
    from janus_tpu.vdaf.testing import random_measurements

    rng = np.random.default_rng(53)
    meas = random_measurements(inst, 10, rng)
    reports = [
        client.prepare_report(m.tolist() if getattr(m, "ndim", 0) else int(m))
        for m in meas
    ]
    # lane 2: report from the future
    reports[2] = client.prepare_report(
        meas[2].tolist() if getattr(meas[2], "ndim", 0) else int(meas[2]),
        when=Time(1_600_000_000 + 30 * 24 * 3600),
    )
    # lane 4: unknown HPKE config id
    reports[4] = dataclasses.replace(
        reports[4],
        leader_encrypted_input_share=dataclasses.replace(
            reports[4].leader_encrypted_input_share, config_id=HpkeConfigId(99)
        ),
    )
    # lane 6: tampered leader ciphertext
    p6 = reports[6].leader_encrypted_input_share.payload
    reports[6] = dataclasses.replace(
        reports[6],
        leader_encrypted_input_share=dataclasses.replace(
            reports[6].leader_encrypted_input_share,
            payload=bytes([p6[0] ^ 1]) + p6[1:],
        ),
    )
    bodies = [r.to_bytes() for r in reports]

    ta = TaskAggregator(task, Config())
    # oracle pass
    want = []
    for r in reports:
        try:
            kp = ta.upload_prepare(clock, r)
            want.append(ta.upload_decrypt_validate(r, kp))
        except Exception as e:
            want.append(e)
    # batch pass
    col = decode_reports_fast(bodies)
    idxs = list(range(len(bodies)))
    prepared = ta.upload_prepare_columns(clock, col, idxs)
    got = [None] * len(bodies)
    live = []
    for i, res in enumerate(prepared):
        if isinstance(res, BaseException):
            got[i] = res
        else:
            live.append(i)
    keypair = next(prepared[i] for i in live)
    for i, res in zip(live, ta.upload_decrypt_validate_batch(col, live, keypair)):
        got[i] = res

    for i in range(len(bodies)):
        if isinstance(want[i], BaseException):
            assert type(got[i]) is type(want[i]), (i, got[i], want[i])
            # same reject CLASS and same handler-visible prefix; the
            # crypto-internal detail after the first colon may phrase
            # the same failure differently (batch lanes can't always
            # tell which EVP step rejected)
            assert str(got[i]).split(":")[0] == str(want[i]).split(":")[0]
        else:
            assert got[i] == want[i], i


def test_upload_batch_share_out_of_range_rejects_right_lane():
    """An in-range window with ONE out-of-field-range share: the numpy
    batch validation must reject that lane (same error type as the
    oracle) and keep its neighbors."""
    clock, task, client = _leader_task(VdafInstance.sum(8))
    from janus_tpu.aggregator import errors as agg_errors
    from janus_tpu.core.hpke import hpke_seal as seal
    from janus_tpu.messages import InputShareAad

    reports = [client.prepare_report(3) for _ in range(5)]
    # re-seal lane 2's leader share with an out-of-range field element
    r = reports[2]
    ta = TaskAggregator(task, Config())
    keypair = task.hpke_keys[0]
    aad = InputShareAad(task.task_id, r.metadata, r.public_share).to_bytes()
    plaintext = hpke_open(
        keypair, UPLOAD_INFO, r.leader_encrypted_input_share, aad
    )
    share = bytearray(PlaintextInputShare.from_bytes(plaintext).payload)
    share[: ta.wire.enc_size] = b"\xff" * ta.wire.enc_size  # >= MODULUS
    forged = PlaintextInputShare((), bytes(share)).to_bytes()
    reports[2] = dataclasses.replace(
        r, leader_encrypted_input_share=seal(keypair.config, UPLOAD_INFO, forged, aad)
    )

    bodies = [x.to_bytes() for x in reports]
    col = decode_reports_fast(bodies)
    idxs = list(range(5))
    kps = ta.upload_prepare_columns(clock, col, idxs)
    out = ta.upload_decrypt_validate_batch(col, idxs, kps[0])
    for i in range(5):
        if i == 2:
            assert isinstance(out[i], agg_errors.ReportRejected)
            assert "out of field range" in str(out[i])
        else:
            assert not isinstance(out[i], BaseException)
    # …and the oracle agrees about lane 2
    with pytest.raises(agg_errors.ReportRejected):
        ta.upload_decrypt_validate(reports[2], kps[2])


# ---------------------------------------------------------------------------
# window-batched pipeline semantics
# ---------------------------------------------------------------------------


def test_batched_pipeline_mixed_window_per_ticket_outcomes():
    """One window holding valid, undecodable, future-dated and
    tampered uploads: every ticket resolves to its own verdict and the
    batched path demonstrably ran (one hpke_open_batch for the
    window's surviving lanes)."""
    clock, task, client = _leader_task()
    from janus_tpu.aggregator import errors as agg_errors

    eph = EphemeralDatastore(clock=clock)
    try:
        eph.datastore.run_tx(lambda tx: tx.put_task(task))
        ta = TaskAggregator(task, Config())
        writer = ReportWriteBatcher(eph.datastore, 100, 0)
        # window == submit count so the window flushes on FILL, with a
        # long linger only as backstop — the calls==1 assertion must
        # not ride a 200 ms scheduler-stall race (the bench windowing
        # proof uses the same discipline)
        pipe = IngestPipeline(
            writer, queue_depth=16, batch_window=6, batch_linger_ms=2000.0
        )
        try:
            good = [client.prepare_report(1) for _ in range(4)]
            future = client.prepare_report(1, when=Time(1_600_000_000 + 30 * 24 * 3600))
            p = good[3].leader_encrypted_input_share.payload
            tampered = dataclasses.replace(
                good[3],
                metadata=ReportMetadata(ReportId.random(), good[3].metadata.time),
                leader_encrypted_input_share=dataclasses.replace(
                    good[3].leader_encrypted_input_share,
                    payload=bytes([p[0] ^ 1]) + p[1:],
                ),
            )
            calls0, lanes0 = 0, 0
            with metrics.hpke_batch_size._lock:
                calls0 = sum(metrics.hpke_batch_size._totals.values())
                lanes0 = sum(metrics.hpke_batch_size._sums.values())
            bodies = [r.to_bytes() for r in good[:3]] + [
                b"garbage",
                future.to_bytes(),
                tampered.to_bytes(),
            ]
            tickets = [pipe.submit(ta, clock, b) for b in bodies]
            outcomes = []
            for t in tickets:
                try:
                    outcomes.append(t.result(timeout_s=60))
                except Exception as e:
                    outcomes.append(e)
            assert outcomes[0] is True and outcomes[1] is True and outcomes[2] is True
            assert isinstance(outcomes[3], DecodeError)
            assert isinstance(outcomes[4], agg_errors.ReportTooEarly)
            assert isinstance(outcomes[5], agg_errors.ReportRejected)
            with metrics.hpke_batch_size._lock:
                calls = sum(metrics.hpke_batch_size._totals.values()) - calls0
                lanes = sum(metrics.hpke_batch_size._sums.values()) - lanes0
            assert calls == 1  # one batched open for the window
            assert lanes == 4  # 3 valid + the tampered lane reached crypto
            total, _ = eph.datastore.run_tx(
                lambda tx: tx.count_client_reports_for_task(task.task_id)
            )
            assert total == 3
        finally:
            pipe.close()
            writer.close()
    finally:
        eph.cleanup()


def test_single_report_fallback_mode_still_works():
    """batch_window=1 restores the per-report path end to end."""
    clock, task, client = _leader_task()
    eph = EphemeralDatastore(clock=clock)
    try:
        eph.datastore.run_tx(lambda tx: tx.put_task(task))
        ta = TaskAggregator(task, Config())
        writer = ReportWriteBatcher(eph.datastore, 100, 0)
        pipe = IngestPipeline(writer, queue_depth=8, batch_window=1)
        try:
            tickets = [
                pipe.submit(ta, clock, client.prepare_report(1).to_bytes())
                for _ in range(3)
            ]
            assert all(t.result(timeout_s=60) for t in tickets)
            with pytest.raises(DecodeError):
                pipe.submit(ta, clock, b"junk").result(timeout_s=60)
        finally:
            pipe.close()
            writer.close()
        total, _ = eph.datastore.run_tx(
            lambda tx: tx.count_client_reports_for_task(task.task_id)
        )
        assert total == 3
    finally:
        eph.cleanup()


def test_decrypt_pool_sizing_follows_batch_gil_capability(monkeypatch):
    """Satellite fix: the default decrypt pool is sized from the crypto
    backend's batch GIL-release capability, not blindly from cores — a
    GIL-holding PyDLL batch call serializes workers, so extra threads
    only add convoy switches."""
    import os as _os

    monkeypatch.setattr(_os, "cpu_count", lambda: 16)
    monkeypatch.setattr(hpke_backend, "BATCH_RELEASES_GIL", False)
    assert default_decrypt_workers(batched=True) == 2
    monkeypatch.setattr(hpke_backend, "BATCH_RELEASES_GIL", True)
    assert default_decrypt_workers(batched=True) == 16
    # the per-report fallback mode keeps the old cores-wide pool (its
    # parallelizable stage is the GIL-releasing numpy validation)
    monkeypatch.setattr(hpke_backend, "BATCH_RELEASES_GIL", False)
    assert default_decrypt_workers(batched=False) == 16
