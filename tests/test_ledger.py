"""Report-flow conservation ledger (janus_tpu/ledger.py; ISSUE 20).

Balance closure through the REAL pipeline — live leader+helper pair
over loopback HTTP, upload -> aggregate -> collect — on every datastore
engine; terminal attribution for the rejected and expired lanes;
exactly-once booking under a replayed helper job step plus detection of
a simulated double-count; cross-aggregator reconciliation against a
tampered helper; and torn-read safety of the /debug/ledger document
under concurrent evaluation.
"""

import base64
import threading
import time
from dataclasses import replace

import pytest
from conftest import DATASTORE_ENGINES
from test_e2e import provision

from janus_tpu import ledger
from janus_tpu.aggregator import Aggregator, Config
from janus_tpu.aggregator.aggregation_job_creator import (
    AggregationJobCreator,
    AggregationJobCreatorConfig,
)
from janus_tpu.aggregator.aggregation_job_driver import AggregationJobDriver
from janus_tpu.aggregator.collection_job_driver import CollectionJobDriver
from janus_tpu.aggregator.garbage_collector import GarbageCollector
from janus_tpu.aggregator.http_handlers import DapHttpApp, DapServer
from janus_tpu.aggregator.job_driver import JobDriver, JobDriverConfig
from janus_tpu.client import Client, ClientParameters
from janus_tpu.collector import Collector, CollectorParameters
from janus_tpu.core.http_client import HttpClient
from janus_tpu.core.time_util import MockClock
from janus_tpu.datastore.models import (
    AggregationJobModel,
    AggregationJobState,
    LeaderStoredReport,
    ReportAggregationModel,
    ReportAggregationState,
)
from janus_tpu.datastore.store import EphemeralDatastore
from janus_tpu.messages import (
    AggregationJobId,
    Duration,
    HpkeCiphertext,
    HpkeConfigId,
    Interval,
    PrepareError,
    Query,
    ReportId,
    Role,
    TaskId,
    Time,
)
from janus_tpu.metrics import task_id_label
from janus_tpu.task import QueryTypeConfig, TaskBuilder
from janus_tpu.vdaf.registry import VdafInstance

# every per-task entry in the balance document carries this shape; the
# torn-read test asserts no reader ever sees a partial one
TASK_DOC_KEYS = {
    "admitted",
    "aggregated",
    "rejected",
    "expired",
    "expired_reclaimed",
    "lost",
    "collected",
    "param",
    "in_flight",
    "imbalance",
    "peer",
}
DOC_KEYS = {"enabled", "evaluations", "tasks", "breaches"}
BALANCED = {"ingest": 0, "param": 0, "collect": 0}


class _LivePair:
    """test_e2e's `pair` fixture as a context manager so the engine can
    be parameterized per test instead of per fixture instantiation."""

    def __init__(self, engine: str = "sqlite"):
        self.engine = engine

    def __enter__(self):
        clock = MockClock(Time(1_600_000_000))
        self._leader_eph = EphemeralDatastore(clock=clock, engine=self.engine)
        self._helper_eph = EphemeralDatastore(clock=clock, engine=self.engine)
        leader_agg = Aggregator(self._leader_eph.datastore, clock, Config())
        helper_agg = Aggregator(self._helper_eph.datastore, clock, Config())
        self._leader_srv = DapServer(DapHttpApp(leader_agg)).start()
        self._helper_srv = DapServer(DapHttpApp(helper_agg)).start()
        return {
            "clock": clock,
            "leader": leader_agg,
            "helper": helper_agg,
            "leader_srv": self._leader_srv,
            "helper_srv": self._helper_srv,
            "leader_ds": self._leader_eph.datastore,
            "helper_ds": self._helper_eph.datastore,
        }

    def __exit__(self, *exc):
        ledger.uninstall_ledger()
        self._leader_srv.stop()
        self._helper_srv.stop()
        self._leader_eph.cleanup()
        self._helper_eph.cleanup()
        return False


def _upload(pair, leader_task, vdaf, measurements):
    http = HttpClient()
    params = ClientParameters(
        leader_task.task_id,
        pair["leader_srv"].url,
        pair["helper_srv"].url,
        leader_task.time_precision,
    )
    client = Client.with_fetched_configs(params, vdaf, http, clock=pair["clock"])
    for m in measurements:
        client.upload(m)


def _drive_aggregation(pair):
    AggregationJobCreator(
        pair["leader_ds"], AggregationJobCreatorConfig(min_aggregation_job_size=1)
    ).run_once()
    driver = AggregationJobDriver(pair["leader_ds"], HttpClient())
    JobDriver(JobDriverConfig(), driver.acquirer(), driver.stepper).run_once()


def _drive_collection(pair, leader_task, collector_kp, vdaf):
    http = HttpClient()
    clock = pair["clock"]
    start = Time(clock.now().seconds).to_batch_interval_start(leader_task.time_precision)
    query = Query.time_interval(Interval(Time(start.seconds - 3600), Duration(2 * 3600)))
    collector = Collector(
        CollectorParameters(
            leader_task.task_id,
            pair["leader_srv"].url,
            leader_task.collector_auth_token,
            collector_kp,
        ),
        vdaf,
        http,
    )
    job_id = collector.start_collection(query)
    cdriver = CollectionJobDriver(pair["leader_ds"], http)
    JobDriver(JobDriverConfig(), cdriver.acquirer(), cdriver.stepper).run_once()
    return collector.poll_once(job_id, query)


@pytest.mark.parametrize("engine", DATASTORE_ENGINES)
def test_balance_closure_upload_aggregate_collect(engine):
    """The books close at EVERY pipeline stage, on every engine: after
    upload (all mass pending), after aggregation (all mass awaiting
    collection), after collection (all mass terminal) — zero imbalance
    and zero breaches throughout, on both aggregators, with the in-line
    peer reconciliation reporting zero divergence."""
    vdaf = VdafInstance.count()
    with _LivePair(engine) as pair:
        leader_task, helper_task, collector_kp = provision(pair, vdaf)
        ev = ledger.install_ledger(pair["leader_ds"], ledger.LedgerConfig(grace_s=0.0))
        label = task_id_label(leader_task.task_id.data)

        _upload(pair, leader_task, vdaf, [1, 0, 1, 1])
        t = ev.evaluate_once()["tasks"][label]
        assert t["admitted"] == 4
        assert t["in_flight"]["pending_reports"] == 4
        assert t["imbalance"] == BALANCED

        _drive_aggregation(pair)
        doc = ev.evaluate_once()
        t = doc["tasks"][label]
        assert t["aggregated"] == 4
        assert t["in_flight"]["pending_reports"] == 0
        assert t["in_flight"]["pending_aggregation"] == 0
        assert t["in_flight"]["awaiting_collection"] == 4
        assert t["imbalance"] == BALANCED
        assert doc["breaches"] == []

        result = _drive_collection(pair, leader_task, collector_kp, vdaf)
        assert result.report_count == 4 and result.aggregate_result == 3
        doc = ev.evaluate_once()
        t = doc["tasks"][label]
        assert t["collected"] == 4
        assert t["in_flight"]["awaiting_collection"] == 0
        assert t["imbalance"] == BALANCED
        assert doc["breaches"] == []
        # the collection driver reconciled with the helper in-line
        assert t["peer"] is not None
        assert t["peer"]["divergence"] == 0
        assert t["peer"]["batches_compared"] >= 1

        # the helper keeps its own books from its own choke points
        # (aggregate init/continue + aggregate_share) — they close too
        hev = ledger.LedgerEvaluator(pair["helper_ds"], ledger.LedgerConfig(grace_s=0.0))
        ht = hev.evaluate_once()["tasks"][label]
        assert ht["admitted"] == 4 and ht["aggregated"] == 4 and ht["collected"] == 4
        assert ht["imbalance"] == BALANCED


def test_rejected_lane_attribution():
    """A report whose shares cannot be decoded reaches the
    rejected:<reason> terminal instead of lingering as imbalance: the
    books still close, with the rejection attributed per-reason."""
    vdaf = VdafInstance.count()
    with _LivePair() as pair:
        leader_task, _, _ = provision(pair, vdaf)
        ev = ledger.LedgerEvaluator(pair["leader_ds"], ledger.LedgerConfig(grace_s=0.0))
        label = task_id_label(leader_task.task_id.data)

        _upload(pair, leader_task, vdaf, [1, 1])
        # one garbage report admitted straight into the store (and
        # booked, as the report writer would): undecodable leader share
        clock = pair["clock"]

        def put_garbage(tx):
            tx.put_client_report(
                LeaderStoredReport(
                    leader_task.task_id,
                    ReportId(b"\xaa" * 16),
                    Time(clock.now().seconds - 60),
                    b"",
                    b"\xff" * 8,
                    HpkeCiphertext(HpkeConfigId(13), b"enc", b"garbage"),
                )
            )
            ledger.count_admitted(tx, leader_task.task_id, 1)

        pair["leader_ds"].run_tx(put_garbage)
        _drive_aggregation(pair)

        doc = ev.evaluate_once()
        t = doc["tasks"][label]
        assert t["admitted"] == 3
        assert t["aggregated"] == 2
        assert sum(t["rejected"].values()) == 1, t["rejected"]
        assert t["imbalance"] == BALANCED
        assert doc["breaches"] == []


def test_expired_attribution_through_gc():
    """GC deleting an expired never-claimed report books it to the
    `expired` terminal inside the delete transaction — the report
    leaves the pending pool and the books stay closed."""
    clock = MockClock(Time(1_600_000_000))
    eph = EphemeralDatastore(clock=clock)
    try:
        ds = eph.datastore
        task = (
            TaskBuilder(QueryTypeConfig.time_interval(), VdafInstance.count(), Role.LEADER)
            .with_(min_batch_size=1, report_expiry_age=Duration(3600))
            .build()
        )
        label = task_id_label(task.task_id.data)

        def put(tx):
            tx.put_task(task)
            tx.put_client_report(
                LeaderStoredReport(
                    task.task_id,
                    ReportId(b"\x01" * 16),
                    Time(clock.now().seconds - 60),
                    b"",
                    b"share",
                    HpkeCiphertext(HpkeConfigId(0), b"enc", b"payload"),
                )
            )
            ledger.count_admitted(tx, task.task_id, 1)

        ds.run_tx(put)
        ev = ledger.LedgerEvaluator(ds, ledger.LedgerConfig(grace_s=0.0))
        t = ev.evaluate_once()["tasks"][label]
        assert t["in_flight"]["pending_reports"] == 1
        assert t["imbalance"]["ingest"] == 0

        clock.advance(Duration(2 * 3600))
        deleted = GarbageCollector(ds, clock).run_once()
        assert deleted["reports"] == 1

        doc = ev.evaluate_once()
        t = doc["tasks"][label]
        assert t["expired"] == 1
        assert t["in_flight"]["pending_reports"] == 0
        assert t["imbalance"] == BALANCED
        assert doc["breaches"] == []
    finally:
        eph.cleanup()


def test_replayed_job_step_books_exactly_once():
    """Replaying a helper aggregation step verbatim (leader retry after
    a lost response) must not move the helper's counters — booking
    rides inside the step's transaction, and the request-hash replay
    short-circuit never re-runs it. A counter bumped OUTSIDE a
    transaction (the bug this ledger exists to catch) shows up as a
    negative residual and breaches."""
    vdaf = VdafInstance.count()
    with _LivePair() as pair:
        leader_task, helper_task, _ = provision(pair, vdaf)
        _upload(pair, leader_task, vdaf, [1, 0, 1])
        AggregationJobCreator(
            pair["leader_ds"], AggregationJobCreatorConfig(min_aggregation_job_size=1)
        ).run_once()

        captured = {}

        class CapturingHttp(HttpClient):
            def put(self, url, body, headers=None, timeout=None):
                if "aggregation_jobs" in url:
                    captured["url"] = url
                    captured["body"] = body
                    captured["headers"] = headers
                return super().put(url, body, headers, timeout=timeout)

        driver = AggregationJobDriver(pair["leader_ds"], CapturingHttp())
        assert JobDriver(JobDriverConfig(), driver.acquirer(), driver.stepper).run_once() == 1
        assert "body" in captured

        counters = lambda: pair["helper_ds"].run_tx(
            lambda tx: tx.get_task_counters(helper_task.task_id)
        )
        before = counters()
        assert before.get(ledger.ADMITTED) == 3

        # identical replay: same response, identical books
        status, _ = HttpClient().put(captured["url"], captured["body"], captured["headers"])
        assert status == 200
        assert counters() == before

        hev = ledger.LedgerEvaluator(pair["helper_ds"], ledger.LedgerConfig(grace_s=0.0))
        label = task_id_label(helper_task.task_id.data)
        doc = hev.evaluate_once()
        assert doc["tasks"][label]["imbalance"]["ingest"] == 0
        assert doc["breaches"] == []

        # simulate the double-count this test guards against: an
        # out-of-tx increment goes negative and breaches immediately
        pair["helper_ds"].run_tx(
            lambda tx: tx.increment_task_counters(helper_task.task_id, {ledger.AGGREGATED: 1})
        )
        doc = hev.evaluate_once()
        assert doc["tasks"][label]["imbalance"]["ingest"] == -1
        assert f"{label}/ingest" in doc["breaches"]


def test_peer_divergence_with_tampered_helper_count():
    """Cross-aggregator reconciliation: identical per-batch counts read
    as zero divergence; a helper under-reporting one report per batch
    (tampering, or a silent helper-side loss) exports a nonzero
    janus_ledger_peer_divergence and breaches stage="peer". The
    endpoint itself sits behind aggregator auth."""
    vdaf = VdafInstance.count()
    with _LivePair() as pair:
        leader_task, _, collector_kp = provision(pair, vdaf)
        ev = ledger.install_ledger(pair["leader_ds"], ledger.LedgerConfig(grace_s=0.0))
        label = task_id_label(leader_task.task_id.data)

        _upload(pair, leader_task, vdaf, [1, 1, 0])
        _drive_aggregation(pair)
        result = _drive_collection(pair, leader_task, collector_kp, vdaf)
        assert result.report_count == 3

        # the collection step already reconciled: clean lanes diverge by 0
        peer = ev.evaluate_once()["tasks"][label]["peer"]
        assert peer is not None and peer["divergence"] == 0

        cdriver = CollectionJobDriver(pair["leader_ds"], HttpClient())
        theirs = cdriver._fetch_helper_ledger(leader_task)
        assert theirs and sum(theirs.values()) == 3

        tampered = {bid: n - 1 for bid, n in theirs.items()}
        divergence = ev.record_peer_divergence(leader_task.task_id, dict(theirs), tampered)
        assert divergence == len(theirs)
        doc = ev.evaluate_once()
        assert doc["tasks"][label]["peer"]["divergence"] == divergence
        assert doc["tasks"][label]["peer"]["mismatched"]
        assert f"{label}/peer" in doc["breaches"]

        # unauthenticated read is refused (it is the helper's books)
        b64 = base64.urlsafe_b64encode(leader_task.task_id.data).decode().rstrip("=")
        status, body = HttpClient().get(
            pair["helper_srv"].url.rstrip("/") + f"/tasks/{b64}/ledger",
            {"Authorization": "Bearer wrong"},
        )
        assert status == 400 and b"unauthorizedRequest" in body


def test_debug_ledger_reads_never_torn():
    """GET /debug/ledger and the statusz section read the last COMPLETE
    balance document: with evaluations continuously swapping the doc on
    other threads, every read still carries the full key shape and
    internally consistent per-task entries."""
    clock = MockClock(Time(1_600_000_000))
    eph = EphemeralDatastore(clock=clock)
    ev = ledger.install_ledger(eph.datastore, ledger.LedgerConfig(grace_s=0.0))
    try:
        # two balanced tasks' worth of counters (no live rows: all mass
        # terminal, books close at admitted == aggregated == collected)
        def seed(tx):
            from janus_tpu.messages import TaskId

            for b in (b"\x01", b"\x02"):
                tx.increment_task_counters(
                    TaskId(b * 32), {ledger.ADMITTED: 5, ledger.AGGREGATED: 5, ledger.COLLECTED: 5}
                )

        eph.datastore.run_tx(seed)

        stop = threading.Event()
        errors: list[BaseException] = []

        def evaluator_loop():
            while not stop.is_set():
                try:
                    ev.evaluate_once()
                except BaseException as e:  # pragma: no cover - surfaced below
                    errors.append(e)
                    return

        writers = [threading.Thread(target=evaluator_loop) for _ in range(2)]
        for w in writers:
            w.start()
        try:
            for _ in range(300):
                doc = ledger.ledger_document()
                assert DOC_KEYS <= set(doc), doc.keys()
                for label, t in doc["tasks"].items():
                    assert set(t) == TASK_DOC_KEYS, (label, set(t))
                    assert t["imbalance"] == BALANCED
                st = ev.status()
                assert {"enabled", "evaluations", "grace_s", "breaches", "imbalance"} <= set(st)
        finally:
            stop.set()
            for w in writers:
                w.join()
        assert not errors, errors
        assert ev.document()["evaluations"] >= 1
    finally:
        ledger.uninstall_ledger()
        eph.cleanup()

def test_param_fanout_lane_books_and_inflight_split():
    """The parameter-fanout lane (Poplar1-style: one report aggregates
    once PER collection parameter) keeps its own books: param-scoped
    admissions/terminals never debit the single canonical `admitted`,
    in-flight rows split by lane on the job's aggregation parameter,
    and all three balance equations close simultaneously."""
    clock = MockClock(Time(1_600_000_000))
    eph = EphemeralDatastore(clock=clock)
    try:
        ds = eph.datastore
        task = (
            TaskBuilder(QueryTypeConfig.time_interval(), VdafInstance.count(), Role.LEADER)
            .with_(min_batch_size=1)
            .build()
        )
        label = task_id_label(task.task_id.data)
        param = b"\x01level2"

        def seed(tx):
            tx.put_task(task)
            now = Time(clock.now().seconds - 60)
            # three admitted reports; for a param task these stay in
            # pending_reports (never claimed canonically) for life
            for i in range(3):
                tx.put_client_report(
                    LeaderStoredReport(
                        task.task_id,
                        ReportId(bytes([i + 1]) * 16),
                        now,
                        b"",
                        b"share",
                        HpkeCiphertext(HpkeConfigId(0), b"enc", b"payload"),
                    )
                )
            ledger.count_admitted(tx, task.task_id, 3)
            # two completed fanout levels over those reports, collected
            tx.increment_task_counters(
                task.task_id,
                {ledger.ADMITTED_PARAM: 6, ledger.AGGREGATED_PARAM: 6, ledger.COLLECTED: 6},
            )
            # a third level mid-flight: 2 rows pending under an
            # in-progress param job, 1 already failed (booked terminal)
            job_id = AggregationJobId(b"\x0a" * 16)
            tx.put_aggregation_job(
                AggregationJobModel(
                    task.task_id,
                    job_id,
                    param,
                    b"",
                    Interval(now, Duration(60)),
                    AggregationJobState.IN_PROGRESS,
                    0,
                )
            )
            for ord_ in range(2):
                tx.put_report_aggregation(
                    ReportAggregationModel(
                        task.task_id,
                        job_id,
                        ReportId(bytes([ord_ + 1]) * 16),
                        now,
                        ord_,
                        ReportAggregationState.START,
                    )
                )
            failed = ReportAggregationModel(
                task.task_id,
                job_id,
                ReportId(b"\x03" * 16),
                now,
                2,
                ReportAggregationState.FAILED,
                b"",
                PrepareError.VDAF_PREP_ERROR,
            )
            tx.put_report_aggregation(failed)
            ledger.count_admitted(tx, task.task_id, 3, aggregation_parameter=param)
            ledger.count_ra_outcomes(
                tx, task.task_id, [failed], aggregation_parameter=param
            )

        ds.run_tx(seed)
        ev = ledger.LedgerEvaluator(ds, ledger.LedgerConfig(grace_s=0.0))
        doc = ev.evaluate_once()
        t = doc["tasks"][label]
        assert t["admitted"] == 3 and t["aggregated"] == 0 and t["rejected"] == {}
        assert t["param"] == {
            "admitted": 9,
            "aggregated": 6,
            "rejected": {"vdaf_prep_error": 1},
            "expired": 0,
        }
        assert t["in_flight"]["pending_reports"] == 3
        assert t["in_flight"]["pending_aggregation"] == 0
        assert t["in_flight"]["pending_aggregation_param"] == 2
        assert t["imbalance"] == BALANCED
        assert doc["breaches"] == []
    finally:
        eph.cleanup()


def test_abandoned_job_start_rows_not_double_booked_by_gc():
    """abandon_job returns a job's START rows to the unclaimed pool —
    those reports retry under a fresh job, so GC must NOT also book
    their rows `expired` when it deletes the abandoned job's storage
    (double terminal -> permanently negative ingest residual). Only the
    waiting rows, whose claims die with the job, are genuinely gone."""
    clock = MockClock(Time(1_600_000_000))
    eph = EphemeralDatastore(clock=clock)
    try:
        ds = eph.datastore
        task = (
            TaskBuilder(QueryTypeConfig.time_interval(), VdafInstance.count(), Role.LEADER)
            .with_(min_batch_size=1, report_expiry_age=Duration(3600))
            .build()
        )
        label = task_id_label(task.task_id.data)

        def put(tx):
            tx.put_task(task)
            for i in range(3):
                tx.put_client_report(
                    LeaderStoredReport(
                        task.task_id,
                        ReportId(bytes([i + 1]) * 16),
                        Time(clock.now().seconds - 60),
                        b"",
                        b"share",
                        HpkeCiphertext(HpkeConfigId(0), b"enc", b"payload"),
                    )
                )
            ledger.count_admitted(tx, task.task_id, 3)

        ds.run_tx(put)
        AggregationJobCreator(
            ds, AggregationJobCreatorConfig(min_aggregation_job_size=1)
        ).run_once()
        acquired = ds.run_tx(
            lambda tx: tx.acquire_incomplete_aggregation_jobs(Duration(600), 10)
        )
        assert len(acquired) == 1

        # one row has already advanced past START when the job dies:
        # its claim is lost with the job (no retry path), the other two
        # START rows go back to the unclaimed pool
        def advance_one(tx):
            ras = tx.get_report_aggregations_for_job(task.task_id, acquired[0].job_id)
            tx.update_report_aggregation(
                replace(ras[0], state=ReportAggregationState.WAITING_LEADER)
            )

        ds.run_tx(advance_one)
        AggregationJobDriver(ds, HttpClient()).abandon_job(acquired[0])

        # grace large enough that the wedged waiting row (visible as a
        # +1 residual until GC attributes it) never counts as a breach
        ev = ledger.LedgerEvaluator(ds, ledger.LedgerConfig(grace_s=60.0))
        t = ev.evaluate_once()["tasks"][label]
        assert t["in_flight"]["pending_reports"] == 2  # back in the pool
        assert t["in_flight"]["pending_aggregation"] == 0  # job not in progress
        assert t["imbalance"]["ingest"] == 1  # the wedged waiting row

        clock.advance(Duration(2 * 3600))
        deleted = GarbageCollector(ds, clock).run_once()
        assert deleted["reports"] == 3 and deleted["aggregation"] == 1

        doc = ev.evaluate_once()
        t = doc["tasks"][label]
        # 2 unclaimed reports + 1 dead waiting row — NOT 5 (the
        # abandoned job's returned START rows must not be re-booked)
        assert t["expired"] == 3
        assert t["expired_reclaimed"] == 1
        assert t["imbalance"] == BALANCED
        assert doc["breaches"] == []
    finally:
        eph.cleanup()


def test_peer_breach_gauge_advances_without_new_sample():
    """A nonzero peer divergence recorded ONCE must flip
    janus_ledger_breach_active{stage="peer"} after the grace window
    elapses even when no further collection (hence no further
    record_peer_divergence call) happens — the evaluator re-runs the
    peer tracks every tick."""
    from janus_tpu import metrics

    clock = MockClock(Time(1_600_000_000))
    eph = EphemeralDatastore(clock=clock)
    try:
        ev = ledger.LedgerEvaluator(eph.datastore, ledger.LedgerConfig(grace_s=0.5))
        task_id = TaskId(b"\x07" * 32)
        label = task_id_label(task_id.data)
        key = "aa" * 32 + ":01"
        assert ev.record_peer_divergence(task_id, {key: 3}, {key: 2}) == 1
        assert f"{label}/peer" not in ev.evaluate_once()["breaches"]
        assert (
            metrics.ledger_breach_active.get(
                task_id=label, stage="peer", **metrics.replica_labels()
            )
            == 0.0
        )
        time.sleep(0.6)
        doc = ev.evaluate_once()
        assert f"{label}/peer" in doc["breaches"]
        assert (
            metrics.ledger_breach_active.get(
                task_id=label, stage="peer", **metrics.replica_labels()
            )
            == 1.0
        )
    finally:
        eph.cleanup()


def test_poplar1_multi_param_books_close():
    """Multi-parameter (Poplar1) task through the LIVE pair: each
    report aggregates once per collection parameter, and the books on
    BOTH aggregators close via the param-fanout lane — the canonical
    `admitted` is never debited by per-param terminals, and the
    (batch, parameter)-keyed peer reconciliation reads zero divergence
    where batch-only keys would sum the fanout and false-alarm."""
    BITS = 2
    vdaf = VdafInstance.poplar1(bits=BITS)
    from janus_tpu.vdaf.poplar1 import Poplar1AggParam

    with _LivePair() as pair:
        leader_task, helper_task, collector_kp = provision(
            pair, vdaf, max_batch_query_count=BITS + 1
        )
        ev = ledger.install_ledger(pair["leader_ds"], ledger.LedgerConfig(grace_s=0.0))
        label = task_id_label(leader_task.task_id.data)
        measurements = [0b10, 0b10, 0b01]
        _upload(pair, leader_task, vdaf, measurements)

        http = HttpClient()
        clock = pair["clock"]
        start = clock.now().to_batch_interval_start(leader_task.time_precision)
        query = Query.time_interval(Interval(Time(start.seconds - 3600), Duration(2 * 3600)))
        collector = Collector(
            CollectorParameters(
                leader_task.task_id,
                pair["leader_srv"].url,
                leader_task.collector_auth_token,
                collector_kp,
            ),
            vdaf,
            http,
        )
        adriver = AggregationJobDriver(pair["leader_ds"], http)
        ajd = JobDriver(
            JobDriverConfig(max_concurrent_job_workers=1), adriver.acquirer(), adriver.stepper
        )
        cdriver = CollectionJobDriver(pair["leader_ds"], http)
        cjd = JobDriver(
            JobDriverConfig(max_concurrent_job_workers=1), cdriver.acquirer(), cdriver.stepper
        )
        expected = {0: [1, 2], 1: [0, 1, 2, 0]}
        for level, prefixes in ((0, (0, 1)), (1, (0, 1, 2, 3))):
            agg_param = Poplar1AggParam(level, prefixes).encode()
            job_id = collector.start_collection(query, agg_param=agg_param)
            for _ in range(8):
                if not (cjd.run_once() + ajd.run_once()):
                    break
            result = collector.poll_once(job_id, query, agg_param=agg_param)
            assert result.report_count == len(measurements)
            assert result.aggregate_result == expected[level]

        doc = ev.evaluate_once()
        t = doc["tasks"][label]
        # canonical lane: 3 uploads admitted, never claimed (param
        # tasks' client_reports stay pending until GC expiry)
        assert t["admitted"] == 3 and t["aggregated"] == 0
        assert t["in_flight"]["pending_reports"] == 3
        # fanout lane: 3 reports x 2 levels, all finished + collected
        assert t["param"]["admitted"] == 6 and t["param"]["aggregated"] == 6
        assert t["collected"] == 6
        assert t["imbalance"] == BALANCED
        assert doc["breaches"] == []
        # in-line reconciliation with composite keys sees no divergence
        assert t["peer"] is not None and t["peer"]["divergence"] == 0
        assert t["peer"]["batches_compared"] >= 1

        # the helper admits per init request — i.e. per (report, param),
        # entirely in the fanout lane; its books close the same way
        hev = ledger.LedgerEvaluator(pair["helper_ds"], ledger.LedgerConfig(grace_s=0.0))
        ht = hev.evaluate_once()["tasks"][label]
        assert ht["admitted"] == 0 and ht["aggregated"] == 0
        assert ht["param"]["admitted"] == 6 and ht["param"]["aggregated"] == 6
        assert ht["collected"] == 6
        assert ht["imbalance"] == BALANCED
