"""Golden CLI tests: every binary's --help output is pinned byte-exact
(the analog of the reference's trycmd goldens, tools/tests/cli.rs and
aggregator/tests/cli.rs). Regenerate with
JANUS_REGEN_GOLDENS=1 python -m pytest tests/test_cli_goldens.py."""

import os
import subprocess
import sys
from pathlib import Path

import pytest

GOLDEN_DIR = Path(__file__).parent / "goldens"
REPO_ROOT = Path(__file__).resolve().parents[1]

BINARIES = [
    "aggregator",
    "aggregation_job_creator",
    "aggregation_job_driver",
    "collection_job_driver",
    "janus_cli",
]


def _run_help(binary: str) -> str:
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    out = subprocess.run(
        [sys.executable, "-m", f"janus_tpu.bin.{binary}", "--help"],
        capture_output=True,
        text=True,
        cwd=REPO_ROOT,
        env=env,
        timeout=120,
    )
    assert out.returncode == 0, out.stderr
    return out.stdout + out.stderr


@pytest.mark.parametrize("binary", BINARIES)
def test_help_matches_golden(binary):
    golden = GOLDEN_DIR / f"{binary}_help.txt"
    got = _run_help(binary)
    if os.environ.get("JANUS_REGEN_GOLDENS") == "1":
        golden.write_text(got)
    assert got == golden.read_text(), (
        f"{binary} --help drifted from its golden; regenerate with "
        "JANUS_REGEN_GOLDENS=1 if the change is intentional"
    )


def test_janus_cli_create_datastore_key_shape():
    """create-datastore-key output is random; pin its shape instead."""
    import base64

    env = dict(os.environ, JAX_PLATFORMS="cpu")
    out = subprocess.run(
        [sys.executable, "-m", "janus_tpu.bin.janus_cli", "create-datastore-key"],
        capture_output=True,
        text=True,
        cwd=REPO_ROOT,
        env=env,
        timeout=120,
    )
    assert out.returncode == 0, out.stderr
    key = out.stdout.strip()
    assert len(base64.urlsafe_b64decode(key + "=" * (-len(key) % 4))) == 16
