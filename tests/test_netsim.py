"""Wire-level fault proxy (core/netsim.py) and the HttpClient
hardening it pins down (core/http_client.py): every toxic kind
exercised against a real HTTP upstream, the wall-clock body budget vs
a slow-drip wire (a per-read socket timeout alone can NEVER end that
read), the response size cap's non-retryable contract, and the
per-connection toxic count budgets the chaos lanes rely on."""

import http.client
import socket
import threading
import time
import urllib.error
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from janus_tpu.core.http_client import HttpClient, PeerResponseTooLarge
from janus_tpu.core.netsim import FaultProxy


class _Handler(BaseHTTPRequestHandler):
    """GET /<n> answers 200 with an n-byte body and a Content-Length,
    so a truncated wire surfaces as IncompleteRead on the client."""

    protocol_version = "HTTP/1.1"

    def do_GET(self):
        n = int(self.path.rsplit("/", 1)[1])
        payload = b"x" * n
        self.send_response(200)
        self.send_header("Content-Length", str(len(payload)))
        self.end_headers()
        self.wfile.write(payload)

    def log_message(self, *args):
        pass


@pytest.fixture(scope="module")
def upstream():
    srv = ThreadingHTTPServer(("127.0.0.1", 0), _Handler)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    yield srv
    srv.shutdown()
    srv.server_close()


@pytest.fixture()
def proxy(upstream):
    with FaultProxy("127.0.0.1", upstream.server_address[1]) as p:
        yield p


def _settles(pred, timeout=2.0):
    """The pump threads account stats just after forwarding; give them
    a beat before asserting on the counters."""
    deadline = time.monotonic() + timeout
    while not pred() and time.monotonic() < deadline:
        time.sleep(0.01)
    return pred()


def test_passthrough_and_stats(proxy):
    status, body = HttpClient(timeout=5.0).get(proxy.url + "1000")
    assert status == 200 and body == b"x" * 1000
    assert proxy.stats["connections_total"] == 1
    assert _settles(lambda: proxy.stats["bytes_down"] >= 1000)  # headers + body
    assert _settles(lambda: proxy.stats["bytes_up"] > 0)  # the GET request line


def test_latency_toxic_delays_the_response(proxy):
    proxy.set_toxics("down", [{"kind": "latency", "latency_s": 0.3}])
    t0 = time.monotonic()
    status, body = HttpClient(timeout=5.0).get(proxy.url + "100")
    assert status == 200 and body == b"x" * 100
    assert time.monotonic() - t0 >= 0.25
    assert proxy.stats["toxic_fired"].get("latency", 0) >= 1


def test_bandwidth_toxic_caps_throughput(proxy):
    proxy.set_toxics("down", [{"kind": "bandwidth", "bytes_per_s": 16384}])
    t0 = time.monotonic()
    status, body = HttpClient(timeout=10.0).get(proxy.url + "8192")
    assert status == 200 and len(body) == 8192
    assert time.monotonic() - t0 >= 0.3  # ~0.5 s at 16 KiB/s
    assert proxy.stats["toxic_fired"].get("bandwidth", 0) >= 1


def test_slicer_defeats_socket_timeout_but_not_body_budget(proxy):
    """THE satellite pin for the wall-clock body budget: a slow-drip
    body (slicer) makes progress on every read, so the generous
    per-read socket timeout never fires — only HttpClient's wall-clock
    body budget ends the attempt, and it surfaces as a retryable
    URLError-wrapped socket.timeout."""
    proxy.set_toxics(
        "down", [{"kind": "slicer", "slice_bytes": 256, "delay_s": 0.05}]
    )
    # control: same hostile wire, budget = the (ample) attempt timeout
    status, body = HttpClient(timeout=10.0).get(proxy.url + "4096")
    assert status == 200 and len(body) == 4096
    assert proxy.stats["toxic_fired"].get("slicer", 0) >= 1

    # tight wall-clock budget: the drip (~0.8 s) must be cut short even
    # though every individual read completes well inside the 10 s
    # socket timeout
    with pytest.raises(urllib.error.URLError) as ei:
        HttpClient(timeout=10.0, body_budget_s=0.3).get(proxy.url + "4096")
    assert isinstance(ei.value.reason, socket.timeout)
    assert "wall-clock budget" in str(ei.value.reason)


def test_reset_toxic_is_a_transport_error(proxy):
    proxy.set_toxics("up", [{"kind": "reset", "after_bytes": 0}])
    with pytest.raises((urllib.error.URLError, OSError)):
        HttpClient(timeout=5.0).get(proxy.url + "100")
    assert proxy.stats["resets"] >= 1


def test_truncate_toxic_normalizes_to_urlerror(proxy):
    """A mid-body FIN (short body under a Content-Length) raises
    http.client.IncompleteRead — an HTTPException, not an OSError —
    which HttpClient normalizes to a retryable URLError instead of
    letting a raw stdlib internal escape the retry loop."""
    proxy.set_toxics("down", [{"kind": "truncate", "after_bytes": 300}])
    with pytest.raises(urllib.error.URLError) as ei:
        HttpClient(timeout=5.0).get(proxy.url + "4096")
    assert isinstance(ei.value.reason, http.client.HTTPException)
    assert proxy.stats["truncates"] >= 1


def test_blackhole_bounded_by_attempt_timeout(proxy):
    proxy.set_toxics("down", [{"kind": "blackhole"}])
    t0 = time.monotonic()
    with pytest.raises((urllib.error.URLError, OSError)):
        HttpClient(timeout=0.5).get(proxy.url + "100")
    # the client's own timeout is the only way out — and it worked
    assert time.monotonic() - t0 < 5.0
    assert proxy.stats["blackholed_chunks"] >= 1


def test_count_budget_applies_to_exactly_n_connections(proxy):
    proxy.set_toxics("up", [{"kind": "reset", "after_bytes": 0, "count": 1}])
    with pytest.raises((urllib.error.URLError, OSError)):
        HttpClient(timeout=5.0).get(proxy.url + "100")
    # budget spent at accept time: the next connection sees a clean wire
    status, body = HttpClient(timeout=5.0).get(proxy.url + "100")
    assert status == 200 and body == b"x" * 100
    assert proxy.toxics()["up"] == []  # expired, not lingering


def test_runtime_toggle_heals_live_proxy(proxy):
    proxy.set_toxics("down", [{"kind": "blackhole"}])
    with pytest.raises((urllib.error.URLError, OSError)):
        HttpClient(timeout=0.4).get(proxy.url + "100")
    proxy.clear()
    status, body = HttpClient(timeout=5.0).get(proxy.url + "100")
    assert status == 200 and body == b"x" * 100


def test_unknown_toxic_kind_rejected(proxy):
    with pytest.raises(ValueError):
        proxy.set_toxics("down", [{"kind": "gremlin"}])
    with pytest.raises(ValueError):
        proxy.set_toxics("sideways", [])


def test_response_size_cap_is_non_retryable(upstream):
    """A peer streaming more than max_response_bytes raises
    PeerResponseTooLarge — deliberately NOT an OSError, so
    retry_http_request propagates it after ONE attempt instead of
    replaying the giant download."""
    from janus_tpu.core.retries import Backoff, retry_http_request

    url = f"http://127.0.0.1:{upstream.server_address[1]}/200000"
    client = HttpClient(timeout=5.0, max_response_bytes=1024)
    calls = {"n": 0}

    def do_request():
        calls["n"] += 1
        return client.get(url)

    with pytest.raises(PeerResponseTooLarge) as ei:
        retry_http_request(do_request, backoff=Backoff.test())
    assert calls["n"] == 1  # no replay
    assert not isinstance(ei.value, OSError)
    assert ei.value.limit_bytes == 1024
