"""Leader-side collect-query validation (reference
aggregator_core/src/query_type.rs:204 CollectableQueryType checks and
aggregator/src/aggregator.rs:2185-2485): time-interval batch-overlap
rejection and max_batch_query_count enforcement at collection-job
creation — without these, a misbehaving collector gets unbounded
leader work and the privacy budget is enforced only by the peer."""

import pytest

from janus_tpu.aggregator import Aggregator, Config
from janus_tpu.aggregator.errors import (
    BatchOverlap,
    BatchQueryCountExceeded,
    InvalidMessage,
)
from janus_tpu.core.auth import AuthenticationToken
from janus_tpu.core.hpke import generate_hpke_config_and_private_key
from janus_tpu.core.time_util import MockClock
from janus_tpu.datastore.store import EphemeralDatastore
from janus_tpu.messages import (
    BatchId,
    CollectionJobId,
    CollectionReq,
    Duration,
    FixedSizeQuery,
    Interval,
    Query,
    Role,
    Time,
)
from janus_tpu.task import QueryTypeConfig, TaskBuilder
from janus_tpu.vdaf.registry import VdafInstance


def _mk(query_type, vdaf=None, **kw):
    clock = MockClock(Time(1_600_000_000))
    eph = EphemeralDatastore(clock=clock)
    collector_kp = generate_hpke_config_and_private_key(config_id=7)
    task = (
        TaskBuilder(query_type, vdaf or VdafInstance.count(), Role.LEADER)
        .with_(
            collector_hpke_config=collector_kp.config,
            aggregator_auth_token=AuthenticationToken.random_bearer(),
            collector_auth_token=AuthenticationToken.random_bearer(),
            min_batch_size=1,
            **kw,
        )
        .build()
    )
    eph.datastore.run_tx(lambda tx: tx.put_task(task))
    agg = Aggregator(eph.datastore, clock, Config())
    ta = agg.task_aggregator_for(task.task_id)
    return eph, agg, ta, task


def _ti_req(start, dur):
    return CollectionReq(Query.time_interval(Interval(Time(start), Duration(dur))), b"")


def test_time_interval_overlap_rejected():
    eph, agg, ta, task = _mk(QueryTypeConfig.time_interval())
    tp = task.time_precision.seconds
    base = 1_600_000_000 - (1_600_000_000 % tp)
    ta.handle_create_collection_job(
        agg.ds, CollectionJobId(bytes(16)), _ti_req(base, 2 * tp)
    )
    # overlapping interval (shifted by one precision unit) -> batchOverlap
    with pytest.raises(BatchOverlap):
        ta.handle_create_collection_job(
            agg.ds, CollectionJobId(bytes([1]) * 16), _ti_req(base + tp, 2 * tp)
        )
    # disjoint interval is fine
    ta.handle_create_collection_job(
        agg.ds, CollectionJobId(bytes([2]) * 16), _ti_req(base + 2 * tp, tp)
    )
    eph.cleanup()


def test_time_interval_idempotent_retry_and_job_id_reuse():
    eph, agg, ta, task = _mk(QueryTypeConfig.time_interval())
    tp = task.time_precision.seconds
    base = 1_600_000_000 - (1_600_000_000 % tp)
    jid = CollectionJobId(bytes([3]) * 16)
    ta.handle_create_collection_job(agg.ds, jid, _ti_req(base, tp))
    # same query, same job id: idempotent
    ta.handle_create_collection_job(agg.ds, jid, _ti_req(base, tp))
    # same query, different job id: rejected
    with pytest.raises(BatchOverlap):
        ta.handle_create_collection_job(
            agg.ds, CollectionJobId(bytes([4]) * 16), _ti_req(base, tp)
        )
    # different query, same job id: rejected
    with pytest.raises(InvalidMessage):
        ta.handle_create_collection_job(agg.ds, jid, _ti_req(base + tp, tp))
    eph.cleanup()


def test_time_interval_same_interval_new_agg_param_counts_not_overlaps():
    """Re-collecting the SAME interval under a different aggregation
    parameter is a distinct collection governed by query count, not
    batch overlap (an interval trivially 'overlaps' itself)."""
    eph, agg, ta, task = _mk(
        QueryTypeConfig.time_interval(),
        max_batch_query_count=2,
        vdaf=VdafInstance.fake(),  # accepts nonempty parameters
    )
    tp = task.time_precision.seconds
    base = 1_600_000_000 - (1_600_000_000 % tp)
    q = Query.time_interval(Interval(Time(base), Duration(tp)))
    ta.handle_create_collection_job(
        agg.ds, CollectionJobId(bytes([30]) * 16), CollectionReq(q, b"")
    )
    # same interval, different agg param: allowed (2nd of 2)
    ta.handle_create_collection_job(
        agg.ds, CollectionJobId(bytes([31]) * 16), CollectionReq(q, b"\x01")
    )
    # 3rd query of the same batch: budget exhausted
    with pytest.raises(BatchQueryCountExceeded):
        ta.handle_create_collection_job(
            agg.ds, CollectionJobId(bytes([32]) * 16), CollectionReq(q, b"\x02")
        )
    eph.cleanup()


def test_fixed_size_query_count_enforced_on_leader():
    # the fake VDAF accepts arbitrary aggregation parameters (the
    # reference's dummy_vdaf, used for exactly these query-count
    # tests); real Prio3 rejects nonempty parameters at creation
    eph, agg, ta, task = _mk(
        QueryTypeConfig.fixed_size(max_batch_size=8),
        max_batch_query_count=2,
        vdaf=VdafInstance.fake(),
    )
    bid = BatchId(bytes([9]) * 32)
    # distinct aggregation parameters are distinct queries over the same
    # batch, each consuming query count
    def by_batch_id_query():
        return Query.fixed_size(FixedSizeQuery(FixedSizeQuery.BY_BATCH_ID, bid))

    for i in range(2):
        ta.handle_create_collection_job(
            agg.ds,
            CollectionJobId(bytes([10 + i]) * 16),
            CollectionReq(by_batch_id_query(), bytes([i])),
        )
    with pytest.raises(BatchQueryCountExceeded):
        ta.handle_create_collection_job(
            agg.ds,
            CollectionJobId(bytes([20]) * 16),
            CollectionReq(by_batch_id_query(), bytes([2])),
        )
    eph.cleanup()


def test_retry_after_emitted_and_honored():
    """The leader answers 202 polls with Retry-After and the collector
    honors it (reference collector/src/lib.rs:466)."""
    from janus_tpu.aggregator.http_handlers import DapHttpApp, DapServer
    from janus_tpu.collector import (
        CollectionJobNotReady,
        Collector,
        CollectorParameters,
    )
    from janus_tpu.core.http_client import HttpClient

    eph, agg, ta, task = _mk(QueryTypeConfig.time_interval())
    agg.cfg.collection_retry_after_s = 3
    srv = DapServer(DapHttpApp(agg)).start()
    try:
        collector_kp = generate_hpke_config_and_private_key(config_id=7)
        http = HttpClient()
        collector = Collector(
            CollectorParameters(
                task.task_id, srv.url, task.collector_auth_token, collector_kp
            ),
            task.vdaf,
            http,
        )
        q = Query.time_interval(Interval(Time(1_599_998_400), Duration(7200)))
        job_id = collector.start_collection(q)
        with pytest.raises(CollectionJobNotReady) as ei:
            collector.poll_once(job_id, q)
        assert ei.value.retry_after_s == 3.0
        # poll_until_complete sleeps per the hint: with a deadline
        # shorter than the hinted wait it gives up without sleeping 3s
        import time as _t

        t0 = _t.monotonic()
        with pytest.raises(TimeoutError):
            collector.poll_until_complete(job_id, q, timeout_s=1.0)
        assert _t.monotonic() - t0 < 2.5
    finally:
        srv.stop()
        eph.cleanup()
