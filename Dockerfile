# janus_tpu container image (the analog of the reference's Dockerfile:
# one image, the binary selected at run time).
#
# For TPU deployments use a base image with libtpu preinstalled (e.g.
# a Cloud-TPU PyTorch/JAX image) and run the VDAF hot-path binaries
# (helper `aggregator`, leader `aggregation_job_driver`) on TPU hosts;
# every other binary pins jax_platform: cpu in its YAML and can run
# anywhere. Intra-deployment coordination is the datastore
# (database.url: postgres://... for multi-host), exactly like the
# reference's Postgres-only control plane (docs/DEPLOYING.md).
FROM python:3.13-slim

WORKDIR /opt/janus_tpu

# Runtime deps. For CPU-only processes jax[cpu] suffices; TPU hosts
# need jax[tpu] (libtpu) instead — build with
#   --build-arg JAX_EXTRA=tpu
ARG JAX_EXTRA=cpu
RUN pip install --no-cache-dir "jax[${JAX_EXTRA}]" numpy cryptography pyyaml

COPY pyproject.toml README.md ./
COPY janus_tpu ./janus_tpu
RUN pip install --no-cache-dir .

# build the native Keccak/XOF helper used by the host staging path
# (available() compiles xof.c on first call when a C compiler exists)
RUN apt-get update && apt-get install -y --no-install-recommends gcc libc6-dev \
    && python -c "import janus_tpu.native as n; print('native:', n.available())" \
    && apt-get purge -y gcc libc6-dev && apt-get autoremove -y \
    && rm -rf /var/lib/apt/lists/*

# healthz/metrics listener (CommonConfig.health_check_listen_address)
EXPOSE 8080 9001

# Select the binary: aggregator | aggregation_job_creator |
# aggregation_job_driver | collection_job_driver | janus_cli |
# interop_client | interop_aggregator | interop_collector
ENTRYPOINT ["python", "-m"]
CMD ["janus_tpu.bin.aggregator", "--config-file", "/etc/janus/aggregator.yaml"]
