#!/usr/bin/env python
"""One-command incident debug bundle (thin wrapper).

    python scripts/debug_bundle.py --url http://127.0.0.1:9001 \\
        [--url ...] [--config-file cfg.yaml] [--journal-dir DIR] [--out X.tar.gz]

Snapshots /metrics (both exposition modes), /statusz, /debug/vars,
/debug/traces, /alertz, /readyz and /healthz from each listener, plus
a secrets-redacted config and the upload-journal directory state, into
a timestamped tar.gz with a MANIFEST.json. See
janus_tpu/tools/debug_bundle.py (importable, tested) for the logic.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from janus_tpu.tools.debug_bundle import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
