"""Component-level timing of the two-party SumVec step on the chip.

Times each stage of the prepare pipeline separately under fetch-forced
timing (the axon tunnel's block_until_ready lies; only a value fetch
proves remote completion — BASELINE.md "measurement methodology").
Every component is wrapped in a jit that reduces its outputs to one
u64 checksum so the fetch is O(1) bytes.

Usage (alone on the tunnel — single-process grant):
    python scripts/profile_components.py --batch 2048 --length 1000
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=2048)
    ap.add_argument("--length", type=int, default=1000)
    ap.add_argument("--bits", type=int, default=16)
    ap.add_argument("--iters", type=int, default=4)
    ap.add_argument("--only", default="", help="comma list of component names")
    ap.add_argument("--cpu", action="store_true", help="pin the CPU backend")
    args = ap.parse_args()

    import jax

    if args.cpu:
        # sitecustomize preimports jax with the axon platform; env vars
        # alone don't stick
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np

    from janus_tpu.binary_utils import enable_compile_cache

    enable_compile_cache()

    backend = jax.default_backend()
    print(f"[profile] backend={backend}", flush=True)

    from janus_tpu.vdaf.registry import VdafInstance, prio3_batched
    from janus_tpu.vdaf.engine import flp_query_batched, flp_decide_batched
    from janus_tpu.vdaf.xof import USAGE_MEASUREMENT_SHARE, USAGE_PROOF_SHARE
    from janus_tpu.parallel.api import two_party_step
    from janus_tpu.vdaf.testing import make_report_batch, random_measurements

    inst = VdafInstance.sum_vec(length=args.length, bits=args.bits)
    p3 = prio3_batched(inst)
    bc = p3.bc
    jf = p3.jf
    circ = p3.circ
    B = args.batch
    print(
        f"[profile] input_len={circ.input_len} proof_len={circ.proof_len} "
        f"chunk={circ.chunk_length} calls={bc.calls} m={bc.m} gp_len={bc.gp_len}",
        flush=True,
    )

    rng = np.random.default_rng(0x50F11E)
    verify_key = bytes(range(16))

    def rand_field(shape):
        lo = jnp.asarray(rng.integers(0, 1 << 63, size=shape, dtype=np.uint64))
        if jf.LIMBS == 1:
            return (lo,)
        hi = jnp.asarray(rng.integers(0, 1 << 62, size=shape, dtype=np.uint64))
        return (lo, hi)

    def rand_lanes(shape):
        return jnp.asarray(rng.integers(0, 1 << 63, size=shape, dtype=np.uint64))

    def checksum(tree):
        leaves = jax.tree_util.tree_leaves(tree)
        acc = jnp.uint64(0)
        for x in leaves:
            acc = acc + jnp.sum(x.astype(jnp.uint64))
        return acc

    timings = {}

    def timeit(name, fn, *a):
        if args.only and name not in args.only.split(","):
            return
        f = jax.jit(lambda *xs: checksum(fn(*xs)))
        t0 = time.time()
        v = int(f(*a))
        compile_s = time.time() - t0
        t0 = time.time()
        for _ in range(args.iters):
            v = int(f(*a))
        per = (time.time() - t0) / args.iters
        timings[name] = per
        print(
            json.dumps(
                {
                    "component": name,
                    "s_per_call": round(per, 4),
                    "us_per_report": round(per / B * 1e6, 2),
                    "rps": round(B / per, 1),
                    "compile_s": round(compile_s, 1),
                }
            ),
            flush=True,
        )
        return v

    # --- staged inputs (device-resident before timing) ---
    helper_seed = rand_lanes((B, 2))
    nonce = rand_lanes((B, 2))
    blind = rand_lanes((B, 2))
    meas = rand_field((B, circ.input_len))
    proof = rand_field((B, circ.proof_len))
    qr = rand_field((B, circ.query_rand_len))
    jr = rand_field((B, circ.joint_rand_len))
    (helper_seed, nonce, blind, meas, proof, qr, jr) = jax.device_put(
        (helper_seed, nonce, blind, meas, proof, qr, jr)
    )
    jax.block_until_ready((helper_seed, nonce, blind, meas, proof, qr, jr))

    # 1. XOF expansion of the helper measurement share (the dominant
    #    op count per the BASELINE.md roofline)
    timeit(
        "expand_meas",
        lambda s: p3._expand_share(s, USAGE_MEASUREMENT_SHARE, circ.input_len),
        helper_seed,
    )
    # 2. proof-share expansion
    timeit(
        "expand_proof",
        lambda s: p3._expand_share(s, USAGE_PROOF_SHARE, circ.proof_len),
        helper_seed,
    )
    # 3. FLP query on staged shares (leader-shaped: no expansion)
    timeit(
        "flp_query",
        lambda m, p, q, j: flp_query_batched(bc, m, p, q, j, 2),
        meas,
        proof,
        qr,
        jr,
    )
    # 4. truncate + masked aggregate
    def trunc_agg(m):
        out = bc.truncate(m)
        mask = jnp.ones((B,), bool)
        return p3.aggregate(out, mask)

    timeit("truncate_aggregate", trunc_agg, meas)
    # 5. joint-rand derivation chain (leader binder = full share enc)
    timeit(
        "joint_rand_chain",
        lambda b, n, m: p3._joint_rand_part(0, b, n, p3._part_binder(0, m, None)),
        blind,
        nonce,
        meas,
    )
    # 6. helper init (expansion + query fused by XLA)
    from janus_tpu.parallel.api import helper_init_step

    hi_step = helper_init_step(inst, verify_key)
    public_parts = rand_lanes((B, 2, 2))
    timeit("helper_init", hi_step, nonce, public_parts, helper_seed, blind)

    # 7. full two-party step with real staged reports
    t0 = time.time()
    ms = random_measurements(inst, B, rng)
    step_args, _ = make_report_batch(inst, ms, seed=1, shard_chunk=8 if circ.input_len * 16 > (1 << 22) else 0)
    step_args = jax.device_put(step_args)
    jax.block_until_ready(step_args)
    print(f"[profile] staging: {time.time()-t0:.1f}s", flush=True)
    step = two_party_step(inst, verify_key)
    timeit("two_party_step", step, *step_args)

    total = sum(v for k, v in timings.items() if k not in ("two_party_step", "helper_init"))
    if "two_party_step" in timings:
        print(
            f"[profile] component sum (1x expand_meas/proof/query/trunc/jr) = "
            f"{total:.3f}s vs full step {timings['two_party_step']:.3f}s",
            flush=True,
        )


if __name__ == "__main__":
    main()
