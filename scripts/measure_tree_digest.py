"""Chip microbench: tree-digest leaf layout — contiguous vs planar.

Hypothesis (r5): the leader joint-rand binder at SumVec len=100k costs
~5 ms/report not in Keccak but in the stride-14 gather that turns
contiguous 112-byte leaf chunks into per-lane columns ([batch, n, 14]
minor-dim slices = an 819 MB strided transpose at ~10% bandwidth).
The planar variant maps leaf k's lane l to data[l*n + k] — every lane
column is then a contiguous slice, no transpose — at the price of a
(self-consistent, internal) derivation change.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    import jax

    from janus_tpu.binary_utils import enable_compile_cache

    enable_compile_cache()

    import jax.numpy as jnp
    import numpy as np

    import janus_tpu.vdaf.keccak_jax as kj

    print(f"[tree] backend={jax.default_backend()}", flush=True)
    batch, lanes_n = 32, 3_200_000  # the len=100k leader share binder
    rng = np.random.default_rng(3)
    data = jnp.asarray(
        rng.integers(0, 1 << 63, size=(batch, lanes_n), dtype=np.uint64)
    )
    jax.block_until_ready(data)

    def timeit(name, fn):
        f = jax.jit(fn)
        t0 = time.time()
        v = np.asarray(f(data)).sum()
        compile_s = time.time() - t0
        ts = []
        for _ in range(3):
            t0 = time.time()
            v = np.asarray(f(data)).sum()
            ts.append(time.time() - t0)
        print(
            json.dumps(
                {"variant": name, "s": round(min(ts), 4), "compile_s": round(compile_s, 1)}
            ),
            flush=True,
        )

    def current(d):
        return kj.tree_digest_lanes([(0, d)], lanes_n * 8, batch)

    CH = kj.TREE_CHUNK_LANES

    def planar_level0(d):
        # planar leaves: lane l of node k = data[l*n + k]; every lane
        # column is one contiguous slice
        n = -(-lanes_n // CH)
        pad = n * CH - lanes_n
        if pad:
            d = jnp.pad(d, ((0, 0), (0, pad)))
        planes = d.reshape(batch, CH, n)
        idx = jnp.broadcast_to(jnp.arange(n, dtype=jnp.uint64)[None, :], (batch, n))
        consts = {
            0: np.uint64(kj.TREE_MAGIC_LANE),
            1: np.uint64(0),
            3: np.uint64(lanes_n * 8),
            18: kj.PAD_START,
            20: kj.PAD_END,
        }
        cols = []
        for lane in range(kj.RATE_LANES):
            if lane == 2:
                cols.append(idx)
            elif 4 <= lane < 4 + CH:
                cols.append(planes[:, lane - 4, :])
            else:
                cols.append(
                    jnp.broadcast_to(
                        jnp.asarray(consts.get(lane, np.uint64(0))), (batch, n)
                    )
                )
        state = kj._single_block_keccak(cols, out_lanes=2)
        digs = jnp.stack(state[:2], axis=-1)
        # upper levels on the (small) digest array, current layout
        level, nn = 0, n
        while nn > 1:
            level += 1
            groups = -(-nn // kj.TREE_ARITY)
            gpad = groups * kj.TREE_ARITY - nn
            if gpad:
                digs = jnp.pad(digs, ((0, 0), (0, gpad), (0, 0)))
            chunks = digs.reshape(batch, groups, CH)
            digs = kj._tree_level(chunks, level, lanes_n * 8)
            nn = groups
        return digs[:, 0, :]

    def level0_only_current(d):
        n = -(-lanes_n // CH)
        pad = n * CH - lanes_n
        if pad:
            d = jnp.pad(d, ((0, 0), (0, pad)))
        chunks = d.reshape(batch, n, CH)
        return kj._tree_level(chunks, 0, lanes_n * 8)

    # NOTE post-r5: the library digest IS the planar layout now, so
    # "library_full" ~= "planar_full"; "contiguous_level0" preserves
    # the pre-r5 contiguous-leaf baseline this change was measured
    # against (245 ms library vs 176 ms planar on this config,
    # 2026-08-01 — recorded in BASELINE.md).
    timeit("library_full", current)
    timeit("contiguous_level0", level0_only_current)
    timeit("planar_full", planar_level0)


if __name__ == "__main__":
    main()
