#!/usr/bin/env python
"""Multi-chip serving benchmark CLI.

Runs the hardened serving benchmark (__graft_entry__.serving_multichip):
rps through the EngineCache serving path at 1 vs N devices, with
bit-identity, mesh-active, and dispatch-lock-removed gates. Each phase
runs in its own subprocess with a timeout; a failed phase still yields
an ``"ok": false`` partial record, so the output is always one
parseable JSON line (schema ``janus_multichip_serving/v1``).

Usage:
    python scripts/multichip_bench.py --devices 4 --out MULTICHIP_r06.json

Exit code 0 iff the record's top-level ``ok`` is true.
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--devices", type=int, default=4, help="mesh device count")
    ap.add_argument("--batch", type=int, default=256, help="reports per round")
    ap.add_argument("--iters", type=int, default=8, help="timed rounds per phase")
    ap.add_argument(
        "--phase-timeout",
        type=float,
        default=900.0,
        help="per-phase subprocess timeout (seconds)",
    )
    ap.add_argument("--out", default=None, help="also write the record here")
    args = ap.parse_args()

    import __graft_entry__ as g

    record = g.serving_multichip(
        n_devices=args.devices,
        out_path=args.out,
        batch=args.batch,
        iters=args.iters,
        phase_timeout_s=args.phase_timeout,
    )
    return 0 if record.get("ok") else 1


if __name__ == "__main__":
    sys.exit(main())
