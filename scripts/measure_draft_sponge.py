"""Chip measurement: draft-mode sequential sponge under nested scans.

Round 4 measured a single flat lax.scan squeeze going superlinear past
~32k blocks (1.9 s @ 32k vs 209 s @ 152k, batch 8) and capped the
draft device gate there. keccak_jax now chunks long chains into nested
scans (_SCAN_CHUNK); this script re-measures the knee and the batch
amortization the r4 verdict asked for (item 2): per-report cost at
batch 8 vs 64 vs 512, and a full draft SumVec len=100k prepare if the
squeeze proves linear.

Usage (alone on the tunnel):
    python scripts/measure_draft_sponge.py
    python scripts/measure_draft_sponge.py --full-prepare --batch 64
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--blocks", default="8192,32768,152382")
    ap.add_argument("--batches", default="8,64,256")
    ap.add_argument("--iters", type=int, default=2)
    ap.add_argument("--full-prepare", action="store_true")
    ap.add_argument("--batch", type=int, default=64, help="for --full-prepare")
    ap.add_argument("--cpu", action="store_true")
    args = ap.parse_args()

    import jax

    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
    from janus_tpu.binary_utils import enable_compile_cache

    enable_compile_cache()

    import jax.numpy as jnp
    import numpy as np

    import janus_tpu.vdaf.keccak_jax as kj

    print(f"[sponge] backend={jax.default_backend()} chunk={kj._SCAN_CHUNK}", flush=True)

    def checksum_squeeze(batch, blocks):
        @jax.jit
        def f(msg):
            out = kj.shake128_squeeze_lanes(msg, blocks)
            return jnp.sum(out)

        return f

    rng = np.random.default_rng(1)
    for blocks in [int(b) for b in args.blocks.split(",")]:
        for batch in [int(b) for b in args.batches.split(",")]:
            msg = jnp.asarray(
                rng.integers(0, 1 << 63, size=(batch, 2, kj.RATE_LANES), dtype=np.uint64)
            )
            f = checksum_squeeze(batch, blocks)
            t0 = time.time()
            v = int(f(msg))
            compile_s = time.time() - t0
            t0 = time.time()
            for _ in range(args.iters):
                v = int(f(msg))
            per = (time.time() - t0) / args.iters
            print(
                json.dumps(
                    {
                        "squeeze_blocks": blocks,
                        "batch": batch,
                        "s_per_chain": round(per, 3),
                        "us_per_block": round(per / blocks * 1e6, 2),
                        "chain_per_report_s": round(per, 3),
                        "amortized_r_per_s": round(batch / per, 2),
                        "compile_s": round(compile_s, 1),
                    }
                ),
                flush=True,
            )

    if args.full_prepare:
        import dataclasses

        from janus_tpu.vdaf import draft_jax
        from janus_tpu.vdaf.registry import VdafInstance
        from janus_tpu.parallel.api import two_party_step
        from janus_tpu.vdaf.testing import make_report_batch, random_measurements

        draft_jax.Prio3BatchedDraft.MAX_STREAM_BLOCKS = 1 << 20  # lift the gate
        inst = VdafInstance.sum_vec(length=100_000, bits=16, chunk_length=0)
        inst = dataclasses.replace(inst, xof_mode="draft")
        batch = args.batch
        t0 = time.time()
        meas = random_measurements(inst, batch, rng)
        step_args, _ = make_report_batch(inst, meas, seed=1, shard_chunk=8)
        step_args = jax.device_put(step_args)
        jax.block_until_ready(step_args)
        print(f"[sponge] staging: {time.time()-t0:.1f}s", flush=True)
        step = jax.jit(two_party_step(inst, bytes(range(16))))
        t0 = time.time()
        out = step(*step_args)
        assert int(out[2]) == batch, int(out[2])
        print(f"[sponge] compile+first: {time.time()-t0:.1f}s", flush=True)
        t0 = time.time()
        iters = max(1, args.iters)
        for _ in range(iters):
            out = step(*step_args)
            assert int(out[2]) == batch
        per = (time.time() - t0) / iters
        print(
            json.dumps(
                {
                    "metric": "draft_sumvec_len100k_two_party",
                    "batch": batch,
                    "s_per_step": round(per, 2),
                    "r_per_s": round(batch / per, 2),
                }
            ),
            flush=True,
        )


if __name__ == "__main__":
    main()
