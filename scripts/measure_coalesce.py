"""Chip measurement: coalesced small-job throughput vs one big batch.

VERDICT r4 item 3's done-bar: small aggregation jobs within ~20% of
the large-batch device capability. This drives the REAL engine surface
(EngineCache.helper_init + aggregate — the helper serving hot path)
from N concurrent driver-shaped threads submitting small jobs, against
the same total rows as one monolithic dispatch.

Usage (alone on the tunnel):
    python scripts/measure_coalesce.py --job-rows 1024 --jobs 16 --threads 8
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from concurrent.futures import ThreadPoolExecutor

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--config", default="count", choices=["count", "sumvec"])
    ap.add_argument("--job-rows", type=int, default=1024)
    ap.add_argument("--jobs", type=int, default=16)
    ap.add_argument("--threads", type=int, default=8)
    ap.add_argument("--iters", type=int, default=3)
    ap.add_argument("--cpu", action="store_true")
    args = ap.parse_args()

    import jax

    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
    from janus_tpu.binary_utils import enable_compile_cache

    enable_compile_cache()

    import numpy as np

    from janus_tpu.aggregator.engine_cache import EngineCache
    from janus_tpu.vdaf.registry import VdafInstance
    from janus_tpu.vdaf.testing import make_report_batch, random_measurements

    inst = (
        VdafInstance.count()
        if args.config == "count"
        else VdafInstance.sum_vec(length=1000, bits=16)
    )
    engine = EngineCache(inst, bytes(range(16)))
    rng = np.random.default_rng(5)
    total = args.job_rows * args.jobs
    print(
        f"[coalesce] backend={jax.default_backend()} config={args.config} "
        f"job_rows={args.job_rows} jobs={args.jobs} threads={args.threads}",
        flush=True,
    )

    meas = random_measurements(inst, total, rng)
    t0 = time.time()
    big_args, _ = make_report_batch(inst, meas, seed=3)
    print(f"[coalesce] staging: {time.time()-t0:.1f}s", flush=True)

    def cut(a, s, e):
        if a is None:
            return None
        if isinstance(a, tuple):
            return tuple(x[s:e] for x in a)
        return np.asarray(a)[s:e]

    job_args = [
        tuple(cut(a, j * args.job_rows, (j + 1) * args.job_rows) for a in big_args)
        for j in range(args.jobs)
    ]

    def run_job(a):
        nonce, public, meas_c, proof, blind0, hseed, blind1 = a
        n = nonce.shape[0]
        out0, seed0, ver0, part0 = engine.leader_init(nonce, public, meas_c, proof, blind0)
        out1, mask, _ = engine.helper_init(
            nonce, public, hseed, blind1, ver0, part0, np.ones(n, bool)
        )
        agg1 = engine.aggregate(out1, mask)
        return int(mask.sum())

    def small_jobs_concurrent():
        with ThreadPoolExecutor(max_workers=args.threads) as pool:
            done = sum(pool.map(run_job, job_args))
        assert done == total, done
        return done

    def one_big_job():
        return run_job(big_args)

    for name, fn in (("big_single_dispatch", one_big_job), ("small_jobs_coalesced", small_jobs_concurrent)):
        fn()  # compile
        t0 = time.time()
        for _ in range(args.iters):
            fn()
        per = (time.time() - t0) / args.iters
        print(
            json.dumps(
                {
                    "variant": name,
                    "rows": total,
                    "s": round(per, 3),
                    "rps": round(total / per, 1),
                    "coalesce_rounds": list(engine._co_leader.rounds)[-8:],
                }
            ),
            flush=True,
        )


if __name__ == "__main__":
    main()
