#!/usr/bin/env python
"""Deploy smoke check: scrape a running aggregator's health listener
and validate the output with the same exposition parser the tests use.

    python scripts/scrape_check.py --url http://127.0.0.1:9001 [--statusz]

Exit status 0 when /metrics parses clean (and, with --statusz, the
/statusz snapshot is well-formed JSON with the expected sections);
non-zero with the errors on stderr otherwise. Exercised in tier-1 via
bench.py --dry-run's observability smoke (tests/test_tools.py).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from janus_tpu.exposition import (  # noqa: E402
    lint_metric_names,
    parse_exposition,
    validate_exposition,
)


def _fetch(url: str, timeout: float) -> tuple[str, str]:
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return resp.read().decode("utf-8"), resp.headers.get("Content-Type", "")


def _fetch_any_status(url: str, timeout: float) -> tuple[int, str]:
    """(status, body) tolerating non-2xx (a degraded /readyz answers
    503, which urllib raises as HTTPError)."""
    from janus_tpu.core.http_client import fetch_any_status

    status, body = fetch_any_status(url, timeout=timeout)
    return status, body.decode("utf-8")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument(
        "--url",
        required=True,
        help="health listener base URL, e.g. http://127.0.0.1:9001",
    )
    ap.add_argument(
        "--statusz", action="store_true", help="also validate the /statusz snapshot"
    )
    ap.add_argument("--timeout", type=float, default=10.0)
    args = ap.parse_args(argv)
    base = args.url.rstrip("/")

    errors: list[str] = []
    try:
        text, ctype = _fetch(base + "/metrics", args.timeout)
    except Exception as e:
        print(f"scrape_check: GET /metrics failed: {e}", file=sys.stderr)
        return 2
    if not ctype.startswith("text/plain") or "version=0.0.4" not in ctype:
        errors.append(f"/metrics Content-Type not exposition format: {ctype!r}")
    errors.extend(validate_exposition(text))
    # exemplars belong to the OpenMetrics mode only: re-reading the
    # default scrape WITH exemplar parsing must find none (a substring
    # test would false-positive on a legal label value containing
    # ' # {'; real leaked clauses also fail validate_exposition above)
    leak_fams, _ = parse_exposition(text, openmetrics=True)
    if any(f.exemplars for f in leak_fams.values()):
        errors.append("/metrics default mode leaked an exemplar clause")
    families, _ = parse_exposition(text)
    errors.extend(lint_metric_names({f.name: f.type for f in families.values()}))
    if not families:
        errors.append("/metrics exposed no metric families")

    # OpenMetrics exposition mode (?openmetrics=1): same families plus
    # histogram exemplars and the # EOF terminator, exemplar syntax
    # validated by the shared parser
    try:
        om_text, om_ctype = _fetch(base + "/metrics?openmetrics=1", args.timeout)
    except Exception as e:
        errors.append(f"GET /metrics?openmetrics=1 failed: {e}")
    else:
        if not om_ctype.startswith("application/openmetrics-text"):
            errors.append(
                f"/metrics?openmetrics=1 Content-Type not OpenMetrics: {om_ctype!r}"
            )
        om_errors = validate_exposition(om_text, openmetrics=True)
        errors.extend(f"openmetrics: {e}" for e in om_errors)
        om_families, _ = parse_exposition(om_text, openmetrics=True)
        if set(om_families) != set(families):
            errors.append(
                "openmetrics mode exposes a different family set than the default scrape"
            )
    # device-path watchdog/quarantine families (docs/ROBUSTNESS.md
    # "Device hangs & deadlines"): registered at import in every
    # binary, so absence is a deploy regression, not an idle process
    for fam in (
        "janus_hung_dispatches_total",
        "janus_abandoned_dispatch_threads",
        "janus_engine_quarantines_total",
        # stage-pipelined leader stepper (ISSUE 9; registered at import
        # in every binary — absence is a deploy regression)
        "janus_step_pipeline_stage_seconds",
        "janus_step_pipeline_queue_depth",
        "janus_device_lane_busy_ratio",
        "janus_device_lane_busy_seconds_total",
        "janus_step_pipeline_overlap_total",
        "janus_prep_resp_order_mismatch_total",
        # SLO burn-rate engine (ISSUE 10) + the standard process/build
        # families scrapers expect — all registered at import in every
        # binary, so absence is a deploy regression
        "janus_alert_active",
        "janus_slo_error_budget_remaining_ratio",
        "janus_slo_burn_rate",
        "janus_build_info",
        "janus_process_start_time_seconds",
        # batched ingest crypto (ISSUE 11) — registered at import in
        # every binary, so absence is a deploy regression
        "janus_hpke_batch_size",
        "janus_ingest_decrypt_batch_seconds",
        # device-resident aggregate state + host<->device traffic
        # (ISSUE 12) — registered at import in every binary
        "janus_engine_resident_buffers",
        "janus_engine_resident_bytes",
        "janus_engine_hd_bytes_total",
        "janus_engine_resident_flushes_total",
        "janus_engine_prestage_total",
        # continuous profiler + device cost ledger + boot timeline
        # (ISSUE 13) — registered at import in every binary
        "janus_profiler_samples_total",
        "janus_profiler_threads",
        "janus_profiler_overhead_ratio",
        "janus_device_cost_seconds_total",
        "janus_device_cost_us_per_report",
        "janus_boot_phase_seconds",
        # shape-manifest AOT prewarm (ISSUE 14) — registered at import
        # in every binary
        "janus_engine_prewarm_total",
        "janus_engine_prewarm_seconds",
        # fleet scale-out: batched sharded lease claims + replica
        # identity (ISSUE 15) — registered at import in every binary
        "janus_replica_info",
        "janus_lease_acquire_tx_total",
        "janus_lease_acquired_jobs_total",
        "janus_lease_steals_total",
        "janus_lease_conflicts_total",
        # single-controller mesh dispatch queue (ISSUE 16) — registered
        # at import in every binary, so absence is a deploy regression
        "janus_mesh_dispatch_total",
        "janus_mesh_dispatch_queue_depth",
        "janus_mesh_dispatch_wait_seconds",
        "janus_mesh_dispatch_busy_seconds_total",
        # block-sparse scatter-merge (ISSUE 17) — registered at import
        # in every binary, so absence is a deploy regression
        "janus_engine_scatter_rows_total",
        "janus_engine_sparse_block_occupancy",
        # flight recorder: telemetry history + trend/leak verdicts
        # (ISSUE 18) — registered at import in every binary
        "janus_flight_slope",
        "janus_flight_leak_active",
        "janus_flight_p99_ratio",
        "janus_flight_snapshots_total",
        "janus_flight_ring_bytes",
        "janus_flight_ring_segments",
        "janus_flight_overhead_ratio",
        # lifecycle gauges the recorder trends (ISSUE 18 satellites)
        "janus_gc_deleted_rows_total",
        "janus_gc_tasks_scanned_total",
        "janus_gc_runs_total",
        "janus_gc_lag_seconds",
        "janus_datastore_table_rows",
        "janus_artifact_bytes",
        # peer-outage parking + half-open probing (ISSUE 19) —
        # registered at import in every binary, so absence is a deploy
        # regression (labeled families render even with zero samples)
        "janus_peer_parked",
        "janus_peer_outage_seconds_total",
        "janus_peer_probes_total",
        # report-flow conservation ledger (ISSUE 20) — registered at
        # import in every binary, so absence is a deploy regression
        "janus_ledger_imbalance",
        "janus_ledger_breach_active",
        "janus_ledger_peer_divergence",
        "janus_ledger_evaluations_total",
    ):
        if fam not in families:
            errors.append(f"/metrics missing the {fam} family")

    # janus_build_info must carry the identity labels with value 1
    bi = families.get("janus_build_info")
    if bi is not None:
        live = [(labels, v) for _, labels, v in bi.samples if v == 1]
        if len(live) != 1 or not {"version", "python", "jax", "backend"} <= set(
            live[0][0]
        ):
            errors.append(
                "janus_build_info needs exactly one value-1 sample with "
                "version/python/jax/backend labels"
            )

    # janus_replica_info (ISSUE 15): exactly one value-1 sample with
    # the fleet identity labels — the join key when N replicas export
    # to one scrape plane
    ri = families.get("janus_replica_info")
    if ri is not None:
        live = [(labels, v) for _, labels, v in ri.samples if v == 1]
        if len(live) != 1 or not {
            "replica_id",
            "shard_index",
            "shard_count",
        } <= set(live[0][0]):
            errors.append(
                "janus_replica_info needs exactly one value-1 sample with "
                "replica_id/shard_index/shard_count labels"
            )

    if args.statusz:
        try:
            body, _ = _fetch(base + "/statusz", args.timeout)
            snap = json.loads(body)
        except Exception as e:
            errors.append(f"/statusz not valid JSON: {e}")
        else:
            if not isinstance(snap, dict) or not snap:
                errors.append("/statusz snapshot is empty")
            else:
                # the device_watchdog section must carry the abandoned-
                # thread accounting and, for every stalled dispatch, a
                # live stack dump — the first artifact an operator
                # needs when a dispatch wedges
                wd = snap.get("device_watchdog")
                if not isinstance(wd, dict):
                    errors.append("/statusz missing the device_watchdog section")
                else:
                    for key in ("abandoned_threads", "abandoned_thread_cap", "host_only", "stalled"):
                        if key not in wd:
                            errors.append(f"/statusz device_watchdog missing {key!r}")
                    for ent in wd.get("stalled", []) or []:
                        if not ent.get("stack"):
                            errors.append(
                                "/statusz device_watchdog stalled entry without a stack dump"
                            )
                # resident aggregate state (ISSUE 12): process-wide
                # byte ledger + per-engine buffer/merge/eviction counts
                ra = snap.get("resident_accumulators")
                if not isinstance(ra, dict):
                    errors.append("/statusz missing the resident_accumulators section")
                else:
                    # `sparse` rides the section unconditionally (ISSUE
                    # 17): the process-wide scatter-merge rollup must be
                    # present even with zero sparse engines provisioned
                    for key in ("total_bytes", "max_bytes", "cross_task_coalesce", "sparse", "engines"):
                        if key not in ra:
                            errors.append(f"/statusz resident_accumulators missing {key!r}")
                    sp = ra.get("sparse")
                    if isinstance(sp, dict):
                        for key in ("engines", "scatter_rows"):
                            if key not in sp:
                                errors.append(
                                    f"/statusz resident_accumulators sparse missing {key!r}"
                                )
                    for ent in ra.get("engines", []) or []:
                        for key in ("vdaf", "buffers", "bytes", "merges", "evictions"):
                            if key not in ent:
                                errors.append(
                                    f"/statusz resident_accumulators engine entry missing {key!r}"
                                )
                                break
                # continuous profiler + device cost ledger (ISSUE 13):
                # the compact profiler summary (per-role shares, top
                # frames, measured overhead) and the per-(vdaf, op,
                # bucket) cost table with the µs/report attribution
                prof = snap.get("profile")
                if not isinstance(prof, dict):
                    errors.append("/statusz missing the profile section")
                else:
                    for key in ("enabled", "roles", "top_frames", "overhead_ratio"):
                        if key not in prof:
                            errors.append(f"/statusz profile missing {key!r}")
                # shape-manifest AOT prewarm (ISSUE 14): compile cache
                # + AOT blob state, manifest inventory and the prewarm
                # outcome counters — the cold-start surface an operator
                # reads after a slow boot
                ep = snap.get("engine_prewarm")
                if not isinstance(ep, dict):
                    errors.append("/statusz missing the engine_prewarm section")
                else:
                    for key in ("compile_cache", "aot", "manifest", "prewarm"):
                        if key not in ep:
                            errors.append(f"/statusz engine_prewarm missing {key!r}")
                    for key in ("enabled", "dir", "files", "bytes"):
                        if key not in (ep.get("compile_cache") or {}):
                            errors.append(
                                f"/statusz engine_prewarm compile_cache missing {key!r}"
                            )
                    for key in ("state", "warmed", "cache_hits", "cache_misses"):
                        if key not in (ep.get("prewarm") or {}):
                            errors.append(
                                f"/statusz engine_prewarm prewarm missing {key!r}"
                            )
                    for key in ("enabled", "blobs", "loads", "saves"):
                        if key not in (ep.get("aot") or {}):
                            errors.append(
                                f"/statusz engine_prewarm aot missing {key!r}"
                            )
                    if "installed" not in (ep.get("manifest") or {}):
                        errors.append(
                            "/statusz engine_prewarm manifest missing 'installed'"
                        )
                # fleet identity (ISSUE 15): every process carries its
                # replica id + shard slice on /statusz
                fl = snap.get("fleet")
                if not isinstance(fl, dict):
                    errors.append("/statusz missing the fleet section")
                else:
                    for key in ("replica_id", "shard_index", "shard_count"):
                        if key not in fl:
                            errors.append(f"/statusz fleet missing {key!r}")
                # peer-outage parking (ISSUE 19): the peer-health
                # tracker registers its section only in the job driver
                # binaries, so it is validated when present rather than
                # required
                ph = snap.get("peer_health")
                if ph is not None:
                    if not isinstance(ph, dict):
                        errors.append("/statusz peer_health is not an object")
                    else:
                        for key in ("config", "parked", "peers"):
                            if key not in ph:
                                errors.append(f"/statusz peer_health missing {key!r}")
                        for peer, ent in (ph.get("peers") or {}).items():
                            for key in ("state", "probes"):
                                if key not in (ent or {}):
                                    errors.append(
                                        f"/statusz peer_health peer {peer} missing {key!r}"
                                    )
                                    break
                # multi-chip serving (ISSUE 16): mesh geometry + the
                # single-controller dispatch-queue accounting — present
                # (devices may be null pre-backend-init) on every binary
                mesh = snap.get("mesh")
                if not isinstance(mesh, dict):
                    errors.append("/statusz missing the mesh section")
                else:
                    for key in ("devices", "queue", "engines"):
                        if key not in mesh:
                            errors.append(f"/statusz mesh missing {key!r}")
                    for key in ("depth", "lane_alive", "submitted", "completed", "errors"):
                        if key not in (mesh.get("queue") or {}):
                            errors.append(f"/statusz mesh queue missing {key!r}")
                    for ent in mesh.get("engines", []) or []:
                        for key in ("vdaf", "dp", "sp", "mesh"):
                            if key not in ent:
                                errors.append(f"/statusz mesh engine entry missing {key!r}")
                                break
                dc = snap.get("device_cost")
                if not isinstance(dc, dict):
                    errors.append("/statusz missing the device_cost section")
                else:
                    for key in ("entries", "us_per_report"):
                        if key not in dc:
                            errors.append(f"/statusz device_cost missing {key!r}")
                    for ent in dc.get("entries", []) or []:
                        for key in ("vdaf", "op", "bucket", "dispatches", "rows"):
                            if key not in ent:
                                errors.append(
                                    f"/statusz device_cost entry missing {key!r}"
                                )
                                break
                # telemetry flight recorder (ISSUE 18): every binary
                # installs it by default; a running recorder whose last
                # snapshot has gone stale is a deploy regression — the
                # long-horizon evidence trail has silently stopped
                fr = snap.get("flight")
                if not isinstance(fr, dict):
                    errors.append("/statusz missing the flight section")
                else:
                    for key in (
                        "enabled",
                        "running",
                        "series_tracked",
                        "last_snapshot_age_s",
                        "leaks_active",
                    ):
                        if key not in fr:
                            errors.append(f"/statusz flight missing {key!r}")
                    if fr.get("enabled") and fr.get("running"):
                        age = fr.get("last_snapshot_age_s")
                        stale_after = max(3 * float(fr.get("interval_s") or 10.0), 30.0)
                        if age is None:
                            errors.append(
                                "/statusz flight recorder running but never snapshotted"
                            )
                        elif float(age) > stale_after:
                            errors.append(
                                f"/statusz flight last snapshot {age}s old "
                                f"(stale after {stale_after:g}s) — the recorder "
                                "has stopped recording"
                            )
                    elif fr.get("enabled") and not fr.get("running"):
                        errors.append(
                            "/statusz flight recorder enabled but not running"
                        )
                # report-flow conservation ledger (ISSUE 20): every
                # binary that owns a datastore installs it by default; a
                # listener without the section means report-loss
                # accounting is dark on that replica
                lg = snap.get("ledger")
                if not isinstance(lg, dict):
                    errors.append("/statusz missing the ledger section")
                else:
                    for key in (
                        "enabled",
                        "evaluations",
                        "grace_s",
                        "breaches",
                        "imbalance",
                    ):
                        if key not in lg:
                            errors.append(f"/statusz ledger missing {key!r}")
                    if lg.get("enabled") and lg.get("breaches"):
                        errors.append(
                            f"/statusz ledger reports active conservation "
                            f"breaches: {lg.get('breaches')} — reports are "
                            "leaking between pipeline stages"
                        )

    # /readyz semantics (docs/ROBUSTNESS.md "Datastore outages"): 200
    # with {"ready": true} when serving, 503 with a JSON reason map when
    # degraded (datastore down / upload journal full). Anything else —
    # missing route, non-JSON body, status/body disagreement — is a
    # deploy regression.
    try:
        status, body = _fetch_any_status(base + "/readyz", args.timeout)
    except Exception as e:
        errors.append(f"GET /readyz failed: {e}")
    else:
        if status not in (200, 503):
            errors.append(f"/readyz answered {status} (want 200 or 503)")
        else:
            try:
                ready = json.loads(body)
            except Exception as e:
                errors.append(f"/readyz not valid JSON: {e}")
            else:
                if not isinstance(ready, dict) or "ready" not in ready:
                    errors.append("/readyz JSON missing 'ready'")
                elif ready["ready"] is not (status == 200):
                    errors.append(
                        f"/readyz status {status} disagrees with body {ready}"
                    )
                elif status == 503 and not ready.get("reasons"):
                    errors.append("/readyz degraded (503) without a JSON reason")

    # the always-on flight recorder (janus_tpu.trace) serves
    # /debug/traces on every binary; a listener that can't render it
    # is a deploy regression
    try:
        body, _ = _fetch(base + "/debug/traces?limit=5", args.timeout)
        traces = json.loads(body)
    except Exception as e:
        errors.append(f"/debug/traces not valid JSON: {e}")
    else:
        for key in ("recent", "slow_traces", "digests", "recorded_total"):
            if key not in traces:
                errors.append(f"/debug/traces missing {key!r}")

    # conservation ledger (ISSUE 20): /debug/ledger answers the full
    # balance document on every binary — {"enabled": false} when no
    # evaluator is installed, the per-task books otherwise
    try:
        body, _ = _fetch(base + "/debug/ledger", args.timeout)
        ledger_doc = json.loads(body)
    except Exception as e:
        errors.append(f"/debug/ledger not valid JSON: {e}")
    else:
        if not isinstance(ledger_doc, dict) or "enabled" not in ledger_doc:
            errors.append("/debug/ledger JSON missing 'enabled'")
        elif ledger_doc["enabled"]:
            for key in ("evaluations", "tasks", "breaches"):
                if key not in ledger_doc:
                    errors.append(f"/debug/ledger missing {key!r}")

    # /alertz (ISSUE 10): every binary answers the SLO engine state as
    # well-formed JSON — enabled or not — with the alert/slo lists; a
    # firing alert must carry its burn rates and firing-since
    try:
        body, _ = _fetch(base + "/alertz", args.timeout)
        alertz = json.loads(body)
    except Exception as e:
        errors.append(f"/alertz not valid JSON: {e}")
    else:
        for key in ("enabled", "firing", "alerts", "slos"):
            if key not in alertz:
                errors.append(f"/alertz missing {key!r}")
        for a in alertz.get("alerts", []) or []:
            for key in ("alert", "severity", "state", "burn_rate_threshold"):
                if key not in a:
                    errors.append(f"/alertz alert entry missing {key!r}: {a}")
                    break
            if a.get("state") == "firing" and a.get("firing_since_unix") is None:
                errors.append(f"/alertz firing alert without firing_since: {a}")
        if alertz.get("enabled"):
            for s in alertz.get("slos", []) or []:
                for key in (
                    "name",
                    "objective",
                    "burn_rates",
                    "error_budget_remaining_ratio",
                    "evidence",
                ):
                    if key not in s:
                        errors.append(f"/alertz slo entry missing {key!r}: {s}")
                        break

    # continuous profiler (ISSUE 13): /debug/profile must serve a
    # well-formed collapsed-stack document (hostile thread names must
    # not corrupt the fold — validated with the shared validator) and a
    # JSON mode with per-role shares; every binary runs the sampler by
    # default, so a disabled profiler is a deploy regression
    from janus_tpu.profiler import validate_collapsed  # noqa: E402

    try:
        body, ctype = _fetch(base + "/debug/profile", args.timeout)
    except Exception as e:
        errors.append(f"GET /debug/profile failed: {e}")
    else:
        if not ctype.startswith("text/plain"):
            errors.append(f"/debug/profile Content-Type not text/plain: {ctype!r}")
        errors.extend(
            f"/debug/profile collapsed: {e}" for e in validate_collapsed(body)
        )
    try:
        body, ctype = _fetch(base + "/debug/profile?format=json", args.timeout)
        prof = json.loads(body)
    except Exception as e:
        errors.append(f"/debug/profile?format=json not valid JSON: {e}")
    else:
        if not ctype.startswith("application/json"):
            errors.append(f"/debug/profile json Content-Type: {ctype!r}")
        for key in ("enabled", "roles", "top_frames", "overhead_ratio", "samples"):
            if key not in prof:
                errors.append(f"/debug/profile json missing {key!r}")
        if prof.get("enabled") is not True:
            errors.append(
                "/debug/profile reports the sampler disabled (it is on by "
                "default in every binary — a disabled profiler is a deploy "
                "regression)"
            )

    # telemetry flight recorder (ISSUE 18): /debug/flight must serve a
    # well-formed history + trend-analysis document on every binary
    # (the recorder is on by default; even a disabled one answers
    # enabled: false with the document shape intact)
    try:
        body, ctype = _fetch(base + "/debug/flight", args.timeout)
        flight = json.loads(body)
    except Exception as e:
        errors.append(f"/debug/flight not valid JSON: {e}")
    else:
        if not ctype.startswith("application/json"):
            errors.append(f"/debug/flight Content-Type: {ctype!r}")
        for key in ("enabled", "series_tracked", "snapshots", "analysis"):
            if key not in flight:
                errors.append(f"/debug/flight missing {key!r}")
        if flight.get("enabled"):
            for key in ("window_s", "snapshots_total", "overhead_ratio", "ring"):
                if key not in flight:
                    errors.append(f"/debug/flight missing {key!r}")
            analysis = flight.get("analysis") or {}
            for key in ("series", "latency", "leaking"):
                if key not in analysis:
                    errors.append(f"/debug/flight analysis missing {key!r}")

    # boot-phase timeline (ISSUE 13): /debug/boot is one contiguous,
    # monotone phase sequence from process start
    try:
        body, _ = _fetch(base + "/debug/boot", args.timeout)
        boot = json.loads(body)
    except Exception as e:
        errors.append(f"/debug/boot not valid JSON: {e}")
    else:
        for key in ("started_unix", "ready", "phases", "boot_phases_sum_s"):
            if key not in boot:
                errors.append(f"/debug/boot missing {key!r}")
        last_end = 0.0
        for p in boot.get("phases", []) or []:
            if not {"phase", "start_s", "end_s", "seconds"} <= set(p):
                errors.append(f"/debug/boot phase entry malformed: {p}")
                break
            if p["start_s"] < last_end - 1e-6 or p["end_s"] < p["start_s"] - 1e-6:
                errors.append(f"/debug/boot phases not monotone at {p['phase']!r}")
                break
            last_end = p["end_s"]

    # the endpoint-discovery index page (GET /) must link the surface
    try:
        body, ctype = _fetch(base + "/", args.timeout)
    except Exception as e:
        errors.append(f"GET / failed: {e}")
    else:
        if not ctype.startswith("text/html"):
            errors.append(f"GET / Content-Type not HTML: {ctype!r}")
        for link in (
            "/metrics",
            "/statusz",
            "/alertz",
            "/debug/traces",
            "/debug/flight",
            "/readyz",
        ):
            if link not in body:
                errors.append(f"GET / index page does not link {link}")

    for err in errors:
        print(f"scrape_check: {err}", file=sys.stderr)
    if errors:
        return 1
    print(f"scrape_check: OK ({len(families)} metric families)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
